"""Recommender tests: wire codec golden bytes, imputer recovery, hermetic
in-process gRPC server+client, retrain-on-change, the TPU plugin consuming
the REAL service end to end, and the observed-throughput feedback loop."""
import math
import os
import time

import numpy as np
import pytest


class FakeRegistryKV:
    """Dict-backed stand-in for registry.Client (set/get/get_keys)."""

    def __init__(self):
        self.data = {}

    def set(self, key, value):
        self.data[key] = value

    def get(self, key):
        return self.data.get(key)

    def get_keys(self, pattern="*"):
        prefix = pattern.rstrip("*")
        return [k for k in self.data if k.startswith(prefix)]

from k8s_gpu_scheduler_tpu.recommender import (
    Client,
    IterativeImputer,
    RecommenderServer,
    find_max_index,
)
from k8s_gpu_scheduler_tpu.recommender.wire import (
    decode_reply,
    decode_request,
    encode_reply,
    encode_request,
)


class TestWireCodec:
    def test_request_golden_bytes(self):
        # proto3: field 1, LEN — tag 0x0A, length, utf8. Byte-compatible
        # with the reference's Request{index} (recom.proto:10-12).
        assert encode_request("abc") == b"\x0a\x03abc"
        assert decode_request(b"\x0a\x03abc") == "abc"

    def test_reply_roundtrip(self):
        buf = encode_reply([1.5, -2.25], ["1P_V5E", "2P_V5E"])
        result, columns = decode_reply(buf)
        assert result == [1.5, -2.25]
        assert columns == ["1P_V5E", "2P_V5E"]

    def test_reply_golden_packed_floats(self):
        # packed fixed32: tag 0x0A, len 4, IEEE754 LE of 1.0
        assert encode_reply([1.0], []) == b"\x0a\x04\x00\x00\x80\x3f"

    def test_empty_reply(self):
        assert decode_reply(encode_reply([], [])) == ([], [])

    def test_decode_skips_unknown_fields(self):
        # field 3 varint (tag 0x18) must be skipped, not crash
        buf = b"\x18\x2a" + encode_reply([2.0], ["c"])
        result, columns = decode_reply(buf)
        assert result == [2.0] and columns == ["c"]


class TestImputer:
    def test_recovers_linear_structure(self):
        # col1 = 2*col0, col2 = col0 + 10 — missing cells must land close.
        rng = np.random.default_rng(0)
        base = rng.uniform(1, 100, size=(20, 1))
        X = np.hstack([base, 2 * base, base + 10])
        X_missing = X.copy()
        X_missing[3, 1] = np.nan
        X_missing[7, 2] = np.nan
        imp = IterativeImputer()
        done = imp.fit_transform(X_missing)
        assert done[3, 1] == pytest.approx(X[3, 1], rel=0.05)
        assert done[7, 2] == pytest.approx(X[7, 2], rel=0.05)

    def test_transform_unseen_row(self):
        rng = np.random.default_rng(1)
        base = rng.uniform(1, 100, size=(20, 1))
        imp = IterativeImputer().fit(np.hstack([base, 3 * base]))
        row = np.array([[50.0, np.nan]])
        assert imp.transform(row)[0, 1] == pytest.approx(150.0, rel=0.05)

    def test_all_nan_column_mean_zero(self):
        X = np.array([[1.0, np.nan], [2.0, np.nan]])
        done = IterativeImputer().fit_transform(X)
        assert np.isfinite(done).all()


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    import os

    here = os.path.dirname(os.path.abspath(__file__))
    data = os.path.join(
        here, "..", "k8s_gpu_scheduler_tpu", "recommender", "data"
    )
    srv = RecommenderServer(
        configurations_path=os.path.join(data, "configurations_train.tsv"),
        interference_path=os.path.join(data, "interference_train.tsv"),
        port=0,
        retrain_interval_s=0.2,
    ).start()
    yield srv
    srv.stop()


class TestService:
    def test_configurations_by_pod_name_substring(self, server):
        """Pod-style request 'bert-base-infer-7f9c' must hit the
        'bert_base_infer' row ('-'→'_' normalization parity)."""
        with Client(port=server.port) as c:
            preds = c.impute_configurations("bert-base-infer-7f9c")
        assert preds["1P_V5E"] == pytest.approx(3900.0)
        # The blank 4P_V5P cell was imputed to something finite/positive.
        assert np.isfinite(preds["4P_V5P"]) and preds["4P_V5P"] > 0

    def test_interference_keyed_by_workload_gen(self, server):
        with Client(port=server.port) as c:
            row = c.impute_interference("llama3-8b-serve-0_V5E")
        assert row["resnet50_train"] == pytest.approx(118.0)

    def test_unknown_workload_empty_reply(self, server):
        with Client(port=server.port) as c:
            assert c.impute_configurations("nosuch-workload") == {}

    def test_find_max_index(self):
        preds = {"1P_V5E": 100.0, "2P_V5E": 60.0, "1P_V5P": 150.0}
        assert find_max_index(preds) == ("1P_V5P", 150.0)
        assert find_max_index(preds, "V5E") == ("1P_V5E", 100.0)

    def test_client_ttl_cache_short_circuits_repeats(self, server):
        """Within the TTL a repeated (method, index) query never leaves the
        client — scoring N nodes against the same resident pods repeats
        identical queries, and the server only changes on its 30 s retrain
        cadence. Distinct methods/indices stay distinct, a served ERROR is
        not cached, and ttl=0 disables the memo."""
        with Client(port=server.port, cache_ttl_s=60.0) as c:
            calls = {"n": 0}
            orig = c._conf

            def counting(index, timeout=None):
                calls["n"] += 1
                return orig(index, timeout=timeout)

            c._conf = counting
            a = c.impute_configurations("bert-base-infer")
            b = c.impute_configurations("bert-base-infer")
            assert a == b and calls["n"] == 1        # second hit cached
            c.impute_configurations("resnet50-train")
            assert calls["n"] == 2                   # distinct index: miss
            # SAME index through the other METHOD must be a separate cache
            # key (a regression keying on index alone would serve
            # configuration rows to interference queries).
            intf = c.impute_interference("bert-base-infer")
            assert calls["n"] == 2                   # own channel, not _conf
            assert intf != a
            # Mutating a returned reply must not poison later cache hits.
            a_again = c.impute_configurations("bert-base-infer")
            a_again["1P_V5E"] = -1.0
            assert c.impute_configurations("bert-base-infer")["1P_V5E"] != -1.0
        with Client(port=server.port, cache_ttl_s=0.0) as c:
            calls = {"n": 0}
            orig = c._conf

            def counting0(index, timeout=None):
                calls["n"] += 1
                return orig(index, timeout=timeout)

            c._conf = counting0
            c.impute_configurations("bert-base-infer")
            c.impute_configurations("bert-base-infer")
            assert calls["n"] == 2                   # ttl=0: no memo

    def test_client_does_not_cache_errors(self, server):
        """A transient failure must not pin an error (or stale emptiness)
        for the TTL — only successful replies are memoized."""
        with Client(port=server.port, cache_ttl_s=60.0) as c:
            fail = {"on": True}
            orig = c._conf

            def flaky(index, timeout=None):
                if fail["on"]:
                    raise RuntimeError("transient")
                return orig(index, timeout=timeout)

            c._conf = flaky
            with pytest.raises(RuntimeError):
                c.impute_configurations("bert-base-infer")
            fail["on"] = False
            preds = c.impute_configurations("bert-base-infer")
            assert preds["1P_V5E"] == pytest.approx(3900.0)

    def test_plugin_consumes_real_service(self, server):
        """The gRPC client satisfies plugins.tpu.PredictionClient: the
        SLO-slack scorer runs against the live server."""
        from k8s_gpu_scheduler_tpu.api.objects import (
            Container, EnvVar, PodSpec, Pod, ObjectMeta, ResourceRequirements,
            TPU_RESOURCE,
        )
        from k8s_gpu_scheduler_tpu.cluster import APIServer
        from k8s_gpu_scheduler_tpu.config import SchedulerConfig
        from k8s_gpu_scheduler_tpu.plugins import TPUPlugin
        from k8s_gpu_scheduler_tpu.sched import CycleState, Profile, Scheduler
        from tests.test_plugins import FakeRegistry, mk_node

        reg = FakeRegistry()
        reg.publish("n1", utilization=0.0)
        sched = Scheduler(APIServer(), profile=Profile(),
                          config=SchedulerConfig())
        with Client(port=server.port) as rec:
            plugin = TPUPlugin(sched.handle, registry=reg, recommender=rec)
            sched.cache.add_node(mk_node("n1"))
            state = CycleState()
            pod = Pod(
                metadata=ObjectMeta(name="bert-base-infer-0"),
                spec=PodSpec(containers=[Container(
                    env=[EnvVar("SLO", "2000")],
                    resources=ResourceRequirements(requests={TPU_RESOURCE: 8}),
                )]),
            )
            plugin.pre_filter(state, pod)
            assert plugin.filter(state, pod, sched.cache.snapshot()["n1"]).ok
            score, st = plugin.score(state, pod, "n1")
            assert st.ok
            # 1P_V5E predicts 3900 vs SLO 2000 → satisfied → positive score.
            assert score > 50


class TestRetrain:
    def test_md5_watch_hot_swap(self, tmp_path):
        conf = tmp_path / "conf.tsv"
        intf = tmp_path / "intf.tsv"
        conf.write_text("workload\t1P_V5E\njob_a\t100\n")
        intf.write_text("pair\tjob_a\njob_a_V5E\t5\n")
        srv = RecommenderServer(str(conf), str(intf), port=0,
                                retrain_interval_s=0.05).start()
        try:
            with Client(port=srv.port) as c:
                assert c.impute_configurations("job_a")["1P_V5E"] == 100.0
                conf.write_text("workload\t1P_V5E\njob_a\t250\n")
                deadline = time.time() + 5
                while time.time() < deadline:
                    if c.impute_configurations("job_a")["1P_V5E"] == 250.0:
                        break
                    time.sleep(0.05)
                assert c.impute_configurations("job_a")["1P_V5E"] == 250.0
        finally:
            srv.stop()


class TestCollector:
    """The observed-throughput feedback loop (recommender/collector.py):
    workload publishes → collector folds into the TSV → md5 retrain →
    imputation replies anchored on measurement (VERDICT.md weak #5)."""

    @staticmethod
    def _seed_tsv(tmp_path):
        src = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "..",
            "k8s_gpu_scheduler_tpu", "recommender", "data",
            "configurations_train.tsv")
        dst = str(tmp_path / "conf.tsv")
        with open(src) as f, open(dst, "w") as g:
            g.write(f.read())
        return dst

    def test_observation_fills_blank_cell_and_shows_in_reply(self, tmp_path):
        from k8s_gpu_scheduler_tpu.recommender.collector import (
            Collector, publish_observation,
        )
        from k8s_gpu_scheduler_tpu.recommender.server import _Table, load_matrix

        path = self._seed_tsv(tmp_path)
        reg = FakeRegistryKV()
        # llama3_8b_serve @ 4P_V5E is BLANK in the seed data.
        labels, columns, X = load_matrix(path)
        i, j = labels.index("llama3_8b_serve"), columns.index("4P_V5E")
        assert math.isnan(X[i][j])

        publish_observation(reg, "llama3_8b_serve", "4P_V5E", 13.5)
        collector = Collector(reg, path, interval_s=999)
        assert collector.collect_once()

        table = _Table(path)  # fresh load = what the md5 retrain produces
        result, cols = table.lookup("llama3-8b-serve-0")
        assert result[cols.index("4P_V5E")] == pytest.approx(13.5)

    def test_p99_sample_folds_into_latency_key_by_ewma(self, tmp_path):
        """Serving p99 samples (Observation.p99_ms) land in
        latency/<workload>/<column> registry keys — what the TPU plugin's
        rightsize/score read back (VERDICT r4 #3: right-size against
        MEASURED latency). First sample verbatim, repeats EWMA; a sample
        with p99 0 (throughput-only workloads) never writes the key."""
        from k8s_gpu_scheduler_tpu.recommender.collector import (
            Collector, publish_observation,
        )
        from k8s_gpu_scheduler_tpu.registry.inventory import latency_key

        path = self._seed_tsv(tmp_path)
        reg = FakeRegistryKV()
        key = latency_key("llama3_8b_serve", "4P_V5E")
        collector = Collector(reg, path, interval_s=999, alpha=0.5)

        publish_observation(reg, "llama3_8b_serve", "4P_V5E", 13.5)
        collector.collect_once()
        assert reg.get(key) is None          # no p99 measured → no key

        publish_observation(reg, "llama3_8b_serve", "4P_V5E", 13.5,
                            p99_ms=200.0)
        collector.collect_once()
        assert float(reg.get(key)) == pytest.approx(200.0)

        publish_observation(reg, "llama3_8b_serve", "4P_V5E", 13.5,
                            p99_ms=100.0)
        collector.collect_once()
        assert float(reg.get(key)) == pytest.approx(150.0)   # EWMA alpha .5

    def test_measured_cell_moves_by_ewma(self, tmp_path):
        from k8s_gpu_scheduler_tpu.recommender.collector import (
            Collector, publish_observation,
        )
        from k8s_gpu_scheduler_tpu.recommender.server import load_matrix

        path = self._seed_tsv(tmp_path)
        reg = FakeRegistryKV()
        # 1P_V5E for llama3_8b_serve is 46 in the seed; observe 60.
        publish_observation(reg, "llama3_8b_serve", "1P_V5E", 60.0)
        Collector(reg, path, interval_s=999, alpha=0.5).collect_once()
        labels, columns, X = load_matrix(path)
        got = X[labels.index("llama3_8b_serve")][columns.index("1P_V5E")]
        assert got == pytest.approx(0.5 * 60 + 0.5 * 46)

    def test_new_workload_appends_row_unknown_column_dropped(self, tmp_path):
        from k8s_gpu_scheduler_tpu.recommender.collector import (
            Collector, publish_observation,
        )
        from k8s_gpu_scheduler_tpu.recommender.server import load_matrix

        path = self._seed_tsv(tmp_path)
        reg = FakeRegistryKV()
        publish_observation(reg, "llama3_8b_pretrain", "8P_V5E", 81060.0)
        publish_observation(reg, "llama3_8b_pretrain", "3P_WEIRD", 1.0)
        Collector(reg, path, interval_s=999).collect_once()
        labels, columns, X = load_matrix(path)
        assert "llama3_8b_pretrain" in labels
        assert "3P_WEIRD" not in columns
        row = X[labels.index("llama3_8b_pretrain")]
        assert row[columns.index("8P_V5E")] == pytest.approx(81060.0)
        # Second pass with identical data: no spurious rewrite (md5 stable).
        assert not Collector(reg, path, interval_s=999).collect_once()

    def test_stale_sample_folded_only_once(self, tmp_path):
        """A sample left sitting in the registry (workload stopped
        publishing) is folded exactly once: re-folding every 30 s pass would
        converge the cell to the raw sample — defeating the EWMA damping —
        and rewrite the TSV (retraining the server) forever (ADVICE r3
        medium)."""
        from k8s_gpu_scheduler_tpu.recommender.collector import (
            Collector, publish_observation,
        )
        from k8s_gpu_scheduler_tpu.recommender.server import load_matrix

        path = self._seed_tsv(tmp_path)
        reg = FakeRegistryKV()
        publish_observation(reg, "llama3_8b_serve", "1P_V5E", 60.0)
        collector = Collector(reg, path, interval_s=999, alpha=0.5)
        assert collector.collect_once()
        # Same sample still in the registry: later passes must not re-fold.
        assert not collector.collect_once()
        labels, columns, X = load_matrix(path)
        got = X[labels.index("llama3_8b_serve")][columns.index("1P_V5E")]
        assert got == pytest.approx(0.5 * 60 + 0.5 * 46)  # folded ONCE
        # A genuinely new sample (fresh timestamp) folds again.
        publish_observation(reg, "llama3_8b_serve", "1P_V5E", 60.0)
        assert collector.collect_once()

    def test_colocation_delta_folds_into_interference_matrix(self, tmp_path):
        """VERDICT r3 #7 'done' criterion: a neighbors-tagged sample updates
        an interference row, and the next ImputeInterference reflects it.
        Solo baseline 20 QPS, co-located 14 alongside one neighbor →
        degradation 6."""
        import shutil

        from k8s_gpu_scheduler_tpu.recommender.collector import (
            Collector, publish_observation,
        )
        from k8s_gpu_scheduler_tpu.recommender.server import _Table, load_matrix

        conf = self._seed_tsv(tmp_path)
        intf = str(tmp_path / "intf.tsv")
        shutil.copy(
            os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "..",
                "k8s_gpu_scheduler_tpu", "recommender", "data",
                "interference_train.tsv"), intf)
        reg = FakeRegistryKV()
        collector = Collector(reg, conf, interval_s=999,
                              interference_path=intf)
        # Solo baseline first (no neighbors → configurations).
        publish_observation(reg, "llama3_8b_serve", "4P_V5E", 20.0)
        assert collector.collect_once()
        # Then a co-located sample: 14 QPS next to bert_base_serve.
        publish_observation(reg, "llama3_8b_serve", "4P_V5E", 14.0,
                            neighbors=["bert_base_serve"])
        assert collector.collect_once()

        labels, columns, X = load_matrix(intf)
        assert "llama3_8b_serve_V5E" in labels
        assert "bert_base_serve" in columns
        i = labels.index("llama3_8b_serve_V5E")
        j = columns.index("bert_base_serve")
        assert X[i][j] == pytest.approx(6.0)
        # The serving table sees it on the next (md5-triggered) reload.
        table = _Table(intf)
        result, cols = table.lookup("llama3-8b-serve-0_V5E")
        assert result[cols.index("bert_base_serve")] == pytest.approx(6.0)

    def test_interference_sample_without_baseline_deferred(self, tmp_path):
        """A co-located sample with no solo baseline can't produce a delta
        — it must be skipped without corrupting either matrix."""
        import shutil

        from k8s_gpu_scheduler_tpu.recommender.collector import (
            Collector, publish_observation,
        )

        conf = self._seed_tsv(tmp_path)
        intf = str(tmp_path / "intf.tsv")
        shutil.copy(
            os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "..",
                "k8s_gpu_scheduler_tpu", "recommender", "data",
                "interference_train.tsv"), intf)
        before = open(intf).read()
        reg = FakeRegistryKV()
        collector = Collector(reg, conf, interval_s=999,
                              interference_path=intf)
        publish_observation(reg, "never_measured_workload", "4P_V5E", 9.0,
                            neighbors=["bert_base_serve"])
        assert not collector.collect_once()
        assert open(intf).read() == before
        # Genuinely deferred, not dropped: once the solo baseline lands,
        # the SAME (unchanged-timestamp) sample folds on the next pass.
        publish_observation(reg, "never_measured_workload", "4P_V5E", 15.0)
        assert collector.collect_once()
        from k8s_gpu_scheduler_tpu.recommender.server import load_matrix

        labels, columns, X = load_matrix(intf)
        i = labels.index("never_measured_workload_V5E")
        j = columns.index("bert_base_serve")
        assert X[i][j] == pytest.approx(15.0 - 9.0)

    def test_end_to_end_through_grpc_server(self, tmp_path):
        """Full loop over the wire: gRPC reply BEFORE vs AFTER an
        observation lands and the md5-watch retrains."""
        from k8s_gpu_scheduler_tpu.recommender.client import Client
        from k8s_gpu_scheduler_tpu.recommender.collector import (
            Collector, publish_observation,
        )
        from k8s_gpu_scheduler_tpu.recommender.server import RecommenderServer

        conf = self._seed_tsv(tmp_path)
        intf = str(tmp_path / "intf.tsv")
        with open(intf, "w") as f:
            f.write("workload\tllama3_8b_serve\nllama3_8b_serve\t1.0\n")
        server = RecommenderServer(conf, intf, port=0,
                                   retrain_interval_s=0.1).start()
        try:
            # ttl=0: this test polls for retrain freshness — the client's
            # reply memo would otherwise hide the new matrix for its TTL.
            client = Client("127.0.0.1", server.port, cache_ttl_s=0.0)
            before = client.impute_configurations("llama3-8b-serve-0")
            assert before, "seed lookup must hit"
            reg = FakeRegistryKV()
            publish_observation(reg, "llama3_8b_serve", "4P_V5E", 13.5)
            Collector(reg, conf, interval_s=999).collect_once()
            deadline = time.time() + 5
            after = {}
            while time.time() < deadline:
                after = client.impute_configurations("llama3-8b-serve-0")
                if after.get("4P_V5E") == pytest.approx(13.5):
                    break
                time.sleep(0.1)
            assert after.get("4P_V5E") == pytest.approx(13.5)
        finally:
            server.stop()
