"""Fleet tier: cache-aware routing + snapshot load shedding + the
serve-entrypoint preemption lifecycle.

Proof obligations of the fleet PR:

- **Scoring determinism** — placement is a pure function of the
  published summaries: same summaries, same placements, always (the
  tiebreak is the lowest replica id, never iteration order or a clock).
- **Migration token identity** — a request finishes byte-identically
  whether it stays on its original replica or is shed mid-stream
  (partial ``drain(slots=...)`` → ``absorb``) to another.
- **Refcount consistency** — ``PageAllocator.assert_consistent`` holds
  on BOTH engines after a shed, including when two shed slots share a
  mounted prefix page.
- **Degraded routing** — stale or unreachable summaries downgrade to
  deterministic round-robin (worse placement, never a crash).
- **Lifecycle** — SIGTERM/``Preempted`` → drain → orbax persist →
  ``resume_or_fresh`` resumes token-identically (models/lifecycle.py).
- **Crash tolerance** (the non-cooperative failure matrix) — a HARD
  replica kill (engine discarded, no drain) at any point — during
  prefill, mid-decode, right after a shed (source or target), twice in
  a row — loses zero requests: the router's journal replays them onto
  survivors and every stream stays byte-identical to the no-fault
  reference; flapping replicas quarantine on a growing backoff and
  rejoin serving; deadlines expire with surfaced errors; the journal
  round-trips orbax and a restarted router resumes from it.
"""
import dataclasses
import os
import signal
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_scheduler_tpu.fleet import (
    DEAD, FleetError, HealthMonitor, HealthPolicy, JournalError, LIVE,
    MemoryStore, QUARANTINED, REJOINING, ReplicaSummary, RequestJournal,
    Router, SUSPECT, list_summaries, prefix_match_len, publish_summary,
    summarize,
)
from k8s_gpu_scheduler_tpu.metrics.exporter import (
    FLEET_EXPIRED_TOTAL, FLEET_FAILOVERS_TOTAL, FLEET_JOURNAL_SIZE,
    FLEET_LOST_TOTAL, FLEET_MIGRATED_TOTAL, FLEET_REPLAYED_TOKENS_TOTAL,
    FLEET_REPLICA_STATE, FLEET_ROUTED_TOTAL, FLEET_SHED_TOTAL, Registry,
)
from k8s_gpu_scheduler_tpu.models import LlamaConfig, init_params
from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher
from k8s_gpu_scheduler_tpu.models.snapshot import (
    ServingSnapshot, SnapshotError,
)
from k8s_gpu_scheduler_tpu.obs import VirtualClock
from k8s_gpu_scheduler_tpu.testing.faults import (
    FaultInjector, FaultProxy, FaultRule, Preempted, ReplicaCrashed,
)
from k8s_gpu_scheduler_tpu.utils.retry import RetryPolicy

PAGE = 8


@pytest.fixture(scope="module")
def setup():
    cfg = dataclasses.replace(LlamaConfig.tiny(), dtype=jnp.float32)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def mk_engine(params, cfg, **kw):
    base = dict(n_slots=4, max_len=64, chunk=4, prefill_bucket=8,
                kv_layout="paged", page_size=PAGE, prefix_cache=True)
    base.update(kw)
    return ContinuousBatcher(params, cfg, **base)


def mk_workload(cfg, n=10, n_classes=2, seed=0):
    """n prompts over n_classes shared 2-page system prefixes."""
    rng = np.random.default_rng(seed)
    hot = [list(rng.integers(0, cfg.vocab, 2 * PAGE))
           for _ in range(n_classes)]
    prompts = [hot[i % n_classes]
               + list(rng.integers(0, cfg.vocab, 2 + i % 5))
               for i in range(n)]
    return prompts, hot


def reference(params, cfg, prompts, max_new=8, **kw):
    """Single-engine streams — greedy decode does not depend on
    placement, so one engine's answers are every fleet's truth."""
    eng = mk_engine(params, cfg, **kw)
    ids = [eng.submit(p, max_new=max_new) for p in prompts]
    done = {}
    while eng.pending:
        done.update(eng.step())
    return [done[i] for i in ids]


# -- summary / scoring primitives -----------------------------------------
class TestSummary:
    def test_prefix_match_len_page_floor_and_full_cover_cap(self):
        path = list(range(100, 124))                 # 3 pages cached
        digest = [(path, 24)]
        # 20 shared tokens -> floor to 2 pages = 16.
        assert prefix_match_len(path[:20] + [1, 2], digest, PAGE) == 16
        # Full cover (prompt == cached path): the last page always
        # re-prefills (admission needs last-position logits) -> 16.
        assert prefix_match_len(path, digest, PAGE) == 16
        # Under one page -> 0; disjoint -> 0.
        assert prefix_match_len(path[:5], digest, PAGE) == 0
        assert prefix_match_len([1, 2, 3] * 10, digest, PAGE) == 0

    def test_match_len_respects_truncated_digest(self):
        # A digest path truncated to 8 tokens under-claims (8-token
        # match) even though 24 tokens are cached.
        digest = [(list(range(100, 108)), 24)]
        prompt = list(range(100, 124)) + [7]
        assert prefix_match_len(prompt, digest, PAGE) == 8

    def test_summary_json_roundtrip_and_listing(self):
        store = MemoryStore()
        s = ReplicaSummary(replica="r1", fleet="f", seq=3,
                           published_wall=12.5, page_size=PAGE,
                           pages_total=32, pages_free=10, n_slots=4,
                           active_slots=2, queued=1, decode_p50_s=0.01,
                           digest=[([1, 2, 3], 8)])
        publish_summary(store, s)
        publish_summary(store, ReplicaSummary(replica="r2", fleet="f"))
        publish_summary(store, ReplicaSummary(replica="rX", fleet="g"))
        got = list_summaries(store, "f")
        assert set(got) == {"r1", "r2"}
        assert got["r1"] == s

    def test_summarize_reads_live_engine(self, setup):
        cfg, params = setup
        eng = mk_engine(params, cfg)
        prompts, _ = mk_workload(cfg, n=2)
        for p in prompts:
            eng.submit(p, max_new=8)
        eng.step()
        s = summarize(eng, "r0", fleet="f", seq=1, now_wall=5.0)
        assert s.active_slots == 2 and s.page_size == PAGE
        assert s.pages_free < s.pages_total
        # Donations appear in the digest after the requests reap.
        while eng.pending:
            eng.step()
        s2 = summarize(eng, "r0")
        assert s2.digest and s2.active_slots == 0


class TestScoring:
    def summaries(self):
        base = dict(fleet="f", published_wall=0.0, page_size=PAGE,
                    pages_total=32, n_slots=4)
        return {
            "r0": ReplicaSummary(replica="r0", pages_free=32,
                                 active_slots=0, **base),
            "r1": ReplicaSummary(replica="r1", pages_free=32,
                                 active_slots=0, **base),
        }

    def router(self, setup, **kw):
        cfg, params = setup
        return Router([("r0", mk_engine(params, cfg)),
                       ("r1", mk_engine(params, cfg))], **kw)

    def test_match_dominates_equal_load(self, setup):
        r = self.router(setup)
        subs = self.summaries()
        subs["r1"].digest = [(list(range(16)), 16)]
        prompt = list(range(16)) + [99]
        s0, m0 = r.score(subs["r0"], prompt)
        s1, m1 = r.score(subs["r1"], prompt)
        assert m1 == 16 and m0 == 0 and s1 > s0

    def test_load_breaks_ties_and_id_breaks_exact_ties(self, setup):
        r = self.router(setup)
        subs = self.summaries()
        subs["r1"].active_slots = 4           # busy
        subs["r1"].pages_free = 2
        prompt = [1, 2, 3]
        s0, _ = r.score(subs["r0"], prompt)
        s1, _ = r.score(subs["r1"], prompt)
        assert s0 > s1
        # Prefill backlog alone breaks an otherwise exact tie: a
        # replica mid-way through chunking a long prompt looks free on
        # the page/slot axes, so the backlog term must be what moves
        # the next long prompt elsewhere.
        subs = self.summaries()
        subs["r0"].prefill_backlog_tokens = 512
        s0, _ = r.score(subs["r0"], prompt)
        s1, _ = r.score(subs["r1"], prompt)
        assert s1 > s0
        # Exactly equal summaries -> the lowest replica id wins.
        fresh = self.router(setup)
        rid, policy, _ = fresh.route(prompt)
        assert (rid, policy) == ("r0", "affinity")

    def test_same_summaries_same_placement(self, setup):
        """Determinism: routing is a pure function of the published
        summaries — two routers fed byte-identical summary stores
        route an identical prompt sequence identically (no engine
        steps involved: route() never consults the engines)."""
        cfg, params = setup
        rng = np.random.default_rng(11)
        prompts = [list(rng.integers(0, cfg.vocab, 4 + i % 9))
                   for i in range(12)]
        digests = {
            "r0": [(prompts[0][:PAGE], PAGE)],
            "r1": [(prompts[1][:2 * PAGE], 2 * PAGE)],
        }
        backlogs = {"r0": 96, "r1": 0}       # chunked-prefill pressure

        def placements():
            r = self.router(setup)
            for rid, s in self.summaries().items():
                s.fleet = r.fleet
                s.digest = digests[rid]
                s.prefill_backlog_tokens = backlogs[rid]
                s.published_wall = r._clock.wall()
                publish_summary(r._store, s)
            return [r.route(p) for p in prompts]

        first = placements()
        assert first == placements()
        assert {pol for _, pol, _ in first} == {"affinity"}

    def test_decode_p50_pressure_discounts(self, setup):
        r = self.router(setup)
        subs = self.summaries()
        slow = dataclasses.replace(subs["r1"], decode_p50_s=10.0)
        s_fast, _ = r.score(subs["r1"], [1, 2])
        s_slow, _ = r.score(slow, [1, 2])
        assert s_slow < s_fast

    def test_prefill_backlog_pressure_discounts(self, setup):
        """The chunked-prefill complement of the decode-p50 test: a
        replica with admitted-but-unfinished prefill scores below an
        idle twin, monotonically in the backlog, and a live mid-prefill
        engine publishes the backlog in its summary."""
        cfg, params = setup
        r = self.router(setup)
        subs = self.summaries()
        idle, _ = r.score(subs["r1"], [1, 2])
        mild, _ = r.score(dataclasses.replace(
            subs["r1"], prefill_backlog_tokens=512), [1, 2])
        flood, _ = r.score(dataclasses.replace(
            subs["r1"], prefill_backlog_tokens=8192), [1, 2])
        assert idle > mild > flood
        eng = mk_engine(params, cfg, prefill_chunk_tokens=PAGE,
                        max_len=128)
        eng.submit(list(np.random.default_rng(9).integers(
            0, cfg.vocab, 5 * PAGE)), max_new=4)
        eng.step()
        s = summarize(eng, "r0")
        assert s.prefill_backlog_tokens == 4 * PAGE
        while eng.pending:
            eng.step()
        assert summarize(eng, "r0").prefill_backlog_tokens == 0


# -- partial drain / absorb ------------------------------------------------
class TestShedMigration:
    def test_shed_is_token_identical_and_consistent(self, setup):
        """The acceptance core: mid-stream shed of two slots; every
        stream (migrated or not) byte-equal to the uninterrupted
        reference; both allocators consistent; source keeps serving."""
        cfg, params = setup
        prompts, _ = mk_workload(cfg, n=6)
        ref = reference(params, cfg, prompts)
        src = mk_engine(params, cfg)
        dst = mk_engine(params, cfg)
        ids = [src.submit(p, max_new=8) for p in prompts]
        done = {}
        done.update(src.step())
        shed = src.active_slot_ids()[:2]
        snap = src.drain(slots=shed)
        assert snap.partial and len(snap.slot_req) == 2
        shed_rids = set(snap.slot_req.values())
        # Codec round trip: a shed snapshot may cross a process.
        snap = ServingSnapshot.from_pytree(snap.to_pytree())
        mapping = dst.absorb(snap)
        assert set(mapping) == shed_rids
        src._alloc.assert_consistent()
        dst._alloc.assert_consistent()
        # Source is NOT drained: it keeps admitting and serving.
        extra = src.submit(prompts[0], max_new=4)
        while src.pending:
            done.update(src.step())
        dst_done = {}
        while dst.pending:
            dst_done.update(dst.step())
        src._alloc.assert_consistent()
        dst._alloc.assert_consistent()
        got = []
        for rid in ids:
            if rid in shed_rids:
                got.append(dst_done[mapping[rid]])
            else:
                got.append(done[rid])
        assert got == ref
        assert len(done[extra]) == 4
        # Flight recorders logged the handoff on both sides.
        assert src._flight.records("shed")
        assert dst._flight.records("absorb")
        # Engine-level shed/resume gauges moved.
        assert src.pool_metrics()["requests_shed_total"] == 2.0
        assert dst.pool_metrics()["requests_resumed_total"] == 2.0

    @pytest.mark.slow
    def test_shared_prefix_page_shed_together(self, setup):
        """Two shed slots MOUNTING THE SAME cached prefix page: the
        page ships once, allocs once on the target, and the extra
        holder retains — the refcount partition survives on both
        ends."""
        cfg, params = setup
        prompts, hot = mk_workload(cfg, n=1, n_classes=1)
        src = mk_engine(params, cfg)
        # Warm the tree: one request of the hot class reaps + donates.
        warm = src.submit(prompts[0], max_new=2)
        while src.pending:
            src.step()
        rng = np.random.default_rng(7)
        pair = [hot[0] + list(rng.integers(0, cfg.vocab, 3)),
                hot[0] + list(rng.integers(0, cfg.vocab, 4))]
        ref = reference(params, cfg, [prompts[0]] + pair)[1:]
        ids = [src.submit(p, max_new=8) for p in pair]
        src.step()
        for slot in src.active_slot_ids():
            assert src._slot_shared[slot]     # both mounted the hit
        snap = src.drain(slots=src.active_slot_ids())
        dst = mk_engine(params, cfg)
        mapping = dst.absorb(snap)
        src._alloc.assert_consistent()
        dst._alloc.assert_consistent()
        done = {}
        while dst.pending:
            done.update(dst.step())
        dst._alloc.assert_consistent()
        assert [done[mapping[r]] for r in ids] == ref

    def test_partial_drain_validations(self, setup):
        cfg, params = setup
        prompts, _ = mk_workload(cfg, n=3)
        eng = mk_engine(params, cfg)
        for p in prompts:
            eng.submit(p, max_new=8)
        eng.step()
        with pytest.raises(ValueError, match="inactive slot"):
            eng.drain(slots=[99])
        with pytest.raises(ValueError, match="at least one"):
            eng.drain(slots=[])
        snap = eng.drain(slots=eng.active_slot_ids()[:1])
        # restore() refuses partial snapshots...
        fresh = mk_engine(params, cfg)
        with pytest.raises(SnapshotError, match="partial"):
            fresh.restore(snap)
        # ...and absorb() refuses full ones.
        full = eng.drain()
        busy = mk_engine(params, cfg)
        busy.submit(prompts[0], max_new=4)
        with pytest.raises(SnapshotError, match="PARTIAL"):
            busy.absorb(full)

    @pytest.mark.slow
    def test_absorb_needs_free_slots(self, setup):
        cfg, params = setup
        prompts, _ = mk_workload(cfg, n=8)
        src = mk_engine(params, cfg)
        dst = mk_engine(params, cfg, n_slots=1)
        with pytest.raises(SnapshotError):
            # Fingerprints differ (n_slots) — rejected before slots
            # even get counted.
            for p in prompts:
                src.submit(p, max_new=8)
            src.step()
            dst.absorb(src.drain(slots=src.active_slot_ids()))
        # Same geometry, but the target is full.
        src2 = mk_engine(params, cfg)
        dst2 = mk_engine(params, cfg)
        for p in prompts:
            src2.submit(p, max_new=8)
            dst2.submit(p, max_new=8)
        src2.step()
        dst2.step()
        with pytest.raises(SnapshotError, match="free here"):
            dst2.absorb(src2.drain(slots=src2.active_slot_ids()))


# -- router end to end -----------------------------------------------------
class TestRouterEndToEnd:
    @pytest.mark.slow  # double-covered (PR 15 budget): the fleet bench
    # CI step drives the same forced-shed e2e (token identity vs the
    # single-engine reference + migration counters) on every push, and
    # test_shed_is_token_identical_and_consistent keeps the shed
    # machinery tier-1.
    def test_fleet_run_with_forced_shed_token_identity(self, setup):
        cfg, params = setup
        prompts, _ = mk_workload(cfg, n=12, n_classes=3)
        ref = reference(params, cfg, prompts)
        reg = Registry()
        router = Router([(f"r{i}", mk_engine(params, cfg))
                         for i in range(3)], metrics=reg)
        frids, done = [], {}
        for i, p in enumerate(prompts):
            frids.append(router.submit(p, max_new=8))
            if i % 3 == 2:                   # keep several in flight
                done.update(router.step())
            if i == 7:
                stats = {r: rep.engine.replica_stats()
                         for r, rep in router._replicas.items()}
                src = max(stats, key=lambda r: (
                    stats[r]["active_slots"], r))
                dst = min(stats, key=lambda r: (
                    stats[r]["active_slots"], r))
                active = router._replicas[src].engine.active_slot_ids()
                assert active and src != dst
                moved = router.shed(src, dst, slots=active)
                assert moved == len(active) >= 1
        done.update(router.run())
        assert [done[f] for f in frids] == ref
        for rep in router._replicas.values():
            rep.engine._alloc.assert_consistent()
        st = router.stats()
        assert st["aggregate_prefix_hit_rate"] > 0
        assert st["degraded_routes"] == 0
        routed = sum(
            reg.counter(FLEET_ROUTED_TOTAL).value(
                replica=f"r{i}", policy="affinity") for i in range(3))
        assert routed == len(prompts)
        migrated = sum(
            reg.counter(FLEET_MIGRATED_TOTAL).value(replica=f"r{i}")
            for i in range(3))
        shed = sum(
            reg.counter(FLEET_SHED_TOTAL).value(replica=f"r{i}")
            for i in range(3))
        assert migrated == shed >= 1
        # Migration-safe latency records: every request closed one.
        met = router.pop_request_metrics()
        assert set(met) == set(frids)

    def test_affinity_routes_hot_class_to_warm_replica(self, setup):
        cfg, params = setup
        prompts, hot = mk_workload(cfg, n=2, n_classes=2)
        router = Router([("r0", mk_engine(params, cfg)),
                         ("r1", mk_engine(params, cfg))])
        # Warm r0 with class 0 end to end (reap donates + publish).
        f0 = router.submit(prompts[0], max_new=4)
        first = router.locate(f0)[0]
        router.run()
        rng = np.random.default_rng(3)
        again = hot[0] + list(rng.integers(0, cfg.vocab, 3))
        f1 = router.submit(again, max_new=4)
        # Same class follows the cache; the warm replica's digest won.
        assert router.locate(f1)[0] == first
        router.run()

    def test_followup_turn_routes_to_conversation_replica(self, setup):
        """The 2-turn chat edition of affinity-follows-warm-cache: turn
        1 lands somewhere, its reap donates PROMPT + DECODED pages into
        that replica's tree, the digest publishes the transcript — so
        turn 2 (whose prompt IS the transcript + new user text) must
        route back to the replica holding the conversation, and its
        prefill must actually skip the transcript's pages."""
        cfg, params = setup
        router = Router([("r0", mk_engine(params, cfg)),
                         ("r1", mk_engine(params, cfg))])
        rng = np.random.default_rng(7)
        p1 = list(rng.integers(0, cfg.vocab, 2 * PAGE))
        f1 = router.submit(p1, max_new=12)
        first = router.locate(f1)[0]
        done = router.run()
        turn1 = done[f1]
        holder = router._replica(first).engine
        assert holder.pool_metrics()["decoded_pages_donated_total"] >= 1
        skipped0 = holder.pool_metrics()["prefill_tokens_skipped"]
        # Turn 2: the whole transcript + new user text. The digest now
        # carries the conversation path (prompt + decoded), so the
        # match length dominates the otherwise-identical scores.
        p2 = p1 + turn1 + list(rng.integers(0, cfg.vocab, 3))
        f2 = router.submit(p2, max_new=4)
        assert router.locate(f2)[0] == first
        router.run()
        skipped = holder.pool_metrics()["prefill_tokens_skipped"] - skipped0
        conv = len(p1) + len(turn1) - 1
        assert skipped >= (conv // PAGE) * PAGE > len(p1)
        holder._alloc.assert_consistent()

    def test_stale_summaries_degrade_to_round_robin(self, setup):
        cfg, params = setup
        clock = VirtualClock()
        router = Router([("r0", mk_engine(params, cfg)),
                         ("r1", mk_engine(params, cfg)),
                         ("r2", mk_engine(params, cfg))],
                        clock=clock, stale_s=1.0)
        assert router.route([1, 2, 3])[1] == "affinity"
        # Fresh summaries: a prefill-flooded r0 loses the otherwise
        # exact tie (the backlog discount steers around it).
        s0 = summarize(router._replica("r0").engine, "r0",
                       fleet=router.fleet, now_wall=clock.wall())
        s0.prefill_backlog_tokens = 10_000
        publish_summary(router._store, s0)
        router._summaries_cache = None
        assert router.route([1, 2, 3])[0] == "r1"
        clock.advance(5.0)                   # summaries now stale
        picks = [router.route([1, 2, 3]) for _ in range(4)]
        assert [p[1] for p in picks] == ["degraded"] * 4
        # Degraded round-robin is pressure-blind BY DESIGN: the flooded
        # r0 is back in rotation (bounded staleness degrades placement
        # quality, never the deterministic fallback).
        assert [p[0] for p in picks] == ["r0", "r1", "r2", "r0"]
        assert router.stats()["degraded_routes"] == 4
        router.publish()                     # fresh summaries again
        assert router.route([1, 2, 3])[1] == "affinity"

    def test_unreachable_store_degrades_not_crashes(self, setup):
        cfg, params = setup
        inj = FaultInjector(seed=0, rules=[
            FaultRule(site="fleetstore", kind="drop", every=1)])
        store = FaultProxy(MemoryStore(), inj, "fleetstore")
        router = Router([("r0", mk_engine(params, cfg)),
                         ("r1", mk_engine(params, cfg))], store=store)
        rid, policy, _ = router.route([1, 2, 3])
        assert policy == "degraded" and rid == "r0"
        frid = router.submit([1, 2, 3, 4], max_new=4)
        done = router.run()
        assert len(done[frid]) == 4
        assert router.stats()["store_errors"] > 0

    def test_maybe_shed_relieves_page_pressure(self, setup):
        cfg, params = setup
        # r0: tiny pool (11 usable pages) -> two mid-size requests
        # exhaust it; r1: default pool, idle.
        r0 = mk_engine(params, cfg, n_pages=12)
        r1 = mk_engine(params, cfg)
        router = Router([("r0", r0), ("r1", r1)], auto_shed=True)
        rng = np.random.default_rng(5)
        for _ in range(2):
            r0.submit(list(rng.integers(0, cfg.vocab, 28)), max_new=12)
        r0.step()
        assert r0.replica_stats()["pages_free"] <= 1
        moved = router.maybe_shed()
        assert moved >= 1
        r0._alloc.assert_consistent()
        r1._alloc.assert_consistent()
        assert r1.replica_stats()["active_slots"] >= 1

    def test_router_rejects_bad_fleets(self, setup):
        cfg, params = setup
        with pytest.raises(FleetError, match="at least one"):
            Router([])
        with pytest.raises(FleetError, match="duplicate"):
            Router([("r0", mk_engine(params, cfg)),
                    ("r0", mk_engine(params, cfg))])
        # Heterogeneous engines are rejected at CONSTRUCTION (anything
        # but n_pages) — discovering the mismatch mid-shed would strand
        # the drained requests.
        with pytest.raises(FleetError, match="shed-compatible"):
            Router([("r0", mk_engine(params, cfg)),
                    ("r1", mk_engine(params, cfg, page_size=16,
                                     prefill_bucket=16))])
        with pytest.raises(FleetError, match="shed-compatible"):
            Router([("r0", mk_engine(params, cfg)),
                    ("r1", mk_engine(params, cfg, n_slots=8))])
        # n_pages is exempt, exactly like restore: pool size may differ.
        Router([("r0", mk_engine(params, cfg)),
                ("r1", mk_engine(params, cfg, n_pages=40))])
        router = Router([("r0", mk_engine(params, cfg)),
                         ("r1", mk_engine(params, cfg))])
        with pytest.raises(FleetError, match="distinct"):
            router.shed("r0", "r0")
        with pytest.raises(FleetError, match="unknown replica"):
            router.shed("r0", "nope")


# -- serve-entrypoint lifecycle (SIGTERM / Preempted) ----------------------
class TestServeLifecycle:
    def test_preempted_drain_persist_resume_identity(self, setup,
                                                     tmp_path):
        """The chaos version of the SIGTERM path: an injected
        ``Preempted`` mid-run → drain_to_checkpoint → a 'replacement
        pod' resume_or_fresh → token-identical finish."""
        pytest.importorskip("orbax.checkpoint")
        from k8s_gpu_scheduler_tpu.models.lifecycle import (
            drain_to_checkpoint, resume_or_fresh,
        )
        cfg, params = setup
        prompts, _ = mk_workload(cfg, n=5)
        ref = reference(params, cfg, prompts, max_new=9)
        inj = FaultInjector(seed=1, rules=[
            FaultRule(site="serve.step", kind="preempt", at=[2])])
        eng = mk_engine(params, cfg, fault_injector=inj)
        ids = [eng.submit(p, max_new=9) for p in prompts]
        done = {}
        with pytest.raises(Preempted):
            while eng.pending:
                done.update(eng.step())
        snap = drain_to_checkpoint(eng, str(tmp_path / "snap"))
        assert snap.n_requests_in_flight > 0

        def make():
            return mk_engine(params, cfg)

        fresh, resumed = resume_or_fresh(make, str(tmp_path / "snap"))
        assert resumed == snap.n_requests_in_flight
        while fresh.pending:
            done.update(fresh.step())
        assert [done[i] for i in ids] == ref

    def test_second_preemption_of_a_pod_lineage_persists(self, setup,
                                                         tmp_path):
        """Regression: orbax's force= does not overwrite an existing
        step, so a pod lineage's SECOND drain (resume → serve → get
        preempted again) used to die with StepAlreadyExists; persist
        now advances the step with max_to_keep=1 and resume always
        reads the latest."""
        pytest.importorskip("orbax.checkpoint")
        from k8s_gpu_scheduler_tpu.models.lifecycle import (
            drain_to_checkpoint, resume_or_fresh,
        )
        cfg, params = setup
        d = str(tmp_path / "lineage")
        rng = np.random.default_rng(2)
        eng = mk_engine(params, cfg)
        eng.submit(list(rng.integers(0, cfg.vocab, 6)), max_new=6)
        drain_to_checkpoint(eng, d)
        eng2, resumed = resume_or_fresh(lambda: mk_engine(params, cfg),
                                        d)
        assert resumed == 1
        eng2.step()
        marker = eng2.submit(list(rng.integers(0, cfg.vocab, 5)),
                             max_new=3)
        drain_to_checkpoint(eng2, d)          # second preemption
        eng3, resumed3 = resume_or_fresh(lambda: mk_engine(params, cfg),
                                         d)
        assert resumed3 == eng3.pending >= 1  # the LATEST state loaded
        done = {}
        while eng3.pending:
            done.update(eng3.step())
        assert len(done[marker]) == 3

    def test_resume_or_fresh_without_snapshot(self, setup, tmp_path):
        pytest.importorskip("orbax.checkpoint")
        from k8s_gpu_scheduler_tpu.models.lifecycle import resume_or_fresh
        cfg, params = setup
        eng, resumed = resume_or_fresh(
            lambda: mk_engine(params, cfg), str(tmp_path / "none"))
        assert resumed == 0
        eng2, resumed2 = resume_or_fresh(
            lambda: mk_engine(params, cfg), None)
        assert resumed2 == 0

    def test_sigterm_sets_request_flag(self):
        from k8s_gpu_scheduler_tpu.models.lifecycle import PreemptionGuard
        prev = signal.getsignal(signal.SIGTERM)
        guard = PreemptionGuard().install()
        try:
            assert not guard.requested
            os.kill(os.getpid(), signal.SIGTERM)
            assert guard.requested
        finally:
            guard.uninstall()
        assert signal.getsignal(signal.SIGTERM) is prev

    def test_zero_page_snapshot_round_trips_through_orbax(self, setup,
                                                          tmp_path):
        """Regression: a drain with every slot finished (queue-only
        snapshot) has ZERO page payload rows — orbax refuses zero-size
        arrays, so the codec omits them and rebuilds from the recorded
        geometry."""
        pytest.importorskip("orbax.checkpoint")
        from k8s_gpu_scheduler_tpu.models.lifecycle import (
            load_snapshot, persist_snapshot,
        )
        cfg, params = setup
        eng = mk_engine(params, cfg, prefix_cache=False)
        rng = np.random.default_rng(0)
        ids = [eng.submit(list(rng.integers(0, cfg.vocab, 6)), max_new=3)
               for _ in range(2)]
        snap = eng.drain()      # nothing admitted yet: queue-only
        assert snap.page_ids == [] and len(snap.queue) == 2
        persist_snapshot(snap, str(tmp_path / "zp"))
        back = load_snapshot(str(tmp_path / "zp"))
        assert back.queue == snap.queue
        assert back.k_pages.shape == snap.k_pages.shape
        fresh = mk_engine(params, cfg, prefix_cache=False)
        assert fresh.restore(back) == 2
        done = {}
        while fresh.pending:
            done.update(fresh.step())
        assert all(len(done[i]) == 3 for i in ids)


# -- crash tolerance: health states, journal, deterministic replay --------
# Rejoin-friendly hold (rejoin paths wait it out in a step loop) vs a
# hold long enough that a rejoin can never interleave with a test's
# multi-kill choreography (the serving order must stay put while a
# second kill is armed).
FAST_QUARANTINE = RetryPolicy(attempts=8, base_s=0.02, multiplier=2.0,
                              max_s=0.1, jitter=0.5)
SLOW_QUARANTINE = RetryPolicy(attempts=8, base_s=60.0, multiplier=2.0,
                              max_s=60.0, jitter=0.0)


def mk_fleet(params, cfg, n=3, quarantine=FAST_QUARANTINE, **router_kw):
    """A crash-tolerant fleet: fresh-engine factory for rejoin and a
    test-speed quarantine ladder."""
    def factory(rid):
        return mk_engine(params, cfg)

    kw = dict(engine_factory=factory,
              health=HealthPolicy(quarantine=quarantine))
    kw.update(router_kw)
    return Router([(f"r{i}", mk_engine(params, cfg)) for i in range(n)],
                  **kw)


def kill_next(router, inj, rid):
    """Arm a hard kill of replica ``rid`` at the NEXT router step: the
    ``replica.crash`` site fires once per serving replica per step in id
    order, so the target's position in the serving list gives the
    deterministic call index."""
    order = [r for r in router._replicas if router.health.serving(r)]
    offset = order.index(rid) + 1
    inj.rules.append(FaultRule(site="replica.crash", kind="crash",
                               at=(inj.count("replica.crash") + offset,)))


class TestHealthMonitor:
    def test_error_ladder_and_redemption(self):
        hm = HealthMonitor(HealthPolicy(suspect_after=1, dead_after=3))
        hm.add("r0")
        boom = RuntimeError("x")
        assert hm.note_error("r0", boom, 1.0) == (LIVE, SUSPECT)
        assert hm.note_error("r0", boom, 2.0) is None      # still suspect
        assert hm.note_ok("r0", 3.0) == (SUSPECT, LIVE)    # redeemed
        for t in (4.0, 5.0):
            hm.note_error("r0", boom, t)
        assert hm.note_error("r0", boom, 6.0) == (SUSPECT, DEAD)

    def test_declare_dead_is_terminal_evidence(self):
        hm = HealthMonitor()
        hm.add("r0")
        assert hm.declare_dead("r0", "crash", 1.0) == (LIVE, DEAD)
        assert not hm.serving("r0") and not hm.routable("r0")

    def test_heartbeat_staleness_suspect_then_dead(self):
        hm = HealthMonitor(HealthPolicy(stale_s=5.0, dead_s=15.0))
        hm.add("r0")
        assert hm.observe("r0", 1.0, heartbeat_age_s=4.0) is None
        assert hm.observe("r0", 2.0, heartbeat_age_s=6.0) == \
            (LIVE, SUSPECT)
        assert hm.observe("r0", 3.0, heartbeat_age_s=16.0) == \
            (SUSPECT, DEAD)

    def test_watchdog_kills_wedged_engine(self):
        hm = HealthMonitor(HealthPolicy(watchdog_s=30.0))
        hm.add("r0")
        assert hm.observe("r0", 1.0, last_step_age_s=10.0) is None
        assert hm.observe("r0", 2.0, last_step_age_s=31.0) == (LIVE, DEAD)

    def test_policy_validates_threshold_order(self):
        with pytest.raises(ValueError, match="dead_s"):
            HealthPolicy(stale_s=5.0, dead_s=5.0)
        with pytest.raises(ValueError, match="dead_after"):
            HealthPolicy(suspect_after=3, dead_after=2)

    def test_quarantine_backoff_grows_and_breaker_latches(self):
        pol = HealthPolicy(quarantine=RetryPolicy(
            attempts=3, base_s=1.0, multiplier=2.0, max_s=100.0,
            jitter=0.0))
        hm = HealthMonitor(pol)
        hm.add("r0")
        hm.declare_dead("r0", "crash", 0.0)
        hm.quarantine("r0", 0.0)
        first_hold = hm.get("r0").quarantined_until
        assert first_hold == pytest.approx(1.0)
        assert not hm.due_for_rejoin("r0", 0.5)
        assert hm.due_for_rejoin("r0", 1.5)
        hm.start_rejoin("r0", 1.5)
        hm.rejoined("r0", 1.6)
        # Second death: longer hold (deaths are never reset — flap
        # memory is the point of the breaker).
        hm.declare_dead("r0", "crash again", 2.0)
        hm.quarantine("r0", 2.0)
        assert hm.get("r0").quarantined_until == pytest.approx(4.0)
        # Third death: the attempts bound latches the breaker open.
        hm.start_rejoin("r0", 7.0)
        hm.rejoined("r0", 7.1)
        hm.declare_dead("r0", "crash 3", 8.0)
        hm.quarantine("r0", 8.0)
        assert hm.get("r0").quarantined_until == float("inf")
        assert not hm.due_for_rejoin("r0", 1e12)

    def test_jitter_is_seeded_deterministic(self):
        def holds(seed):
            hm = HealthMonitor(HealthPolicy(quarantine=RetryPolicy(
                attempts=8, base_s=1.0, jitter=0.5)), seed=seed)
            hm.add("r0")
            hm.declare_dead("r0", "x", 0.0)
            hm.quarantine("r0", 0.0)
            return hm.get("r0").quarantined_until

        assert holds(7) == holds(7)
        assert holds(7) != holds(8)


class TestJournal:
    def test_open_deliver_close_stream(self):
        j = RequestJournal()
        a = j.open([1, 2, 3], 8, trace_id="t", replica="r0",
                   deadline_wall=123.0, submitted_wall=100.0)
        b = j.open([4], 2, replica="r1")
        assert (a, b) == (0, 1)
        j.deliver(a, [10, 11])
        j.deliver(a, [12])
        assert j.stream(a) == [10, 11, 12]
        assert j.entry(a).remaining == 5
        assert j.delivered_tokens_total == 3
        assert len(j) == 2 and a in j
        e = j.close(a, "done")
        assert e.trace_id == "t" and a not in j
        assert j.closed["done"] == 1
        with pytest.raises(JournalError):
            j.entry(a)
        with pytest.raises(JournalError):
            j.close(b, "bogus-outcome")

    def test_deliver_over_budget_raises(self):
        j = RequestJournal()
        f = j.open([1], 2)
        with pytest.raises(JournalError, match="budget"):
            j.deliver(f, [5, 6, 7])

    def test_inflight_on_and_reassign(self):
        j = RequestJournal()
        a = j.open([1], 4, replica="r0")
        b = j.open([2], 4, replica="r0")
        j.open([3], 4, replica="r1")
        assert [e.frid for e in j.inflight_on("r0")] == [a, b]
        j.reassign(a, None, failover=True)
        assert [e.frid for e in j.inflight_on(None)] == [a]
        assert j.entry(a).failovers == 1

    def test_pytree_codec_round_trip(self):
        j = RequestJournal()
        a = j.open([1, 2], 8, trace_id="x", replica="r2",
                   deadline_wall=9.5, submitted_wall=1.5)
        j.deliver(a, [7, 8, 9])
        done = j.open([3], 1)
        j.close(done, "done")
        back = RequestJournal.from_pytree(j.to_pytree())
        assert back.open_frids() == [a]
        assert back.entry(a) == j.entry(a)
        assert back.delivered_tokens_total == 3
        assert back.closed["done"] == 1
        # id namespace continues (unique across restart)
        assert back.open([5], 1) == 2
        with pytest.raises(JournalError):
            RequestJournal.from_pytree({"nope": np.zeros(3)})

    def test_journal_orbax_round_trip(self, tmp_path):
        pytest.importorskip("orbax.checkpoint")
        from k8s_gpu_scheduler_tpu.models.lifecycle import (
            load_journal, persist_journal,
        )
        j = RequestJournal()
        a = j.open([1, 2, 3], 6, trace_id="conv-1", replica="r0")
        j.deliver(a, [42, 43])
        d = str(tmp_path / "journal")
        assert load_journal(d) is None
        persist_journal(j, d)
        persist_journal(j, d)        # second persist: step must advance
        back = load_journal(d)
        assert back.entry(a) == j.entry(a)
        assert back.delivered_tokens_total == 2


class TestEngineCancelAndEmitted:
    def test_emitted_tracks_inflight_progress(self, setup):
        cfg, params = setup
        eng = mk_engine(params, cfg)
        rid = eng.submit([1, 2, 3, 4], max_new=16)
        assert eng.emitted(rid) == []
        eng.step()
        first = eng.emitted(rid)
        assert len(first) >= 1
        eng.step()
        second = eng.emitted(rid)
        assert len(second) > len(first)
        assert second[:len(first)] == first              # append-only
        assert eng.emitted(999) == []
        done = {}
        while eng.pending:
            done.update(eng.step())
        assert eng.emitted(rid) == []                    # popped at finish
        assert done[rid][:len(second)] == second

    def test_cancel_queued_and_active(self, setup):
        cfg, params = setup
        eng = mk_engine(params, cfg, n_slots=2)
        rng = np.random.default_rng(0)
        ids = [eng.submit(list(rng.integers(0, cfg.vocab, 6)), max_new=8)
               for _ in range(4)]
        eng.step()                       # 2 admitted, 2 queued
        active = sorted(eng._slot_req.values())
        queued = [r for r in ids if r not in active]
        assert eng.cancel(queued[0], reason="deadline") is True
        assert eng.cancel(active[0], reason="deadline") is True
        assert eng.cancel(12345) is False
        assert "deadline" in eng.errors[queued[0]]
        assert "deadline" in eng.errors[active[0]]
        eng._alloc.assert_consistent()
        done = {}
        while eng.pending:
            done.update(eng.step())
        # the untouched requests still finish, full-length
        assert all(r in done or r in eng.errors for r in ids)
        assert all(len(done[r]) == 8 for r in done)


class TestCrashFailover:
    def drive(self, router, prompts, max_new=10, deadlines=None):
        frids = [router.submit(p, max_new=max_new,
                               deadline_s=(deadlines[i] if deadlines
                                           else None))
                 for i, p in enumerate(prompts)]
        done = router.run()
        return frids, done

    def test_crash_during_prefill_replays_queued_requests(self, setup):
        """Kill the first replica on its very first step: its requests
        have zero delivered tokens (prefill/queue), so replay is a
        plain resubmission — zero loss, byte identity."""
        cfg, params = setup
        prompts, _ = mk_workload(cfg, n=9, seed=3)
        ref = reference(params, cfg, prompts, max_new=10)
        inj = FaultInjector(seed=0, rules=[
            FaultRule(site="replica.crash", kind="crash", at=(1,))])
        router = mk_fleet(params, cfg, faults=inj)
        frids, done = self.drive(router, prompts)
        assert [done[f] for f in frids] == ref
        st = router.stats()
        assert st["failovers"] == 1 and st["requests_lost"] == 0
        assert st["replayed_tokens"] == 0          # nothing delivered yet

    @pytest.mark.slow  # double-covered (PR 15 budget): the fleet_chaos
    # bench CI step kills replicas mid-trace and asserts zero loss +
    # byte identity + bounded replay on every push; the prefill-crash
    # and journal-restart cells keep the failover machinery tier-1.
    def test_crash_mid_decode_verifies_and_streams_suffix(self, setup):
        """Kill a replica mid-decode: replay re-decodes only the verify
        window (bounded rework) and the final stream is
        byte-identical."""
        cfg, params = setup
        prompts, _ = mk_workload(cfg, n=9, seed=4)
        ref = reference(params, cfg, prompts, max_new=12)
        inj = FaultInjector(seed=0)
        router = mk_fleet(params, cfg, faults=inj)
        frids = [router.submit(p, max_new=12) for p in prompts]
        done = dict(router.step())       # progress: tokens delivered
        victim = next(f for f in frids if f in router.journal
                      and router.journal.entry(f).delivered)
        kill_next(router, inj, router.locate(victim)[0])
        done.update(router.step())
        done.update(router.run())
        assert [done[f] for f in frids] == ref
        st = router.stats()
        assert st["failovers"] == 1 and st["requests_lost"] == 0
        assert 0 < st["replayed_tokens"] <= st["journal_delivered_tokens"]

    @pytest.mark.slow
    def test_double_failure_two_replicas_die(self, setup):
        """A replayed request's new home dies too: the journal carries
        it through BOTH failovers. The long quarantine keeps the dead
        replicas out so the second kill lands where the replays live."""
        cfg, params = setup
        prompts, _ = mk_workload(cfg, n=9, seed=5)
        ref = reference(params, cfg, prompts, max_new=10)
        inj = FaultInjector(seed=0)
        router = mk_fleet(params, cfg, faults=inj,
                          quarantine=SLOW_QUARANTINE)
        frids = [router.submit(p, max_new=10) for p in prompts]
        done = dict(router.step())
        kill_next(router, inj, router.locate(frids[0])[0])
        done.update(router.step())       # first death → replay
        assert frids[0] in router.journal
        kill_next(router, inj, router.locate(frids[0])[0])
        done.update(router.step())       # second death → replay again
        done.update(router.run())
        assert [done[f] for f in frids] == ref
        st = router.stats()
        assert st["failovers"] == 2 and st["requests_lost"] == 0
        assert router.journal.closed["done"] == len(frids)

    def test_crash_after_shed_source_and_target(self, setup):
        """The mid-shed cells of the failure matrix: migrate slots,
        then kill the source (its remaining requests fail over) and
        then the target (the migrated requests fail over — the
        journal's replica pointer moved with the shed)."""
        cfg, params = setup
        prompts, _ = mk_workload(cfg, n=10, seed=6)
        ref = reference(params, cfg, prompts, max_new=12)
        inj = FaultInjector(seed=0)
        router = mk_fleet(params, cfg, faults=inj,
                          quarantine=SLOW_QUARANTINE)
        frids = [router.submit(p, max_new=12) for p in prompts]
        done = dict(router.step())
        # all requests landed on one replica (same summaries, same
        # placement); shed half its slots to a cold peer
        src = router.locate(frids[0])[0]
        dst = next(r for r in router._replicas if r != src)
        moved = router.shed(src, dst)
        assert moved > 0
        migrated = [f for f in frids if router.locate(f)[0] == dst]
        assert migrated
        kill_next(router, inj, src)      # crash the shed SOURCE
        done.update(router.step())
        kill_next(router, inj, dst)      # then the shed TARGET
        done.update(router.step())
        done.update(router.run())
        assert [done[f] for f in frids] == ref
        st = router.stats()
        assert st["failovers"] == 2 and st["requests_lost"] == 0

    @pytest.mark.slow  # double-covered (PR 15 budget): the health-
    # ladder/breaker unit tests keep quarantine→rejoin logic tier-1 and
    # the fleet_chaos bench CI step runs a rejoining engine_factory
    # through seeded kills on every push.
    def test_quarantined_replica_rejoins_and_serves_again(self, setup):
        cfg, params = setup
        prompts, _ = mk_workload(cfg, n=6, seed=7)
        inj = FaultInjector(seed=0)
        router = mk_fleet(params, cfg, faults=inj)
        frids = [router.submit(p, max_new=8) for p in prompts]
        victim = router.locate(frids[0])[0]
        kill_next(router, inj, victim)
        done = dict(router.step())
        done.update(router.run())
        assert len(done) == len(frids)
        # step (possibly idle) until the quarantine expires and the
        # factory rebuilds the replica: everything live again...
        t0 = time.monotonic()
        while router.health.state(victim) != LIVE \
                and time.monotonic() - t0 < 10.0:
            done.update(router.step())
        assert router.stats()["health_states"][LIVE] == 3
        # ...and the rejoined replica takes new traffic.
        prompts2, _ = mk_workload(cfg, n=6, seed=8)
        ref2 = reference(params, cfg, prompts2, max_new=8)
        frids2, done2 = self.drive(router, prompts2, max_new=8)
        assert [done2[f] for f in frids2] == ref2

    def test_flapping_replica_latches_breaker_open(self, setup):
        """A replica that dies again after rejoining must end
        PERMANENTLY quarantined, not churn the fleet."""
        cfg, params = setup
        prompts, _ = mk_workload(cfg, n=6, seed=9)
        ref = reference(params, cfg, prompts, max_new=8)
        inj = FaultInjector(seed=0)
        router = mk_fleet(
            params, cfg, faults=inj,
            quarantine=RetryPolicy(attempts=2, base_s=0.02,
                                   multiplier=2.0, max_s=0.05,
                                   jitter=0.0))
        frids = [router.submit(p, max_new=8) for p in prompts]
        victim = router.locate(frids[0])[0]
        kill_next(router, inj, victim)    # first death
        done = dict(router.step())
        # wait out the quarantine, let it rejoin, then kill it again
        t0 = time.monotonic()
        while router.health.state(victim) != LIVE \
                and time.monotonic() - t0 < 10.0:
            done.update(router.step())
        assert router.health.state(victim) == LIVE
        kill_next(router, inj, victim)    # second death → breaker open
        done.update(router.step())
        done.update(router.run())
        assert [done[f] for f in frids] == ref
        assert router.health.state(victim) == QUARANTINED
        assert router.health.get(victim).quarantined_until == float("inf")
        assert router.stats()["requests_lost"] == 0

    def test_all_dead_no_factory_watchdog_raises(self, setup):
        cfg, params = setup
        prompts, _ = mk_workload(cfg, n=4, seed=10)
        inj = FaultInjector(seed=0, rules=[
            FaultRule(site="replica.crash", kind="crash", at=(1, 2))])
        router = Router(
            [(f"r{i}", mk_engine(params, cfg)) for i in range(2)],
            faults=inj, health=HealthPolicy(quarantine=FAST_QUARANTINE))
        frids = [router.submit(p, max_new=6) for p in prompts]
        with pytest.raises(FleetError, match="no progress"):
            router.run(no_progress_s=0.3)
        # Nothing lost: the journal still holds every request, orphaned.
        st = router.stats()
        assert st["requests_lost"] == 0
        assert st["journal_inflight"] == len(frids)

    def test_replay_divergence_is_surfaced_not_streamed(self, setup):
        """Tamper a journaled delivery, then kill its replica: the
        replayed stream cannot match the forged journal, and the
        request must FAIL LOUDLY (Router.errors) rather than stream a
        spliced answer."""
        cfg, params = setup
        prompts, _ = mk_workload(cfg, n=6, seed=11)
        inj = FaultInjector(seed=0)
        router = mk_fleet(params, cfg, faults=inj)
        frids = [router.submit(p, max_new=12) for p in prompts]
        done = dict(router.step())
        victims = [f for f in frids
                   if f in router.journal
                   and len(router.journal.entry(f).delivered) >= 2]
        assert victims, "need an in-flight request with progress"
        victim = victims[0]
        router.journal.entry(victim).delivered[-1] ^= 1   # forge
        kill_next(router, inj, router.locate(victim)[0])
        done.update(router.step())
        done.update(router.run())
        assert victim in router.errors
        assert "divergence" in router.errors[victim]
        assert victim not in done
        # every OTHER request is intact
        for f in frids:
            if f != victim:
                assert f in done

    def test_deadline_expiry_queued_and_active(self, setup):
        """submit(deadline_s=): expired requests fail with a surfaced
        error record, pages retired, journal entry closed — never
        silently stuck."""
        cfg, params = setup
        clock = VirtualClock()
        router = Router([("r0", mk_engine(params, cfg, n_slots=2))],
                        clock=clock)
        rng = np.random.default_rng(0)
        prompts = [list(rng.integers(0, cfg.vocab, 6)) for _ in range(4)]
        # 2 admit, 2 queue behind them (n_slots=2)
        frids = [router.submit(p, max_new=32, deadline_s=5.0)
                 for p in prompts]
        ok = router.submit(prompts[0], max_new=4)       # no deadline
        router.step()
        clock.advance(10.0)                             # all 4 expire
        done = router.step()
        for f in frids:
            assert "deadline exceeded" in router.errors[f]
            assert f not in router.journal
        eng = router._replicas["r0"].engine
        eng._alloc.assert_consistent()                  # pages retired
        assert len(eng.errors) == 4                     # engine mirror
        done.update(router.run())
        assert len(done[ok]) == 4                       # survivor fine
        assert router.stats()["deadline_expired"] == 4

    def test_journal_survives_router_restart(self, setup, tmp_path):
        """Persist the journal mid-flight, throw the router away, boot
        a new one over FRESH engines from the same journal_dir: every
        open request replays and completes byte-identically."""
        pytest.importorskip("orbax.checkpoint")
        cfg, params = setup
        prompts, _ = mk_workload(cfg, n=6, seed=12)
        ref = reference(params, cfg, prompts, max_new=10)
        jdir = str(tmp_path / "journal")
        r1 = mk_fleet(params, cfg, journal_dir=jdir)
        frids = [r1.submit(p, max_new=10) for p in prompts]
        for _ in range(3):
            r1.step()
        assert len(r1.journal) > 0
        r1.checkpoint_journal()
        delivered_before = {f: r1.journal.stream(f)
                            for f in r1.journal.open_frids()}
        # r1's process "dies" here (no drain); new router, new engines.
        r2 = mk_fleet(params, cfg, journal_dir=jdir)
        done = r2.run()
        for f in frids:
            if f in done:
                assert done[f] == ref[f]
                assert done[f][:len(delivered_before.get(f, []))] == \
                    delivered_before.get(f, [])
        # every entry that was open at checkpoint time completed
        assert set(done) == set(delivered_before)
        assert r2.stats()["requests_lost"] == 0

    def test_step_isolates_one_replicas_exception(self, setup):
        """The PR's bugfix satellite: one replica raising inside
        Router.step() no longer unwinds the peers' step — it walks the
        suspect→dead ladder while everyone else makes progress."""
        cfg, params = setup
        prompts, _ = mk_workload(cfg, n=8, seed=13)
        ref = reference(params, cfg, prompts, max_new=8)
        bad_inj = FaultInjector(seed=0, rules=[
            FaultRule(site="serve.step", kind="drop", every=1)])
        engines = [("r0", mk_engine(params, cfg, fault_injector=bad_inj)),
                   ("r1", mk_engine(params, cfg)),
                   ("r2", mk_engine(params, cfg))]
        router = Router(engines,
                        health=HealthPolicy(quarantine=FAST_QUARANTINE))
        frids = [router.submit(p, max_new=8) for p in prompts]
        done = router.run()
        assert [done[f] for f in frids] == ref
        st = router.stats()
        # r0 errored its way down the ladder and its requests replayed
        assert st["health_states"][QUARANTINED] == 1
        assert st["requests_lost"] == 0
        assert router.health.get("r0").consecutive_errors == 0

    def test_fleet_metrics_catalog(self, setup):
        cfg, params = setup
        prompts, _ = mk_workload(cfg, n=8, seed=14)
        reg = Registry()
        inj = FaultInjector(seed=0)
        router = mk_fleet(params, cfg, faults=inj, metrics=reg)
        frids = [router.submit(p, max_new=10) for p in prompts]
        done = dict(router.step())
        victim = router.locate(frids[0])[0]
        kill_next(router, inj, victim)
        done.update(router.step())
        done.update(router.run())
        assert reg.counter(FLEET_FAILOVERS_TOTAL).value(
            replica=victim) == 1
        assert reg.counter(FLEET_LOST_TOTAL).value() == 0
        assert reg.counter(FLEET_REPLAYED_TOKENS_TOTAL).value() > 0
        assert reg.counter(FLEET_EXPIRED_TOTAL).value() == 0
        # step until the victim rejoins, then the state gauge must be
        # one-hot live for every replica
        t0 = time.monotonic()
        while router.health.state(victim) != LIVE \
                and time.monotonic() - t0 < 10.0:
            router.step()
        g = reg.gauge(FLEET_REPLICA_STATE)
        for rid in ("r0", "r1", "r2"):
            assert g.value(replica=rid, state=LIVE) == 1.0
            assert sum(g.value(replica=rid, state=s)
                       for s in ("live", "suspect", "dead",
                                 "quarantined", "rejoining")) == 1.0
        assert reg.gauge(FLEET_JOURNAL_SIZE).value() == 0.0
        exposition = reg.expose()
        assert "tpu_fleet_replica_state" in exposition
        assert "tpu_fleet_requests_lost_total" in exposition
        assert "tpu_fleet_journal_inflight_requests" in exposition

    def test_tracer_records_failover_events(self, setup):
        from k8s_gpu_scheduler_tpu.obs import Tracer
        cfg, params = setup
        prompts, _ = mk_workload(cfg, n=6, seed=15)
        tracer = Tracer()
        inj = FaultInjector(seed=0)
        router = mk_fleet(params, cfg, faults=inj, tracer=tracer)
        frids = [router.submit(p, max_new=8) for p in prompts]
        done = dict(router.step())
        kill_next(router, inj, router.locate(frids[0])[0])
        done.update(router.step())
        done.update(router.run())
        names = [s.name for s in tracer.spans()]
        assert "replica_dead" in names
        assert "failover" in names
        assert "replay" in names
        # the target engine's flight recorder logged the replay too
        assert any(rep.engine is not None
                   and rep.engine._flight.records("replay")
                   for rep in router._replicas.values())
