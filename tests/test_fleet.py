"""Fleet tier: cache-aware routing + snapshot load shedding + the
serve-entrypoint preemption lifecycle.

Proof obligations of the fleet PR:

- **Scoring determinism** — placement is a pure function of the
  published summaries: same summaries, same placements, always (the
  tiebreak is the lowest replica id, never iteration order or a clock).
- **Migration token identity** — a request finishes byte-identically
  whether it stays on its original replica or is shed mid-stream
  (partial ``drain(slots=...)`` → ``absorb``) to another.
- **Refcount consistency** — ``PageAllocator.assert_consistent`` holds
  on BOTH engines after a shed, including when two shed slots share a
  mounted prefix page.
- **Degraded routing** — stale or unreachable summaries downgrade to
  deterministic round-robin (worse placement, never a crash).
- **Lifecycle** — SIGTERM/``Preempted`` → drain → orbax persist →
  ``resume_or_fresh`` resumes token-identically (models/lifecycle.py).
"""
import dataclasses
import os
import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_scheduler_tpu.fleet import (
    FleetError, MemoryStore, ReplicaSummary, Router, list_summaries,
    prefix_match_len, publish_summary, summarize,
)
from k8s_gpu_scheduler_tpu.metrics.exporter import (
    FLEET_MIGRATED_TOTAL, FLEET_ROUTED_TOTAL, FLEET_SHED_TOTAL, Registry,
)
from k8s_gpu_scheduler_tpu.models import LlamaConfig, init_params
from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher
from k8s_gpu_scheduler_tpu.models.snapshot import (
    ServingSnapshot, SnapshotError,
)
from k8s_gpu_scheduler_tpu.obs import VirtualClock
from k8s_gpu_scheduler_tpu.testing.faults import (
    FaultInjector, FaultProxy, FaultRule, Preempted,
)

PAGE = 8


@pytest.fixture(scope="module")
def setup():
    cfg = dataclasses.replace(LlamaConfig.tiny(), dtype=jnp.float32)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def mk_engine(params, cfg, **kw):
    base = dict(n_slots=4, max_len=64, chunk=4, prefill_bucket=8,
                kv_layout="paged", page_size=PAGE, prefix_cache=True)
    base.update(kw)
    return ContinuousBatcher(params, cfg, **base)


def mk_workload(cfg, n=10, n_classes=2, seed=0):
    """n prompts over n_classes shared 2-page system prefixes."""
    rng = np.random.default_rng(seed)
    hot = [list(rng.integers(0, cfg.vocab, 2 * PAGE))
           for _ in range(n_classes)]
    prompts = [hot[i % n_classes]
               + list(rng.integers(0, cfg.vocab, 2 + i % 5))
               for i in range(n)]
    return prompts, hot


def reference(params, cfg, prompts, max_new=8, **kw):
    """Single-engine streams — greedy decode does not depend on
    placement, so one engine's answers are every fleet's truth."""
    eng = mk_engine(params, cfg, **kw)
    ids = [eng.submit(p, max_new=max_new) for p in prompts]
    done = {}
    while eng.pending:
        done.update(eng.step())
    return [done[i] for i in ids]


# -- summary / scoring primitives -----------------------------------------
class TestSummary:
    def test_prefix_match_len_page_floor_and_full_cover_cap(self):
        path = list(range(100, 124))                 # 3 pages cached
        digest = [(path, 24)]
        # 20 shared tokens -> floor to 2 pages = 16.
        assert prefix_match_len(path[:20] + [1, 2], digest, PAGE) == 16
        # Full cover (prompt == cached path): the last page always
        # re-prefills (admission needs last-position logits) -> 16.
        assert prefix_match_len(path, digest, PAGE) == 16
        # Under one page -> 0; disjoint -> 0.
        assert prefix_match_len(path[:5], digest, PAGE) == 0
        assert prefix_match_len([1, 2, 3] * 10, digest, PAGE) == 0

    def test_match_len_respects_truncated_digest(self):
        # A digest path truncated to 8 tokens under-claims (8-token
        # match) even though 24 tokens are cached.
        digest = [(list(range(100, 108)), 24)]
        prompt = list(range(100, 124)) + [7]
        assert prefix_match_len(prompt, digest, PAGE) == 8

    def test_summary_json_roundtrip_and_listing(self):
        store = MemoryStore()
        s = ReplicaSummary(replica="r1", fleet="f", seq=3,
                           published_wall=12.5, page_size=PAGE,
                           pages_total=32, pages_free=10, n_slots=4,
                           active_slots=2, queued=1, decode_p50_s=0.01,
                           digest=[([1, 2, 3], 8)])
        publish_summary(store, s)
        publish_summary(store, ReplicaSummary(replica="r2", fleet="f"))
        publish_summary(store, ReplicaSummary(replica="rX", fleet="g"))
        got = list_summaries(store, "f")
        assert set(got) == {"r1", "r2"}
        assert got["r1"] == s

    def test_summarize_reads_live_engine(self, setup):
        cfg, params = setup
        eng = mk_engine(params, cfg)
        prompts, _ = mk_workload(cfg, n=2)
        for p in prompts:
            eng.submit(p, max_new=8)
        eng.step()
        s = summarize(eng, "r0", fleet="f", seq=1, now_wall=5.0)
        assert s.active_slots == 2 and s.page_size == PAGE
        assert s.pages_free < s.pages_total
        # Donations appear in the digest after the requests reap.
        while eng.pending:
            eng.step()
        s2 = summarize(eng, "r0")
        assert s2.digest and s2.active_slots == 0


class TestScoring:
    def summaries(self):
        base = dict(fleet="f", published_wall=0.0, page_size=PAGE,
                    pages_total=32, n_slots=4)
        return {
            "r0": ReplicaSummary(replica="r0", pages_free=32,
                                 active_slots=0, **base),
            "r1": ReplicaSummary(replica="r1", pages_free=32,
                                 active_slots=0, **base),
        }

    def router(self, setup, **kw):
        cfg, params = setup
        return Router([("r0", mk_engine(params, cfg)),
                       ("r1", mk_engine(params, cfg))], **kw)

    def test_match_dominates_equal_load(self, setup):
        r = self.router(setup)
        subs = self.summaries()
        subs["r1"].digest = [(list(range(16)), 16)]
        prompt = list(range(16)) + [99]
        s0, m0 = r.score(subs["r0"], prompt)
        s1, m1 = r.score(subs["r1"], prompt)
        assert m1 == 16 and m0 == 0 and s1 > s0

    def test_load_breaks_ties_and_id_breaks_exact_ties(self, setup):
        r = self.router(setup)
        subs = self.summaries()
        subs["r1"].active_slots = 4           # busy
        subs["r1"].pages_free = 2
        prompt = [1, 2, 3]
        s0, _ = r.score(subs["r0"], prompt)
        s1, _ = r.score(subs["r1"], prompt)
        assert s0 > s1
        # Prefill backlog alone breaks an otherwise exact tie: a
        # replica mid-way through chunking a long prompt looks free on
        # the page/slot axes, so the backlog term must be what moves
        # the next long prompt elsewhere.
        subs = self.summaries()
        subs["r0"].prefill_backlog_tokens = 512
        s0, _ = r.score(subs["r0"], prompt)
        s1, _ = r.score(subs["r1"], prompt)
        assert s1 > s0
        # Exactly equal summaries -> the lowest replica id wins.
        fresh = self.router(setup)
        rid, policy, _ = fresh.route(prompt)
        assert (rid, policy) == ("r0", "affinity")

    def test_same_summaries_same_placement(self, setup):
        """Determinism: routing is a pure function of the published
        summaries — two routers fed byte-identical summary stores
        route an identical prompt sequence identically (no engine
        steps involved: route() never consults the engines)."""
        cfg, params = setup
        rng = np.random.default_rng(11)
        prompts = [list(rng.integers(0, cfg.vocab, 4 + i % 9))
                   for i in range(12)]
        digests = {
            "r0": [(prompts[0][:PAGE], PAGE)],
            "r1": [(prompts[1][:2 * PAGE], 2 * PAGE)],
        }
        backlogs = {"r0": 96, "r1": 0}       # chunked-prefill pressure

        def placements():
            r = self.router(setup)
            for rid, s in self.summaries().items():
                s.fleet = r.fleet
                s.digest = digests[rid]
                s.prefill_backlog_tokens = backlogs[rid]
                s.published_wall = r._clock.wall()
                publish_summary(r._store, s)
            return [r.route(p) for p in prompts]

        first = placements()
        assert first == placements()
        assert {pol for _, pol, _ in first} == {"affinity"}

    def test_decode_p50_pressure_discounts(self, setup):
        r = self.router(setup)
        subs = self.summaries()
        slow = dataclasses.replace(subs["r1"], decode_p50_s=10.0)
        s_fast, _ = r.score(subs["r1"], [1, 2])
        s_slow, _ = r.score(slow, [1, 2])
        assert s_slow < s_fast

    def test_prefill_backlog_pressure_discounts(self, setup):
        """The chunked-prefill complement of the decode-p50 test: a
        replica with admitted-but-unfinished prefill scores below an
        idle twin, monotonically in the backlog, and a live mid-prefill
        engine publishes the backlog in its summary."""
        cfg, params = setup
        r = self.router(setup)
        subs = self.summaries()
        idle, _ = r.score(subs["r1"], [1, 2])
        mild, _ = r.score(dataclasses.replace(
            subs["r1"], prefill_backlog_tokens=512), [1, 2])
        flood, _ = r.score(dataclasses.replace(
            subs["r1"], prefill_backlog_tokens=8192), [1, 2])
        assert idle > mild > flood
        eng = mk_engine(params, cfg, prefill_chunk_tokens=PAGE,
                        max_len=128)
        eng.submit(list(np.random.default_rng(9).integers(
            0, cfg.vocab, 5 * PAGE)), max_new=4)
        eng.step()
        s = summarize(eng, "r0")
        assert s.prefill_backlog_tokens == 4 * PAGE
        while eng.pending:
            eng.step()
        assert summarize(eng, "r0").prefill_backlog_tokens == 0


# -- partial drain / absorb ------------------------------------------------
class TestShedMigration:
    def test_shed_is_token_identical_and_consistent(self, setup):
        """The acceptance core: mid-stream shed of two slots; every
        stream (migrated or not) byte-equal to the uninterrupted
        reference; both allocators consistent; source keeps serving."""
        cfg, params = setup
        prompts, _ = mk_workload(cfg, n=6)
        ref = reference(params, cfg, prompts)
        src = mk_engine(params, cfg)
        dst = mk_engine(params, cfg)
        ids = [src.submit(p, max_new=8) for p in prompts]
        done = {}
        done.update(src.step())
        shed = src.active_slot_ids()[:2]
        snap = src.drain(slots=shed)
        assert snap.partial and len(snap.slot_req) == 2
        shed_rids = set(snap.slot_req.values())
        # Codec round trip: a shed snapshot may cross a process.
        snap = ServingSnapshot.from_pytree(snap.to_pytree())
        mapping = dst.absorb(snap)
        assert set(mapping) == shed_rids
        src._alloc.assert_consistent()
        dst._alloc.assert_consistent()
        # Source is NOT drained: it keeps admitting and serving.
        extra = src.submit(prompts[0], max_new=4)
        while src.pending:
            done.update(src.step())
        dst_done = {}
        while dst.pending:
            dst_done.update(dst.step())
        src._alloc.assert_consistent()
        dst._alloc.assert_consistent()
        got = []
        for rid in ids:
            if rid in shed_rids:
                got.append(dst_done[mapping[rid]])
            else:
                got.append(done[rid])
        assert got == ref
        assert len(done[extra]) == 4
        # Flight recorders logged the handoff on both sides.
        assert src._flight.records("shed")
        assert dst._flight.records("absorb")
        # Engine-level shed/resume gauges moved.
        assert src.pool_metrics()["requests_shed_total"] == 2.0
        assert dst.pool_metrics()["requests_resumed_total"] == 2.0

    @pytest.mark.slow
    def test_shared_prefix_page_shed_together(self, setup):
        """Two shed slots MOUNTING THE SAME cached prefix page: the
        page ships once, allocs once on the target, and the extra
        holder retains — the refcount partition survives on both
        ends."""
        cfg, params = setup
        prompts, hot = mk_workload(cfg, n=1, n_classes=1)
        src = mk_engine(params, cfg)
        # Warm the tree: one request of the hot class reaps + donates.
        warm = src.submit(prompts[0], max_new=2)
        while src.pending:
            src.step()
        rng = np.random.default_rng(7)
        pair = [hot[0] + list(rng.integers(0, cfg.vocab, 3)),
                hot[0] + list(rng.integers(0, cfg.vocab, 4))]
        ref = reference(params, cfg, [prompts[0]] + pair)[1:]
        ids = [src.submit(p, max_new=8) for p in pair]
        src.step()
        for slot in src.active_slot_ids():
            assert src._slot_shared[slot]     # both mounted the hit
        snap = src.drain(slots=src.active_slot_ids())
        dst = mk_engine(params, cfg)
        mapping = dst.absorb(snap)
        src._alloc.assert_consistent()
        dst._alloc.assert_consistent()
        done = {}
        while dst.pending:
            done.update(dst.step())
        dst._alloc.assert_consistent()
        assert [done[mapping[r]] for r in ids] == ref

    def test_partial_drain_validations(self, setup):
        cfg, params = setup
        prompts, _ = mk_workload(cfg, n=3)
        eng = mk_engine(params, cfg)
        for p in prompts:
            eng.submit(p, max_new=8)
        eng.step()
        with pytest.raises(ValueError, match="inactive slot"):
            eng.drain(slots=[99])
        with pytest.raises(ValueError, match="at least one"):
            eng.drain(slots=[])
        snap = eng.drain(slots=eng.active_slot_ids()[:1])
        # restore() refuses partial snapshots...
        fresh = mk_engine(params, cfg)
        with pytest.raises(SnapshotError, match="partial"):
            fresh.restore(snap)
        # ...and absorb() refuses full ones.
        full = eng.drain()
        busy = mk_engine(params, cfg)
        busy.submit(prompts[0], max_new=4)
        with pytest.raises(SnapshotError, match="PARTIAL"):
            busy.absorb(full)

    @pytest.mark.slow
    def test_absorb_needs_free_slots(self, setup):
        cfg, params = setup
        prompts, _ = mk_workload(cfg, n=8)
        src = mk_engine(params, cfg)
        dst = mk_engine(params, cfg, n_slots=1)
        with pytest.raises(SnapshotError):
            # Fingerprints differ (n_slots) — rejected before slots
            # even get counted.
            for p in prompts:
                src.submit(p, max_new=8)
            src.step()
            dst.absorb(src.drain(slots=src.active_slot_ids()))
        # Same geometry, but the target is full.
        src2 = mk_engine(params, cfg)
        dst2 = mk_engine(params, cfg)
        for p in prompts:
            src2.submit(p, max_new=8)
            dst2.submit(p, max_new=8)
        src2.step()
        dst2.step()
        with pytest.raises(SnapshotError, match="free here"):
            dst2.absorb(src2.drain(slots=src2.active_slot_ids()))


# -- router end to end -----------------------------------------------------
class TestRouterEndToEnd:
    def test_fleet_run_with_forced_shed_token_identity(self, setup):
        cfg, params = setup
        prompts, _ = mk_workload(cfg, n=12, n_classes=3)
        ref = reference(params, cfg, prompts)
        reg = Registry()
        router = Router([(f"r{i}", mk_engine(params, cfg))
                         for i in range(3)], metrics=reg)
        frids, done = [], {}
        for i, p in enumerate(prompts):
            frids.append(router.submit(p, max_new=8))
            if i % 3 == 2:                   # keep several in flight
                done.update(router.step())
            if i == 7:
                stats = {r: rep.engine.replica_stats()
                         for r, rep in router._replicas.items()}
                src = max(stats, key=lambda r: (
                    stats[r]["active_slots"], r))
                dst = min(stats, key=lambda r: (
                    stats[r]["active_slots"], r))
                active = router._replicas[src].engine.active_slot_ids()
                assert active and src != dst
                moved = router.shed(src, dst, slots=active)
                assert moved == len(active) >= 1
        done.update(router.run())
        assert [done[f] for f in frids] == ref
        for rep in router._replicas.values():
            rep.engine._alloc.assert_consistent()
        st = router.stats()
        assert st["aggregate_prefix_hit_rate"] > 0
        assert st["degraded_routes"] == 0
        routed = sum(
            reg.counter(FLEET_ROUTED_TOTAL).value(
                replica=f"r{i}", policy="affinity") for i in range(3))
        assert routed == len(prompts)
        migrated = sum(
            reg.counter(FLEET_MIGRATED_TOTAL).value(replica=f"r{i}")
            for i in range(3))
        shed = sum(
            reg.counter(FLEET_SHED_TOTAL).value(replica=f"r{i}")
            for i in range(3))
        assert migrated == shed >= 1
        # Migration-safe latency records: every request closed one.
        met = router.pop_request_metrics()
        assert set(met) == set(frids)

    def test_affinity_routes_hot_class_to_warm_replica(self, setup):
        cfg, params = setup
        prompts, hot = mk_workload(cfg, n=2, n_classes=2)
        router = Router([("r0", mk_engine(params, cfg)),
                         ("r1", mk_engine(params, cfg))])
        # Warm r0 with class 0 end to end (reap donates + publish).
        f0 = router.submit(prompts[0], max_new=4)
        first = router.locate(f0)[0]
        router.run()
        rng = np.random.default_rng(3)
        again = hot[0] + list(rng.integers(0, cfg.vocab, 3))
        f1 = router.submit(again, max_new=4)
        # Same class follows the cache; the warm replica's digest won.
        assert router.locate(f1)[0] == first
        router.run()

    def test_stale_summaries_degrade_to_round_robin(self, setup):
        cfg, params = setup
        clock = VirtualClock()
        router = Router([("r0", mk_engine(params, cfg)),
                         ("r1", mk_engine(params, cfg)),
                         ("r2", mk_engine(params, cfg))],
                        clock=clock, stale_s=1.0)
        assert router.route([1, 2, 3])[1] == "affinity"
        # Fresh summaries: a prefill-flooded r0 loses the otherwise
        # exact tie (the backlog discount steers around it).
        s0 = summarize(router._replica("r0").engine, "r0",
                       fleet=router.fleet, now_wall=clock.wall())
        s0.prefill_backlog_tokens = 10_000
        publish_summary(router._store, s0)
        router._summaries_cache = None
        assert router.route([1, 2, 3])[0] == "r1"
        clock.advance(5.0)                   # summaries now stale
        picks = [router.route([1, 2, 3]) for _ in range(4)]
        assert [p[1] for p in picks] == ["degraded"] * 4
        # Degraded round-robin is pressure-blind BY DESIGN: the flooded
        # r0 is back in rotation (bounded staleness degrades placement
        # quality, never the deterministic fallback).
        assert [p[0] for p in picks] == ["r0", "r1", "r2", "r0"]
        assert router.stats()["degraded_routes"] == 4
        router.publish()                     # fresh summaries again
        assert router.route([1, 2, 3])[1] == "affinity"

    def test_unreachable_store_degrades_not_crashes(self, setup):
        cfg, params = setup
        inj = FaultInjector(seed=0, rules=[
            FaultRule(site="fleetstore", kind="drop", every=1)])
        store = FaultProxy(MemoryStore(), inj, "fleetstore")
        router = Router([("r0", mk_engine(params, cfg)),
                         ("r1", mk_engine(params, cfg))], store=store)
        rid, policy, _ = router.route([1, 2, 3])
        assert policy == "degraded" and rid == "r0"
        frid = router.submit([1, 2, 3, 4], max_new=4)
        done = router.run()
        assert len(done[frid]) == 4
        assert router.stats()["store_errors"] > 0

    def test_maybe_shed_relieves_page_pressure(self, setup):
        cfg, params = setup
        # r0: tiny pool (11 usable pages) -> two mid-size requests
        # exhaust it; r1: default pool, idle.
        r0 = mk_engine(params, cfg, n_pages=12)
        r1 = mk_engine(params, cfg)
        router = Router([("r0", r0), ("r1", r1)], auto_shed=True)
        rng = np.random.default_rng(5)
        for _ in range(2):
            r0.submit(list(rng.integers(0, cfg.vocab, 28)), max_new=12)
        r0.step()
        assert r0.replica_stats()["pages_free"] <= 1
        moved = router.maybe_shed()
        assert moved >= 1
        r0._alloc.assert_consistent()
        r1._alloc.assert_consistent()
        assert r1.replica_stats()["active_slots"] >= 1

    def test_router_rejects_bad_fleets(self, setup):
        cfg, params = setup
        with pytest.raises(FleetError, match="at least one"):
            Router([])
        with pytest.raises(FleetError, match="duplicate"):
            Router([("r0", mk_engine(params, cfg)),
                    ("r0", mk_engine(params, cfg))])
        # Heterogeneous engines are rejected at CONSTRUCTION (anything
        # but n_pages) — discovering the mismatch mid-shed would strand
        # the drained requests.
        with pytest.raises(FleetError, match="shed-compatible"):
            Router([("r0", mk_engine(params, cfg)),
                    ("r1", mk_engine(params, cfg, page_size=16,
                                     prefill_bucket=16))])
        with pytest.raises(FleetError, match="shed-compatible"):
            Router([("r0", mk_engine(params, cfg)),
                    ("r1", mk_engine(params, cfg, n_slots=8))])
        # n_pages is exempt, exactly like restore: pool size may differ.
        Router([("r0", mk_engine(params, cfg)),
                ("r1", mk_engine(params, cfg, n_pages=40))])
        router = Router([("r0", mk_engine(params, cfg)),
                         ("r1", mk_engine(params, cfg))])
        with pytest.raises(FleetError, match="distinct"):
            router.shed("r0", "r0")
        with pytest.raises(FleetError, match="unknown replica"):
            router.shed("r0", "nope")


# -- serve-entrypoint lifecycle (SIGTERM / Preempted) ----------------------
class TestServeLifecycle:
    def test_preempted_drain_persist_resume_identity(self, setup,
                                                     tmp_path):
        """The chaos version of the SIGTERM path: an injected
        ``Preempted`` mid-run → drain_to_checkpoint → a 'replacement
        pod' resume_or_fresh → token-identical finish."""
        pytest.importorskip("orbax.checkpoint")
        from k8s_gpu_scheduler_tpu.models.lifecycle import (
            drain_to_checkpoint, resume_or_fresh,
        )
        cfg, params = setup
        prompts, _ = mk_workload(cfg, n=5)
        ref = reference(params, cfg, prompts, max_new=9)
        inj = FaultInjector(seed=1, rules=[
            FaultRule(site="serve.step", kind="preempt", at=[2])])
        eng = mk_engine(params, cfg, fault_injector=inj)
        ids = [eng.submit(p, max_new=9) for p in prompts]
        done = {}
        with pytest.raises(Preempted):
            while eng.pending:
                done.update(eng.step())
        snap = drain_to_checkpoint(eng, str(tmp_path / "snap"))
        assert snap.n_requests_in_flight > 0

        def make():
            return mk_engine(params, cfg)

        fresh, resumed = resume_or_fresh(make, str(tmp_path / "snap"))
        assert resumed == snap.n_requests_in_flight
        while fresh.pending:
            done.update(fresh.step())
        assert [done[i] for i in ids] == ref

    def test_second_preemption_of_a_pod_lineage_persists(self, setup,
                                                         tmp_path):
        """Regression: orbax's force= does not overwrite an existing
        step, so a pod lineage's SECOND drain (resume → serve → get
        preempted again) used to die with StepAlreadyExists; persist
        now advances the step with max_to_keep=1 and resume always
        reads the latest."""
        pytest.importorskip("orbax.checkpoint")
        from k8s_gpu_scheduler_tpu.models.lifecycle import (
            drain_to_checkpoint, resume_or_fresh,
        )
        cfg, params = setup
        d = str(tmp_path / "lineage")
        rng = np.random.default_rng(2)
        eng = mk_engine(params, cfg)
        eng.submit(list(rng.integers(0, cfg.vocab, 6)), max_new=6)
        drain_to_checkpoint(eng, d)
        eng2, resumed = resume_or_fresh(lambda: mk_engine(params, cfg),
                                        d)
        assert resumed == 1
        eng2.step()
        marker = eng2.submit(list(rng.integers(0, cfg.vocab, 5)),
                             max_new=3)
        drain_to_checkpoint(eng2, d)          # second preemption
        eng3, resumed3 = resume_or_fresh(lambda: mk_engine(params, cfg),
                                         d)
        assert resumed3 == eng3.pending >= 1  # the LATEST state loaded
        done = {}
        while eng3.pending:
            done.update(eng3.step())
        assert len(done[marker]) == 3

    def test_resume_or_fresh_without_snapshot(self, setup, tmp_path):
        pytest.importorskip("orbax.checkpoint")
        from k8s_gpu_scheduler_tpu.models.lifecycle import resume_or_fresh
        cfg, params = setup
        eng, resumed = resume_or_fresh(
            lambda: mk_engine(params, cfg), str(tmp_path / "none"))
        assert resumed == 0
        eng2, resumed2 = resume_or_fresh(
            lambda: mk_engine(params, cfg), None)
        assert resumed2 == 0

    def test_sigterm_sets_request_flag(self):
        from k8s_gpu_scheduler_tpu.models.lifecycle import PreemptionGuard
        prev = signal.getsignal(signal.SIGTERM)
        guard = PreemptionGuard().install()
        try:
            assert not guard.requested
            os.kill(os.getpid(), signal.SIGTERM)
            assert guard.requested
        finally:
            guard.uninstall()
        assert signal.getsignal(signal.SIGTERM) is prev

    def test_zero_page_snapshot_round_trips_through_orbax(self, setup,
                                                          tmp_path):
        """Regression: a drain with every slot finished (queue-only
        snapshot) has ZERO page payload rows — orbax refuses zero-size
        arrays, so the codec omits them and rebuilds from the recorded
        geometry."""
        pytest.importorskip("orbax.checkpoint")
        from k8s_gpu_scheduler_tpu.models.lifecycle import (
            load_snapshot, persist_snapshot,
        )
        cfg, params = setup
        eng = mk_engine(params, cfg, prefix_cache=False)
        rng = np.random.default_rng(0)
        ids = [eng.submit(list(rng.integers(0, cfg.vocab, 6)), max_new=3)
               for _ in range(2)]
        snap = eng.drain()      # nothing admitted yet: queue-only
        assert snap.page_ids == [] and len(snap.queue) == 2
        persist_snapshot(snap, str(tmp_path / "zp"))
        back = load_snapshot(str(tmp_path / "zp"))
        assert back.queue == snap.queue
        assert back.k_pages.shape == snap.k_pages.shape
        fresh = mk_engine(params, cfg, prefix_cache=False)
        assert fresh.restore(back) == 2
        done = {}
        while fresh.pending:
            done.update(fresh.step())
        assert all(len(done[i]) == 3 for i in ids)
