"""API server / informer / Descriptor tests — the hermetic cluster fixture
the reference never had (its resource tests mutate a real dev cluster,
SURVEY.md §4 'Live-infra integration')."""
import threading
import time

import pytest

from k8s_gpu_scheduler_tpu.api.objects import (
    ConfigMap,
    ConfigMapRef,
    Container,
    EnvVar,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    ResourceRequirements,
    TPU_RESOURCE,
)
from k8s_gpu_scheduler_tpu.cluster import APIServer, Descriptor, PatchNodeParam, SharedInformerFactory
from k8s_gpu_scheduler_tpu.cluster.apiserver import AlreadyExists, NotFound
from k8s_gpu_scheduler_tpu.utils import find_nodes_ip_from_pod


def mk_pod(name, ns="default", node="", chips=0, cm_refs=(), env=()):
    return Pod(
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=PodSpec(
            node_name=node,
            containers=[
                Container(
                    env=[EnvVar(k, v) for k, v in env],
                    env_from=[ConfigMapRef(r) for r in cm_refs],
                    resources=ResourceRequirements(requests={TPU_RESOURCE: chips} if chips else {}),
                )
            ],
        ),
    )


def mk_node(name, chips=8, addr=None, labels=None):
    return Node(
        metadata=ObjectMeta(name=name, namespace="default", labels=labels or {}),
        status=NodeStatus(
            capacity={TPU_RESOURCE: chips},
            allocatable={TPU_RESOURCE: chips},
            addresses=[addr or f"10.0.0.{hash(name) % 250}"],
        ),
    )


class TestAPIServer:
    def test_crud_roundtrip(self):
        s = APIServer()
        s.create(mk_pod("a"))
        assert s.get("Pod", "a").metadata.name == "a"
        with pytest.raises(AlreadyExists):
            s.create(mk_pod("a"))
        s.delete("Pod", "a")
        with pytest.raises(NotFound):
            s.get("Pod", "a")

    def test_list_filters(self):
        s = APIServer()
        s.create(mk_pod("p1", ns="redis", node="n1"))
        s.create(mk_pod("p2", ns="default", node="n1"))
        s.create(mk_pod("p3", ns="default", node="n2"))
        assert len(s.list("Pod")) == 3
        assert len(s.list("Pod", namespace="default")) == 2
        assert len(s.list("Pod", field_fn=lambda p: p.spec.node_name == "n1")) == 2

    def test_deepcopy_isolation(self):
        s = APIServer()
        pod = mk_pod("a")
        s.create(pod)
        pod.spec.node_name = "mutated-outside"
        assert s.get("Pod", "a").spec.node_name == ""
        got = s.get("Pod", "a")
        got.spec.node_name = "mutated-copy"
        assert s.get("Pod", "a").spec.node_name == ""

    def test_mutate_is_atomic_under_contention(self):
        s = APIServer()
        s.create(ConfigMap(metadata=ObjectMeta(name="cm"), data={"n": "0"}))

        def bump():
            for _ in range(100):
                s.mutate("ConfigMap", "cm", "default",
                         lambda cm: cm.data.__setitem__("n", str(int(cm.data["n"]) + 1)))

        ts = [threading.Thread(target=bump) for _ in range(4)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert s.get("ConfigMap", "cm").data["n"] == "400"

    def test_watch_stream(self):
        s = APIServer()
        s.create(mk_pod("pre"))
        w = s.watch("Pod")
        ev = w.next(timeout=1)
        assert ev.type == "ADDED" and ev.obj.metadata.name == "pre"
        s.create(mk_pod("post"))
        ev = w.next(timeout=1)
        assert ev.type == "ADDED" and ev.obj.metadata.name == "post"
        s.delete("Pod", "post")
        assert w.next(timeout=1).type == "DELETED"
        w.stop()
        assert w.next(timeout=0.2) is None


class TestInformers:
    def test_cache_sync_and_lister(self):
        s = APIServer()
        s.create(mk_node("n1"))
        f = SharedInformerFactory(s)
        nodes = f.informer("Node")
        f.start()
        assert f.wait_for_cache_sync()
        assert [n.metadata.name for n in nodes.list()] == ["n1"]
        s.create(mk_node("n2"))
        deadline = time.time() + 2
        while time.time() < deadline and len(nodes.list()) < 2:
            time.sleep(0.01)
        assert nodes.get("n2") is not None
        f.stop()

    def test_event_handlers(self):
        s = APIServer()
        f = SharedInformerFactory(s)
        pods = f.informer("Pod")
        seen = []
        pods.add_event_handler(
            on_add=lambda o: seen.append(("add", o.metadata.name)),
            on_delete=lambda o: seen.append(("del", o.metadata.name)),
        )
        f.start()
        s.create(mk_pod("x"))
        s.delete("Pod", "x")
        deadline = time.time() + 2
        while time.time() < deadline and len(seen) < 2:
            time.sleep(0.01)
        assert seen == [("add", "x"), ("del", "x")]
        f.stop()


class TestDescriptor:
    def test_configmap_append_via_envfrom(self):
        # The device-assignment side channel end to end (SURVEY.md §3.3).
        s = APIServer()
        d = Descriptor(s)
        d.create_configmap(ConfigMap(metadata=ObjectMeta(name="game-demo"), data={}))
        pod = mk_pod("worker", cm_refs=["game-demo", "missing-cm"])
        d.create_pod(pod)
        written = d.append_to_pod_configmaps(pod, {"TPU_WORKER_ID": "0"})
        assert written == ["game-demo"]
        assert d.get_configmap("game-demo").data["TPU_WORKER_ID"] == "0"

    def test_label_node(self):
        s = APIServer()
        d = Descriptor(s)
        s.create(mk_node("tpu-node"))
        d.label_node(PatchNodeParam("tpu-node", "add", "/metadata/labels",
                                    {"tpu.sched/slice.config": "2x2"}))
        assert d.get_node("tpu-node").metadata.labels["tpu.sched/slice.config"] == "2x2"
        d.label_node(PatchNodeParam("tpu-node", "remove", "/metadata/labels",
                                    {"tpu.sched/slice.config": ""}))
        assert "tpu.sched/slice.config" not in d.get_node("tpu-node").metadata.labels

    def test_bind_and_phase(self):
        s = APIServer()
        d = Descriptor(s)
        d.create_pod(mk_pod("w"))
        d.bind_pod("w", "default", "n1")
        d.set_pod_phase("w", "default", "Running")
        got = d.get_pod("w")
        assert got.spec.node_name == "n1" and got.status.phase == "Running"

    def test_discovery_parity(self):
        # FindNodesIPFromPod parity: locate registry node via '-0' pod in
        # namespace 'registry' (reference: utils.go:59-70 w/ ns 'redis').
        s = APIServer()
        d = Descriptor(s)
        s.create(mk_node("ctrl", addr="172.20.0.5"))
        d.create_pod(mk_pod("kvstore-0", ns="registry", node="ctrl"))
        assert find_nodes_ip_from_pod(d, "-0", "registry") == ["172.20.0.5"]


class TestAdviceRegressions:
    """Regression tests for the round-1 advisor findings (ADVICE.md)."""

    def test_pre_registered_handler_sees_initial_list(self):
        # ADVICE medium: handlers registered before start() must receive ADD
        # events for objects that existed before the informer started.
        s = APIServer()
        s.create(mk_pod("pre-existing"))
        f = SharedInformerFactory(s)
        pods = f.informer("Pod")
        seen = []
        pods.add_event_handler(on_add=lambda o: seen.append(o.metadata.name))
        f.start()
        assert f.wait_for_cache_sync()
        assert seen == ["pre-existing"]
        # And the watch replay of the same object must not double-deliver.
        s.create(mk_pod("later"))
        deadline = time.time() + 2
        while time.time() < deadline and len(seen) < 2:
            time.sleep(0.01)
        assert seen == ["pre-existing", "later"]
        f.stop()

    def test_late_handler_gets_synthetic_adds(self):
        s = APIServer()
        s.create(mk_pod("a"))
        f = SharedInformerFactory(s)
        pods = f.informer("Pod")
        f.start()
        assert f.wait_for_cache_sync()
        seen = []
        pods.add_event_handler(on_add=lambda o: seen.append(o.metadata.name))
        assert seen == ["a"]
        f.stop()

    def test_raising_handler_does_not_kill_watch(self):
        s = APIServer()
        f = SharedInformerFactory(s)
        pods = f.informer("Pod")
        seen = []

        def bad_handler(obj):
            raise RuntimeError("boom")

        pods.add_event_handler(on_add=bad_handler)
        pods.add_event_handler(on_add=lambda o: seen.append(o.metadata.name))
        f.start()
        s.create(mk_pod("x"))
        s.create(mk_pod("y"))
        deadline = time.time() + 2
        while time.time() < deadline and len(seen) < 2:
            time.sleep(0.01)
        assert seen == ["x", "y"]
        f.stop()

    def test_failed_mutate_leaves_store_untouched(self):
        # ADVICE: mutate() must run fn on a copy and swap only on success.
        s = APIServer()
        s.create(ConfigMap(metadata=ObjectMeta(name="cm"), data={"k": "v"}))
        rv_before = s.get("ConfigMap", "cm").metadata.resource_version

        def partial_then_raise(cm):
            cm.data["poison"] = "1"
            raise RuntimeError("midway failure")

        with pytest.raises(RuntimeError):
            s.mutate("ConfigMap", "cm", "default", partial_then_raise)
        got = s.get("ConfigMap", "cm")
        assert "poison" not in got.data
        assert got.metadata.resource_version == rv_before

    def test_bind_pod_sets_real_host_ip(self):
        s = APIServer()
        d = Descriptor(s)
        s.create(mk_node("n1", addr="10.1.2.3"))
        d.create_pod(mk_pod("w"))
        d.bind_pod("w", "default", "n1")
        assert d.get_pod("w").status.host_ip == "10.1.2.3"

    def test_mutate_fn_cannot_retain_live_reference(self):
        s = APIServer()
        s.create(ConfigMap(metadata=ObjectMeta(name="cm"), data={}))
        captured = []
        s.mutate("ConfigMap", "cm", "default", lambda cm: captured.append(cm))
        rv = s.get("ConfigMap", "cm").metadata.resource_version
        captured[0].data["poison"] = "1"  # mutating the retained ref
        got = s.get("ConfigMap", "cm")
        assert "poison" not in got.data
        assert got.metadata.resource_version == rv

    def test_informer_restart_is_noop(self):
        # Informers are single-use: a second start() must not re-deliver
        # synthetic ADDs for cached objects.
        s = APIServer()
        s.create(mk_pod("p"))
        f = SharedInformerFactory(s)
        pods = f.informer("Pod")
        seen = []
        pods.add_event_handler(on_add=lambda o: seen.append(o.metadata.name))
        f.start()
        assert f.wait_for_cache_sync()
        f.stop()
        pods.start()
        assert seen == ["p"]
