"""Scheduler framework tests — queue, cache accounting, and the full cycle.

The reference's scheduling framework comes from upstream kube-scheduler and
is completely untested in its repo (SURVEY.md §4: "zero tests for the
scheduler plugin itself"); these are the hermetic scheduler tests the rebuild
owes (SURVEY.md hard part d).
"""
import threading
import time

import pytest

from k8s_gpu_scheduler_tpu.api.objects import (
    Container,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    ResourceRequirements,
    TPU_RESOURCE,
    LABEL_TPU_ACCELERATOR,
    LABEL_TPU_TOPOLOGY,
)
from k8s_gpu_scheduler_tpu.cluster import APIServer, Descriptor
from k8s_gpu_scheduler_tpu.config import SchedulerConfig
from k8s_gpu_scheduler_tpu.sched import (
    Cache,
    CycleState,
    FilterPlugin,
    PermitPlugin,
    PostBindPlugin,
    Profile,
    ReservePlugin,
    Scheduler,
    SchedulingQueue,
    ScorePlugin,
    Status,
)


def mk_node(name, chips=8, gen="tpu-v5-lite-podslice", topo="2x4"):
    return Node(
        metadata=ObjectMeta(
            name=name,
            labels={LABEL_TPU_ACCELERATOR: gen, LABEL_TPU_TOPOLOGY: topo},
        ),
        status=NodeStatus(
            capacity={TPU_RESOURCE: chips},
            allocatable={TPU_RESOURCE: chips},
            addresses=[f"10.0.0.{abs(hash(name)) % 250}"],
        ),
    )


def mk_pod(name, chips=1, priority=None, ns="default"):
    ann = {"tpu.sched/priority": str(priority)} if priority is not None else {}
    return Pod(
        metadata=ObjectMeta(name=name, namespace=ns, annotations=ann),
        spec=PodSpec(
            containers=[
                Container(resources=ResourceRequirements(requests={TPU_RESOURCE: chips}))
            ]
        ),
    )


def wait_until(fn, timeout=5.0, interval=0.01):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


# --- building-block plugins used across tests --------------------------------


class FitFilter(FilterPlugin):
    """Minimal chip-fit predicate (free chips >= requested)."""

    name = "FitFilter"

    def filter(self, state, pod, node_info):
        need = pod.spec.tpu_chips()
        if node_info.free_tpu >= need:
            return Status.success()
        return Status.unschedulable(
            f"insufficient google.com/tpu: need {need}, free {node_info.free_tpu}"
        )


class MostFreeScore(ScorePlugin):
    name = "MostFreeScore"

    def __init__(self, cache):
        self._cache = cache

    def score(self, state, pod, node_name):
        info = self._cache.snapshot()[node_name]
        return float(info.free_tpu), Status.success()


# --- queue --------------------------------------------------------------------


class TestSchedulingQueue:
    def test_fifo_within_priority(self):
        q = SchedulingQueue()
        a, b = mk_pod("a"), mk_pod("b")
        a.metadata.creation_timestamp = 1.0
        b.metadata.creation_timestamp = 2.0
        q.add(a)
        q.add(b)
        assert q.pop(0.1).metadata.name == "a"
        assert q.pop(0.1).metadata.name == "b"

    def test_priority_order(self):
        q = SchedulingQueue()
        lo, hi = mk_pod("lo", priority=0), mk_pod("hi", priority=10)
        lo.metadata.creation_timestamp = 1.0
        hi.metadata.creation_timestamp = 2.0
        q.add(lo)
        q.add(hi)
        assert q.pop(0.1).metadata.name == "hi"

    def test_backoff_then_ready(self):
        q = SchedulingQueue(backoff_initial_s=0.05, backoff_max_s=0.2)
        p = mk_pod("p")
        q.add(p)
        assert q.pop(0.1) is not None
        q.add_unschedulable(p)
        assert q.pop(0.01) is None  # still backing off
        assert q.pop(1.0).metadata.name == "p"  # becomes ready

    def test_move_all_to_active_flushes_backoff(self):
        q = SchedulingQueue(backoff_initial_s=30.0, backoff_max_s=60.0)
        p = mk_pod("p")
        q.add(p)
        q.pop(0.1)
        q.add_unschedulable(p)
        q.move_all_to_active("node-added")
        assert q.pop(0.1).metadata.name == "p"

    def test_remove_while_queued(self):
        q = SchedulingQueue()
        p = mk_pod("p")
        q.add(p)
        q.remove(p)
        assert q.pop(0.05) is None

    def test_pop_blocks_until_add(self):
        q = SchedulingQueue()
        got = []
        t = threading.Thread(target=lambda: got.append(q.pop(2.0)))
        t.start()
        time.sleep(0.05)
        q.add(mk_pod("late"))
        t.join()
        assert got[0].metadata.name == "late"


# --- cache --------------------------------------------------------------------


class TestCache:
    def test_chip_accounting(self):
        c = Cache()
        c.add_node(mk_node("n1", chips=8))
        p = mk_pod("p", chips=3)
        p.spec.node_name = "n1"
        c.add_pod(p)
        info = c.snapshot()["n1"]
        assert info.allocatable_tpu == 8 and info.requested_tpu == 3 and info.free_tpu == 5

    def test_assume_then_confirm(self):
        c = Cache()
        c.add_node(mk_node("n1", chips=8))
        p = mk_pod("p", chips=4)
        c.assume(p, "n1")
        assert c.snapshot()["n1"].free_tpu == 4
        bound = mk_pod("p", chips=4)
        bound.metadata.uid = p.metadata.uid
        bound.spec.node_name = "n1"
        c.add_pod(bound)  # watch confirms — no double debit
        assert c.snapshot()["n1"].free_tpu == 4

    def test_assume_then_forget(self):
        c = Cache()
        c.add_node(mk_node("n1", chips=8))
        p = mk_pod("p", chips=4)
        c.assume(p, "n1")
        c.forget(p)
        assert c.snapshot()["n1"].free_tpu == 8

    def test_delete_pod_credits_back(self):
        c = Cache()
        c.add_node(mk_node("n1", chips=8))
        p = mk_pod("p", chips=2)
        p.spec.node_name = "n1"
        c.add_pod(p)
        c.delete_pod(p)
        assert c.snapshot()["n1"].free_tpu == 8

    def test_pod_before_node_ordering(self):
        c = Cache()
        p = mk_pod("p", chips=2)
        p.spec.node_name = "n1"
        c.add_pod(p)  # node not yet known
        c.add_node(mk_node("n1", chips=8))
        assert c.snapshot()["n1"].free_tpu == 6

    def test_slice_topology_from_labels(self):
        c = Cache()
        c.add_node(mk_node("n1", gen="tpu-v5p-slice", topo="2x2x1", chips=4))
        st = c.snapshot()["n1"].slice_topology()
        assert st is not None and st.chips == 4 and st.hosts == 1


# --- full cycle ---------------------------------------------------------------


def make_scheduler(server, extra_profile=None, config=None):
    config = config or SchedulerConfig(backoff_initial_s=0.05, backoff_max_s=0.2)
    sched = Scheduler(server, profile=Profile(), config=config)
    profile = extra_profile(sched) if callable(extra_profile) else Profile(
        filter=[FitFilter()], score=[MostFreeScore(sched.cache)]
    )
    sched.profile = profile
    return sched


class TestSchedulerCycle:
    def test_binds_all_schedulable_leaves_rest_pending(self):
        # VERDICT.md next-round item 1's acceptance test: N nodes + M pods,
        # daemon binds every schedulable pod, unschedulable ones stay Pending.
        server = APIServer()
        d = Descriptor(server)
        for i in range(3):
            server.create(mk_node(f"n{i}", chips=8))
        sched = make_scheduler(server)
        sched.start()
        try:
            for i in range(6):
                d.create_pod(mk_pod(f"fit-{i}", chips=4))  # 24 chips = capacity
            d.create_pod(mk_pod("too-big", chips=16))  # can never fit
            assert wait_until(
                lambda: all(
                    d.get_pod(f"fit-{i}").spec.node_name for i in range(6)
                )
            )
            # chips: each node got exactly 2 × 4-chip pods
            by_node = {}
            for i in range(6):
                by_node.setdefault(d.get_pod(f"fit-{i}").spec.node_name, 0)
                by_node[d.get_pod(f"fit-{i}").spec.node_name] += 4
            assert all(v == 8 for v in by_node.values())
            time.sleep(0.2)
            big = d.get_pod("too-big")
            assert big.spec.node_name == "" and big.status.phase == "Pending"
            assert "insufficient google.com/tpu" in sched.failure_reasons["default/too-big"]
        finally:
            sched.stop()

    def test_per_class_e2e_histograms(self):
        """Every bind lands in exactly one per-class e2e histogram (the
        mixed1024 bench's per-population split): plain pods in `single`,
        pod-group-labelled in `gang`, priority-annotated in
        `preempting` — and the classes partition the aggregate count."""
        from k8s_gpu_scheduler_tpu.api.objects import LABEL_POD_GROUP
        from k8s_gpu_scheduler_tpu.sched.scheduler import pod_class

        assert pod_class(mk_pod("a")) == "single"
        assert pod_class(mk_pod("b", priority=50)) == "preempting"
        gangish = mk_pod("c", priority=50)
        gangish.metadata.labels[LABEL_POD_GROUP] = "g1"
        assert pod_class(gangish) == "gang"          # group label wins

        server = APIServer()
        d = Descriptor(server)
        server.create(mk_node("n0", chips=8))
        sched = make_scheduler(server)
        sched.start()
        try:
            d.create_pod(mk_pod("plain-0", chips=1))
            d.create_pod(mk_pod("plain-1", chips=1))
            d.create_pod(mk_pod("prio-0", chips=1, priority=10))
            assert wait_until(
                lambda: sched.metrics.histogram(
                    "tpu_sched_e2e_duration_seconds").count == 3)
            single = sched.metrics.histogram(
                "tpu_sched_e2e_duration_seconds_class_single")
            preempting = sched.metrics.histogram(
                "tpu_sched_e2e_duration_seconds_class_preempting")
            gang = sched.metrics.histogram(
                "tpu_sched_e2e_duration_seconds_class_gang")
            assert single.count == 2
            assert preempting.count == 1
            assert gang.count == 0
            assert (single.quantile(0.99) or 0) > 0
        finally:
            sched.stop()

    def test_scores_pick_emptiest_node(self):
        server = APIServer()
        d = Descriptor(server)
        server.create(mk_node("busy", chips=8))
        server.create(mk_node("empty", chips=8))
        # Pre-bound pod occupies 6 chips on 'busy'.
        squatter = mk_pod("squatter", chips=6)
        squatter.spec.node_name = "busy"
        d.create_pod(squatter)
        sched = make_scheduler(server)
        sched.start()
        try:
            d.create_pod(mk_pod("new", chips=1))
            assert wait_until(lambda: d.get_pod("new").spec.node_name != "")
            assert d.get_pod("new").spec.node_name == "empty"
        finally:
            sched.stop()

    def test_pod_created_before_start_is_scheduled(self):
        server = APIServer()
        d = Descriptor(server)
        server.create(mk_node("n1"))
        d.create_pod(mk_pod("early", chips=1))
        sched = make_scheduler(server)
        sched.start()
        try:
            assert wait_until(lambda: d.get_pod("early").spec.node_name == "n1")
        finally:
            sched.stop()

    def test_capacity_freed_reschedules_pending(self):
        server = APIServer()
        d = Descriptor(server)
        server.create(mk_node("n1", chips=8))
        sched = make_scheduler(server)
        sched.start()
        try:
            d.create_pod(mk_pod("first", chips=8))
            assert wait_until(lambda: d.get_pod("first").spec.node_name == "n1")
            d.create_pod(mk_pod("second", chips=8))
            time.sleep(0.2)
            assert d.get_pod("second").spec.node_name == ""
            d.delete_pod("first")
            assert wait_until(lambda: d.get_pod("second").spec.node_name == "n1")
        finally:
            sched.stop()

    def test_foreign_scheduler_pods_ignored(self):
        server = APIServer()
        d = Descriptor(server)
        server.create(mk_node("n1"))
        sched = make_scheduler(server)
        sched.start()
        try:
            foreign = mk_pod("foreign", chips=1)
            foreign.spec.scheduler_name = "default-scheduler"
            d.create_pod(foreign)
            time.sleep(0.2)
            assert d.get_pod("foreign").spec.node_name == ""
        finally:
            sched.stop()

    def test_reserve_failure_rolls_back(self):
        server = APIServer()
        d = Descriptor(server)
        server.create(mk_node("n1", chips=8))

        events = []

        class FailingReserve(ReservePlugin):
            name = "FailingReserve"

            def reserve(self, state, pod, node_name):
                events.append(("reserve", pod.metadata.name))
                return Status.unschedulable("always refuses")

            def unreserve(self, state, pod, node_name):
                events.append(("unreserve", pod.metadata.name))

        sched = make_scheduler(
            server,
            extra_profile=lambda s: Profile(
                filter=[FitFilter()], reserve=[FailingReserve()]
            ),
        )
        sched.start()
        try:
            d.create_pod(mk_pod("p", chips=2))
            assert wait_until(lambda: ("unreserve", "p") in events)
            # chips credited back after forget
            assert wait_until(lambda: sched.cache.snapshot()["n1"].free_tpu == 8)
            assert d.get_pod("p").spec.node_name == ""
        finally:
            sched.stop()

    def test_permit_wait_then_allow_binds(self):
        server = APIServer()
        d = Descriptor(server)
        server.create(mk_node("n1", chips=8))

        class WaitingPermit(PermitPlugin):
            name = "WaitingPermit"

            def permit(self, state, pod, node_name):
                return Status.wait(), 5.0

        sched = make_scheduler(
            server,
            extra_profile=lambda s: Profile(
                filter=[FitFilter()], permit=[WaitingPermit()]
            ),
        )
        sched.start()
        try:
            p = mk_pod("gated", chips=1)
            created = d.create_pod(p)
            uid = created.metadata.uid
            assert wait_until(lambda: sched.handle.get_waiting_pod(uid) is not None)
            time.sleep(0.1)
            assert d.get_pod("gated").spec.node_name == ""  # still parked
            sched.handle.get_waiting_pod(uid).allow("WaitingPermit")
            assert wait_until(lambda: d.get_pod("gated").spec.node_name == "n1")
        finally:
            sched.stop()

    def test_permit_timeout_rejects_and_requeues(self):
        server = APIServer()
        d = Descriptor(server)
        server.create(mk_node("n1", chips=8))

        class ShortWaitPermit(PermitPlugin):
            name = "ShortWaitPermit"

            def permit(self, state, pod, node_name):
                return Status.wait(), 0.05

        sched = make_scheduler(
            server,
            extra_profile=lambda s: Profile(
                filter=[FitFilter()], permit=[ShortWaitPermit()]
            ),
        )
        sched.start()
        try:
            d.create_pod(mk_pod("gated", chips=4))
            # times out, chips credited back, pod requeued (and will wait
            # again — we just assert the rollback happened)
            assert wait_until(
                lambda: "timed out" in sched.failure_reasons.get("default/gated", "")
            )
            assert d.get_pod("gated").spec.node_name == ""
        finally:
            sched.stop()

    def test_post_bind_runs_after_binding(self):
        server = APIServer()
        d = Descriptor(server)
        server.create(mk_node("n1", chips=8))
        seen = []

        class Recorder(PostBindPlugin):
            name = "Recorder"

            def post_bind(self, state, pod, node_name):
                seen.append((pod.metadata.name, node_name, d.get_pod(pod.metadata.name).spec.node_name))

        sched = make_scheduler(
            server,
            extra_profile=lambda s: Profile(filter=[FitFilter()], post_bind=[Recorder()]),
        )
        sched.start()
        try:
            d.create_pod(mk_pod("p", chips=1))
            assert wait_until(lambda: len(seen) == 1)
            # post_bind observed the pod already bound
            assert seen[0] == ("p", "n1", "n1")
        finally:
            sched.stop()


class TestCacheIdempotency:
    """Regression tests: redundant watch events must never corrupt chip
    accounting (terminal update followed by DELETE, replayed ADDs, double
    assume)."""

    def test_double_delete_no_double_credit(self):
        c = Cache()
        c.add_node(mk_node("n1", chips=8))
        p = mk_pod("p", chips=4)
        p.spec.node_name = "n1"
        c.add_pod(p)
        c.delete_pod(p)
        c.delete_pod(p)  # DELETE after terminal credit — must be a no-op
        assert c.snapshot()["n1"].free_tpu == 8

    def test_replayed_add_no_double_debit(self):
        c = Cache()
        c.add_node(mk_node("n1", chips=8))
        p = mk_pod("p", chips=4)
        p.spec.node_name = "n1"
        c.add_pod(p)
        c.add_pod(p)
        assert c.snapshot()["n1"].free_tpu == 4

    def test_update_after_terminal_credit_is_noop(self):
        c = Cache()
        c.add_node(mk_node("n1", chips=8))
        p = mk_pod("p", chips=4)
        p.spec.node_name = "n1"
        c.add_pod(p)
        c.delete_pod(p)  # terminal credit
        c.update_pod(p, p)  # trailing MODIFIED must not re-add
        c.delete_pod(p)
        assert c.snapshot()["n1"].free_tpu == 8

    def test_double_assume_same_node_idempotent(self):
        c = Cache()
        c.add_node(mk_node("n1", chips=8))
        p = mk_pod("p", chips=4)
        c.assume(p, "n1")
        c.assume(p, "n1")
        c.forget(p)
        assert c.snapshot()["n1"].free_tpu == 8

    def test_reassume_moves_debit(self):
        c = Cache()
        c.add_node(mk_node("n1", chips=8))
        c.add_node(mk_node("n2", chips=8))
        p = mk_pod("p", chips=4)
        c.assume(p, "n1")
        c.assume(p, "n2")
        snap = c.snapshot()
        assert snap["n1"].free_tpu == 8 and snap["n2"].free_tpu == 4


class TestSchedulerRobustness:
    def test_terminal_pod_at_start_holds_no_chips(self):
        server = APIServer()
        d = Descriptor(server)
        server.create(mk_node("n1", chips=8))
        done = mk_pod("done", chips=8)
        done.spec.node_name = "n1"
        done.status.phase = "Succeeded"
        d.create_pod(done)
        sched = make_scheduler(server)
        sched.start()
        try:
            d.create_pod(mk_pod("fresh", chips=8))
            assert wait_until(lambda: d.get_pod("fresh").spec.node_name == "n1")
        finally:
            sched.stop()

    def test_raising_reserve_plugin_does_not_leak_chips(self):
        server = APIServer()
        d = Descriptor(server)
        server.create(mk_node("n1", chips=8))

        calls = []

        class Exploding(ReservePlugin):
            name = "Exploding"

            def reserve(self, state, pod, node_name):
                calls.append(1)
                if len(calls) < 3:
                    raise RuntimeError("kaboom")
                return Status.success()

            def unreserve(self, state, pod, node_name):
                pass

        sched = make_scheduler(
            server,
            extra_profile=lambda s: Profile(filter=[FitFilter()], reserve=[Exploding()]),
        )
        sched.start()
        try:
            d.create_pod(mk_pod("p", chips=8))
            # First two cycles explode; the retry must still find 8 free
            # chips (no leak) and eventually bind.
            assert wait_until(lambda: d.get_pod("p").spec.node_name == "n1")
            assert sched.cache.snapshot()["n1"].free_tpu == 0
        finally:
            sched.stop()

    def test_stop_with_parked_waiting_pod_is_prompt(self):
        server = APIServer()
        d = Descriptor(server)
        server.create(mk_node("n1", chips=8))

        class ForeverPermit(PermitPlugin):
            name = "ForeverPermit"

            def permit(self, state, pod, node_name):
                return Status.wait(), 300.0

        config = SchedulerConfig(
            backoff_initial_s=0.05, backoff_max_s=0.2, permit_timeout_s=300.0
        )
        sched = make_scheduler(
            server,
            extra_profile=lambda s: Profile(filter=[FitFilter()], permit=[ForeverPermit()]),
            config=config,
        )
        sched.start()
        d.create_pod(mk_pod("parked", chips=1))
        uid_holder = []
        assert wait_until(
            lambda: (sched.handle.iterate_waiting_pods(lambda wp: uid_holder.append(wp.uid)), uid_holder)[1]
        )
        t0 = time.time()
        sched.stop()
        assert time.time() - t0 < 5.0  # not the 300s permit timeout


class TestCycleScaling:
    """Parallel Filter/Score + feasible-node sampling + event-filtered queue
    moves (VERDICT.md r3 weak #3/#4 — the r3 cycle was O(nodes) serial and
    move_all_to_active fired on every node heartbeat)."""

    def test_num_feasible_to_find_adaptive(self):
        sched = make_scheduler(APIServer())
        # At or below the floor: scan everything.
        assert sched._num_feasible_to_find(16) == 16
        assert sched._num_feasible_to_find(100) == 100
        # Above: adaptive pct = 50 - n/125, floored at the min-feasible 100.
        assert sched._num_feasible_to_find(256) == 256 * 47 // 100
        assert sched._num_feasible_to_find(5000) == 5000 * 10 // 100
        # Literal percentage override.
        sched.config.percentage_of_nodes_to_score = 20
        assert sched._num_feasible_to_find(1000) == 200

    def test_parallel_filter_binds_on_large_pool(self):
        """256 nodes crosses the parallelize threshold AND the sampling
        floor; pods must still bind correctly (and only feasible nodes
        win)."""
        server = APIServer()
        d = Descriptor(server)
        for i in range(256):
            server.create(mk_node(f"n{i:03d}", chips=8))
        sched = make_scheduler(server)
        sched.start()
        try:
            for i in range(8):
                d.create_pod(mk_pod(f"p{i}", chips=8))
            assert wait_until(
                lambda: all(d.get_pod(f"p{i}").spec.node_name
                            for i in range(8)), timeout=15)
            # All on distinct nodes (8 chips each, nodes hold 8).
            hosts = {d.get_pod(f"p{i}").spec.node_name for i in range(8)}
            assert len(hosts) == 8
        finally:
            sched.stop()

    def test_heartbeat_node_update_does_not_flush_backoff(self):
        """A node status write that changes nothing schedulability-relevant
        must leave backed-off pods in backoff; a label change must flush."""
        server = APIServer()
        sched = make_scheduler(server)
        n = mk_node("n1", chips=8)
        sched.cache.add_node(n)
        flushes = []
        orig = sched.queue.move_all_to_active
        sched.queue.move_all_to_active = lambda reason="": flushes.append(reason)
        # Identical object (heartbeat/resync): no flush.
        import copy

        same = copy.deepcopy(n)
        sched._on_node_update(n, same)
        assert flushes == []
        # Allocatable change: flush.
        grown = copy.deepcopy(n)
        grown.status.allocatable[TPU_RESOURCE] = 16
        sched._on_node_update(n, grown)
        assert flushes == ["node-update"]
        # Label change (topology relabel): flush.
        relabeled = copy.deepcopy(n)
        relabeled.metadata.labels["x"] = "y"
        sched._on_node_update(n, relabeled)
        assert flushes == ["node-update", "node-update"]
        sched.queue.move_all_to_active = orig
