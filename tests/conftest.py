"""Test configuration: force an 8-device virtual CPU mesh BEFORE jax loads.

Multi-chip TPU hardware is unavailable in CI; all sharding tests run on
XLA's host-platform device virtualization (8 CPU devices), which exercises
the same GSPMD partitioner the TPU path uses.
"""
import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The axon TPU plugin (tunnel to the single real chip) registers itself even
# when JAX_PLATFORMS=cpu is exported; the config flag wins, so force it too.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# NOTE: do NOT enable the persistent XLA compilation cache
# (jax_compilation_cache_dir) for this suite. It was tried for the
# tier-1 wall-clock budget and produces WRONG STREAMS for the shard_map
# island programs on the virtual host-platform devices (jax 0.4.37:
# hot-cache runs flip tokens in the tp=2 byte-identity grid — the
# deserialized multi-device executables do not reproduce the compiled
# ones here). Wall-clock is managed by the pytest.mark.slow rebalance
# convention instead.

import pytest  # noqa: E402


@pytest.fixture
def recompile_guard():
    """graftcheck recompile guard (analysis/recompile.py): track jitted
    entry points, ``snapshot()`` after warmup, and the fixture FAILS the
    test at teardown if any tracked jit cache grew afterwards — the
    steady-state zero-retrace contract. Donation checks ride the same
    module (``check_donation``)."""
    from k8s_gpu_scheduler_tpu.analysis.recompile import RecompileGuard

    guard = RecompileGuard()
    yield guard
    if guard.snapshotted:                # snapshot taken -> enforce
        guard.assert_steady_state()
