"""graftcheck (k8s_gpu_scheduler_tpu/analysis/) — the analyzer's own tests.

Covers: suppression syntax, each AST rule's true-positive AND
true-negative, the VMEM budgeter's accept/reject around the 16 MiB line,
golden jaxpr-audit findings on the deliberately-bad toy function, the
recompile guard + donation checks, the steady-state ContinuousBatcher
regression (the serving engine's zero-retrace contract), and the CLI
exit-code contract: 0 on the repaired tree, non-zero when any seeded
bad-fixture file is reintroduced into the scanned paths.
"""
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from k8s_gpu_scheduler_tpu.analysis import (
    VMEM_BYTES_PER_CORE, audit_vmem, decode_attention_footprint,
    flash_attention_footprint, paged_decode_attention_footprint,
    run_fast_passes, parse_suppressions,
)
from k8s_gpu_scheduler_tpu.analysis.astlint import lint_source
from k8s_gpu_scheduler_tpu.analysis.vmem import KernelFootprint

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
FIXTURES = os.path.join(HERE, "data", "graftcheck")


def rules_of(findings):
    return {f.rule for f in findings}


# -- suppressions -------------------------------------------------------------

class TestSuppressions:
    def test_inline_and_bracketed(self):
        sup = parse_suppressions(
            "x = 1  # graftcheck: ignore[rule-a, rule-b]\n"
            "y = 2  # graftcheck: ignore\n")
        assert sup[1] == {"rule-a", "rule-b"}
        assert "*" in sup[2]

    def test_comment_only_line_covers_next(self):
        sup = parse_suppressions(
            "# graftcheck: ignore[host-sync] — rationale here\n"
            "jax.device_get(x)\n")
        assert "host-sync" in sup[1] and "host-sync" in sup[2]

    def test_trailing_prose_before_marker(self):
        sup = parse_suppressions(
            "foo()  # compile — graftcheck: ignore[host-sync] (why)\n")
        assert "host-sync" in sup[1]

    def test_wrong_rule_does_not_suppress(self):
        src = textwrap.dedent("""
            import jax
            def f(x):
                def body(c, _):
                    return c * float(c.sum()), None  # graftcheck: ignore[host-sync]
                return jax.lax.scan(body, x, None, length=2)
        """)
        assert "tracer-cast" in rules_of(lint_source("<t>", src))


# -- AST lint -----------------------------------------------------------------

class TestAstLint:
    def test_traced_rules_fire(self):
        findings = lint_source(
            os.path.join(FIXTURES, "bad_astlint.py"),
            open(os.path.join(FIXTURES, "bad_astlint.py")).read())
        rules = rules_of(findings)
        assert {"lock-guard", "tracer-cast", "host-time-in-trace",
                "bare-except"} <= rules

    def test_retry_lint_rules_fire(self):
        """The retry-lint fixture: the unbounded reconnect loop and the
        lock-held backoff sleep must BOTH fire (and the fast CLI test
        below proves reintroducing the file fails the gate)."""
        findings = lint_source(
            os.path.join(FIXTURES, "bad_retry.py"),
            open(os.path.join(FIXTURES, "bad_retry.py")).read())
        rules = rules_of(findings)
        assert {"unbounded-retry", "blocking-io-under-lock"} <= rules

    def test_trace_in_jit_rules_fire(self):
        """The trace-lint fixture (graftcheck's seventh pass): span
        context manager, flight-recorder append and tracer event inside
        traced bodies must all fire as trace-in-jit (and the fast CLI
        test below proves reintroducing the file fails the gate)."""
        findings = lint_source(
            os.path.join(FIXTURES, "bad_trace.py"),
            open(os.path.join(FIXTURES, "bad_trace.py")).read())
        traced = [f for f in findings if f.rule == "trace-in-jit"]
        assert len(traced) == 3, [f.render() for f in findings]

    def test_host_side_tracing_is_clean(self):
        """The production shape — spans timing the host side of a jitted
        dispatch — must NOT flag: the rule polices traced bodies only."""
        src = textwrap.dedent("""
            import jax
            from k8s_gpu_scheduler_tpu.obs import Tracer

            tracer = Tracer()

            def host_step(fn, x):
                with tracer.span("decode_chunk", lane="engine"):
                    out = fn(x)               # fn is jitted; span is host
                tracer.event("reap", rid="req-0")
                return out
        """)
        assert "trace-in-jit" not in rules_of(lint_source("<t>", src))

    def test_bounded_retry_is_clean(self):
        """A loop whose failure path re-raises at the bound (the
        registry client's shape) must NOT flag, and neither must a
        Condition.wait under its lock."""
        src = textwrap.dedent("""
            import threading, time
            class Bounded:
                def __init__(self):
                    self._mu = threading.Lock()
                    self._cv = threading.Condition(self._mu)
                def call(self, op, policy):
                    attempt = 0
                    while True:
                        try:
                            return op()
                        except OSError:
                            attempt += 1
                            if attempt >= policy.attempts:
                                raise
                            time.sleep(policy.backoff_s(attempt))
                def wait_ready(self):
                    with self._mu:
                        self._cv.wait(1.0)
        """)
        assert not {"unbounded-retry", "blocking-io-under-lock"} \
            & rules_of(lint_source("<t>", src))

    def test_numpy_in_trace(self):
        src = textwrap.dedent("""
            import numpy as np
            import jax
            @jax.jit
            def f(x):
                return x + np.square(x)
        """)
        assert "numpy-in-trace" in rules_of(lint_source("<t>", src))

    def test_host_code_is_not_flagged(self):
        """int()/float()/np/time OUTSIDE traced functions are host code."""
        src = textwrap.dedent("""
            import time
            import numpy as np
            def host(x):
                t = time.time()
                return float(np.mean(x)) + int(t)
        """)
        assert lint_source("<t>", src) == []

    def test_transitive_traced_detection(self):
        """A module-level fn CALLED from a jitted fn is traced too."""
        src = textwrap.dedent("""
            import jax
            def helper(x):
                return x * float(x.sum())
            step = jax.jit(lambda x: helper(x))
        """)
        assert "tracer-cast" in rules_of(lint_source("<t>", src))

    def test_lock_guard_true_negative(self):
        """with-block accesses, *_locked helpers, __init__, Event attrs
        and read-only deps must NOT be flagged."""
        src = textwrap.dedent("""
            import threading
            class Good:
                def __init__(self, dep):
                    self._mu = threading.Lock()
                    self._stop = threading.Event()
                    self.dep = dep
                    self._items = []
                def put(self, x):
                    with self._mu:
                        self._items.append(self.dep.tag(x))
                    self._stop.set()
                def _drain_locked(self):
                    out, self._items = self._items, []
                    return out
                def take(self):
                    with self._mu:
                        return self._drain_locked()
        """)
        assert lint_source("<t>", src) == []

    def test_lock_guard_suppression(self):
        src = textwrap.dedent("""
            import threading
            class C:
                def __init__(self):
                    self._mu = threading.Lock()
                    self._n = 0
                def bump(self):
                    with self._mu:
                        self._n += 1
                def peek(self):
                    return self._n  # graftcheck: ignore[lock-guard] — GIL-atomic
        """)
        assert lint_source("<t>", src) == []

    def test_host_sync_rule(self):
        src = "def f(out):\n    out.block_until_ready()\n"
        assert rules_of(lint_source("<t>", src)) == {"host-sync"}


# -- VMEM budgeter ------------------------------------------------------------

class TestVmem:
    def test_presets_fit(self):
        assert audit_vmem() == []

    def test_accept_reject_around_the_line(self):
        usable = int(VMEM_BYTES_PER_CORE * 0.9)
        pad = usable - 4 * (usable // 4)         # land EXACTLY on the line
        fits = KernelFootprint("fits", in_blocks=usable // 4,
                               out_blocks=usable // 4, scratch=pad)
        assert fits.total == usable and fits.check() == []
        over = KernelFootprint("over", in_blocks=usable // 4,
                               out_blocks=usable // 4, scratch=pad + 1)
        bad = over.check()
        assert len(bad) == 1 and bad[0].rule == "vmem-budget"

    def test_double_buffer_accounting(self):
        fp = decode_attention_footprint(s=8192, g=4, hd=128, block_k=256)
        # k+v blocks dominate: 2 dtypes x 2 (double buffer) x 256 x 128 x 2B
        assert fp.total >= 2 * 2 * 256 * 128 * 2
        assert fp.check() == []

    def test_oversized_kernel_rejected(self):
        fp = decode_attention_footprint(s=32768, g=32, hd=512,
                                        block_k=16384, quant=True)
        assert fp.check() and fp.total > VMEM_BYTES_PER_CORE

    def test_flash_backward_larger_than_forward(self):
        fwd = flash_attention_footprint(256, 256, 128)
        bwd = flash_attention_footprint(256, 256, 128, backward=True)
        assert bwd.total > fwd.total - 2 ** 17  # same ballpark, bwd-heavy

    def test_paged_footprint_fits_and_rejects(self):
        """The paged plan at serving shapes fits comfortably (the page is
        the kv block — same working set as contiguous plus the block-table
        scalars); a pathological page size blows the budget."""
        fp = paged_decode_attention_footprint(64, 4, 128, 128, quant=True)
        assert fp.check() == []
        # The table scalars are counted: more blocks -> more bytes.
        fp_wide = paged_decode_attention_footprint(64, 4, 128, 1024,
                                                   batch=64, quant=True)
        assert fp_wide.total > fp.total
        bad = paged_decode_attention_footprint(8192, 32, 512, 64,
                                               batch=32, quant=True)
        findings = bad.check()
        assert len(findings) == 1 and findings[0].rule == "vmem-budget"

    def test_verify_footprint_window_multiplier(self):
        """The multi-query verify footprint: serving shapes fit at
        realistic gammas, and the t·g q-window multiplier alone walks a
        modest-page config over the budget — the failure mode the decode
        footprint cannot see."""
        from k8s_gpu_scheduler_tpu.analysis import (
            paged_verify_attention_footprint,
        )

        ok = paged_verify_attention_footprint(64, 4, 128, 128, t=5,
                                              quant=True)
        assert ok.check() == []
        # Same kv-side shape as the passing paged decode footprint at
        # page 256 — only the window grows.
        small = paged_verify_attention_footprint(256, 32, 512, 32, t=1,
                                                 batch=32, quant=True)
        assert small.check() == []
        big = paged_verify_attention_footprint(256, 32, 512, 32, t=64,
                                               batch=32, quant=True)
        findings = big.check()
        assert len(findings) == 1 and findings[0].rule == "vmem-budget"
        assert "q-window rows" in findings[0].message

    def test_bad_vmem_verify_fixture_is_over_budget(self):
        sys.path.insert(0, FIXTURES)
        try:
            import bad_vmem_verify
        finally:
            sys.path.pop(0)
        (name, fp), = bad_vmem_verify.GRAFTCHECK_VMEM_AUDIT
        assert name == "oversized_verify_window"
        assert rules_of(fp.check()) == {"vmem-budget"}

    def test_prefill_footprint_q_window_multiplier(self):
        """The prefix-attention prefill footprint: modest pages pass at
        small tail buckets, and the tb·g q-row stack — not the kv
        traffic — is what walks it over the budget (the bad_vmem_prefill
        failure mode, unit-level)."""
        from k8s_gpu_scheduler_tpu.analysis import (
            paged_prefill_attention_footprint,
        )

        ok = paged_prefill_attention_footprint(64, 4, 128, 16, 64,
                                               quant=True)
        assert ok.check() == []
        # Every rung the runtime plan accepts fits (the audit_vmem
        # sweep's contract, pinned at the largest accepted rung).
        edge = paged_prefill_attention_footprint(64, 4, 128, 1, 512,
                                                 quant=True)
        assert edge.check() == []
        big = paged_prefill_attention_footprint(64, 8, 256, 16, 1024,
                                                quant=True)
        findings = big.check()
        assert len(findings) == 1 and findings[0].rule == "vmem-budget"
        assert "q-window rows" in findings[0].message

    def test_bad_vmem_prefill_fixture_is_over_budget(self):
        sys.path.insert(0, FIXTURES)
        try:
            import bad_vmem_prefill
        finally:
            sys.path.pop(0)
        (name, fp), = bad_vmem_prefill.GRAFTCHECK_VMEM_AUDIT
        assert name == "oversized_prefill_window"
        assert rules_of(fp.check()) == {"vmem-budget"}

    def test_paged_page_size_divisibility_finding(self, monkeypatch):
        """A preset cache length the default page size does not divide
        must surface as block-divisibility from audit_vmem's PAGED arm —
        driven end-to-end by injecting a trap preset (S=96: the
        contiguous plan still exists at block 32, so only the paged gate
        can fire)."""
        from k8s_gpu_scheduler_tpu.analysis import vmem
        from k8s_gpu_scheduler_tpu.models.llama import LlamaConfig

        assert 96 % 64 != 0 and 96 % 32 == 0
        monkeypatch.setattr(vmem, "_presets", lambda: [
            ("trap", LlamaConfig.tiny(), {"cache_lens": (96,)})])
        findings = vmem.audit_vmem()
        paged = [f for f in findings if "paged" in f.message]
        assert len(paged) == 1 and paged[0].rule == "block-divisibility"
        assert "page_size=64" in paged[0].message
        # ... and nothing else fires for the trap preset (the contiguous
        # plan and the flash blocks are legal at these shapes).
        assert findings == paged


# -- jaxpr audit --------------------------------------------------------------

class TestJaxprAudit:
    def test_golden_findings_on_bad_toy(self):
        from k8s_gpu_scheduler_tpu.analysis.jaxpr_audit import audit_callable

        sys.path.insert(0, FIXTURES)
        try:
            import bad_jaxpr
        finally:
            sys.path.pop(0)
        (name, fn, args), = bad_jaxpr.GRAFTCHECK_JAXPR_AUDIT
        findings = audit_callable(fn, args, name)
        rules = rules_of(findings)
        assert {"captured-const", "f32-upcast", "host-transfer",
                "dead-output"} <= rules
        # the callback is inside the scan body -> ERROR severity
        host = [f for f in findings if f.rule == "host-transfer"]
        assert any(f.severity == "error" for f in host)

    def test_clean_function_has_no_findings(self):
        import jax.numpy as jnp

        from k8s_gpu_scheduler_tpu.analysis.jaxpr_audit import audit_callable

        findings = audit_callable(
            lambda x, w: (x @ w).sum(), (jnp.ones((8, 8), jnp.bfloat16),
                                         jnp.ones((8, 8), jnp.bfloat16)),
            "clean")
        assert findings == []

    # PR 13 rebalance: at ~57 s (every registered entry point traced,
    # now including the prefix-attention prefill kernel entries) this is
    # tier-1's single most expensive test while being triple-covered per
    # push — the unfiltered CI pytest run executes it, the full
    # graftcheck CLI runs the same registry (slow CLI test + `bench.py
    # --leg analysis`), and the per-rule unit tests above stay tier-1.
    @pytest.mark.slow
    def test_entry_points_are_clean(self):
        from k8s_gpu_scheduler_tpu.analysis import run_traced_passes

        report = run_traced_passes(paths=[])
        assert report.errors == [], "\n" + report.render()


# -- recompile guard + donation ----------------------------------------------

class TestRecompileGuard:
    def test_detects_retrace(self):
        import jax
        import jax.numpy as jnp

        from k8s_gpu_scheduler_tpu.analysis.recompile import (
            assert_no_retrace,
        )

        f = jax.jit(lambda x: x + 1)
        f(jnp.ones(3))
        with pytest.raises(AssertionError, match="retrace"):
            with assert_no_retrace({"f": f}):
                f(jnp.ones(4))                    # new shape -> retrace

    def test_steady_state_passes(self):
        import jax
        import jax.numpy as jnp

        from k8s_gpu_scheduler_tpu.analysis.recompile import (
            assert_no_retrace,
        )

        f = jax.jit(lambda x: x + 1)
        f(jnp.ones(3))
        with assert_no_retrace({"f": f}):
            for _ in range(3):
                f(jnp.ones(3))

    def test_donation_held_and_broken(self):
        import jax
        import jax.numpy as jnp

        from k8s_gpu_scheduler_tpu.analysis.recompile import check_donation

        good = jax.jit(lambda x: x * 2, donate_argnums=(0,))
        assert check_donation(good, jnp.ones((8, 8)), donated=(0,)) == []
        # Shape-mismatched output -> XLA cannot alias; donation breaks.
        bad = jax.jit(lambda x: x[0] + 1.0, donate_argnums=(0,))
        findings = check_donation(bad, jnp.ones((8, 8)), donated=(0,))
        assert findings and all(f.rule == "donation-broken"
                                for f in findings)

    def test_bad_recompile_fixture_caught(self):
        from k8s_gpu_scheduler_tpu.analysis.recompile import (
            audit_steady_state,
        )

        sys.path.insert(0, FIXTURES)
        try:
            import bad_recompile
        finally:
            sys.path.pop(0)
        (name, build), = bad_recompile.GRAFTCHECK_RECOMPILE_AUDIT
        findings = audit_steady_state(build, name)
        assert rules_of(findings) == {"steady-state-retrace"}


class TestBatcherSteadyState:
    """The ISSUE's serving regression: warmed-up continuous batching must
    decode indefinitely with ZERO jit cache misses and donated caches."""

    def test_three_chunks_varying_bitmaps_zero_retrace(self, recompile_guard):
        import jax

        from k8s_gpu_scheduler_tpu.models.llama import (
            LlamaConfig, init_params,
        )
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        cfg = LlamaConfig.tiny()
        params = init_params(cfg, jax.random.PRNGKey(0))
        eng = ContinuousBatcher(params, cfg, n_slots=2, max_len=48,
                                chunk=2, prefill_bucket=8, kv_dtype="int8")
        rng = np.random.default_rng(0)
        # Warmup: covers the prefill rung and the decode chunk program.
        eng.submit(rng.integers(0, cfg.vocab, 5), max_new=3)
        eng.run()
        # A long-running request pins a slot so the engine never fully
        # drains mid-test (a drain epoch-rolls, which REPLACES the bitmap
        # instead of donating it — by design). One step admits it AND
        # performs the post-drain epoch roll before the measured waves.
        eng.submit(rng.integers(0, cfg.vocab, 5), max_new=9)
        eng.step()

        recompile_guard.track("decode", eng._decode)
        recompile_guard.track("prefill", eng._prefill)
        recompile_guard.snapshot()
        # 3 decode chunks with different prompt lengths => different fill
        # bitmaps/cursors each wave; by design ONE compiled program serves
        # them all.
        for plen in (4, 6, 8):
            eng.submit(rng.integers(0, cfg.vocab, plen), max_new=2)
            k_before = eng._k
            bitmap_before = eng._bitmap
            eng.step()
            # Donation held: the pre-dispatch cache and bitmap buffers
            # were consumed by the donating dispatch, not copied.
            assert k_before.is_deleted(), "kv cache was not donated"
            assert bitmap_before.is_deleted(), "bitmap was not donated"
        assert recompile_guard.misses_since() == {"decode": 0, "prefill": 0}
        eng.run()                                  # drain the long request
        # fixture teardown re-asserts steady state

    def test_paged_three_chunks_varying_tables_zero_retrace(
            self, recompile_guard):
        """Paged edition of the regression above: steady-state decode
        across chunks whose BLOCK TABLES differ (each wave's admission
        lands on recycled pages in a different physical order) must be
        zero-retrace — the table varies in content, never in shape — and
        the page pool AND the table must ride the donation chain (the
        table is donated-through unchanged on steps with no admission/
        free, which still has to alias rather than copy)."""
        import jax

        from k8s_gpu_scheduler_tpu.models.llama import (
            LlamaConfig, init_params,
        )
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        cfg = LlamaConfig.tiny()
        params = init_params(cfg, jax.random.PRNGKey(0))
        eng = ContinuousBatcher(params, cfg, n_slots=2, max_len=48,
                                chunk=2, prefill_bucket=8, kv_dtype="int8",
                                kv_layout="paged", page_size=8)
        rng = np.random.default_rng(0)
        # Warmup: covers the prefill rung and the decode chunk program.
        eng.submit(rng.integers(0, cfg.vocab, 5), max_new=3)
        eng.run()
        # A long-running request pins a slot so pure-decode steps exist
        # after the admission waves.
        eng.submit(rng.integers(0, cfg.vocab, 5), max_new=15)
        eng.step()
        # One pure step completes the warmup: a no-admission chunk passes
        # the DEVICE-resident table (committed), which jit caches under a
        # different key than the numpy upload of admission steps — both
        # variants must be resident before the zero-retrace window.
        eng.step()

        recompile_guard.track("decode", eng._decode)
        recompile_guard.track("prefill", eng._prefill)
        recompile_guard.snapshot()
        # Read the tables the decode dispatches actually carried (the
        # host mirror re-zeroes a row the moment its request frees, but
        # the device table of each step still shows the wave's pages).
        tables = [np.asarray(eng._table)]
        for plen in (4, 6, 8):
            eng.submit(rng.integers(0, cfg.vocab, plen), max_new=2)
            k_before = eng._k
            eng.step()
            # Donation held for the pool on every dispatch.
            assert k_before.is_deleted(), "kv page pool was not donated"
            tables.append(np.asarray(eng._table))
        # The waves really did vary the table (recycled pages, different
        # physical placement per wave).
        assert any((a != b).any() for a, b in zip(tables, tables[1:]))
        # Two pure decode steps (no admission/free): the device-resident
        # table is donated-through — consumed, not copied.
        eng.step()
        tbl_before, k_before = eng._table, eng._k
        assert hasattr(tbl_before, "is_deleted"), "table should be on device"
        eng.step()
        assert k_before.is_deleted(), "kv page pool was not donated"
        assert tbl_before.is_deleted(), "block table was not donated"
        assert recompile_guard.misses_since() == {"decode": 0, "prefill": 0}
        eng.run()                                  # drain the long request

    def test_chunked_mixed_waves_zero_retrace(self, recompile_guard):
        """Chunked-prefill edition: waves that INTERLEAVE a long
        prompt's budgeted prefill chunks with live decode traffic must
        be zero-retrace — every chunk is a (tb, hb) rung of the same
        prefill program family, hb walking up as the slot's own earlier
        chunks become the resident prefix — and the pool must ride the
        donation chain through prefill-chunk and decode dispatches
        alike. Mirrors the registered graftcheck scenario
        ``batcher_steady_mixed_chunked``."""
        import jax

        from k8s_gpu_scheduler_tpu.models.llama import (
            LlamaConfig, init_params,
        )
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        cfg = LlamaConfig.tiny()
        params = init_params(cfg, jax.random.PRNGKey(0))
        eng = ContinuousBatcher(params, cfg, n_slots=2, max_len=48,
                                chunk=2, prefill_bucket=8, kv_dtype="int8",
                                kv_layout="paged", page_size=8,
                                prefill_chunk_tokens=8)
        rng = np.random.default_rng(0)
        # Warmup walks every chunk rung the waves use — (8,0) (8,1)
        # (8,2) via the 20-token prompt — plus the single-chunk short
        # rung and both block-table jit keys of the decode program.
        eng.submit(rng.integers(0, cfg.vocab, 20), max_new=3)
        eng.submit(rng.integers(0, cfg.vocab, 5), max_new=3)
        eng.run()

        recompile_guard.track("decode", eng._decode)
        recompile_guard.track("prefill", eng._prefill)
        recompile_guard.snapshot()
        for plen in (20, 19, 18):
            eng.submit(rng.integers(0, cfg.vocab, plen), max_new=3)
            eng.submit(rng.integers(0, cfg.vocab, 5), max_new=2)
            k_before = eng._k
            eng.step()
            # Donation held through the chunk dispatch (the pool is
            # consumed by whichever program ran this step).
            assert k_before.is_deleted(), "kv page pool was not donated"
            eng.run()
        assert recompile_guard.misses_since() == {"decode": 0,
                                                  "prefill": 0}

    def test_chunked_scenario_registered(self):
        from k8s_gpu_scheduler_tpu.analysis import entrypoints as eps

        names = [n for n, _ in eps.recompile_scenarios()]
        assert "batcher_steady_mixed_chunked" in names

    def test_spec_three_waves_varying_accepts_zero_retrace(
            self, recompile_guard):
        """Speculative edition: three waves whose verify dispatches
        commit DIFFERENT numbers of tokens (repetitive prompts accept,
        random prompts reject everything) must be zero-retrace — the
        window pads to the fixed 1+gamma and the commit length is
        traced — with the pool AND the block table still riding the
        donation chain on every verify dispatch."""
        import dataclasses

        import jax

        from k8s_gpu_scheduler_tpu.models.llama import (
            LlamaConfig, init_params,
        )
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        cfg = dataclasses.replace(LlamaConfig.tiny(), decode_attn="fused")
        params = init_params(cfg, jax.random.PRNGKey(0))
        eng = ContinuousBatcher(params, cfg, n_slots=2, max_len=64,
                                chunk=2, prefill_bucket=8, kv_dtype="int8",
                                kv_layout="paged", page_size=8,
                                speculative=True, gamma=2)
        rng = np.random.default_rng(0)
        phrase = list(rng.integers(0, cfg.vocab, 3))
        # Warmup: prefill rung + the verify program under both block-
        # table jit keys (numpy upload on admission steps, committed
        # device table on pure-verify steps).
        eng.submit(phrase * 2, max_new=6)
        eng.run()

        recompile_guard.track("decode", eng._decode)
        recompile_guard.track("prefill", eng._prefill)
        recompile_guard.snapshot()
        for _ in range(3):
            # One cycling prompt (multi-token accepts once the stream
            # loops), one random prompt (0-accept rewinds): the waves'
            # verify dispatches commit anywhere from 1 to gamma+1 tokens.
            eng.submit(phrase * 2, max_new=16)
            eng.submit(list(rng.integers(0, cfg.vocab, 5)), max_new=4)
            k_before = eng._k
            while eng.pending:
                eng.step()
            # Donation held for the pool on every verify dispatch (the
            # wave's first included).
            assert k_before.is_deleted(), "kv page pool was not donated"
        m = eng.pool_metrics()
        assert m["spec_accept_rate"] > 0, "waves must actually accept"
        assert m["spec_rewound_tokens_total"] > 0, \
            "waves must actually rewind"
        # Pure verify steps (no admission/free): the device-resident
        # table must be donated-through — consumed, not copied.
        eng.submit(list(rng.integers(0, cfg.vocab, 5)), max_new=8)
        eng.step()                                 # admission step
        k_before, tbl_before = eng._k, eng._table
        assert hasattr(tbl_before, "is_deleted"), "table should be on device"
        eng.step()                                 # pure verify step
        assert k_before.is_deleted(), "kv page pool was not donated"
        assert tbl_before.is_deleted(), "block table was not donated"
        assert recompile_guard.misses_since() == {"decode": 0,
                                                  "prefill": 0}
        eng.run()                                  # drain
        eng._alloc.assert_consistent()


# -- shared-page (alias) audit ------------------------------------------------

class TestAliasAudit:
    def test_bad_fixture_caught(self):
        from k8s_gpu_scheduler_tpu.analysis.alias import audit_shared_pages

        sys.path.insert(0, FIXTURES)
        try:
            import bad_prefix_alias
        finally:
            sys.path.pop(0)
        (name, build), = bad_prefix_alias.GRAFTCHECK_ALIAS_AUDIT
        findings = audit_shared_pages(build, name)
        assert rules_of(findings) == {"shared-page-write"}
        assert "page(s) [1]" in findings[0].message

    def test_bad_demote_write_fixture_caught(self):
        """The tiering twin of the bad fixture above: a promotion
        upload that scatters into a page another slot still mounts —
        handing the upload the RESIDENT half of a part-demoted match
        path instead of only the freshly-reserved promo pages — must
        trip the same byte-compare (the CI graftcheck step runs this
        fixture too)."""
        from k8s_gpu_scheduler_tpu.analysis.alias import audit_shared_pages

        sys.path.insert(0, FIXTURES)
        try:
            import bad_demote_write
        finally:
            sys.path.pop(0)
        (name, build), = bad_demote_write.GRAFTCHECK_ALIAS_AUDIT
        findings = audit_shared_pages(build, name)
        assert rules_of(findings) == {"shared-page-write"}
        assert "page(s) [1]" in findings[0].message

    def test_clean_writer_passes_and_vacuous_audit_does_not(self):
        import jax
        import jax.numpy as jnp

        from k8s_gpu_scheduler_tpu.analysis.alias import check_shared_pages

        pool = jnp.zeros((2, 4, 8), jnp.float32)
        new = jnp.ones((2, 1, 8), jnp.float32)
        good = jax.jit(
            lambda p, n: (p.at[:, jnp.asarray([2])].set(n),))
        assert check_shared_pages(good, (pool, new), (0,), (0,),
                                  [1], name="good") == []
        # No shared pages declared -> the audit verified nothing, which
        # must surface as a finding rather than read as a clean pass.
        vac = check_shared_pages(good, (pool, new), (0,), (0,), [],
                                 name="vacuous")
        assert rules_of(vac) == {"alias-guard"}

    def test_engine_scenarios_are_clean(self):
        """The repo's own prefill-with-hit and decode-over-shared-rows
        dispatches uphold the copy-on-write contract."""
        from k8s_gpu_scheduler_tpu.analysis import entrypoints as eps
        from k8s_gpu_scheduler_tpu.analysis.alias import audit_shared_pages

        for name, build in eps.alias_scenarios():
            findings = audit_shared_pages(build, name)
            assert findings == [], "\n".join(f.render() for f in findings)


class TestPrefixBatcherSteadyState:
    def test_prefix_hits_three_chunks_zero_retrace(self, recompile_guard):
        """Steady-state decode with PREFIX-CACHE HITS: after warmup has
        compiled the miss and hit prefill rungs, waves of shared-prefix
        admissions (varying suffixes, varying tables, shared pages
        mounted read-only) must be zero-retrace with the pool still
        riding the donation chain."""
        import jax

        from k8s_gpu_scheduler_tpu.models.llama import (
            LlamaConfig, init_params,
        )
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        cfg = LlamaConfig.tiny()
        params = init_params(cfg, jax.random.PRNGKey(0))
        eng = ContinuousBatcher(params, cfg, n_slots=2, max_len=48,
                                chunk=2, prefill_bucket=8, kv_dtype="int8",
                                kv_layout="paged", page_size=8,
                                prefix_cache=True)
        rng = np.random.default_rng(0)
        sysp = list(rng.integers(0, cfg.vocab, 8))
        # Warmup: the miss rung, then (reap donated) the hit rung.
        eng.submit(sysp + list(rng.integers(0, cfg.vocab, 5)), max_new=3)
        eng.run()
        eng.submit(sysp + list(rng.integers(0, cfg.vocab, 5)), max_new=3)
        eng.run()
        # Pin a slot + warm both block-table jit keys (committed/numpy).
        eng.submit(sysp + list(rng.integers(0, cfg.vocab, 5)), max_new=15)
        eng.step()
        eng.step()

        recompile_guard.track("decode", eng._decode)
        recompile_guard.track("prefill", eng._prefill)
        recompile_guard.snapshot()
        for suffix in (3, 4, 5):
            eng.submit(sysp + list(rng.integers(0, cfg.vocab, suffix)),
                       max_new=2)
            k_before = eng._k
            eng.step()
            assert k_before.is_deleted(), "kv page pool was not donated"
        assert recompile_guard.misses_since() == {"decode": 0,
                                                  "prefill": 0}
        m = eng.pool_metrics()
        assert m["prefix_hit_tokens"] > 0, "waves must actually hit"
        eng.run()
        eng._alloc.assert_consistent()

    def test_multiturn_prefix_kernel_zero_retrace_and_donation(
            self, recompile_guard):
        """Steady-state MULTI-TURN conversations through the Pallas
        prefix-attention prefill kernel (the tier-1 mirror of scenario
        ``batcher_steady_prefix_kernel``): after warmup has compiled the
        turn-1 (miss) and turn-2 (transcript-mounting) rungs, fresh
        2-turn conversations — turn 1 donating prompt AND decoded pages,
        turn 2 mounting the whole transcript — must be zero-retrace with
        the pool riding the donation chain. Hit lengths, prefix tables
        and the donated decoded content vary per wave; the compiled
        (tb, hb) rungs must not."""
        import dataclasses

        import jax

        from k8s_gpu_scheduler_tpu.analysis.entrypoints import (
            recompile_scenarios,
        )
        from k8s_gpu_scheduler_tpu.models.llama import (
            LlamaConfig, init_params,
        )
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        assert "batcher_steady_prefix_kernel" in dict(recompile_scenarios())
        cfg = dataclasses.replace(LlamaConfig.tiny(), decode_attn="fused")
        params = init_params(cfg, jax.random.PRNGKey(0))
        eng = ContinuousBatcher(params, cfg, n_slots=2, max_len=64,
                                chunk=2, prefill_bucket=8, kv_dtype="int8",
                                kv_layout="paged", page_size=8,
                                prefix_cache=True)
        rng = np.random.default_rng(0)

        def conversation():
            p1 = list(rng.integers(0, cfg.vocab, 16))
            eng.submit(p1, max_new=12)
            done = {}
            while eng.pending:
                done.update(eng.step())
            (_, toks), = done.items()
            eng.submit(p1 + toks + list(rng.integers(0, cfg.vocab, 4)),
                       max_new=4)
            while eng.pending:
                eng.step()

        conversation()                       # warmup: compiles both rungs
        base = eng.pool_metrics()
        assert base["decoded_pages_donated_total"] >= 1
        recompile_guard.track("decode", eng._decode)
        recompile_guard.track("prefill", eng._prefill)
        recompile_guard.snapshot()
        for _ in range(3):
            k_before = eng._k
            conversation()
            assert k_before.is_deleted(), "kv page pool was not donated"
        assert recompile_guard.misses_since() == {"decode": 0,
                                                  "prefill": 0}
        m = eng.pool_metrics()
        assert m["decoded_pages_donated_total"] \
            > base["decoded_pages_donated_total"]
        assert m["prefix_hit_tokens"] > base["prefix_hit_tokens"], \
            "turn 2 must actually mount the transcript"
        eng._alloc.assert_consistent()


class TestTracedBatcherSteadyState:
    def test_tracing_on_zero_retrace_and_donation(self, recompile_guard):
        """The obs tentpole's perf guarantee, enforced: steady-state
        paged decode with a TRACER ATTACHED (spans around every
        dispatch, phase-histogram folds, per-slot lanes) runs the same
        compiled programs — zero retraces across waves, pool still
        donated. Tracing observes the host side of the dispatch and
        must be invisible to jit (the trace-in-jit lint is the static
        half of this guarantee; this is the dynamic half, the scenario
        `batcher_steady_decode_paged_traced` runs in the full CLI)."""
        import jax

        from k8s_gpu_scheduler_tpu.models.llama import (
            LlamaConfig, init_params,
        )
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher
        from k8s_gpu_scheduler_tpu.obs import Tracer

        cfg = LlamaConfig.tiny()
        params = init_params(cfg, jax.random.PRNGKey(0))
        tr = Tracer()
        eng = ContinuousBatcher(params, cfg, n_slots=2, max_len=48,
                                chunk=2, prefill_bucket=8, kv_dtype="int8",
                                kv_layout="paged", page_size=8, tracer=tr)
        rng = np.random.default_rng(0)
        # Warmup: prefill rung + both block-table jit keys
        # (numpy-on-admission / committed-on-steady).
        eng.submit(list(rng.integers(0, cfg.vocab, 5)), max_new=7)
        eng.run()

        recompile_guard.track("decode", eng._decode)
        recompile_guard.track("prefill", eng._prefill)
        recompile_guard.snapshot()
        for plen in (4, 6, 8):
            eng.submit(list(rng.integers(0, cfg.vocab, plen)), max_new=3)
            k_before = eng._k
            eng.step()
            assert k_before.is_deleted(), "kv page pool was not donated"
        assert recompile_guard.misses_since() == {"decode": 0,
                                                  "prefill": 0}
        assert {"queue", "admit", "prefill",
                "decode_chunk"} <= {s.name for s in tr.spans()}
        eng.run()
        eng._alloc.assert_consistent()


# -- lock-order / use-after-donate / torn-snapshot (pass 10) ------------------

class TestLockOrder:
    def _lint(self, src):
        from k8s_gpu_scheduler_tpu.analysis.lockorder import (
            lint_lockorder_source,
        )

        return lint_lockorder_source("<t>", textwrap.dedent(src))

    def test_cycle_flagged_dag_clean(self):
        cycle = self._lint("""
            import threading
            class C:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()
                def ab(self):
                    with self._a:
                        with self._b:
                            pass
                def ba(self):
                    with self._b:
                        with self._a:
                            pass
        """)
        assert rules_of(cycle) == {"lock-cycle"}
        dag = self._lint("""
            import threading
            class C:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()
                def ab(self):
                    with self._a:
                        with self._b:
                            pass
                def ab2(self):
                    with self._a:
                        with self._b:
                            pass
        """)
        assert dag == []

    def test_self_reacquire_via_call_flagged_rlock_exempt(self):
        src = """
            import threading
            class C:
                def __init__(self):
                    self._mu = threading.{kind}()
                def _bump(self):
                    with self._mu:
                        pass
                def outer(self):
                    with self._mu:
                        self._bump()
        """
        assert rules_of(self._lint(src.format(kind="Lock"))) \
            == {"lock-cycle"}
        assert self._lint(src.format(kind="RLock")) == []

    def test_use_after_donate_positive_and_negative(self):
        src = """
            import jax
            def _step(pool, x):
                return (pool + x,)
            class Eng:
                def __init__(self, pool):
                    self._pool = pool
                    self._bytes = pool.nbytes     # __init__ exempt
                    self._fn = jax.jit(_step, donate_argnums=(0,))
                def step(self, x):
                    self._pool, = self._fn(self._pool, x)
                def restore(self, snap):
                    self._pool = self._pool.at[0].set(snap)  # rebind exempt
                def shape(self):
                    return self._pool.shape       # metadata exempt
                def quant(self):
                    return self._pool is not None  # identity exempt
                def scrape(self):
                    return float(self._pool[0])   # FLAGGED
        """
        findings = self._lint(src)
        assert rules_of(findings) == {"use-after-donate"}
        assert len(findings) == 1 and "scrape" in findings[0].message

    def test_multi_item_with_orders_like_nesting(self):
        # `with self._a, self._b:` vs `with self._b: with self._a:` is
        # the same a->b/b->a deadlock as two nested withs (review
        # finding: edges must come from everything held INCLUDING locks
        # acquired earlier in the same statement).
        cycle = self._lint("""
            import threading
            class C:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()
                def ab(self):
                    with self._a, self._b:
                        pass
                def ba(self):
                    with self._b:
                        with self._a:
                            pass
        """)
        assert rules_of(cycle) == {"lock-cycle"}

    def test_non_donating_branch_clears_donated_set(self):
        # A construction branch whose jit wrapper donates NOTHING means
        # the attr is not certainly donated — no finding (review
        # finding: the empty branch must empty the intersection).
        src = """
            import jax
            def _f(a):
                return (a,)
            class Eng:
                def __init__(self, mode, pool):
                    self._pool = pool
                    if mode:
                        self._fn = jax.jit(_f, donate_argnums=(0,))
                    else:
                        self._fn = jax.jit(_f)
                def step(self):
                    self._pool, = self._fn(self._pool)
                def scrape(self):
                    return self._pool[0]
        """
        assert self._lint(src) == []

    def test_use_after_donate_branch_intersection(self):
        # The same dispatcher attr assigned with different donate tuples
        # on two construction branches: only positions donated on BOTH
        # branches may indict a call-site argument.
        src = """
            import jax
            def _f(a, b):
                return (a, b)
            class Eng:
                def __init__(self, mode, pool, aux):
                    self._pool, self._aux = pool, aux
                    if mode:
                        self._fn = jax.jit(_f, donate_argnums=(0, 1))
                    else:
                        self._fn = jax.jit(_f, donate_argnums=(0,))
                def step(self):
                    self._pool, self._aux = self._fn(self._pool, self._aux)
                def scrape(self):
                    return self._pool[0], self._aux[0]
        """
        findings = self._lint(src)
        assert [f for f in findings if "_pool" in f.message]
        assert not [f for f in findings if "_aux" in f.message]

    def test_torn_snapshot_positive_and_negatives(self):
        torn = self._lint("""
            import threading
            class C:
                def __init__(self):
                    self._mu = threading.Lock()
                    self._g1 = 0
                    self._g2 = 0
                def bump(self):
                    with self._mu:
                        self._g1 = 1
                        self._g2 = 2
                def scrape(self):
                    with self._mu:
                        a = self._g1
                    with self._mu:
                        b = self._g2
                    return a, b
        """)
        assert rules_of(torn) == {"torn-snapshot"}
        # ONE lock snapshot: clean.
        one = self._lint("""
            import threading
            class C:
                def __init__(self):
                    self._mu = threading.Lock()
                    self._g1 = 0
                    self._g2 = 0
                def bump(self):
                    with self._mu:
                        self._g1 = 1
                        self._g2 = 2
                def scrape(self):
                    with self._mu:
                        return self._g1, self._g2
        """)
        assert one == []
        # Check-then-act over a single attr (read, compute outside the
        # lock, write back) is a different, sound pattern.
        fill = self._lint("""
            import threading
            class C:
                def __init__(self):
                    self._mu = threading.Lock()
                    self._cache = {}
                def get(self, k):
                    with self._mu:
                        v = self._cache.get(k)
                    if v is None:
                        v = expensive(k)
                        with self._mu:
                            self._cache[k] = v
                    return v
        """)
        assert fill == []

    def test_suppression_with_rationale_applies(self):
        src = """
            import jax
            def _step(pool):
                return (pool * 2,)
            class Eng:
                def __init__(self, pool):
                    self._pool = pool
                    self._fn = jax.jit(_step, donate_argnums=(0,))
                def step(self):
                    self._pool, = self._fn(self._pool)
                def drain(self):
                    # graftcheck: ignore[use-after-donate] — drain runs at a step boundary, nothing races it
                    return self._pool[0]
        """
        assert self._lint(src) == []

    def test_bad_lockorder_fixture_fires_every_family(self):
        from k8s_gpu_scheduler_tpu.analysis import run_fast_passes

        report = run_fast_passes(
            [os.path.join(FIXTURES, "bad_lockorder.py")])
        assert {"lock-cycle", "torn-snapshot", "use-after-donate",
                "bare-suppression"} <= rules_of(report.findings)

    def test_fleet_lock_conventions_hold(self):
        """The satellite sweep's pin: fleet/health.py + fleet/journal.py
        uphold the lock-lint ``_locked`` conventions AND the pass-10
        rules (no cycles, no torn snapshots, no donated-alias reads)."""
        import k8s_gpu_scheduler_tpu

        pkg = os.path.dirname(os.path.abspath(
            k8s_gpu_scheduler_tpu.__file__))
        from k8s_gpu_scheduler_tpu.analysis import run_fast_passes

        for mod in ("fleet/health.py", "fleet/journal.py"):
            report = run_fast_passes([os.path.join(pkg, mod)])
            assert report.findings == [], "\n" + report.render(header=mod)


# -- suppression policy + catalogue -------------------------------------------

class TestSuppressionPolicy:
    def _lint(self, src):
        from k8s_gpu_scheduler_tpu.analysis.findings import (
            lint_suppressions,
        )

        return lint_suppressions("<t>", textwrap.dedent(src))

    def test_bare_marker_flagged(self):
        out = self._lint("x = f()  # graftcheck: ignore[host-sync]\n")
        assert rules_of(out) == {"bare-suppression"}

    def test_rationale_after_marker_clean(self):
        assert self._lint(
            "x = f()  # graftcheck: ignore[host-sync] — sanctioned: the "
            "one batched readback\n") == []

    def test_rationale_in_comment_above_clean(self):
        assert self._lint(
            "# B/T come from .shape — static Python ints, not tracers.\n"
            "y = float(b * t)  # graftcheck: ignore[tracer-cast]\n") == []

    def test_not_self_suppressible(self):
        out = lint_source(
            "<t>", "x = f()  # graftcheck: ignore[bare-suppression]\n")
        assert rules_of(out) == {"bare-suppression"}

    def test_catalogue_rows_and_readme_in_sync(self):
        """The README suppression catalogue is REGENERATED from the tree
        (python -m k8s_gpu_scheduler_tpu.analysis --suppressions): a
        suppression added, removed or reworded without updating the
        README block fails here, so the docs cannot drift."""
        import k8s_gpu_scheduler_tpu
        from k8s_gpu_scheduler_tpu.analysis.findings import (
            suppression_catalogue,
        )

        pkg = os.path.dirname(os.path.abspath(
            k8s_gpu_scheduler_tpu.__file__))
        rows = suppression_catalogue([pkg])
        assert rows and any("models/serving.py" in r for r in rows)
        readme = open(os.path.join(REPO, "README.md")).read()
        begin = "<!-- suppression-catalogue:begin -->"
        end = "<!-- suppression-catalogue:end -->"
        assert begin in readme and end in readme, \
            "README is missing the generated suppression-catalogue block"
        block = readme.split(begin, 1)[1].split(end, 1)[0]
        got = [ln for ln in block.strip().splitlines()
               if ln.startswith("| `")]
        assert got == rows, (
            "README suppression catalogue is stale — regenerate with "
            "`python -m k8s_gpu_scheduler_tpu.analysis --suppressions`")


# -- symbolic traffic audit (pass 9) ------------------------------------------

class TestTraffic:
    # Scale symbols mutually distinct (the registry convention): hit =
    # HB(2) × ps(6) for the gather tests below.
    GEO = {"n_pages": 11, "S": 13, "hit": 12, "tb": 4, "W": 5, "M": 3,
           "Hkv": 2, "hd": 7, "ps": 6}

    def test_symbolize_priority_and_constants(self):
        from collections import Counter

        from k8s_gpu_scheduler_tpu.analysis.traffic import symbolize_shape

        # On a collision the FIRST geometry entry wins — scale symbols
        # are declared first, so a structural dim can never shadow one.
        geo = {"tb": 4, "ps": 4, "M": 3}
        syms, const = symbolize_shape((3, 4, 4, 9, 1), geo)
        assert syms == Counter({"M": 1, "tb": 2})
        assert const == 9          # unmatched dims fold into the constant

    def test_contract_validation(self):
        from k8s_gpu_scheduler_tpu.analysis.traffic import TrafficContract

        with pytest.raises(ValueError, match="rationale"):
            TrafficContract(dense_ok=True)
        with pytest.raises(ValueError, match="untracked"):
            TrafficContract(kv_scale={"bogus": 1})

    def _audit(self, fn, args, contract):
        from k8s_gpu_scheduler_tpu.analysis.traffic import (
            audit_traffic_callable,
        )

        return audit_traffic_callable(fn, args, "t", self.GEO, contract)

    def test_dense_materialization_positive_negative(self):
        import jax.numpy as jnp
        import numpy as np

        from k8s_gpu_scheduler_tpu.analysis.traffic import TrafficContract

        pool = jnp.zeros((11, 6, 2, 7), jnp.float32)   # [n_pages,ps,Hkv,hd]
        tbl = np.tile(np.asarray([[1, 2]], np.int32), (3, 1))

        def gather(pool, tbl):
            return pool[tbl].reshape(3, 12, 2, 7).sum()  # [M, hit, Hkv, hd]

        found = self._audit(gather, (pool, tbl),
                            TrafficContract(donated=(0,)))
        assert "dense-materialization" in rules_of(found)
        sanctioned = self._audit(
            gather, (pool, tbl),
            TrafficContract(kv_scale={"hit": 1}, dense_ok=True,
                            rationale="parity-reference fallback",
                            donated=(0,)))
        assert sanctioned == []
        # The pool UPDATE chain (scatter pool->pool) is never dense.
        def update(pool, row):
            return (pool.at[1].set(row),)

        clean = self._audit(update, (pool, jnp.ones((6, 2, 7))),
                            TrafficContract(donated=(0,)))
        assert clean == []

    def test_whole_pool_dequant_is_dense(self):
        import jax.numpy as jnp

        from k8s_gpu_scheduler_tpu.analysis.traffic import TrafficContract

        pool = jnp.zeros((11, 6, 2, 7), jnp.int8)

        def dequant(pool):
            return (pool,), pool.astype(jnp.float32).sum()

        found = self._audit(
            dequant, (pool,),
            TrafficContract(donated=(0,), residency_multiple=None))
        assert "dense-materialization" in rules_of(found)

    def test_kv_class_exceeded(self):
        import jax.numpy as jnp

        from k8s_gpu_scheduler_tpu.analysis.traffic import TrafficContract

        x = jnp.zeros((3, 13), jnp.float32)            # [M, S]

        def quad(x):
            return (x[:, :, None] * x[:, None, :]).sum()   # [M, S, S]

        linear = TrafficContract(kv_scale={"S": 1},
                                 residency_multiple=None)
        found = self._audit(quad, (x,), linear)
        assert rules_of(found) == {"traffic-contract"}
        assert "S^2" in found[0].message
        square = TrafficContract(kv_scale={"S": 2},
                                 residency_multiple=None)
        assert self._audit(quad, (x,), square) == []

    def test_peak_residency_broken_vs_held_donation(self):
        import jax.numpy as jnp

        from k8s_gpu_scheduler_tpu.analysis.traffic import TrafficContract

        pool = jnp.zeros((11, 6, 2, 7), jnp.float32)
        row = jnp.ones((6, 2, 7), jnp.float32)

        def broken(pool, row):
            new = pool.at[1].set(row)
            return new, pool.sum()          # old pool read AFTER new exists

        found = self._audit(broken, (pool, row),
                            TrafficContract(donated=(0,)))
        assert rules_of(found) == {"peak-residency"}
        assert "2.00×" in found[0].message

        def held(pool, row):
            return (pool.at[1].set(row),)

        assert self._audit(held, (pool, row),
                           TrafficContract(donated=(0,))) == []
        # An UNDONATED pool argument keeps the caller's copy live for
        # the whole program: the same 2x high-water.
        found = self._audit(held, (pool, row),
                            TrafficContract(donated=()))
        assert rules_of(found) == {"peak-residency"}

    def test_vacuous_geometry_surfaces(self):
        import jax.numpy as jnp

        from k8s_gpu_scheduler_tpu.analysis.traffic import TrafficContract

        x = jnp.zeros((3, 13), jnp.float32)            # no n_pages dim
        found = self._audit(lambda x: (x * 2,), (x,),
                            TrafficContract(donated=(0,)))
        assert [f for f in found if f.severity == "warning"
                and "vacuous" in f.message]

    def test_every_registered_entry_declares_a_contract(self):
        """The acceptance gate, tier-1 fast (no engine builds): every
        serving entry point in the traffic registry — decode chunk,
        verify window, every (tb, hb) prefill rung, the tp-island
        variants — declares a traffic contract, and no contract is
        orphaned."""
        from k8s_gpu_scheduler_tpu.analysis import entrypoints as eps

        names = eps.traffic_entry_names()
        contracts = eps.traffic_contracts()
        assert set(names) == set(contracts), (
            "registry/contract drift: every traffic entry must declare "
            "a contract (missing contract = finding) and vice versa")
        assert {"traffic_decode_chunk", "traffic_verify_window",
                "traffic_prefill_tb16_hb0",
                "traffic_prefill_tb16_hb4_kernel",
                "traffic_prefill_tb16_hb4_gather",
                "traffic_decode_chunk_tp2",
                "traffic_decode_chunk_tp2_psum",
                "traffic_decode_chunk_tp2_replicated",
                "traffic_verify_window_tp2",
                "traffic_prefill_tb16_hb0_tp2",
                "traffic_prefill_tb16_hb4_kernel_tp2",
                "traffic_prefill_tb16_hb4_gather_tp2"} <= set(names)
        gather = contracts["traffic_prefill_tb16_hb4_gather"]
        assert gather.dense_ok and gather.rationale, \
            "the gather fallback is the ONE sanctioned dense carrier"
        assert not contracts["traffic_prefill_tb16_hb4_kernel"].dense_ok
        # Every sharded-weight dispatch row declares the replicated-
        # weight check; the legacy replicated island is the ONE tp row
        # that (by design) does not.
        for name, c in contracts.items():
            if name.endswith("_tp2") or name.endswith("_tp2_psum"):
                assert c.tp == 2 and c.weight_sharded, name
        assert not contracts[
            "traffic_decode_chunk_tp2_replicated"].weight_sharded

    def test_bad_traffic_fixture_caught(self):
        sys.path.insert(0, FIXTURES)
        try:
            import bad_traffic
        finally:
            sys.path.remove(FIXTURES)
        from k8s_gpu_scheduler_tpu.analysis.traffic import (
            TrafficContract, audit_traffic_callable,
        )

        by_name = {e[0]: e for e in bad_traffic.GRAFTCHECK_TRAFFIC_AUDIT}
        name, fn, args, geo, contract = by_name["bad_dense_gather"]
        found = audit_traffic_callable(fn, args, name, geo,
                                       TrafficContract(**contract))
        assert {"dense-materialization",
                "traffic-contract"} <= rules_of(found)
        name, fn, args, geo, contract = by_name["bad_broken_donation"]
        found = audit_traffic_callable(fn, args, name, geo,
                                       TrafficContract(**contract))
        assert rules_of(found) == {"peak-residency"}
        assert by_name["bad_no_contract"][4] is None

    @pytest.mark.slow   # builds + traces the full audit-engine registry
    # (~20 s); triple-covered per push: the dedicated CI step asserts
    # run_traffic_pass([]) is clean, the unfiltered CI pytest run
    # executes this cell, and the full CLI folds the pass in. The
    # per-rule unit tests above keep the rule logic tier-1.
    def test_registry_entries_audit_clean(self):
        """The acceptance criterion: the real serving dispatches uphold
        their declared traffic classes — decode O(pos), verify O(pos+γ),
        prefill rungs O(hit+tail) with zero dense prefix intermediates
        on the kernel path (the gather flagged-unless-sanctioned proof
        lives in the registry contract itself)."""
        from k8s_gpu_scheduler_tpu.analysis import run_traffic_pass

        report = run_traffic_pass([])
        assert report.findings == [], "\n" + report.render(
            header="traffic-contract regressions:")

    @pytest.mark.slow   # builds one audit engine + traces the gather
    # rung (~5 s); the toy-gather cell in
    # test_dense_materialization_positive_negative keeps the rule's
    # positive signal tier-1, and the unfiltered CI run executes this
    # engine-level edition.
    def test_gather_without_sanction_is_flagged(self):
        """The PR 13 bug-class proof: the SAME gather-mode prefill rung,
        audited under the kernel's strict contract, trips
        dense-materialization — so the rule would catch the dense
        prefix gather being reintroduced on the kernel path."""
        from k8s_gpu_scheduler_tpu.analysis import entrypoints as eps
        from k8s_gpu_scheduler_tpu.analysis.traffic import (
            TrafficContract, audit_traffic_callable,
        )

        ents = dict(eps.traffic_entrypoints())
        fn, args = ents["traffic_prefill_tb16_hb4_gather"]()
        strict = TrafficContract(kv_scale={"tb": 2}, donated=(1, 2, 3, 4))
        found = audit_traffic_callable(fn, args, "gather_strict",
                                       eps.TRAFFIC_GEOMETRY, strict)
        assert {"dense-materialization",
                "traffic-contract"} <= rules_of(found)
        assert any("hit" in f.message for f in found)

    @pytest.mark.slow   # builds one tp audit engine (~5 s); the fixture
    # seed (bad_replicated_weight_island) keeps the rule's positive
    # signal tier-1, and the unfiltered CI run executes this
    # engine-level edition.
    def test_replicated_weight_island_is_flagged(self):
        """The PR 15 silent-downgrade proof: the LEGACY replicated-
        weight island (weight_sharding=False), audited under a
        weight_sharded contract, trips the replicated-weight finding —
        so a dispatch quietly losing its weight slices cannot pass its
        contract row."""
        import warnings

        from k8s_gpu_scheduler_tpu.analysis import entrypoints as eps
        from k8s_gpu_scheduler_tpu.analysis.traffic import (
            TrafficContract, audit_traffic_callable,
        )

        ents = dict(eps.traffic_entrypoints())
        if "traffic_decode_chunk_tp2_replicated" not in ents:
            pytest.skip("needs >= 2 devices")
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            fn, args = ents["traffic_decode_chunk_tp2_replicated"]()
        strict = TrafficContract(kv_scale={"S": 1},
                                 donated=(1, 2, 3, 4, 5), tp=2,
                                 weight_sharded=True)
        found = audit_traffic_callable(fn, args, "replicated_strict",
                                       eps.TRAFFIC_GEOMETRY, strict)
        assert any(f.rule == "traffic-contract"
                   and "replicated weight" in f.message.lower()
                   for f in found), found

    def test_weight_sharded_contract_vacuous_geometry_warns(self):
        """A weight_sharded contract whose geometry lacks d/d_ff cannot
        check anything — surfaced, never silently green."""
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import Mesh, PartitionSpec as P

        from k8s_gpu_scheduler_tpu.analysis.traffic import (
            TrafficContract, audit_traffic_jaxpr,
        )
        from k8s_gpu_scheduler_tpu.parallel.sharding import shard_map

        mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
        fn = shard_map(lambda w: w.sum(), mesh=mesh, in_specs=(P(),),
                       out_specs=P(), check_vma=False)
        closed = jax.make_jaxpr(fn)(jnp.zeros((2, 6, 6), jnp.float32))
        found = audit_traffic_jaxpr(
            closed, "vacuous2", {"n_pages": 11, "L": 2},
            TrafficContract(kv_scale={}, weight_sharded=True,
                            residency_multiple=None))
        assert any("vacuous" in f.message for f in found), found


# -- determinism lint (pass 12) -----------------------------------------------

class TestDeterminism:
    """Per-rule true-positive AND true-negative cells, plus the scope
    gate — the pass's value is precision: fleet/ must stay clean not
    because the rules are blind but because the code really is
    deterministic."""

    def lint(self, src, path="x/fleet/mod.py"):
        from k8s_gpu_scheduler_tpu.analysis.determinism import (
            lint_determinism_source,
        )
        return lint_determinism_source(path, textwrap.dedent(src))

    def test_unseeded_random_instance(self):
        found = self.lint("""
            import random
            r = random.Random()
        """)
        assert rules_of(found) == {"unseeded-rng"}

    def test_seeded_random_instance_clean(self):
        # The faults.py idiom: crc32-derived per-decision seeds.
        found = self.lint("""
            import random
            import zlib
            def rng_for(key, run_seed):
                return random.Random(zlib.crc32(key.encode()) ^ run_seed)
        """)
        assert found == []

    def test_module_global_random_fn(self):
        found = self.lint("""
            import random
            def jitter(xs):
                return random.choice(xs)
        """)
        assert rules_of(found) == {"unseeded-rng"}

    def test_numpy_legacy_global_and_unseeded_default_rng(self):
        found = self.lint("""
            import numpy as np
            def a(xs):
                np.random.shuffle(xs)
            def b():
                return np.random.default_rng()
        """)
        assert [f.rule for f in found] == ["unseeded-rng", "unseeded-rng"]

    def test_seeded_default_rng_clean(self):
        found = self.lint("""
            import numpy as np
            def mk(seed):
                return np.random.default_rng(seed)
        """)
        assert found == []

    def test_builtin_hash(self):
        found = self.lint("""
            def route(prompt, n):
                return hash(tuple(prompt)) % n
        """)
        assert rules_of(found) == {"builtin-hash"}

    def test_crc32_clean(self):
        found = self.lint("""
            import zlib
            def route(blob, n):
                return zlib.crc32(blob) % n
        """)
        assert found == []

    def test_unordered_iteration_append_and_first_match(self):
        found = self.lint("""
            class Picker:
                def __init__(self):
                    self._members = {"a", "b"}
                def victims(self, n):
                    out = []
                    for m in self._members:
                        out.append(m)
                        if len(out) == n:
                            break
                    return out
                def first_live(self, dead):
                    for m in self._members - dead:
                        return m
        """)
        assert [f.rule for f in found] == ["unordered-iteration"] * 2

    def test_sorted_iteration_clean(self):
        found = self.lint("""
            class Picker:
                def __init__(self):
                    self._members = {"a", "b"}
                def victims(self):
                    out = []
                    for m in sorted(self._members):
                        out.append(m)
                    return out
        """)
        assert found == []

    def test_membership_check_loop_clean(self):
        # A loop that only validates (raise — no ordered sink) is fine:
        # the paging.py assert_consistent shape.
        found = self.lint("""
            def check(dram, disk, nxt):
                for k in dram | disk:
                    if k >= nxt:
                        raise ValueError(k)
        """)
        assert found == []

    def test_wall_clock_decision(self):
        found = self.lint("""
            import time
            def expired(deadline):
                return time.time() > deadline
        """)
        assert rules_of(found) == {"wall-clock-decision"}

    def test_injected_clock_clean(self):
        found = self.lint("""
            def expired(clock, deadline):
                return clock.wall() > deadline
        """)
        assert found == []

    def test_out_of_scope_file_ignored(self):
        from k8s_gpu_scheduler_tpu.analysis.determinism import (
            lint_determinism_source,
        )
        src = "import random\nr = random.Random()\n"
        assert lint_determinism_source("x/bench_helpers.py", src) == []
        # …until it opts in with the fixture marker.
        marked = "GRAFTCHECK_DETERMINISM_LINT = True\n" + src
        assert rules_of(lint_determinism_source(
            "x/bench_helpers.py", marked)) == {"unseeded-rng"}

    def test_suppression_with_rationale_honored(self):
        found = self.lint("""
            import random
            # demo-only path, never replayed — graftcheck: ignore[unseeded-rng]
            r = random.Random()
        """)
        assert found == []

    def test_fixture_trips_all_four_rules(self):
        from k8s_gpu_scheduler_tpu.analysis.determinism import (
            lint_determinism_source,
        )
        with open(os.path.join(FIXTURES, "bad_determinism.py")) as fh:
            src = fh.read()
        assert rules_of(lint_determinism_source(
            os.path.join(FIXTURES, "bad_determinism.py"), src)) == {
                "unseeded-rng", "builtin-hash", "unordered-iteration",
                "wall-clock-decision"}

    def test_rides_fast_passes_with_timing(self):
        report = run_fast_passes([os.path.join(FIXTURES,
                                               "bad_determinism.py")])
        assert "determinism" in report.pass_seconds
        assert {"unseeded-rng", "builtin-hash", "unordered-iteration",
                "wall-clock-decision"} <= rules_of(report.findings)


# -- wire-format schema audit (pass 11) ---------------------------------------

class TestWirecompat:
    """Diff-rule cells against synthetic schemas (the golden-vs-live
    mechanics; the real registry's clean diff and the per-artifact
    decode fidelity live in tests/test_wire_compat.py)."""

    GOLDEN = {
        "artifact": "toy", "schema_version": 1,
        "groups": {"json": {
            "a": {"type": "str", "required": True},
            "b": {"type": "int", "required": False},
        }},
    }

    def diff(self, live):
        from k8s_gpu_scheduler_tpu.analysis.wirecompat import diff_schemas
        return diff_schemas("toy", live, self.GOLDEN)

    def test_identical_schemas_clean(self):
        import copy
        assert self.diff(copy.deepcopy(self.GOLDEN)) == []

    def test_missing_golden_is_stale(self):
        from k8s_gpu_scheduler_tpu.analysis.wirecompat import diff_schemas
        found = diff_schemas("toy", self.GOLDEN, None)
        assert rules_of(found) == {"wire-golden-stale"}
        assert "--update-schemas" in found[0].message

    def test_removed_field_is_wire_break(self):
        live = {"artifact": "toy", "schema_version": 1,
                "groups": {"json": {
                    "a": {"type": "str", "required": True}}}}
        assert "wire-break" in rules_of(self.diff(live))

    def test_type_change_is_wire_break(self):
        import copy
        live = copy.deepcopy(self.GOLDEN)
        live["groups"]["json"]["b"]["type"] = "float"
        found = self.diff(live)
        assert "wire-break" in rules_of(found)
        assert any("int -> float" in f.message for f in found)

    def test_new_required_field_is_wire_no_default(self):
        import copy
        live = copy.deepcopy(self.GOLDEN)
        live["groups"]["json"]["c"] = {"type": "str", "required": True}
        assert "wire-no-default" in rules_of(self.diff(live))

    def test_benign_add_with_default_is_only_stale(self):
        import copy
        live = copy.deepcopy(self.GOLDEN)
        live["groups"]["json"]["c"] = {"type": "str", "required": False}
        assert rules_of(self.diff(live)) == {"wire-golden-stale"}

    def test_requiredness_probe_uses_real_decoder(self):
        """The probe literally deletes a field and runs from_json: the
        only required ReplicaSummary field is the one with no dataclass
        default."""
        from k8s_gpu_scheduler_tpu.analysis.wirecompat import (
            extract_schemas,
        )
        fields = extract_schemas()["replica_summary"]["groups"]["json"]
        required = {k for k, v in fields.items() if v["required"]}
        assert required == {"replica"}

    def test_update_flag_then_clean(self, tmp_path):
        """--update-schemas writes goldens the next run diffs clean, and
        a second update is byte-identical (the CI no-op pin)."""
        from k8s_gpu_scheduler_tpu.analysis import run_wirecompat_pass
        rep = run_wirecompat_pass(paths=[], schema_dir=str(tmp_path),
                                  update=True)
        assert rep.errors == []
        first = {p.name: p.read_bytes() for p in tmp_path.iterdir()}
        assert set(first) == {"serving_snapshot.json",
                              "replica_summary.json",
                              "request_journal.json"}
        rep = run_wirecompat_pass(paths=[], schema_dir=str(tmp_path))
        assert rep.findings == []
        run_wirecompat_pass(paths=[], schema_dir=str(tmp_path),
                            update=True)
        assert {p.name: p.read_bytes()
                for p in tmp_path.iterdir()} == first

    def test_hook_entries_and_hook_error(self, tmp_path):
        """The seeded-fixture protocol: a GRAFTCHECK_WIRECOMPAT_AUDIT
        hook's drifted schema fails the pass, and a malformed entry
        surfaces as hook-error instead of crashing the run."""
        from k8s_gpu_scheduler_tpu.analysis import run_wirecompat_pass
        rep = run_wirecompat_pass(
            paths=[os.path.join(FIXTURES, "bad_wirecompat.py")])
        assert {"wire-break", "wire-no-default",
                "wire-golden-stale"} <= rules_of(rep.findings)
        assert "wirecompat" in rep.pass_seconds
        bad = tmp_path / "bad_hook.py"
        bad.write_text("GRAFTCHECK_WIRECOMPAT_AUDIT = [('only-name',)]\n")
        rep = run_wirecompat_pass(paths=[str(bad)])
        assert "hook-error" in rules_of(rep.findings)


# -- CLI contract -------------------------------------------------------------

def run_cli(*extra, fast=True):
    cmd = [sys.executable, "-m", "k8s_gpu_scheduler_tpu.analysis"]
    if fast:
        cmd.append("--fast")
    cmd += list(extra)
    return subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                          timeout=300)


class TestCli:
    def test_repaired_tree_exits_zero(self):
        proc = run_cli()
        assert proc.returncode == 0, proc.stderr

    # PR 15 budget: each CLI invocation re-runs every fast pass over the
    # whole tree (~5 s × 8 fixtures), so one representative fixture
    # keeps the exit-code wiring tier-1 and the rest ride slow — the
    # per-rule unit tests keep every family's DETECTION tier-1, the
    # all-families full-CLI slow test + the unfiltered CI pytest run +
    # the dedicated CI lint step re-run every fixture on every push.
    @pytest.mark.parametrize("fixture", [
        "bad_astlint.py",
        *(pytest.param(f, marks=pytest.mark.slow)
          for f in ("bad_retry.py", "bad_trace.py", "bad_lockorder.py",
                    "bad_determinism.py",
                    "bad_vmem.py", "bad_vmem_paged.py",
                    "bad_vmem_verify.py", "bad_vmem_prefill.py")),
    ])
    def test_reintroduced_fast_fixtures_fail(self, fixture):
        proc = run_cli(os.path.join(FIXTURES, fixture))
        assert proc.returncode == 1, (fixture, proc.stderr)
        assert ": [" in proc.stderr           # file:line: [rule] rendering

    def test_json_findings_schema(self):
        """--json carries the full findings list in a stable schema
        (rule/path/line/severity/message) so CI can annotate instead of
        grepping the text rendering."""
        import json as _json

        proc = run_cli(os.path.join(FIXTURES, "bad_lockorder.py"),
                       "--json")
        assert proc.returncode == 1
        summary = _json.loads(proc.stdout.strip().splitlines()[-1])
        assert summary["n_findings"] == len(summary["findings"]) > 0
        assert summary["errors"] > 0
        for f in summary["findings"]:
            assert set(f) == {"rule", "path", "line", "severity",
                              "message"}
        assert "lock-cycle" in summary["rules"]
        assert "lockorder" in summary["pass_seconds"]

    def test_suppressions_catalogue_flag(self):
        proc = run_cli("--suppressions")
        assert proc.returncode == 0, proc.stderr
        rows = [ln for ln in proc.stdout.splitlines()
                if ln.startswith("| `")]
        assert rows and all(ln.count("|") == 4 for ln in rows)

    @pytest.mark.slow   # ~1 min of traced-pass subprocess; the fast-pass
    # fixture test above keeps per-family CLI signal in tier-1, and the
    # unfiltered CI suite runs this end-to-end check.
    def test_full_cli_catches_all_fixture_families(self):
        """The acceptance criterion end-to-end: the DEFAULT twelve-pass
        CLI exits non-zero with file:line findings when the seeded bad
        fixtures are in the scanned paths (one subprocess run for every
        family — the traced passes dominate its wall time)."""
        proc = run_cli(FIXTURES, "--json", fast=False)
        assert proc.returncode == 1, proc.stderr
        import json as _json

        summary = _json.loads(proc.stdout.strip().splitlines()[-1])
        assert {"lock-guard", "vmem-budget", "captured-const",
                "steady-state-retrace", "shared-page-write",
                "unbounded-retry", "trace-in-jit",
                # pass 10 (bad_lockorder.py) + the suppression policy
                "lock-cycle", "torn-snapshot", "use-after-donate",
                "bare-suppression",
                # pass 9 (bad_traffic.py hook entries)
                "dense-materialization", "peak-residency",
                "traffic-contract",
                # pass 11 (bad_wirecompat.py hook entries)
                "wire-break", "wire-no-default", "wire-golden-stale",
                # pass 12 (bad_determinism.py, opt-in marker)
                "unseeded-rng", "builtin-hash", "unordered-iteration",
                "wall-clock-decision"} <= set(summary["rules"])
