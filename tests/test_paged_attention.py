"""Paged KV cache (ops/decode_attention.py paged_* + the paged
ContinuousBatcher) — parity against the contiguous fused and dense paths.

The paged kernel reuses the contiguous kernel's online-softmax/split-K
body; only the BlockSpec index maps change (logical kv block j streams
physical page ``block_table[b, j]``). So the parity matrix here pins the
TABLE INDIRECTION — pools are built by scattering a known contiguous
cache through a random page permutation, and every output must match the
contiguous kernel and the dense reference bit-for-tolerance. The engine
tests pin the layout end-to-end: paged and contiguous ContinuousBatchers
must emit identical token streams, and the admission test demonstrates
the design win — a prompt the contiguous cursor window rejects admits
immediately against fragmented free pages, with no epoch-roll idle step.

Everything runs in interpret mode on CPU (ops.pallas_interpret); the
same kernel compiles on TPU, where `bench.py --leg paged_attention`
measures it.
"""
import dataclasses
import json
import math
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_scheduler_tpu.ops import (
    dense_decode_reference, flash_decode_attention, gather_paged_kv,
    paged_decode_attention, paged_plan,
)

TOL = {jnp.float32: 3e-6, jnp.bfloat16: 4e-2}


def maxdiff(a, b):
    return float(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32)).max())


def paged_case(B=2, H=8, Hkv=4, hd=32, S=64, ps=16, dtype=jnp.float32,
               seed=0, perm_seed=0):
    """A contiguous cache plus its paged twin: pages scattered through a
    random permutation (page 0 reserved as null), table mapping logical
    blocks back to them."""
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (B, H, hd), dtype)
    k = jax.random.normal(ks[1], (B, S, Hkv, hd), dtype)
    v = jax.random.normal(ks[2], (B, S, Hkv, hd), dtype)
    nb = S // ps
    n_pages = 1 + B * nb
    rng = np.random.default_rng(perm_seed)
    table = rng.permutation(np.arange(1, n_pages)).reshape(B, nb)
    kp = jnp.zeros((n_pages, ps, Hkv, hd), dtype)
    vp = jnp.zeros((n_pages, ps, Hkv, hd), dtype)
    kp = kp.at[table].set(k.reshape(B, nb, ps, Hkv, hd))
    vp = vp.at[table].set(v.reshape(B, nb, ps, Hkv, hd))
    return q, k, v, kp, vp, jnp.asarray(table, jnp.int32)


class TestPagedPlan:
    def test_plan_legality(self):
        assert paged_plan(128, 64) == 8
        assert paged_plan(4, 16) == 1
        assert paged_plan(12, 32) == 4
        assert paged_plan(4, 48) is None             # not a pow2 page
        assert paged_plan(4, 4) is None              # page below tile min
        assert paged_plan(4, 512) is None            # page above block max
        assert paged_plan(8, 16, 3) is None          # splits must divide
        assert paged_plan(8, 16, 4) == 4

    def test_unsupported_shapes_raise(self):
        q, k, v, kp, vp, table = paged_case()
        with pytest.raises(ValueError):
            paged_decode_attention(q, kp, vp, table, 50, n_splits=3,
                                   interpret=True)
        q6 = jax.random.normal(jax.random.PRNGKey(0), (2, 6, 32))
        with pytest.raises(ValueError):
            paged_decode_attention(q6, kp, vp, table, 50, interpret=True)

    def test_gather_inverts_the_permutation(self):
        q, k, v, kp, vp, table = paged_case()
        assert maxdiff(gather_paged_kv(kp, table), k) == 0.0
        assert maxdiff(gather_paged_kv(vp, table), v) == 0.0


class TestPagedParity:
    """The indirection matrix: paged kernel vs the contiguous fused kernel
    vs the dense reference, across GQA ratios, dtypes, raggedness, int8-KV
    and split-K — the acceptance parity grid."""

    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    @pytest.mark.parametrize("hkv", [8, 2, 1])           # Hkv = H, H/4, H/8
    def test_gqa_and_dtypes(self, dtype, hkv):
        q, k, v, kp, vp, table = paged_case(Hkv=hkv, dtype=dtype)
        lengths = jnp.array([17, 63])
        ref = dense_decode_reference(q, k, v, lengths=lengths)
        fused = flash_decode_attention(q, k, v, lengths, block_k=16,
                                       interpret=True)
        out = paged_decode_attention(q, kp, vp, table, lengths,
                                     interpret=True)
        assert out.dtype == q.dtype
        assert maxdiff(out, ref) < TOL[dtype]
        # Same kernel body either side of the indirection: paged and
        # contiguous fused agree to float-noise, not just to dense-tol.
        assert maxdiff(out, fused) < TOL[dtype]

    def test_ragged_fill_lengths(self):
        """pos = 0, 1, page-1, page, S-1 with ps=16: every page-boundary
        case of the traced length mask (lengths = pos+1)."""
        B = 5
        q, k, v, kp, vp, table = paged_case(B=B)
        lengths = jnp.array([1, 2, 16, 17, 64])      # pos + 1
        ref = dense_decode_reference(q, k, v, lengths=lengths)
        out = paged_decode_attention(q, kp, vp, table, lengths,
                                     interpret=True)
        assert maxdiff(out, ref) < 1e-5

    def test_scalar_length_broadcasts(self):
        q, k, v, kp, vp, table = paged_case()
        ref = dense_decode_reference(q, k, v, lengths=jnp.array([23, 23]))
        out = paged_decode_attention(q, kp, vp, table, 23, interpret=True)
        assert maxdiff(out, ref) < 1e-5

    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_int8_kv(self, dtype):
        from k8s_gpu_scheduler_tpu.models.serving import _kv_quant

        q, k, v, kp, vp, table = paged_case(dtype=dtype)
        kq, ks = _kv_quant(k)
        vq, vs = _kv_quant(v)
        # Quantize the POOL the same way the engine does (per-row scales
        # travel with their page).
        kpq, kps = _kv_quant(kp)
        vpq, vps = _kv_quant(vp)
        lengths = jnp.array([9, 64])
        ref = dense_decode_reference(q, kq, vq, lengths=lengths,
                                     k_scale=ks, v_scale=vs)
        out = paged_decode_attention(q, kpq, vpq, table, lengths,
                                     k_scale=kps, v_scale=vps,
                                     interpret=True)
        assert maxdiff(out, ref) < TOL[dtype]

    def test_split_k_combine(self):
        """Split-K over the block-table axis: logical splits whose pages
        are physically scattered must still LSE-combine to the dense
        answer, including splits entirely past the filled prefix."""
        q, k, v, kp, vp, table = paged_case(S=128, ps=16)
        lengths = jnp.array([5, 100])                # split 4 dead for row 0
        ref = dense_decode_reference(q, k, v, lengths=lengths)
        one = paged_decode_attention(q, kp, vp, table, lengths, n_splits=1,
                                     interpret=True)
        four = paged_decode_attention(q, kp, vp, table, lengths, n_splits=4,
                                      interpret=True)
        assert maxdiff(one, ref) < 1e-5
        assert maxdiff(four, ref) < 1e-5
        assert maxdiff(four, one) < 1e-5

    def test_permutation_invariance(self):
        """The physical page order is INVISIBLE: two pools holding the
        same logical cache under different permutations produce
        identical outputs."""
        q, k, v, kp1, vp1, t1 = paged_case(perm_seed=1)
        _, _, _, kp2, vp2, t2 = paged_case(perm_seed=2)
        lengths = jnp.array([33, 61])
        a = paged_decode_attention(q, kp1, vp1, t1, lengths, interpret=True)
        b = paged_decode_attention(q, kp2, vp2, t2, lengths, interpret=True)
        assert maxdiff(a, b) < 1e-6

    def test_stale_tail_rows_are_masked(self):
        """Rows past `lengths` inside the last page carry stale garbage
        from freed requests by design — poison them and assert the
        output is untouched."""
        q, k, v, kp, vp, table = paged_case()
        lengths = jnp.array([18, 30])                # mid-page fills
        poisoned_k, poisoned_v = kp, vp
        for b in range(2):
            pos = int(lengths[b])
            pg = table[b, pos // 16]
            poisoned_k = poisoned_k.at[pg, pos % 16:].set(1e4)
            poisoned_v = poisoned_v.at[pg, pos % 16:].set(1e4)
        clean = paged_decode_attention(q, kp, vp, table, lengths,
                                       interpret=True)
        dirty = paged_decode_attention(q, poisoned_k, poisoned_v, table,
                                       lengths, interpret=True)
        assert maxdiff(clean, dirty) == 0.0

    def test_runs_under_jit_and_scan(self):
        q, k, v, kp, vp, table = paged_case()
        lengths = jnp.array([17, 63])
        ref = dense_decode_reference(q, k, v, lengths=lengths)

        def step(c, _):
            return c, paged_decode_attention(q, kp, vp, table, lengths)

        _, outs = jax.jit(
            lambda: jax.lax.scan(step, 0, None, length=2))()
        assert maxdiff(outs[1], ref) < 1e-5


class TestPagedEngine:
    """The layout end-to-end: a paged ContinuousBatcher must be token-
    identical to the contiguous engine, and admission must be free of the
    cursor design's contiguity constraint and epoch roll."""

    def _cfg(self, **kw):
        from k8s_gpu_scheduler_tpu.models import LlamaConfig

        return dataclasses.replace(LlamaConfig.tiny(), dtype=jnp.float32,
                                   **kw)

    def _run(self, cfg, layout, prompts, max_new=5, **kw):
        from k8s_gpu_scheduler_tpu.models import init_params
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        params = init_params(cfg, jax.random.PRNGKey(0))
        eng = ContinuousBatcher(params, cfg, n_slots=2, max_len=32, chunk=4,
                                prefill_bucket=8, kv_layout=layout,
                                page_size=8, **kw)
        ids = [eng.submit(p, max_new=max_new) for p in prompts]
        done = eng.run()
        return [done[i] for i in ids], eng

    @pytest.mark.parametrize("impl,kvd", [
        ("dense", None),
        pytest.param("dense", "int8", marks=pytest.mark.slow),
        pytest.param("fused", None, marks=pytest.mark.slow),
        ("fused", "int8"),
    ])
    def test_paged_matches_contiguous_engine(self, impl, kvd):
        cfg = self._cfg(decode_attn=impl)
        rng = np.random.default_rng(0)
        prompts = [list(rng.integers(0, cfg.vocab, n)) for n in (3, 5, 4)]
        paged, peng = self._run(cfg, "paged", prompts, kv_dtype=kvd)
        contig, _ = self._run(cfg, "contiguous", prompts, kv_dtype=kvd)
        assert paged == contig
        # Every page came back at drain.
        m = peng.pool_metrics()
        assert m["pages_in_use"] == 0 and m["pages_free"] == m["pages_total"]
        assert m["pages_watermark"] > 0

    def test_generate_token_identity(self):
        """Single request through the paged engine == the static generate
        path (greedy, f32 params — no near-tie noise)."""
        from k8s_gpu_scheduler_tpu.models import generate, init_params

        cfg = self._cfg(decode_attn="fused")
        params = init_params(cfg, jax.random.PRNGKey(0))
        prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 5), 0,
                                    cfg.vocab)
        ref = generate(params, prompt, cfg, max_new=6, max_len=32)
        out, _ = self._run(cfg, "paged", [list(np.asarray(prompt[0]))],
                           max_new=6)
        # generate emits max_new CONTINUATION tokens; the engine's stream
        # starts at the same first token (prefill argmax).
        assert out[0] == list(np.asarray(ref[0]))

    def test_fragmented_admission_no_epoch_roll(self):
        """The acceptance scenario: a long prompt the contiguous cursor
        window REJECTS (cursor too far advanced, epoch roll pending)
        admits immediately against fragmented free pages — while another
        request is still decoding, i.e. with no all-slots-drained idle
        step — and the final token streams are identical anyway."""
        from k8s_gpu_scheduler_tpu.models import init_params
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        cfg = self._cfg()
        params = init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        pA = list(rng.integers(0, cfg.vocab, 4))     # long-running pin
        pB = list(rng.integers(0, cfg.vocab, 4))     # finishes early
        pC = list(rng.integers(0, cfg.vocab, 20))    # the blocked head

        def drive(layout):
            eng = ContinuousBatcher(params, cfg, n_slots=2, max_len=32,
                                    chunk=4, prefill_bucket=8,
                                    kv_layout=layout, page_size=8)
            a = eng.submit(pA, max_new=29)
            b = eng.submit(pB, max_new=5)
            done = {}
            for _ in range(5):                       # B done, cursor >= 24
                done.update(eng.step())
            c = eng.submit(pC, max_new=5)
            done.update(eng.step())
            admitted = c not in [rid for rid, _ in eng._queue]
            slot_still_active = bool(eng._slot_req)  # A still decoding
            steps = 6
            while eng.pending:
                done.update(eng.step())
                steps += 1
            return admitted, slot_still_active, steps, \
                {k: done[k] for k in (a, b, c)}

        p_adm, p_active, p_steps, p_out = drive("paged")
        c_adm, _, c_steps, c_out = drive("contiguous")
        assert p_adm, "paged admission should take fragmented free pages"
        assert p_active, "admission must not wait for an all-slots drain"
        assert not c_adm, \
            "scenario broken: the contiguous cursor window admitted too"
        assert p_steps < c_steps, "paged should skip the epoch-roll wait"
        assert p_out == c_out

    def test_page_exhaustion_blocks_then_recovers(self):
        """A pool too small for two concurrent requests serializes them
        (strict FCFS on page shortage) instead of deadlocking or
        corrupting streams."""
        from k8s_gpu_scheduler_tpu.models import init_params
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        cfg = self._cfg()
        params = init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.default_rng(1)
        # Each request needs ceil((4+8)/8) = 2 pages; the pool has 3
        # usable — the second admission must wait for the first to free.
        eng = ContinuousBatcher(params, cfg, n_slots=2, max_len=32,
                                chunk=4, prefill_bucket=8,
                                kv_layout="paged", page_size=8, n_pages=4)
        prompts = [list(rng.integers(0, cfg.vocab, 4)) for _ in range(2)]
        ids = [eng.submit(p, max_new=9) for p in prompts]
        eng.step()
        assert len(eng._slot_req) == 1               # second is page-blocked
        assert eng._alloc.metrics()["page_denied"] >= 1
        done = eng.run()
        assert sorted(done) == sorted(ids)
        assert all(len(done[i]) == 9 for i in ids)
        assert eng.pool_metrics()["pages_in_use"] == 0

    def test_eos_frees_pages_early(self):
        from k8s_gpu_scheduler_tpu.models import init_params
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        cfg = self._cfg()
        params = init_params(cfg, jax.random.PRNGKey(0))
        # Find the first decode token greedily, then use it as eos so the
        # request reaps on its first chunk with budget left.
        probe = ContinuousBatcher(params, cfg, n_slots=1, max_len=32,
                                  chunk=2, prefill_bucket=8,
                                  kv_layout="paged", page_size=8)
        rid = probe.submit([5, 7, 11], max_new=4)
        first_tokens = probe.run()[rid]
        eos = first_tokens[1]
        eng = ContinuousBatcher(params, cfg, n_slots=1, max_len=32,
                                chunk=2, prefill_bucket=8,
                                kv_layout="paged", page_size=8, eos_id=eos)
        rid = eng.submit([5, 7, 11], max_new=20)
        out = eng.run()[rid]
        assert out[-1] == eos and len(out) < 20
        assert eng.pool_metrics()["pages_in_use"] == 0

    def test_reaped_shared_pages_stay_out_of_the_free_list(self):
        """EOS/reap × prefix sharing: when a reaped request's prefix
        pages are still referenced by a live slot (and the tree), they
        must NOT return to the free list until the last reference drops —
        a premature free would hand a live slot's system prompt to the
        next admission as scratch."""
        from k8s_gpu_scheduler_tpu.models import init_params
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        cfg = self._cfg()
        params = init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.default_rng(5)
        sysp = list(rng.integers(0, cfg.vocab, 16))  # 2 shareable pages
        eng = ContinuousBatcher(params, cfg, n_slots=2, max_len=64,
                                chunk=4, prefill_bucket=8,
                                kv_layout="paged", page_size=8,
                                prefix_cache=True)
        # Donor: populates the tree at reap.
        eng.submit(sysp + list(rng.integers(0, cfg.vocab, 3)), max_new=2)
        eng.run()
        shared = eng._prefix.match(sysp + [1])
        assert len(shared) == 2
        # Two sharers: A reaps early, B keeps decoding on the same pages.
        a = eng.submit(sysp + list(rng.integers(0, cfg.vocab, 3)),
                       max_new=2)
        b = eng.submit(sysp + list(rng.integers(0, cfg.vocab, 5)),
                       max_new=17)
        done = {}
        while a not in done:
            done.update(eng.step())
        assert eng.pending                           # B still live
        for p in shared:
            # tree + B: two references, and nowhere near the free list.
            assert eng._alloc.ref(p) == 2
            assert p not in eng._alloc._free
        eng._alloc.assert_consistent()
        done.update(eng.run())                       # B drains, releases
        for p in shared:
            assert eng._alloc.ref(p) == 1            # tree's reference only
        eng._prefix.evict(10)                        # last reference drops
        for p in shared:
            assert eng._alloc.ref(p) == 0
            assert p in eng._alloc._free
        eng._alloc.assert_consistent()

    def test_paged_rejects_bad_page_size(self):
        # (The old paged-rejects-mesh gate is gone: a mesh now selects
        # the shard_map island path — tests/test_sharded_serving.py.)
        from k8s_gpu_scheduler_tpu.models import init_params
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        cfg = self._cfg()
        params = init_params(cfg, jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="divisible"):
            ContinuousBatcher(params, cfg, n_slots=1, max_len=36,
                              kv_layout="paged", page_size=8)
        with pytest.raises(ValueError, match="kv_layout"):
            ContinuousBatcher(params, cfg, n_slots=1, max_len=32,
                              kv_layout="paging")
        # A request whose worst-case reservation exceeds the whole pool
        # could never admit — submit refuses instead of spinning FCFS.
        small = ContinuousBatcher(params, cfg, n_slots=1, max_len=32,
                                  chunk=4, kv_layout="paged", page_size=8,
                                  n_pages=3)
        with pytest.raises(ValueError, match="pages"):
            small.submit([1, 2, 3], max_new=20)


class TestPageAllocator:
    def test_double_free_and_foreign_page_rejected(self):
        """A double free must raise BEFORE mutating state: the same id on
        the free list twice would hand one physical page to two requests
        — silent KV cross-contamination (PageAllocator is public API,
        not protected by the engine's bookkeeping discipline)."""
        from k8s_gpu_scheduler_tpu.models.paging import PageAllocator

        a = PageAllocator(9)
        held_a, held_b = a.alloc(4), a.alloc(4)
        a.free(held_b)
        with pytest.raises(RuntimeError, match="double free"):
            a.free(held_b)
        assert a.in_use == 4 and a.free_count == 4   # state unchanged
        with pytest.raises(RuntimeError, match="double free"):
            a.free([99])                             # never handed out
        with pytest.raises(ValueError, match="null page"):
            a.free([0])
        a.free(held_a)
        m = a.metrics()
        assert m["pages_in_use"] == 0 and m["pages_free"] == 8

    def test_all_or_nothing_and_watermark(self):
        from k8s_gpu_scheduler_tpu.models.paging import PageAllocator

        a = PageAllocator(5)
        first = a.alloc(3)
        assert a.alloc(2) is None                    # only 1 free
        assert a.metrics()["page_denied"] == 1
        a.free(first)
        assert a.alloc(4) is not None
        assert a.metrics()["pages_watermark"] == 4


class TestBenchLeg:
    @pytest.mark.slow   # the dedicated CI step runs the same leg
    def test_paged_attention_microbench_smoke(self):
        """`bench.py --leg paged_attention --smoke` must emit ONE JSON
        line with paged-vs-contiguous fused-vs-dense tokens/s for both
        cache dtypes plus cache bytes and page utilization — the contract
        the CI bench-contract job and future BENCH_*.json capture ride
        on."""
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        out = subprocess.run(
            [sys.executable, "bench.py", "--leg", "paged_attention",
             "--smoke"],
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            capture_output=True, text=True, timeout=600, env=env)
        assert out.returncode == 0, out.stderr[-2000:]
        lines = [ln for ln in out.stdout.splitlines() if ln.strip()]
        assert len(lines) == 1, out.stdout
        rec = json.loads(lines[0])
        assert rec["metric"] == "paged_attention_microbench"
        extra = rec["extra"]
        for key in ("pagedattn_contig_fused_bf16_tok_s",
                    "pagedattn_paged_fused_bf16_tok_s",
                    "pagedattn_contig_fused_int8kv_tok_s",
                    "pagedattn_paged_fused_int8kv_tok_s",
                    "pagedattn_paged_dense_bf16_tok_s",
                    "pagedattn_contig_dense_bf16_tok_s",
                    "pagedattn_bytes_per_step_bf16",
                    "pagedattn_bytes_per_step_int8kv"):
            assert key in extra and extra[key] > 0, (key, extra)
        for key in ("paged_engine_page_utilization_peak",
                    "paged_engine_pages_total"):
            assert key in extra and extra[key] > 0, (key, extra)
