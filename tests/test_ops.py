"""Ops tests — sequence-parallel attention vs the dense reference.

Ring and Ulysses run under shard_map on the virtual 8-device CPU mesh
(conftest.py) — the same GSPMD path the TPU uses, so agreement here is the
multi-chip correctness evidence VERDICT.md weak-item 2 demanded.
"""
from functools import partial

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from k8s_gpu_scheduler_tpu.ops import (
    apply_rope,
    dense_attention,
    ring_attention,
    rms_norm,
    rope_freqs,
    swiglu,
    ulysses_attention,
)
from k8s_gpu_scheduler_tpu.parallel import MeshSpec, make_mesh
from k8s_gpu_scheduler_tpu.parallel.sharding import shard_map


def qkv(B=2, T=32, H=8, Hkv=4, d=16, dtype=jnp.float32):
    return (
        jax.random.normal(jax.random.PRNGKey(0), (B, T, H, d), dtype),
        jax.random.normal(jax.random.PRNGKey(1), (B, T, Hkv, d), dtype),
        jax.random.normal(jax.random.PRNGKey(2), (B, T, Hkv, d), dtype),
    )


def sharded(impl, mesh):
    spec = P("dp", "sp", "tp", None)
    return jax.jit(
        shard_map(
            partial(impl, axis_name="sp", causal=True),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=False,
        )
    )


class TestSequenceParallelAttention:
    @pytest.fixture(scope="class")
    def mesh(self):
        return make_mesh(MeshSpec({"dp": 1, "sp": 4, "tp": 2}))

    def test_ring_matches_dense(self, mesh):
        q, k, v = qkv()
        ref = dense_attention(q, k, v, causal=True)
        out = sharded(ring_attention, mesh)(q, k, v)
        assert jnp.abs(out - ref).max() < 1e-5

    def test_ulysses_matches_dense(self, mesh):
        q, k, v = qkv()
        ref = dense_attention(q, k, v, causal=True)
        out = sharded(ulysses_attention, mesh)(q, k, v)
        assert jnp.abs(out - ref).max() < 1e-5

    def test_ring_non_causal(self, mesh):
        q, k, v = qkv()
        ref = dense_attention(q, k, v, causal=False)
        spec = P("dp", "sp", "tp", None)
        out = jax.jit(
            shard_map(
                partial(ring_attention, axis_name="sp", causal=False),
                mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
                check_vma=False,
            )
        )(q, k, v)
        assert jnp.abs(out - ref).max() < 1e-5

    def test_gqa_repeat_equivalence(self):
        """GQA must equal MHA with explicitly repeated kv heads."""
        q, k, v = qkv(H=8, Hkv=2)
        expanded = dense_attention(
            q, jnp.repeat(k, 4, axis=2), jnp.repeat(v, 4, axis=2), causal=True
        )
        assert jnp.abs(dense_attention(q, k, v) - expanded).max() < 1e-6

    def test_causal_first_token_attends_only_itself(self):
        q, k, v = qkv(T=4, H=2, Hkv=2)
        out = dense_attention(q, k, v, causal=True)
        # Row 0 sees only k[0] → output is exactly v[0] (softmax of one).
        assert jnp.allclose(out[:, 0], v[:, 0], atol=1e-6)


class TestLayers:
    def test_rms_norm_unit_scale(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 64)) * 10
        y = rms_norm(x, jnp.ones((64,)))
        rms = jnp.sqrt(jnp.mean(y.astype(jnp.float32) ** 2, axis=-1))
        assert jnp.allclose(rms, 1.0, atol=1e-3)

    def test_rope_preserves_norm_and_relative_phase(self):
        angles = rope_freqs(16, 8)
        x = jax.random.normal(jax.random.PRNGKey(0), (1, 8, 2, 16))
        y = apply_rope(x, angles)
        assert jnp.allclose(
            jnp.linalg.norm(x, axis=-1), jnp.linalg.norm(y, axis=-1), atol=1e-4
        )
        # Position 0 gets the identity rotation.
        assert jnp.allclose(y[:, 0], x[:, 0], atol=1e-6)

    def test_swiglu_shapes(self):
        x = jnp.ones((2, 8, 16))
        out = swiglu(
            x, jnp.ones((16, 32)), jnp.ones((16, 32)), jnp.ones((32, 16))
        )
        assert out.shape == (2, 8, 16)


class TestFlashAttention:
    """Pallas kernel in interpret mode (CPU) vs the dense reference — the
    same kernel runs compiled on TPU (bench.py exercises that path)."""

    def test_matches_dense_causal_and_not(self):
        from k8s_gpu_scheduler_tpu.ops import flash_attention

        q, k, v = qkv(T=256, H=4, Hkv=2, d=64)
        for causal in (True, False):
            ref = dense_attention(q, k, v, causal=causal)
            out = flash_attention(q, k, v, causal=causal, interpret=True)
            assert jnp.abs(out - ref).max() < 2e-5

    def test_multi_kv_block_accumulation(self):
        from k8s_gpu_scheduler_tpu.ops import flash_attention

        # T=512 with block 128 → 4 kv blocks per q block: the running
        # (m, l, acc) recurrence crosses blocks.
        q, k, v = qkv(T=512, H=2, Hkv=2, d=32)
        ref = dense_attention(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True, block_q=128, block_k=128,
                              interpret=True)
        assert jnp.abs(out - ref).max() < 2e-5

    def test_ragged_length_rejected(self):
        from k8s_gpu_scheduler_tpu.ops import flash_attention

        q, k, v = qkv(T=100, H=2, Hkv=2, d=32)
        # T <= block: clamps to one block and still works...
        ref = dense_attention(q, k, v, causal=True)
        out = flash_attention(q, k, v, interpret=True)
        assert jnp.abs(out - ref).max() < 2e-5
        # ...but an explicit non-dividing block is an error, not silence.
        with pytest.raises(ValueError):
            flash_attention(q, k, v, block_q=64, block_k=64, interpret=True)

    def test_gradients_match_dense(self):
        from k8s_gpu_scheduler_tpu.ops import flash_attention_diff

        q, k, v = qkv(T=128, H=2, Hkv=2, d=32)

        def loss_flash(q, k, v):
            return flash_attention_diff(q, k, v, True).sum()

        def loss_dense(q, k, v):
            return dense_attention(q, k, v, causal=True).sum()

        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for gf, gd in zip(g_flash, g_dense):
            assert jnp.abs(gf - gd).max() < 2e-5

    def test_gradients_multi_block_weighted(self):
        """T=512 with 128-blocks: the bwd dq kv-sweep and dkv q-sweep both
        cross 4 blocks; a non-uniform cotangent catches p/ds mixups that a
        .sum() loss cancels out."""
        from k8s_gpu_scheduler_tpu.ops import flash_attention_diff

        q, k, v = qkv(T=512, H=2, Hkv=2, d=32)
        w = jax.random.normal(jax.random.PRNGKey(7), (2, 512, 2, 32))

        def loss(impl):
            def f(q, k, v):
                return (impl(q, k, v) * w).sum()
            return f

        g_flash = jax.grad(
            loss(lambda q, k, v: flash_attention_diff(q, k, v, True, 128, 128)),
            argnums=(0, 1, 2))(q, k, v)
        g_dense = jax.grad(
            loss(lambda q, k, v: dense_attention(q, k, v, causal=True)),
            argnums=(0, 1, 2))(q, k, v)
        for gf, gd in zip(g_flash, g_dense):
            assert jnp.abs(gf - gd).max() < 3e-4

    def test_gradients_gqa_and_noncausal(self):
        """GQA: dk/dv must sum over the repeated head groups; also checks
        the non-causal backward (no block skipping)."""
        from k8s_gpu_scheduler_tpu.ops import flash_attention_diff

        q, k, v = qkv(T=128, H=4, Hkv=2, d=32)
        w = jax.random.normal(jax.random.PRNGKey(3), (2, 128, 4, 32))
        for causal in (True, False):
            g_flash = jax.grad(
                lambda q, k, v: (flash_attention_diff(q, k, v, causal) * w).sum(),
                argnums=(0, 1, 2))(q, k, v)
            g_dense = jax.grad(
                lambda q, k, v: (dense_attention(q, k, v, causal=causal) * w).sum(),
                argnums=(0, 1, 2))(q, k, v)
            for gf, gd in zip(g_flash, g_dense):
                assert gf.shape == gd.shape
                assert jnp.abs(gf - gd).max() < 3e-4

    def test_flash_shard_map_dp_tp(self):
        """The model's non-sp mesh path: flash under shard_map sharded over
        (batch, heads) must match dense on the global arrays — fwd and bwd."""
        from k8s_gpu_scheduler_tpu.ops import flash_attention_diff

        mesh = make_mesh(MeshSpec({"dp": 2, "fsdp": 1, "sp": 1, "tp": 4}))
        q, k, v = qkv(B=2, T=128, H=8, Hkv=4, d=32)
        spec = P(("dp", "fsdp"), None, "tp", None)
        fn = jax.jit(shard_map(
            lambda q, k, v: flash_attention_diff(q, k, v, True),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        ))
        ref = dense_attention(q, k, v, causal=True)
        assert jnp.abs(fn(q, k, v) - ref).max() < 2e-5
        g_flash = jax.grad(lambda q, k, v: fn(q, k, v).sum(),
                           argnums=(0, 1, 2))(q, k, v)
        g_dense = jax.grad(
            lambda q, k, v: dense_attention(q, k, v, causal=True).sum(),
            argnums=(0, 1, 2))(q, k, v)
        for gf, gd in zip(g_flash, g_dense):
            assert jnp.abs(gf - gd).max() < 3e-4

    def test_gqa_head_divisibility_rejected(self):
        from k8s_gpu_scheduler_tpu.ops import flash_attention

        q, _, _ = qkv(T=128, H=6, Hkv=6, d=32)
        _, k, v = qkv(T=128, H=6, Hkv=4, d=32)
        with pytest.raises(ValueError):
            flash_attention(q, k, v, interpret=True)
        with pytest.raises(ValueError):
            dense_attention(q, k, v)
