"""Registry tests: build the C++ kvstored, drive it over a real socket.

The reference's Redis test dials a hardcoded live cluster
(pkg/redis/client/client_test.go:156 → 172.20.0.5:32767) and fails without
it; these tests own their server lifecycle and run anywhere with g++.
"""
import json
import os
import re
import socket
import subprocess
import threading
import time

import pytest

from k8s_gpu_scheduler_tpu.registry import (
    AuthError,
    ChipInfo,
    Client,
    NodeInventory,
    RegistryError,
    list_inventories,
    publish_inventory,
    read_inventory,
)
from k8s_gpu_scheduler_tpu.registry.ctl import main as ctl_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
KVSTORE_DIR = os.path.join(REPO, "native", "kvstore")
BINARY = os.path.join(KVSTORE_DIR, "kvstored")


def build_binary():
    subprocess.run(["make", "-C", KVSTORE_DIR], check=True, capture_output=True)
    return BINARY


class KVServer:
    """Test harness: one kvstored process on an OS-assigned port."""

    def __init__(self, password=None, appendonly=None):
        args = [build_binary(), "--port", "0"]
        if password:
            args += ["--requirepass", password]
        if appendonly:
            args += ["--appendonly", appendonly]
        self.proc = subprocess.Popen(
            args, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True
        )
        # Diagnostics (e.g. the corrupt-AOF-tail warning) may precede the
        # ready line — skip them, bounded.
        m = None
        for _ in range(10):
            line = self.proc.stdout.readline()
            m = re.search(r"ready on port (\d+)", line)
            if m or not line:
                break
        assert m, f"kvstored never reported ready: {line!r}"
        self.port = int(m.group(1))

    def stop(self):
        self.proc.terminate()
        self.proc.wait(timeout=5)


@pytest.fixture
def server():
    s = KVServer()
    yield s
    s.stop()


@pytest.fixture
def auth_server():
    s = KVServer(password="sekrit")
    yield s
    s.stop()


class TestKVStore:
    def test_set_get_roundtrip(self, server):
        with Client(port=server.port) as c:
            assert c.ping()
            c.set("node/v5e-0", '["chip0","chip1"]')
            assert c.get("node/v5e-0") == '["chip0","chip1"]'
            assert c.get("missing") is None

    def test_get_range(self, server):
        # Parity: client.Descriptor.GetRange (client.go:36-40).
        with Client(port=server.port) as c:
            c.set("k", "hello world")
            assert c.get_range("k", 0, 4) == "hello"
            assert c.get_range("k", -5, -1) == "world"
            assert c.get_range("nope", 0, 10) == ""

    def test_keys_glob(self, server):
        with Client(port=server.port) as c:
            c.set("node/a", "1")
            c.set("node/b", "2")
            c.set("other", "3")
            assert sorted(c.get_keys("node/*")) == ["node/a", "node/b"]
            assert sorted(c.get_keys("*")) == ["node/a", "node/b", "other"]
            assert sorted(c.get_keys("node/?")) == ["node/a", "node/b"]

    def test_mget_order_and_missing_nils(self, server):
        with Client(port=server.port) as c:
            c.set("a", "1")
            c.set("b", "2")
            assert c.mget("b", "missing", "a") == ["2", None, "1"]
            assert c.mget() == []

    def test_delete_exists_dbsize_flush(self, server):
        with Client(port=server.port) as c:
            c.set("a", "1")
            c.set("b", "2")
            assert c.exists("a") and c.dbsize() == 2
            assert c.delete("a", "zzz") == 1
            assert not c.exists("a")
            c.flush()
            assert c.dbsize() == 0

    def test_binary_safe_values(self, server):
        with Client(port=server.port) as c:
            val = json.dumps({"topo": "2x4", "note": "line1\r\nline2\t\x00ish"})
            c.set("k", val)
            assert c.get("k") == val

    def test_db_isolation(self, server):
        with Client(port=server.port, db=0) as c0, Client(port=server.port, db=1) as c1:
            c0.set("k", "db0")
            assert c1.get("k") is None
            c1.set("k", "db1")
            assert c0.get("k") == "db0"

    def test_auth_required(self, auth_server):
        with Client(port=auth_server.port) as c:
            with pytest.raises(AuthError):
                c.ping()
        with pytest.raises(AuthError):
            with Client(port=auth_server.port, password="wrong") as c:
                c.ping()
        with Client(port=auth_server.port, password="sekrit") as c:
            assert c.ping()
            c.set("k", "v")
            assert c.get("k") == "v"

    def test_concurrent_clients(self, server):
        errors = []

        def worker(i):
            try:
                with Client(port=server.port) as c:
                    for j in range(50):
                        c.set(f"w{i}/k{j}", str(j))
                    assert len(c.get_keys(f"w{i}/*")) == 50
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        with Client(port=server.port) as c:
            assert c.dbsize() == 400

    def test_raw_socket_resp(self, server):
        # Prove the wire format is real RESP — drive it without our client.
        s = socket.create_connection(("127.0.0.1", server.port), timeout=5)
        s.sendall(b"*3\r\n$3\r\nSET\r\n$1\r\nk\r\n$2\r\nvv\r\n")
        assert s.recv(64) == b"+OK\r\n"
        s.sendall(b"*2\r\n$3\r\nGET\r\n$1\r\nk\r\n")
        assert s.recv(64) == b"$2\r\nvv\r\n"
        # inline command form too
        s.sendall(b"PING\r\n")
        assert s.recv(64) == b"+PONG\r\n"
        s.close()

    def test_append_only_persistence(self, tmp_path):
        aof = str(tmp_path / "registry.aof")
        srv = KVServer(appendonly=aof)
        try:
            with Client(port=srv.port) as c:
                c.set("survives", "yes")
                c.set("gone", "deleted")
                c.delete("gone")
        finally:
            srv.stop()
        srv2 = KVServer(appendonly=aof)
        try:
            with Client(port=srv2.port) as c:
                assert c.get("survives") == "yes"
                assert c.get("gone") is None
        finally:
            srv2.stop()

    def test_aof_auto_rewrite_compacts_superseded_writes(self, tmp_path):
        """Heartbeat-style rewrites of the same key grow the log past the
        1 MiB floor and double threshold (kvstore.cpp aof_record); the
        auto-rewrite must compact it to live state only, and a restart
        must replay the compacted log to the LAST value."""
        aof = str(tmp_path / "compact.aof")
        srv = KVServer(appendonly=aof)
        try:
            with Client(port=srv.port) as c:
                val = "x" * 10_000
                for i in range(130):                 # ~1.3 MB of records
                    c.set("node/hb", f"{val}{i}")
                c.set("keep", "final")
            size = os.path.getsize(aof)
            # The rewrite fires crossing the 1 MiB floor and compacts the
            # log to the ~10 KB live value; appends written AFTER it
            # remain (~250 KB here) until the next doubling. Without any
            # rewrite the log would be the full ~1.3 MB.
            assert size < 500_000, size
        finally:
            srv.stop()
        srv2 = KVServer(appendonly=aof)
        try:
            with Client(port=srv2.port) as c:
                assert c.get("node/hb") == f"{val}129"
                assert c.get("keep") == "final"
        finally:
            srv2.stop()

    def test_client_reconnects_after_server_restart(self, tmp_path):
        aof = str(tmp_path / "r.aof")
        srv = KVServer(appendonly=aof)
        c = Client(port=srv.port)
        c.set("k", "v")
        port = srv.port
        srv.stop()
        # New server on the same port (bind explicitly this time).
        proc = subprocess.Popen(
            [BINARY, "--port", str(port), "--appendonly", aof],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        try:
            assert "ready" in proc.stdout.readline()
            assert c.get("k") == "v"  # transparent reconnect
        finally:
            c.close()
            proc.terminate()
            proc.wait(timeout=5)


class TestInventorySchema:
    def test_publish_read_roundtrip(self, server):
        with Client(port=server.port) as c:
            inv = NodeInventory(
                node_name="v5e-3",
                accelerator="tpu-v5-lite-podslice",
                topology="2x4",
                chips=[ChipInfo(device_id=i, coords=[i // 4, i % 4], duty_cycle=0.5)
                       for i in range(8)],
                utilization=0.5,
                published_at=123.0,
            )
            publish_inventory(c, inv)
            got = read_inventory(c, "v5e-3")
            assert got == inv
            assert read_inventory(c, "absent") is None

    def test_list_inventories_skips_garbage(self, server):
        with Client(port=server.port) as c:
            publish_inventory(c, NodeInventory(node_name="good", topology="2x4"))
            c.set("node/bad", "{not json")
            c.set("node/good/heartbeat", "123")
            invs = list_inventories(c)
            assert list(invs) == ["good"]

    def test_list_inventories_uses_one_mget(self, server):
        """A fleet listing must cost 2 round trips (KEYS + MGET), not
        N+1 — and still work against registries without mget."""
        with Client(port=server.port) as c:
            for i in range(5):
                publish_inventory(c, NodeInventory(node_name=f"n{i}",
                                                   topology="2x4"))
            gets = {"n": 0}
            orig_get = c.get
            def counting(key):
                gets["n"] += 1
                return orig_get(key)
            c.get = counting
            invs = list_inventories(c)
            assert sorted(invs) == [f"n{i}" for i in range(5)]
            assert gets["n"] == 0                    # MGET path, no GETs

            class NoMget:                            # plain-KV fallback
                get_keys = c.get_keys
                get = staticmethod(orig_get)
            invs2 = list_inventories(NoMget())
            assert sorted(invs2) == sorted(invs)


class TestCtl:
    def test_ctl_set_get_list_flush(self, server, capsys):
        base = ["--host", "127.0.0.1", "--port", str(server.port)]
        assert ctl_main(base + ["--set", "k1", "v1"]) == 0
        assert ctl_main(base + ["--get", "k1"]) == 0
        assert "v1" in capsys.readouterr().out
        assert ctl_main(base + ["-l"]) == 0
        assert "k1\tv1" in capsys.readouterr().out
        assert ctl_main(base + ["-f"]) == 0
        capsys.readouterr()
        assert ctl_main(base + ["--get", "k1"]) == 1


class TestReviewRegressions:
    def test_corrupt_aof_does_not_crash_startup(self, tmp_path):
        aof = tmp_path / "bad.aof"
        # A good record, then a truncated/corrupt tail (crash mid-write).
        good = b"#0\r\n*3\r\n$3\r\nSET\r\n$1\r\nk\r\n$1\r\nv\r\n"
        aof.write_bytes(good + b"#99\r\n*3\r\n$3\r\nSET\r")
        srv = KVServer(appendonly=str(aof))
        try:
            with Client(port=srv.port) as c:
                assert c.get("k") == "v"  # complete prefix replayed
        finally:
            srv.stop()

    def test_large_reply_not_truncated(self, server):
        # Replies far larger than a socket buffer must arrive complete.
        with Client(port=server.port) as c:
            big = "x" * 300_000
            c.set("big", big)
            assert c.get("big") == big
            for i in range(500):
                c.set(f"many/{i:04d}", str(i))
            keys = c.get_keys("many/*")
            assert len(keys) == 500
            # connection still in sync afterwards
            assert c.ping()

    def test_non_idempotent_command_not_retried(self, tmp_path):
        srv = KVServer()
        c = Client(port=srv.port)
        c.set("k", "v")
        port = srv.port
        srv.stop()
        proc = subprocess.Popen(
            [BINARY, "--port", str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        try:
            assert "ready" in proc.stdout.readline()
            # DEL over the dead connection must surface the failure rather
            # than silently re-running against the new server.
            with pytest.raises(RegistryError):
                c.delete("k")
            # idempotent command transparently reconnects afterwards
            assert c.ping()
        finally:
            c.close()
            proc.terminate()
            proc.wait(timeout=5)


class TestAOFHygiene:
    """AOF compaction + fsync policy (VERDICT r3 weak #8: the r3 log grew
    unboundedly — one record per heartbeat forever — and every restart
    replayed all of it)."""

    def test_startup_compacts_heartbeat_history(self, tmp_path):
        """1000 overwrites of one key compact to ~one SET at restart; the
        state survives byte-for-byte."""
        aof = str(tmp_path / "registry.aof")
        srv = KVServer(appendonly=aof)
        try:
            with Client(port=srv.port) as c:
                for i in range(1000):
                    c.set("node/n1/heartbeat", str(1000000 + i))
                c.set("node/n1", "inventory-json")
        finally:
            srv.stop()
        grown = os.path.getsize(aof)
        srv2 = KVServer(appendonly=aof)
        try:
            compacted = os.path.getsize(aof)
            # 1001 records -> 2 live keys: the rewrite must shed >95%.
            assert compacted < grown / 20, (grown, compacted)
            with Client(port=srv2.port) as c:
                assert c.get("node/n1/heartbeat") == str(1000000 + 999)
                assert c.get("node/n1") == "inventory-json"
        finally:
            srv2.stop()

    def test_auto_rewrite_bounds_log_growth(self, tmp_path):
        """The live log rewrites itself once it doubles past the last
        compaction (1 MiB floor): hammering one key with large values must
        not grow the file linearly with write count."""
        aof = str(tmp_path / "registry.aof")
        srv = KVServer(appendonly=aof)
        try:
            big = "x" * 4096
            with Client(port=srv.port) as c:
                for i in range(2000):           # ~8 MiB of raw records
                    c.set("fat-key", big + str(i))
                assert c.get("fat-key") == big + "1999"
            size = os.path.getsize(aof)
            # Without auto-rewrite this is ~8 MiB; with it the log stays
            # within ~2x the single-record size plus the floor.
            assert size < 3 * (1 << 20), size
        finally:
            srv.stop()

    def test_appendfsync_flag_accepted(self, tmp_path):
        for policy in ("always", "everysec", "no"):
            aof = str(tmp_path / f"a-{policy}.aof")
            proc = subprocess.Popen(
                [build_binary(), "--port", "0", "--appendonly", aof,
                 "--appendfsync", policy],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
            try:
                line = proc.stdout.readline()
                m = re.search(r"ready on port (\d+)", line)
                assert m, (policy, line)
                with Client(port=int(m.group(1))) as c:
                    c.set("k", policy)
                    assert c.get("k") == policy
            finally:
                proc.terminate()
                proc.wait(timeout=5)
        # Garbage policy is rejected up front.
        proc = subprocess.Popen(
            [build_binary(), "--appendfsync", "sometimes"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        assert proc.wait(timeout=5) != 0
