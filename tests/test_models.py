"""Flagship model tests: forward, training convergence, and cross-layout
agreement on the virtual 8-device mesh (dp/fsdp vs sp-ring vs sp-ulysses)."""
import jax
import jax.numpy as jnp
import optax
import pytest

from k8s_gpu_scheduler_tpu.models import (
    LlamaConfig,
    forward,
    init_params,
    loss_fn,
    make_train_step,
)
from k8s_gpu_scheduler_tpu.parallel import MeshSpec, make_mesh


def toy_batch(cfg, B=4, T=32):
    tokens = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0, cfg.vocab)
    return {"tokens": tokens, "targets": jnp.roll(tokens, -1, axis=1)}


class TestLlama:
    def test_forward_shape_and_dtype(self):
        cfg = LlamaConfig.tiny()
        params = init_params(cfg, jax.random.PRNGKey(0))
        logits = forward(params, toy_batch(cfg)["tokens"], cfg)
        assert logits.shape == (4, 32, cfg.vocab)
        assert logits.dtype == jnp.float32

    def test_loss_decreases_single_device(self):
        cfg = LlamaConfig.tiny()
        params = init_params(cfg, jax.random.PRNGKey(0))
        batch = toy_batch(cfg)
        opt = optax.adamw(3e-3)
        state = opt.init(params)
        step = make_train_step(cfg, None, opt)
        first = None
        for _ in range(8):
            params, state, loss = step(params, state, batch)
            first = first if first is not None else float(loss)
        assert float(loss) < first - 0.5, (first, float(loss))

    @pytest.mark.parametrize(
        "impl,spec",
        [
            ("dense", MeshSpec.for_devices(8, fsdp=2, tp=2)),
            ("ring", MeshSpec.for_devices(8, sp=2, tp=2)),
            ("ulysses", MeshSpec.for_devices(8, sp=4)),
        ],
    )
    def test_sharded_loss_matches_unsharded(self, impl, spec):
        """One sharded train step must produce the same loss as the
        single-device step — GSPMD layouts change math order, not math."""
        cfg = LlamaConfig.tiny(attn_impl=impl)
        params = init_params(cfg, jax.random.PRNGKey(0))
        batch = toy_batch(cfg)
        ref_loss = float(loss_fn(params, batch, LlamaConfig.tiny(), None))
        mesh = make_mesh(spec)
        opt = optax.adamw(1e-3)
        state = opt.init(params)
        step = make_train_step(cfg, mesh, opt)
        _, _, loss = step(params, state, batch)
        # rel covers GSPMD reduction-order noise, which scales with the
        # loss magnitude (observed ~2.3e-3 drift at loss ~5.5 under the
        # dp2/fsdp2/tp2 layout — just past a bare abs=2e-3).
        assert float(loss) == pytest.approx(ref_loss, rel=1e-3, abs=2e-3)

    def test_flops_per_token_order_of_magnitude(self):
        # Llama-3-8B ≈ 8e9 params → ~4.8e10 train FLOPs/token.
        f = LlamaConfig.llama3_8b().flops_per_token()
        assert 3e10 < f < 7e10


class TestBert:
    def test_classify_shape_and_bidirectional(self):
        from k8s_gpu_scheduler_tpu.models.bert import (
            BertConfig, classify, encode, init_params,
        )

        cfg = BertConfig.tiny()
        params = init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab)
        logits = classify(params, tokens, cfg)
        assert logits.shape == (2, cfg.n_classes)
        # Bidirectionality: changing the LAST token must change the FIRST
        # position's hidden state (causal attention would not).
        h1 = encode(params, tokens, cfg)
        tokens2 = tokens.at[:, -1].set((tokens[:, -1] + 1) % cfg.vocab)
        h2 = encode(params, tokens2, cfg)
        assert float(jnp.abs(h1[:, 0] - h2[:, 0]).max()) > 0


class TestResNet:
    def test_forward_shape(self):
        from k8s_gpu_scheduler_tpu.models.resnet import (
            ResNetConfig, forward, init_params,
        )

        cfg = ResNetConfig.tiny()
        params = init_params(cfg, jax.random.PRNGKey(0))
        images = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
        logits = forward(params, images, cfg)
        assert logits.shape == (2, cfg.n_classes)

    def test_train_step_decreases_loss(self):
        import optax

        from k8s_gpu_scheduler_tpu.models.resnet import (
            ResNetConfig, init_params, make_train_step,
        )

        cfg = ResNetConfig.tiny()
        params = init_params(cfg, jax.random.PRNGKey(0))
        batch = {
            "images": jax.random.normal(jax.random.PRNGKey(1), (8, 32, 32, 3)),
            "labels": jax.random.randint(jax.random.PRNGKey(2), (8,), 0,
                                         cfg.n_classes),
        }
        opt = optax.sgd(0.05, momentum=0.9)
        state = opt.init(params)
        step = make_train_step(cfg, opt)
        first = None
        for _ in range(6):
            params, state, loss = step(params, state, batch)
            first = first if first is not None else float(loss)
        assert float(loss) < first


class TestServing:
    """KV-cache decode (models/serving.py) vs the training forward — the
    cached path must reproduce full-context greedy decoding exactly."""

    @staticmethod
    def f32_cfg():
        return LlamaConfig(
            vocab=128, d_model=32, n_layers=2, n_heads=4, n_kv_heads=2,
            d_ff=64, max_seq=64, dtype=jnp.float32, remat=False,
        )

    def test_prefill_logits_match_forward(self):
        from k8s_gpu_scheduler_tpu.models import forward_with_cache, init_cache

        cfg = self.f32_cfg()
        params = init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab)
        ref = forward(params, tokens, cfg)
        cache = init_cache(cfg, 2, 32)
        logits, cache = forward_with_cache(params, tokens, cfg, cache)
        assert int(cache["len"]) == 16
        assert float(jnp.abs(logits - ref).max()) < 1e-4

    def test_incremental_decode_matches_full_context(self):
        """Decode one token at a time through the cache; at every step the
        last-position logits must match a from-scratch forward over the
        whole sequence so far."""
        from k8s_gpu_scheduler_tpu.models import forward_with_cache, init_cache

        cfg = self.f32_cfg()
        params = init_params(cfg, jax.random.PRNGKey(0))
        seq = jax.random.randint(jax.random.PRNGKey(1), (1, 12), 0, cfg.vocab)
        cache = init_cache(cfg, 1, 16)
        logits, cache = forward_with_cache(params, seq[:, :4], cfg, cache)
        assert float(jnp.abs(logits[:, -1] - forward(params, seq[:, :4], cfg)[:, -1]).max()) < 1e-4
        for i in range(4, 12):
            logits, cache = forward_with_cache(params, seq[:, i:i + 1], cfg, cache)
            ref = forward(params, seq[:, :i + 1], cfg)
            assert float(jnp.abs(logits[:, -1] - ref[:, -1]).max()) < 1e-4, i

    def test_generate_matches_naive_greedy(self):
        from k8s_gpu_scheduler_tpu.models import generate

        cfg = self.f32_cfg()
        params = init_params(cfg, jax.random.PRNGKey(0))
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab)
        out = generate(params, prompt, cfg, max_new=6, max_len=32)
        assert out.shape == (2, 6)
        # Naive reference: grow the sequence, full forward each step.
        seq = prompt
        for i in range(6):
            nxt = jnp.argmax(forward(params, seq, cfg)[:, -1], axis=-1)
            assert jnp.array_equal(out[:, i], nxt.astype(out.dtype)), i
            seq = jnp.concatenate([seq, nxt[:, None].astype(seq.dtype)], axis=1)

    def test_generate_sharded_cache(self):
        """Multi-chip serving: generate under a dp×tp mesh with the cache
        sharded (batch over dp·fsdp, kv heads over tp) matches unsharded."""
        from k8s_gpu_scheduler_tpu.models import generate, make_server_step

        cfg = self.f32_cfg()
        params = init_params(cfg, jax.random.PRNGKey(0))
        prompt = jax.random.randint(jax.random.PRNGKey(1), (4, 8), 0, cfg.vocab)
        ref = generate(params, prompt, cfg, max_new=5, max_len=32)
        mesh = make_mesh(MeshSpec.for_devices(8, fsdp=2, tp=2))
        handler = make_server_step(cfg, mesh, max_new=5, max_len=32)
        out = handler(params, prompt)
        assert jnp.array_equal(out, ref)


class TestBatcherFuzz:
    """Seeded randomized schedules for the continuous batcher: arbitrary
    interleavings of prompt lengths (across bucket rungs), budgets, and
    engine geometries must reproduce static generate exactly. The shared-
    cursor row-space logic (backward prompt windows, mid-step slot reuse,
    epoch rolls, ladder rungs) is where an off-by-one would corrupt
    streams only under specific interleavings a hand-written case misses."""

    cfg = TestServing.f32_cfg()

    # One seed in tier-1 keeps the fuzz signal inside the wall-clock
    # budget (PR 15 trimmed the second — the seeds are interchangeable
    # probes of one property); the full six-seed sweep runs in the
    # unfiltered CI suite.
    @pytest.mark.parametrize("seed", [
        0,
        *(pytest.param(s, marks=pytest.mark.slow) for s in range(1, 6)),
    ])
    def test_random_schedule_matches_static_generate(self, seed):
        import numpy as np

        from k8s_gpu_scheduler_tpu.models import generate
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        rng = np.random.default_rng(seed)
        params = init_params(self.cfg, jax.random.PRNGKey(0))
        n_slots = int(rng.integers(1, 4))
        chunk = int(rng.integers(1, 5))
        max_len = int(rng.choice([24, 32, 48]))
        bucket = int(rng.choice([2, 4, 8]))
        eng = ContinuousBatcher(params, self.cfg, n_slots=n_slots,
                                max_len=max_len, chunk=chunk,
                                prefill_bucket=bucket)
        reqs = []
        for _ in range(int(rng.integers(3, 9))):
            if rng.random() < 0.3:
                # Long prompt: reaches the TOP ladder rung (tb clamped to
                # S), whose prefill window only fits at an epoch start —
                # the admission-blocking path.
                plen = int(rng.integers(max_len // 2, max_len))
                budget = int(rng.integers(1, max(2, (max_len - plen) // 2)))
            else:
                plen = int(rng.integers(1, max_len // 2))
                budget = int(rng.integers(1, max(2, (max_len - plen) // 2)))
            prompt = rng.integers(0, self.cfg.vocab, plen)
            try:
                rid = eng.submit(prompt, max_new=budget)
            except ValueError:
                continue                             # over capacity — fine
            reqs.append((rid, prompt, budget))
        assert reqs, "schedule degenerated; adjust generator bounds"
        done = eng.run()
        assert eng.pending == 0
        for rid, prompt, budget in reqs:
            ref = generate(params, jnp.asarray(prompt)[None, :], self.cfg,
                           max_new=budget, max_len=max_len)
            assert done[rid] == [int(t) for t in ref[0]], (
                seed, rid, len(prompt), budget, done[rid])


class TestSpeculativeDecode:
    """Prompt-lookup speculative decoding (serving.generate_speculative):
    greedy-exact output, variable per-pass acceptance, degenerate-input
    safety."""

    cfg = TestServing.f32_cfg()

    def _params(self):
        return init_params(self.cfg, jax.random.PRNGKey(0))

    def test_matches_generate_on_repetitive_prompt(self):
        """A self-repeating prompt is the win case — bigram lookups hit,
        multi-token passes accept — and the output must still equal plain
        greedy decoding."""
        from k8s_gpu_scheduler_tpu.models import generate, generate_speculative

        params = self._params()
        phrase = jax.random.randint(jax.random.PRNGKey(1), (6,), 0,
                                    self.cfg.vocab)
        prompt = jnp.tile(phrase, 3)[None, :]        # 18 tokens, repeating
        ref = generate(params, prompt, self.cfg, max_new=8, max_len=40)
        got = generate_speculative(params, prompt, self.cfg, max_new=8,
                                   gamma=4, max_len=40)
        assert jnp.array_equal(got, ref), (got, ref)

    def test_matches_generate_on_random_prompt(self):
        """No bigram repeats → every proposal is garbage → one token per
        pass; output must still be exact."""
        from k8s_gpu_scheduler_tpu.models import generate, generate_speculative

        params = self._params()
        prompt = jnp.arange(10)[None, :] * 7 % self.cfg.vocab
        ref = generate(params, prompt, self.cfg, max_new=6, max_len=40)
        got = generate_speculative(params, prompt, self.cfg, max_new=6,
                                   gamma=3, max_len=40)
        assert jnp.array_equal(got, ref), (got, ref)

    def test_rejects_batch_and_capacity_overflow(self):
        from k8s_gpu_scheduler_tpu.models import generate_speculative

        params = self._params()
        two = jnp.zeros((2, 4), jnp.int32)
        with pytest.raises(ValueError):
            generate_speculative(params, two, self.cfg, max_new=4)
        one = jnp.zeros((1, 4), jnp.int32)
        with pytest.raises(ValueError):
            generate_speculative(params, one, self.cfg, max_new=60,
                                 gamma=4, max_len=64)


class TestQuantizedServing:
    """Weight-only int8 (ops/quant.py): per-channel round-trip error
    bound, exact equivalence of the qdot path with dequantized weights
    through the float path, and the batcher running quantized end to end."""

    cfg = TestServing.f32_cfg()

    def test_roundtrip_error_bounded_per_channel(self):
        from k8s_gpu_scheduler_tpu.ops import dequantize_weight, quantize_weight

        w = jax.random.normal(jax.random.PRNGKey(0), (3, 16, 8)) * 0.3
        wq = quantize_weight(w)
        assert wq["q"].dtype == jnp.int8 and wq["s"].shape == (3, 1, 8)
        back = dequantize_weight(wq, jnp.float32)
        # Symmetric int8: per-element error <= half a step = s/2 per channel.
        err = jnp.abs(back - w)
        assert bool(jnp.all(err <= wq["s"] * 0.5 + 1e-7)), float(err.max())

    def test_qdot_path_equals_dequantized_float_path(self):
        """(x @ q) * s must equal x @ (q * s) through the whole serving
        forward — same math by linearity, so the two paths only differ by
        float associativity. Catches wrong scale axes or missed sites."""
        from k8s_gpu_scheduler_tpu.models import forward_with_cache, init_cache
        from k8s_gpu_scheduler_tpu.ops import dequantize_weight, quantize_llama_params

        params = init_params(self.cfg, jax.random.PRNGKey(0))
        qparams = quantize_llama_params(params)
        deq = {
            **qparams,
            "blocks": {
                k: (dequantize_weight(v, jnp.float32)
                    if isinstance(v, dict) else v)
                for k, v in qparams["blocks"].items()
            },
            "lm_head": dequantize_weight(qparams["lm_head"], jnp.float32),
        }
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0,
                                    self.cfg.vocab)
        ql, _ = forward_with_cache(qparams, tokens, self.cfg,
                                   init_cache(self.cfg, 2, 32))
        dl, _ = forward_with_cache(deq, tokens, self.cfg,
                                   init_cache(self.cfg, 2, 32))
        assert jnp.allclose(ql, dl, atol=1e-4), float(jnp.abs(ql - dl).max())

    def test_batcher_runs_quantized_and_tracks_float_stream(self):
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher
        from k8s_gpu_scheduler_tpu.ops import quantize_llama_params

        params = init_params(self.cfg, jax.random.PRNGKey(0))
        qparams = quantize_llama_params(params)
        prompt = jax.random.randint(jax.random.PRNGKey(2), (6,), 0,
                                    self.cfg.vocab)

        def run(p):
            eng = ContinuousBatcher(p, self.cfg, n_slots=2, max_len=32,
                                    chunk=2, prefill_bucket=8)
            rid = eng.submit(prompt, max_new=6)
            return eng.run()[rid]

        fp, q8 = run(params), run(qparams)
        assert len(q8) == 6 and all(0 <= t < self.cfg.vocab for t in q8)
        # int8 streams may diverge at near-ties; they should still agree
        # on a majority of early tokens for a 0.02-std random model.
        agree = sum(a == b for a, b in zip(fp, q8))
        assert agree >= 3, (fp, q8)

    def test_moe_quantized_matches_dequantized_float_path(self):
        """Expert weights ([L, E, D, F]) quantize per-(layer, expert,
        channel) and flow through qeinsum in the dropless serving path;
        the router stays f32. Same linearity check as the dense case."""
        from k8s_gpu_scheduler_tpu.models import forward_with_cache, init_cache
        from k8s_gpu_scheduler_tpu.ops import dequantize_weight, quantize_llama_params

        moe_cfg = LlamaConfig(vocab=64, d_model=32, n_layers=2, n_heads=4,
                              n_kv_heads=4, d_ff=64, max_seq=32,
                              dtype=jnp.float32, n_experts=4)
        params = init_params(moe_cfg, jax.random.PRNGKey(0))
        qparams = quantize_llama_params(params)
        assert qparams["blocks"]["w_gate"]["s"].shape == (2, 4, 1, 64)
        assert not isinstance(qparams["blocks"]["router"], dict)
        deq = {
            **qparams,
            "blocks": {
                k: (dequantize_weight(v, jnp.float32)
                    if isinstance(v, dict) else v)
                for k, v in qparams["blocks"].items()
            },
            "lm_head": dequantize_weight(qparams["lm_head"], jnp.float32),
        }
        tokens = jax.random.randint(jax.random.PRNGKey(3), (2, 8), 0,
                                    moe_cfg.vocab)
        ql, _ = forward_with_cache(qparams, tokens, moe_cfg,
                                   init_cache(moe_cfg, 2, 32))
        dl, _ = forward_with_cache(deq, tokens, moe_cfg,
                                   init_cache(moe_cfg, 2, 32))
        assert jnp.allclose(ql, dl, atol=1e-4), float(jnp.abs(ql - dl).max())


class TestContinuousBatching:
    """ContinuousBatcher (models/serving.py): per-slot positions, slot
    reuse mid-stream, greedy-token parity with the static generate path."""

    cfg = TestServing.f32_cfg()

    def _params(self):
        return init_params(self.cfg, jax.random.PRNGKey(0))

    def test_tokens_match_static_generate(self):
        from k8s_gpu_scheduler_tpu.models import generate
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        params = self._params()
        prompts = jax.random.randint(jax.random.PRNGKey(1), (3, 8), 0,
                                     self.cfg.vocab)
        ref = generate(params, prompts, self.cfg, max_new=6, max_len=32)
        eng = ContinuousBatcher(params, self.cfg, n_slots=3, max_len=32,
                                chunk=2, prefill_bucket=8)
        ids = [eng.submit(prompts[i], max_new=6) for i in range(3)]
        done = eng.run()
        for i, rid in enumerate(ids):
            assert done[rid] == [int(t) for t in ref[i]], (i, done[rid])

    def test_varied_prompt_lengths_right_padded(self):
        """Right-padded prompts with different real lengths decode exactly
        like per-request static generate — the padded cache rows must never
        be attended."""
        from k8s_gpu_scheduler_tpu.models import generate
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        params = self._params()
        lens = [3, 8, 5]
        key = jax.random.PRNGKey(2)
        prompts = [jax.random.randint(jax.random.fold_in(key, i), (n,), 0,
                                      self.cfg.vocab)
                   for i, n in enumerate(lens)]
        eng = ContinuousBatcher(params, self.cfg, n_slots=2, max_len=32,
                                chunk=3, prefill_bucket=8)
        ids = [eng.submit(p, max_new=5) for p in prompts]
        done = eng.run()
        for p, rid in zip(prompts, ids):
            ref = generate(params, p[None, :], self.cfg, max_new=5, max_len=32)
            assert done[rid] == [int(t) for t in ref[0]], rid

    def test_same_step_slot_reuse_in_one_batched_prefill(self):
        """A max_new==1 request frees its slot DURING admission, so a later
        request reuses it within the same step — both ride the one batched
        prefill dispatch. The pad rows duplicate the LAST admission
        (serving.py step): padding with an earlier one would re-apply the
        freed slot's superseded writes after the reuser's and corrupt its
        cache window."""
        from k8s_gpu_scheduler_tpu.models import generate
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        params = self._params()
        key = jax.random.PRNGKey(7)
        prompts = [jax.random.randint(jax.random.fold_in(key, i), (4,), 0,
                                      self.cfg.vocab) for i in range(2)]
        eng = ContinuousBatcher(params, self.cfg, n_slots=3, max_len=32,
                                chunk=2, prefill_bucket=4)
        one_id = eng.submit(prompts[0], max_new=1)     # slot freed mid-step
        long_id = eng.submit(prompts[1], max_new=4)    # may reuse that slot
        done = eng.run()
        for p, rid, budget in [(prompts[0], one_id, 1),
                               (prompts[1], long_id, 4)]:
            ref = generate(params, p[None, :], self.cfg, max_new=budget,
                           max_len=32)
            assert done[rid] == [int(t) for t in ref[0]], rid

    def test_short_request_burst_admits_at_most_n_slots_per_step(self):
        """max_new==1 admissions free their slot immediately; without the
        per-step cap a burst would grow the prefill batch M past n_slots
        and recompile the prefill program per distinct burst size."""
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        params = self._params()
        key = jax.random.PRNGKey(11)
        prompts = [jax.random.randint(jax.random.fold_in(key, i), (4,), 0,
                                      self.cfg.vocab) for i in range(5)]
        eng = ContinuousBatcher(params, self.cfg, n_slots=2, max_len=32,
                                chunk=2, prefill_bucket=4)
        seen_m = set()
        orig = eng._prefill
        def spy(p, k, v, ks, vs, bm, rp, last, slots, curs, tokens,
                real_lens, seed):
            seen_m.add(tokens.shape[0])
            return orig(p, k, v, ks, vs, bm, rp, last, slots, curs, tokens,
                        real_lens, seed)
        eng._prefill = spy
        ids = [eng.submit(p, max_new=1) for p in prompts]
        done = eng.run()
        assert set(done) == set(ids)
        assert all(len(done[r]) == 1 for r in ids)
        assert seen_m == {eng.n_slots}, seen_m    # one compiled shape only

    def test_int8_kv_cache_matches_model_dtype_cache(self):
        """kv_dtype="int8" stores K/V quantized (per-token-per-head scales,
        serving.py _kv_quant) — greedy tokens must match the full-precision
        cache on a short decode (the quant error ~0.4% per row is far below
        typical argmax margins at this scale), across admission, slot
        reuse, and the epoch roll."""
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        params = self._params()
        key = jax.random.PRNGKey(17)
        prompts = [jax.random.randint(jax.random.fold_in(key, i), (n,), 0,
                                      self.cfg.vocab)
                   for i, n in enumerate((4, 7, 5, 6))]
        outs = {}
        for kvd in (None, "int8"):
            eng = ContinuousBatcher(params, self.cfg, n_slots=2,
                                    max_len=32, chunk=3, prefill_bucket=8,
                                    kv_dtype=kvd)
            ids = [eng.submit(p, max_new=6) for p in prompts]
            done = eng.run()
            outs[kvd] = [done[r] for r in ids]
        assert outs["int8"] == outs[None]

    def test_int8_kv_cache_halves_cache_bytes(self):
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        params = self._params()
        bf = ContinuousBatcher(params, self.cfg, n_slots=2, max_len=32,
                               chunk=2, prefill_bucket=8)
        q8 = ContinuousBatcher(params, self.cfg, n_slots=2, max_len=32,
                               chunk=2, prefill_bucket=8, kv_dtype="int8")
        bytes_bf = bf._k.nbytes + bf._v.nbytes
        bytes_q8 = (q8._k.nbytes + q8._v.nbytes
                    + q8._ks.nbytes + q8._vs.nbytes)
        # int8 payload is dtype_bytes x smaller; the f32 scale plane adds
        # 4/head_dim per element.
        assert bytes_q8 < bytes_bf, (bytes_q8, bytes_bf)

    def test_request_metrics_ttft_and_latency(self):
        """pop_request_metrics: every finished request carries monotone
        0 <= ttft <= latency and its decoded-token count; the records drain
        on read."""
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        params = self._params()
        key = jax.random.PRNGKey(19)
        eng = ContinuousBatcher(params, self.cfg, n_slots=2, max_len=32,
                                chunk=2, prefill_bucket=8)
        ids = [eng.submit(
            jax.random.randint(jax.random.fold_in(key, i), (4,), 0,
                               self.cfg.vocab), max_new=4) for i in range(3)]
        done = {}
        while eng.pending:
            done.update(eng.step())
        m = eng.pop_request_metrics()
        assert set(m) == set(ids)
        for rid in ids:
            assert m[rid]["tokens"] == 4
            assert 0 <= m[rid]["ttft_s"] <= m[rid]["latency_s"]
        assert eng.pop_request_metrics() == {}

    def test_blocked_long_head_is_not_starved_by_short_requests(self):
        """Strict FCFS at a blocked head (serving.py _step_lazy): a
        long-prompt request that can't fit mid-epoch must NOT be bypassed
        by later short requests — skip-ahead admission keeps consuming
        cursor rows, the epoch never rolls, and the head starves (r4
        advisor finding). With admission frozen the occupied slots drain,
        the epoch rolls, and the head decodes exactly like static
        generate."""
        from k8s_gpu_scheduler_tpu.models import generate
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        params = self._params()
        key = jax.random.PRNGKey(13)
        eng = ContinuousBatcher(params, self.cfg, n_slots=2, max_len=32,
                                chunk=2, prefill_bucket=4)
        # Two residents push the cursor deep into the epoch...
        filler = [jax.random.randint(jax.random.fold_in(key, i), (4,), 0,
                                     self.cfg.vocab) for i in range(2)]
        filler_ids = [eng.submit(p, max_new=16) for p in filler]
        # ...so this head (prompt 12 + rows for 10 tokens) blocks, while a
        # stream of tiny requests queues behind it.
        long_prompt = jax.random.randint(jax.random.fold_in(key, 9), (12,), 0,
                                         self.cfg.vocab)
        long_id = eng.submit(long_prompt, max_new=10)
        short_ids = [eng.submit(
            jax.random.randint(jax.random.fold_in(key, 20 + i), (4,), 0,
                               self.cfg.vocab), max_new=2) for i in range(6)]
        done = {}
        for _ in range(80):
            done.update(eng.step())
            if not eng.pending:
                break
        assert not eng.pending, "head starved: queue never drained"
        assert set(done) == set(filler_ids) | {long_id} | set(short_ids)
        ref = generate(params, long_prompt[None, :], self.cfg, max_new=10,
                       max_len=32)
        assert done[long_id] == [int(t) for t in ref[0]]

    def test_long_prompts_take_the_next_bucket_rung(self):
        """Prompts longer than prefill_bucket pad to the next power-of-two
        rung (one compiled prefill per rung) instead of being rejected;
        mixed rungs admitted in one step dispatch as ordered runs and every
        stream still matches static generate."""
        from k8s_gpu_scheduler_tpu.models import generate
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        params = self._params()
        key = jax.random.PRNGKey(13)
        lens = [3, 11, 6, 17]                        # rungs 4, 16, 8, 32
        prompts = [jax.random.randint(jax.random.fold_in(key, i), (n,), 0,
                                      self.cfg.vocab)
                   for i, n in enumerate(lens)]
        eng = ContinuousBatcher(params, self.cfg, n_slots=4, max_len=64,
                                chunk=2, prefill_bucket=4)
        ids = [eng.submit(p, max_new=4) for p in prompts]
        done = eng.run()
        for p, rid in zip(prompts, ids):
            ref = generate(params, p[None, :], self.cfg, max_new=4,
                           max_len=64)
            assert done[rid] == [int(t) for t in ref[0]], rid
        with pytest.raises(ValueError):
            eng.submit(jax.numpy.zeros(70, jax.numpy.int32), max_new=2)

    def test_sharded_batcher_matches_single_device_stream(self):
        """ContinuousBatcher under a dp×fsdp×tp mesh (cache batch sharded
        over (dp, fsdp), kv heads over tp — CACHE_SPEC) must emit the same
        greedy streams as the mesh-less engine. n_slots divides dp·fsdp so
        the cache's slot axis shards evenly."""
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher
        from k8s_gpu_scheduler_tpu.parallel import MeshSpec, make_mesh

        params = self._params()
        key = jax.random.PRNGKey(9)
        prompts = [jax.random.randint(jax.random.fold_in(key, i), (5,), 0,
                                      self.cfg.vocab) for i in range(6)]

        def run(mesh):
            eng = ContinuousBatcher(params, self.cfg, n_slots=4, max_len=32,
                                    chunk=2, prefill_bucket=8, mesh=mesh)
            ids = [eng.submit(p, max_new=4) for p in prompts]
            done = eng.run()
            return [done[r] for r in ids]

        plain = run(None)
        sharded = run(make_mesh(MeshSpec.for_devices(8, fsdp=2, tp=2)))
        assert sharded == plain, (sharded, plain)

    def test_eos_stops_early_and_frees_the_slot(self):
        """eos_id finishes a request at its first eos (inclusive) before
        the budget runs out, and the freed slot admits queued work. The
        eos token is taken from a greedy run so the model genuinely emits
        it mid-stream."""
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        params = self._params()
        prompt = jax.random.randint(jax.random.PRNGKey(5), (4,), 0,
                                    self.cfg.vocab)
        ref_eng = ContinuousBatcher(params, self.cfg, n_slots=1, max_len=32,
                                    chunk=2, prefill_bucket=4)
        rid = ref_eng.submit(prompt, max_new=8)
        ref = ref_eng.run()[rid]
        eos = ref[2]                                  # emitted by step 3
        want = ref[: ref.index(eos) + 1]              # ...at its FIRST occurrence
        assert len(want) < len(ref)                   # genuinely early

        eng = ContinuousBatcher(params, self.cfg, n_slots=1, max_len=32,
                                chunk=2, prefill_bucket=4, eos_id=eos)
        a = eng.submit(prompt, max_new=8)
        b = eng.submit(prompt, max_new=8)             # queued behind a
        done = eng.run()
        assert done[a] == want, (done[a], want)       # truncated incl. eos
        assert done[b] == want                        # same prompt, greedy
        assert eng.pending == 0

    def test_sampling_topk1_matches_greedy_and_is_reproducible(self):
        """temperature>0 with top_k=1 must reproduce greedy argmax (the
        categorical collapses to the single surviving logit), and a fresh
        engine with the same seed path must replay the identical stream;
        unconstrained high-temperature sampling must diverge from greedy
        somewhere."""
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        params = self._params()
        prompt = jax.random.randint(jax.random.PRNGKey(6), (4,), 0,
                                    self.cfg.vocab)

        def run_engine(**kw):
            eng = ContinuousBatcher(params, self.cfg, n_slots=2, max_len=32,
                                    chunk=2, prefill_bucket=4, **kw)
            rid = eng.submit(prompt, max_new=8)
            return eng.run()[rid]

        greedy = run_engine()
        topk1 = run_engine(temperature=1.0, top_k=1)
        assert topk1 == greedy, (topk1, greedy)
        hot_a = run_engine(temperature=5.0)
        hot_b = run_engine(temperature=5.0)
        assert hot_a == hot_b                          # deterministic seed path
        assert hot_a != greedy                         # actually sampling

    def test_midstream_admission_reuses_freed_slot(self):
        """More requests than slots with unequal budgets: a short request
        finishes, its slot admits a queued request while the long request
        is still decoding — the continuous part of continuous batching."""
        from k8s_gpu_scheduler_tpu.models import generate
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        params = self._params()
        key = jax.random.PRNGKey(3)
        prompts = [jax.random.randint(jax.random.fold_in(key, i), (4,), 0,
                                      self.cfg.vocab) for i in range(3)]
        eng = ContinuousBatcher(params, self.cfg, n_slots=2, max_len=32,
                                chunk=2, prefill_bucket=4)
        long_id = eng.submit(prompts[0], max_new=10)
        short_id = eng.submit(prompts[1], max_new=2)
        queued_id = eng.submit(prompts[2], max_new=2)   # waits for a slot
        finished = eng.step()                          # chunk=2: short done
        assert short_id in finished and long_id not in finished
        assert eng.pending == 2                        # queued admitted next
        done = eng.run()
        done.update(finished)
        for p, rid, budget in [(prompts[0], long_id, 10),
                               (prompts[1], short_id, 2),
                               (prompts[2], queued_id, 2)]:
            ref = generate(params, p[None, :], self.cfg, max_new=budget,
                           max_len=32)
            assert done[rid] == [int(t) for t in ref[0]], rid


class TestMoE:
    """Mixture-of-Experts FFN + expert parallelism (ops/moe.py, the ep mesh
    axis) — the one parallelism-checklist entry (EP) absent through r3."""

    def _cfg(self, experts=4, top_k=2, cf=2.0):
        return LlamaConfig(
            vocab=128, d_model=32, n_layers=2, n_heads=4, n_kv_heads=4,
            d_ff=64, max_seq=64, dtype=jnp.float32, remat=False,
            n_experts=experts, moe_top_k=top_k, moe_capacity_factor=cf,
        )

    def test_identical_experts_match_dense(self):
        """With every expert's weights EQUAL and ample capacity, routing is
        irrelevant: MoE output must equal the dense SwiGLU (gates sum to 1
        after renormalization)."""
        from k8s_gpu_scheduler_tpu.ops.layers import swiglu
        from k8s_gpu_scheduler_tpu.ops.moe import moe_ffn

        key = jax.random.PRNGKey(0)
        D, F, E = 32, 64, 4
        x = jax.random.normal(key, (2, 8, D), jnp.float32)
        wg = jax.random.normal(jax.random.fold_in(key, 1), (D, F)) * 0.1
        wu = jax.random.normal(jax.random.fold_in(key, 2), (D, F)) * 0.1
        wd = jax.random.normal(jax.random.fold_in(key, 3), (F, D)) * 0.1
        router = jax.random.normal(jax.random.fold_in(key, 4), (D, E)) * 0.1
        stack = lambda w: jnp.broadcast_to(w, (E,) + w.shape)
        out, aux = moe_ffn(x, router, stack(wg), stack(wu), stack(wd),
                           top_k=2, capacity_factor=8.0)
        assert float(aux) > 0.0
        ref = swiglu(x, wg, wu, wd)
        assert float(jnp.abs(out - ref).max()) < 1e-4

    def test_capacity_drop_passes_residual(self):
        """Capacity 1 with all tokens routed to one expert: only the first
        token per batch row gets computed; the rest emit zeros (the model's
        residual add then passes them through)."""
        from k8s_gpu_scheduler_tpu.ops.moe import moe_ffn

        D, F, E = 8, 16, 2
        x = jnp.ones((1, 4, D), jnp.float32)
        # Router forces expert 0 for every token.
        router = jnp.zeros((D, E)).at[:, 0].set(10.0)
        wg = jnp.ones((E, D, F)) * 0.1
        wu = jnp.ones((E, D, F)) * 0.1
        wd = jnp.ones((E, F, D)) * 0.1
        out, _ = moe_ffn(x, router, wg, wu, wd, top_k=1, capacity_factor=0.25)
        assert float(jnp.abs(out[0, 0]).max()) > 0           # served
        assert float(jnp.abs(out[0, 1:]).max()) == 0.0       # dropped

    def test_moe_train_step_decreases_loss(self):
        import optax

        from k8s_gpu_scheduler_tpu.models import (
            init_params, make_train_step,
        )

        cfg = self._cfg()
        params = init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, cfg.vocab)
        batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}
        opt = optax.adamw(1e-2)
        state = opt.init(params)
        step = make_train_step(cfg, None, opt)
        params, state, first = step(params, state, batch)
        for _ in range(5):
            params, state, loss = step(params, state, batch)
        assert float(loss) < float(first)

    def test_ep_sharded_loss_matches_unsharded(self):
        """Full train-step parity on an 8-device mesh with a real ep axis
        ({fsdp:2, ep:2, tp:2}): GSPMD's all_to_all dispatch must be
        numerically identical to the single-device path."""
        from k8s_gpu_scheduler_tpu.models import init_params, loss_fn
        from k8s_gpu_scheduler_tpu.parallel import MeshSpec, make_mesh

        cfg = self._cfg()
        params = init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab)
        batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}
        ref = float(loss_fn(params, batch, cfg, None))
        mesh = make_mesh(MeshSpec.for_devices(8, fsdp=2, ep=2, tp=2))
        got = float(loss_fn(params, batch, cfg, mesh))
        assert abs(got - ref) < 1e-4, (got, ref)

    def test_balance_loss_uniform_is_one(self):
        from k8s_gpu_scheduler_tpu.ops.moe import load_balancing_loss

        D, E = 16, 4
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 64, D))
        router = jnp.zeros((D, E))  # uniform probs
        val = float(load_balancing_loss(x, router, top_k=1))
        # Uniform probs: mean_prob = 1/E; top-1 ties broken deterministically
        # but frac sums to 1 → loss = E * (1/E) = 1.
        assert val == pytest.approx(1.0, abs=1e-5)


class TestPipelineParallel:
    """GPipe-style pipeline parallelism (models/pipeline.py): the pp mesh
    axis, activation ppermute ring, microbatch schedule, autodiff through
    the pipeline."""

    @staticmethod
    def _cfg():
        return LlamaConfig(
            vocab=128, d_model=32, n_layers=4, n_heads=4, n_kv_heads=4,
            d_ff=64, max_seq=64, dtype=jnp.float32, remat=False,
        )

    def test_pp_loss_matches_single_device(self):
        from jax.sharding import Mesh

        from k8s_gpu_scheduler_tpu.models.pipeline import pp_loss_fn

        cfg = self._cfg()
        params = init_params(cfg, jax.random.PRNGKey(0))
        batch = toy_batch(cfg, B=8, T=16)
        ref = float(loss_fn(params, batch, cfg, None))
        mesh = Mesh(jax.devices()[:4], ("pp",))
        for M in (2, 4, 8):
            got = float(pp_loss_fn(params, batch, cfg, mesh, microbatches=M))
            assert got == pytest.approx(ref, abs=2e-4), (M, got, ref)

    def test_pp_train_step_decreases_loss_and_matches_dense_step(self):
        from jax.sharding import Mesh

        from k8s_gpu_scheduler_tpu.models.pipeline import make_pp_train_step

        cfg = self._cfg()
        params = init_params(cfg, jax.random.PRNGKey(0))
        batch = toy_batch(cfg, B=8, T=16)
        mesh = Mesh(jax.devices()[:4], ("pp",))
        opt = optax.adamw(1e-2)

        # Reference: one single-device step on an identical copy.
        ref_params = jax.tree.map(jnp.copy, params)
        ref_state = opt.init(ref_params)
        ref_step = make_train_step(cfg, None, opt)
        _, _, ref_loss = ref_step(ref_params, ref_state, batch)

        step = make_pp_train_step(cfg, mesh, opt, microbatches=4)
        state = opt.init(params)
        params, state, first = step(params, state, batch)
        assert float(first) == pytest.approx(float(ref_loss), abs=2e-4)
        for _ in range(5):
            params, state, loss = step(params, state, batch)
        assert float(loss) < float(first)

    @pytest.mark.slow  # double-covered (PR 15 budget), transitively:
    # test_1f1b_train_step_matches_gpipe (1f1b == gpipe) and
    # test_pp_train_step_decreases_loss_and_matches_dense_step
    # (gpipe == dense) stay tier-1, so a 1f1b wiring bug still fails
    # tier-1; this direct per-(M, remat) grads sweep rides the
    # unfiltered CI run.
    def test_1f1b_loss_and_grads_match_single_device(self):
        """The manual-VJP 1F1B schedule (pp_1f1b_loss_and_grads) must
        reproduce the single-device loss AND every parameter gradient —
        the schedule only reorders compute, so any divergence is a wiring
        bug (wrong stash slot, unmasked bubble tick, missed psum)."""
        from jax.sharding import Mesh

        from k8s_gpu_scheduler_tpu.models.pipeline import (
            pp_1f1b_loss_and_grads,
        )

        cfg = self._cfg()
        params = init_params(cfg, jax.random.PRNGKey(0))
        batch = toy_batch(cfg, B=8, T=16)
        ref_loss, ref_grads = jax.value_and_grad(loss_fn)(
            params, batch, cfg, None)
        mesh = Mesh(jax.devices()[:4], ("pp",))
        import dataclasses

        for M, remat in ((2, False), (4, False), (8, False), (4, True)):
            loss, grads = pp_1f1b_loss_and_grads(
                params, batch, dataclasses.replace(cfg, remat=remat), mesh,
                microbatches=M)
            assert float(loss) == pytest.approx(float(ref_loss), abs=2e-4)
            diffs = jax.tree.map(
                lambda a, b: float(jnp.max(jnp.abs(a - b))), grads,
                {k: ref_grads[k] for k in grads})
            assert max(jax.tree.leaves(diffs)) < 1e-4, (M, remat, diffs)

    def test_1f1b_train_step_matches_gpipe(self):
        from jax.sharding import Mesh

        from k8s_gpu_scheduler_tpu.models.pipeline import make_pp_train_step

        cfg = self._cfg()
        batch = toy_batch(cfg, B=8, T=16)
        mesh = Mesh(jax.devices()[:4], ("pp",))
        opt = optax.adamw(1e-2)
        losses = {}
        for sched in ("gpipe", "1f1b"):
            params = init_params(cfg, jax.random.PRNGKey(0))
            step = make_pp_train_step(cfg, mesh, opt, microbatches=4,
                                      schedule=sched)
            state = opt.init(params)
            run = []
            for _ in range(3):
                params, state, loss = step(params, state, batch)
                run.append(float(loss))
            losses[sched] = run
        assert losses["1f1b"] == pytest.approx(losses["gpipe"], abs=2e-4)
        assert losses["1f1b"][-1] < losses["1f1b"][0]

    @staticmethod
    def _scan_saved_bytes(fn, args):
        """Static stash accounting from the jaxpr: walk every scan
        (recursing through shard_map/pjit/cond/remat sub-jaxprs) and
        return (stacked_ys_bytes, carry_shapes) — ys outputs are the
        arrays a scan materializes ONCE PER TICK and keeps live until
        consumed (exactly autodiff-GPipe's activation stash: the forward
        scan's residuals, stacked over M+P-1 ticks, survive until the
        reverse scan); carries are O(1)-per-scan live state (1F1B's
        explicit [2P, mb, T, D] stash ring lives here)."""
        closed = jax.make_jaxpr(fn)(*args)
        stacked = 0
        carry_shapes = []

        def walk(jaxpr):
            nonlocal stacked
            for eqn in jaxpr.eqns:
                if eqn.primitive.name == "scan":
                    num_carry = eqn.params["num_carry"]
                    for v in eqn.outvars[:num_carry]:
                        carry_shapes.append(tuple(v.aval.shape))
                    for v in eqn.outvars[num_carry:]:
                        aval = v.aval
                        if getattr(aval, "shape", None) is not None \
                                and aval.ndim >= 1:
                            stacked += aval.size * aval.dtype.itemsize
                for p in eqn.params.values():
                    for sub in (p if isinstance(p, (tuple, list)) else (p,)):
                        if hasattr(sub, "jaxpr"):    # ClosedJaxpr
                            walk(sub.jaxpr)
                        elif hasattr(sub, "eqns"):   # plain Jaxpr
                            walk(sub)

        walk(closed.jaxpr)
        return stacked, carry_shapes

    def test_gpipe_stash_is_o_m_and_1f1b_is_o_p(self):
        """The 1F1B headline claim, test-enforced instead of comment-
        asserted (VERDICT weak #4): at FIXED microbatch size, autodiff-
        GPipe's scan-stacked residual bytes grow linearly with M (every
        microbatch's forward activations wait for the reverse pass),
        while 1F1B's stay flat — its only activation stash is the
        explicit [2P, mb, T, D] carry ring, whose size depends on stages,
        not microbatches."""
        from functools import partial

        from jax.sharding import Mesh

        from k8s_gpu_scheduler_tpu.models.pipeline import (
            pp_1f1b_loss_and_grads, pp_loss_fn,
        )

        cfg = self._cfg()
        params = init_params(cfg, jax.random.PRNGKey(0))
        P, mb, T = 2, 2, 16
        mesh = Mesh(jax.devices()[:P], ("pp",))
        act_bytes = mb * T * cfg.d_model * 4          # one f32 activation

        stash = {}
        for M in (2, 8):
            batch = toy_batch(cfg, B=M * mb, T=T)
            gp, _ = self._scan_saved_bytes(
                jax.value_and_grad(partial(
                    pp_loss_fn, cfg=cfg, mesh=mesh, microbatches=M)),
                (params, batch))
            f1, carries = self._scan_saved_bytes(
                partial(pp_1f1b_loss_and_grads, cfg=cfg, mesh=mesh,
                        microbatches=M),
                (params, batch))
            # 1F1B's stash ring: 2(P-1)+2 = 2P in-flight input slots,
            # present and M-independent.
            assert (2 * P, mb, T, cfg.d_model) in carries, carries
            stash[M] = (gp, f1)

        gp2, f12 = stash[2]
        gp8, f18 = stash[8]
        # GPipe: ticks = M+P-1 (3 -> 9), so the stacked residual stash
        # must grow ~3x (measured 2.4x — a tick-independent residual
        # constant dilutes it); anything near-flat means the accounting
        # regressed (or remat silently engaged).
        assert gp8 >= 2.0 * gp2, (gp2, gp8)
        assert gp2 >= act_bytes, (gp2, act_bytes)     # it IS a real stash
        # 1F1B: byte-identical stash across a 4x change in M — the only
        # stacked arrays left are per-layer residuals of the in-tick VJP,
        # which depend on depth, never on M.
        assert f18 == f12, (f12, f18)
        # And the flat 1F1B stash is smaller than GPipe's already at M=8.
        assert f18 < gp8, (f18, gp8)

    def test_pp_requires_divisible_layers(self):
        from jax.sharding import Mesh

        from k8s_gpu_scheduler_tpu.models.pipeline import pp_loss_fn

        cfg = LlamaConfig(
            vocab=64, d_model=32, n_layers=3, n_heads=4, n_kv_heads=4,
            d_ff=64, max_seq=32, dtype=jnp.float32, remat=False)
        params = init_params(cfg, jax.random.PRNGKey(0))
        batch = toy_batch(cfg, B=4, T=8)
        mesh = Mesh(jax.devices()[:4], ("pp",))
        with pytest.raises(AssertionError):
            pp_loss_fn(params, batch, cfg, mesh, microbatches=2)


class TestMoEServing:
    """KV-cache decode with routed experts. Serving routes DROPLESS
    (capacity drops are a training-throughput tradeoff; at inference they
    would make completions depend on co-batched tokens and prefill
    padding), so serving outputs are per-token functions — exact across
    padding and batching by construction."""

    @staticmethod
    def _cfg(cf=1.0):
        return LlamaConfig(
            vocab=128, d_model=32, n_layers=2, n_heads=4, n_kv_heads=4,
            d_ff=64, max_seq=64, dtype=jnp.float32, remat=False,
            n_experts=4, moe_top_k=2, moe_capacity_factor=cf,
        )

    def test_moe_generate_matches_naive_greedy_dropless(self):
        """Exact-by-construction parity: with capacity_factor = n_experts
        the TRAINING forward is dropless too, so the cached path must
        reproduce it bit-for-bit (a tight cf would let training drop a
        token serving keeps — regime-dependent, not asserted here)."""
        from k8s_gpu_scheduler_tpu.models import generate

        cfg = self._cfg(cf=4.0)
        params = init_params(cfg, jax.random.PRNGKey(0))
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab)
        out = generate(params, prompt, cfg, max_new=5, max_len=32)
        seq = prompt
        for i in range(5):
            nxt = jnp.argmax(forward(params, seq, cfg)[:, -1], axis=-1)
            assert jnp.array_equal(out[:, i], nxt.astype(out.dtype)), i
            seq = jnp.concatenate([seq, nxt[:, None].astype(seq.dtype)], axis=1)

    def test_moe_batcher_matches_generate_despite_padding(self):
        """The batcher right-pads prompts to the bucket; dropless routing
        makes MoE outputs padding-invariant, so a bucket far larger than
        the prompt must not change a single emitted token — even with a
        TIGHT training capacity factor (serving ignores it)."""
        from k8s_gpu_scheduler_tpu.models import generate
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        cfg = self._cfg(cf=1.0)
        params = init_params(cfg, jax.random.PRNGKey(0))
        prompts = jax.random.randint(jax.random.PRNGKey(2), (2, 6), 0,
                                     cfg.vocab)
        ref = generate(params, prompts, cfg, max_new=4, max_len=64)
        for bucket in (6, 32):
            eng = ContinuousBatcher(params, cfg, n_slots=2, max_len=64,
                                    chunk=2, prefill_bucket=bucket)
            ids = [eng.submit(prompts[i], max_new=4) for i in range(2)]
            done = eng.run()
            for i, rid in enumerate(ids):
                assert done[rid] == [int(t) for t in ref[i]], (
                    bucket, i, done[rid])


class TestMoEDroplessRoute:
    def test_matches_capacity_path_when_no_drops(self):
        """moe_ffn_dropless must equal moe_ffn at ample capacity — the two
        formulations are the same function in the no-drop regime."""
        from k8s_gpu_scheduler_tpu.ops.moe import moe_ffn, moe_ffn_dropless

        key = jax.random.PRNGKey(0)
        D, F, E = 32, 64, 4
        x = jax.random.normal(key, (2, 8, D), jnp.float32)
        router = jax.random.normal(jax.random.fold_in(key, 1), (D, E)) * 0.1
        wg = jax.random.normal(jax.random.fold_in(key, 2), (E, D, F)) * 0.1
        wu = jax.random.normal(jax.random.fold_in(key, 3), (E, D, F)) * 0.1
        wd = jax.random.normal(jax.random.fold_in(key, 4), (E, F, D)) * 0.1
        ref, _ = moe_ffn(x, router, wg, wu, wd, top_k=2,
                         capacity_factor=float(E))
        got = moe_ffn_dropless(x, router, wg, wu, wd, top_k=2)
        assert float(jnp.abs(got - ref).max()) < 1e-4
