"""Flagship model tests: forward, training convergence, and cross-layout
agreement on the virtual 8-device mesh (dp/fsdp vs sp-ring vs sp-ulysses)."""
import jax
import jax.numpy as jnp
import optax
import pytest

from k8s_gpu_scheduler_tpu.models import (
    LlamaConfig,
    forward,
    init_params,
    loss_fn,
    make_train_step,
)
from k8s_gpu_scheduler_tpu.parallel import MeshSpec, make_mesh


def toy_batch(cfg, B=4, T=32):
    tokens = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0, cfg.vocab)
    return {"tokens": tokens, "targets": jnp.roll(tokens, -1, axis=1)}


class TestLlama:
    def test_forward_shape_and_dtype(self):
        cfg = LlamaConfig.tiny()
        params = init_params(cfg, jax.random.PRNGKey(0))
        logits = forward(params, toy_batch(cfg)["tokens"], cfg)
        assert logits.shape == (4, 32, cfg.vocab)
        assert logits.dtype == jnp.float32

    def test_loss_decreases_single_device(self):
        cfg = LlamaConfig.tiny()
        params = init_params(cfg, jax.random.PRNGKey(0))
        batch = toy_batch(cfg)
        opt = optax.adamw(3e-3)
        state = opt.init(params)
        step = make_train_step(cfg, None, opt)
        first = None
        for _ in range(8):
            params, state, loss = step(params, state, batch)
            first = first if first is not None else float(loss)
        assert float(loss) < first - 0.5, (first, float(loss))

    @pytest.mark.parametrize(
        "impl,spec",
        [
            ("dense", MeshSpec.for_devices(8, fsdp=2, tp=2)),
            ("ring", MeshSpec.for_devices(8, sp=2, tp=2)),
            ("ulysses", MeshSpec.for_devices(8, sp=4)),
        ],
    )
    def test_sharded_loss_matches_unsharded(self, impl, spec):
        """One sharded train step must produce the same loss as the
        single-device step — GSPMD layouts change math order, not math."""
        cfg = LlamaConfig.tiny(attn_impl=impl)
        params = init_params(cfg, jax.random.PRNGKey(0))
        batch = toy_batch(cfg)
        ref_loss = float(loss_fn(params, batch, LlamaConfig.tiny(), None))
        mesh = make_mesh(spec)
        opt = optax.adamw(1e-3)
        state = opt.init(params)
        step = make_train_step(cfg, mesh, opt)
        _, _, loss = step(params, state, batch)
        assert float(loss) == pytest.approx(ref_loss, abs=2e-3)

    def test_flops_per_token_order_of_magnitude(self):
        # Llama-3-8B ≈ 8e9 params → ~4.8e10 train FLOPs/token.
        f = LlamaConfig.llama3_8b().flops_per_token()
        assert 3e10 < f < 7e10


class TestBert:
    def test_classify_shape_and_bidirectional(self):
        from k8s_gpu_scheduler_tpu.models.bert import (
            BertConfig, classify, encode, init_params,
        )

        cfg = BertConfig.tiny()
        params = init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab)
        logits = classify(params, tokens, cfg)
        assert logits.shape == (2, cfg.n_classes)
        # Bidirectionality: changing the LAST token must change the FIRST
        # position's hidden state (causal attention would not).
        h1 = encode(params, tokens, cfg)
        tokens2 = tokens.at[:, -1].set((tokens[:, -1] + 1) % cfg.vocab)
        h2 = encode(params, tokens2, cfg)
        assert float(jnp.abs(h1[:, 0] - h2[:, 0]).max()) > 0


class TestResNet:
    def test_forward_shape(self):
        from k8s_gpu_scheduler_tpu.models.resnet import (
            ResNetConfig, forward, init_params,
        )

        cfg = ResNetConfig.tiny()
        params = init_params(cfg, jax.random.PRNGKey(0))
        images = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
        logits = forward(params, images, cfg)
        assert logits.shape == (2, cfg.n_classes)

    def test_train_step_decreases_loss(self):
        import optax

        from k8s_gpu_scheduler_tpu.models.resnet import (
            ResNetConfig, init_params, make_train_step,
        )

        cfg = ResNetConfig.tiny()
        params = init_params(cfg, jax.random.PRNGKey(0))
        batch = {
            "images": jax.random.normal(jax.random.PRNGKey(1), (8, 32, 32, 3)),
            "labels": jax.random.randint(jax.random.PRNGKey(2), (8,), 0,
                                         cfg.n_classes),
        }
        opt = optax.sgd(0.05, momentum=0.9)
        state = opt.init(params)
        step = make_train_step(cfg, opt)
        first = None
        for _ in range(6):
            params, state, loss = step(params, state, batch)
            first = first if first is not None else float(loss)
        assert float(loss) < first
