"""Node-agent tests: the BUILT C++ prober over its fake seam, scrape
parsing, change-detected publishing, and the full agent→registry→scheduler
integration (Score consumes agent-published utilization)."""
import json
import os
import subprocess
import sys
import time

import pytest

from k8s_gpu_scheduler_tpu.agent import Publisher, Scraper
from k8s_gpu_scheduler_tpu.registry.inventory import (
    NodeInventory,
    node_key,
    read_inventory,
)

HERE = os.path.dirname(os.path.abspath(__file__))
PROBE_DIR = os.path.join(HERE, "..", "native", "tpuprobe")
PROBE_BIN = os.path.join(PROBE_DIR, "tpuprobe")
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module", autouse=True)
def build_probe():
    subprocess.run(["make", "-C", PROBE_DIR], check=True, capture_output=True)
    assert os.path.exists(PROBE_BIN)


def write_fake(tmp_path, chips):
    path = tmp_path / "fake.json"
    path.write_text(json.dumps({"chips": chips}))
    return str(path)


class MemRegistry:
    def __init__(self):
        self.data = {}

    def set(self, key, value):
        self.data[key] = value

    def get(self, key):
        return self.data.get(key)

    def get_keys(self, pattern="*"):
        return [k for k in self.data if k.startswith(pattern.rstrip("*"))]


class TestProber:
    def test_fake_seam_roundtrip(self, tmp_path):
        fake = write_fake(tmp_path, [
            {"device_id": 0, "duty_cycle": 0.75, "hbm_used": 8, "hbm_total": 16},
            {"device_id": 1, "duty_cycle": 0.25, "hbm_used": 4, "hbm_total": 16},
        ])
        out = subprocess.run([PROBE_BIN, "--once", "--fake", fake],
                             capture_output=True, check=True)
        doc = json.loads(out.stdout)
        assert [c["device_id"] for c in doc["chips"]] == [0, 1]
        assert doc["chips"][0]["duty_cycle"] == pytest.approx(0.75)

    def test_no_devices_empty_and_nonzero_exit(self, tmp_path):
        env = {**os.environ, "TPUPROBE_DEV_GLOB": str(tmp_path / "nope*")}
        out = subprocess.run([PROBE_BIN, "--once"], capture_output=True, env=env)
        assert out.returncode == 1
        assert json.loads(out.stdout) == {"chips": []}

    def test_devnode_enumeration(self, tmp_path):
        for i in (0, 1, 3):
            (tmp_path / f"accel{i}").touch()
        env = {**os.environ, "TPUPROBE_DEV_GLOB": str(tmp_path / "accel*")}
        out = subprocess.run([PROBE_BIN, "--once"], capture_output=True,
                             env=env, check=True)
        ids = [c["device_id"] for c in json.loads(out.stdout)["chips"]]
        assert ids == [0, 1, 3]


class TestScraper:
    def test_scrape_parses_chips(self, tmp_path):
        fake = write_fake(tmp_path, [
            {"device_id": 2, "duty_cycle": 0.5, "hbm_used": 1, "hbm_total": 2},
        ])
        chips = Scraper(binary=PROBE_BIN, fake_file=fake).scrape()
        assert len(chips) == 1
        assert chips[0].device_id == 2
        assert chips[0].duty_cycle == 0.5

    def test_missing_binary_raises(self):
        with pytest.raises(RuntimeError):
            Scraper(binary="/nonexistent/tpuprobe").scrape()


class TestPublisher:
    def _publisher(self, tmp_path, reg, duty=0.5):
        fake = write_fake(tmp_path, [
            {"device_id": i, "duty_cycle": duty, "hbm_used": 0,
             "hbm_total": 16 << 30} for i in range(4)
        ])
        return Publisher(
            reg,
            scraper=Scraper(binary=PROBE_BIN, fake_file=fake),
            node_name="w0",
            accelerator="tpu-v5-lite-podslice",
            topology="2x4",
            interval_s=0.05,
            heartbeat_s=60,
        ), fake

    def test_publish_once_and_change_detection(self, tmp_path):
        reg = MemRegistry()
        pub, fake = self._publisher(tmp_path, reg)
        assert pub.publish_once() is True
        inv = read_inventory(reg, "w0")
        assert inv.utilization == pytest.approx(0.5)
        assert len(inv.chips) == 4
        assert inv.topology == "2x4"
        # Unchanged scrape within heartbeat → no write.
        assert pub.publish_once() is False
        # Changed duty → write.
        with open(fake, "w") as f:
            json.dump({"chips": [
                {"device_id": i, "duty_cycle": 0.9, "hbm_used": 0,
                 "hbm_total": 16 << 30} for i in range(4)
            ]}, f)
        assert pub.publish_once() is True
        assert read_inventory(reg, "w0").utilization == pytest.approx(0.9)

    def test_heartbeat_key_written(self, tmp_path):
        reg = MemRegistry()
        pub, _ = self._publisher(tmp_path, reg)
        pub.publish_once()
        assert node_key("w0") + "/heartbeat" in reg.data

    def test_loop_publishes(self, tmp_path):
        reg = MemRegistry()
        pub, _ = self._publisher(tmp_path, reg)
        pub.start()
        try:
            deadline = time.time() + 5
            while time.time() < deadline and node_key("w0") not in reg.data:
                time.sleep(0.02)
            assert node_key("w0") in reg.data
        finally:
            pub.stop()


class TestAgentSchedulerIntegration:
    def test_score_consumes_agent_utilization(self, tmp_path):
        """VERDICT item 6 'done' criterion: agent publishes, Score reads —
        the idle node (agent-reported) wins over the busy one."""
        from k8s_gpu_scheduler_tpu.cluster import APIServer
        from k8s_gpu_scheduler_tpu.config import SchedulerConfig
        from k8s_gpu_scheduler_tpu.plugins import TPUPlugin
        from k8s_gpu_scheduler_tpu.sched import CycleState, Profile, Scheduler
        from tests.test_plugins import mk_node, mk_pod

        reg = MemRegistry()
        for name, duty in [("busy", 0.95), ("idle", 0.05)]:
            fake = write_fake(tmp_path, [
                {"device_id": i, "duty_cycle": duty, "hbm_used": 0,
                 "hbm_total": 16 << 30} for i in range(8)
            ])
            Publisher(
                reg, scraper=Scraper(binary=PROBE_BIN, fake_file=fake),
                node_name=name, accelerator="tpu-v5-lite-podslice",
                topology="2x4",
            ).publish_once()

        sched = Scheduler(APIServer(), profile=Profile(), config=SchedulerConfig())
        plugin = TPUPlugin(sched.handle, registry=reg)
        for n in ("busy", "idle"):
            sched.cache.add_node(mk_node(n))
        state = CycleState()
        pod = mk_pod("p", chips=1)
        plugin.pre_filter(state, pod)
        for n in ("busy", "idle"):
            assert plugin.filter(state, pod, sched.cache.snapshot()[n]).ok
        s_busy, _ = plugin.score(state, pod, "busy")
        s_idle, _ = plugin.score(state, pod, "idle")
        assert s_idle > s_busy
        assert s_idle == pytest.approx(95.0)


class TestMetricsLogger:
    """C18 parity: the offline poll-to-TSV tool
    (reference parse_smi_metrics.py:25-42), over the prober fake seam."""

    def test_samples_and_dumps_tsv(self, tmp_path):
        from k8s_gpu_scheduler_tpu.agent.metrics_logger import (
            COLUMNS, MetricsLogger,
        )

        fake = write_fake(tmp_path, [
            {"device_id": 0, "duty_cycle": 0.5, "hbm_used": 10,
             "hbm_total": 100},
            {"device_id": 1, "duty_cycle": 0.25, "hbm_used": 20,
             "hbm_total": 100},
        ])
        out = str(tmp_path / "metrics.tsv")
        logger = MetricsLogger(Scraper(binary=PROBE_BIN, fake_file=fake), out,
                               interval_s=0.01)
        logger.run(max_samples=3)
        path = logger.dump()
        lines = open(path).read().strip().split("\n")
        assert lines[0].split("\t") == list(COLUMNS)
        assert len(lines) == 1 + 3 * 2  # header + samples × chips
        first = lines[1].split("\t")
        assert first[1] == "0" and float(first[2]) == 0.5

    def test_cli_entrypoint(self, tmp_path):
        fake = write_fake(tmp_path, [
            {"device_id": 0, "duty_cycle": 0.75, "hbm_used": 1,
             "hbm_total": 2},
        ])
        out = str(tmp_path / "cli.tsv")
        env = dict(os.environ, TPUPROBE_BIN=PROBE_BIN)
        proc = subprocess.run(
            [sys.executable, "-m", "k8s_gpu_scheduler_tpu.agent.metrics_logger",
             "-o", out, "--interval", "0.01", "--samples", "2",
             "--fake", fake],
            capture_output=True, env=env, timeout=30, cwd=REPO_ROOT,
        )
        assert proc.returncode == 0, proc.stderr
        lines = open(out).read().strip().split("\n")
        assert len(lines) == 3


class FakeDevicePlugin:
    """A fake GKE tpu-device-plugin metrics endpoint: serves Prometheus
    text with the device-plugin naming (duty_cycle/memory_used/memory_total,
    accelerator_id label)."""

    def __init__(self, per_chip):
        import threading
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self.per_chip = per_chip
        fake = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                lines = ["# HELP duty_cycle TPU duty cycle percent",
                         "# TYPE duty_cycle gauge"]
                for idx, m in fake.per_chip.items():
                    lab = f'accelerator_id="4804277629165885214-{idx}",make="cloud-tpu"'
                    lines.append(f'duty_cycle{{{lab}}} {m["duty"]}')
                    lines.append(f'memory_used{{{lab}}} {m["used"]}')
                    lines.append(f'memory_total{{{lab}}} {m["total"]}')
                body = "\n".join(lines).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.url = f"http://127.0.0.1:{self.server.server_port}/metrics"
        threading.Thread(target=self.server.serve_forever, daemon=True).start()

    def close(self):
        self.server.shutdown()
        self.server.server_close()


class TestDevicePluginSource:
    def test_parses_gke_convention(self):
        from k8s_gpu_scheduler_tpu.agent.deviceplugin import DevicePluginSource

        gib = 1 << 30
        dp = FakeDevicePlugin({
            0: {"duty": 87.5, "used": 12 * gib, "total": 16 * gib},
            3: {"duty": 2.0, "used": 1 * gib, "total": 16 * gib},
        })
        try:
            metrics = DevicePluginSource(dp.url).read()
        finally:
            dp.close()
        assert metrics[0].duty_cycle == pytest.approx(0.875)
        assert metrics[0].hbm_used_bytes == 12 * gib
        assert metrics[3].duty_cycle == pytest.approx(0.02)
        assert metrics[3].hbm_total_bytes == 16 * gib

    def test_parses_own_reexported_convention(self):
        """Round-trip: the agent's OWN exporter output parses back (same
        synonyms table), proving the two conventions interoperate."""
        from k8s_gpu_scheduler_tpu.agent.deviceplugin import (
            DevicePluginSource, parse_prom_text,
        )
        from k8s_gpu_scheduler_tpu.metrics.exporter import Registry

        reg = Registry()
        reg.gauge("tpu_duty_cycle_percent", "").set(
            42.0, node="n1", device_id="2")
        reg.gauge("tpu_hbm_memory_usage_bytes", "").set(
            5.0, node="n1", device_id="2")
        samples = list(parse_prom_text(reg.expose()))
        assert ("tpu_duty_cycle_percent",
                {"node": "n1", "device_id": "2"}, 42.0) in samples

        class Src(DevicePluginSource):
            def fetch_text(self):
                return reg.expose()

        metrics = Src("unused").read()
        assert metrics[2].duty_cycle == pytest.approx(0.42)
        assert metrics[2].hbm_used_bytes == 5

    def test_unreachable_endpoint_degrades_to_empty(self):
        from k8s_gpu_scheduler_tpu.agent.deviceplugin import DevicePluginSource

        assert DevicePluginSource("http://127.0.0.1:1/metrics").read() == {}


class TestLiveUtilizationE2E:
    def test_device_plugin_duty_reaches_scheduler_score(self, tmp_path):
        """VERDICT r3 #4 'done' criterion: duty cycles originate from a fake
        device-plugin HTTP endpoint (the prober's own values are ZERO, as on
        real hardware), flow agent -> registry -> plugin, and Score reflects
        them."""
        from k8s_gpu_scheduler_tpu.agent.deviceplugin import DevicePluginSource
        from k8s_gpu_scheduler_tpu.cluster import APIServer
        from k8s_gpu_scheduler_tpu.config import SchedulerConfig
        from k8s_gpu_scheduler_tpu.plugins import TPUPlugin
        from k8s_gpu_scheduler_tpu.sched import CycleState, Profile, Scheduler
        from tests.test_plugins import mk_node, mk_pod

        gib = 1 << 30
        reg = MemRegistry()
        endpoints = {}
        try:
            for name, duty_pct in [("busy", 90.0), ("idle", 10.0)]:
                # Prober reports zeros (the real /dev/accel* seam has no
                # utilization); the device-plugin endpoint has the truth.
                fake = write_fake(tmp_path, [
                    {"device_id": i, "duty_cycle": 0.0, "hbm_used": 0,
                     "hbm_total": 0} for i in range(8)
                ])
                dp = FakeDevicePlugin({
                    i: {"duty": duty_pct, "used": 2 * gib, "total": 16 * gib}
                    for i in range(8)
                })
                endpoints[name] = dp
                Publisher(
                    reg,
                    scraper=Scraper(binary=PROBE_BIN, fake_file=fake,
                                    device_plugin=DevicePluginSource(dp.url)),
                    node_name=name, accelerator="tpu-v5-lite-podslice",
                    topology="2x4",
                ).publish_once()

            inv = read_inventory(reg, "busy")
            assert inv.utilization == pytest.approx(0.9)
            assert inv.chips[0].hbm_total_bytes == 16 * gib

            sched = Scheduler(APIServer(), profile=Profile(),
                              config=SchedulerConfig())
            plugin = TPUPlugin(sched.handle, registry=reg)
            for n in ("busy", "idle"):
                sched.cache.add_node(mk_node(n))
            state = CycleState()
            pod = mk_pod("p", chips=1)
            plugin.pre_filter(state, pod)
            for n in ("busy", "idle"):
                assert plugin.filter(state, pod, sched.cache.snapshot()[n]).ok
            s_busy, _ = plugin.score(state, pod, "busy")
            s_idle, _ = plugin.score(state, pod, "idle")
            assert s_idle == pytest.approx(90.0)
            assert s_busy == pytest.approx(10.0)
        finally:
            for dp in endpoints.values():
                dp.close()

    def test_agent_reexports_series_prometheus_fallback_reads(self, tmp_path):
        """The agent's own /metrics re-exporter serves EXACTLY the series
        names metrics/client.py queries, with the node/device_id labels its
        parser extracts — so a Prometheus scraping only our agents feeds
        the scheduler's fallback with no third-party exporter."""
        from k8s_gpu_scheduler_tpu.agent.deviceplugin import parse_prom_text
        from k8s_gpu_scheduler_tpu.metrics.client import (
            HBM_TOTAL, HBM_USED, MXU_DUTY_CYCLE,
        )
        from k8s_gpu_scheduler_tpu.metrics.exporter import MetricsServer, Registry
        import urllib.request

        fake = write_fake(tmp_path, [
            {"device_id": i, "duty_cycle": 0.5, "hbm_used": 1,
             "hbm_total": 2} for i in range(4)
        ])
        metrics_registry = Registry()
        pub = Publisher(
            MemRegistry(), scraper=Scraper(binary=PROBE_BIN, fake_file=fake),
            node_name="n1", metrics_registry=metrics_registry,
        )
        pub.publish_once()
        server = MetricsServer(metrics_registry).start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/metrics", timeout=5) as r:
                text = r.read().decode()
        finally:
            server.stop()
        samples = {(n, l.get("node"), l.get("device_id")): v
                   for n, l, v in parse_prom_text(text)}
        for i in range(4):
            assert samples[(MXU_DUTY_CYCLE, "n1", str(i))] == 50.0
            assert samples[(HBM_USED, "n1", str(i))] == 1.0
            assert samples[(HBM_TOTAL, "n1", str(i))] == 2.0
