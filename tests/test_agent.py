"""Node-agent tests: the BUILT C++ prober over its fake seam, scrape
parsing, change-detected publishing, and the full agent→registry→scheduler
integration (Score consumes agent-published utilization)."""
import json
import os
import subprocess
import sys
import time

import pytest

from k8s_gpu_scheduler_tpu.agent import Publisher, Scraper
from k8s_gpu_scheduler_tpu.registry.inventory import (
    NodeInventory,
    node_key,
    read_inventory,
)

HERE = os.path.dirname(os.path.abspath(__file__))
PROBE_DIR = os.path.join(HERE, "..", "native", "tpuprobe")
PROBE_BIN = os.path.join(PROBE_DIR, "tpuprobe")
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module", autouse=True)
def build_probe():
    subprocess.run(["make", "-C", PROBE_DIR], check=True, capture_output=True)
    assert os.path.exists(PROBE_BIN)


def write_fake(tmp_path, chips):
    path = tmp_path / "fake.json"
    path.write_text(json.dumps({"chips": chips}))
    return str(path)


class MemRegistry:
    def __init__(self):
        self.data = {}

    def set(self, key, value):
        self.data[key] = value

    def get(self, key):
        return self.data.get(key)

    def get_keys(self, pattern="*"):
        return [k for k in self.data if k.startswith(pattern.rstrip("*"))]


class TestProber:
    def test_fake_seam_roundtrip(self, tmp_path):
        fake = write_fake(tmp_path, [
            {"device_id": 0, "duty_cycle": 0.75, "hbm_used": 8, "hbm_total": 16},
            {"device_id": 1, "duty_cycle": 0.25, "hbm_used": 4, "hbm_total": 16},
        ])
        out = subprocess.run([PROBE_BIN, "--once", "--fake", fake],
                             capture_output=True, check=True)
        doc = json.loads(out.stdout)
        assert [c["device_id"] for c in doc["chips"]] == [0, 1]
        assert doc["chips"][0]["duty_cycle"] == pytest.approx(0.75)

    def test_no_devices_empty_and_nonzero_exit(self, tmp_path):
        env = {**os.environ, "TPUPROBE_DEV_GLOB": str(tmp_path / "nope*")}
        out = subprocess.run([PROBE_BIN, "--once"], capture_output=True, env=env)
        assert out.returncode == 1
        assert json.loads(out.stdout) == {"chips": []}

    def test_devnode_enumeration(self, tmp_path):
        for i in (0, 1, 3):
            (tmp_path / f"accel{i}").touch()
        env = {**os.environ, "TPUPROBE_DEV_GLOB": str(tmp_path / "accel*")}
        out = subprocess.run([PROBE_BIN, "--once"], capture_output=True,
                             env=env, check=True)
        ids = [c["device_id"] for c in json.loads(out.stdout)["chips"]]
        assert ids == [0, 1, 3]


class TestScraper:
    def test_scrape_parses_chips(self, tmp_path):
        fake = write_fake(tmp_path, [
            {"device_id": 2, "duty_cycle": 0.5, "hbm_used": 1, "hbm_total": 2},
        ])
        chips = Scraper(binary=PROBE_BIN, fake_file=fake).scrape()
        assert len(chips) == 1
        assert chips[0].device_id == 2
        assert chips[0].duty_cycle == 0.5

    def test_missing_binary_raises(self):
        with pytest.raises(RuntimeError):
            Scraper(binary="/nonexistent/tpuprobe").scrape()


class TestPublisher:
    def _publisher(self, tmp_path, reg, duty=0.5):
        fake = write_fake(tmp_path, [
            {"device_id": i, "duty_cycle": duty, "hbm_used": 0,
             "hbm_total": 16 << 30} for i in range(4)
        ])
        return Publisher(
            reg,
            scraper=Scraper(binary=PROBE_BIN, fake_file=fake),
            node_name="w0",
            accelerator="tpu-v5-lite-podslice",
            topology="2x4",
            interval_s=0.05,
            heartbeat_s=60,
        ), fake

    def test_publish_once_and_change_detection(self, tmp_path):
        reg = MemRegistry()
        pub, fake = self._publisher(tmp_path, reg)
        assert pub.publish_once() is True
        inv = read_inventory(reg, "w0")
        assert inv.utilization == pytest.approx(0.5)
        assert len(inv.chips) == 4
        assert inv.topology == "2x4"
        # Unchanged scrape within heartbeat → no write.
        assert pub.publish_once() is False
        # Changed duty → write.
        with open(fake, "w") as f:
            json.dump({"chips": [
                {"device_id": i, "duty_cycle": 0.9, "hbm_used": 0,
                 "hbm_total": 16 << 30} for i in range(4)
            ]}, f)
        assert pub.publish_once() is True
        assert read_inventory(reg, "w0").utilization == pytest.approx(0.9)

    def test_heartbeat_key_written(self, tmp_path):
        reg = MemRegistry()
        pub, _ = self._publisher(tmp_path, reg)
        pub.publish_once()
        assert node_key("w0") + "/heartbeat" in reg.data

    def test_loop_publishes(self, tmp_path):
        reg = MemRegistry()
        pub, _ = self._publisher(tmp_path, reg)
        pub.start()
        try:
            deadline = time.time() + 5
            while time.time() < deadline and node_key("w0") not in reg.data:
                time.sleep(0.02)
            assert node_key("w0") in reg.data
        finally:
            pub.stop()


class TestAgentSchedulerIntegration:
    def test_score_consumes_agent_utilization(self, tmp_path):
        """VERDICT item 6 'done' criterion: agent publishes, Score reads —
        the idle node (agent-reported) wins over the busy one."""
        from k8s_gpu_scheduler_tpu.cluster import APIServer
        from k8s_gpu_scheduler_tpu.config import SchedulerConfig
        from k8s_gpu_scheduler_tpu.plugins import TPUPlugin
        from k8s_gpu_scheduler_tpu.sched import CycleState, Profile, Scheduler
        from tests.test_plugins import mk_node, mk_pod

        reg = MemRegistry()
        for name, duty in [("busy", 0.95), ("idle", 0.05)]:
            fake = write_fake(tmp_path, [
                {"device_id": i, "duty_cycle": duty, "hbm_used": 0,
                 "hbm_total": 16 << 30} for i in range(8)
            ])
            Publisher(
                reg, scraper=Scraper(binary=PROBE_BIN, fake_file=fake),
                node_name=name, accelerator="tpu-v5-lite-podslice",
                topology="2x4",
            ).publish_once()

        sched = Scheduler(APIServer(), profile=Profile(), config=SchedulerConfig())
        plugin = TPUPlugin(sched.handle, registry=reg)
        for n in ("busy", "idle"):
            sched.cache.add_node(mk_node(n))
        state = CycleState()
        pod = mk_pod("p", chips=1)
        plugin.pre_filter(state, pod)
        for n in ("busy", "idle"):
            assert plugin.filter(state, pod, sched.cache.snapshot()[n]).ok
        s_busy, _ = plugin.score(state, pod, "busy")
        s_idle, _ = plugin.score(state, pod, "idle")
        assert s_idle > s_busy
        assert s_idle == pytest.approx(95.0)


class TestMetricsLogger:
    """C18 parity: the offline poll-to-TSV tool
    (reference parse_smi_metrics.py:25-42), over the prober fake seam."""

    def test_samples_and_dumps_tsv(self, tmp_path):
        from k8s_gpu_scheduler_tpu.agent.metrics_logger import (
            COLUMNS, MetricsLogger,
        )

        fake = write_fake(tmp_path, [
            {"device_id": 0, "duty_cycle": 0.5, "hbm_used": 10,
             "hbm_total": 100},
            {"device_id": 1, "duty_cycle": 0.25, "hbm_used": 20,
             "hbm_total": 100},
        ])
        out = str(tmp_path / "metrics.tsv")
        logger = MetricsLogger(Scraper(binary=PROBE_BIN, fake_file=fake), out,
                               interval_s=0.01)
        logger.run(max_samples=3)
        path = logger.dump()
        lines = open(path).read().strip().split("\n")
        assert lines[0].split("\t") == list(COLUMNS)
        assert len(lines) == 1 + 3 * 2  # header + samples × chips
        first = lines[1].split("\t")
        assert first[1] == "0" and float(first[2]) == 0.5

    def test_cli_entrypoint(self, tmp_path):
        fake = write_fake(tmp_path, [
            {"device_id": 0, "duty_cycle": 0.75, "hbm_used": 1,
             "hbm_total": 2},
        ])
        out = str(tmp_path / "cli.tsv")
        env = dict(os.environ, TPUPROBE_BIN=PROBE_BIN)
        proc = subprocess.run(
            [sys.executable, "-m", "k8s_gpu_scheduler_tpu.agent.metrics_logger",
             "-o", out, "--interval", "0.01", "--samples", "2",
             "--fake", fake],
            capture_output=True, env=env, timeout=30, cwd=REPO_ROOT,
        )
        assert proc.returncode == 0, proc.stderr
        lines = open(out).read().strip().split("\n")
        assert len(lines) == 3
