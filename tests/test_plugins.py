"""TPU plugin + gang plugin tests.

The reference's 930-line plugin has ZERO tests (SURVEY.md §4); this suite is
the hermetic coverage the rebuild owes: scoring-formula parity, the
no-registry fallback, side-effect-free Score (losing nodes get no writes —
the reference's hazard at gpu_plugins.go:653-666,760-772), device-assignment
injection, and all-or-nothing gang admission (no reference analogue).
"""
import time

import pytest

from k8s_gpu_scheduler_tpu.api.objects import (
    ANN_SLICE_CONFIG,
    ConfigMap,
    ConfigMapRef,
    Container,
    EnvVar,
    LABEL_POD_GROUP,
    LABEL_SLICE_GROUP,
    LABEL_TPU_ACCELERATOR,
    LABEL_TPU_TOPOLOGY,
    LABEL_WORKER_INDEX,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodGroup,
    PodSpec,
    ResourceRequirements,
    TPU_RESOURCE,
)
from k8s_gpu_scheduler_tpu.cluster import APIServer
from k8s_gpu_scheduler_tpu.config import SchedulerConfig
from k8s_gpu_scheduler_tpu.plugins import GangPlugin, TPUPlugin
from k8s_gpu_scheduler_tpu.plugins.tpu import (
    ENV_VISIBLE_CHIPS,
    ENV_WORKER_HOSTNAMES,
    ENV_WORKER_ID,
    combine_terms,
    match_interference,
    pod_slo,
    slo_slack_terms,
)
from k8s_gpu_scheduler_tpu.registry.inventory import NodeInventory, node_key
from k8s_gpu_scheduler_tpu.sched import CycleState, Profile, Scheduler, Status


# --- builders ----------------------------------------------------------------


def mk_node(name, chips=8, gen="tpu-v5-lite-podslice", topo="2x4", labels=None,
            annotations=None):
    lab = {LABEL_TPU_ACCELERATOR: gen, LABEL_TPU_TOPOLOGY: topo}
    lab.update(labels or {})
    return Node(
        metadata=ObjectMeta(name=name, labels=lab, annotations=annotations or {}),
        status=NodeStatus(
            capacity={TPU_RESOURCE: chips},
            allocatable={TPU_RESOURCE: chips},
            addresses=["10.0.0.1"],
        ),
    )


def mk_pod(name, chips=1, slo=None, cm=None, group=None, ns="default",
           priority=None, owner=None):
    env = [EnvVar("SLO", str(slo))] if slo is not None else []
    env_from = [ConfigMapRef(cm)] if cm else []
    labels = {LABEL_POD_GROUP: group} if group else {}
    annotations = {"tpu.sched/priority": str(priority)} if priority else {}
    return Pod(
        metadata=ObjectMeta(name=name, namespace=ns, labels=labels,
                            annotations=annotations,
                            owner_references=[owner] if owner else []),
        spec=PodSpec(
            containers=[
                Container(
                    env=env,
                    env_from=env_from,
                    resources=ResourceRequirements(requests={TPU_RESOURCE: chips}),
                )
            ]
        ),
    )


class FakeRegistry:
    """In-memory stand-in for the RESP client (registry/client.py)."""

    def __init__(self):
        self.data = {}

    def get(self, key):
        return self.data.get(key)

    def set(self, key, value):
        self.data[key] = value

    def get_keys(self, pattern="*"):
        prefix = pattern.rstrip("*")
        return [k for k in self.data if k.startswith(prefix)]

    def publish(self, node_name, utilization=0.0):
        inv = NodeInventory(node_name=node_name, utilization=utilization)
        self.data[node_key(node_name)] = inv.to_json()


class FakeRecommender:
    """PredictionClient fake — canned conf/interference matrices."""

    def __init__(self, conf=None, intf=None):
        self.conf = conf or {}
        self.intf = intf or {}

    def impute_configurations(self, index):
        for key, row in self.conf.items():
            if key in index.replace("-", "_"):
                return row
        return {}

    def impute_interference(self, index):
        for key, row in self.intf.items():
            if key in index.replace("-", "_"):
                return row
        return {}


def wait_until(fn, timeout=5.0, interval=0.01):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


def make_scheduler(server, registry=None, recommender=None, config=None,
                   with_gang=False, with_preemption=False):
    config = config or SchedulerConfig(backoff_initial_s=0.05, backoff_max_s=0.2)
    sched = Scheduler(server, profile=Profile(), config=config)
    tpu = TPUPlugin(sched.handle, registry=registry, recommender=recommender)
    profile = Profile(
        pre_filter=[tpu], filter=[tpu], score=[tpu], reserve=[tpu], post_bind=[tpu]
    )
    if with_gang:
        gang = GangPlugin(sched.handle)
        profile.pre_filter.append(gang)
        profile.filter.append(gang)
        profile.score.append(gang)
        profile.reserve.append(gang)
        profile.permit.append(gang)
        profile.post_bind.append(gang)
    if with_preemption:
        from k8s_gpu_scheduler_tpu.plugins import PreemptionPlugin

        profile.post_filter.append(PreemptionPlugin(
            sched.handle, filter_plugins=list(profile.filter), tpu=tpu))
    sched.profile = profile
    return sched


# --- formula parity ----------------------------------------------------------


class TestScoringFormula:
    def test_violated_slo_quadratic_penalty(self):
        # SLO 10, predicted 8, no interference: slack 2, rel 0.2 →
        # 1/(1+(1.2)^2) = 1/2.44
        term, violated = slo_slack_terms(10.0, 8.0, 0.0)
        assert violated
        assert term == pytest.approx(1 / 2.44)

    def test_satisfied_slo_linear(self):
        # SLO 10, predicted 15: slack -5, rel 0.5 → 1/1.5
        term, violated = slo_slack_terms(10.0, 15.0, 0.0)
        assert not violated
        assert term == pytest.approx(1 / 1.5)

    def test_interference_flips_verdict(self):
        _, ok_before = slo_slack_terms(10.0, 15.0, 0.0)
        _, ok_after = slo_slack_terms(10.0, 15.0, 6.0)
        assert (ok_before, ok_after) == (False, True)

    def test_combine_blends_by_violation_fraction(self):
        # 1 positive (sum .5), 1 negative (sum .25): k=0.5 →
        # 100*(0.5*0.5 + 0.5*0.25) = 37.5
        assert combine_terms(0.5, 1, 0.25, 1) == pytest.approx(37.5)
        assert combine_terms(0.5, 1, 0.0, 0) == pytest.approx(50.0)
        assert combine_terms(0.0, 0, 0.25, 1) == pytest.approx(25.0)
        assert combine_terms(0.0, 0, 0.0, 0) == 0.0

    def test_match_interference_normalizes_dashes(self):
        row = {"bert_base": 3.0, "resnet": 1.0}
        assert match_interference(row, "bert-base-serving-0") == 3.0
        assert match_interference(row, "unrelated") == 0.0

    def test_pod_slo_tolerant_parse(self):
        assert pod_slo(mk_pod("a", slo=12.5)) == 12.5
        assert pod_slo(mk_pod("a", slo="garbage")) == 0.0
        assert pod_slo(mk_pod("a")) == 0.0


# --- filter ------------------------------------------------------------------


class TestTPUFilter:
    def _plugin(self, server=None, registry=None):
        sched = make_scheduler(server or APIServer(), registry=registry)
        return sched, sched.profile.filter[0]

    def test_insufficient_chips(self):
        server = APIServer()
        sched, plugin = self._plugin(server)
        node = mk_node("n1", chips=4)
        sched.cache.add_node(node)
        info = sched.cache.snapshot()["n1"]
        state = CycleState()
        pod = mk_pod("p", chips=8)
        assert plugin.pre_filter(state, pod).ok
        st = plugin.filter(state, pod, info)
        assert not st.ok and "insufficient" in st.message

    def test_missing_labels_rejected_for_tpu_pod(self):
        server = APIServer()
        sched, plugin = self._plugin(server)
        bare = Node(metadata=ObjectMeta(name="cpu1"),
                    status=NodeStatus(allocatable={TPU_RESOURCE: 8}))
        sched.cache.add_node(bare)
        info = sched.cache.snapshot()["cpu1"]
        st = plugin.filter(CycleState(), mk_pod("p", chips=1), info)
        assert not st.ok and "labels" in st.message

    def test_cpu_pod_lands_anywhere_ready(self):
        server = APIServer()
        sched, plugin = self._plugin(server)
        bare = Node(metadata=ObjectMeta(name="cpu1"), status=NodeStatus())
        sched.cache.add_node(bare)
        info = sched.cache.snapshot()["cpu1"]
        assert plugin.filter(CycleState(), mk_pod("busybox", chips=0), info).ok

    def test_node_selector_respected(self):
        server = APIServer()
        sched, plugin = self._plugin(server)
        sched.cache.add_node(mk_node("n1"))
        info = sched.cache.snapshot()["n1"]
        pod = mk_pod("p", chips=1)
        pod.spec.node_selector = {"zone": "us-central2-b"}
        assert not plugin.filter(CycleState(), pod, info).ok


# --- score -------------------------------------------------------------------


class TestTPUScore:
    def test_utilization_fallback_prefers_idle_node(self):
        """No SLO/recommender → 100*(1-duty) from agent-published inventory
        (parity gpu_plugins.go:508-527, minus its return-0 bug)."""
        server = APIServer()
        reg = FakeRegistry()
        reg.publish("busy", utilization=0.9)
        reg.publish("idle", utilization=0.1)
        sched = make_scheduler(server, registry=reg)
        for n in ("busy", "idle"):
            sched.cache.add_node(mk_node(n))
        plugin = sched.profile.score[0]
        state = CycleState()
        pod = mk_pod("p", chips=1)
        plugin.pre_filter(state, pod)
        for name in ("busy", "idle"):
            info = sched.cache.snapshot()[name]
            assert plugin.filter(state, pod, info).ok
        s_busy, _ = plugin.score(state, pod, "busy")
        s_idle, _ = plugin.score(state, pod, "idle")
        assert s_idle == pytest.approx(90.0)
        assert s_busy == pytest.approx(10.0)

    def test_prom_fallback_uses_percent_scale(self):
        """node_duty_cycle returns 0..100 (metrics/client.py contract); the
        fallback score must be 100-duty_pct, not a clamped fraction."""

        class FakeProm:
            def node_duty_cycle(self, node_name):
                return {"busy": 87.5, "idle": 5.0}[node_name]

        sched = make_scheduler(APIServer())
        plugin = sched.profile.score[0]
        plugin.prom = FakeProm()
        for n in ("busy", "idle"):
            sched.cache.add_node(mk_node(n))
        state = CycleState()
        pod = mk_pod("p", chips=1)
        plugin.pre_filter(state, pod)
        for n in ("busy", "idle"):
            plugin.filter(state, pod, sched.cache.snapshot()[n])
        assert plugin.score(state, pod, "busy")[0] == pytest.approx(12.5)
        assert plugin.score(state, pod, "idle")[0] == pytest.approx(95.0)

    def test_normalize_min_max(self):
        sched = make_scheduler(APIServer())
        plugin = sched.profile.score[0]
        scores = {"a": 10.0, "b": 30.0, "c": 20.0}
        plugin.normalize_scores(CycleState(), mk_pod("p"), scores)
        assert scores == {"a": 0.0, "b": 100.0, "c": 50.0}
        same = {"a": 42.0, "b": 42.0}
        plugin.normalize_scores(CycleState(), mk_pod("p"), same)
        assert same == {"a": 100.0, "b": 100.0}

    def test_slo_scoring_avoids_contended_node(self):
        """SLO-slack path: a node whose resident pod's SLO would be violated
        by co-location scores below an empty one."""
        server = APIServer()
        reg = FakeRegistry()
        reg.publish("loaded", utilization=0.0)
        reg.publish("empty", utilization=0.0)
        conf = {"bert": {"1P_V5E": 20.0}, "newpod": {"1P_V5E": 20.0}}
        intf = {"bert": {"newpod": 15.0}, "newpod": {"bert": 15.0}}
        rec = FakeRecommender(conf=conf, intf=intf)
        sched = make_scheduler(server, registry=reg, recommender=rec)
        for n in ("loaded", "empty"):
            sched.cache.add_node(mk_node(n))
        # Resident pod with SLO 18 on "loaded" (bound, known via cache).
        resident = mk_pod("bert-0", chips=8, slo=18.0)
        resident.spec.node_name = "loaded"
        sched.cache.add_pod(resident)

        plugin = sched.profile.score[0]
        state = CycleState()
        pod = mk_pod("newpod-0", chips=8, slo=18.0)
        plugin.pre_filter(state, pod)
        infos = sched.cache.snapshot()
        assert plugin.filter(state, pod, infos["empty"]).ok
        # "loaded" has 0 free chips for an 8-chip pod → filtered out; score
        # the empty node and check the decision was stashed, not written.
        s_empty, st = plugin.score(state, pod, "empty")
        assert st.ok
        # empty node: only the incoming pod contributes; conf 20 vs SLO 18,
        # no co-located interference → satisfied: 1/(1+2/18) → *100
        assert s_empty == pytest.approx(100 / (1 + 2.0 / 18.0))
        assert state.read("tpu.decision/empty") is not None

    def test_rightsizing_picks_cheapest_satisfying_config(self):
        """V100-MPS right-sizing parity (gpu_plugins.go:638-666): smallest
        predicted QPS that still clears the SLO wins."""
        reg = FakeRegistry()
        reg.publish("n1", utilization=0.0)
        conf = {
            "2x4": {"1P_V5E": 100.0},
            "2x2": {"2P_V5E": 60.0},
            "1x2": {"4P_V5E": 30.0},
            "1x1": {"8P_V5E": 12.0},
            "newpod": {"1P_V5E": 100.0},
        }
        rec = FakeRecommender(conf=conf, intf={})
        sched = make_scheduler(APIServer(), registry=reg, recommender=rec)
        sched.cache.add_node(mk_node("n1"))
        plugin = sched.profile.score[0]
        state = CycleState()
        pod = mk_pod("newpod-0", chips=1, slo=25.0)
        plugin.pre_filter(state, pod)
        plugin.filter(state, pod, sched.cache.snapshot()["n1"])
        plugin.score(state, pod, "n1")
        decision = state.read("tpu.decision/n1")
        # 30 QPS (1x2, 4-way) is the cheapest config above SLO 25.
        assert decision.rightsized_config == "1x2"

    def test_multihost_partitions_limited_to_host_board(self):
        """A multi-host v5e 4x4 host owns a 2x2 4-chip board — assignments
        must never name chips 4..7 that don't exist on the host."""
        reg = FakeRegistry()
        reg.publish("w0", utilization=0.0)
        sched = make_scheduler(APIServer(), registry=reg)
        sched.cache.add_node(mk_node("w0", chips=4, topo="4x4"))
        plugin = sched.profile.score[0]
        state = CycleState()
        pod = mk_pod("p", chips=4)
        plugin.pre_filter(state, pod)
        assert plugin.filter(state, pod, sched.cache.snapshot()["w0"]).ok
        plugin.score(state, pod, "w0")
        decision = state.read("tpu.decision/w0")
        assert decision.partition.chip_ids == (0, 1, 2, 3)
        assert decision.partition.topology == "2x2"

    def test_partition_carving_from_annotation(self):
        """ANN_SLICE_CONFIG partitions the board — MIG-instance analogue."""
        reg = FakeRegistry()
        reg.publish("n1", utilization=0.0)
        sched = make_scheduler(APIServer(), registry=reg)
        sched.cache.add_node(
            mk_node("n1", annotations={ANN_SLICE_CONFIG: "2x2"})
        )
        plugin = sched.profile.score[0]
        state = CycleState()
        pod = mk_pod("p", chips=4)
        plugin.pre_filter(state, pod)
        plugin.filter(state, pod, sched.cache.snapshot()["n1"])
        plugin.score(state, pod, "n1")
        decision = state.read("tpu.decision/n1")
        assert decision.partition is not None
        assert decision.partition.topology == "2x2"
        assert decision.partition.chip_ids in ((0, 1, 2, 3), (4, 5, 6, 7))
        # Shared host → HBM/duty caps (MPS-limit analogue).
        assert decision.hbm_limit_bytes > 0
        assert decision.duty_pct == 50


class TestLatencySLO:
    """The measured-latency SLO loop (VERDICT r4 #3): serving p99 lands in
    latency/<workload>/<column> registry keys (collector), and the plugin's
    rightsize/Score consult them via the pod's SLO_P99_MS env — a pod whose
    measured p99 violates its SLO gets a bigger partition on its next
    placement."""

    @staticmethod
    def _pod(chips=2, slo=None, slo_p99=None, workload="llama3_8b_serve"):
        env = [EnvVar("WORKLOAD_NAME", workload)]
        if slo is not None:
            env.append(EnvVar("SLO", str(slo)))
        if slo_p99 is not None:
            env.append(EnvVar("SLO_P99_MS", str(slo_p99)))
        return Pod(
            metadata=ObjectMeta(name="llama3-8b-serve-0", namespace="default"),
            spec=PodSpec(containers=[Container(
                env=env,
                resources=ResourceRequirements(requests={TPU_RESOURCE: chips}),
            )]),
        )

    @staticmethod
    def _lat(reg, column, p99):
        from k8s_gpu_scheduler_tpu.registry.inventory import latency_key

        reg.set(latency_key("llama3_8b_serve", column), str(p99))

    def _decide(self, sched, pod, node="n1"):
        plugin = sched.profile.score[0]
        state = CycleState()
        plugin.pre_filter(state, pod)
        assert plugin.filter(state, pod, sched.cache.snapshot()[node]).ok
        score, _ = plugin.score(state, pod, node)
        return state.read(f"tpu.decision/{node}"), score

    def test_measured_violation_rightsizes_bigger_without_recommender(self):
        """Latency-only mode (no QPS SLO, no recommender): measured p99
        150 ms at the 2- and 4-chip sub-slices vs a 100 ms SLO → rightsize
        escapes to the smallest size not observed violating (whole board)."""
        reg = FakeRegistry()
        reg.publish("n1")
        self._lat(reg, "2P_V5E", 150.0)
        self._lat(reg, "4P_V5E", 120.0)
        sched = make_scheduler(APIServer(), registry=reg)
        sched.cache.add_node(mk_node("n1"))
        decision, _ = self._decide(sched, self._pod(slo_p99=100.0))
        assert decision.rightsized_config == "2x4"

    def test_no_measured_violation_no_reshape_churn(self):
        """A latency SLO with nothing measured violating must NOT
        right-size — reshapes are disruptive and there is no evidence."""
        reg = FakeRegistry()
        reg.publish("n1")
        self._lat(reg, "2P_V5E", 80.0)       # within SLO
        sched = make_scheduler(APIServer(), registry=reg)
        sched.cache.add_node(mk_node("n1"))
        decision, _ = self._decide(sched, self._pod(slo_p99=100.0))
        assert decision.rightsized_config == ""

    def test_latency_overlay_overrides_qps_rightsize(self):
        """QPS rightsizing picks the cheapest config whose PREDICTED QPS
        clears the SLO (reference parity); a MEASURED p99 violation at that
        size excludes it, so the pod lands one size up."""
        reg = FakeRegistry()
        reg.publish("n1")
        conf = {
            "1x2": {"4P_V5E": 25.0},
            "2x2": {"2P_V5E": 30.0},
            "2x4": {"1P_V5E": 40.0},
        }
        rec = FakeRecommender(conf=conf)
        sched = make_scheduler(APIServer(), registry=reg, recommender=rec)
        sched.cache.add_node(mk_node("n1"))
        # Without latency evidence: cheapest QPS-clearing config (1x2).
        decision, _ = self._decide(sched, self._pod(slo=20.0, slo_p99=100.0))
        assert decision.rightsized_config == "1x2"
        # Measured p99 at 2 chips breaks the SLO → next placement gets 2x2.
        self._lat(reg, "2P_V5E", 150.0)
        decision, _ = self._decide(sched, self._pod(slo=20.0, slo_p99=100.0))
        assert decision.rightsized_config == "2x2"

    def test_score_prefers_partition_size_meeting_measured_latency(self):
        """Between a node carved into sub-slices this workload was measured
        violating its p99 on and a whole-board node measured healthy, the
        healthy node must score higher (all else equal)."""
        reg = FakeRegistry()
        reg.publish("n-small")
        reg.publish("n-big")
        self._lat(reg, "2P_V5E", 150.0)      # 2-chip sub-slice: violating
        self._lat(reg, "8P_V5E", 50.0)       # whole board: healthy
        rec = FakeRecommender(conf={
            "llama3_8b_serve": {"4P_V5E": 30.0, "1P_V5E": 30.0},
        })
        sched = make_scheduler(APIServer(), registry=reg, recommender=rec)
        sched.cache.add_node(
            mk_node("n-small", annotations={ANN_SLICE_CONFIG: "1x2"}))
        sched.cache.add_node(mk_node("n-big"))
        pod = self._pod(slo=20.0, slo_p99=100.0)
        _, small = self._decide(sched, pod, node="n-small")
        _, big = self._decide(sched, pod, node="n-big")
        assert big > small, (big, small)


class TestPerChipPartitionChoice:
    """Per-chip duty/HBM from the agent inventory drives partition selection
    (the per-UUID DCGM richness of gpu_plugins.go:162-236 → :561-756, which
    r3 published but ignored — VERDICT.md r3 missing #3)."""

    @staticmethod
    def _publish_chips(reg, node, duties, hbm_used=None, hbm_total=None):
        from k8s_gpu_scheduler_tpu.registry.inventory import ChipInfo

        chips = [
            ChipInfo(
                device_id=i,
                duty_cycle=d,
                hbm_used_bytes=(hbm_used or [0] * len(duties))[i],
                hbm_total_bytes=(hbm_total or [0] * len(duties))[i],
            )
            for i, d in enumerate(duties)
        ]
        inv = NodeInventory(node_name=node, chips=chips,
                            utilization=sum(duties) / len(duties))
        reg.data[node_key(node)] = inv.to_json()

    def _scored_decision(self, reg, pod, annotations=None):
        sched = make_scheduler(APIServer(), registry=reg)
        sched.cache.add_node(
            mk_node("n1", annotations=annotations or {ANN_SLICE_CONFIG: "2x2"}))
        plugin = sched.profile.score[0]
        state = CycleState()
        plugin.pre_filter(state, pod)
        assert plugin.filter(state, pod, sched.cache.snapshot()["n1"]).ok
        plugin.score(state, pod, "n1")
        return state.read("tpu.decision/n1")

    def test_second_pod_lands_on_lower_duty_partition(self):
        """Two 2x2 partitions, equal pod count: chips 0-3 run hot (0.8),
        chips 4-7 idle (0.1) → the idle sub-slice wins."""
        reg = FakeRegistry()
        self._publish_chips(reg, "n1", duties=[0.8, 0.8, 0.8, 0.8,
                                               0.1, 0.1, 0.1, 0.1])
        decision = self._scored_decision(reg, mk_pod("p", chips=4))
        assert decision.partition.chip_ids == (4, 5, 6, 7)

    def test_hbm_breaks_duty_ties(self):
        """Equal duty, partition 0 holds more HBM → partition 1 wins."""
        gib = 1 << 30
        reg = FakeRegistry()
        self._publish_chips(
            reg, "n1", duties=[0.5] * 8,
            hbm_used=[10 * gib] * 4 + [1 * gib] * 4,
            hbm_total=[16 * gib] * 8,
        )
        decision = self._scored_decision(reg, mk_pod("p", chips=4))
        assert decision.partition.chip_ids == (4, 5, 6, 7)

    def test_sharing_limit_debits_used_hbm(self):
        """The injected HBM cap is what's actually free on the partition,
        not nameplate capacity (MPS-limit analogue, gpu_plugins.go:896-904,
        minus the static split)."""
        gib = 1 << 30
        reg = FakeRegistry()
        self._publish_chips(
            reg, "n1", duties=[0.0] * 8,
            hbm_used=[0] * 4 + [4 * gib] * 4,
            hbm_total=[16 * gib] * 8,
        )
        # Partition 0 is fully free: cap = 4 chips × 16 GiB.
        decision = self._scored_decision(reg, mk_pod("p", chips=4))
        assert decision.partition.chip_ids == (0, 1, 2, 3)
        assert decision.hbm_limit_bytes == 4 * 16 * gib
        # Make partition 0 the busy one; the winner (1) debits its 16 GiB.
        self._publish_chips(
            reg, "n1", duties=[0.9] * 4 + [0.0] * 4,
            hbm_used=[4 * gib] * 4 + [4 * gib] * 4,
            hbm_total=[16 * gib] * 8,
        )
        decision = self._scored_decision(reg, mk_pod("p", chips=4))
        assert decision.partition.chip_ids == (4, 5, 6, 7)
        assert decision.hbm_limit_bytes == 4 * 16 * gib - 4 * 4 * gib

    def test_slo_score_tie_breaks_on_duty(self):
        """SLO path: two partitions with identical slack scores — the
        lower-duty one is chosen."""
        reg = FakeRegistry()
        self._publish_chips(reg, "n1", duties=[0.7, 0.7, 0.7, 0.7,
                                               0.2, 0.2, 0.2, 0.2])
        conf = {"newpod": {"2P_V5E": 30.0}}
        rec = FakeRecommender(conf=conf, intf={})
        sched = make_scheduler(APIServer(), registry=reg, recommender=rec)
        sched.cache.add_node(mk_node("n1", annotations={ANN_SLICE_CONFIG: "2x2"}))
        plugin = sched.profile.score[0]
        state = CycleState()
        pod = mk_pod("newpod-0", chips=4, slo=20.0)
        plugin.pre_filter(state, pod)
        assert plugin.filter(state, pod, sched.cache.snapshot()["n1"]).ok
        plugin.score(state, pod, "n1")
        decision = state.read("tpu.decision/n1")
        assert decision.partition.chip_ids == (4, 5, 6, 7)


class TestNeighborInjection:
    def test_second_tenant_gets_neighbor_names(self):
        """PostBind injects TPU_NEIGHBORS = co-residents on the same
        partition, so the workload can tag its throughput samples as
        interference measurements (collector.py folds the delta)."""
        server = APIServer()
        server.create(ConfigMap(metadata=ObjectMeta(name="cm-n1"), data={}))
        server.create(ConfigMap(metadata=ObjectMeta(name="cm-n2"), data={}))
        reg = FakeRegistry()
        reg.publish("n1", utilization=0.0)
        sched = make_scheduler(server, registry=reg)
        server.create(mk_node("n1", annotations={ANN_SLICE_CONFIG: "2x4"}))
        server.create(mk_pod("tenant-a", chips=0, cm="cm-n1"))
        sched.start()
        try:
            assert wait_until(
                lambda: server.get("Pod", "tenant-a", "default").spec.node_name)
            # tenant-a is a CPU pod — no partition, no neighbors entry.
            # tenant-b takes the whole-board partition where a chip pod
            # resides; seed that resident first.
            server.create(mk_pod("resident", chips=4, cm="cm-n1"))
            assert wait_until(
                lambda: server.get("Pod", "resident", "default").spec.node_name)
            server.create(mk_pod("tenant-b", chips=4, cm="cm-n2"))
            assert wait_until(
                lambda: server.get("Pod", "tenant-b", "default").spec.node_name)
            cm = server.get("ConfigMap", "cm-n2", "default")
            assert cm.data.get("TPU_NEIGHBORS") == "resident", cm.data
            # The RESIDENT's live registry key was refreshed too — it must
            # stop tagging samples as solo now that tenant-b moved in
            # (names are workload identities, replica ordinals stripped).
            assert reg.get("neighbors/resident") == "tenant_b"
            assert reg.get("neighbors/tenant-b") == "resident"
        finally:
            sched.stop()


# --- end-to-end: assignment + side-effect-free score -------------------------


class TestTPUEndToEnd:
    def test_postbind_injects_assignment_and_losers_untouched(self):
        server = APIServer()
        server.create(ConfigMap(metadata=ObjectMeta(name="cm-p"), data={}))
        reg = FakeRegistry()
        reg.publish("winner", utilization=0.0)
        reg.publish("loser", utilization=0.8)
        sched = make_scheduler(server, registry=reg)
        server.create(mk_node("winner"))
        server.create(mk_node("loser"))
        pod = mk_pod("p-0", chips=8, cm="cm-p")
        server.create(pod)
        sched.start()
        try:
            assert wait_until(
                lambda: server.get("Pod", "p-0", "default").spec.node_name
            )
            bound = server.get("Pod", "p-0", "default")
            assert bound.spec.node_name == "winner"
            cm = server.get("ConfigMap", "cm-p", "default")
            # Device assignment injected (CUDA_VISIBLE_DEVICES analogue).
            assert cm.data[ENV_VISIBLE_CHIPS] == "0,1,2,3,4,5,6,7"
            assert cm.data[ENV_WORKER_ID] == "0"
            # {nodeName: partition} parity key for the WINNER only — the
            # loser key proves Score stayed side-effect-free.
            assert "winner" in cm.data
            assert "loser" not in cm.data
        finally:
            sched.stop()

    def test_unpublished_node_still_schedulable(self):
        """Registry reachable but node never published by an agent — the
        conservative fallback still places the pod."""
        server = APIServer()
        server.create(ConfigMap(metadata=ObjectMeta(name="cm-q"), data={}))
        sched = make_scheduler(server, registry=FakeRegistry())
        server.create(mk_node("n1"))
        server.create(mk_pod("q-0", chips=1, cm="cm-q"))
        sched.start()
        try:
            assert wait_until(
                lambda: server.get("Pod", "q-0", "default").spec.node_name
            )
        finally:
            sched.stop()


# --- gang --------------------------------------------------------------------


def v5p_slice(pool, n_hosts=4, topo="2x2x4"):
    """Nodes of one multi-host v5p slice: 4 chips/host, shared slice-group."""
    return [
        mk_node(
            f"{pool}-w{i}",
            chips=4,
            gen="tpu-v5p-slice",
            topo=topo,
            labels={LABEL_SLICE_GROUP: pool, LABEL_WORKER_INDEX: str(i)},
        )
        for i in range(n_hosts)
    ]


class TestGang:
    def _gang_setup(self, server, n_pods, min_member, timeout=5.0):
        server.create(
            PodGroup(
                metadata=ObjectMeta(name="llama"),
                min_member=min_member,
                topology="2x2x4",
                schedule_timeout_s=timeout,
            )
        )
        pods = []
        for i in range(n_pods):
            server.create(ConfigMap(metadata=ObjectMeta(name=f"cm-g{i}"), data={}))
            pod = mk_pod(f"llama-{i}", chips=4, cm=f"cm-g{i}", group="llama")
            server.create(pod)
            pods.append(pod)
        return pods

    def test_full_gang_lands_atomically(self):
        """BASELINE config 4: a 4-pod v5p-16 gang lands on the 4 hosts of one
        slice, each host exactly one member, with worker env injected."""
        server = APIServer()
        for n in v5p_slice("pool-a"):
            server.create(n)
        sched = make_scheduler(server, registry=FakeRegistry(), with_gang=True)
        self._gang_setup(server, n_pods=4, min_member=4)
        sched.start()
        try:
            assert wait_until(
                lambda: all(
                    server.get("Pod", f"llama-{i}", "default").spec.node_name
                    for i in range(4)
                ),
                timeout=10,
            )
            nodes = {
                server.get("Pod", f"llama-{i}", "default").spec.node_name
                for i in range(4)
            }
            assert nodes == {f"pool-a-w{i}" for i in range(4)}  # one per host
            # Worker env: distinct ids 0..3, identical hostnames list.
            ids, hostlists = set(), set()
            for i in range(4):
                cm = server.get("ConfigMap", f"cm-g{i}", "default")
                ids.add(cm.data[ENV_WORKER_ID])
                hostlists.add(cm.data[ENV_WORKER_HOSTNAMES])
            assert ids == {"0", "1", "2", "3"}
            assert len(hostlists) == 1
            assert hostlists.pop().split(",") == [f"pool-a-w{i}" for i in range(4)]
        finally:
            sched.stop()

    def test_gang_prefers_single_slice_when_one_fits(self):
        """Multislice is a LAST resort: with a 2-host pool and a 4-host
        pool, a 3-member gang must land entirely in the pool that fits it
        — and get no multislice env."""
        server = APIServer()
        for n in v5p_slice("pool-a", n_hosts=2):
            server.create(n)
        for n in v5p_slice("pool-b", n_hosts=4):
            server.create(n)
        sched = make_scheduler(server, registry=FakeRegistry(), with_gang=True)
        self._gang_setup(server, n_pods=3, min_member=3)
        sched.start()
        try:
            assert wait_until(
                lambda: all(
                    server.get("Pod", f"llama-{i}", "default").spec.node_name
                    for i in range(3)),
                timeout=10,
            )
            nodes = [server.get("Pod", f"llama-{i}", "default").spec.node_name
                     for i in range(3)]
            assert all(n.startswith("pool-b") for n in nodes), nodes
            for i in range(3):
                cm = server.get("ConfigMap", f"cm-g{i}", "default")
                assert "TPU_SLICE_ID" not in cm.data
        finally:
            sched.stop()

    def test_gang_spans_two_slices_when_no_single_slice_fits(self):
        """VERDICT r4 missing #3: two 2-host pools, a 3-member gang — no
        single slice group can host it, so the gang spans groups (outer dp
        over DCN) and every member gets consistent multislice env:
        TPU_NUM_SLICES=2, TPU_SLICE_ID matching its node's group (sorted),
        TPU_SLICE_HOSTNAMES = its own slice's members, and slice-major
        contiguous worker ids."""
        server = APIServer()
        for n in v5p_slice("pool-a", n_hosts=2):
            server.create(n)
        for n in v5p_slice("pool-b", n_hosts=2):
            server.create(n)
        sched = make_scheduler(server, registry=FakeRegistry(), with_gang=True)
        self._gang_setup(server, n_pods=3, min_member=3)
        sched.start()
        try:
            assert wait_until(
                lambda: all(
                    server.get("Pod", f"llama-{i}", "default").spec.node_name
                    for i in range(3)),
                timeout=10,
            )
            node_of = {i: server.get("Pod", f"llama-{i}", "default").spec.node_name
                       for i in range(3)}
            groups_used = {n.rsplit("-w", 1)[0] for n in node_of.values()}
            assert groups_used == {"pool-a", "pool-b"}, node_of
            seen_ids, hostlists = set(), set()
            for i in range(3):
                cm = server.get("ConfigMap", f"cm-g{i}", "default")
                assert cm.data["TPU_NUM_SLICES"] == "2"
                my_group = node_of[i].rsplit("-w", 1)[0]
                expect_slice = {"pool-a": "0", "pool-b": "1"}[my_group]
                assert cm.data["TPU_SLICE_ID"] == expect_slice, cm.data
                # My slice's hostname set holds exactly the members bound
                # into my group.
                mine = sorted(n for n in node_of.values()
                              if n.startswith(my_group))
                assert sorted(cm.data["TPU_SLICE_HOSTNAMES"].split(",")) == mine
                seen_ids.add(cm.data[ENV_WORKER_ID])
                hostlists.add(cm.data[ENV_WORKER_HOSTNAMES])
            assert seen_ids == {"0", "1", "2"}
            assert len(hostlists) == 1        # identical rendezvous list
            # Slice-major worker ids: pool-a members numbered before pool-b.
            order = hostlists.pop().split(",")
            groups_in_order = [n.rsplit("-w", 1)[0] for n in order]
            assert groups_in_order == sorted(groups_in_order)
        finally:
            sched.stop()

    def test_statefulset_gang_gets_pod_dns_hostnames(self):
        """A placed gang must be able to RENDEZVOUS: StatefulSet members
        (hostname + subdomain set, as the controller does) get stable pod
        DNS <pod>.<svc>.<ns>.svc injected — NOT node names, which pods
        don't listen on without hostNetwork (VERDICT.md r3 missing #1).
        Worker order still follows the hosts' worker-index labels, so
        worker 0's DNS is the jax.distributed coordinator."""
        server = APIServer()
        for n in v5p_slice("pool-a"):
            server.create(n)
        sched = make_scheduler(server, registry=FakeRegistry(), with_gang=True)
        server.create(
            PodGroup(metadata=ObjectMeta(name="llama"), min_member=4,
                     topology="2x2x4", schedule_timeout_s=5.0))
        for i in range(4):
            server.create(ConfigMap(metadata=ObjectMeta(name=f"cm-g{i}"), data={}))
            pod = mk_pod(f"llama-{i}", chips=4, cm=f"cm-g{i}", group="llama")
            pod.spec.hostname = f"llama-{i}"
            pod.spec.subdomain = "llama-svc"
            server.create(pod)
        sched.start()
        try:
            assert wait_until(
                lambda: all(
                    server.get("Pod", f"llama-{i}", "default").spec.node_name
                    for i in range(4)), timeout=10)
            ids, hostlists = {}, set()
            for i in range(4):
                cm = server.get("ConfigMap", f"cm-g{i}", "default")
                ids[cm.data[ENV_WORKER_ID]] = i
                hostlists.add(cm.data[ENV_WORKER_HOSTNAMES])
            assert set(ids) == {"0", "1", "2", "3"}
            assert len(hostlists) == 1, "all members must agree on the list"
            addresses = hostlists.pop().split(",")
            # Every address is pod DNS, none is a node name.
            assert all(a.endswith(".llama-svc.default.svc") for a in addresses)
            # Order = host worker-index order: the member bound to w0 is
            # worker 0 and its DNS leads the list (the coordinator).
            w0_member = server.get("Pod", f"llama-{ids['0']}", "default")
            assert addresses[0] == (f"{w0_member.spec.hostname}."
                                    f"{w0_member.spec.subdomain}.default.svc")
            assert w0_member.spec.node_name == "pool-a-w0"
        finally:
            sched.stop()

    def test_capacity_short_gang_admits_zero(self):
        """3 hosts for a min_member=4 gang: nothing may bind; after the
        permit timeout all chips are credited back."""
        server = APIServer()
        for n in v5p_slice("pool-a", n_hosts=3):
            server.create(n)
        cfg = SchedulerConfig(
            backoff_initial_s=10, backoff_max_s=10, permit_timeout_s=0.4
        )
        sched = make_scheduler(
            server, registry=FakeRegistry(), with_gang=True, config=cfg
        )
        self._gang_setup(server, n_pods=4, min_member=4, timeout=0.4)
        sched.start()
        try:
            # Let the gang attempt, park, and time out.
            assert wait_until(
                lambda: not sched.handle._waiting
                and all(
                    not server.get("Pod", f"llama-{i}", "default").spec.node_name
                    for i in range(4)
                )
                and sum(i.requested_tpu for i in sched.cache.snapshot().values()) == 0,
                timeout=10,
            ), "gang must fully roll back: no binds, no leaked chips"
        finally:
            sched.stop()

    def test_gang_members_share_one_slice(self):
        """Two 2-host pools; a min_member=2 gang must not straddle pools."""
        server = APIServer()
        for n in v5p_slice("pool-a", n_hosts=2, topo="2x2x2") + v5p_slice(
            "pool-b", n_hosts=2, topo="2x2x2"
        ):
            server.create(n)
        sched = make_scheduler(server, registry=FakeRegistry(), with_gang=True)
        server.create(
            PodGroup(
                metadata=ObjectMeta(name="llama"),
                min_member=2,
                topology="2x2x2",
                schedule_timeout_s=5.0,
            )
        )
        for i in range(2):
            server.create(ConfigMap(metadata=ObjectMeta(name=f"cm-g{i}"), data={}))
            server.create(mk_pod(f"llama-{i}", chips=4, cm=f"cm-g{i}", group="llama"))
        sched.start()
        try:
            assert wait_until(
                lambda: all(
                    server.get("Pod", f"llama-{i}", "default").spec.node_name
                    for i in range(2)
                ),
                timeout=10,
            )
            pools = {
                server.get("Pod", f"llama-{i}", "default").spec.node_name.rsplit("-w", 1)[0]
                for i in range(2)
            }
            assert len(pools) == 1, f"gang straddled slices: {pools}"
        finally:
            sched.stop()

    def test_missing_group_is_unschedulable(self):
        server = APIServer()
        for n in v5p_slice("pool-a"):
            server.create(n)
        sched = make_scheduler(server, registry=FakeRegistry(), with_gang=True)
        server.create(mk_pod("orphan-0", chips=4, group="nosuch"))
        sched.start()
        try:
            assert wait_until(
                lambda: "not found"
                in sched.failure_reasons.get("default/orphan-0", "")
            )
        finally:
            sched.stop()


class TestPreemption:
    """PostFilter preemption — parity with the DefaultPreemption plugin the
    reference inherits whole from kube-scheduler v1.21
    (/root/reference/cmd/scheduler/main.go:20-22)."""

    def _full_cluster(self, server, owner="StatefulSet/low"):
        """One 8-chip node filled by two owned, low-priority pods."""
        server.create(mk_node("n1", chips=8))
        for i in range(2):
            server.create(ConfigMap(metadata=ObjectMeta(name=f"cm-l{i}"), data={}))
            server.create(mk_pod(f"low-{i}", chips=4, cm=f"cm-l{i}",
                                 priority=1, owner=owner))

    def test_high_priority_pod_preempts_on_full_cluster(self):
        server = APIServer()
        self._full_cluster(server)
        sched = make_scheduler(server, registry=FakeRegistry(),
                               with_preemption=True)
        sched.start()
        try:
            assert wait_until(
                lambda: all(
                    server.get("Pod", f"low-{i}", "default").spec.node_name
                    for i in range(2)), timeout=10)
            server.create(ConfigMap(metadata=ObjectMeta(name="cm-h"), data={}))
            server.create(mk_pod("high", chips=4, cm="cm-h", priority=100,
                                 owner="Job/high"))
            # The high-priority pod lands; exactly one victim was evicted
            # (one 4-chip eviction frees enough for the 4-chip preemptor).
            assert wait_until(
                lambda: server.get("Pod", "high", "default").spec.node_name,
                timeout=10)
            remaining = [p.metadata.name for p in server.list("Pod")]
            assert "high" in remaining
            assert len([n for n in remaining if n.startswith("low-")]) == 1
        finally:
            sched.stop()

    def test_priority_zero_never_preempts(self):
        server = APIServer()
        self._full_cluster(server)
        sched = make_scheduler(server, registry=FakeRegistry(),
                               with_preemption=True)
        sched.start()
        try:
            assert wait_until(
                lambda: all(
                    server.get("Pod", f"low-{i}", "default").spec.node_name
                    for i in range(2)), timeout=10)
            server.create(ConfigMap(metadata=ObjectMeta(name="cm-h"), data={}))
            server.create(mk_pod("meek", chips=4, cm="cm-h"))
            assert wait_until(
                lambda: "never preempt" in
                sched.failure_reasons.get("default/meek", "")
                or "nodes available" in
                sched.failure_reasons.get("default/meek", ""), timeout=5)
            time.sleep(0.3)
            assert not server.get("Pod", "meek", "default").spec.node_name
            assert len(server.list("Pod")) == 3  # nobody was evicted
        finally:
            sched.stop()

    def test_bare_and_gang_pods_are_never_victims(self):
        """Victims must have a controller owner and must not be gang
        members — a bare pod is unrecoverable, a gang member's eviction
        is the gang plugin's decision."""
        server = APIServer()
        server.create(mk_node("n1", chips=8))
        server.create(ConfigMap(metadata=ObjectMeta(name="cm-b"), data={}))
        server.create(mk_pod("bare", chips=4, cm="cm-b", priority=1))
        server.create(ConfigMap(metadata=ObjectMeta(name="cm-g"), data={}))
        server.create(
            PodGroup(metadata=ObjectMeta(name="g1"), min_member=1,
                     topology="2x4", schedule_timeout_s=5.0))
        gang_pod = mk_pod("gangster", chips=4, cm="cm-g", group="g1",
                          priority=1, owner="StatefulSet/g1")
        server.create(gang_pod)
        sched = make_scheduler(server, registry=FakeRegistry(),
                               with_preemption=True)
        sched.start()
        try:
            assert wait_until(
                lambda: all(p.spec.node_name for p in server.list("Pod")),
                timeout=10)
            server.create(ConfigMap(metadata=ObjectMeta(name="cm-h"), data={}))
            server.create(mk_pod("high", chips=4, cm="cm-h", priority=100))
            assert wait_until(
                lambda: "no node frees enough" in
                sched.failure_reasons.get("default/high", ""), timeout=5)
            assert len(server.list("Pod")) == 3  # nobody was evicted
        finally:
            sched.stop()


    def test_partition_aware_victim_selection(self):
        """Victims must free chips that form a usable hole: a node carved
        into two 2x2 partitions, each half-full, needs BOTH victims from ONE
        partition — evicting the two globally-lowest-priority pods (one per
        partition) frees 4 chips that no 4-chip pod can use. The chosen
        partition minimizes (victim count, summed priority)."""
        server = APIServer()
        server.create(mk_node("n1", chips=8,
                              annotations={ANN_SLICE_CONFIG: "2x2"}))
        # part-0: a1 (prio 1) + a2 (prio 5) → cost (2, 6)
        # part-1: b1 (prio 2) + b2 (prio 3) → cost (2, 5)  ← cheaper
        residents = [("a1", 1, "part-0/2x2"), ("a2", 5, "part-0/2x2"),
                     ("b1", 2, "part-1/2x2"), ("b2", 3, "part-1/2x2")]
        for name, prio, part in residents:
            server.create(ConfigMap(metadata=ObjectMeta(name=f"cm-{name}"),
                                    data={"n1": part}))
            server.create(mk_pod(name, chips=2, cm=f"cm-{name}",
                                 priority=prio, owner="StatefulSet/lows"))
            server.mutate("Pod", name, "default",
                          lambda p: setattr(p.spec, "node_name", "n1"))
        sched = make_scheduler(server, registry=FakeRegistry(),
                               with_preemption=True)
        sched.start()
        try:
            server.create(ConfigMap(metadata=ObjectMeta(name="cm-h"), data={}))
            server.create(mk_pod("high", chips=4, cm="cm-h", priority=100,
                                 owner="Job/high"))
            assert wait_until(
                lambda: server.get("Pod", "high", "default") is not None
                and server.get("Pod", "high", "default").spec.node_name,
                timeout=10)
            survivors = {p.metadata.name for p in server.list("Pod")}
            # The whole of part-1 went; part-0 (incl. lowest-priority a1)
            # is untouched — a cross-partition eviction would have left an
            # unusable 2+2 hole.
            assert survivors == {"a1", "a2", "high"}, survivors
        finally:
            sched.stop()

    def test_preemption_sees_through_rival_nomination(self):
        """A node whose raw free_tpu covers the preemptor but whose free
        chips are held by an equal-priority NOMINATION must still yield
        victims: evicting the low-priority residents helps around the
        reservation. Without the nomination-adjusted guard the node is
        skipped as 'capacity was never the problem' and the preemptor
        starves behind a stuck rival nomination."""
        server = APIServer()
        sched = make_scheduler(server, registry=FakeRegistry(),
                               with_preemption=True)
        cache = sched.handle.cache
        cache.add_node(mk_node("n1", chips=8))
        # 4 chips held by low-prio residents (bound), 4 chips raw-free but
        # reserved by rival Q's nomination (equal priority).
        for i in range(2):
            low = mk_pod(f"low-{i}", chips=2, priority=1,
                         owner="StatefulSet/lows")
            low.spec.node_name = "n1"
            server.create(low)
            cache.add_pod(low)
        rival = mk_pod("rival-q", chips=4, priority=100)
        sched.handle.nominator.nominate(rival, "n1")

        preempt = sched.profile.post_filter[0]
        pod = mk_pod("p", chips=4, priority=100, owner="Job/p")
        st = preempt.post_filter(CycleState(), pod, {"n1": "insufficient"})
        assert st.ok, st.message
        # Both residents evicted (their 4 chips form the only free-able
        # hole); P nominated alongside Q.
        assert [p.metadata.name for p in server.list("Pod")] == []
        assert sched.handle.nominator.node_for(pod.metadata.uid) == "n1"

    def test_partitioned_node_absorbs_nomination_elsewhere(self):
        """Partition-aware variant: a rival's 4-chip nomination can live in
        the raw-free partition, so evicting the other partition's residents
        still helps — debiting every partition by the full nominated count
        would wrongly conclude eviction is futile."""
        server = APIServer()
        sched = make_scheduler(server, registry=FakeRegistry(),
                               with_preemption=True)
        cache = sched.handle.cache
        cache.add_node(mk_node("n1", chips=8,
                               annotations={ANN_SLICE_CONFIG: "2x2"}))
        # part-1 holds two low-prio residents; part-0 is raw-free but
        # notionally reserved by rival Q's nomination.
        for i in range(2):
            server.create(ConfigMap(metadata=ObjectMeta(name=f"cm-pl{i}"),
                                    data={"n1": "part-1/2x2"}))
            low = mk_pod(f"plow-{i}", chips=2, cm=f"cm-pl{i}", priority=1,
                         owner="StatefulSet/lows")
            low.spec.node_name = "n1"
            server.create(low)
            cache.add_pod(low)
        rival = mk_pod("rival-q", chips=4, priority=100)
        sched.handle.nominator.nominate(rival, "n1")

        preempt = sched.profile.post_filter[0]
        pod = mk_pod("p", chips=4, priority=100, owner="Job/p")
        st = preempt.post_filter(CycleState(), pod, {"n1": "insufficient"})
        assert st.ok, st.message
        # Both part-1 residents evicted (rival-q itself was never created
        # on the server — only nominated).
        assert [p.metadata.name for p in server.list("Pod")] == []
        assert sched.handle.nominator.node_for(pod.metadata.uid) == "n1"

    def test_cross_partition_victims_make_room_for_nominee_and_preemptor(self):
        """Live-loop scenario: the scheduler spread one low-prio resident
        per partition, a rival's nomination holds 4 chips, the preemptor
        needs 4 — only evicting BOTH residents (one per partition) lets the
        nominee take one partition and the preemptor the other. Victim
        selection must plan the nominee's placement, not just this
        partition's hole."""
        server = APIServer()
        server.create(mk_node("n1", chips=8,
                              annotations={ANN_SLICE_CONFIG: "2x2"}))
        sched = make_scheduler(server, registry=FakeRegistry(),
                               with_preemption=True)
        for i in range(2):
            server.create(ConfigMap(metadata=ObjectMeta(name=f"cm-x{i}"),
                                    data={}))
            server.create(mk_pod(f"xlow-{i}", chips=2, cm=f"cm-x{i}",
                                 priority=1, owner="StatefulSet/lows"))
        sched.start()
        try:
            assert wait_until(lambda: all(
                p.spec.node_name for p in server.list("Pod")), timeout=10)
            rival = mk_pod("rival-q", chips=4, priority=100)
            sched.handle.nominator.nominate(rival, "n1")
            server.create(ConfigMap(metadata=ObjectMeta(name="cm-h"), data={}))
            server.create(mk_pod("high", chips=4, cm="cm-h", priority=100,
                                 owner="Job/high"))
            assert wait_until(lambda: any(
                p.metadata.name == "high" and p.spec.node_name
                for p in server.list("Pod")), timeout=10)
            assert sorted(p.metadata.name
                          for p in server.list("Pod")) == ["high"]
        finally:
            sched.stop()

    def test_no_eviction_when_nominee_cannot_fit_any_partition(self):
        """The nominee's chips must land in ONE partition. A 4-chip
        nomination on a board carved into 2-chip partitions can never be
        placed — victim selection must decline (no destructive deletes for
        an impossible plan), not count scattered free chips as if the
        nominee were divisible."""
        server = APIServer()
        sched = make_scheduler(server, registry=FakeRegistry(),
                               with_preemption=True)
        cache = sched.handle.cache
        cache.add_node(mk_node("n1", chips=8,
                               annotations={ANN_SLICE_CONFIG: "1x2"}))
        # One evictable 1-chip resident per 2-chip partition (1 free each).
        for i in range(4):
            server.create(ConfigMap(metadata=ObjectMeta(name=f"cm-s{i}"),
                                    data={"n1": f"part-{i}/1x2"}))
            low = mk_pod(f"slow-{i}", chips=1, cm=f"cm-s{i}", priority=1,
                         owner="StatefulSet/lows")
            low.spec.node_name = "n1"
            server.create(low)
            cache.add_pod(low)
        rival = mk_pod("rival-q", chips=4, priority=100)
        sched.handle.nominator.nominate(rival, "n1")

        preempt = sched.profile.post_filter[0]
        pod = mk_pod("p", chips=2, priority=100, owner="Job/p")
        st = preempt.post_filter(CycleState(), pod, {"n1": "insufficient"})
        assert not st.ok
        assert len(server.list("Pod")) == 4  # nobody was evicted

    def test_nomination_blocks_equal_priority_rivals(self):
        """After preemption, the freed chips are reserved for the nominee:
        an equal-priority rival's Filter counts them as taken, a
        higher-priority pod outranks the nomination (kube's
        addNominatedPods semantics)."""
        server = APIServer()
        sched = make_scheduler(server, registry=FakeRegistry())
        tpu_pl = sched.profile.filter[0]
        cache = sched.handle.cache
        cache.add_node(mk_node("n1", chips=8))
        nominee = mk_pod("nominee", chips=8, priority=100)
        sched.handle.nominator.nominate(nominee, "n1")
        info = cache.snapshot()["n1"]
        # Equal-priority rival: the nominated 8 chips are subtracted.
        rival = mk_pod("rival", chips=8, priority=100)
        st = tpu_pl.filter(CycleState(), rival, info)
        assert not st.ok and "insufficient" in st.message
        # The nominee itself is unaffected by its own nomination.
        assert tpu_pl.filter(CycleState(), nominee, info).ok
        # A higher-priority pod outranks the nomination.
        vip = mk_pod("vip", chips=8, priority=200)
        assert tpu_pl.filter(CycleState(), vip, info).ok
        # Binding clears the nomination: rival fits afterwards.
        sched.handle.nominator.clear(nominee.metadata.uid)
        assert tpu_pl.filter(CycleState(), rival, info).ok


class TestGangBarePodGuard:
    def test_collapse_spares_bare_members(self):
        """Post-quorum gang collapse evicts only members a controller will
        recreate; bare pods (no ownerReferences) are spared."""
        server = APIServer()
        server.create(
            PodGroup(metadata=ObjectMeta(name="g"), min_member=3,
                     topology="2x2x4", schedule_timeout_s=5.0))
        owned = mk_pod("owned", chips=4, group="g", owner="StatefulSet/g")
        bare = mk_pod("bare", chips=4, group="g")
        for p in (owned, bare):
            server.create(p)
        sched = make_scheduler(server, registry=FakeRegistry(), with_gang=True)
        # Bind both members directly (simulating the post-quorum window),
        # then collapse the gang.
        for name, node in (("owned", "w0"), ("bare", "w1")):
            server.mutate("Pod", name, "default",
                          lambda p, n=node: setattr(p.spec, "node_name", n))
        sched.factory.start()
        sched.factory.wait_for_cache_sync()
        gang = next(pl for pl in sched.profile.permit)
        gang._reject_gang("default/g", "test collapse")
        names = {p.metadata.name for p in server.list("Pod")}
        assert names == {"bare"}, names
        sched.stop()
