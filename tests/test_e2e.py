"""ALL-REAL end-to-end smoke (VERDICT r4 #10).

Every other integration test exercises one seam against a real counterpart
(live kvstored in test_registry, in-process gRPC in test_recommender, REST
fakekube in test_kubeapi, prober exec in test_agent). This one boots ALL of
them AT ONCE — the C++ kvstored, the C++ tpuprobe driven by real agent
Publishers, the gRPC recommender as a SUBPROCESS serving the seed matrices,
the fakekube apiserver as a subprocess, and the scheduler over the REST
adapter with the TPU + Gang plugins — then schedules a gang and an
SLO-scored singleton through every real seam simultaneously and asserts
the injected device env actually landed in the ConfigMaps. This is the
cross-component drift net the pairwise tests cannot catch.

Also reachable as ``make e2e``.
"""
import json
import os
import shutil
import socket
import subprocess
import sys
import time

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
KVSTORED = os.path.join(REPO, "native", "kvstore", "kvstored")
TPUPROBE = os.path.join(REPO, "native", "tpuprobe", "tpuprobe")
SEED_CONF = os.path.join(REPO, "k8s_gpu_scheduler_tpu", "recommender",
                         "data", "configurations_train.tsv")
SEED_INTF = os.path.join(REPO, "k8s_gpu_scheduler_tpu", "recommender",
                         "data", "interference_train.tsv")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait_port(port, timeout=15.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port), timeout=0.3).close()
            return True
        except OSError:
            time.sleep(0.05)
    return False


def _wait(fn, timeout=20.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


@pytest.mark.skipif(not (os.path.exists(KVSTORED) and os.path.exists(TPUPROBE)),
                    reason="native binaries not built (make native)")
def test_all_real_components_schedule_a_gang(tmp_path):
    procs = []
    try:
        # ---- 1. C++ kvstored (the registry) ---------------------------
        kv_port = _free_port()
        procs.append(subprocess.Popen(
            [KVSTORED, "--port", str(kv_port), "--requirepass", "pw"],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL))
        assert _wait_port(kv_port), "kvstored did not come up"

        # ---- 2. gRPC recommender subprocess on the seed matrices ------
        conf = tmp_path / "conf.tsv"
        intf = tmp_path / "intf.tsv"
        shutil.copy(SEED_CONF, conf)
        shutil.copy(SEED_INTF, intf)
        rec_port = _free_port()
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "k8s_gpu_scheduler_tpu.recommender.server"],
            cwd=REPO,
            env={**os.environ, "PORT": str(rec_port),
                 "CONFIGURATIONS_DATA_PATH": str(conf),
                 "INTERFERENCE_DATA_PATH": str(intf)},
            stdout=subprocess.DEVNULL))
        assert _wait_port(rec_port), "recommender did not come up"

        # ---- 3. fakekube apiserver subprocess -------------------------
        kube = subprocess.Popen(
            [sys.executable, "-m", "tests.fakekube", "--nodes", "2",
             "--slice-size", "2"],
            cwd=REPO, stdout=subprocess.PIPE, text=True)
        procs.append(kube)
        port_line = kube.stdout.readline().strip()
        assert port_line.startswith("PORT "), port_line

        # ---- 4. real agents: tpuprobe → Publisher → kvstored ----------
        from k8s_gpu_scheduler_tpu.agent import Publisher, Scraper
        from k8s_gpu_scheduler_tpu.registry.client import Client

        fake = tmp_path / "chips.json"
        fake.write_text(json.dumps({"chips": [
            {"device_id": i, "duty_cycle": 0.1 * i, "hbm_used": 1,
             "hbm_total": 16} for i in range(8)
        ]}))
        agent_reg = Client("127.0.0.1", kv_port, password="pw")
        for node in ("v5e-0", "v5e-1"):
            Publisher(
                agent_reg,
                scraper=Scraper(binary=TPUPROBE, fake_file=str(fake)),
                node_name=node, accelerator="tpu-v5-lite-podslice",
                topology="2x4",
            ).publish_once(force=True)

        # ---- 5. scheduler over REST with real registry + recommender --
        from k8s_gpu_scheduler_tpu.cluster.kubeapi import KubeAPIServer
        from k8s_gpu_scheduler_tpu.config import SchedulerConfig
        from k8s_gpu_scheduler_tpu.plugins import GangPlugin, TPUPlugin
        from k8s_gpu_scheduler_tpu.recommender.client import (
            Client as RecomClient,
        )
        from k8s_gpu_scheduler_tpu.sched import Profile, Scheduler

        server = KubeAPIServer(
            base_url=f"http://127.0.0.1:{port_line.split()[1]}")
        sched_reg = Client("127.0.0.1", kv_port, password="pw")
        recom = RecomClient("127.0.0.1", rec_port)
        sched = Scheduler(
            server, profile=Profile(),
            config=SchedulerConfig(backoff_initial_s=0.05, backoff_max_s=0.5))
        tpu = TPUPlugin(sched.handle, registry=sched_reg, recommender=recom)
        gang = GangPlugin(sched.handle)
        sched.profile = Profile(
            pre_filter=[tpu, gang], filter=[tpu, gang], score=[tpu, gang],
            reserve=[tpu, gang], permit=[gang], post_bind=[tpu, gang])

        # ---- 6. workloads: a 2-member gang + an SLO singleton ---------
        from k8s_gpu_scheduler_tpu.api.objects import (
            ConfigMap, ConfigMapRef, Container, EnvVar, ObjectMeta, Pod,
            PodGroup, PodSpec, ResourceRequirements, TPU_RESOURCE,
        )

        server.create(PodGroup(metadata=ObjectMeta(name="gang"),
                               min_member=2, topology="",
                               schedule_timeout_s=20.0))
        for i in range(2):
            server.create(ConfigMap(
                metadata=ObjectMeta(name=f"cm-gang-{i}"), data={}))
            server.create(Pod(
                metadata=ObjectMeta(name=f"gang-{i}",
                                    labels={"tpu.sched/pod-group": "gang"}),
                spec=PodSpec(containers=[Container(
                    env_from=[ConfigMapRef(f"cm-gang-{i}")],
                    resources=ResourceRequirements(
                        requests={TPU_RESOURCE: 4}),
                )])))
        server.create(ConfigMap(metadata=ObjectMeta(name="cm-solo"), data={}))
        server.create(Pod(
            metadata=ObjectMeta(name="llama3-8b-serve-0"),
            spec=PodSpec(containers=[Container(
                env=[EnvVar("SLO", "5"),
                     EnvVar("WORKLOAD_NAME", "llama3_8b_serve")],
                env_from=[ConfigMapRef("cm-solo")],
                resources=ResourceRequirements(requests={TPU_RESOURCE: 2}),
            )])))

        sched.start()
        try:
            assert _wait(lambda: all(
                server.get("Pod", n, "default").spec.node_name
                for n in ("gang-0", "gang-1", "llama3-8b-serve-0")
            )), "pods did not all bind through the real stack"

            # Gang: one member per host, consistent worker env.
            nodes = {server.get("Pod", f"gang-{i}", "default").spec.node_name
                     for i in range(2)}
            assert nodes == {"v5e-0", "v5e-1"}
            ids, hostlists = set(), set()
            for i in range(2):
                cm = server.get("ConfigMap", f"cm-gang-{i}", "default")
                assert "TPU_VISIBLE_CHIPS" in cm.data, cm.data
                assert cm.data["TPU_WORKER_COUNT"] == "2"
                ids.add(cm.data["TPU_WORKER_ID"])
                hostlists.add(cm.data["TPU_WORKER_HOSTNAMES"])
            assert ids == {"0", "1"}
            assert len(hostlists) == 1

            # Singleton: the device assignment landed via the REAL
            # agent-published inventory and the REAL gRPC predictions.
            cm = server.get("ConfigMap", "cm-solo", "default")
            assert "TPU_VISIBLE_CHIPS" in cm.data, cm.data
            assert cm.data["TPU_ACCELERATOR_TYPE"] == "tpu-v5-lite-podslice"
        finally:
            sched.stop()
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=5)
            except Exception:  # noqa: BLE001
                p.kill()
