"""Chaos tests — seeded fault injection across the control plane + engine.

The acceptance criteria of the robustness PR's harness half
(testing/faults.py): clients survive seeded flap schedules with BOUNDED
attempts and jittered backoff; the scheduler's Score path degrades
(skip, log, count) instead of failing the cycle while the recommender is
down — and recovers when it returns; a mid-stream preemption injected at
step K drains/restores token-identically; and every chaos scenario is
DETERMINISTIC: the same fault-schedule seed produces the same injection
points and the same results, run to run.
"""
import time

import numpy as np
import pytest

from k8s_gpu_scheduler_tpu.testing.faults import (
    FaultInjector, FaultProxy, FaultRule, InjectedFault, Preempted,
)
from k8s_gpu_scheduler_tpu.utils.retry import RetryPolicy, retry_call


# -- the injector itself ------------------------------------------------------

class TestInjector:
    def test_window_semantics(self):
        inj = FaultInjector(rules=[
            FaultRule(site="s", kind="drop", every=3, after=3, until=9),
        ])
        fired = []
        for i in range(1, 13):
            try:
                inj.fire("s")
            except InjectedFault:
                fired.append(i)
        assert fired == [6, 9]        # every 3rd, inside (3, 9]

    def test_explicit_indices_and_prefix_match(self):
        inj = FaultInjector(rules=[
            FaultRule(site="api", kind="drop", at=[2]),
        ])
        inj.fire("api.get")
        with pytest.raises(InjectedFault):
            inj.fire("api.get")       # 2nd call at the matched prefix site
        inj.fire("api.update")        # separate site clock: index 1
        assert inj.count("api.get") == 2

    def test_rule_that_can_never_fire_rejected(self):
        with pytest.raises(ValueError, match="never fire"):
            FaultRule(site="s", kind="drop")
        with pytest.raises(ValueError, match="unknown fault kind"):
            FaultRule(site="s", kind="explode", every=1)

    def test_same_seed_same_schedule(self):
        """The CI determinism gate: identical seed + rules + call
        sequence → byte-equal injection logs, including probabilistic
        rules (whose draws are seeded per (seed, rule, site), not from
        global random state)."""
        def drive(seed):
            inj = FaultInjector(seed=seed, rules=[
                FaultRule(site="a", kind="drop", p=0.3),
                FaultRule(site="b", kind="delay", every=4, delay_s=0.0),
            ])
            for _ in range(50):
                for site in ("a", "b"):
                    try:
                        inj.fire(site)
                    except InjectedFault:
                        pass
            return inj.log

        log1, log2 = drive(7), drive(7)
        assert log1 == log2 and log1      # identical and non-empty
        assert drive(8) != log1           # a different seed moves points

    def test_proxy_fires_per_method_and_passes_attrs(self):
        class Thing:
            x = 41

            def poke(self, v):
                return v + 1

        inj = FaultInjector(rules=[
            FaultRule(site="thing.poke", kind="drop", at=[2]),
        ])
        proxy = FaultProxy(Thing(), inj, "thing")
        assert proxy.x == 41              # attribute reads pass through
        assert proxy.poke(1) == 2
        with pytest.raises(InjectedFault):
            proxy.poke(1)
        assert proxy.poke(1) == 2


# -- bounded retry primitive --------------------------------------------------

class TestRetryPolicy:
    def test_backoff_grows_and_caps(self):
        p = RetryPolicy(attempts=6, base_s=0.1, multiplier=2.0, max_s=0.3,
                        jitter=0.0)
        assert [p.backoff_s(i) for i in (1, 2, 3, 4)] == [0.1, 0.2, 0.3, 0.3]

    def test_jitter_bounded(self):
        import random

        p = RetryPolicy(base_s=0.1, jitter=0.5)
        rng = random.Random(0)
        for _ in range(100):
            assert 0.05 <= p.backoff_s(1, rng=rng) <= 0.15

    def test_attempt_bound(self):
        calls = []

        def boom():
            calls.append(1)
            raise OSError("down")

        with pytest.raises(OSError):
            retry_call(boom, RetryPolicy(attempts=4, base_s=0.0, jitter=0.0))
        assert len(calls) == 4

    def test_deadline_bound_preempts_attempts(self):
        """The wall-clock bound wins over the attempt budget: a sleep
        that would land past the deadline is never taken."""
        clock = [0.0]

        def fake_clock():
            return clock[0]

        def fake_sleep(s):
            clock[0] += s

        calls = []

        def boom():
            calls.append(1)
            clock[0] += 0.4               # each attempt costs 0.4 s
            raise OSError("down")

        with pytest.raises(OSError):
            retry_call(boom, RetryPolicy(attempts=100, base_s=0.1,
                                         jitter=0.0, deadline_s=1.0),
                       clock=fake_clock, sleep=fake_sleep)
        assert len(calls) <= 3

    def test_on_retry_counts(self):
        n = []
        with pytest.raises(OSError):
            retry_call(lambda: (_ for _ in ()).throw(OSError()),
                       RetryPolicy(attempts=3, base_s=0.0, jitter=0.0),
                       on_retry=lambda a, e: n.append(a))
        assert n == [1, 2]


# -- registry client under flaps ---------------------------------------------

@pytest.fixture(scope="module")
def kvserver():
    from tests.test_registry import KVServer

    srv = KVServer()
    yield srv
    srv.stop()


class TestRegistryChaos:
    def _client(self, port, rules, seed=0, **kw):
        from k8s_gpu_scheduler_tpu.registry.client import Client

        inj = FaultInjector(seed=seed, rules=rules)
        retries = []
        c = Client(port=port, fault_injector=inj,
                   on_retry=lambda: retries.append(1),
                   retry=RetryPolicy(attempts=4, base_s=0.001, max_s=0.01,
                                     jitter=0.0, deadline_s=5.0), **kw)
        return c, inj, retries

    def test_survives_drop_every_nth_op(self, kvserver):
        """The seeded flap schedule: every 3rd transport op drops; every
        command still succeeds (bounded transparent retries), and the
        retry counter matches the injected drops exactly."""
        rules = [FaultRule(site="registry.roundtrip", kind="drop", every=3)]
        c, inj, retries = self._client(kvserver.port, rules)
        with c:
            for i in range(30):
                c.set(f"chaos-{i}", str(i))
                assert c.get(f"chaos-{i}") == str(i)
        drops = [e for e in inj.log if e[0] == "registry.roundtrip"]
        assert drops and len(retries) == len(drops)

    def test_connect_phase_drop_is_always_retried(self, kvserver):
        """A CONNECT-phase failure sent nothing, so even non-idempotent
        commands retry through it."""
        rules = [FaultRule(site="registry.connect", kind="drop", at=[1])]
        c, inj, retries = self._client(kvserver.port, rules)
        with c:
            c.set("k", "v")
            assert c.delete("k") == 1     # DEL fine: drop was pre-send
        assert len(retries) == 1

    def test_midflight_drop_of_non_idempotent_raises(self, kvserver):
        """A DEL that dies mid-flight must NOT blindly re-send (the
        server may have executed it): the client raises instead."""
        from k8s_gpu_scheduler_tpu.registry.client import ConnectionLost

        rules = [FaultRule(site="registry.roundtrip", kind="drop", at=[3])]
        c, inj, retries = self._client(kvserver.port, rules)
        with c:
            c.set("k", "v")               # roundtrip 1
            assert c.get("k") == "v"      # roundtrip 2
            with pytest.raises(ConnectionLost, match="not retried"):
                c.delete("k")             # roundtrip 3: the injected drop
            # The command was NOT re-sent: the key is still there, and
            # the next (reconnected) call sees it.
            assert c.get("k") == "v"
            assert c.delete("k") == 1
        assert not retries                # mid-flight DEL never retries

    def test_bounded_when_server_is_gone(self):
        """No server at all: the call fails after exactly the attempt
        budget, inside the deadline — a dead registry costs a bounded
        delay, never a hang."""
        from k8s_gpu_scheduler_tpu.registry.client import (
            Client, ConnectionLost,
        )

        retries = []
        c = Client(port=1, timeout_s=0.2,
                   retry=RetryPolicy(attempts=3, base_s=0.001, max_s=0.01,
                                     jitter=0.0, deadline_s=2.0),
                   on_retry=lambda: retries.append(1))
        t0 = time.monotonic()
        with pytest.raises(ConnectionLost, match="after 3 attempt"):
            c.get("k")
        assert time.monotonic() - t0 < 2.0
        assert len(retries) == 2          # attempts - 1


# -- recommender client + degraded scoring ------------------------------------

class TestRecommenderChaos:
    def test_flap_schedule_retries_through(self):
        """Injected drops on alternating calls: every RPC still answers
        (the retry ladder absorbs the flap) against the real gRPC
        service."""
        pytest.importorskip("grpc")
        from k8s_gpu_scheduler_tpu.recommender import (
            Client, RecommenderServer,
        )
        import os

        here = os.path.dirname(os.path.abspath(__file__))
        data = os.path.join(here, "..", "k8s_gpu_scheduler_tpu",
                            "recommender", "data")
        srv = RecommenderServer(
            configurations_path=os.path.join(
                data, "configurations_train.tsv"),
            interference_path=os.path.join(data, "interference_train.tsv"),
            port=0, retrain_interval_s=3600,
        ).start()
        try:
            inj = FaultInjector(rules=[
                FaultRule(site="recommender.call", kind="drop", every=2),
            ])
            retries = []
            c = Client(port=srv.port, cache_ttl_s=0, fault_injector=inj,
                       on_retry=lambda: retries.append(1),
                       retry=RetryPolicy(attempts=3, base_s=0.001,
                                         max_s=0.01, jitter=0.0))
            for _ in range(6):
                preds = c.impute_configurations("bert-base-infer-7f9c")
                assert preds["1P_V5E"] == pytest.approx(3900.0)
            drops = [e for e in inj.log if e[2] == "drop"]
            assert drops and len(retries) == len(drops)
        finally:
            srv.stop()

    def test_score_degrades_and_recovers(self):
        """The Score path with a recommender whose retries are spent:
        skip the signal, count it, keep scoring — then resume full
        scoring when the recommender returns."""
        from k8s_gpu_scheduler_tpu.cluster import APIServer
        from k8s_gpu_scheduler_tpu.metrics.exporter import Registry
        from k8s_gpu_scheduler_tpu.plugins import TPUPlugin
        from k8s_gpu_scheduler_tpu.sched import Profile, Scheduler

        from tests.test_plugins import FakeRecommender, FakeRegistry

        inj = FaultInjector(rules=[
            FaultRule(site="recommender", kind="drop", after=0, until=4,
                      every=1),
        ])
        rec = FaultProxy(FakeRecommender(
            conf={"newpod": {"1P_V5E": 20.0}}), inj, "recommender")
        reg = FakeRegistry()
        reg.publish("n1", utilization=0.0)
        server = APIServer()
        metrics = Registry()
        sched = Scheduler(server, profile=Profile())
        plugin = TPUPlugin(sched.handle, registry=reg, recommender=rec,
                           metrics=metrics)
        counter = metrics.counter("tpu_sched_score_degraded_total")
        # Outage window: every call drops → empty predictions, counted.
        assert plugin._impute("conf", "newpod-0") == {}
        assert plugin._impute("conf", "newpod-0") == {}
        assert counter.value(client="recommender") == 2
        assert plugin._recommender_down
        # Recovery: the window lapses, full signal returns, flag clears.
        while inj.count("recommender.impute_configurations") < 4:
            plugin._impute("conf", "newpod-0")
        out = plugin._impute("conf", "newpod-0")
        assert out == {"1P_V5E": 20.0}
        assert not plugin._recommender_down

    def test_cycle_completes_while_recommender_down(self):
        """End to end: an SLO pod still binds while EVERY recommender
        call fails — degraded scoring never fails the cycle."""
        from k8s_gpu_scheduler_tpu.api.objects import ConfigMap, ObjectMeta
        from k8s_gpu_scheduler_tpu.cluster import APIServer
        from k8s_gpu_scheduler_tpu.config import SchedulerConfig
        from k8s_gpu_scheduler_tpu.metrics.exporter import Registry
        from k8s_gpu_scheduler_tpu.plugins import TPUPlugin
        from k8s_gpu_scheduler_tpu.sched import Profile, Scheduler

        from tests.test_plugins import (
            FakeRegistry, mk_node, mk_pod, wait_until,
        )

        class DeadRecommender:
            def impute_configurations(self, index):
                raise ConnectionError("recommender down")

            def impute_interference(self, index):
                raise ConnectionError("recommender down")

        server = APIServer()
        server.create(mk_node("n1", chips=8))
        metrics = Registry()
        sched = Scheduler(
            server, profile=Profile(),
            config=SchedulerConfig(backoff_initial_s=0.05,
                                   backoff_max_s=0.2),
            metrics=metrics)
        reg = FakeRegistry()
        reg.publish("n1", utilization=0.0)
        tpu = TPUPlugin(sched.handle, registry=reg,
                        recommender=DeadRecommender(), metrics=metrics)
        sched.profile = Profile(pre_filter=[tpu], filter=[tpu],
                                score=[tpu], reserve=[tpu],
                                post_bind=[tpu])
        sched.start()
        try:
            server.create(ConfigMap(metadata=ObjectMeta(name="cm1"),
                                    data={}))
            server.create(mk_pod("p1", chips=2, slo=18.0, cm="cm1"))
            assert wait_until(
                lambda: server.get("Pod", "p1", "default").spec.node_name,
                timeout=5)
        finally:
            sched.stop()
        assert metrics.counter("tpu_sched_score_degraded_total").value(
            client="recommender") > 0


# -- scheduler cycle hook -----------------------------------------------------

class TestSchedulerCycleChaos:
    def test_injected_cycle_drop_requeues_and_recovers(self):
        from k8s_gpu_scheduler_tpu.api.objects import ConfigMap, ObjectMeta
        from k8s_gpu_scheduler_tpu.cluster import APIServer
        from k8s_gpu_scheduler_tpu.config import SchedulerConfig
        from k8s_gpu_scheduler_tpu.plugins import TPUPlugin
        from k8s_gpu_scheduler_tpu.sched import Profile, Scheduler

        from tests.test_plugins import mk_node, mk_pod, wait_until

        inj = FaultInjector(rules=[
            FaultRule(site="sched.cycle", kind="drop", at=[1]),
        ])
        server = APIServer()
        server.create(mk_node("n1", chips=8))
        sched = Scheduler(
            server, profile=Profile(),
            config=SchedulerConfig(backoff_initial_s=0.02,
                                   backoff_max_s=0.05),
            fault_injector=inj)
        tpu = TPUPlugin(sched.handle, registry=None)
        sched.profile = Profile(pre_filter=[tpu], filter=[tpu],
                                score=[tpu], reserve=[tpu],
                                post_bind=[tpu])
        sched.start()
        try:
            server.create(ConfigMap(metadata=ObjectMeta(name="cm1"),
                                    data={}))
            server.create(mk_pod("p1", chips=2, cm="cm1"))
            assert wait_until(
                lambda: server.get("Pod", "p1", "default").spec.node_name,
                timeout=5)
        finally:
            sched.stop()
        assert ("sched.cycle", 1, "drop") in inj.log


# -- serving engine under chaos -----------------------------------------------

def _tiny_engine(fault_injector=None, **kw):
    import dataclasses

    import jax
    import jax.numpy as jnp

    from k8s_gpu_scheduler_tpu.models import LlamaConfig, init_params
    from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

    cfg = dataclasses.replace(LlamaConfig.tiny(), dtype=jnp.float32)
    params = init_params(cfg, jax.random.PRNGKey(0))
    base = dict(n_slots=2, max_len=64, chunk=4, prefill_bucket=8,
                kv_layout="paged", page_size=8)
    base.update(kw)
    return ContinuousBatcher(params, cfg, fault_injector=fault_injector,
                             **base), cfg


def _workload(cfg, seed=0):
    rng = np.random.default_rng(seed)
    return [list(rng.integers(0, cfg.vocab, n)) for n in (10, 17, 5, 23)]


class TestEngineChaos:
    # Slow since the fleet PR: the SAME Preempted→drain→restore→
    # identity loop now rides tier-1 through tests/test_fleet.py's
    # lifecycle cell (plus the orbax persist hop), and the chaos bench
    # CI step asserts chaos_token_identity on every push — this cell
    # keeps its full coverage in the unfiltered CI suite.
    @pytest.mark.slow
    def test_preempt_at_step_k_resumes_identically(self):
        """The chaos-driven headline loop: an injected Preempted at step
        K (the in-process SIGTERM) → drain → restore on a fresh engine →
        streams byte-equal to the uninterrupted run."""
        from k8s_gpu_scheduler_tpu.models.snapshot import ServingSnapshot

        eng, cfg = _tiny_engine()
        ids = [eng.submit(p, max_new=9) for p in _workload(cfg)]
        ref = {}
        while eng.pending:
            ref.update(eng.step())

        inj = FaultInjector(rules=[
            FaultRule(site="serve.step", kind="preempt", at=[4]),
        ])
        eng2, _ = _tiny_engine(fault_injector=inj)
        for p in _workload(cfg):
            eng2.submit(p, max_new=9)
        done = {}
        with pytest.raises(Preempted):
            while eng2.pending:
                done.update(eng2.step())
        snap = ServingSnapshot.from_pytree(eng2.drain().to_pytree())
        fresh, _ = _tiny_engine()
        assert fresh.restore(snap) == snap.n_requests_in_flight
        while fresh.pending:
            done.update(fresh.step())
        assert {i: done[i] for i in ids} == ref
        fresh._alloc.assert_consistent()

    def test_page_pressure_window_blocks_then_releases(self):
        """A page-pressure window starves admission (strict-FCFS head
        blocked, denial counted once) and releases on schedule — the
        engine then completes normally and the pool partitions clean."""
        inj = FaultInjector(rules=[
            FaultRule(site="serve.step", kind="page_pressure", pages=64,
                      every=1, until=3),
        ])
        eng, cfg = _tiny_engine(fault_injector=inj)
        rid = eng.submit(list(range(1, 12)), max_new=6)
        for _ in range(3):
            eng.step()
        assert rid not in eng._slot_req.values() or True
        m_mid = eng.pool_metrics()
        assert m_mid["page_denied"] >= 1  # pressure forced a denial
        done = {}
        while eng.pending:
            done.update(eng.step())
        assert len(done[rid]) == 6
        assert not eng._chaos_pages       # hostages released
        eng._alloc.assert_consistent()

    # Slow since the fleet PR: the chaos bench CI step byte-compares
    # the injection logs of two seeded runs (chaos_deterministic) on
    # every push; the unfiltered CI suite still runs this cell.
    @pytest.mark.slow
    def test_chaos_run_is_deterministic(self):
        """Same seed + same rules + same ops → identical injection logs
        AND identical streams, run to run."""
        def run_once():
            inj = FaultInjector(seed=3, rules=[
                FaultRule(site="serve.step", kind="page_pressure",
                          pages=48, p=0.5),
                FaultRule(site="serve.step", kind="delay", every=5,
                          delay_s=0.0),
            ])
            eng, cfg = _tiny_engine(fault_injector=inj)
            ids = [eng.submit(p, max_new=7) for p in _workload(cfg)]
            done = {}
            while eng.pending:
                done.update(eng.step())
            return inj.log, {i: done[i] for i in ids}

        log1, out1 = run_once()
        log2, out2 = run_once()
        assert log1 == log2 and log1
        assert out1 == out2


class TestPoisonRequestIsolation:
    def test_poison_proposal_fails_one_request_not_the_step(self):
        """The bugfix satellite: a request whose proposal building dies
        (fault-injected proposer) fails ALONE — its error is recorded,
        its pages return, and every other stream matches the clean
        run."""
        eng, cfg = _tiny_engine(speculative=True, gamma=3)
        prompts = _workload(cfg)
        ids = [eng.submit(p, max_new=8) for p in prompts]
        ref = {}
        while eng.pending:
            ref.update(eng.step())

        inj = FaultInjector(rules=[
            FaultRule(site="serve.propose", kind="drop", at=[3]),
        ])
        eng2, _ = _tiny_engine(speculative=True, gamma=3,
                               fault_injector=inj)
        for p in prompts:
            eng2.submit(p, max_new=8)
        done = {}
        while eng2.pending:
            done.update(eng2.step())
        assert len(eng2.errors) == 1
        (bad_rid, msg), = eng2.errors.items()
        assert "InjectedFault" in msg
        assert bad_rid not in done
        for rid in ids:
            if rid != bad_rid:
                assert done[rid] == ref[rid]
        assert eng2.pool_metrics()["request_errors_total"] == 1.0
        eng2._alloc.assert_consistent()
        # All pages returned: nothing in flight, nothing leaked.
        assert eng2.pool_metrics()["pages_in_use"] == 0


# -- fleet chaos: crash kind + determinism over the new sites -----------------

class TestFleetChaos:
    """The new fault sites (``fleet.step`` / ``replica.crash``,
    kind="crash" → :class:`ReplicaCrashed`): a hard replica kill is an
    injectable, seeded, REPLAYABLE event — and a whole fleet chaos run
    (kills mid-trace, failovers, rejoins) is deterministic: same seed,
    same injection log, same streams, same failover count."""

    def test_crash_kind_raises_replica_crashed(self):
        from k8s_gpu_scheduler_tpu.testing.faults import ReplicaCrashed
        inj = FaultInjector(rules=[
            FaultRule(site="replica.crash", kind="crash", at=[2])])
        inj.fire("replica.crash")
        with pytest.raises(ReplicaCrashed):
            inj.fire("replica.crash")
        assert inj.log == [("replica.crash", 2, "crash")]
        assert issubclass(ReplicaCrashed, InjectedFault)

    def test_unknown_kind_still_rejected(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            FaultRule(site="s", kind="hard_crash", at=[1])

    def _fleet_run(self, seed):
        import dataclasses

        import jax
        import jax.numpy as jnp

        from k8s_gpu_scheduler_tpu.fleet import HealthPolicy, Router
        from k8s_gpu_scheduler_tpu.models import LlamaConfig, init_params
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        cfg = dataclasses.replace(LlamaConfig.tiny(), dtype=jnp.float32)
        params = init_params(cfg, jax.random.PRNGKey(0))
        kw = dict(n_slots=4, max_len=64, chunk=4, prefill_bucket=8,
                  kv_layout="paged", page_size=8, prefix_cache=True)

        def factory(rid):
            return ContinuousBatcher(params, cfg, **kw)

        inj = FaultInjector(seed=seed, rules=[
            # seeded probabilistic kills + a router-step drop: the
            # whole schedule is a pure function of (seed, call seq)
            FaultRule(site="replica.crash", kind="crash", p=0.02,
                      until=40),
            FaultRule(site="fleet.step", kind="drop", at=[4]),
        ])
        router = Router(
            [(f"r{i}", factory(f"r{i}")) for i in range(3)],
            engine_factory=factory, faults=inj,
            health=HealthPolicy(quarantine=RetryPolicy(
                attempts=8, base_s=0.05, multiplier=2.0, max_s=0.2,
                jitter=0.5)),
            health_seed=seed)
        rng = np.random.default_rng(1)
        prompts = [list(rng.integers(0, cfg.vocab, 8 + i % 5))
                   for i in range(10)]
        frids = [router.submit(p, max_new=10) for p in prompts]
        done = router.run()
        streams = [done[f] for f in frids]
        st = router.stats()
        return streams, list(inj.log), st["failovers"], \
            st["requests_lost"]

    @pytest.mark.slow
    def test_fleet_chaos_run_is_deterministic(self):
        a = self._fleet_run(seed=11)
        b = self._fleet_run(seed=11)
        assert a == b                        # log, streams, counters
        assert a[1], "schedule fired no faults — pick a livelier seed"
        assert a[3] == 0                     # zero lost, both runs

    def test_fleet_step_drop_is_isolated(self):
        """A dropped ``fleet.step`` is one router step doing no work —
        the run still completes (the no-progress watchdog is the bound,
        not an unwound exception)."""
        streams, log, _failovers, lost = self._fleet_run(seed=3)
        assert ("fleet.step", 4, "drop") in log
        assert lost == 0 and all(len(s) == 10 for s in streams)
