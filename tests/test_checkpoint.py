"""Checkpoint/resume tests: save → kill → fresh process-equivalent restore
continues training bit-exactly; retention honors max_to_keep."""
import jax
import jax.numpy as jnp
import optax
import pytest

from k8s_gpu_scheduler_tpu.models import LlamaConfig, init_params, make_train_step
from k8s_gpu_scheduler_tpu.utils.checkpoint import TrainCheckpointer


def toy_state(cfg):
    params = init_params(cfg, jax.random.PRNGKey(0))
    opt = optax.adamw(3e-3)
    return params, opt, opt.init(params)


class TestTrainCheckpointer:
    def test_resume_is_bit_exact(self, tmp_path):
        cfg = LlamaConfig.tiny()
        params, opt, opt_state = toy_state(cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab)
        batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, axis=1)}
        step_fn = make_train_step(cfg, None, opt)

        # Train 3 steps, checkpoint, train 2 more — remember the losses.
        for _ in range(3):
            params, opt_state, _ = step_fn(params, opt_state, batch)
        with TrainCheckpointer(str(tmp_path / "ck")) as ck:
            ck.save(3, {"params": params, "opt_state": opt_state})
        ref_losses = []
        for _ in range(2):
            params, opt_state, loss = step_fn(params, opt_state, batch)
            ref_losses.append(float(loss))

        # "Crash": fresh checkpointer + freshly-initialized state restores
        # step 3 and must reproduce the exact same continuation.
        params2, opt2, opt_state2 = toy_state(cfg)
        with TrainCheckpointer(str(tmp_path / "ck")) as ck2:
            step, state = ck2.restore_or(lambda: {
                "params": params2, "opt_state": opt_state2,
            })
        assert step == 3
        params2 = state["params"]
        opt_state2 = state["opt_state"]
        # Structure preserved through the restore template (NamedTuples,
        # not lists) — a list here would break optax.update.
        assert type(opt_state2) is type(opt_state)
        step_fn2 = make_train_step(cfg, None, opt)
        got_losses = []
        for _ in range(2):
            params2, opt_state2, loss = step_fn2(params2, opt_state2, batch)
            got_losses.append(float(loss))
        assert got_losses == ref_losses

    def test_restore_or_fresh_when_empty(self, tmp_path):
        with TrainCheckpointer(str(tmp_path / "empty")) as ck:
            step, state = ck.restore_or(lambda: {"x": jnp.ones((2,))})
        assert step == 0
        assert float(state["x"].sum()) == 2.0

    def test_max_to_keep_retention(self, tmp_path):
        with TrainCheckpointer(str(tmp_path / "ret"), max_to_keep=2) as ck:
            for s in (1, 2, 3, 4):
                ck.save(s, {"s": jnp.array(s)})
            ck.wait()
            assert ck.latest_step() == 4
            restored = ck.restore(4)
            assert int(restored["s"]) == 4
            # Oldest steps were garbage-collected.
            with pytest.raises(Exception):
                ck.restore(1)

    def test_restore_missing_raises(self, tmp_path):
        with TrainCheckpointer(str(tmp_path / "none")) as ck:
            with pytest.raises(FileNotFoundError):
                ck.restore()
