"""obs/ tests — span API, flight recorder, exporters, and the lifecycle
threading through engine and scheduler.

Coverage map (ISSUE 7 satellite): span nesting/threading, ring-buffer
drop-oldest under overflow, Perfetto export schema validation,
request-id correlation scheduler→engine, flight-recorder round trip
through ServingSnapshot/orbax, tracing-on token identity vs tracing-off,
plus the injected-clock seams (virtual time in the queue/backoff, the
wall-clock-jump immunity the Clock sweep bought).
"""
import dataclasses
import json
import threading

import pytest

import jax
import jax.numpy as jnp
import numpy as np

from k8s_gpu_scheduler_tpu.models import LlamaConfig, init_params
from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher
from k8s_gpu_scheduler_tpu.models.snapshot import ServingSnapshot
from k8s_gpu_scheduler_tpu.obs import (
    FlightRecorder, Tracer, VirtualClock, to_perfetto, validate_perfetto,
    write_perfetto,
)


# -- span API -----------------------------------------------------------------

class TestTracer:
    def test_span_records_interval_and_attrs(self):
        clk = VirtualClock()
        tr = Tracer(clock=clk)
        with tr.span("decode_chunk", lane="engine", rid="r1") as attrs:
            clk.advance(0.5)
            attrs["tokens"] = 8
        (s,) = tr.spans()
        assert s.name == "decode_chunk" and s.rid == "r1"
        assert s.duration == pytest.approx(0.5)
        assert s.attrs["tokens"] == 8

    def test_span_nesting_intervals_nest(self):
        clk = VirtualClock()
        tr = Tracer(clock=clk)
        with tr.span("outer", lane="engine"):
            clk.advance(0.1)
            with tr.span("inner", lane="engine"):
                clk.advance(0.2)
            clk.advance(0.1)
        inner = tr.spans(name="inner")[0]
        outer = tr.spans(name="outer")[0]
        # Same lane, nested intervals — what renders nested in Perfetto.
        assert outer.t0 <= inner.t0 <= inner.t1 <= outer.t1
        assert inner.duration == pytest.approx(0.2)
        assert outer.duration == pytest.approx(0.4)

    def test_threaded_appends_all_land_with_thread_ids(self):
        tr = Tracer(capacity=4096)

        def worker(i):
            for j in range(50):
                tr.record(f"w{i}", 0.0, 1.0, lane="engine")

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        spans = tr.spans()
        assert len(spans) == 400 and tr.dropped == 0
        assert len({s.name for s in spans}) == 8

    def test_ring_drop_oldest_under_overflow(self):
        tr = Tracer(capacity=16)
        for i in range(40):
            tr.record(f"s{i}", float(i), float(i) + 1)
        spans = tr.spans()
        assert len(spans) == 16
        assert tr.dropped == 24
        # OLDEST dropped: the surviving window is the most recent 16.
        assert [s.name for s in spans] == [f"s{i}" for i in range(24, 40)]

    def test_disabled_tracer_records_nothing(self):
        tr = Tracer(enabled=False)
        with tr.span("x"):
            pass
        tr.record("y", 0.0, 1.0)
        tr.event("z")
        assert len(tr) == 0

    def test_event_is_zero_duration(self):
        clk = VirtualClock()
        tr = Tracer(clock=clk)
        tr.event("page_shortage", rid="r9", need=4, free=0)
        (s,) = tr.spans()
        assert s.duration == 0.0 and s.attrs["need"] == 4


class TestFlightRecorder:
    def test_ring_drop_oldest_and_seq_monotonic(self):
        fr = FlightRecorder(capacity=8, clock=VirtualClock())
        for i in range(20):
            fr.record("decode", tokens=i)
        recs = fr.records()
        assert len(recs) == 8 and fr.dropped == 12
        assert [r["tokens"] for r in recs] == list(range(12, 20))
        assert [r["seq"] for r in recs] == list(range(12, 20))

    def test_seed_continues_seq_past_payload(self):
        fr = FlightRecorder(capacity=8)
        for i in range(5):
            fr.record("decode")
        payload = fr.to_payload()
        fresh = FlightRecorder(capacity=8)
        fresh.seed(payload)
        rec = fresh.record("restore")
        assert rec["seq"] == 5
        assert [r["kind"] for r in fresh.records()] == ["decode"] * 5 + [
            "restore"]

    def test_seed_trims_to_capacity_newest_kept(self):
        fr = FlightRecorder(capacity=32)
        for i in range(10):
            fr.record("decode", i=i)
        small = FlightRecorder(capacity=4)
        small.seed(fr.to_payload())
        assert [r["i"] for r in small.records()] == [6, 7, 8, 9]


# -- Perfetto export ----------------------------------------------------------

class TestPerfettoExport:
    def _spans(self):
        clk = VirtualClock()
        tr = Tracer(clock=clk)
        tr.record("queue", 0.0, 1.0, lane="engine", rid="req-0")
        tr.record("decode_chunk", 1.0, 2.0, lane="slot0", rid="req-0",
                  tokens=8)
        tr.record("sched_cycle", 0.5, 0.7, lane="sched", rid="pod-a")
        return tr.spans()

    def test_export_passes_schema_and_loads_as_json(self, tmp_path):
        path = str(tmp_path / "trace.json")
        doc = write_perfetto(self._spans(), path)
        assert validate_perfetto(doc) == []
        with open(path) as fh:
            reloaded = json.load(fh)
        assert validate_perfetto(reloaded) == []

    def test_lanes_split_engine_vs_control_plane(self):
        doc = to_perfetto(self._spans())
        names = {(e["args"]["name"]): (e["pid"], e["tid"])
                 for e in doc["traceEvents"]
                 if e["ph"] == "M" and e["name"] == "thread_name"}
        assert names["engine"][0] == names["slot0"][0]      # one process
        assert names["sched"][0] != names["engine"][0]      # the other
        xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert {e["name"] for e in xs} == {"queue", "decode_chunk",
                                           "sched_cycle"}
        # Timestamps rebase to the earliest span.
        assert min(e["ts"] for e in xs) == 0.0

    def test_rid_rides_args(self):
        doc = to_perfetto(self._spans())
        ev = next(e for e in doc["traceEvents"]
                  if e.get("name") == "decode_chunk")
        assert ev["args"]["rid"] == "req-0" and ev["args"]["tokens"] == 8

    def test_validator_rejects_malformed_docs(self):
        assert validate_perfetto([]) != []
        assert validate_perfetto({"traceEvents": []}) != []
        assert validate_perfetto(
            {"traceEvents": [{"ph": "X", "name": "a", "ts": -1.0,
                              "dur": 1.0, "pid": 1, "tid": 1}]}) != []
        # Complete event on a lane with no thread_name metadata.
        bad = {"traceEvents": [
            {"name": "a", "ph": "X", "ts": 0.0, "dur": 1.0,
             "pid": 9, "tid": 9}]}
        assert any("thread_name" in p for p in validate_perfetto(bad))


# -- engine lifecycle ---------------------------------------------------------

def _tiny_cfg():
    return dataclasses.replace(LlamaConfig.tiny(), dtype=jnp.float32)


@pytest.fixture(scope="module")
def tiny_model():
    cfg = _tiny_cfg()
    return cfg, init_params(cfg, jax.random.PRNGKey(0))


def _engine(params, cfg, **kw):
    base = dict(n_slots=2, max_len=64, chunk=4, prefill_bucket=8,
                kv_layout="paged", page_size=8)
    base.update(kw)
    return ContinuousBatcher(params, cfg, **base)


class TestEngineTracing:
    def test_all_phases_and_timeline(self, tiny_model):
        cfg, params = tiny_model[0], tiny_model[1]
        tr = Tracer()
        eng = _engine(params, cfg, tracer=tr, prefix_cache=True)
        rid = eng.submit(list(range(1, 12)), max_new=14,
                         trace_id="pod-a")
        eng.submit(list(range(1, 9)), max_new=4)
        eng.run()
        names = {s.name for s in tr.spans()}
        assert {"queue", "admit", "prefill", "decode_chunk",
                "reap"} <= names
        tl = eng.request_timeline("pod-a")
        assert tl is not None and tl["request"] == rid
        assert tl["phases"]["queue"]["count"] == 1
        assert tl["phases"]["decode_chunk"]["count"] >= 3    # 14 tok / 4
        assert tl["phases"]["reap"]["count"] == 1
        # Same summary by integer id.
        assert eng.request_timeline(rid)["phases"] == tl["phases"]
        # Per-slot lanes exist next to the engine lane.
        lanes = {s.lane for s in tr.spans()}
        assert "engine" in lanes and any(l.startswith("slot")
                                         for l in lanes)

    def test_speculative_verify_and_rewind_spans(self, tiny_model):
        cfg, params = tiny_model[0], tiny_model[1]
        tr = Tracer()
        eng = _engine(params, cfg, tracer=tr, speculative=True, gamma=2,
                      max_len=96)
        rng = np.random.default_rng(0)
        eng.submit(list(rng.integers(0, cfg.vocab, 5)), max_new=6)
        eng.run()
        names = {s.name for s in tr.spans()}
        assert "verify" in names
        # Random prompts reject essentially everything — rewinds fire.
        assert "rewind" in names
        rew = tr.spans(name="rewind")[0]
        assert rew.attrs["rewound"] >= 1

    def test_page_shortage_event_fires_once_per_denial(self, tiny_model):
        cfg, params = tiny_model[0], tiny_model[1]
        tr = Tracer()
        # Pool sized so the second request cannot admit while the first
        # holds its reservation.
        eng = _engine(params, cfg, tracer=tr, n_slots=2, n_pages=1 + 3)
        eng.submit(list(range(1, 9)), max_new=8)
        eng.submit(list(range(1, 9)), max_new=8)
        eng.run()
        events = tr.spans(name="page_shortage")
        assert len(events) >= 1
        # Deduped like the denial metric: blocked-head retries do not
        # spam one event per step.
        assert len(events) <= 2

    def test_tracing_on_token_identity_vs_off(self, tiny_model):
        cfg, params = tiny_model[0], tiny_model[1]
        rng = np.random.default_rng(1)
        prompts = [list(rng.integers(0, cfg.vocab, 4 + i)) for i in range(5)]

        def drive(tracer):
            eng = _engine(params, cfg, tracer=tracer, prefix_cache=True)
            ids = [eng.submit(p, max_new=6) for p in prompts]
            done = eng.run()
            return [done[i] for i in ids]

        assert drive(None) == drive(Tracer())

    def test_virtual_clock_drives_queue_wait_exactly(self, tiny_model):
        cfg, params = tiny_model[0], tiny_model[1]
        clk = VirtualClock()
        tr = Tracer(clock=clk)
        eng = _engine(params, cfg, tracer=tr, clock=clk)
        eng.submit(list(range(1, 6)), max_new=2)
        clk.advance(3.0)                        # the request waits 3 s
        eng.run()
        (q,) = tr.spans(name="queue")
        assert q.duration == pytest.approx(3.0)

    def test_tracer_buffer_never_grows_past_capacity(self, tiny_model):
        cfg, params = tiny_model[0], tiny_model[1]
        tr = Tracer(capacity=8)
        eng = _engine(params, cfg, tracer=tr)
        for i in range(4):
            eng.submit(list(range(1, 6)), max_new=6)
        eng.run()
        assert len(tr) == 8 and tr.dropped > 0


class TestFlightIntoSnapshot:
    def test_flight_round_trip_through_snapshot(self, tiny_model):
        cfg, params = tiny_model[0], tiny_model[1]
        eng = _engine(params, cfg)
        eng.submit(list(range(1, 12)), max_new=10)
        for _ in range(2):
            eng.step()
        pre = eng._flight.records()
        assert [r["kind"] for r in pre].count("decode") >= 2
        snap = eng.drain()
        assert [r["kind"] for r in snap.flight][-1] == "drain"
        # Codec round trip preserves the ring verbatim.
        snap2 = ServingSnapshot.from_pytree(snap.to_pytree())
        assert snap2.flight == snap.flight
        fresh = _engine(params, cfg)
        fresh.restore(snap2)
        kinds = [r["kind"] for r in fresh._flight.records()]
        assert kinds[-1] == "restore" and "drain" in kinds
        assert "decode" in kinds                 # pre-preemption history
        # Seq continues across the boundary — one ordered timeline.
        seqs = [r["seq"] for r in fresh._flight.records()]
        assert seqs == sorted(seqs)
        fresh.run()
        fresh._alloc.assert_consistent()

    def test_flight_round_trip_through_orbax(self, tiny_model, tmp_path):
        pytest.importorskip("orbax.checkpoint")
        from k8s_gpu_scheduler_tpu.utils.checkpoint import TrainCheckpointer

        cfg, params = tiny_model[0], tiny_model[1]
        eng = _engine(params, cfg)
        eng.submit(list(range(1, 10)), max_new=8)
        eng.step()
        snap = eng.drain()
        with TrainCheckpointer(str(tmp_path / "snap")) as ckpt:
            assert ckpt.save(0, snap.to_pytree(), force=True)
        with TrainCheckpointer(str(tmp_path / "snap")) as ckpt:
            tree = ckpt.restore(0)
        restored = ServingSnapshot.from_pytree(tree)
        assert restored.flight == snap.flight
        assert [r["kind"] for r in restored.flight][-1] == "drain"

    def test_old_snapshot_without_flight_loads(self, tiny_model):
        cfg, params = tiny_model[0], tiny_model[1]
        eng = _engine(params, cfg)
        eng.submit(list(range(1, 10)), max_new=6)
        eng.step()
        snap = eng.drain()
        tree = snap.to_pytree()
        # Simulate a pre-obs snapshot: strip the flight key from the doc.
        doc = json.loads(bytes(np.asarray(tree["meta_json"]).tobytes()))
        doc.pop("flight")
        tree["meta_json"] = np.frombuffer(
            json.dumps(doc).encode(), dtype=np.uint8).copy()
        snap2 = ServingSnapshot.from_pytree(tree)
        assert snap2.flight == []
        fresh = _engine(params, cfg)
        fresh.restore(snap2)                     # restores cleanly
        fresh.run()


# -- scheduler-plane correlation ----------------------------------------------

class TestCrossPlaneCorrelation:
    def test_request_id_correlates_scheduler_to_engine(self, tiny_model):
        from k8s_gpu_scheduler_tpu.cluster import APIServer, Descriptor
        from k8s_gpu_scheduler_tpu.config import SchedulerConfig
        from k8s_gpu_scheduler_tpu.sched.framework import Profile
        from k8s_gpu_scheduler_tpu.sched.scheduler import Scheduler
        from tests.test_sched import (
            FitFilter, MostFreeScore, mk_node, mk_pod, wait_until,
        )

        tr = Tracer()                            # ONE tracer, both planes
        server = APIServer()
        d = Descriptor(server)
        server.create(mk_node("n1", chips=8))
        sched = Scheduler(
            server, profile=Profile(),
            config=SchedulerConfig(backoff_initial_s=0.05,
                                   backoff_max_s=0.2),
            tracer=tr)
        sched.profile = Profile(filter=[FitFilter()],
                                score=[MostFreeScore(sched.cache)])
        sched.start()
        try:
            d.create_pod(mk_pod("serve-req-7", chips=2))
            assert wait_until(
                lambda: d.get_pod("serve-req-7").spec.node_name == "n1")
        finally:
            sched.stop()

        cfg, params = tiny_model[0], tiny_model[1]
        eng = _engine(params, cfg, tracer=tr)
        eng.submit(list(range(1, 8)), max_new=4, trace_id="serve-req-7")
        eng.run()

        mine = tr.spans(rid="serve-req-7")
        lanes = {s.lane for s in mine}
        names = {s.name for s in mine}
        # The SAME rid strings a timeline view groups on, across planes:
        # control-plane spans (sched lane) and engine spans correlate.
        assert "sched" in lanes and "engine" in lanes
        assert {"sched_queue", "sched_cycle", "sched_bind"} <= names
        assert {"queue", "admit", "prefill"} <= names
        # And the export keeps them on separate process groups.
        doc = to_perfetto(mine)
        assert validate_perfetto(doc) == []
        pids = {e["pid"] for e in doc["traceEvents"] if e["ph"] == "X"}
        assert len(pids) == 2

    def test_scheduler_queue_wait_on_virtual_clock(self):
        from k8s_gpu_scheduler_tpu.api.objects import Pod
        from k8s_gpu_scheduler_tpu.sched.queue import SchedulingQueue
        from tests.test_sched import mk_pod

        clk = VirtualClock()
        q = SchedulingQueue(backoff_initial_s=1.0, backoff_max_s=4.0,
                            clock=clk)
        pod = mk_pod("p")
        q.add(pod)
        clk.advance(2.5)
        popped = q.pop(timeout=0)
        assert popped is not None
        t0 = q.enqueued_at(pod.metadata.uid)
        assert clk.monotonic() - t0 == pytest.approx(2.5)
        # Backoff keeps the FIRST enqueue time (queue wait is e2e).
        q.add_unschedulable(pod)
        clk.advance(1.0)
        assert q.pop(timeout=0) is not None      # backoff elapsed on clk
        assert q.enqueued_at(pod.metadata.uid) == t0
