"""Golden wire ARTIFACTS from prior-PR formats still load (tier-1).

tests/data/wire/ holds serialized bytes as older PRs wrote them — a
pre-tiering engine's mid-run drain (no ``tier_keys`` in the meta doc),
a PR 8 registry heartbeat (no backlog/tp/weight/dram fields, 2-tuple
digest), a PR 10 journal doc — and this suite proves TODAY's decoders
load all three token/byte-faithfully. This turns the scattered
back-compat pins (the payload_shape default, the tier sidecar default,
the default-0 summary fields) into one fixture-driven contract: break
any decoder default and a committed artifact stops loading right here,
before graftcheck pass 11 (``wirecompat``) even diffs the schemas.

The last test closes the loop with the pass itself: a deliberately
field-dropped live schema must trip ``wire-break`` against the
committed golden — the audit is what turns "we remembered a default"
into "removal cannot land without a golden bump".

Regeneration policy: tests/data/wire/regen.py — these artifacts stand
in for bytes already on the wire at upgrade time and should essentially
never change (unlike the schema goldens, which ``--update-schemas``
moves whenever the format evolves deliberately).
"""
import copy
import dataclasses
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_scheduler_tpu.analysis.wirecompat import (
    diff_schemas, extract_schemas, load_golden,
)
from k8s_gpu_scheduler_tpu.fleet.journal import RequestJournal
from k8s_gpu_scheduler_tpu.fleet.summary import (
    ReplicaSummary, prefix_match_parts,
)
from k8s_gpu_scheduler_tpu.models.snapshot import ServingSnapshot

WIRE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "data", "wire")


def load_snapshot_tree():
    with np.load(os.path.join(WIRE, "snapshot_pre_tiering.npz")) as z:
        return {k: z[k] for k in z.files}


def load_expect():
    with open(os.path.join(WIRE, "snapshot_pre_tiering.expect.json")) as fh:
        return json.load(fh)


class TestPreTieringSnapshot:
    def test_doc_is_really_pre_tiering(self):
        """The fixture's meta doc must NOT carry ``tier_keys`` — if a
        regen accidentally writes today's format, this suite would be
        vacuously green."""
        tree = load_snapshot_tree()
        doc = json.loads(bytes(np.asarray(tree["meta_json"])).decode())
        assert "tier_keys" not in doc
        assert doc["version"] == 1

    def test_loads_byte_faithfully(self):
        """Every field decodes to the recorded drain-time value; the page
        payload is byte-identical (sha256); the PR 16 tier sidecar
        defaults to empty."""
        import hashlib

        snap = ServingSnapshot.from_pytree(load_snapshot_tree())
        exp = load_expect()
        assert snap.fingerprint == exp["fingerprint"]
        assert [int(p) for p in snap.page_ids] == exp["page_ids"]
        assert [int(x) for x in snap.lens] == exp["lens"]
        assert snap.n_requests_in_flight == exp["n_requests_in_flight"]
        assert [[r, p] for r, p in snap.queue] == exp["queue"]
        assert {str(r): ts for r, ts in snap.out.items()} == exp["out"]
        assert {str(r): b for r, b in snap.budgets.items()} == exp["budgets"]
        assert len(snap.tree_paths) == exp["n_tree_paths"]
        payload = hashlib.sha256(
            np.ascontiguousarray(snap.k_pages).tobytes()
            + np.ascontiguousarray(snap.v_pages).tobytes()).hexdigest()
        assert payload == exp["payload_sha256"]
        # Fields the doc never carried take their decoder defaults.
        assert snap.tier_keys == [] and snap.tier_k is None
        assert snap.partial is False

    def test_restores_into_live_engine_token_faithfully(self):
        """The real upgrade path: today's engine absorbs the pre-tiering
        drain — fingerprint accepted, every interrupted request resumes,
        and each finished stream STARTS WITH the tokens the drained
        engine had already emitted (the journal/replay invariant: bytes
        a client was sent must survive the format boundary)."""
        from k8s_gpu_scheduler_tpu.models import LlamaConfig, init_params
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        exp = load_expect()
        cfg = dataclasses.replace(
            LlamaConfig.tiny(), dtype=jnp.float32,
            decode_attn=exp["cfg"]["decode_attn"])
        params = init_params(cfg, jax.random.PRNGKey(exp["seed"]))
        eng = ContinuousBatcher(params, cfg, **exp["engine_kw"])
        snap = ServingSnapshot.from_pytree(load_snapshot_tree())
        resumed = eng.restore(snap)
        assert resumed == exp["n_requests_in_flight"]
        done = {}
        while eng.pending:
            done.update(eng.step())
        expected_rids = {int(r) for r in exp["out"]} \
            | {r for r, _ in exp["queue"]}
        assert expected_rids <= set(done)
        for r, emitted in exp["out"].items():
            assert done[int(r)][:len(emitted)] == emitted

    def test_max_new_respected_after_restore(self):
        """Budgets survive the boundary: no stream exceeds the recorded
        remaining budget + already-emitted tokens."""
        exp = load_expect()
        snap = ServingSnapshot.from_pytree(load_snapshot_tree())
        for r, b in snap.budgets.items():
            emitted = len(snap.out.get(r, []))
            assert emitted + b <= exp["max_new"]


class TestPr8Summary:
    def test_loads_with_defaults(self):
        with open(os.path.join(WIRE, "summary_pr8.json")) as fh:
            raw = fh.read()
        d = json.loads(raw)
        # The fixture must really be the PR 8 field set.
        assert "tp" not in d and "prefill_backlog_tokens" not in d
        s = ReplicaSummary.from_json(raw)
        assert (s.replica, s.fleet, s.seq) == ("replica-3", "serving", 17)
        assert (s.pages_total, s.pages_free, s.active_slots, s.queued) \
            == (64, 12, 3, 2)
        # Post-PR-8 fields take their documented defaults.
        assert s.prefill_backlog_tokens == 0 and s.tp == 1
        assert s.weight_device_bytes == 0 and s.dram_cached_pages == 0
        assert s.digest == [([101, 102, 103, 104, 105, 106, 107, 108], 16),
                            ([201, 202, 203, 204], 8)]

    def test_two_tuple_digest_scores_fully_resident(self):
        """A pre-tiering digest entry (2-tuple) must keep scoring as
        fully resident — the router's demoted-match discount never
        penalizes an un-upgraded replica."""
        with open(os.path.join(WIRE, "summary_pr8.json")) as fh:
            s = ReplicaSummary.from_json(fh.read())
        prompt = [101, 102, 103, 104, 105, 106, 107, 108, 9, 9, 9]
        match, resident = prefix_match_parts(prompt, s.digest, s.page_size)
        assert match == 8 and resident == 8


class TestPr10Journal:
    def _tree(self):
        with open(os.path.join(WIRE, "journal_pr10.json")) as fh:
            doc = json.load(fh)
        raw = json.dumps(doc, sort_keys=True).encode()
        return {"journal_doc": np.frombuffer(raw, np.uint8).copy()}

    def test_loads_faithfully(self):
        j = RequestJournal.from_pytree(self._tree())
        assert len(j) == 2 and j.open_frids() == [2, 4]
        assert j.delivered_tokens_total == 23
        assert j.closed == {"done": 2, "error": 0, "expired": 1}
        e = j.entry(2)
        assert e.delivered == [41, 42, 43] and e.failovers == 1
        assert e.remaining == 5 and e.replica == "replica-0"
        # The orphan (replica None) is exactly what failover replays.
        assert [o.frid for o in j.inflight_on(None)] == [4]

    def test_round_trips_through_todays_encoder(self):
        j = RequestJournal.from_pytree(self._tree())
        j2 = RequestJournal.from_pytree(j.to_pytree())
        assert j2.open_frids() == j.open_frids()
        assert j2.stream(2) == j.stream(2)


class TestWireBreakTripsAudit:
    """The acceptance-criterion loop: drop a field from the live schema
    and the committed golden must trip ``wire-break`` — for a JSON field
    and for a pytree leaf."""

    @pytest.fixture(scope="class")
    def live(self):
        schemas = extract_schemas()
        assert set(schemas) == {"serving_snapshot", "replica_summary",
                                "request_journal"}
        return schemas

    def test_clean_schemas_match_committed_goldens(self, live):
        for name, schema in live.items():
            assert diff_schemas(name, schema, load_golden(name)) == []

    def test_dropped_summary_field_trips_wire_break(self, live):
        broken = copy.deepcopy(live["replica_summary"])
        del broken["groups"]["json"]["pages_free"]
        rules = {f.rule for f in diff_schemas(
            "replica_summary", broken, load_golden("replica_summary"))}
        assert "wire-break" in rules and "wire-golden-stale" in rules

    def test_dropped_snapshot_leaf_trips_wire_break(self, live):
        broken = copy.deepcopy(live["serving_snapshot"])
        del broken["groups"]["pytree"]["meta_json"]
        rules = {f.rule for f in diff_schemas(
            "serving_snapshot", broken, load_golden("serving_snapshot"))}
        assert "wire-break" in rules

    def test_new_no_default_field_trips_wire_no_default(self, live):
        broken = copy.deepcopy(live["request_journal"])
        broken["groups"]["entry"]["tenant"] = {"type": "str",
                                               "required": True}
        rules = {f.rule for f in diff_schemas(
            "request_journal", broken, load_golden("request_journal"))}
        assert "wire-no-default" in rules
        # The benign variant only goes stale — add-with-default is the
        # sanctioned evolution path.
        benign = copy.deepcopy(live["request_journal"])
        benign["groups"]["entry"]["tenant"] = {"type": "str",
                                               "required": False}
        rules = {f.rule for f in diff_schemas(
            "request_journal", benign, load_golden("request_journal"))}
        assert rules == {"wire-golden-stale"}
