"""Speculative decoding inside the paged continuous batcher — the
multi-query verify kernel (ops/decode_attention.paged_verify_attention)
and the batcher's propose/verify/accept loop (serving.ContinuousBatcher
speculative=True).

Two layers of parity:

- **Kernel**: the verify window's per-row causal bound must reproduce the
  dense multi-query reference AND, row by row, the t = 1 decode kernel at
  that row's own length — the property that makes the speculative stream
  equal the greedy stream (each window row accumulates exactly what its
  own decode step would).
- **Engine**: `speculative=True` must emit BYTE-IDENTICAL token streams
  to plain greedy paged decode across dense/fused verify × f32/bf16 ×
  int8-KV × prefix-cache on/off — including steps where every proposal
  is rejected (0-accept full rewinds). Rewind is a lens clamp inside the
  slot's own reserved pages: the allocator invariant must hold through
  exhaustion/EOS/reject-all waves, and mounted shared prefix pages must
  come back byte-identical (the graftcheck alias scenario's contract,
  re-checked here at the engine level).

Everything runs in interpret mode on CPU (ops.pallas_interpret); the
same kernel compiles on TPU, where `bench.py --leg speculative` measures
the accept-rate/tok-s story.
"""
import dataclasses
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_scheduler_tpu.ops import (
    contiguous_as_paged, dense_verify_reference, paged_decode_attention,
    paged_verify_attention, verify_plan,
)

TOL = {jnp.float32: 3e-6, jnp.bfloat16: 4e-2}


def maxdiff(a, b):
    return float(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32)).max())


def verify_case(B=2, H=8, Hkv=4, hd=32, S=64, ps=16, t=4,
                dtype=jnp.float32, seed=0, perm_seed=0):
    """A t-row verify window plus a contiguous cache and its paged twin
    (pages scattered through a random permutation, page 0 null)."""
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (B, t, H, hd), dtype)
    k = jax.random.normal(ks[1], (B, S, Hkv, hd), dtype)
    v = jax.random.normal(ks[2], (B, S, Hkv, hd), dtype)
    nb = S // ps
    n_pages = 1 + B * nb
    rng = np.random.default_rng(perm_seed)
    table = rng.permutation(np.arange(1, n_pages)).reshape(B, nb)
    kp = jnp.zeros((n_pages, ps, Hkv, hd), dtype)
    vp = jnp.zeros((n_pages, ps, Hkv, hd), dtype)
    kp = kp.at[table].set(k.reshape(B, nb, ps, Hkv, hd))
    vp = vp.at[table].set(v.reshape(B, nb, ps, Hkv, hd))
    return q, k, v, kp, vp, jnp.asarray(table, jnp.int32)


class TestVerifyPlan:
    def test_plan_legality(self):
        assert verify_plan(4, 16, 4) == 1
        assert verify_plan(8, 16, 3) == 8          # splits engage at >= 8
        assert verify_plan(8, 16, 3, n_splits=2) == 2
        assert verify_plan(8, 16, 0) is None       # empty window
        assert verify_plan(8, 12, 3) is None       # non-pow2 page
        assert verify_plan(8, 16, 3, n_splits=3) is None


class TestVerifyKernelParity:
    """paged_verify_attention against the dense multi-query reference and
    the t = 1 decode kernel."""

    # f32 cells pin the math per GQA ratio; bf16 re-runs (same code path,
    # looser tolerance) ride the unfiltered CI suite only.
    @pytest.mark.parametrize("dtype,hkv", [
        (jnp.float32, 8), (jnp.float32, 4), (jnp.float32, 2),
        pytest.param(jnp.bfloat16, 8, marks=pytest.mark.slow),
        pytest.param(jnp.bfloat16, 4, marks=pytest.mark.slow),
        pytest.param(jnp.bfloat16, 2, marks=pytest.mark.slow),
    ])
    def test_gqa_and_dtypes(self, dtype, hkv):
        q, k, v, kp, vp, table = verify_case(Hkv=hkv, dtype=dtype)
        lens = jnp.asarray([17, 33], jnp.int32)
        ref = dense_verify_reference(q, k, v, lens)
        got = paged_verify_attention(q, kp, vp, table, lens)
        assert got.shape == q.shape
        assert maxdiff(got, ref) < TOL[dtype]

    def test_rows_match_the_decode_kernel(self):
        """THE speculative-correctness property: window row i must equal
        the t = 1 paged decode kernel at lengths + i + 1 — what that
        token's own greedy decode step would have computed."""
        q, k, v, kp, vp, table = verify_case(t=4)
        lens = jnp.asarray([9, 30], jnp.int32)
        got = paged_verify_attention(q, kp, vp, table, lens)
        for i in range(q.shape[1]):
            one = paged_decode_attention(q[:, i], kp, vp, table,
                                         lens + i + 1)
            assert maxdiff(got[:, i], one) < 1e-6, i

    def test_t1_is_the_decode_kernel(self):
        q, k, v, kp, vp, table = verify_case(t=1)
        lens = jnp.asarray([11, 25], jnp.int32)
        got = paged_verify_attention(q, kp, vp, table, lens)
        one = paged_decode_attention(q[:, 0], kp, vp, table, lens + 1)
        assert maxdiff(got[:, 0], one) < 1e-6

    def test_int8_kv(self):
        from k8s_gpu_scheduler_tpu.models.serving import _kv_quant

        q, k, v, kp, vp, table = verify_case(t=3, dtype=jnp.bfloat16)
        k8, ks = _kv_quant(k)
        v8, vs = _kv_quant(v)
        nb = k.shape[1] // kp.shape[1]
        B, ps = q.shape[0], kp.shape[1]

        def pool_of(a):
            out = jnp.zeros((kp.shape[0], ps) + a.shape[2:], a.dtype)
            return out.at[table].set(a.reshape(B, nb, ps, *a.shape[2:]))

        lens = jnp.asarray([9, 30], jnp.int32)
        ref = dense_verify_reference(q, k8, v8, lens, k_scale=ks,
                                     v_scale=vs)
        got = paged_verify_attention(q, pool_of(k8), pool_of(v8), table,
                                     lens, k_scale=pool_of(ks),
                                     v_scale=pool_of(vs))
        assert maxdiff(got, ref) < TOL[jnp.bfloat16]

    def test_split_k(self):
        q, k, v, kp, vp, table = verify_case(S=128, ps=16, t=3)
        lens = jnp.asarray([77, 121], jnp.int32)
        ref = dense_verify_reference(q, k, v, lens)
        for ns in (1, 8):                    # no-split vs max-split ends
            got = paged_verify_attention(q, kp, vp, table, lens,
                                         n_splits=ns)
            assert maxdiff(got, ref) < 1e-5, ns

    def test_stale_overshoot_rows_are_masked(self):
        """Garbage above each row's bound — exactly what rejected
        overshoot leaves behind — must never contribute."""
        q, k, v, kp, vp, table = verify_case(t=3)
        lens = jnp.asarray([10, 20], jnp.int32)
        ref = paged_verify_attention(q, kp, vp, table, lens)
        # Poison every row past lens + t (committed + window).
        S, ps = k.shape[1], kp.shape[1]
        col = np.arange(S)
        poison = np.zeros((2, S), bool)
        for b in range(2):
            poison[b] = col >= int(lens[b]) + q.shape[1]
        nb = S // ps
        pb = jnp.asarray(poison).reshape(2, nb, ps)
        kp2 = kp.at[table].set(
            jnp.where(pb[..., None, None], 1e4,
                      kp[table].reshape(2, nb, ps, *kp.shape[2:])))
        vp2 = vp.at[table].set(
            jnp.where(pb[..., None, None], -1e4,
                      vp[table].reshape(2, nb, ps, *vp.shape[2:])))
        got = paged_verify_attention(q, kp2, vp2, table, lens)
        assert maxdiff(got, ref) < 1e-6

    def test_contiguous_view_and_cached_attention(self):
        """contiguous_as_paged + the kernel == cached_attention's dense
        t > 1 mask — the generate_speculative fused verify route."""
        from k8s_gpu_scheduler_tpu.models.serving import cached_attention

        q, k, v, _, _, _ = verify_case(t=3)
        pos = jnp.int32(21)
        ref = cached_attention(q, k, v, pos, impl="dense")
        kp, table = contiguous_as_paged(k, 16)
        vp, _ = contiguous_as_paged(v, 16)
        got = paged_verify_attention(q, kp, vp, table, pos)
        assert maxdiff(got, ref) < 1e-5
        # And the routed call itself takes the kernel path.
        routed = cached_attention(q, k, v, pos, impl="fused", verify=True)
        assert maxdiff(routed, ref) < 1e-5

    def test_bad_shapes_raise(self):
        q, k, v, kp, vp, table = verify_case(t=0 + 2)
        with pytest.raises(ValueError, match="GQA"):
            paged_verify_attention(q[:, :, :6], kp, vp, table, 4)
        with pytest.raises(ValueError, match="block_table"):
            paged_verify_attention(q, kp, vp, table[0], 4)
        with pytest.raises(ValueError, match="verify blocking"):
            paged_verify_attention(q[:, :0], kp, vp, table, 4)


class TestSpeculativeEngine:
    """speculative=True vs plain greedy paged decode: byte-identical
    streams, free rewind, clean page accounting."""

    def _cfg(self, dtype=jnp.float32, **kw):
        from k8s_gpu_scheduler_tpu.models import LlamaConfig

        return dataclasses.replace(LlamaConfig.tiny(), dtype=dtype, **kw)

    def _prompts(self, cfg, seed=0):
        rng = np.random.default_rng(seed)
        phrase = list(rng.integers(0, cfg.vocab, 4))
        # A cycling prompt (accepts fire once the greedy stream loops),
        # a random prompt (proposals mostly rejected), and a short
        # phrase copy exercising slot reuse — all within ONE prefill
        # bucket rung, so each engine compiles a single prefill program.
        return [phrase * 2, list(rng.integers(0, cfg.vocab, 7)),
                phrase + phrase[:1]]

    def _run(self, cfg, prompts, spec, max_new=8, gamma=3, **kw):
        from k8s_gpu_scheduler_tpu.models import init_params
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        params = init_params(cfg, jax.random.PRNGKey(0))
        eng = ContinuousBatcher(params, cfg, n_slots=2, max_len=64,
                                chunk=4, prefill_bucket=8,
                                kv_layout="paged", page_size=8,
                                speculative=spec, gamma=gamma, **kw)
        ids = [eng.submit(p, max_new=max_new) for p in prompts]
        done = eng.run()
        return [done[i] for i in ids], eng

    # f32 grid: the all-reference (dense, bf16-free pool) corner stays
    # in tier-1; the mixed cells AND the f32 fused-int8 corner ride the
    # unfiltered CI suite (budget note on the bf16 grid — the bf16
    # fused-int8 cell below is the production combination and keeps
    # that corner tier-1; PR 15 budget).
    @pytest.mark.parametrize("impl,kvd", [
        ("dense", None),
        pytest.param("dense", "int8", marks=pytest.mark.slow),
        pytest.param("fused", None, marks=pytest.mark.slow),
        pytest.param("fused", "int8", marks=pytest.mark.slow),
    ])
    def test_spec_matches_greedy_paged_f32(self, impl, kvd):
        cfg = self._cfg(decode_attn=impl)
        prompts = self._prompts(cfg)
        spec, eng = self._run(cfg, prompts, True, kv_dtype=kvd)
        plain, _ = self._run(cfg, prompts, False, kv_dtype=kvd)
        assert spec == plain
        m = eng.pool_metrics()
        # Every page back at drain; the allocator invariant holds.
        assert m["pages_in_use"] == 0 and m["pages_free"] == m["pages_total"]
        eng._alloc.assert_consistent()

    # bf16 grid: the fused+int8 cell (the production combination) stays
    # in tier-1; the remaining bf16 cells ride the full CI suite only
    # (tier-1 runs under a wall-clock budget with -m 'not slow').
    @pytest.mark.parametrize("impl,kvd", [
        pytest.param("dense", None, marks=pytest.mark.slow),
        pytest.param("dense", "int8", marks=pytest.mark.slow),
        pytest.param("fused", None, marks=pytest.mark.slow),
        ("fused", "int8"),
    ])
    def test_spec_matches_greedy_paged_bf16(self, impl, kvd):
        cfg = self._cfg(dtype=jnp.bfloat16, decode_attn=impl)
        prompts = self._prompts(cfg)
        spec, _ = self._run(cfg, prompts, True, kv_dtype=kvd)
        plain, _ = self._run(cfg, prompts, False, kv_dtype=kvd)
        assert spec == plain

    @pytest.mark.parametrize("impl", [
        pytest.param("dense", marks=pytest.mark.slow), "fused"])
    def test_spec_matches_greedy_with_prefix_cache(self, impl):
        """Speculation × shared-prefix reuse: hit admissions mount shared
        pages read-only, the verify overshoot lands past them, and the
        streams still match plain greedy paged decode with the same
        cache."""
        cfg = self._cfg(decode_attn=impl)
        rng = np.random.default_rng(1)
        sysp = list(rng.integers(0, cfg.vocab, 8))
        prompts = [sysp + list(rng.integers(0, cfg.vocab, 3)),
                   sysp + list(rng.integers(0, cfg.vocab, 4)),
                   sysp + list(rng.integers(0, cfg.vocab, 2))]
        spec, eng = self._run(cfg, prompts, True, kv_dtype="int8",
                              prefix_cache=True)
        plain, _ = self._run(cfg, prompts, False, kv_dtype="int8",
                             prefix_cache=True)
        assert spec == plain
        m = eng.pool_metrics()
        assert m["prefix_hit_tokens"] > 0, "scenario must actually hit"
        eng._alloc.assert_consistent()

    def test_speculation_actually_accepts(self):
        """On a long self-repetitive stream the verify must commit more
        than one token per dispatch — the whole point of the PR."""
        cfg = self._cfg(decode_attn="fused")
        rng = np.random.default_rng(0)
        phrase = list(rng.integers(0, cfg.vocab, 4))
        prompts = [phrase * 2, phrase + phrase[:1]]
        spec, eng = self._run(cfg, prompts, True, max_new=28)
        plain, _ = self._run(cfg, prompts, False, max_new=28)
        assert spec == plain
        m = eng.pool_metrics()
        assert m["spec_accept_rate"] > 0
        assert m["spec_tokens_per_dispatch"] > 1.0

    def test_zero_accept_full_rewinds(self):
        """A stream with no usable bigram repeats rejects every proposal:
        one token per dispatch, gamma rows rewound per slot-step, output
        still byte-identical."""
        cfg = self._cfg(decode_attn="fused")
        rng = np.random.default_rng(2)
        prompts = [list(rng.integers(0, cfg.vocab, 5))]
        spec, eng = self._run(cfg, prompts, True, max_new=5, gamma=3)
        plain, _ = self._run(cfg, prompts, False, max_new=5, gamma=3)
        assert spec == plain
        m = eng.pool_metrics()
        assert m["spec_accept_rate"] == 0.0, \
            "prompt drew a usable bigram repeat — reseed to restore the " \
            "zero-accept regime this test exists to cover"
        assert m["spec_tokens_per_dispatch"] == 1.0
        assert m["spec_rewound_tokens_total"] == 3 * 4  # gamma × steps
        eng._alloc.assert_consistent()

    def test_eos_reap_and_exhaustion_keep_pool_consistent(self):
        """Rewind never leaks or double-frees a page: a tight pool under
        page-exhaustion blocking, EOS early reaps mid-speculation, and
        reject-heavy random streams must leave the allocator partitioned
        clean after every step and fully free at drain."""
        from k8s_gpu_scheduler_tpu.models import init_params
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        cfg = self._cfg(decode_attn="fused")
        params = init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.default_rng(3)
        # Three slots over a pool that can only back two live requests:
        # the third admission finds a FREE SLOT but no pages — the
        # page-denied path — until a finish returns its reservation.
        eng = ContinuousBatcher(params, cfg, n_slots=3, max_len=64,
                                chunk=4, prefill_bucket=8,
                                kv_layout="paged", page_size=8,
                                n_pages=7, speculative=True, gamma=3,
                                eos_id=7)
        for plen, mn in ((5, 9), (11, 5), (3, 13), (7, 3)):
            eng.submit(rng.integers(0, cfg.vocab, plen), max_new=mn)
        denied_seen = False
        while eng.pending:
            eng.step()
            eng._alloc.assert_consistent()
            denied_seen = denied_seen or \
                eng.pool_metrics()["page_denied"] > 0
        m = eng.pool_metrics()
        assert m["pages_in_use"] == 0 and m["pages_free"] == m["pages_total"]
        assert denied_seen, "pool was never exhausted; shrink n_pages"

    @pytest.mark.slow   # tier-1 covers this via the graftcheck alias
    def test_shared_prefix_pages_survive_overshoot(self):
        """Engine-level alias check (the graftcheck scenario
        `batcher_verify_paged_prefix` pins the same contract in tier-1
        through tests/test_analysis.py): the bytes of a mounted shared
        page are identical before and after speculative steps that verify
        (and rewind) on top of it."""
        from k8s_gpu_scheduler_tpu.models import init_params
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        cfg = self._cfg(decode_attn="fused")
        params = init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.default_rng(4)
        sysp = list(rng.integers(0, cfg.vocab, 8))
        eng = ContinuousBatcher(params, cfg, n_slots=2, max_len=64,
                                chunk=4, prefill_bucket=8, kv_dtype="int8",
                                kv_layout="paged", page_size=8,
                                prefix_cache=True, speculative=True,
                                gamma=3)
        eng.submit(sysp + list(rng.integers(0, cfg.vocab, 3)), max_new=2)
        eng.run()                          # reap donates the prefix page
        eng.submit(sysp + list(rng.integers(0, cfg.vocab, 4)), max_new=9)
        eng.step()                         # mounts the shared page
        shared = sorted({p for pages in eng._slot_shared.values()
                         for p in pages})
        assert shared
        before = np.array(np.asarray(eng._k)[:, shared])
        before_s = np.array(np.asarray(eng._ks)[:, shared])
        while eng.pending:
            eng.step()
        assert np.array_equal(np.asarray(eng._k)[:, shared], before)
        assert np.array_equal(np.asarray(eng._ks)[:, shared], before_s)
        eng._alloc.assert_consistent()

    def test_rejects_bad_configs(self):
        from k8s_gpu_scheduler_tpu.models import LlamaConfig, init_params
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        cfg = LlamaConfig.tiny()
        params = init_params(cfg, jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="paged"):
            ContinuousBatcher(params, cfg, speculative=True)
        # The greedy-only guard is GONE: temperature > 0 speculation now
        # routes through the rejection-sampling verify and must construct.
        eng = ContinuousBatcher(params, cfg, kv_layout="paged",
                                max_len=64, speculative=True,
                                temperature=0.7)
        assert eng.spec and eng.temperature == 0.7
        with pytest.raises(ValueError, match="gamma"):
            ContinuousBatcher(params, cfg, kv_layout="paged",
                              max_len=64, speculative=True, gamma=0)
        with pytest.raises(ValueError, match="proposer"):
            ContinuousBatcher(params, cfg, kv_layout="paged",
                              max_len=64, speculative=True,
                              proposer="markov-chain")

    def test_overshoot_reserved_in_admission_math(self):
        """submit() must account the gamma overshoot: a request that fits
        without speculation is rejected when the verify window would walk
        past the cache capacity."""
        from k8s_gpu_scheduler_tpu.models import LlamaConfig, init_params
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        cfg = LlamaConfig.tiny()
        params = init_params(cfg, jax.random.PRNGKey(0))
        eng = ContinuousBatcher(params, cfg, kv_layout="paged",
                                max_len=32, page_size=8, n_slots=2,
                                speculative=True, gamma=4)
        eng.submit(list(range(8)), max_new=21)       # 8 + 20 + 4 == 32
        with pytest.raises(ValueError, match="exceeds"):
            eng.submit(list(range(8)), max_new=22)   # ... == 33 > 32


class TestSpeculativeSampling:
    """temperature > 0 speculation: the rejection-sampling verify must
    leave the emitted stream distributed EXACTLY as the plain target
    sampler — delta-q accept prob p[prop] for deterministic proposers,
    min(1, p/q) + residual resample for distributional ones — while the
    temperature == 0 configs keep compiling to the byte-identical
    exact-match cumprod.

    The tiny random-weight model's logits are nearly flat (std ~0.15
    over vocab 256), so \"low temperature\" here means low relative to
    THAT scale: T = 0.005 sharpens p enough for the repetitive-stream
    proposals to accept, the regime a real model reaches at ordinary
    temperatures."""

    def _cfg(self, **kw):
        from k8s_gpu_scheduler_tpu.models import LlamaConfig

        return dataclasses.replace(LlamaConfig.tiny(), dtype=jnp.float32,
                                   **kw)

    def _run(self, cfg, prompts, spec, max_new=8, gamma=3, **kw):
        from k8s_gpu_scheduler_tpu.models import init_params
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        params = init_params(cfg, jax.random.PRNGKey(0))
        eng = ContinuousBatcher(params, cfg, n_slots=2, max_len=64,
                                chunk=4, prefill_bucket=8,
                                kv_layout="paged", page_size=8,
                                speculative=spec, gamma=gamma, **kw)
        ids = [eng.submit(p, max_new=max_new) for p in prompts]
        done = eng.run()
        return [done[i] for i in ids], eng

    def test_topk1_sampled_equals_greedy_with_zero_accepts(self):
        """top_k=1 collapses the target law to a point mass: the sampled
        engine must emit the plain greedy stream byte-for-byte, and on
        the no-bigram-repeat prompt every proposal rejects EXACTLY
        (accept prob is p[prop] ∈ {0, 1}) — the sampled edition of the
        0-accept full-rewind pins."""
        cfg = self._cfg(decode_attn="fused")
        rng = np.random.default_rng(2)
        prompts = [list(rng.integers(0, cfg.vocab, 5))]
        s, eng = self._run(cfg, prompts, True, max_new=5, gamma=3,
                           temperature=0.7, top_k=1)
        g, _ = self._run(cfg, prompts, False, max_new=5, gamma=3)
        assert s == g
        m = eng.pool_metrics()
        assert m["spec_accept_rate"] == 0.0
        assert m["spec_tokens_per_dispatch"] == 1.0
        assert m["spec_rewound_tokens_total"] == 3 * 4  # gamma × steps
        eng._alloc.assert_consistent()

    def test_sampled_speculation_accepts_on_repetitive_stream(self):
        """At a temperature well under the logit scale the sampled
        stream self-repeats like the greedy one, the delta-q accept prob
        p[prop] approaches 1 on in-cycle proposals, and the engine must
        beat one token per dispatch — the sampled speedup exists."""
        cfg = self._cfg(decode_attn="fused")
        rng = np.random.default_rng(0)
        phrase = list(rng.integers(0, cfg.vocab, 4))
        prompts = [phrase * 2, phrase + phrase[:1]]
        s, eng = self._run(cfg, prompts, True, max_new=24,
                           temperature=0.005)
        m = eng.pool_metrics()
        assert m["spec_accept_rate"] > 0
        assert m["spec_tokens_per_dispatch"] > 1.0
        assert all(0 <= t < cfg.vocab for out in s for t in out)
        assert m["pages_in_use"] == 0
        eng._alloc.assert_consistent()

    def test_draft_equals_target_full_accepts(self):
        """A draft proposer sharing the target's weights and sampler
        settings yields q == p (up to float noise between the dense
        draft forward and the paged verify): min(1, p/q) accepts every
        proposal, every dispatch commits gamma+1 tokens, and the bonus
        token rides the full-accept branch."""
        from k8s_gpu_scheduler_tpu.models import init_params
        from k8s_gpu_scheduler_tpu.models.proposers import (
            DraftModelProposer,
        )
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        cfg = self._cfg()
        params = init_params(cfg, jax.random.PRNGKey(0))
        draft = DraftModelProposer(cfg, params, temperature=0.7,
                                   top_k=0, ctx=32)
        eng = ContinuousBatcher(params, cfg, n_slots=2, max_len=64,
                                chunk=4, prefill_bucket=8,
                                kv_layout="paged", page_size=8,
                                speculative=True, gamma=3,
                                proposer=draft, temperature=0.7)
        rng = np.random.default_rng(1)
        # 9 = 1 prefill token + 2 full-accept dispatches × (gamma+1):
        # no budget clamp, so the pins are exact.
        rid = eng.submit(list(rng.integers(0, cfg.vocab, 6)), max_new=9)
        done = eng.run()
        m = eng.pool_metrics()
        assert m["spec_accept_rate"] == 1.0
        assert m["spec_tokens_per_dispatch"] == 4.0
        assert len(done[rid]) == 9
        eng._alloc.assert_consistent()

    def test_sampled_stream_matches_target_distribution(self):
        """Seeded distributional equivalence on a toy vocab: across many
        seeds the B=1 rejection sampler's emitted tokens must match the
        EXACT target marginals — softmax(logits/T) for the first token,
        the one-step chain marginal for the second (which rides the
        propose/accept/resample loop). Total-variation distance against
        the enumerated truth stays at the multinomial noise floor
        (~0.08 for 16 symbols × 400 draws); a biased acceptance rule
        (e.g. always committing proposals) lands near 0.9."""
        from k8s_gpu_scheduler_tpu.models import (
            generate_speculative, init_params,
        )
        from k8s_gpu_scheduler_tpu.models.llama import forward

        cfg = self._cfg(vocab=16)
        params = init_params(cfg, jax.random.PRNGKey(3))
        rng = np.random.default_rng(5)
        phrase = list(rng.integers(0, 16, 3))
        prompt = jnp.asarray(phrase * 3, jnp.int32)[None, :]

        gen = jax.jit(lambda s: generate_speculative(
            params, prompt, cfg, max_new=2, gamma=2, max_len=24,
            temperature=1.0, seed=s))
        N = 400
        draws = np.stack([np.asarray(gen(s)) for s in range(N)])[:, 0]

        p1 = np.asarray(jax.nn.softmax(
            forward(params, prompt, cfg)[0, -1].astype(jnp.float32)))
        p2 = np.zeros(16)
        for t1 in range(16):
            ext = jnp.concatenate(
                [prompt, jnp.asarray([[t1]], jnp.int32)], axis=1)
            p2 += p1[t1] * np.asarray(jax.nn.softmax(
                forward(params, ext, cfg)[0, -1].astype(jnp.float32)))

        emp1 = np.bincount(draws[:, 0], minlength=16) / N
        emp2 = np.bincount(draws[:, 1], minlength=16) / N
        tv1 = 0.5 * np.abs(emp1 - p1).sum()
        tv2 = 0.5 * np.abs(emp2 - p2).sum()
        assert tv1 < 0.2, f"first-token TV {tv1:.3f} off the target law"
        assert tv2 < 0.2, f"second-token TV {tv2:.3f} off the target law"

    def test_ngram_proposer_keeps_greedy_identity(self):
        """Proposal sources never change WHAT a greedy engine emits,
        only how fast: an ngram-proposer engine must match plain greedy
        byte-for-byte."""
        cfg = self._cfg(decode_attn="fused")
        rng = np.random.default_rng(0)
        phrase = list(rng.integers(0, cfg.vocab, 4))
        prompts = [phrase * 2, list(rng.integers(0, cfg.vocab, 7))]
        s, eng = self._run(cfg, prompts, True, proposer="ngram:3")
        g, _ = self._run(cfg, prompts, False)
        assert s == g
        assert eng.pool_metrics()["spec_proposer"] == "3gram"
        eng._alloc.assert_consistent()


class TestAdaptiveGamma:
    """spec_adaptive=True: the accept-rate EMA drives per-slot effective
    windows 0..gamma while the dispatch stays padded to the static
    1+gamma shape — stream content is NEVER a function of eff, and the
    adaptive state must ride snapshots across drain/restore/absorb."""

    def _engine(self, params, cfg, **kw):
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        return ContinuousBatcher(params, cfg, n_slots=2, max_len=64,
                                 chunk=4, prefill_bucket=8,
                                 kv_layout="paged", page_size=8,
                                 speculative=True, gamma=3,
                                 spec_adaptive=True, **kw)

    def test_adaptive_greedy_stream_identical(self):
        """Shrinking a verify window only forgoes speedup: the greedy
        adaptive engine must stay byte-identical to plain greedy while
        the gamma gauge actually moves off the static configuration."""
        from k8s_gpu_scheduler_tpu.models import LlamaConfig, init_params
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        cfg = dataclasses.replace(LlamaConfig.tiny(), dtype=jnp.float32,
                                  decode_attn="fused")
        params = init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.default_rng(2)
        prompts = [list(rng.integers(0, cfg.vocab, 5)),
                   list(rng.integers(0, cfg.vocab, 7))]

        def run(eng):
            ids = [eng.submit(p, max_new=16) for p in prompts]
            done = eng.run()
            return [done[i] for i in ids]

        adaptive = self._engine(params, cfg)
        s = run(adaptive)
        plain = ContinuousBatcher(params, cfg, n_slots=2, max_len=64,
                                  chunk=4, prefill_bucket=8,
                                  kv_layout="paged", page_size=8)
        assert s == run(plain)
        # Reject-heavy traffic must have CLOSED windows (the speedup
        # knob works) without ever reopening past the configured gamma.
        m = adaptive.pool_metrics()
        assert m["spec_gamma_agg"]["max"] <= 3
        assert adaptive._spec_fleet_ema < 1.0
        adaptive._alloc.assert_consistent()

    def test_adaptive_state_rides_snapshot_and_absorb(self):
        """drain() carries the per-request EMAs, pinned reservations and
        the fleet prior through the pytree codec; restore() resumes them
        verbatim and absorb() remaps them to the destination's new
        request ids."""
        from k8s_gpu_scheduler_tpu.models import LlamaConfig, init_params
        from k8s_gpu_scheduler_tpu.models.snapshot import ServingSnapshot

        cfg = dataclasses.replace(LlamaConfig.tiny(), dtype=jnp.float32,
                                  decode_attn="fused")
        params = init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.default_rng(4)
        src = self._engine(params, cfg)
        for plen in (6, 9):
            src.submit(list(rng.integers(0, cfg.vocab, plen)), max_new=24)
        for _ in range(4):                   # EMAs move off the prior
            src.step()
        assert src._spec_ema and src._spec_reserve
        fleet = src._spec_fleet_ema
        assert fleet != 1.0

        # Full drain → codec round trip → restore resumes verbatim.
        snap = ServingSnapshot.from_pytree(src.drain().to_pytree())
        assert snap.spec_ema and snap.spec_reserve
        assert snap.spec_fleet_ema == fleet
        dst = self._engine(params, cfg)
        dst.restore(snap)
        assert dst._spec_ema == snap.spec_ema
        assert dst._spec_reserve == snap.spec_reserve
        assert dst._spec_fleet_ema == fleet
        dst.run()
        dst._alloc.assert_consistent()

        # Partial shed → absorb: the adaptive state follows the request
        # under its REMAPPED id.
        src2 = self._engine(params, cfg)
        rids = [src2.submit(list(rng.integers(0, cfg.vocab, 6)),
                            max_new=16) for _ in range(2)]
        for _ in range(3):
            src2.step()
        shed = src2.active_slot_ids()[:1]
        snap2 = src2.drain(slots=shed)
        (old_rid,) = set(snap2.slot_req.values())
        ema, reserve = snap2.spec_ema[old_rid], snap2.spec_reserve[old_rid]
        dst2 = self._engine(params, cfg)
        mapping = dst2.absorb(
            ServingSnapshot.from_pytree(snap2.to_pytree()))
        new_rid = mapping[old_rid]
        assert dst2._spec_ema[new_rid] == ema
        assert dst2._spec_reserve[new_rid] == reserve
        while dst2.pending:
            dst2.step()
        while src2.pending:
            src2.step()
        src2._alloc.assert_consistent()
        dst2._alloc.assert_consistent()


class TestGenerateSpeculativeFusedVerify:
    """The B=1 reference API routed through the multi-query kernel."""

    def test_fused_verify_token_identity(self):
        from k8s_gpu_scheduler_tpu.models import (
            LlamaConfig, generate, generate_speculative, init_params,
        )

        cfg = dataclasses.replace(LlamaConfig.tiny(), dtype=jnp.float32)
        cfg_fused = dataclasses.replace(cfg, decode_attn="fused")
        params = init_params(cfg, jax.random.PRNGKey(0))
        phrase = jax.random.randint(jax.random.PRNGKey(1), (6,), 0,
                                    cfg.vocab)
        prompt = jnp.tile(phrase, 3)[None, :]
        ref = generate(params, prompt, cfg, max_new=8, max_len=40)
        dense = generate_speculative(params, prompt, cfg, max_new=8,
                                     gamma=4, max_len=40)
        fused = generate_speculative(params, prompt, cfg_fused, max_new=8,
                                     gamma=4, max_len=40)
        assert jnp.array_equal(dense, ref)
        assert jnp.array_equal(fused, ref)

    def test_b1_sampled_is_seed_deterministic(self):
        """temperature > 0 routes through the rejection sampler (the
        greedy-only guard is gone): same seed → identical stream, a
        different seed → a different draw of the same law."""
        from k8s_gpu_scheduler_tpu.models import (
            LlamaConfig, generate_speculative, init_params,
        )

        cfg = dataclasses.replace(LlamaConfig.tiny(), dtype=jnp.float32)
        params = init_params(cfg, jax.random.PRNGKey(0))
        phrase = jax.random.randint(jax.random.PRNGKey(1), (6,), 0,
                                    cfg.vocab)
        prompt = jnp.tile(phrase, 3)[None, :]
        kw = dict(max_new=8, gamma=4, max_len=40, temperature=1.0)
        a = generate_speculative(params, prompt, cfg, seed=11, **kw)
        b = generate_speculative(params, prompt, cfg, seed=11, **kw)
        c = generate_speculative(params, prompt, cfg, seed=12, **kw)
        assert jnp.array_equal(a, b)
        assert not jnp.array_equal(a, c)
        assert a.shape == (1, 8)
        assert bool((a >= 0).all() and (a < cfg.vocab).all())

    def test_b1_restriction_still_enforced(self):
        from k8s_gpu_scheduler_tpu.models import (
            LlamaConfig, generate_speculative, init_params,
        )

        cfg = dataclasses.replace(LlamaConfig.tiny(), decode_attn="fused")
        params = init_params(cfg, jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="B=1"):
            generate_speculative(params, jnp.zeros((2, 4), jnp.int32),
                                 cfg, max_new=4)


class TestBenchLeg:
    @pytest.mark.slow          # the dedicated CI step runs the same leg
    def test_speculative_bench_smoke(self):
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        proc = subprocess.run(
            [sys.executable, "bench.py", "--leg", "speculative", "--smoke"],
            cwd=repo, env=env, capture_output=True, text=True, timeout=600)
        assert proc.returncode == 0, proc.stderr[-2000:]
        rec = json.loads(proc.stdout.strip().splitlines()[-1])
        assert rec["metric"] == "speculative_bench"
        e = rec["extra"]
        assert e["spec_token_identity"] is True
        assert e["spec_accept_rate"] > 0
        assert e["spec_tokens_per_dispatch"] > 1.0
        assert e["spec_on_tok_s"] > 0 and e["spec_off_tok_s"] > 0
