"""Leader election tests — scheduler HA over the coordination Lease.

Parity target: the reference turns on kube-scheduler leader election in its
deploy config (/root/reference/deploy/scheduler.yaml:10-13); round 2 shipped
none (VERDICT.md missing #2). Two axes here: the elector protocol itself
(acquire, renew, mutual exclusion, steal-after-expiry, clean release) and
the scheduler integration (exactly one of two replicas binds; failover)."""
import time

from k8s_gpu_scheduler_tpu.api.objects import ConfigMap, ObjectMeta
from k8s_gpu_scheduler_tpu.cluster import APIServer
from k8s_gpu_scheduler_tpu.config import SchedulerConfig
from k8s_gpu_scheduler_tpu.plugins import TPUPlugin
from k8s_gpu_scheduler_tpu.sched import LeaderElector, Profile, Scheduler

from tests.test_plugins import FakeRegistry, mk_node, mk_pod, wait_until


def mk_elector(server, ident, **kw):
    kw.setdefault("lease_duration_s", 0.6)
    kw.setdefault("renew_period_s", 0.1)
    kw.setdefault("retry_period_s", 0.05)
    return LeaderElector(server, ident, **kw)


class TestElector:
    def test_single_elector_acquires(self):
        server = APIServer()
        el = mk_elector(server, "a")
        el.start()
        try:
            assert el.wait_until_leader(3)
            lease = server.get("Lease", "tpu-scheduler")
            assert lease.holder_identity == "a"
        finally:
            el.stop()

    def test_mutual_exclusion_and_release_handover(self):
        server = APIServer()
        a = mk_elector(server, "a")
        b = mk_elector(server, "b")
        a.start()
        assert a.wait_until_leader(3)
        b.start()
        try:
            time.sleep(0.5)
            assert a.is_leader() and not b.is_leader()
            # Clean stop releases the lease: b takes over well inside the
            # lease duration it would otherwise wait out.
            a.stop()
            assert b.wait_until_leader(3)
            assert server.get("Lease", "tpu-scheduler").holder_identity == "b"
            assert server.get("Lease", "tpu-scheduler").lease_transitions >= 1
        finally:
            a.stop()
            b.stop()

    def test_steal_after_crash(self):
        """A holder that dies without releasing is succeeded only after the
        lease duration expires."""
        server = APIServer()
        a = mk_elector(server, "a")
        a.start()
        assert a.wait_until_leader(3)
        # Simulate crash: kill the thread without releasing.
        a._stop.set()
        a._thread.join(timeout=2)
        b = mk_elector(server, "b")
        t0 = time.time()
        b.start()
        try:
            assert b.wait_until_leader(5)
            # b had to wait out a's 0.6 s lease (tolerate scheduling slop).
            assert time.time() - t0 > 0.3
        finally:
            b.stop()

    def test_partitioned_leader_demotes_itself(self):
        """When renewals fail, is_leader() goes False within the lease
        duration — before anyone could steal."""
        server = APIServer()
        a = mk_elector(server, "a")
        a.start()
        assert a.wait_until_leader(3)
        # Partition: every update now conflicts (simulate by deleting the
        # lease and replacing it with someone else's).
        lease = server.get("Lease", "tpu-scheduler")
        server.delete("Lease", "tpu-scheduler")
        lease.holder_identity = "thief"
        lease.renew_time = time.time() + 3600
        server.create(lease)
        try:
            assert wait_until(lambda: not a.is_leader(), timeout=3)
        finally:
            a.stop()


class TestSchedulerHA:
    def _mk_sched(self, server, ident):
        cfg = SchedulerConfig(backoff_initial_s=0.05, backoff_max_s=0.2)
        sched = Scheduler(server, profile=Profile(), config=cfg,
                          elector=mk_elector(server, ident))
        tpu = TPUPlugin(sched.handle, registry=FakeRegistry())
        sched.profile = Profile(pre_filter=[tpu], filter=[tpu], score=[tpu],
                                reserve=[tpu], post_bind=[tpu])
        return sched

    def test_two_replicas_exactly_one_binds_then_failover(self):
        server = APIServer()
        server.create(mk_node("n1", chips=8))
        s1 = self._mk_sched(server, "replica-1")
        s2 = self._mk_sched(server, "replica-2")
        s1.start()
        assert s1.elector.wait_until_leader(3)
        s2.start()
        try:
            server.create(ConfigMap(metadata=ObjectMeta(name="cm1"), data={}))
            server.create(mk_pod("p1", chips=2, cm="cm1"))
            assert wait_until(
                lambda: server.get("Pod", "p1", "default").spec.node_name,
                timeout=5)
            # Only the leader scheduled: the standby never popped it.
            assert s1.metrics.counter(
                "tpu_sched_attempts_total").value(result="scheduled") == 1
            assert s2.metrics.counter(
                "tpu_sched_attempts_total").value(result="scheduled") == 0
            # Failover: stop the leader; the standby takes the lease and
            # schedules the next pod.
            s1.stop()
            assert s2.elector.wait_until_leader(5)
            server.create(ConfigMap(metadata=ObjectMeta(name="cm2"), data={}))
            server.create(mk_pod("p2", chips=2, cm="cm2"))
            assert wait_until(
                lambda: server.get("Pod", "p2", "default").spec.node_name,
                timeout=5)
            assert s2.metrics.counter(
                "tpu_sched_attempts_total").value(result="scheduled") == 1
        finally:
            s1.stop()
            s2.stop()


class TestChaosFailover:
    """Failover under INJECTED registry/lease flaps (the chaos harness,
    testing/faults.py): the leader's lease transport dies mid-cycle, it
    demotes itself, the standby takes over — and no pod is ever
    scheduled twice."""

    def test_leader_flap_hands_over_without_double_leadership(self):
        from k8s_gpu_scheduler_tpu.testing.faults import (
            FaultInjector, FaultProxy, FaultRule,
        )

        server = APIServer()
        inj = FaultInjector(rules=[
            # From its 8th lease op on, every op of the LEADER's client
            # drops — the partitioned-leader scenario. The standby's
            # client is not proxied and keeps working.
            FaultRule(site="lease", kind="drop", after=7, every=1),
        ])
        a = mk_elector(FaultProxy(server, inj, "lease"), "a")
        b = mk_elector(server, "b")
        a.start()
        assert a.wait_until_leader(3)
        b.start()
        try:
            # The flap starts; a demotes itself (its clock) BEFORE b can
            # steal — sample continuously for any double-leadership
            # window (client-go's non-overlap argument).
            deadline = time.time() + 5
            overlap = False
            while time.time() < deadline and not b.is_leader():
                overlap |= a.is_leader() and b.is_leader()
                time.sleep(0.01)
            assert b.is_leader(), "standby never took over"
            assert not a.is_leader()
            assert not overlap
            assert inj.log, "no faults fired — the scenario tested nothing"
        finally:
            a.stop()
            b.stop()

    def test_no_pod_scheduled_twice_through_failover(self):
        """Scheduler integration: the leader loses its lease session
        mid-run, the standby takes over and schedules the NEXT pod; the
        attempts counters prove each pod was bound exactly once."""
        from k8s_gpu_scheduler_tpu.config import SchedulerConfig
        from k8s_gpu_scheduler_tpu.testing.faults import (
            FaultInjector, FaultProxy, FaultRule,
        )

        server = APIServer()
        server.create(mk_node("n1", chips=8))
        inj = FaultInjector(rules=[
            FaultRule(site="lease", kind="drop", after=7, every=1),
        ])
        cfg = SchedulerConfig(backoff_initial_s=0.05, backoff_max_s=0.2)

        def mk_sched(ident, elector_server):
            sched = Scheduler(server, profile=Profile(), config=cfg,
                              elector=mk_elector(elector_server, ident))
            tpu = TPUPlugin(sched.handle, registry=FakeRegistry())
            sched.profile = Profile(pre_filter=[tpu], filter=[tpu],
                                    score=[tpu], reserve=[tpu],
                                    post_bind=[tpu])
            return sched

        s1 = mk_sched("replica-1", FaultProxy(server, inj, "lease"))
        s2 = mk_sched("replica-2", server)
        s1.start()
        assert s1.elector.wait_until_leader(3)
        s2.start()
        try:
            server.create(ConfigMap(metadata=ObjectMeta(name="cm1"),
                                    data={}))
            server.create(mk_pod("p1", chips=2, cm="cm1"))
            assert wait_until(
                lambda: server.get("Pod", "p1", "default").spec.node_name,
                timeout=5)
            # The flap (already scheduled by rule) partitions replica-1
            # from the lease; replica-2 steals after expiry.
            assert wait_until(s2.elector.is_leader, timeout=5)
            server.create(ConfigMap(metadata=ObjectMeta(name="cm2"),
                                    data={}))
            server.create(mk_pod("p2", chips=2, cm="cm2"))
            assert wait_until(
                lambda: server.get("Pod", "p2", "default").spec.node_name,
                timeout=5)
            c1 = s1.metrics.counter("tpu_sched_attempts_total")
            c2 = s2.metrics.counter("tpu_sched_attempts_total")
            # Exactly one bind per pod across BOTH replicas.
            assert c1.value(result="scheduled") \
                + c2.value(result="scheduled") == 2
            assert c2.value(result="scheduled") >= 1
        finally:
            s1.stop()
            s2.stop()


class TestLeaseOverREST:
    def test_lease_cas_roundtrip(self):
        """Lease CRUD + compare-and-swap through the REST adapter: PUT with
        a stale resourceVersion must 409 (leader election's safety)."""
        import pytest

        from k8s_gpu_scheduler_tpu.api.objects import Lease
        from k8s_gpu_scheduler_tpu.cluster.apiserver import Conflict
        from k8s_gpu_scheduler_tpu.cluster.kubeapi import KubeAPIServer
        from tests.test_kubeapi import FakeKube

        fake = FakeKube()
        try:
            api = KubeAPIServer(base_url=fake.url)
            now = time.time()
            api.create(Lease(metadata=ObjectMeta(name="tpu-scheduler"),
                             holder_identity="a", lease_duration_s=15,
                             acquire_time=now, renew_time=now))
            lease = api.get("Lease", "tpu-scheduler")
            assert lease.holder_identity == "a"
            assert abs(lease.renew_time - now) < 1.0
            rv = lease.metadata.resource_version
            lease.holder_identity = "b"
            api.update(lease, expect_rv=rv)
            stale = api.get("Lease", "tpu-scheduler")
            stale.holder_identity = "c"
            with pytest.raises(Conflict):
                api.update(stale, expect_rv=rv)  # rv moved on
            assert api.get("Lease",
                           "tpu-scheduler").holder_identity == "b"
        finally:
            fake.close()

    def test_elector_runs_over_rest(self):
        from k8s_gpu_scheduler_tpu.cluster.kubeapi import KubeAPIServer
        from tests.test_kubeapi import FakeKube

        fake = FakeKube()
        try:
            api = KubeAPIServer(base_url=fake.url)
            el = mk_elector(api, "rest-1")
            el.start()
            try:
                assert el.wait_until_leader(5)
                assert api.get("Lease",
                               "tpu-scheduler").holder_identity == "rest-1"
            finally:
                el.stop()
            assert api.get("Lease", "tpu-scheduler").holder_identity == ""
        finally:
            fake.close()
