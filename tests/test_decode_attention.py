"""Fused Pallas flash-decode attention (ops/decode_attention.py) vs the
grouped dense reference — the serving engine's decode hot path.

Everything runs in interpret mode on CPU (the shared ops.pallas_interpret
toggle); the same kernel compiles on TPU, where bench.py's
`--leg decode_attention` microbench measures it. The dense grouped-einsum
reference is itself pinned against an explicit `_repeat_kv` formulation
(the pre-fused serving path), so the kernel and its reference cannot drift
wrong together.
"""
import dataclasses
import json
import math
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_scheduler_tpu.ops import (
    decode_plan, dense_decode_reference, flash_decode_attention,
    pallas_interpret,
)
from k8s_gpu_scheduler_tpu.ops.attention import _repeat_kv

TOL = {jnp.float32: 3e-6, jnp.bfloat16: 4e-2}


def qkv(B=2, H=8, Hkv=4, hd=32, S=64, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (
        jax.random.normal(ks[0], (B, H, hd), dtype),
        jax.random.normal(ks[1], (B, S, Hkv, hd), dtype),
        jax.random.normal(ks[2], (B, S, Hkv, hd), dtype),
    )


def repeat_reference(q, k, v, lengths, bitmap=None):
    """The pre-fused dense formulation: explicit `_repeat_kv`
    materialization, f32 masked softmax — the semantics both new paths
    must reproduce."""
    B, H, hd = q.shape
    S = k.shape[1]
    kr, vr = _repeat_kv(k, H), _repeat_kv(v, H)
    scores = jnp.einsum("bhd,bkhd->bhk", q, kr).astype(jnp.float32)
    scores = scores / math.sqrt(hd)
    mask = jnp.arange(S)[None, :] < jnp.asarray(lengths)[:, None]
    if bitmap is not None:
        mask = mask & bitmap
    scores = jnp.where(mask[:, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhk,bkhd->bhd", probs, vr)


def maxdiff(a, b):
    return float(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32)).max())


class TestDecodePlan:
    def test_plan_picks_divisible_blocks(self):
        assert decode_plan(8192) == (256, 8)
        assert decode_plan(512) == (256, 1)
        assert decode_plan(32) == (32, 1)
        assert decode_plan(100) is None              # no pow2 block divides
        assert decode_plan(64, block_k=48) is None
        assert decode_plan(64, block_k=8, n_splits=3) is None
        assert decode_plan(64, block_k=8, n_splits=4) == (8, 4)

    def test_unsupported_shapes_raise(self):
        q, k, v = qkv(S=100)
        with pytest.raises(ValueError):
            flash_decode_attention(q, k, v, 50, interpret=True)
        q, k, v = qkv(H=6, Hkv=4)
        with pytest.raises(ValueError):
            flash_decode_attention(q, k, v, 50, interpret=True)


class TestDenseReference:
    """The grouped-einsum rewrite must equal the old repeat-kv math —
    this is the satellite fix (no H/Hkv-times cache copy per token) and
    the anchor for every fused-vs-dense comparison below."""

    @pytest.mark.parametrize("hkv", [8, 2, 1])
    def test_grouped_matches_repeat(self, hkv):
        q, k, v = qkv(Hkv=hkv)
        lengths = jnp.array([17, 63])
        ref = repeat_reference(q, k, v, lengths)
        out = dense_decode_reference(q, k, v, lengths=lengths)
        assert maxdiff(out, ref) < 1e-6

    def test_grouped_int8_matches_dequantized_repeat(self):
        from k8s_gpu_scheduler_tpu.models.serving import _kv_quant

        q, k, v = qkv()
        kq, ks = _kv_quant(k)
        vq, vs = _kv_quant(v)
        lengths = jnp.array([30, 64])
        ref = repeat_reference(q, kq.astype(q.dtype) * ks,
                               vq.astype(q.dtype) * vs, lengths)
        out = dense_decode_reference(q, kq, vq, lengths=lengths,
                                     k_scale=ks, v_scale=vs)
        # Factored scales (on scores/probs) vs elementwise dequant: same
        # math, different rounding points.
        assert maxdiff(out, ref) < 1e-4


class TestFusedParity:
    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    @pytest.mark.parametrize("hkv", [8, 2, 1])           # Hkv = H, H/4, H/8
    def test_gqa_and_dtypes(self, dtype, hkv):
        q, k, v = qkv(Hkv=hkv, dtype=dtype)
        lengths = jnp.array([17, 63])
        ref = dense_decode_reference(q, k, v, lengths=lengths)
        out = flash_decode_attention(q, k, v, lengths, block_k=16,
                                     interpret=True)
        assert out.dtype == q.dtype
        assert maxdiff(out, ref) < TOL[dtype]

    def test_ragged_fill_lengths(self):
        """pos = 0, 1, block-1, block, max_seq-1 with block_k=16: every
        block-boundary case of the traced length mask (lengths = pos+1)."""
        B = 5
        q, k, v = qkv(B=B, S=64)
        lengths = jnp.array([1, 2, 16, 17, 64])      # pos + 1
        ref = dense_decode_reference(q, k, v, lengths=lengths)
        out = flash_decode_attention(q, k, v, lengths, block_k=16,
                                     interpret=True)
        assert maxdiff(out, ref) < 1e-5

    def test_scalar_length_broadcasts(self):
        q, k, v = qkv()
        ref = dense_decode_reference(q, k, v, lengths=jnp.array([23, 23]))
        out = flash_decode_attention(q, k, v, 23, block_k=16, interpret=True)
        assert maxdiff(out, ref) < 1e-5

    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_int8_kv(self, dtype):
        from k8s_gpu_scheduler_tpu.models.serving import _kv_quant

        q, k, v = qkv(dtype=dtype)
        kq, ks = _kv_quant(k)
        vq, vs = _kv_quant(v)
        lengths = jnp.array([9, 64])
        ref = dense_decode_reference(q, kq, vq, lengths=lengths,
                                     k_scale=ks, v_scale=vs)
        out = flash_decode_attention(q, kq, vq, lengths, k_scale=ks,
                                     v_scale=vs, block_k=16, interpret=True)
        assert maxdiff(out, ref) < TOL[dtype]

    def test_split_k_combine(self):
        """Split-K partials merged by the LSE combine must equal both the
        single-split sweep and the dense reference — including splits that
        are entirely past the filled prefix (all-masked partials)."""
        q, k, v = qkv(S=128)
        lengths = jnp.array([5, 100])                # split 4 dead for row 0
        ref = dense_decode_reference(q, k, v, lengths=lengths)
        one = flash_decode_attention(q, k, v, lengths, block_k=16,
                                     n_splits=1, interpret=True)
        four = flash_decode_attention(q, k, v, lengths, block_k=16,
                                      n_splits=4, interpret=True)
        assert maxdiff(one, ref) < 1e-5
        assert maxdiff(four, ref) < 1e-5
        assert maxdiff(four, one) < 1e-5

    def test_bitmap_masking(self):
        """The ContinuousBatcher's validity-bitmap mode: set bits ⊆
        lengths window, holes inside it."""
        q, k, v = qkv()
        lengths = jnp.array([20, 64])
        key = jax.random.PRNGKey(3)
        bm = jax.random.bernoulli(key, 0.6, (2, 64))
        bm = bm & (jnp.arange(64)[None, :] < lengths[:, None])
        bm = bm.at[:, 0].set(True)                   # keep rows non-empty
        ref = dense_decode_reference(q, k, v, bitmap=bm)
        out = flash_decode_attention(q, k, v, lengths, bitmap=bm,
                                     block_k=16, interpret=True)
        assert maxdiff(out, ref) < 1e-5

    def test_runs_under_jit_and_scan(self):
        q, k, v = qkv()
        lengths = jnp.array([17, 63])
        ref = dense_decode_reference(q, k, v, lengths=lengths)

        def step(c, _):
            return c, flash_decode_attention(q, k, v, lengths, block_k=16)

        _, outs = jax.jit(
            lambda: jax.lax.scan(step, 0, None, length=2))()
        assert maxdiff(outs[1], ref) < 1e-5


class TestServingIntegration:
    """The config flag end-to-end: fused decode must be token-identical to
    the dense path through generate() and the ContinuousBatcher (f32
    params so greedy argmax has no near-tie noise)."""

    def _cfg(self, **kw):
        from k8s_gpu_scheduler_tpu.models import LlamaConfig

        return dataclasses.replace(LlamaConfig.tiny(), dtype=jnp.float32,
                                   **kw)

    def test_cached_attention_fused_matches_dense(self):
        from k8s_gpu_scheduler_tpu.models.serving import cached_attention

        q, k, v = qkv(Hkv=2)
        q4 = q[:, None]                              # [B, 1, H, hd]
        pos = jnp.int32(21)
        ref = cached_attention(q4, k, v, pos)
        out = cached_attention(q4, k, v, pos, impl="fused", interpret=True)
        assert maxdiff(out, ref) < 1e-5

    def test_cached_attention_prefill_falls_back(self):
        """t > 1 (prefill / speculative verify) must route dense — and
        keep the causal window inside the new tokens."""
        from k8s_gpu_scheduler_tpu.models.serving import cached_attention

        B, t, H, hd, S = 2, 4, 4, 16, 32
        ks = jax.random.split(jax.random.PRNGKey(5), 3)
        q = jax.random.normal(ks[0], (B, t, H, hd))
        k = jax.random.normal(ks[1], (B, S, H, hd))
        v = jax.random.normal(ks[2], (B, S, H, hd))
        ref = cached_attention(q, k, v, jnp.int32(3))
        out = cached_attention(q, k, v, jnp.int32(3), impl="fused",
                               interpret=True)
        assert maxdiff(out, ref) < 1e-6

    def test_generate_token_identity(self):
        from k8s_gpu_scheduler_tpu.models import generate, init_params

        cfg = self._cfg()
        params = init_params(cfg, jax.random.PRNGKey(0))
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0,
                                    cfg.vocab)
        ref = generate(params, prompt, cfg, max_new=6, max_len=32)
        out = generate(params, prompt,
                       dataclasses.replace(cfg, decode_attn="fused"),
                       max_new=6, max_len=32)
        assert (ref == out).all()

    @pytest.mark.parametrize("kvd", [None, "int8"])
    def test_batcher_fused_matches_dense_engine(self, kvd):
        """Same engine geometry, dense vs fused decode_attn: the emitted
        streams must be identical (bitmap masking + cursor length bound
        reproduce the dense bitmap semantics exactly for active slots)."""
        from k8s_gpu_scheduler_tpu.models import init_params
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        cfg = self._cfg()
        params = init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        prompts = [list(rng.integers(0, cfg.vocab, n)) for n in (3, 5, 4)]
        outs = {}
        for impl in ("dense", "fused"):
            eng = ContinuousBatcher(
                params, dataclasses.replace(cfg, decode_attn=impl),
                n_slots=2, max_len=32, chunk=4, prefill_bucket=8,
                kv_dtype=kvd)
            ids = [eng.submit(p, max_new=5) for p in prompts]
            done = eng.run()
            outs[impl] = [done[i] for i in ids]
        assert outs["fused"] == outs["dense"]


class TestInterpretToggle:
    def test_env_override(self, monkeypatch):
        monkeypatch.setenv("TPU_SCHED_PALLAS_INTERPRET", "1")
        assert pallas_interpret() is True
        monkeypatch.setenv("TPU_SCHED_PALLAS_INTERPRET", "0")
        assert pallas_interpret() is False
        monkeypatch.delenv("TPU_SCHED_PALLAS_INTERPRET")
        # CPU backend in tier-1 → interpret by default.
        assert pallas_interpret() is True
        assert pallas_interpret(False) is False


class TestBenchLeg:
    @pytest.mark.slow  # the dedicated CI step runs the same leg every
    # push (the PR 5 convention for bench smokes with their own CI step)
    def test_decode_attention_microbench_smoke(self):
        """`bench.py --leg decode_attention --smoke` must emit ONE JSON
        line with dense-vs-fused tokens/s for both cache dtypes — the
        contract future BENCH_*.json capture rides on."""
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        out = subprocess.run(
            [sys.executable, "bench.py", "--leg", "decode_attention",
             "--smoke"],
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            capture_output=True, text=True, timeout=600, env=env)
        assert out.returncode == 0, out.stderr[-2000:]
        lines = [ln for ln in out.stdout.splitlines() if ln.strip()]
        assert len(lines) == 1, out.stdout
        rec = json.loads(lines[0])
        assert rec["metric"] == "decode_attention_microbench"
        extra = rec["extra"]
        for key in ("decattn_dense_bf16_tok_s", "decattn_fused_bf16_tok_s",
                    "decattn_dense_int8kv_tok_s",
                    "decattn_fused_int8kv_tok_s",
                    "decattn_bytes_per_step_bf16",
                    "decattn_bytes_per_step_int8kv"):
            assert key in extra and extra[key] > 0, (key, extra)
