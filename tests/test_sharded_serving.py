"""Multi-chip sharded paged serving — shard_map islands over tp.

Runs on XLA's forced host-platform devices (conftest: 8 CPU devices),
which exercises the same shard_map partitioning the TPU path uses. The
contract under test:

- sharded (tp ∈ {2, 4}) streams are BYTE-IDENTICAL to the unsharded
  engine across the full feature grid (dense/fused × int8-KV ×
  prefix-cache × speculative × chunked prefill) — the head-slice +
  exact-all_gather island design makes identity structural, not a
  float-tie accident. Since PR 15 the islands default to
  MEGATRON-SLICED WEIGHTS (weight_sharding=True): column-parallel
  q/k/v/gate/up compute each shard's head/ffn family directly from a
  [·, ·/tp] slice (byte-exact — matmul output columns are independent)
  and row-parallel o/down combine per block — tp_combine="all_gather"
  keeps the byte-identity contract (movement-only), "psum" trades it
  for 1/tp row-matmul FLOPs and is tolerance-checked; the legacy
  replicated-weight island stays behind weight_sharding=False and its
  own identity cells;
- per-chip bytes of the WEIGHT_SPECS-sliced weight leaves scale exactly
  1/tp (the scale-UP axis), unsliceable dims (Hkv % tp, d_ff % tp) fail
  LOUDLY at __init__ with the valid tp divisors, and
  weight_sharding=False on a tp island warns once + counts
  (reason="weights_replicated");
- donation and zero-retrace survive the island boundary (jit keys now
  include shardings);
- per-chip pool residency scales exactly 1/tp;
- snapshots are mesh-agnostic: tp=2 → tp=1 → tp=4 round trips resume
  token-identically, and partial (shed) snapshots absorb across tp;
- the fused→dense downgrade gate is never silent (warn-once + counted
  metric), and the paged sharded path does NOT downgrade;
- the graftcheck GSPMD audit passes on the tree and catches the seeded
  bad fixture.
"""
import dataclasses
import os
import warnings

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from k8s_gpu_scheduler_tpu.models import serving
from k8s_gpu_scheduler_tpu.models.llama import LlamaConfig, init_params
from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher


def tp_mesh(tp: int) -> Mesh:
    devs = jax.devices()
    if len(devs) < tp:
        pytest.skip(f"needs {tp} devices, have {len(devs)}")
    return Mesh(np.array(devs[:tp]), ("tp",))


@pytest.fixture(scope="module")
def tiny():
    cfg = dataclasses.replace(LlamaConfig.tiny(), decode_attn="fused")
    return cfg, init_params(cfg, jax.random.PRNGKey(0))


def build(cfg, params, mesh, **kw):
    kw.setdefault("n_slots", 2)
    kw.setdefault("max_len", 32)
    kw.setdefault("chunk", 2)
    kw.setdefault("prefill_bucket", 8)
    kw.setdefault("page_size", 8)
    return ContinuousBatcher(params, cfg, kv_layout="paged", mesh=mesh,
                             **kw)


def drive(eng, prompts, max_new=4):
    for p in prompts:
        eng.submit(p, max_new=max_new)
    return eng.run()


def mixed_prompts(cfg, seed=0, n=4):
    rng = np.random.default_rng(seed)
    phrase = list(rng.integers(0, cfg.vocab, 3))
    shared = list(rng.integers(0, cfg.vocab, 8))
    out = [list(rng.integers(0, cfg.vocab, int(ln)))
           for ln in rng.integers(4, 21, n - 2)]
    # A prefix-sharing pair (prefix-cache hits) and a self-repetitive
    # prompt (speculative accepts) ride every grid point.
    out.append(shared + list(rng.integers(0, cfg.vocab, 4)))
    out.append(phrase * 4)
    return out


# Tier-1 wall-clock rebalance (the PR 5/8 pattern, applied as PR 13's
# additions brought the suite back to the 870 s budget and again as
# PR 15's weight-sharding default grew every cell's compile): cells
# whose feature combination is a strict subset of a kept cell ride
# pytest.mark.slow — the plain/int8-spec-prefix-SUPERSET/dense-int8
# cells stay tier-1, and the unfiltered CI pytest run still executes
# every cell on every push.
GRID = [
    dict(),
    pytest.param(dict(kv_dtype="int8"), marks=pytest.mark.slow),
    # subset of the kept int8-spec-prefix superset cell (PR 15 budget):
    pytest.param(dict(kv_dtype="int8", prefix_cache=True),
                 marks=pytest.mark.slow),
    pytest.param(dict(prefix_cache=True, prefill_chunk_tokens=8),
                 marks=pytest.mark.slow),
    pytest.param(dict(kv_dtype="int8", prefill_chunk_tokens=8),
                 marks=pytest.mark.slow),
    pytest.param(dict(speculative=True, gamma=2),
                 marks=pytest.mark.slow),
    dict(kv_dtype="int8", speculative=True, gamma=2, prefix_cache=True),
    dict(dense=True, kv_dtype="int8"),
    # subset of the kept dense-int8 cell (PR 15 budget):
    pytest.param(dict(dense=True), marks=pytest.mark.slow),
]


@pytest.mark.parametrize("kw", GRID,
                         ids=lambda kw: "-".join(sorted(
                             k for k, v in kw.items() if v)) or "plain")
def test_sharded_byte_identity_grid(tiny, kw):
    """tp=2 == unsharded, byte for byte, across the feature grid."""
    cfg, params = tiny
    kw = dict(kw)
    if kw.pop("dense", False):
        cfg = dataclasses.replace(cfg, decode_attn="dense")
    prompts = mixed_prompts(cfg)
    ref = drive(build(cfg, params, None, **kw), prompts)
    got = drive(build(cfg, params, tp_mesh(2), **kw), prompts)
    assert got == ref


def test_sharded_byte_identity_tp4(tiny):
    cfg, params = tiny
    prompts = mixed_prompts(cfg, seed=3)
    ref = drive(build(cfg, params, None, kv_dtype="int8"), prompts)
    got = drive(build(cfg, params, tp_mesh(4), kv_dtype="int8"), prompts)
    assert got == ref


def test_per_chip_pool_bytes_scale(tiny):
    cfg, params = tiny
    b1 = build(cfg, params, None,
               kv_dtype="int8").pool_metrics()["kv_pool_device_bytes"]
    for tp in (2, 4):
        pm = build(cfg, params, tp_mesh(tp), kv_dtype="int8").pool_metrics()
        assert pm["tp"] == tp
        assert pm["kv_pool_device_bytes"] * tp == b1


def test_sharded_steady_state_zero_retrace_varying_tables(
        tiny, recompile_guard):
    """Steady-state decode on the mesh: block tables vary in CONTENT
    across waves (fresh admissions on recycled pages), lens/last flip
    between host writes and island outputs — ONE compiled program, with
    pool + scales + table donated through the island."""
    cfg, params = tiny
    eng = build(cfg, params, tp_mesh(2), kv_dtype="int8")
    rng = np.random.default_rng(0)
    for n in (5, 6):                                   # warmup: both table keys
        eng.submit(rng.integers(0, cfg.vocab, n), max_new=3)
        eng.run()
    recompile_guard.track("decode", eng._decode)
    recompile_guard.track("prefill", eng._prefill)
    recompile_guard.snapshot()
    for n in (4, 6, 8):
        eng.submit(rng.integers(0, cfg.vocab, n), max_new=3)
        eng.submit(rng.integers(0, cfg.vocab, n - 1), max_new=2)
        eng.run()
    # teardown asserts zero misses


def test_sharded_donation_through_island(tiny):
    import jax.numpy as jnp

    from k8s_gpu_scheduler_tpu.analysis.recompile import check_donation

    cfg, params = tiny
    eng = build(cfg, params, tp_mesh(2), kv_dtype="int8")
    args = (params, eng._k, eng._v, eng._ks, eng._vs,
            jnp.asarray(eng._table_np), eng._lens, eng._last,
            np.asarray([True, True]), np.int32(1))
    findings = check_donation(eng._decode, *args, donated=(1, 2, 3, 4, 5),
                              name="decode_tp")
    assert findings == []


def test_entrypoints_scenario_registered():
    from k8s_gpu_scheduler_tpu.analysis import entrypoints as eps
    from k8s_gpu_scheduler_tpu.analysis.recompile import audit_steady_state

    scenarios = dict(eps.recompile_scenarios())
    assert "batcher_steady_decode_paged_tp" in scenarios
    findings = audit_steady_state(
        scenarios["batcher_steady_decode_paged_tp"],
        "batcher_steady_decode_paged_tp")
    assert findings == []


# -- snapshot portability across mesh shapes ----------------------------------

@pytest.mark.slow  # double-covered (PR 15 budget):
# test_partial_shed_absorb_across_tp keeps cross-tp snapshot
# re-sharding tier-1, the across-combines round trip + the unfiltered
# CI pytest run pin this exact tp2→1→4 chain on every push.
def test_snapshot_round_trip_tp2_tp1_tp4(tiny):
    """drain on tp=2 → restore on tp=1 (unsharded) → drain → restore on
    tp=4: every stream finishes byte-identical to an uninterrupted
    unsharded run — fleet shed/failover across heterogeneous replicas."""
    cfg, params = tiny
    prompts = mixed_prompts(cfg, seed=1)

    ref = drive(build(cfg, params, None, kv_dtype="int8",
                      prefix_cache=True), prompts, max_new=6)

    e2 = build(cfg, params, tp_mesh(2), kv_dtype="int8", prefix_cache=True)
    for p in prompts:
        e2.submit(p, max_new=6)
    done = {}
    done.update(e2.step())
    snap = e2.drain()

    e1 = build(cfg, params, None, kv_dtype="int8", prefix_cache=True)
    e1.restore(snap)
    done.update(e1.step())
    snap2 = e1.drain()

    e4 = build(cfg, params, tp_mesh(4), kv_dtype="int8", prefix_cache=True)
    e4.restore(snap2)
    while e4.pending:
        done.update(e4.step())
    assert done == ref


def test_partial_shed_absorb_across_tp(tiny):
    """Partial drain (load shedding) from a tp=2 replica absorbs into an
    unsharded one and the migrated stream stays byte-identical."""
    cfg, params = tiny
    rng = np.random.default_rng(2)
    prompts = [list(rng.integers(0, cfg.vocab, n)) for n in (6, 9)]
    ref = drive(build(cfg, params, None, kv_dtype="int8"), prompts,
                max_new=6)

    src = build(cfg, params, tp_mesh(2), kv_dtype="int8")
    rids = [src.submit(p, max_new=6) for p in prompts]
    done = {}
    done.update(src.step())
    shed_slot = src.active_slot_ids()[0]
    shed_rid = src._slot_req[shed_slot]
    snap = src.drain(slots=[shed_slot])
    assert snap.partial

    tgt = build(cfg, params, None, kv_dtype="int8")
    mapping = tgt.absorb(snap)
    while src.pending:
        done.update(src.step())
    migrated = {}
    while tgt.pending:
        migrated.update(tgt.step())
    done[shed_rid] = done.get(shed_rid, []) + migrated[mapping[shed_rid]]
    assert done == ref


def test_fingerprint_mesh_agnostic(tiny):
    cfg, params = tiny
    fp1 = build(cfg, params, None, kv_dtype="int8").fingerprint()
    fp2 = build(cfg, params, tp_mesh(2), kv_dtype="int8").fingerprint()
    assert fp1 == fp2


# -- validation + fallback gate -----------------------------------------------

def test_mesh_without_tp_axis_rejected(tiny):
    cfg, params = tiny
    devs = jax.devices()
    mesh = Mesh(np.array(devs[:2]), ("dp",))
    with pytest.raises(ValueError, match="tp"):
        build(cfg, params, mesh)


def test_kv_heads_not_divisible_rejected(tiny):
    cfg, params = tiny
    cfg3 = dataclasses.replace(cfg, n_heads=6, n_kv_heads=3,
                               d_model=48)
    params3 = init_params(cfg3, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="divisible"):
        build(cfg3, params3, tp_mesh(2))


def test_paged_mesh_no_longer_rejected(tiny):
    """The PR-3 gate (NotImplementedError: paged requires mesh=None) is
    gone — a mesh-built paged engine serves, fused, with no fallback
    counted."""
    cfg, params = tiny
    serving.reset_decode_fallback_counts()
    eng = build(cfg, params, tp_mesh(2))
    eng.submit([1, 2, 3, 4], max_new=2)
    out = eng.run()
    assert len(out) == 1
    assert "mesh_contiguous" not in serving.decode_fallback_counts()
    assert "mesh_constrained_cache" not in serving.decode_fallback_counts()


def test_contiguous_mesh_fallback_warns_once_and_counts(tiny):
    """The old silent downgrade at the contiguous/static paths is now an
    explicit, warn-once, metric-counted gate."""
    from k8s_gpu_scheduler_tpu.parallel.mesh import MeshSpec, make_mesh

    cfg, params = tiny
    serving.reset_decode_fallback_counts()
    mesh = make_mesh(MeshSpec.for_devices(2, tp=2))
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        eng = ContinuousBatcher(params, cfg, n_slots=2, max_len=32,
                                chunk=2, prefill_bucket=8, mesh=mesh)
        eng.submit([1, 2, 3, 4], max_new=2)
        eng.run()
    counts = serving.decode_fallback_counts()
    assert counts.get("mesh_contiguous", 0) >= 1
    hits = [w for w in caught if "downgraded to the dense path"
            in str(w.message)]
    assert len(hits) == 1                    # warn ONCE per reason


def test_fallback_counter_exported():
    from k8s_gpu_scheduler_tpu.metrics.exporter import (
        DECODE_FALLBACK_TOTAL, Registry, export_decode_fallbacks)

    reg = Registry()
    export_decode_fallbacks(reg, {"mesh_contiguous": 2})
    export_decode_fallbacks(reg, {"mesh_contiguous": 3})   # delta-inc
    c = reg.counter(DECODE_FALLBACK_TOTAL)
    assert c.value(reason="mesh_contiguous") == 3.0
    assert 'tpu_serve_decode_fallback_total{reason="mesh_contiguous"} 3.0' \
        in reg.expose()
    # A SOURCE reset (serving.reset_decode_fallback_counts) re-bases the
    # watermark: downgrades after the reset must still export instead of
    # hiding below the old high-water mark.
    export_decode_fallbacks(reg, {"mesh_contiguous": 1})
    assert c.value(reason="mesh_contiguous") == 4.0


def test_replica_summary_carries_tp(tiny):
    from k8s_gpu_scheduler_tpu.fleet.summary import ReplicaSummary, summarize

    cfg, params = tiny
    eng = build(cfg, params, tp_mesh(2))
    assert eng.replica_stats()["tp"] == 2
    s = summarize(eng, "r0")
    assert s.tp == 2
    assert ReplicaSummary.from_json(s.to_json()).tp == 2


# -- Megatron-sliced weights (weight_sharding) --------------------------------

# A focused slice of the feature grid for the non-default island
# layouts: the DEFAULT (weight-sharded, all_gather) already rides the
# full GRID above, so these only need to prove each alternate layout on
# the production-shaped cells. Double-covered cells ride slow per the
# tier-1 budget convention.
WS_GRID = [
    dict(kv_dtype="int8"),
    # The spec/prefix superset and the chunked/dense cells are strict
    # feature supersets of combinations the DEFAULT grid pins tier-1 —
    # they ride slow (PR 5/8/13 budget pattern); the unfiltered CI run
    # still executes every cell.
    pytest.param(dict(kv_dtype="int8", prefix_cache=True,
                      speculative=True, gamma=2),
                 marks=pytest.mark.slow),
    pytest.param(dict(prefix_cache=True, prefill_chunk_tokens=8),
                 marks=pytest.mark.slow),
    pytest.param(dict(dense=True, kv_dtype="int8"),
                 marks=pytest.mark.slow),
]


def _ws_ids(kw):
    return "-".join(sorted(k for k, v in kw.items() if v)) or "plain"


@pytest.mark.slow  # double-covered (PR 15 budget):
# test_psum_qdot_within_tolerance pins the psum numeric contract tier-1
# and the sharded_weights bench CI step asserts the psum stream-
# agreement floor + sliced bytes on every push; the unfiltered CI
# pytest run still executes every grid cell.
@pytest.mark.parametrize("kw", WS_GRID, ids=_ws_ids)
def test_psum_combine_identity_grid(tiny, kw):
    """tp_combine='psum' is tolerance-checked, not byte-pinned — but on
    the pinned-seed grid the greedy streams still match the unsharded
    reference exactly (argmax only flips on a float near-tie, and these
    seeds have none; the numeric tolerance itself is pinned at the
    helper level below)."""
    cfg, params = tiny
    kw = dict(kw)
    if kw.pop("dense", False):
        cfg = dataclasses.replace(cfg, decode_attn="dense")
    prompts = mixed_prompts(cfg)
    ref = drive(build(cfg, params, None, **kw), prompts)
    got = drive(build(cfg, params, tp_mesh(2), tp_combine="psum", **kw),
                prompts)
    assert got == ref


@pytest.mark.slow  # double-covered (PR 15 budget): the warn-once
# construction test keeps the weight_sharding=False gate tier-1, and
# the sharded_weights bench CI step byte-checks the replicated island
# against the wsharded/tp=1 streams on every push; the unfiltered CI
# pytest run still executes every grid cell.
@pytest.mark.parametrize("kw", WS_GRID, ids=_ws_ids)
def test_replicated_legacy_identity_grid(tiny, kw):
    """weight_sharding=False keeps the PR 12 replicated-weight island
    byte-identical — the legacy layout stays a working fallback."""
    cfg, params = tiny
    kw = dict(kw)
    if kw.pop("dense", False):
        cfg = dataclasses.replace(cfg, decode_attn="dense")
    prompts = mixed_prompts(cfg, seed=5)
    ref = drive(build(cfg, params, None, **kw), prompts)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        eng = build(cfg, params, tp_mesh(2), weight_sharding=False, **kw)
    assert drive(eng, prompts) == ref


@pytest.mark.slow  # double-covered: test_sharded_byte_identity_tp4 pins
# wsharded-all_gather tp=4 identity tier-1; this cell keeps the seed-7
# regression trace (the hb=0-kernel near-tie) in the unfiltered CI run.
def test_wsharded_byte_identity_tp4_all_gather(tiny):
    """all_gather is byte-pinned at ANY width/seed — that is the
    contract (seed 7 is one that historically flushed out a near-tie
    when the hb=0 rung briefly ran the kernel instead of dense)."""
    cfg, params = tiny
    prompts = mixed_prompts(cfg, seed=7)
    ref = drive(build(cfg, params, None, kv_dtype="int8"), prompts)
    got = drive(build(cfg, params, tp_mesh(4), kv_dtype="int8",
                      tp_combine="all_gather"), prompts)
    assert got == ref


@pytest.mark.slow  # double-covered: test_psum_qdot_within_tolerance is
# tier-1 and the sharded_weights bench CI step asserts the psum
# agreement floor on every push; the tp=2 grid and this tp=4 edition
# ride the unfiltered CI run.
def test_wsharded_token_identity_tp4_psum(tiny):
    """psum at tp=4: token-identical on a pinned seed. The combine is
    tolerance-checked by contract, NOT byte-pinned — a logit near-tie
    can legitimately flip an argmax under the changed reduction order
    (seed 7 does exactly that at tp=4), so this cell pins a seed whose
    streams agree; the numeric bound itself is pinned by
    test_psum_qdot_within_tolerance."""
    cfg, params = tiny
    prompts = mixed_prompts(cfg, seed=9)
    ref = drive(build(cfg, params, None, kv_dtype="int8"), prompts)
    got = drive(build(cfg, params, tp_mesh(4), kv_dtype="int8",
                      tp_combine="psum"), prompts)
    assert got == ref


def test_psum_qdot_within_tolerance(tiny):
    """The pinned numeric contract of the psum combine: a row-parallel
    partial-product psum matches the monolithic dot to rel 1e-3 (f32
    accumulation across shards), for plain AND int8 weights — the
    tolerance claim the token-identity grid rides on."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from k8s_gpu_scheduler_tpu.models.serving import _psum_qdot
    from k8s_gpu_scheduler_tpu.ops.quant import qdot, quantize_weight
    from k8s_gpu_scheduler_tpu.parallel.sharding import shard_map

    mesh = tp_mesh(2)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((4, 64)), jnp.bfloat16)
    w = jnp.asarray(rng.standard_normal((64, 32)), jnp.bfloat16)
    fn = shard_map(lambda x, w: _psum_qdot(x, w, "tp"), mesh=mesh,
                   in_specs=(P(None, "tp"), P("tp", None)),
                   out_specs=P(), check_vma=False)
    # bf16 inputs: near-cancelling channels can see a few percent of
    # relative drift across the changed reduction order — the pinned
    # bound is loose in rtol, tight in atol against the ~1e1 magnitudes.
    np.testing.assert_allclose(
        np.asarray(fn(x, w), np.float32),
        np.asarray(qdot(x, w), np.float32), rtol=5e-2, atol=8e-2)
    qw = quantize_weight(w)
    fnq = shard_map(
        lambda x, q, s: _psum_qdot(x, {"q": q, "s": s}, "tp"), mesh=mesh,
        in_specs=(P(None, "tp"), P("tp", None), P()),
        out_specs=P(), check_vma=False)
    np.testing.assert_allclose(
        np.asarray(fnq(x, qw["q"], qw["s"]), np.float32),
        np.asarray(qdot(x, qw), np.float32), rtol=5e-2, atol=8e-2)


def test_per_chip_weight_bytes_scale(tiny):
    """The WEIGHT_SPECS-sliced subset is EXACTLY 1/tp per chip at
    tp ∈ {2, 4} (no padding — divisibility is an __init__ invariant),
    and total per-chip weight residency strictly shrinks (embed/norms/
    lm_head stay replicated, so total is not 1/tp — documented)."""
    cfg, params = tiny
    pm1 = build(cfg, params, None, kv_dtype="int8").pool_metrics()
    sliced1 = pm1["weight_sliced_device_bytes"]
    assert sliced1 > 0
    assert pm1["tp_combine"] == "none"
    for tp in (2, 4):
        pm = build(cfg, params, tp_mesh(tp),
                   kv_dtype="int8").pool_metrics()
        assert pm["weight_sliced_device_bytes"] * tp == sliced1
        assert pm["weight_device_bytes"] < pm1["weight_device_bytes"]
        assert pm["tp_combine"] == "all_gather"
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        rep = build(cfg, params, tp_mesh(2), weight_sharding=False)
    pmr = rep.pool_metrics()
    assert pmr["weight_device_bytes"] == pm1["weight_device_bytes"]
    assert pmr["tp_combine"] == "replicated"


def test_unsliceable_d_ff_fails_loudly_with_divisors(tiny):
    """ffn % tp != 0 must FAIL at __init__ naming the workable widths —
    never silently replicate (the quiet 70B-OOM class)."""
    cfg, params = tiny
    cfg2 = dataclasses.replace(cfg, d_ff=130)
    params2 = init_params(cfg2, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="valid tp divisors") as ei:
        build(cfg2, params2, tp_mesh(4))
    assert "divisible" in str(ei.value)
    # weight_sharding=False does not slice d_ff — the same config
    # builds as a legacy replicated island.
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        build(cfg2, params2, tp_mesh(4), weight_sharding=False)


def test_weight_sharding_off_warns_once_and_counts(tiny):
    cfg, params = tiny
    serving.reset_decode_fallback_counts()
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        build(cfg, params, tp_mesh(2), weight_sharding=False)
        build(cfg, params, tp_mesh(2), weight_sharding=False)
    counts = serving.decode_fallback_counts()
    assert counts.get("weights_replicated", 0) == 2
    hits = [w for w in caught
            if "weight_sharding=False" in str(w.message)]
    assert len(hits) == 1                    # warn ONCE per reason


def test_moe_rejected_for_weight_sharding():
    from k8s_gpu_scheduler_tpu.models.llama import LlamaConfig, init_params

    cfg = dataclasses.replace(LlamaConfig.tiny(), n_experts=2,
                              moe_top_k=1)
    params = init_params(cfg, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="MoE"):
        ContinuousBatcher(params, cfg, n_slots=2, max_len=32, chunk=2,
                          prefill_bucket=8, page_size=8,
                          kv_layout="paged", mesh=tp_mesh(2))


def test_bad_tp_combine_rejected(tiny):
    cfg, params = tiny
    with pytest.raises(ValueError, match="tp_combine"):
        build(cfg, params, tp_mesh(2), tp_combine="allreduce")


@pytest.mark.slow  # double-covered: the default grid + tp4 cell pin identity
def test_snapshot_round_trip_across_combines(tiny):
    """drain on a psum tp=2 replica → restore on all_gather tp=4:
    weights never ride the snapshot (rebuilt from config by the target
    engine), so combine/width are invisible to the handoff."""
    cfg, params = tiny
    prompts = mixed_prompts(cfg, seed=11)
    ref = drive(build(cfg, params, None, kv_dtype="int8"), prompts,
                max_new=6)
    src = build(cfg, params, tp_mesh(2), kv_dtype="int8",
                tp_combine="psum")
    for p in prompts:
        src.submit(p, max_new=6)
    done = {}
    done.update(src.step())
    snap = src.drain()
    tgt = build(cfg, params, tp_mesh(4), kv_dtype="int8",
                tp_combine="all_gather")
    tgt.restore(snap)
    while tgt.pending:
        done.update(tgt.step())
    assert done == ref


def test_wsharded_zero_retrace_and_donation(tiny, recompile_guard):
    """Steady-state decode with SLICED params committed at birth: one
    compiled program across waves (the sliced-weight placement must
    never re-key the jit cache) with pool + scales + table donated
    through the island."""
    import jax.numpy as jnp

    from k8s_gpu_scheduler_tpu.analysis.recompile import check_donation

    cfg, params = tiny
    eng = build(cfg, params, tp_mesh(2), kv_dtype="int8")
    rng = np.random.default_rng(0)
    for n in (5, 6):
        eng.submit(rng.integers(0, cfg.vocab, n), max_new=3)
        eng.run()
    recompile_guard.track("decode", eng._decode)
    recompile_guard.track("prefill", eng._prefill)
    recompile_guard.snapshot()
    for n in (4, 6, 8):
        eng.submit(rng.integers(0, cfg.vocab, n), max_new=3)
        eng.run()
    eng2 = build(cfg, params, tp_mesh(2), kv_dtype="int8")
    args = (eng2.params, eng2._k, eng2._v, eng2._ks, eng2._vs,
            jnp.asarray(eng2._table_np), eng2._lens, eng2._last,
            np.asarray([True, True]), np.int32(1))
    assert check_donation(eng2._decode, *args, donated=(1, 2, 3, 4, 5),
                          name="decode_tp_wsharded") == []


def test_wsharded_scenario_registered():
    from k8s_gpu_scheduler_tpu.analysis import entrypoints as eps
    from k8s_gpu_scheduler_tpu.analysis.recompile import audit_steady_state

    scenarios = dict(eps.recompile_scenarios())
    assert "batcher_steady_decode_paged_tp_wsharded" in scenarios
    findings = audit_steady_state(
        scenarios["batcher_steady_decode_paged_tp_wsharded"],
        "batcher_steady_decode_paged_tp_wsharded")
    assert findings == []


def test_replica_summary_carries_weight_bytes(tiny):
    from k8s_gpu_scheduler_tpu.fleet.summary import ReplicaSummary, summarize

    cfg, params = tiny
    eng = build(cfg, params, tp_mesh(2))
    wb = eng.replica_stats()["weight_device_bytes"]
    assert wb == eng.pool_metrics()["weight_device_bytes"]
    s = summarize(eng, "r0")
    assert s.weight_device_bytes == wb
    assert ReplicaSummary.from_json(s.to_json()).weight_device_bytes == wb


# -- GSPMD audit ---------------------------------------------------------------

def test_gspmd_pass_tree_clean():
    from k8s_gpu_scheduler_tpu.analysis import run_gspmd_pass

    report = run_gspmd_pass()
    assert not report.findings, "\n" + report.render(
        header="gspmd regressions:")


def test_gspmd_fixture_caught():
    fixture = os.path.join(os.path.dirname(__file__), "data",
                           "graftcheck", "bad_gspmd.py")
    from k8s_gpu_scheduler_tpu.analysis import run_gspmd_pass

    report = run_gspmd_pass([fixture])
    rules = {f.rule for f in report.findings}
    assert {"cache-spec-mismatch", "oversized-replicated",
            "unconstrained-scan-carry", "island-weight-spec"} <= rules, \
        rules
    assert report.errors                     # fails the CLI


def test_gspmd_flags_wrong_island_mapping(tiny):
    """A hand-built island whose pool maps the PAGE dim instead of the
    kv-heads dim is flagged — the audit reads shard_map in_names, not
    intent."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from k8s_gpu_scheduler_tpu.analysis.gspmd import audit_sharded_callable
    from k8s_gpu_scheduler_tpu.parallel.sharding import shard_map

    mesh = tp_mesh(2)
    bad = shard_map(lambda pool: pool, mesh=mesh,
                    in_specs=(P(None, "tp"),), out_specs=P(None, "tp"),
                    check_vma=False)
    pool = jnp.zeros((2, 4, 8, 8, 8), jnp.bfloat16)
    findings = audit_sharded_callable(bad, (pool,), "bad_island",
                                      pool_spec=True)
    assert any(f.rule == "island-pool-spec" for f in findings), findings


def test_gspmd_weight_specs_flags_replicated_island(tiny):
    """The PR 12 layout — full weights replicated into the island —
    audited UNDER the weight_specs expectation is flagged: the loud
    version of the silent per-chip-bytes-don't-scale downgrade."""
    from k8s_gpu_scheduler_tpu.analysis.entrypoints import (
        _sharded_tiny_engine,
    )
    from k8s_gpu_scheduler_tpu.analysis.gspmd import audit_sharded_callable

    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        eng = _sharded_tiny_engine(weight_sharding=False)
    args = (eng.params, eng._k, eng._v, eng._ks, eng._vs,
            eng._table_np.copy(), eng._lens, eng._last,
            np.asarray([True, False]), np.int32(2))
    findings = audit_sharded_callable(
        eng._decode, args, "replicated_under_wspec", pool_spec=True,
        weight_specs=True)
    assert any(f.rule == "island-weight-spec" for f in findings), findings


def test_gspmd_wsharded_islands_clean(tiny):
    """The default weight-sharded dispatches audit clean under BOTH
    expectations — pool on kv-heads, weights sliced per WEIGHT_SPECS."""
    from k8s_gpu_scheduler_tpu.analysis.entrypoints import (
        _sharded_tiny_engine,
    )
    from k8s_gpu_scheduler_tpu.analysis.gspmd import audit_sharded_callable

    eng = _sharded_tiny_engine()
    args = (eng.params, eng._k, eng._v, eng._ks, eng._vs,
            eng._table_np.copy(), eng._lens, eng._last,
            np.asarray([True, False]), np.int32(2))
    findings = audit_sharded_callable(
        eng._decode, args, "wsharded_decode", pool_spec=True,
        weight_specs=True)
    assert findings == []
