"""Tiered KV-page cache: host-DRAM demotion + promote-ahead-of-decode
behind the radix tree (models/paging.HostTierStore +
prefix_cache.match_tiered/promote + the serving engine's step-boundary
demotion drain and pre-prefill promotion upload).

Proof obligations of the tiering PR:

- **Token identity** — ``kv_tiering=True`` never changes a stream:
  across dense/fused × int8-KV × speculative × chunked × tp, a trace
  that forces full demote→promote round trips (pool too small, re-
  submitted prompts) produces byte-identical output to the same engine
  with tiering off. Promoted pages hold exactly the bytes the evicted
  pages held (device→host readback, host→device re-upload — no
  recompute, no requantize), so reuse through the tier must be
  output-invisible.
- **Lifecycle** — drain/restore/absorb carry the DRAM tier: a snapshot
  with a populated tier resumes token-identically (same or smaller
  ``dram_pages``, or an untiered target that simply drops the
  sidecar), pre-tiering snapshots load unchanged, and absorbing a shed
  slot whose prefix is DEMOTED on the target un-demotes it in place
  (donated bytes equal the parked ones).
- **Ordering** — demote-before-forget: a full tier degrades to the
  plain eviction outcome (forget), never blocks admission; disk is
  used only when DRAM is full; a match that races a PENDING demotion
  cancels it in place (the retain pin wins, the copy never happens).
- **Truthfulness** — ``digest()`` tier-flags demoted paths (3-tuples),
  ``assert_consistent`` holds through every scenario, and the router
  scores a demoted-path match strictly between a resident match and a
  cold miss (``DEMOTED_MATCH_DISCOUNT``), deterministically.
"""
import dataclasses
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_scheduler_tpu.fleet import (
    MemoryStore, ReplicaSummary, Router, prefix_match_len,
    prefix_match_parts, publish_summary, summarize,
)
from k8s_gpu_scheduler_tpu.fleet.router import DEMOTED_MATCH_DISCOUNT
from k8s_gpu_scheduler_tpu.models import LlamaConfig, init_params
from k8s_gpu_scheduler_tpu.models.paging import HostTierStore, PageAllocator
from k8s_gpu_scheduler_tpu.models.prefix_cache import PrefixCache
from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher
from k8s_gpu_scheduler_tpu.models.snapshot import ServingSnapshot

PAGE = 8


@pytest.fixture(scope="module")
def setup():
    cfg = dataclasses.replace(LlamaConfig.tiny(), dtype=jnp.float32,
                              decode_attn="fused")
    return cfg, init_params(cfg, jax.random.PRNGKey(0))


def mk_engine(params, cfg, **kw):
    """A pool deliberately too small for the workload's cached pages
    (10 pages, ~5 per request): every later admission evicts, so with
    tiering on the tier actually cycles."""
    base = dict(n_slots=2, max_len=64, chunk=2, prefill_bucket=8,
                kv_layout="paged", page_size=PAGE, n_pages=10,
                kv_dtype="int8", prefix_cache=True)
    base.update(kw)
    return ContinuousBatcher(params, cfg, **base)


def mk_prompts(cfg, n=3, seed=5):
    """DISTINCT 28-token prompts (3 full pages each + a tail): no
    cross-prompt sharing, so a re-submitted prompt can only hit via its
    own — by then demoted — path."""
    rng = np.random.default_rng(seed)
    return [list(rng.integers(0, cfg.vocab, 28)) for _ in range(n)]


def drive_seq(params, cfg, trace, prompts, max_new=8, eng=None, **kw):
    """Run ``prompts[i] for i in trace`` ONE AT A TIME (each request
    reaps — and with tiering demotes — before the next admits).
    Returns (streams in trace order, engine)."""
    if eng is None:
        eng = mk_engine(params, cfg, **kw)
    out = []
    for i in trace:
        rid = eng.submit(prompts[i], max_new=max_new)
        done = {}
        while eng.pending:
            done.update(eng.step())
        out.append(done[rid])
    return out, eng


# The canonical demote→promote trace: [0, 1, 2] fills the pool and
# demotes prompt 0/1 pages; the re-submissions must promote them back.
ROUND_TRIP = [0, 1, 2, 0, 1]


# -- constructor validation ---------------------------------------------------

class TestValidation:
    def test_tiering_requires_paged_layout(self, setup):
        cfg, params = setup
        with pytest.raises(ValueError, match="kv_layout='paged'"):
            ContinuousBatcher(params, cfg, n_slots=2, max_len=64,
                              kv_tiering=True)

    def test_tiering_requires_prefix_cache(self, setup):
        cfg, params = setup
        with pytest.raises(ValueError, match="prefix_cache=True"):
            mk_engine(params, cfg, prefix_cache=False, kv_tiering=True)

    def test_tier_knobs_require_tiering(self, setup):
        cfg, params = setup
        with pytest.raises(ValueError, match="kv_tiering=True"):
            mk_engine(params, cfg, dram_pages=8)
        with pytest.raises(ValueError, match="kv_tiering=True"):
            mk_engine(params, cfg, kv_tier_disk="/tmp/nope")


# -- token identity through demote→promote round trips ------------------------

class TestTokenIdentity:
    @pytest.mark.parametrize("impl,kvd,spec", [
        # Tier-1 keeps the richest production cells (fused-int8, with
        # and without speculation — the spec verify path re-walks the
        # promoted pages); the remaining grid rides the slow marker
        # like every other engine grid (unfiltered CI runs every cell).
        ("fused", "int8", False),
        ("fused", "int8", True),
        pytest.param("dense", None, False, marks=pytest.mark.slow),
        pytest.param("dense", "int8", True, marks=pytest.mark.slow),
        pytest.param("fused", None, False, marks=pytest.mark.slow),
    ])
    def test_tiering_on_matches_tiering_off(self, setup, impl, kvd, spec):
        cfg, params = setup
        cfg = dataclasses.replace(cfg, decode_attn=impl)
        prompts = mk_prompts(cfg)
        kw = dict(kv_dtype=kvd, speculative=spec)
        on, eng = drive_seq(params, cfg, ROUND_TRIP, prompts,
                            kv_tiering=True, dram_pages=32, **kw)
        off, _ = drive_seq(params, cfg, ROUND_TRIP, prompts, **kw)
        assert on == off
        m = eng.pool_metrics()
        # The trace must actually exercise the tier — a pool that
        # happened to fit everything would make this cell vacuous.
        assert m["page_demotions_total"] > 0
        assert m["page_promotions_total"] > 0
        assert m["tier_dram_pages"] > 0
        eng._alloc.assert_consistent()

    @pytest.mark.slow
    def test_tiering_identity_on_tp_island(self, setup):
        """The sharded cell: demote→promote round trips through a tp=2
        island (readback gathers the sharded pool, the promotion upload
        re-shards) — streams identical to the untiered island."""
        from jax.sharding import Mesh

        devs = jax.devices()
        if len(devs) < 2:
            pytest.skip(f"needs 2 devices, have {len(devs)}")
        cfg, params = setup
        mesh = Mesh(np.array(devs[:2]), ("tp",))
        prompts = mk_prompts(cfg)
        on, eng = drive_seq(params, cfg, ROUND_TRIP, prompts, mesh=mesh,
                            kv_tiering=True, dram_pages=32)
        off, _ = drive_seq(params, cfg, ROUND_TRIP, prompts, mesh=mesh)
        assert on == off
        assert eng.pool_metrics()["page_promotions_total"] > 0
        eng._alloc.assert_consistent()

    def test_promotion_actually_skips_prefill(self, setup):
        """The point of the feature: the re-submitted prompts' full-page
        prefixes are served from the tier (skipped tokens grow by the
        promoted pages), not re-prefilled."""
        cfg, params = setup
        prompts = mk_prompts(cfg)
        _, eng = drive_seq(params, cfg, ROUND_TRIP, prompts,
                           kv_tiering=True, dram_pages=32)
        m = eng.pool_metrics()
        assert m["prefill_tokens_skipped"] \
            >= m["page_promotions_total"] * PAGE > 0
        # The promoted-hit histogram feed drained once, nonzero.
        batch = m["promoted_hit_token_batch"]
        assert batch and all(t > 0 for t in batch)
        assert "promoted_hit_token_batch" not in eng.pool_metrics()


# -- lifecycle: drain / restore / absorb with a populated tier ----------------

class TestLifecycle:
    def _warm_tiered(self, params, cfg, prompts, **kw):
        """An engine whose tier is POPULATED (the [0,1,2] prefix of the
        round trip) with the re-submissions still queued, stepped once
        so a slot is mid-stream at drain time."""
        out, eng = drive_seq(params, cfg, [0, 1, 2], prompts,
                             kv_tiering=True, dram_pages=32, **kw)
        rids = [eng.submit(prompts[i], max_new=8) for i in (0, 1)]
        done = {}
        done.update(eng.step())
        return eng, rids, done, out

    def test_restore_with_populated_tier(self, setup):
        cfg, params = setup
        prompts = mk_prompts(cfg)
        ref, _ = drive_seq(params, cfg, ROUND_TRIP, prompts,
                           kv_tiering=True, dram_pages=32)
        eng, rids, done, out = self._warm_tiered(params, cfg, prompts)
        snap = eng.drain()
        assert len(snap.tier_keys) > 0          # the tier actually shipped
        snap = ServingSnapshot.from_pytree(snap.to_pytree())
        fresh = mk_engine(params, cfg, kv_tiering=True, dram_pages=32)
        fresh.restore(snap)
        while fresh.pending:
            done.update(fresh.step())
        assert out + [done[r] for r in rids] == ref
        fresh._alloc.assert_consistent()
        # The resumed engine can still PROMOTE from the restored tier.
        extra, _ = drive_seq(params, cfg, [2], prompts, eng=fresh)
        assert extra == [ref[2]]
        assert fresh.pool_metrics()["page_promotions_total"] > 0

    @pytest.mark.slow  # tier-1 keeps the populated-tier restore above
    @pytest.mark.parametrize("restore_kw", [
        dict(kv_tiering=True, dram_pages=4),    # smaller budget: hot tail
        dict(),                                 # untiered: sidecar dropped
    ])
    def test_restore_into_different_tier_budget(self, setup, restore_kw):
        """The tier is a CACHE: a target with a smaller DRAM budget
        keeps the hottest tail, an untiered target drops the sidecar —
        both resume token-identically."""
        cfg, params = setup
        prompts = mk_prompts(cfg)
        ref, _ = drive_seq(params, cfg, ROUND_TRIP, prompts,
                           kv_tiering=True, dram_pages=32)
        eng, rids, done, out = self._warm_tiered(params, cfg, prompts)
        snap = ServingSnapshot.from_pytree(eng.drain().to_pytree())
        fresh = mk_engine(params, cfg, **restore_kw)
        fresh.restore(snap)
        while fresh.pending:
            done.update(fresh.step())
        assert out + [done[r] for r in rids] == ref
        fresh._alloc.assert_consistent()
        m = fresh.pool_metrics()
        if restore_kw:
            assert m["tier_dram_pages"] <= 4
        else:
            assert "tier_dram_pages" not in m

    def test_pre_tiering_snapshot_loads_unchanged(self, setup):
        """Back-compat both ways: an untiered engine's snapshot (no
        tier fields in its pytree — the PR 9 absent-field convention)
        restores into a TIERED engine, which then tiers as usual."""
        cfg, params = setup
        prompts = mk_prompts(cfg)
        ref, _ = drive_seq(params, cfg, ROUND_TRIP, prompts)
        eng = mk_engine(params, cfg)
        out, eng = drive_seq(params, cfg, [0, 1, 2], prompts, eng=eng)
        rids = [eng.submit(prompts[i], max_new=8) for i in (0, 1)]
        done = {}
        done.update(eng.step())
        tree = eng.drain().to_pytree()
        # A pre-tiering writer never emitted tier entries at all:
        # no payload arrays (true of any untiered drain), and no
        # ``tier_keys`` in the metadata doc (stripped here to simulate
        # an old-format snapshot byte-for-byte).
        assert not [k for k in tree if "tier" in str(k)]
        meta = json.loads(bytes(tree["meta_json"]).decode("utf-8"))
        meta.pop("tier_keys", None)
        tree["meta_json"] = np.frombuffer(
            json.dumps(meta).encode("utf-8"), dtype=np.uint8).copy()
        fresh = mk_engine(params, cfg, kv_tiering=True, dram_pages=32)
        fresh.restore(ServingSnapshot.from_pytree(tree))
        while fresh.pending:
            done.update(fresh.step())
        # NOTE: identity vs the UNTIERED reference — tiering preserved
        # the streams even though the tiered engine demotes where the
        # snapshot's writer forgot.
        assert out + [done[r] for r in rids] == ref
        fresh._alloc.assert_consistent()

    @pytest.mark.slow  # unfiltered CI runs it; tier-1 lifecycle is the
    # populated-tier restore + the pre-tiering back-compat cell
    def test_absorb_shed_slot_demoted_on_target(self, setup):
        """The shed slot's prefix path is DEMOTED on the target: the
        absorbed request finishes identically, and its reap-time
        donation un-demotes the target's nodes in place (donated bytes
        equal the parked ones — the tier copy is discarded, not
        duplicated)."""
        cfg, params = setup
        prompts = mk_prompts(cfg)
        ref, _ = drive_seq(params, cfg, ROUND_TRIP, prompts)
        # Target: tier populated, prompt-0 path demoted.
        _, dst = drive_seq(params, cfg, [0, 1, 2], prompts,
                           kv_tiering=True, dram_pages=32)
        assert dst._prefix.demoted_count > 0
        # Prompt 0's path is (partially) demoted on the target —
        # leaf-first eviction demotes its deepest chunks first.
        _, demoted = dst._prefix.match_tiered(prompts[0] + [0],
                                              count=False)
        assert demoted
        promos_before = dst.pool_metrics()["page_promotions_total"]
        # Source: an UNTIERED twin serving prompt 0, shed mid-stream.
        src = mk_engine(params, cfg)
        rid = src.submit(prompts[0], max_new=8)
        src.step()
        snap = ServingSnapshot.from_pytree(
            src.drain(slots=src.active_slot_ids()).to_pytree())
        mapping = dst.absorb(snap)
        src._alloc.assert_consistent()
        dst._alloc.assert_consistent()
        done = {}
        while dst.pending:
            done.update(dst.step())
        assert done[mapping[rid]] == ref[0]
        dst._alloc.assert_consistent()
        # Reap donated prompt 0's resident pages over its demoted
        # nodes: the full path is resident again, and it got there
        # through the DONATION un-demote (tier copy discarded) — no
        # promotion upload ever ran for it.
        path, demoted = dst._prefix.match_tiered(prompts[0] + [0],
                                                 count=False)
        assert demoted == [] and len(path) == 3 \
            and all(p is not None for p in path)
        assert dst.pool_metrics()["page_promotions_total"] \
            == promos_before


# -- ordering: demote-before-forget, disk spill, the pending-match race -------

class TestOrdering:
    def test_full_tier_degrades_to_forget_never_blocks(self, setup):
        """dram_pages=2 cannot hold the workload's evictions: the
        overflow is FORGOTTEN (the plain eviction outcome) while
        admission keeps flowing, and streams still match tiering-off."""
        cfg, params = setup
        prompts = mk_prompts(cfg, n=4)
        trace = [0, 1, 2, 3, 0]
        on, eng = drive_seq(params, cfg, trace, prompts,
                            kv_tiering=True, dram_pages=2)
        off, _ = drive_seq(params, cfg, trace, prompts)
        assert on == off
        m = eng.pool_metrics()
        assert m["page_demotions_total"] > 0
        assert m["tier_forgotten_total"] > 0    # demote-before-forget
        assert m["tier_dram_pages"] <= 2        # budget held throughout
        eng._alloc.assert_consistent()

    @pytest.mark.slow  # disk tier is off by default; unfiltered CI runs it
    def test_disk_spills_only_when_dram_full(self, setup, tmp_path):
        """Third tier, off by default: with a roomy DRAM budget the
        disk directory stays EMPTY; with a tiny one the coldest entries
        spill to disk instead of being forgotten — and a re-submitted
        prompt promotes straight from disk, token-identically."""
        cfg, params = setup
        prompts = mk_prompts(cfg)
        roomy = tmp_path / "roomy"
        tiny = tmp_path / "tiny"
        on, eng = drive_seq(params, cfg, ROUND_TRIP, prompts,
                            kv_tiering=True, dram_pages=32,
                            kv_tier_disk=str(roomy))
        m = eng.pool_metrics()
        assert m["tier_spills_total"] == 0 and m["tier_disk_pages"] == 0
        assert not any(os.scandir(roomy)) if roomy.exists() else True
        on2, eng2 = drive_seq(params, cfg, ROUND_TRIP, prompts,
                              kv_tiering=True, dram_pages=2,
                              kv_tier_disk=str(tiny))
        off, _ = drive_seq(params, cfg, ROUND_TRIP, prompts)
        assert on == on2 == off
        m2 = eng2.pool_metrics()
        assert m2["tier_spills_total"] > 0
        assert m2["page_promotions_total"] > 0  # promoted THROUGH disk
        eng2._alloc.assert_consistent()

    def test_pending_match_race_cancels_demotion(self):
        """A match that crosses a PENDING demotion (bytes not yet
        drained off-pool) un-demotes it in place: the retain pin wins,
        the readback is cancelled, nothing is copied."""
        alloc = PageAllocator(8)
        tier = HostTierStore(16)
        cache = PrefixCache(alloc, 4, tier=tier)
        pages = alloc.alloc(2)
        toks = list(range(8))
        cache.insert(toks, pages)
        assert cache.evict(2) == 2
        assert tier.metrics()["tier_pending_demotions"] == 2
        assert cache.demoted_count == 2
        path, demoted = cache.match_tiered(toks + [99])
        assert demoted == [] and path == pages
        m = tier.metrics()
        assert m["tier_cancelled_demotions"] == 2
        assert m["tier_pending_demotions"] == 0
        # A cancelled enqueue never counts as a demotion: the bytes
        # never left the pool.
        assert m["page_demotions_total"] == 0
        assert len(cache) == 2 and cache.demoted_count == 0
        alloc.assert_consistent()


# -- truthfulness: digest tier flags + router scoring -------------------------

class TestDigestAndRouter:
    def test_digest_tier_flags_demoted_paths(self, setup):
        """A tiered replica's digest entries are 3-tuples whose
        resident length is strictly below the cached length on a
        demoted path; untiered digests stay 2-tuples (wire
        back-compat)."""
        cfg, params = setup
        prompts = mk_prompts(cfg)
        _, eng = drive_seq(params, cfg, [0, 1, 2], prompts,
                           kv_tiering=True, dram_pages=32)
        s = summarize(eng, "r0")
        assert s.dram_cached_pages > 0
        assert all(len(e) == 3 for e in s.digest)
        assert any(e[2] < e[1] for e in s.digest), s.digest
        assert all(0 <= e[2] <= e[1] for e in s.digest)
        _, flat = drive_seq(params, cfg, [0, 1, 2], prompts)
        s2 = summarize(flat, "r1")
        assert s2.dram_cached_pages == 0
        assert all(len(e) == 2 for e in s2.digest)

    def test_summary_json_back_compat(self):
        """PR 9 convention: absent fields default, old payloads parse.
        A pre-tiering JSON (no dram_cached_pages, 2-element digest
        entries) round-trips; mixed 2/3-element digests survive the
        codec."""
        s = ReplicaSummary(replica="r1", fleet="f", page_size=PAGE,
                           pages_total=32, pages_free=10,
                           dram_cached_pages=7,
                           digest=[([1, 2, 3], 8), ([4, 5], 16, 8)])
        got = ReplicaSummary.from_json(s.to_json())
        assert got == s
        old = json.loads(s.to_json())
        del old["dram_cached_pages"]
        old["digest"] = [[[1, 2, 3], 8]]
        legacy = ReplicaSummary.from_json(json.dumps(old))
        assert legacy.dram_cached_pages == 0
        assert legacy.digest == [([1, 2, 3], 8)]

    def test_prefix_match_parts_split_and_tiebreak(self):
        path = list(range(100, 124))            # 3 pages cached
        # 3-tuple: 8 of the 24 cached tokens resident.
        digest = [(path, 24, 8)]
        m, r = prefix_match_parts(path[:20] + [1, 2], digest, PAGE)
        assert (m, r) == (16, 8)
        # Full cover: the last-page cap applies to BOTH parts.
        m, r = prefix_match_parts(path, digest, PAGE)
        assert (m, r) == (16, 8)
        # 2-tuple (untiered / pre-tiering): fully resident.
        assert prefix_match_parts(path + [7], [(path, 24)], PAGE) \
            == (24, 24)
        assert prefix_match_len(path + [7], digest, PAGE) == 24
        # Equal total match: the MORE-RESIDENT entry wins the tie.
        two = [(path, 24, 0), (path, 24, 24)]
        assert prefix_match_parts(path + [7], two, PAGE) == (24, 24)

    def _summaries(self, prompt):
        base = dict(fleet="f", published_wall=0.0, page_size=PAGE,
                    pages_total=32, pages_free=32, n_slots=4,
                    active_slots=0)
        cached = 2 * PAGE
        return {
            "cold": ReplicaSummary(replica="cold", **base),
            "demoted": ReplicaSummary(
                replica="demoted",
                digest=[(prompt[:cached], cached, 0)], **base),
            "resident": ReplicaSummary(
                replica="resident",
                digest=[(prompt[:cached], cached)], **base),
        }

    def test_router_scores_demoted_between_resident_and_cold(self, setup):
        """The satellite ordering: for the same digest path at equal
        load, resident > demoted > cold — a demoted match saves the
        prefill compute but pays the promotion upload."""
        cfg, params = setup
        r = Router([("r0", mk_engine(params, cfg)),
                    ("r1", mk_engine(params, cfg))])
        prompt = list(range(3 * PAGE)) + [7]
        subs = self._summaries(prompt)
        s_cold, m_cold = r.score(subs["cold"], prompt)
        s_dem, m_dem = r.score(subs["demoted"], prompt)
        s_res, m_res = r.score(subs["resident"], prompt)
        assert m_cold == 0 and m_dem == m_res == 2 * PAGE
        assert s_res > s_dem > s_cold
        assert 0.0 < DEMOTED_MATCH_DISCOUNT < 1.0

    def test_routing_with_tier_flags_is_deterministic(self, setup):
        """Same summaries (tier flags included), same placements —
        byte-identical stores route an identical prompt sequence
        identically, and the demoted-path replica actually attracts
        its own prompts over a cold twin."""
        cfg, params = setup
        rng = np.random.default_rng(13)
        hot = list(rng.integers(0, cfg.vocab, 2 * PAGE))
        prompts = [hot + list(rng.integers(0, cfg.vocab, 2 + i % 5))
                   for i in range(10)]

        def placements():
            r = Router([("r0", mk_engine(params, cfg)),
                        ("r1", mk_engine(params, cfg))])
            base = dict(fleet=r.fleet, page_size=PAGE, pages_total=32,
                        pages_free=32, n_slots=4, active_slots=0,
                        published_wall=r._clock.wall())
            publish_summary(r._store, ReplicaSummary(
                replica="r0", dram_cached_pages=2,
                digest=[(hot, 2 * PAGE, 0)], **base))
            publish_summary(r._store, ReplicaSummary(
                replica="r1", **base))
            return [r.route(p) for p in prompts]

        first = placements()
        assert first == placements()
        assert {rid for rid, _, _ in first} == {"r0"}
        assert {pol for _, pol, _ in first} == {"affinity"}
