"""A faithful in-memory fake kube-apiserver (REST + WATCH).

Speaks enough of the real surface (all-namespace LIST, streaming WATCH with
resourceVersion, POST create, merge-PATCH, the Binding subresource, DELETE)
that the ENTIRE scheduler stack — informers, cache, TPU plugin, binding —
runs unchanged over HTTP. Used in-process by tests/test_kubeapi.py and as a
SUBPROCESS by bench.py's REST leg (`python -m tests.fakekube --nodes N`):
a real apiserver is a separate process, so benching against an in-process
fake would charge the scheduler for the server's share of the GIL.
"""
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class FakeKube:
    """In-memory k8s REST server. Store: kind -> {ns/name: json-dict}."""

    def __init__(self):
        self.store = {"pods": {}, "nodes": {}, "configmaps": {},
                      "podgroups": {}, "leases": {}}
        self.rv = 100
        self.mu = threading.Lock()
        self.watchers = []  # (plural, queue-like list, condition)
        self.binding_posts = []
        self.gone_on_watch = False  # next watch connect gets a 410 ERROR
        self.watch_idle_s = 10.0    # idle timeout before closing a watch
        fake = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # Responses must not sit behind Nagle waiting for the client's
            # delayed ACK (keep-alive clients hit this as a ~100ms floor).
            disable_nagle_algorithm = True

            def log_message(self, *a):
                pass

            # -- helpers --------------------------------------------------
            def _send(self, code, doc):
                body = json.dumps(doc).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _route(self):
                # /api/v1/<plural>, /api/v1/namespaces/<ns>/<plural>[/<name>[/binding]]
                parts = [p for p in self.path.split("?")[0].split("/") if p]
                if parts[0] == "apis":
                    parts = parts[3:]  # strip apis/<group>/<version>
                else:
                    parts = parts[2:]  # strip api/v1
                ns = name = sub = None
                if parts and parts[0] == "namespaces":
                    ns, parts = parts[1], parts[2:]
                plural = parts[0]
                if len(parts) > 1:
                    name = parts[1]
                if len(parts) > 2:
                    sub = parts[2]
                return plural, ns, name, sub

            def _body(self):
                n = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(n)) if n else {}

            # -- verbs ----------------------------------------------------
            def do_GET(self):
                plural, ns, name, _ = self._route()
                if name:
                    with fake.mu:
                        obj = fake._get(plural, ns, name)
                    if obj is None:
                        return self._send(404, {"reason": "NotFound"})
                    return self._send(200, obj)
                if "watch=1" in self.path:
                    return self._watch(plural)
                with fake.mu:
                    items = [o for k, o in sorted(fake.store[plural].items())]
                    rv = str(fake.rv)
                return self._send(200, {
                    "kind": "List", "metadata": {"resourceVersion": rv},
                    "items": items,
                })

            def _watch(self, plural):
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                # Real apiserver semantics: replay everything newer than the
                # requested resourceVersion on connect, registered under the
                # SAME lock — a create landing between the client's LIST and
                # this connect is replayed, not lost (the round-2 fake
                # ignored the param, making test_watch_streams_events racy).
                req_rv = 0
                for part in self.path.split("?", 1)[-1].split("&"):
                    if part.startswith("resourceVersion="):
                        v = part.split("=", 1)[1]
                        req_rv = int(v) if v.isdigit() else 0
                cond = threading.Condition()
                events = []
                with fake.mu:
                    if fake.gone_on_watch:
                        # Simulate etcd compaction: the rv is too old.
                        fake.gone_on_watch = False
                        body = json.dumps({
                            "type": "ERROR",
                            "object": {"kind": "Status", "code": 410,
                                       "reason": "Expired",
                                       "message": "too old resource version"},
                        }).encode() + b"\n"
                        self.wfile.write(f"{len(body):x}\r\n".encode()
                                         + body + b"\r\n")
                        self.wfile.write(b"0\r\n\r\n")
                        self.wfile.flush()
                        return
                    for obj in sorted(fake.store[plural].values(),
                                      key=lambda o: int(o["metadata"]
                                                        ["resourceVersion"])):
                        if int(obj["metadata"]["resourceVersion"]) > req_rv:
                            events.append({
                                "type": "ADDED",
                                "object": json.loads(json.dumps(obj)),
                            })
                    fake.watchers.append((plural, events, cond))
                # Deregister on ANY exit (idle timeout, client disconnect)
                # — a dead watcher left in the list would keep receiving a
                # deep copy of every event forever: unbounded growth and
                # O(watchers-ever) emit cost after informer reconnects.
                try:
                    while True:
                        with cond:
                            while not events:
                                if not cond.wait(timeout=fake.watch_idle_s):
                                    return
                            ev = events.pop(0)
                        line = json.dumps(ev).encode() + b"\n"
                        self.wfile.write(f"{len(line):x}\r\n".encode()
                                         + line + b"\r\n")
                        self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError):
                    return
                finally:
                    with fake.mu:
                        try:
                            fake.watchers.remove((plural, events, cond))
                        except ValueError:
                            pass

            def do_POST(self):
                plural, ns, name, sub = self._route()
                body = self._body()
                if sub == "binding":
                    node = body["target"]["name"]
                    with fake.mu:
                        obj = fake._get(plural, ns, name)
                        if obj is None:
                            return self._send(404, {})
                        obj["spec"]["nodeName"] = node
                        fake._bump(obj)
                        fake.binding_posts.append((ns, name, node))
                        fake._emit(plural, "MODIFIED", obj)
                    return self._send(201, {"kind": "Status", "status": "Success"})
                with fake.mu:
                    meta = body.setdefault("metadata", {})
                    meta.setdefault("namespace", ns or "default")
                    key = f"{meta['namespace']}/{meta['name']}"
                    if key in fake.store[plural]:
                        return self._send(409, {"reason": "AlreadyExists"})
                    meta.setdefault("uid", f"uid-{meta['name']}")
                    body.setdefault("spec", {})
                    body.setdefault("status", {"phase": "Pending"}
                                    if plural == "pods" else {})
                    fake._bump(body)
                    fake.store[plural][key] = body
                    fake._emit(plural, "ADDED", body)
                return self._send(201, body)

            def do_PATCH(self):
                plural, ns, name, _ = self._route()
                patch = self._body()
                with fake.mu:
                    obj = fake._get(plural, ns, name)
                    if obj is None:
                        return self._send(404, {})
                    fake._merge(obj, patch)
                    fake._bump(obj)
                    fake._emit(plural, "MODIFIED", obj)
                return self._send(200, obj)

            def do_PUT(self):
                plural, ns, name, _ = self._route()
                body = self._body()
                with fake.mu:
                    obj = fake._get(plural, ns, name)
                    if obj is None:
                        return self._send(404, {})
                    want = (body.get("metadata") or {}).get("resourceVersion")
                    have = obj["metadata"]["resourceVersion"]
                    if want is not None and str(want) != str(have):
                        return self._send(409, {
                            "reason": "Conflict",
                            "message": f"rv mismatch {want} != {have}"})
                    key = f"{obj['metadata'].get('namespace', 'default')}/{name}"
                    if plural == "nodes":
                        key = f"default/{name}"
                    body["metadata"]["namespace"] = obj["metadata"].get(
                        "namespace", "default")
                    fake._bump(body)
                    fake.store[plural][key] = body
                    fake._emit(plural, "MODIFIED", body)
                return self._send(200, body)

            def do_DELETE(self):
                plural, ns, name, _ = self._route()
                with fake.mu:
                    obj = fake._get(plural, ns, name)
                    if obj is None:
                        return self._send(404, {})
                    key = f"{obj['metadata'].get('namespace', 'default')}/{name}"
                    if plural == "nodes":
                        key = f"default/{name}"
                    fake.store[plural].pop(key, None)
                    fake._emit(plural, "DELETED", obj)
                return self._send(200, {"kind": "Status", "status": "Success"})

        # Default TCPServer backlog is 5; a burst of concurrent binders
        # overflows it and dropped SYNs stall 1 s (TCP retransmit) — a real
        # apiserver listens with a deep backlog, so the fake must too.
        class _Server(ThreadingHTTPServer):
            request_queue_size = 128

        self.server = _Server(("127.0.0.1", 0), Handler)
        self.thread = threading.Thread(target=self.server.serve_forever,
                                       daemon=True)
        self.thread.start()

    @property
    def url(self):
        return f"http://127.0.0.1:{self.server.server_port}"

    def _get(self, plural, ns, name):
        key = f"{ns or 'default'}/{name}"
        return self.store[plural].get(key)

    def _bump(self, obj):
        self.rv += 1
        obj.setdefault("metadata", {})["resourceVersion"] = str(self.rv)

    def _merge(self, base, patch):
        """RFC 7386 merge patch: dicts merge recursively, None deletes."""
        for k, v in patch.items():
            if v is None:
                base.pop(k, None)
            elif isinstance(v, dict) and isinstance(base.get(k), dict):
                self._merge(base[k], v)
            else:
                base[k] = v

    def _emit(self, plural, ev_type, obj):
        for wplural, events, cond in self.watchers:
            if wplural == plural:
                with cond:
                    events.append({"type": ev_type,
                                   "object": json.loads(json.dumps(obj))})
                    cond.notify_all()

    def add_node(self, name, chips=8, labels=None):
        lab = {"cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
               "cloud.google.com/gke-tpu-topology": "2x4"}
        lab.update(labels or {})
        with self.mu:
            obj = {
                "kind": "Node",
                "metadata": {"name": name, "labels": lab, "annotations": {},
                             "uid": f"uid-{name}"},
                "status": {
                    "capacity": {"google.com/tpu": str(chips)},
                    "allocatable": {"google.com/tpu": str(chips)},
                    "conditions": [{"type": "Ready", "status": "True"}],
                    "addresses": [{"type": "InternalIP",
                                   "address": "10.0.0.1"}],
                },
            }
            self._bump(obj)
            self.store["nodes"][f"default/{name}"] = obj
            self._emit("nodes", "ADDED", obj)

    def close(self):
        self.server.shutdown()
        self.server.server_close()


def main() -> None:  # pragma: no cover — bench.py subprocess entrypoint
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=0)
    ap.add_argument("--chips", type=int, default=8)
    # Group nodes into slice groups of K hosts (tpu.sched/slice-group +
    # worker-index labels) so gang workloads can run over REST — bench.py's
    # mixed1024 leg uses this.
    ap.add_argument("--slice-size", type=int, default=0)
    # Label the first N nodes zone=hot: a scarce pool the mixed leg
    # saturates with low-priority fillers so preemptors have work to do.
    ap.add_argument("--hot-nodes", type=int, default=0)
    args = ap.parse_args()
    fake = FakeKube()
    for i in range(args.nodes):
        labels = {}
        if args.slice_size:
            labels["tpu.sched/slice-group"] = f"sg-{i // args.slice_size}"
            labels["tpu.sched/worker-index"] = str(i % args.slice_size)
        if i < args.hot_nodes:
            labels["zone"] = "hot"
        fake.add_node(f"v5e-{i}", chips=args.chips, labels=labels)
    print(f"PORT {fake.server.server_port}", flush=True)
    threading.Event().wait()


if __name__ == "__main__":  # pragma: no cover
    main()
