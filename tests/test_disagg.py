"""Disaggregated prefill/decode pools (fleet/router.py ``pools=``).

Proof obligations of the disaggregation PR:

- **Phase isolation** — a ``role='prefill'`` engine admits and chunks
  prefills but NEVER dispatches a decode/verify step: completed
  prefills park at the phase boundary until the router hands them off.
- **Handoff token identity** — a request that prefills on one pool and
  decodes on the other streams byte-identically to a colocated
  single-engine reference, across heterogeneous meshes (prefill tp=1 →
  decode tp∈{2,4}), int8 KV, prefix cache, and speculative decode.
- **Phase-boundary discipline** — only completed prefills hand off;
  mid-prefill slots are refused (``FleetError``).
- **Donation before migration** — the prefill replica keeps the
  conversation's pages in its radix tree after the handoff, so turn 2
  routes back to it with a prefix match.
- **Crash tolerance across the boundary** — an absorb failure
  mid-handoff, or a decode-replica death after it, replays from the
  journal with the ORIGINAL deadline and the stream stays
  byte-identical.
- **Observability** — handoff counter/duration histogram (lazily
  registered: a colocated fleet's exposition is untouched), one-hot
  role gauge, a router ``handoff`` span plus ``handoff_out``/
  ``handoff_in`` flight records correlating one request's
  prefill→handoff→decode timeline.
- **Pool sizing** — ``plan_pools`` is a deterministic pure function:
  prefill scales OUT on backlog tokens, decode scales UP on free-page/
  slot watermarks.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from k8s_gpu_scheduler_tpu.fleet import (
    FleetError, HealthPolicy, MemoryStore, PoolPolicy, ReplicaSummary,
    Router, plan_pools,
)
from k8s_gpu_scheduler_tpu.metrics.exporter import (
    FLEET_HANDOFF_DURATION, FLEET_HANDOFFS_TOTAL, FLEET_REPLICA_ROLE,
    Registry,
)
from k8s_gpu_scheduler_tpu.models import LlamaConfig, init_params
from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher
from k8s_gpu_scheduler_tpu.obs import Tracer
from k8s_gpu_scheduler_tpu.utils.retry import RetryPolicy

PAGE = 8
FAST_QUARANTINE = RetryPolicy(attempts=8, base_s=0.05, multiplier=1.0,
                              max_s=0.1, jitter=0.5)


@pytest.fixture(scope="module")
def setup():
    cfg = dataclasses.replace(LlamaConfig.tiny(), dtype=jnp.float32)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def tp_mesh(tp):
    return Mesh(np.array(jax.devices()[:tp]), ("tp",))


def mk_engine(params, cfg, role="mixed", **kw):
    base = dict(n_slots=4, max_len=64, chunk=4, prefill_bucket=8,
                kv_layout="paged", page_size=PAGE, prefix_cache=True,
                role=role)
    if role == "prefill":
        # The prefill pool runs Sarathi-style chunked prefill — the
        # whole point of specializing the replica.
        base.setdefault("prefill_chunk_tokens", PAGE)
    base.update(kw)
    return ContinuousBatcher(params, cfg, **base)


def mk_disagg(params, cfg, n_prefill=2, n_decode=2, pre_kw=None,
              dec_kw=None, **router_kw):
    pre_kw, dec_kw = dict(pre_kw or {}), dict(dec_kw or {})
    reps = [(f"p{i}", mk_engine(params, cfg, role="prefill", **pre_kw))
            for i in range(n_prefill)]
    reps += [(f"d{i}", mk_engine(params, cfg, role="decode", **dec_kw))
             for i in range(n_decode)]
    pools = {"prefill": [f"p{i}" for i in range(n_prefill)],
             "decode": [f"d{i}" for i in range(n_decode)]}
    kw = dict(store=MemoryStore(), pools=pools,
              health=HealthPolicy(quarantine=FAST_QUARANTINE))
    kw.update(router_kw)
    return Router(reps, **kw)


def mk_prompts(cfg, n=8, lo=10, seed=0):
    rng = np.random.default_rng(seed)
    return [list(rng.integers(0, cfg.vocab, lo + i % 7))
            for i in range(n)]


def reference(params, cfg, prompts, max_new=8, **kw):
    eng = mk_engine(params, cfg, **kw)
    ids = [eng.submit(p, max_new=max_new) for p in prompts]
    done = {}
    while eng.pending:
        done.update(eng.step())
    return [done[i] for i in ids]


# -- engine role mode ------------------------------------------------------
class TestRoleEngine:
    def test_role_validation(self, setup):
        cfg, params = setup
        with pytest.raises(ValueError, match="role"):
            mk_engine(params, cfg, role="weird")
        with pytest.raises(ValueError, match="paged"):
            ContinuousBatcher(params, cfg, kv_layout="contiguous",
                              role="prefill")

    def test_prefill_role_never_decodes(self, setup):
        cfg, params = setup
        eng = mk_engine(params, cfg, role="prefill")
        rid = eng.submit(list(range(1, 1 + 2 * PAGE)), max_new=8)
        for _ in range(12):
            eng.step()
        # Prefill completed (first token emitted), then parked: the
        # decode dispatch never ran, so the stream never grows past 1.
        assert eng.pending
        assert len(eng.emitted(rid)) == 1
        ready = eng.handoff_ready_slots()
        assert [r for _, r in ready] == [rid]
        kinds = {r["kind"] for r in eng._flight.records()}
        assert "prefill_only" in kinds
        assert "decode" not in kinds and "verify" not in kinds

    def test_mid_prefill_not_handoff_ready(self, setup):
        cfg, params = setup
        eng = mk_engine(params, cfg, role="prefill")
        eng.submit(list(range(1, 1 + 4 * PAGE)), max_new=8)
        eng.step()                       # admits; one 8-token chunk in
        assert eng.handoff_ready_slots() == []

    def test_run_refused_on_prefill_role(self, setup):
        cfg, params = setup
        eng = mk_engine(params, cfg, role="prefill")
        eng.submit([1, 2, 3], max_new=4)
        with pytest.raises(RuntimeError, match="spin forever"):
            eng.run()

    def test_role_excluded_from_fingerprint(self, setup):
        cfg, params = setup
        fp_pre = mk_engine(params, cfg, role="prefill",
                           prefill_chunk_tokens=None).fingerprint()
        fp_mix = mk_engine(params, cfg).fingerprint()
        assert fp_pre == fp_mix
        assert "role" not in fp_pre

    def test_replica_stats_and_summary_carry_role(self, setup):
        cfg, params = setup
        eng = mk_engine(params, cfg, role="prefill")
        assert eng.replica_stats()["role"] == "prefill"
        from k8s_gpu_scheduler_tpu.fleet import summarize

        assert summarize(eng, "p0").role == "prefill"

    def test_summary_role_default_back_compat(self):
        # A pre-disagg summary (no role key) must keep parsing.
        s = ReplicaSummary(replica="r0", fleet="f")
        raw = s.to_json()
        import json

        d = json.loads(raw)
        d.pop("role")
        old = ReplicaSummary.from_json(json.dumps(d))
        assert old.role == "mixed"


# -- router pool validation ------------------------------------------------
class TestPoolsValidation:
    def test_partition_and_role_checks(self, setup):
        cfg, params = setup

        def reps():
            return [("p0", mk_engine(params, cfg, role="prefill")),
                    ("d0", mk_engine(params, cfg))]

        with pytest.raises(FleetError, match="keys"):
            Router(reps(), pools={"prefill": ["p0"]})
        with pytest.raises(FleetError, match="at least one"):
            Router(reps(), pools={"prefill": [], "decode": ["p0", "d0"]})
        with pytest.raises(FleetError, match="partition"):
            Router(reps(), pools={"prefill": ["p0"], "decode": ["dX"]})
        with pytest.raises(FleetError, match="role='prefill'"):
            Router(reps(), pools={"prefill": ["d0"], "decode": ["p0"]})

    def test_colocated_rejects_prefill_role(self, setup):
        cfg, params = setup
        with pytest.raises(FleetError, match="pools"):
            Router([("r0", mk_engine(params, cfg, role="prefill"))])

    def test_colocated_fallback_unchanged(self, setup):
        cfg, params = setup
        prompts = mk_prompts(cfg, n=4)
        ref = reference(params, cfg, prompts)
        rtr = Router([("r0", mk_engine(params, cfg))])
        frids = [rtr.submit(p, max_new=8) for p in prompts]
        done = rtr.run()
        assert [done[f] for f in frids] == ref
        assert rtr.stats()["pools"] is None
        assert rtr.stats()["handoffs"] == 0


# -- handoff end-to-end ----------------------------------------------------
class TestDisaggServing:
    def test_token_identity_and_handoff_accounting(self, setup):
        cfg, params = setup
        prompts = mk_prompts(cfg, n=8)
        ref = reference(params, cfg, prompts)
        rtr = mk_disagg(params, cfg)
        frids = [rtr.submit(p, max_new=8, trace_id=f"t{i}")
                 for i, p in enumerate(prompts)]
        # Every NEW admission lands on the prefill pool.
        assert {rtr.locate(f)[0] for f in frids} <= {"p0", "p1"}
        done = rtr.run()
        assert [done[f] for f in frids] == ref
        st = rtr.stats()
        assert st["handoffs"] == len(prompts)
        assert st["requests_lost"] == 0
        assert rtr.errors == {}
        for rep in rtr._replicas.values():
            rep.engine._alloc.assert_consistent()

    def test_decode_pool_fallback_when_prefill_down(self, setup):
        cfg, params = setup
        prompts = mk_prompts(cfg, n=4)
        ref = reference(params, cfg, prompts)
        rtr = mk_disagg(params, cfg, n_prefill=1, n_decode=2)
        rtr._crash("p0", RuntimeError("chaos"))
        frids = [rtr.submit(p, max_new=8) for p in prompts]
        # Degraded to the decode pool (colocated-style): requests
        # complete without a prefill replica, nothing lost.
        assert {rtr.locate(f)[0] for f in frids} <= {"d0", "d1"}
        done = rtr.run()
        assert [done[f] for f in frids] == ref
        assert rtr.stats()["requests_lost"] == 0

    def test_manual_handoff_and_mid_prefill_rejection(self, setup):
        cfg, params = setup
        rtr = mk_disagg(params, cfg, n_prefill=1, n_decode=1)
        frid = rtr.submit(list(range(1, 1 + 4 * PAGE)), max_new=4)
        eng = rtr._replicas["p0"].engine
        eng.step()                       # admit + first chunk only
        assert eng.handoff_ready_slots() == []
        with pytest.raises(FleetError, match="mid-prefill"):
            rtr.handoff(frid)
        while eng.handoff_ready_slots() == []:
            eng.step()                   # finish the prefill
        dst = rtr.handoff(frid)
        assert dst == "d0"
        assert rtr.locate(frid)[0] == "d0"
        with pytest.raises(FleetError, match="already on decode"):
            rtr.handoff(frid)
        done = rtr.run()
        assert len(done[frid]) == 4

    def test_shed_cannot_cross_pools(self, setup):
        cfg, params = setup
        rtr = mk_disagg(params, cfg, n_prefill=1, n_decode=1)
        with pytest.raises(FleetError, match="cross pools"):
            rtr.shed("p0", "d0")

    def test_prefill_side_donation_routes_turn2_back(self, setup):
        cfg, params = setup
        rtr = mk_disagg(params, cfg, n_prefill=2, n_decode=1)
        rng = np.random.default_rng(7)
        turn1 = list(rng.integers(0, cfg.vocab, 3 * PAGE))
        frid = rtr.submit(turn1, max_new=4)
        src = rtr.locate(frid)[0]
        done = rtr.run()
        # The conversation's pages were donated into SRC's tree before
        # the pages migrated: turn 2 scores a prefix match there and
        # routes back to the same prefill replica.
        turn2 = turn1 + done[frid] + [5, 6, 7]
        rid, policy, match = rtr.route(turn2)
        assert policy == "affinity"
        assert rid == src
        assert match >= 2 * PAGE


# -- crash tolerance across the boundary -----------------------------------
class TestHandoffFailover:
    def test_absorb_failure_mid_handoff_replays(self, setup):
        cfg, params = setup
        rtr = mk_disagg(params, cfg, n_prefill=1, n_decode=1)
        prompts = mk_prompts(cfg, n=2)
        ref = reference(params, cfg, prompts)
        de = rtr._replicas["d0"].engine
        real_absorb = de.absorb
        boom = {"n": 1}

        def flaky_absorb(snap):
            if boom["n"]:
                boom["n"] -= 1
                raise RuntimeError("absorb died mid-handoff")
            return real_absorb(snap)

        de.absorb = flaky_absorb
        frids = [rtr.submit(p, max_new=8, deadline_s=300.0)
                 for p in prompts]
        deadlines = {f: rtr.journal.entry(f).deadline_wall
                     for f in frids}
        # Step until the injected absorb failure has fired: the victim
        # was orphaned through the journal mid-handoff and immediately
        # replayed — with its ORIGINAL deadline (reassign only moves
        # the placement).
        while boom["n"]:
            rtr.step()
        for f in frids:
            if f in rtr.journal:
                assert rtr.journal.entry(f).deadline_wall \
                    == deadlines[f]
        done = rtr.run()
        assert [done[f] for f in frids] == ref
        assert rtr.stats()["requests_lost"] == 0
        assert rtr.errors == {}

    def test_decode_replica_death_after_handoff(self, setup):
        cfg, params = setup
        prompts = mk_prompts(cfg, n=3)
        ref = reference(params, cfg, prompts, max_new=12)
        rtr = mk_disagg(params, cfg, n_prefill=1, n_decode=2)
        frids = [rtr.submit(p, max_new=12, trace_id=f"t{i}",
                            deadline_s=300.0)
                 for i, p in enumerate(prompts)]
        deadlines = {f: rtr.journal.entry(f).deadline_wall
                     for f in frids}
        # Step until something decodes on d0, then kill it.
        victim = None
        for _ in range(30):
            rtr.step()
            on_d0 = [f for f in frids if f in rtr._where
                     and rtr._where[f][0] == "d0"]
            if on_d0:
                victim = on_d0[0]
                break
        assert victim is not None
        rtr._crash("d0", RuntimeError("decode pool crash"))
        # The orphan replays THROUGH the prefill pool (route() is
        # pool-restricted), re-prefills prompt+delivered, and hands
        # off again — deadline untouched the whole way.
        assert victim in rtr.journal
        assert rtr.journal.entry(victim).deadline_wall \
            == deadlines[victim]
        done = rtr.run()
        assert [done[f] for f in frids] == ref
        st = rtr.stats()
        assert st["requests_lost"] == 0
        assert st["failovers"] >= 1
        assert rtr.errors == {}


# -- metrics + obs ---------------------------------------------------------
class TestDisaggObservability:
    def test_handoff_metrics_and_role_gauge(self, setup):
        cfg, params = setup
        reg = Registry()
        rtr = mk_disagg(params, cfg, n_prefill=1, n_decode=1,
                        metrics=reg)
        # Lazy histogram: nothing handed off yet → no family exposed.
        assert FLEET_HANDOFF_DURATION not in reg.expose()
        frid = rtr.submit(mk_prompts(cfg, n=1)[0], max_new=4)
        rtr.run()
        text = reg.expose()
        assert (f'{FLEET_HANDOFFS_TOTAL}{{dst="d0",src="p0"}} 1.0'
                in text or f'{FLEET_HANDOFFS_TOTAL}{{src="p0",dst="d0"}}'
                in text)
        assert f"{FLEET_HANDOFF_DURATION}_count" in text
        assert (f'{FLEET_REPLICA_ROLE}{{replica="p0",role="prefill"}} 1.0'
                in text)
        assert (f'{FLEET_REPLICA_ROLE}{{replica="d0",role="decode"}} 1.0'
                in text)
        assert (f'{FLEET_REPLICA_ROLE}{{replica="p0",role="decode"}} 0.0'
                in text)
        assert frid not in rtr.journal   # closed DONE

    def test_colocated_role_gauge_is_mixed(self, setup):
        cfg, params = setup
        reg = Registry()
        Router([("r0", mk_engine(params, cfg))], metrics=reg)
        assert (f'{FLEET_REPLICA_ROLE}{{replica="r0",role="mixed"}} 1.0'
                in reg.expose())

    def test_handoff_span_and_flight_correlation(self, setup):
        cfg, params = setup
        tracer = Tracer()
        rtr = mk_disagg(params, cfg, n_prefill=1, n_decode=1,
                        tracer=tracer,
                        pre_kw=dict(tracer=tracer),
                        dec_kw=dict(tracer=tracer))
        frid = rtr.submit(list(range(1, 1 + 2 * PAGE)), max_new=6,
                          trace_id="conv-1")
        rtr.run()
        # One correlated timeline: prefill chunks on the source, the
        # router handoff span, decode chunks on the target — all under
        # the SAME rid label (label_request re-attaches it post-absorb).
        names = {s.name for s in tracer.spans(rid="conv-1")}
        assert "prefill_chunk" in names
        assert "handoff" in names
        assert "decode_chunk" in names
        h = tracer.spans(rid="conv-1", name="handoff")
        assert h and h[0].lane == "router"
        assert h[0].attrs["src"] == "p0" and h[0].attrs["dst"] == "d0"
        # Flight records on both engines, keyed by the fleet id.
        src_recs = rtr._replicas["p0"].engine._flight.records(
            "handoff_out")
        dst_recs = rtr._replicas["d0"].engine._flight.records(
            "handoff_in")
        assert [r["frid"] for r in src_recs] == [frid]
        assert [r["frid"] for r in dst_recs] == [frid]


# -- pool sizing policy ----------------------------------------------------
class TestPoolPlan:
    @staticmethod
    def _summ(rid, backlog=0, pages_total=32, pages_free=32,
              n_slots=4, active=0):
        return ReplicaSummary(
            replica=rid, fleet="f", page_size=PAGE,
            pages_total=pages_total, pages_free=pages_free,
            n_slots=n_slots, active_slots=active,
            prefill_backlog_tokens=backlog)

    def test_prefill_scales_out_on_backlog(self):
        pools = {"prefill": ["p0", "p1"], "decode": ["d0"]}
        summaries = {"p0": self._summ("p0", backlog=9000),
                     "p1": self._summ("p1", backlog=5000),
                     "d0": self._summ("d0")}
        plan = plan_pools(summaries, pools,
                          PoolPolicy(prefill_tokens_per_replica=4096))
        assert plan.prefill_backlog_tokens == 14000
        assert plan.prefill_replicas == 2
        assert plan.prefill_replicas_desired == 4   # ceil(14000/4096)
        assert not plan.decode_scale_up
        assert plan.decode_pages_desired == plan.decode_pages_total == 32

    def test_decode_scales_up_on_watermarks(self):
        pools = {"prefill": ["p0"], "decode": ["d0", "d1"]}
        summaries = {"p0": self._summ("p0"),
                     "d0": self._summ("d0", pages_free=2,
                                      active=4),     # starved
                     "d1": self._summ("d1")}
        plan = plan_pools(summaries, pools, PoolPolicy())
        assert plan.decode_scale_up
        assert plan.decode_pages_total == 64
        assert plan.decode_pages_desired == 128      # 2x headroom
        assert plan.prefill_replicas_desired == 1
        assert any("free-page" in r for r in plan.reasons)

    def test_plan_is_deterministic_and_ignores_missing(self):
        pools = {"prefill": ["p0"], "decode": ["d0", "dGONE"]}
        summaries = {"p0": self._summ("p0", backlog=100),
                     "d0": self._summ("d0")}
        a = plan_pools(summaries, pools)
        b = plan_pools(summaries, pools)
        assert a == b
        assert a.decode_replicas == 1                # dGONE unobserved

    def test_policy_validation(self):
        with pytest.raises(ValueError):
            PoolPolicy(prefill_tokens_per_replica=0)
        with pytest.raises(ValueError):
            PoolPolicy(decode_free_page_frac_low=1.5)
        with pytest.raises(ValueError):
            PoolPolicy(decode_page_headroom=0.5)

    def test_router_pool_plan_wrapper(self, setup):
        cfg, params = setup
        rtr = mk_disagg(params, cfg, n_prefill=1, n_decode=1)
        plan = rtr.pool_plan()
        assert plan.prefill_replicas == 1
        assert plan.decode_replicas == 1
        colo = Router([("r0", mk_engine(params, cfg))])
        with pytest.raises(FleetError, match="pools"):
            colo.pool_plan()


# -- cross-tp / feature handoff grid ---------------------------------------
def run_disagg_grid(setup, dec_tp, pre_kw=None, dec_kw=None, max_new=8):
    cfg, params = setup
    prompts = mk_prompts(cfg, n=4, lo=12, seed=3)
    pre_kw = dict(pre_kw or {})
    dec_kw = dict(dec_kw or {})
    if dec_tp > 1:
        dec_kw["mesh"] = tp_mesh(dec_tp)
    ref = reference(params, cfg, prompts, max_new=max_new,
                    **{k: v for k, v in dec_kw.items() if k != "mesh"})
    rtr = mk_disagg(params, cfg, n_prefill=1, n_decode=1,
                    pre_kw=pre_kw, dec_kw=dec_kw)
    frids = [rtr.submit(p, max_new=max_new) for p in prompts]
    done = rtr.run()
    assert [done[f] for f in frids] == ref
    st = rtr.stats()
    assert st["handoffs"] >= len(prompts)
    assert st["requests_lost"] == 0
    for rep in rtr._replicas.values():
        rep.engine._alloc.assert_consistent()


class TestCrossTpHandoff:
    def test_tp1_prefill_to_tp2_decode(self, setup):
        run_disagg_grid(setup, dec_tp=2)

    @pytest.mark.slow
    def test_tp1_prefill_to_tp4_decode(self, setup):
        run_disagg_grid(setup, dec_tp=4)

    @pytest.mark.slow
    def test_tp2_decode_int8_kv(self, setup):
        run_disagg_grid(setup, dec_tp=2,
                        pre_kw=dict(kv_dtype="int8"),
                        dec_kw=dict(kv_dtype="int8"))

    @pytest.mark.slow
    def test_tp2_decode_no_prefix_cache(self, setup):
        run_disagg_grid(setup, dec_tp=2,
                        pre_kw=dict(prefix_cache=False),
                        dec_kw=dict(prefix_cache=False))

    @pytest.mark.slow
    def test_tp2_decode_speculative(self, setup):
        # speculative=True FLEET-WIDE (fingerprint pins spec/gamma for
        # page-reservation safety); the prefill-role engine never
        # proposes or verifies — spec there is a compat declaration.
        run_disagg_grid(setup, dec_tp=2,
                        pre_kw=dict(speculative=True, gamma=2),
                        dec_kw=dict(speculative=True, gamma=2))

    def test_speculative_handoff_tp1(self, setup):
        run_disagg_grid(setup, dec_tp=1,
                        pre_kw=dict(speculative=True, gamma=2),
                        dec_kw=dict(speculative=True, gamma=2))
