"""Drain → snapshot → restore: the preemption-safe serving loop.

The headline acceptance test of the robustness PR: a paged engine
interrupted mid-stream (drain), serialized (models/snapshot.py), and
restored into a FRESH engine — same or different ``n_pages`` — must
resume every interrupted request **token-identically** to an
uninterrupted run, across decode impls × cache dtypes × int8-KV ×
prefix-cache × speculative. Proof obligations after restore:
``PageAllocator.assert_consistent`` (the refcount partition holds by
construction) and the shared-page alias check (mounted prefix pages are
byte-identical through post-restore dispatches). The snapshot also
round-trips through the orbax machinery in utils/checkpoint.py — the
persistence path a real preemption handler uses.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_scheduler_tpu.models import LlamaConfig, init_params
from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher
from k8s_gpu_scheduler_tpu.models.snapshot import (
    ServingSnapshot, SnapshotError, check_fingerprint,
)

PAGE = 8


def mk_cfg(dtype=jnp.float32, impl="dense"):
    return dataclasses.replace(LlamaConfig.tiny(), dtype=dtype,
                               decode_attn=impl)


def mk_engine(params, cfg, **kw):
    base = dict(n_slots=2, max_len=64, chunk=4, prefill_bucket=8,
                kv_layout="paged", page_size=PAGE)
    base.update(kw)
    return ContinuousBatcher(params, cfg, **base)


def mk_workload(cfg, shared_prefix=False, seed=0):
    """Prompts + budgets sized so a mid-run drain catches slots mid-
    decode AND requests still queued. With ``shared_prefix``, two
    2-page system prompts are shared so the prefix tree has donated
    pages at drain time."""
    rng = np.random.default_rng(seed)
    if shared_prefix:
        sysA = list(rng.integers(0, cfg.vocab, 2 * PAGE))
        sysB = list(rng.integers(0, cfg.vocab, 2 * PAGE))
        prompts = [sysA + list(rng.integers(0, cfg.vocab, 3 + i))
                   for i in range(3)]
        prompts += [sysB + list(rng.integers(0, cfg.vocab, 2 + i))
                    for i in range(2)]
    else:
        prompts = [list(rng.integers(0, cfg.vocab, n))
                   for n in (10, 17, 5, 23, 7)]
    return prompts


def run_uninterrupted(params, cfg, prompts, max_new=9, **kw):
    eng = mk_engine(params, cfg, **kw)
    ids = [eng.submit(p, max_new=max_new) for p in prompts]
    done = {}
    while eng.pending:
        done.update(eng.step())
    return {i: done[i] for i in ids}


def run_interrupted(params, cfg, prompts, preempt_after, max_new=9,
                    restore_kw=None, codec=True, **kw):
    """Step ``preempt_after`` times, drain, (optionally) round-trip the
    snapshot through the pytree codec, restore into a fresh engine
    (``restore_kw`` overrides, e.g. a different n_pages), finish.
    Returns (streams, drained engine, fresh engine, snapshot)."""
    eng = mk_engine(params, cfg, **kw)
    ids = [eng.submit(p, max_new=max_new) for p in prompts]
    done = {}
    for _ in range(preempt_after):
        done.update(eng.step())
    snap = eng.drain()
    if codec:
        snap = ServingSnapshot.from_pytree(snap.to_pytree())
    fresh = mk_engine(params, cfg, **{**kw, **(restore_kw or {})})
    resumed = fresh.restore(snap)
    assert resumed == snap.n_requests_in_flight > 0
    while fresh.pending:
        done.update(fresh.step())
    return {i: done[i] for i in ids}, eng, fresh, snap


class TestTokenIdentity:
    """The acceptance grid: {dense,fused} × {f32,bf16} × int8-KV ×
    prefix on/off × speculative on/off. Production-shaped cells stay
    tier-1; redundant coverage cells ride the slow marker like every
    other engine grid in this suite."""

    @pytest.mark.parametrize("impl,dtype,kvd,prefix,spec", [
        # Tier-1 keeps the RICHEST production cell (fused-int8 WITH
        # prefix+spec); every other cell — including the dense-f32
        # reference since the PR 15 budget pass — is covered by that
        # superset plus the chaos bench CI step (drain→restore identity
        # every push) and rides the slow marker (the fleet PR's tier-1
        # additions paid for their wall-clock here — unfiltered CI
        # still runs every cell).
        pytest.param("dense", jnp.float32, None, False, False,
                     marks=pytest.mark.slow),
        pytest.param("fused", jnp.bfloat16, "int8", False, False,
                     marks=pytest.mark.slow),
        pytest.param("dense", jnp.float32, None, True, False,
                     marks=pytest.mark.slow),
        ("fused", jnp.bfloat16, "int8", True, True),
        pytest.param("dense", jnp.float32, "int8", False, True,
                     marks=pytest.mark.slow),
        pytest.param("fused", jnp.float32, None, True, False,
                     marks=pytest.mark.slow),
        pytest.param("dense", jnp.bfloat16, None, False, False,
                     marks=pytest.mark.slow),
        pytest.param("fused", jnp.bfloat16, None, True, True,
                     marks=pytest.mark.slow),
    ])
    def test_resume_is_token_identical(self, impl, dtype, kvd, prefix,
                                       spec):
        cfg = mk_cfg(dtype, impl)
        params = init_params(cfg, jax.random.PRNGKey(0))
        prompts = mk_workload(cfg, shared_prefix=prefix)
        kw = dict(kv_dtype=kvd, prefix_cache=prefix, speculative=spec)
        ref = run_uninterrupted(params, cfg, prompts, **kw)
        got, eng, fresh, snap = run_interrupted(
            params, cfg, prompts, preempt_after=3, **kw)
        assert got == ref
        fresh._alloc.assert_consistent()
        assert snap.n_requests_in_flight >= 1
        m = fresh.pool_metrics()
        assert m["requests_resumed_total"] == snap.n_requests_in_flight
        assert m["restore_duration_seconds"] > 0
        assert eng.pool_metrics()["drain_duration_seconds"] > 0

    # Slow since the fleet PR (tier-1 wall-clock): the old→new page
    # re-layout under a DIFFERENT allocator state is exercised tier-1
    # by test_fleet's absorb-into-a-busy-engine cells (same LUT path);
    # the full larger/smaller/too-small pool matrix runs in the
    # unfiltered CI suite.
    @pytest.mark.slow
    def test_restore_into_larger_and_smaller_pool(self):
        """``n_pages`` is exempt from the fingerprint: restore into a
        bigger pool and into the smallest pool that still fits — both
        resume identically; a pool that cannot fit raises."""
        cfg = mk_cfg()
        params = init_params(cfg, jax.random.PRNGKey(0))
        prompts = mk_workload(cfg)
        ref = run_uninterrupted(params, cfg, prompts)
        for n_pages in (48, None):
            got, _, fresh, snap = run_interrupted(
                params, cfg, prompts, preempt_after=3,
                restore_kw=dict(n_pages=n_pages) if n_pages else None)
            assert got == ref
            fresh._alloc.assert_consistent()
        # Too small to hold even the snapshot's referenced pages.
        eng = mk_engine(params, cfg)
        for p in prompts:
            eng.submit(p, max_new=9)
        eng.step()
        snap = eng.drain()
        tiny = mk_engine(params, cfg, n_pages=len(snap.page_ids))
        # len(page_ids) total pages = len-1 usable < referenced count.
        with pytest.raises(SnapshotError, match="free"):
            tiny.restore(snap)

    def test_prefix_tree_and_shared_pages_survive_restore(self):
        """Restore rebuilds the radix tree (reuse keeps working: a
        post-restore admission of a cached prefix skips prefill rows)
        and the alias proof obligation: mounted shared pages are
        byte-identical through post-restore dispatches."""
        cfg = mk_cfg()
        params = init_params(cfg, jax.random.PRNGKey(0))
        prompts = mk_workload(cfg, shared_prefix=True)
        eng = mk_engine(params, cfg, prefix_cache=True, n_slots=2)
        ids = [eng.submit(p, max_new=4) for p in prompts]
        done = {}
        # Step until some requests reaped (their prompts donated) but
        # others still queued/in flight.
        while len(done) < 2:
            done.update(eng.step())
        snap = eng.drain()
        assert snap.tree_paths, "drain must carry the radix tree"
        fresh = mk_engine(params, cfg, prefix_cache=True, n_slots=2)
        fresh.restore(snap)
        fresh._alloc.assert_consistent()
        assert len(fresh._prefix) == len(
            {p for _, pgs in snap.tree_paths for p in pgs})
        # Alias check across a post-restore step: every page the tree
        # holds (shared or not) must come back byte-identical.
        tree_pages = sorted(fresh._alloc._cached)
        assert tree_pages
        before = np.array(np.asarray(fresh._k)[:, tree_pages])
        while fresh.pending:
            done.update(fresh.step())
        assert np.array_equal(
            np.asarray(fresh._k)[:, tree_pages], before)
        # Reuse still works: resubmitting a cached prompt skips rows.
        skipped0 = fresh.pool_metrics()["prefill_tokens_skipped"]
        rid = fresh.submit(prompts[0], max_new=2)
        while fresh.pending:
            fresh.step()
        assert fresh.pool_metrics()["prefill_tokens_skipped"] > skipped0

    # Slow since the fleet PR (tier-1 wall-clock): queued-request
    # resume rides tier-1 through test_fleet's zero-page (queue-only)
    # snapshot lifecycle cell; unfiltered CI runs this too.
    @pytest.mark.slow
    def test_queued_requests_resume_too(self):
        """Requests still WAITING at drain (never admitted) survive: a
        1-slot engine drains with most of the queue untouched."""
        cfg = mk_cfg()
        params = init_params(cfg, jax.random.PRNGKey(0))
        prompts = mk_workload(cfg)
        ref = run_uninterrupted(params, cfg, prompts, n_slots=1)
        got, _, fresh, snap = run_interrupted(
            params, cfg, prompts, preempt_after=2, n_slots=1)
        assert got == ref
        assert snap.queue, "drain should have caught waiting requests"


class TestLifecycleContract:
    def test_drained_engine_refuses_work(self):
        cfg = mk_cfg()
        params = init_params(cfg, jax.random.PRNGKey(0))
        eng = mk_engine(params, cfg)
        eng.submit([1, 2, 3], max_new=4)
        eng.step()
        eng.drain()
        with pytest.raises(RuntimeError, match="drained"):
            eng.submit([4, 5], max_new=2)
        with pytest.raises(RuntimeError, match="drained"):
            eng.step()
        with pytest.raises(RuntimeError, match="already drained"):
            eng.drain()

    def test_restore_needs_fresh_engine(self):
        cfg = mk_cfg()
        params = init_params(cfg, jax.random.PRNGKey(0))
        eng = mk_engine(params, cfg)
        eng.submit([1, 2, 3], max_new=4)
        eng.step()
        snap_donor = mk_engine(params, cfg)
        snap_donor.submit([5, 6], max_new=3)
        snap_donor.step()
        snap = snap_donor.drain()
        with pytest.raises(SnapshotError, match="FRESH"):
            eng.restore(snap)

    def test_fingerprint_mismatch_rejected(self):
        cfg = mk_cfg()
        params = init_params(cfg, jax.random.PRNGKey(0))
        eng = mk_engine(params, cfg)
        eng.submit([1, 2, 3], max_new=4)
        eng.step()
        snap = eng.drain()
        for bad_kw, key in [
            (dict(page_size=16), "page_size"),
            (dict(chunk=8), "chunk"),
            (dict(kv_dtype="int8"), "kv_dtype"),
            (dict(prefix_cache=True), "prefix_cache"),
            (dict(n_slots=4), "n_slots"),
        ]:
            other = mk_engine(params, cfg, **bad_kw)
            with pytest.raises(SnapshotError, match=key):
                other.restore(snap)
        # n_pages difference alone is fine by design.
        check_fingerprint(snap.fingerprint,
                          {**snap.fingerprint, "n_pages": 999})

    def test_contiguous_layout_cannot_drain(self):
        cfg = mk_cfg()
        params = init_params(cfg, jax.random.PRNGKey(0))
        eng = ContinuousBatcher(params, cfg, n_slots=2, max_len=64,
                                chunk=4, prefill_bucket=8)
        with pytest.raises(SnapshotError, match="paged"):
            eng.drain()

    def test_snapshot_validate_catches_corruption(self):
        cfg = mk_cfg()
        params = init_params(cfg, jax.random.PRNGKey(0))
        eng = mk_engine(params, cfg)
        eng.submit(list(range(1, 12)), max_new=6)
        eng.step()
        snap = eng.drain()
        assert snap.nbytes() > 0
        broken = dataclasses.replace(
            snap, page_ids=snap.page_ids[:-1],
            k_pages=snap.k_pages[:, :-1], v_pages=snap.v_pages[:, :-1])
        with pytest.raises(SnapshotError):
            broken.validate()

    def test_clock_rebasing_charges_downtime(self):
        """TTFT/latency records survive the process boundary and keep
        charging the preemption gap itself."""
        snap = ServingSnapshot(
            fingerprint={}, page_ids=[], k_pages=np.zeros((1, 0, 8, 1, 4)),
            v_pages=np.zeros((1, 0, 8, 1, 4)), k_scales=None, v_scales=None,
            table=np.zeros((1, 8), np.int32), lens=np.zeros(1, np.int32),
            last=np.zeros(1, np.int32), slot_req={}, slot_pages={},
            slot_shared={}, slot_prompt={}, budgets={}, out={}, queue=[],
            next_id=0, eos_scanned={}, tree_paths=[],
            arrival={7: 100.0}, drained_mono=103.0, drained_wall=1000.0)
        rebased = snap.rebased_clock(snap.arrival, now_mono=50.0,
                                     now_wall=1010.0)
        # Age = (103-100) before drain + 10 s downtime = 13 s.
        assert rebased[7] == pytest.approx(50.0 - 13.0)


class TestCheckpointPersistence:
    # Slow since the fleet PR: the drain → orbax → restore → identity
    # path rides tier-1 through tests/test_fleet.py's lifecycle cells
    # (Preempted + zero-page snapshots); unfiltered CI runs this too.
    @pytest.mark.slow
    def test_orbax_round_trip_resumes_identically(self, tmp_path):
        """The real persistence path: drain → to_pytree → orbax save →
        restore → from_pytree → restore — token identity end to end."""
        pytest.importorskip("orbax.checkpoint")
        from k8s_gpu_scheduler_tpu.utils.checkpoint import TrainCheckpointer

        cfg = mk_cfg()
        params = init_params(cfg, jax.random.PRNGKey(0))
        prompts = mk_workload(cfg)
        ref = run_uninterrupted(params, cfg, prompts)

        eng = mk_engine(params, cfg)
        ids = [eng.submit(p, max_new=9) for p in prompts]
        done = {}
        for _ in range(3):
            done.update(eng.step())
        snap = eng.drain()
        with TrainCheckpointer(str(tmp_path / "snap")) as ckpt:
            assert ckpt.save(0, snap.to_pytree(), force=True)
        with TrainCheckpointer(str(tmp_path / "snap")) as ckpt:
            tree = ckpt.restore(0)
        fresh = mk_engine(params, cfg)
        fresh.restore(ServingSnapshot.from_pytree(tree))
        while fresh.pending:
            done.update(fresh.step())
        assert {i: done[i] for i in ids} == ref
