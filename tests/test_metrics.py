"""Metrics layer tests: golden-fixture parsing, mock instant-query server
with concurrent fan-out, and the scheduler's own exporter.

Mirrors the reference's two hermetic test flavors (SURVEY.md §4): golden
Prometheus fixtures (prom_metrics_test.go:16-77 w/ test_data/
prom_response_mock.txt) and an httptest mock endpoint
(requests/request_test.go:75-88) — rebuilt around TPU series.
"""
import json
import os
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import pytest

from k8s_gpu_scheduler_tpu.metrics import (
    MXU_DUTY_CYCLE,
    HBM_USED,
    MetricsError,
    MetricsServer,
    PromClient,
    Registry,
    TPU_SERIES,
    parse_response,
)

GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data",
                      "tpu_prom_response.json")


class TestParseResponse:
    def test_golden_fixture(self):
        with open(GOLDEN, "rb") as f:
            samples = parse_response(f.read())
        # 5 results, 1 has a non-numeric value and is skipped
        assert len(samples) == 4
        duty = [s for s in samples if s.metric_name == MXU_DUTY_CYCLE]
        assert {s.node for s in duty} == {"v5e-node-0", "v5e-node-1"}
        first = next(s for s in duty if s.device_id == "0" and s.node == "v5e-node-0")
        assert first.value == 87.5
        assert first.exporter == "tpu-agent-x7k2p"
        assert first.labels["accelerator"] == "tpu-v5-lite-podslice"
        hbm = next(s for s in samples if s.metric_name == HBM_USED)
        assert hbm.value == 12884901888.0

    def test_nil_and_empty(self):
        # Parity with the reference's nil-input case (prom_metrics_test.go).
        assert parse_response(None) == []
        assert parse_response(b"") == []
        empty = json.dumps({"status": "success", "data": {"resultType": "vector", "result": []}})
        assert parse_response(empty.encode()) == []

    def test_error_status_raises(self):
        bad = json.dumps({"status": "error", "error": "query parse error"})
        with pytest.raises(MetricsError, match="query parse error"):
            parse_response(bad.encode())

    def test_garbage_raises(self):
        with pytest.raises(MetricsError):
            parse_response(b"<html>not prometheus</html>")


class MockProm:
    """Instant-query mock — httptest.NewServer parity. Serves the golden
    vector filtered by the query's series name and optional node matcher."""

    def __init__(self, delay_s=0.0):
        with open(GOLDEN) as f:
            golden = json.load(f)
        received = []
        self.received = received
        delay = delay_s

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                url = urlparse(self.path)
                if url.path != "/api/v1/query":
                    self.send_error(404)
                    return
                query = parse_qs(url.query).get("query", [""])[0]
                received.append(query)
                if delay:
                    time.sleep(delay)
                series = query.split("{")[0]
                node = None
                if 'node="' in query:
                    node = query.split('node="')[1].split('"')[0]
                result = [
                    r for r in golden["data"]["result"]
                    if r["metric"]["__name__"] == series
                    and (node is None or r["metric"]["node"] == node)
                ]
                body = json.dumps(
                    {"status": "success",
                     "data": {"resultType": "vector", "result": result}}
                ).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    @property
    def url(self):
        return f"http://127.0.0.1:{self.port}"

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


@pytest.fixture
def mock_prom():
    m = MockProm()
    yield m
    m.stop()


class TestPromClient:
    def test_instant_query(self, mock_prom):
        c = PromClient(mock_prom.url)
        samples = c.instant_query(MXU_DUTY_CYCLE)
        assert len(samples) == 3  # 4 series entries, 1 non-numeric skipped
        assert all(s.metric_name == MXU_DUTY_CYCLE for s in samples)

    def test_tpu_metrics_for_node(self, mock_prom):
        c = PromClient(mock_prom.url)
        by_series = c.tpu_metrics_for_node("v5e-node-0")
        assert set(by_series) == set(TPU_SERIES)
        assert [s.value for s in by_series[MXU_DUTY_CYCLE]] == [87.5, 92.5]
        assert len(mock_prom.received) == len(TPU_SERIES)

    def test_node_duty_cycle_mean(self, mock_prom):
        c = PromClient(mock_prom.url)
        assert c.node_duty_cycle("v5e-node-0") == 90.0  # (87.5+92.5)/2
        assert c.node_duty_cycle("absent-node") is None

    def test_fan_out_is_concurrent(self):
        # 5 series × 0.2s serial = 1s; concurrent must be well under that.
        m = MockProm(delay_s=0.2)
        try:
            c = PromClient(m.url, timeout_s=5)
            t0 = time.perf_counter()
            c.tpu_metrics()
            elapsed = time.perf_counter() - t0
            assert elapsed < 0.6, f"fan-out looks serial: {elapsed:.2f}s"
        finally:
            m.stop()

    def test_unreachable_endpoint(self):
        c = PromClient("http://127.0.0.1:1", timeout_s=0.2)
        with pytest.raises(MetricsError, match="unreachable"):
            c.instant_query(MXU_DUTY_CYCLE)
        # fan_out degrades to empty per-series results
        assert all(v == [] for v in c.tpu_metrics().values())


class TestExporter:
    def test_counter_gauge_histogram_exposition(self):
        reg = Registry()
        reg.counter("sched_attempts_total", "attempts").inc(result="scheduled")
        reg.counter("sched_attempts_total").inc(result="scheduled")
        reg.counter("sched_attempts_total").inc(result="unschedulable")
        reg.gauge("pending_pods", "queue depth").set(7)
        h = reg.histogram("cycle_seconds", "cycle", buckets=(0.01, 0.1, 1.0))
        for v in (0.005, 0.05, 0.5, 5.0):
            h.observe(v)
        text = reg.expose()
        assert 'sched_attempts_total{result="scheduled"} 2.0' in text
        assert 'sched_attempts_total{result="unschedulable"} 1.0' in text
        assert "pending_pods 7.0" in text
        assert 'cycle_seconds_bucket{le="0.01"} 1' in text
        assert 'cycle_seconds_bucket{le="0.1"} 2' in text
        assert 'cycle_seconds_bucket{le="1.0"} 3' in text
        assert 'cycle_seconds_bucket{le="+Inf"} 4' in text
        assert "cycle_seconds_count 4" in text

    def test_histogram_quantile(self):
        reg = Registry()
        h = reg.histogram("lat", "x")
        for i in range(100):
            h.observe(i / 1000.0)
        assert h.quantile(0.5) == pytest.approx(0.05, abs=0.002)
        assert reg.histogram("lat").count == 100

    def test_metrics_server_scrape_roundtrip(self):
        reg = Registry()
        reg.counter("hits_total", "hits").inc()
        srv = MetricsServer(reg, port=0).start()
        try:
            with urllib.request.urlopen(f"http://127.0.0.1:{srv.port}/metrics") as r:
                body = r.read().decode()
            assert "hits_total 1.0" in body
            # and our own PromClient-style consumer can't scrape non-/metrics
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(f"http://127.0.0.1:{srv.port}/other")
        finally:
            srv.stop()

    def test_type_conflict_rejected(self):
        reg = Registry()
        reg.counter("m", "x")
        with pytest.raises(TypeError):
            reg.gauge("m", "x")


class TestServingPoolExport:
    def test_pool_metrics_become_prometheus_gauges(self):
        """The serving pool/prefix-cache numbers that previously lived
        only in pool_metrics()/bench ride the standard /metrics
        exposition: every published key gets a tpu_serve_* gauge with
        help text, and scraping round-trips the values."""
        from k8s_gpu_scheduler_tpu.metrics import (
            SERVING_POOL_GAUGES, export_serving_pool,
        )

        reg = Registry()
        snapshot = {
            "pages_total": 32.0, "pages_free": 20.0, "pages_in_use": 12.0,
            "pages_cached": 5.0, "pages_watermark": 14.0,
            "page_utilization": 0.375, "prefix_hit_rate": 0.8,
            "prefix_request_hit_rate": 1.0, "prefix_cached_pages": 5.0,
            "prefix_evictions": 2.0, "prefill_tokens_skipped": 576.0,
        }
        export_serving_pool(reg, snapshot)
        text = reg.expose()
        assert "tpu_serve_page_utilization 0.375" in text
        assert "tpu_serve_pages_watermark 14.0" in text
        assert "tpu_serve_prefix_hit_rate 0.8" in text
        assert "tpu_serve_prefix_cached_pages 5.0" in text
        assert "tpu_serve_prefix_evictions 2.0" in text
        assert "tpu_serve_prefill_tokens_skipped 576.0" in text
        assert "# HELP tpu_serve_pages_cached" in text
        # Every exported key is documented in the gauge map.
        assert set(snapshot) <= set(SERVING_POOL_GAUGES)

    def test_prefix_hit_tokens_histogram_and_decoded_gauge(self):
        """The multi-turn metrics surface: per-admission hit lengths
        fold into the tpu_serve_prefix_hit_tokens HISTOGRAM (misses at
        0, transcript mounts in the tail, _sum = the old cumulative
        gauge's value), decoded donations ride the
        tpu_serve_decoded_pages_donated_total gauge, and the batch
        drains once like the phase batch."""
        from k8s_gpu_scheduler_tpu.metrics import export_serving_pool
        from k8s_gpu_scheduler_tpu.metrics.exporter import (
            PREFIX_HIT_HISTOGRAM, SERVING_POOL_GAUGES,
        )

        assert "decoded_pages_donated_total" in SERVING_POOL_GAUGES
        reg = Registry()
        export_serving_pool(reg, {
            "decoded_pages_donated_total": 3.0,
            "prefix_hit_token_batch": (0, 8, 512),
        })
        text = reg.expose()
        assert "tpu_serve_decoded_pages_donated_total 3.0" in text
        assert f'{PREFIX_HIT_HISTOGRAM}_bucket{{le="8.0"}} 2' in text
        assert f"{PREFIX_HIT_HISTOGRAM}_count 3" in text
        assert f"{PREFIX_HIT_HISTOGRAM}_sum 520.0" in text
        # Labeled (fleet) series ride the same histogram machinery.
        reg2 = Registry()
        export_serving_pool(reg2, {"prefix_hit_token_batch": (64,)},
                            labels={"replica": "r0"})
        assert (f'{PREFIX_HIT_HISTOGRAM}_count{{replica="r0"}} 1'
                in reg2.expose())

    def test_tier_gauges_and_promoted_histogram(self):
        """The KV-tiering metrics surface: tier occupancy/churn gauges
        ride tpu_serve_* like every pool key, promoted-hit tokens fold
        into the tpu_serve_promoted_hit_tokens HISTOGRAM (drained-once
        batch like the phase batch), and an untiered snapshot's
        exposition stays byte-identical — the tier keys exist only on
        tiered engines, so absence is structural, not filtered."""
        from k8s_gpu_scheduler_tpu.metrics import export_serving_pool
        from k8s_gpu_scheduler_tpu.metrics.exporter import (
            PROMOTED_HIT_HISTOGRAM, SERVING_POOL_GAUGES,
        )

        for key in ("tier_dram_pages", "tier_dram_capacity",
                    "tier_disk_pages", "tier_pending_demotions",
                    "page_demotions_total", "page_promotions_total",
                    "prefix_demoted_pages", "tier_spills_total",
                    "tier_forgotten_total", "tier_cancelled_demotions"):
            assert key in SERVING_POOL_GAUGES, key
        reg = Registry()
        export_serving_pool(reg, {
            "tier_dram_pages": 52.0, "tier_dram_capacity": 64.0,
            "page_demotions_total": 100.0,
            "page_promotions_total": 48.0,
            "prefix_demoted_pages": 52.0,
            "promoted_hit_token_batch": (8, 32, 384),
        })
        text = reg.expose()
        assert "tpu_serve_tier_dram_pages 52.0" in text
        assert "tpu_serve_tier_dram_capacity 64.0" in text
        assert "tpu_serve_page_demotions_total 100.0" in text
        assert "tpu_serve_page_promotions_total 48.0" in text
        assert "tpu_serve_prefix_demoted_pages 52.0" in text
        assert f"{PROMOTED_HIT_HISTOGRAM}_count 3" in text
        assert f"{PROMOTED_HIT_HISTOGRAM}_sum 424.0" in text
        # Labeled (fleet) edition rides the same machinery.
        reg2 = Registry()
        export_serving_pool(reg2, {"promoted_hit_token_batch": (64,)},
                            labels={"replica": "r0"})
        assert (f'{PROMOTED_HIT_HISTOGRAM}_count{{replica="r0"}} 1'
                in reg2.expose())
        # Untiered snapshot: no tier/promoted series at all.
        reg3 = Registry()
        export_serving_pool(reg3, {"pages_free": 20.0,
                                   "prefix_hit_rate": 0.8})
        text3 = reg3.expose()
        assert "tier" not in text3 and "promot" not in text3
        assert "demot" not in text3

    def test_weight_gauges_and_tp_combine_info(self):
        """Megatron-sliced weights' metrics surface: per-chip weight
        residency gauges (build-time constants, the kv_pool contract)
        and the tpu_serve_tp_combine{kind=} info metric — 1 under the
        engine's combine label, never a raw string into a gauge; the
        unlabeled exposition stays byte-identical for callers that
        publish no combine key."""
        from k8s_gpu_scheduler_tpu.metrics import export_serving_pool
        from k8s_gpu_scheduler_tpu.metrics.exporter import (
            SERVING_POOL_GAUGES, TP_COMBINE_INFO,
        )

        assert "weight_device_bytes" in SERVING_POOL_GAUGES
        assert "weight_sliced_device_bytes" in SERVING_POOL_GAUGES
        reg = Registry()
        export_serving_pool(reg, {
            "weight_device_bytes": 148096.0,
            "weight_sliced_device_bytes": 81920.0,
            "tp_combine": "all_gather",
        })
        text = reg.expose()
        assert "tpu_serve_weight_device_bytes 148096.0" in text
        assert "tpu_serve_weight_sliced_device_bytes 81920.0" in text
        assert f'{TP_COMBINE_INFO}{{kind="all_gather"}} 1.0' in text
        # Labeled (fleet) edition rides the same machinery.
        reg2 = Registry()
        export_serving_pool(reg2, {"tp_combine": "psum"},
                            labels={"replica": "r0"})
        assert (f'{TP_COMBINE_INFO}{{kind="psum",replica="r0"}} 1.0'
                in reg2.expose())
        # No combine key (contiguous engines / old callers): no
        # tp_combine series at all — exposition unchanged.
        reg3 = Registry()
        export_serving_pool(reg3, {"pages_free": 1.0})
        assert TP_COMBINE_INFO not in reg3.expose()

    def test_replica_labeled_export_and_unlabeled_byte_identity(self):
        """The fleet tier publishes each replica under {replica=}: the
        labeled series ride the SAME gauges/histogram, and a caller
        that passes no labels gets a text exposition byte-identical to
        the pre-label format."""
        from k8s_gpu_scheduler_tpu.metrics import export_serving_pool

        snapshot = {"pages_free": 20.0, "page_utilization": 0.375,
                    "phase_durations": (("decode_chunk", 0.004),)}
        reg_plain = Registry()
        export_serving_pool(reg_plain, dict(snapshot))
        reg_plain2 = Registry()
        export_serving_pool(reg_plain2, dict(snapshot), labels=None)
        assert reg_plain.expose() == reg_plain2.expose()

        reg = Registry()
        export_serving_pool(reg, dict(snapshot),
                            labels={"replica": "r0"})
        export_serving_pool(reg, {"pages_free": 5.0},
                            labels={"replica": "r1"})
        text = reg.expose()
        assert 'tpu_serve_pages_free{replica="r0"} 20.0' in text
        assert 'tpu_serve_pages_free{replica="r1"} 5.0' in text
        assert ('tpu_serve_phase_duration_seconds_count'
                '{phase="decode_chunk",replica="r0"} 1') in text

    def test_fleet_counters_catalogued_and_labeled(self):
        """The router's tpu_fleet_* counters: every name in the catalog
        carries help text, and the routed counter splits by
        replica/policy."""
        from k8s_gpu_scheduler_tpu.metrics.exporter import (
            FLEET_COUNTERS, FLEET_ROUTED_TOTAL,
        )

        reg = Registry()
        for name, help_ in FLEET_COUNTERS.items():
            reg.counter(name, help_)
        c = reg.counter(FLEET_ROUTED_TOTAL)
        c.inc(replica="r0", policy="affinity")
        c.inc(2, replica="r1", policy="degraded")
        text = reg.expose()
        for name in FLEET_COUNTERS:
            assert f"# HELP {name}" in text
        assert ('tpu_fleet_routed_requests_total'
                '{policy="affinity",replica="r0"} 1.0') in text
        assert ('tpu_fleet_routed_requests_total'
                '{policy="degraded",replica="r1"} 2.0') in text

    def test_fleet_gauges_catalogued_one_hot_state(self):
        """The crash-tolerance + disagg gauges: replica_state and
        replica_role are one-hot {replica=,...} families, the journal
        gauge a plain level."""
        from k8s_gpu_scheduler_tpu.metrics.exporter import (
            FLEET_GAUGES, FLEET_JOURNAL_SIZE, FLEET_REPLICA_ROLE,
            FLEET_REPLICA_STATE,
        )

        reg = Registry()
        g = reg.gauge(FLEET_REPLICA_STATE,
                      FLEET_GAUGES[FLEET_REPLICA_STATE])
        for state, v in (("live", 0.0), ("quarantined", 1.0)):
            g.set(v, replica="r0", state=state)
        role = reg.gauge(FLEET_REPLICA_ROLE,
                         FLEET_GAUGES[FLEET_REPLICA_ROLE])
        for r, v in (("prefill", 1.0), ("mixed", 0.0)):
            role.set(v, replica="r0", role=r)
        reg.gauge(FLEET_JOURNAL_SIZE,
                  FLEET_GAUGES[FLEET_JOURNAL_SIZE]).set(3)
        text = reg.expose()
        for name in FLEET_GAUGES:
            assert f"# HELP {name}" in text
        assert ('tpu_fleet_replica_state'
                '{replica="r0",state="quarantined"} 1.0') in text
        assert ('tpu_fleet_replica_state'
                '{replica="r0",state="live"} 0.0') in text
        assert ('tpu_fleet_replica_role'
                '{replica="r0",role="prefill"} 1.0') in text
        assert ('tpu_fleet_replica_role'
                '{replica="r0",role="mixed"} 0.0') in text
        assert "tpu_fleet_journal_inflight_requests 3.0" in text

    def test_absent_keys_are_skipped(self):
        """Contiguous layout ({}) and prefix-cache-off snapshots publish
        what they have — unconditional per-step publishing is safe."""
        from k8s_gpu_scheduler_tpu.metrics import export_serving_pool

        reg = Registry()
        export_serving_pool(reg, {})
        assert "tpu_serve" not in reg.expose()
        export_serving_pool(reg, {"pages_free": 3.0})
        assert "tpu_serve_pages_free 3.0" in reg.expose()

    def test_spec_gauges_exported(self):
        """The speculation gauges ride the same map: a snapshot with the
        spec_* keys (paged engine, speculative=True) round-trips through
        /metrics with help text."""
        from k8s_gpu_scheduler_tpu.metrics import (
            SERVING_POOL_GAUGES, export_serving_pool,
        )

        reg = Registry()
        snapshot = {
            "spec_accept_rate": 0.42,
            "spec_tokens_per_dispatch": 2.25,
            "spec_rewound_tokens_total": 96.0,
        }
        export_serving_pool(reg, snapshot)
        text = reg.expose()
        assert "tpu_serve_spec_accept_rate 0.42" in text
        assert "tpu_serve_spec_tokens_per_dispatch 2.25" in text
        assert "tpu_serve_spec_rewound_tokens_total 96.0" in text
        assert "# HELP tpu_serve_spec_accept_rate" in text
        assert set(snapshot) <= set(SERVING_POOL_GAUGES)

    def test_spec_gamma_agg_and_accept_histogram(self):
        """The adaptive-gamma spread rides one gauge under {slot_agg=},
        the per-dispatch accept batch a proposer-labeled histogram —
        both registered lazily, so a snapshot without the keys leaves
        the exposition byte-identical to before."""
        from k8s_gpu_scheduler_tpu.metrics import export_serving_pool
        from k8s_gpu_scheduler_tpu.metrics.exporter import (
            SPEC_ACCEPT_HISTOGRAM, SPEC_GAMMA_GAUGE,
        )

        reg = Registry()
        export_serving_pool(reg, {
            "spec_accept_rate": 0.5,
            "spec_proposer": "bigram",
            "spec_gamma_agg": {"min": 1.0, "mean": 2.5, "max": 4.0},
            "spec_accept_batch": (0.0, 0.5, 1.0),
        })
        text = reg.expose()
        assert f'{SPEC_GAMMA_GAUGE}{{slot_agg="min"}} 1.0' in text
        assert f'{SPEC_GAMMA_GAUGE}{{slot_agg="mean"}} 2.5' in text
        assert f'{SPEC_GAMMA_GAUGE}{{slot_agg="max"}} 4.0' in text
        assert (f'{SPEC_ACCEPT_HISTOGRAM}_bucket'
                f'{{le="0.5",proposer="bigram"}} 2') in text
        assert (f'{SPEC_ACCEPT_HISTOGRAM}_count'
                f'{{proposer="bigram"}} 3') in text
        # The special keys never leak as plain gauges...
        assert "tpu_serve_spec_gamma_agg" not in text
        assert "tpu_serve_spec_accept_batch" not in text
        assert "tpu_serve_spec_proposer" not in text
        # ...and without them the exposition is byte-identical to the
        # pre-speculation-sampling format (lazy registration).
        reg_old = Registry()
        export_serving_pool(reg_old, {"spec_accept_rate": 0.5})
        reg_new = Registry()
        export_serving_pool(reg_new, {"spec_accept_rate": 0.5,
                                      "spec_proposer": "bigram",
                                      "spec_accept_batch": ()})
        assert reg_old.expose() == reg_new.expose()
        assert f"{SPEC_ACCEPT_HISTOGRAM}_bucket" not in reg_old.expose()

    def test_lifecycle_gauges_exported(self):
        """The robustness gauges (drain/restore/resume/watchdog/error
        isolation) ride the same map: names match the PR contract
        (tpu_serve_drain_duration_seconds, ...)."""
        from k8s_gpu_scheduler_tpu.metrics import (
            SERVING_POOL_GAUGES, export_serving_pool,
        )

        reg = Registry()
        snapshot = {
            "drain_duration_seconds": 0.012,
            "restore_duration_seconds": 0.034,
            "requests_resumed_total": 5.0,
            "request_errors_total": 1.0,
            "last_step_age_seconds": 0.25,
        }
        export_serving_pool(reg, snapshot)
        text = reg.expose()
        assert "tpu_serve_drain_duration_seconds 0.012" in text
        assert "tpu_serve_restore_duration_seconds 0.034" in text
        assert "tpu_serve_requests_resumed_total 5.0" in text
        assert "tpu_serve_request_errors_total 1.0" in text
        assert "tpu_serve_last_step_age_seconds 0.25" in text
        assert "# HELP tpu_serve_last_step_age_seconds" in text
        assert set(snapshot) <= set(SERVING_POOL_GAUGES)

    def test_chunked_prefill_gauges_exported(self):
        """The chunked-prefill gauges ride the same map — the names are
        the PR contract (tpu_serve_prefill_backlog_tokens /
        tpu_serve_prefill_chunks_total) — and the prefill_chunk phase
        folds into the phase histogram next to the pre-existing phases
        without disturbing the unlabeled exposition."""
        from k8s_gpu_scheduler_tpu.metrics import (
            SERVING_POOL_GAUGES, export_serving_pool,
        )
        from k8s_gpu_scheduler_tpu.metrics.exporter import PHASE_HISTOGRAM

        reg = Registry()
        snapshot = {
            "prefill_backlog_tokens": 384.0,
            "prefill_chunks_total": 7.0,
            "phase_durations": (("prefill_chunk", 0.004),
                                ("decode_chunk", 0.002)),
        }
        export_serving_pool(reg, snapshot)
        text = reg.expose()
        assert "tpu_serve_prefill_backlog_tokens 384.0" in text
        assert "tpu_serve_prefill_chunks_total 7.0" in text
        assert "# HELP tpu_serve_prefill_backlog_tokens" in text
        assert (PHASE_HISTOGRAM + '_count{phase="prefill_chunk"} 1') \
            in text
        assert (PHASE_HISTOGRAM + '_count{phase="decode_chunk"} 1') \
            in text
        assert {"prefill_backlog_tokens",
                "prefill_chunks_total"} <= set(SERVING_POOL_GAUGES)

    def test_rpc_retry_counter_labels(self):
        """tpu_sched_rpc_retries_total{client=...}: the per-client retry
        counter the scheduler entrypoint wires into both control-plane
        clients' on_retry hooks (cmd/scheduler.py)."""
        reg = Registry()
        c = reg.counter("tpu_sched_rpc_retries_total",
                        "Bounded control-plane RPC retries, by client")
        c.inc(client="registry")
        c.inc(client="registry")
        c.inc(client="recommender")
        assert c.value(client="registry") == 2
        assert c.value(client="recommender") == 1
        text = reg.expose()
        assert 'tpu_sched_rpc_retries_total{client="registry"} 2' in text
        assert 'tpu_sched_rpc_retries_total{client="recommender"} 1' \
            in text

    def test_live_drained_engine_exports_lifecycle_gauges(self):
        """End to end: a drained+restored paged engine's pool_metrics()
        carries the lifecycle gauges and the exporter publishes them."""
        import dataclasses

        import jax
        import jax.numpy as jnp

        from k8s_gpu_scheduler_tpu.metrics import export_serving_pool
        from k8s_gpu_scheduler_tpu.models import LlamaConfig, init_params
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        cfg = dataclasses.replace(LlamaConfig.tiny(), dtype=jnp.float32)
        params = init_params(cfg, jax.random.PRNGKey(0))

        def engine():
            return ContinuousBatcher(
                params, cfg, n_slots=2, max_len=64, chunk=4,
                prefill_bucket=8, kv_layout="paged", page_size=8)

        eng = engine()
        eng.submit(list(range(1, 12)), max_new=6)
        eng.step()
        snap = eng.drain()
        fresh = engine()
        fresh.restore(snap)
        m = fresh.pool_metrics()
        assert m["requests_resumed_total"] == 1.0
        assert m["restore_duration_seconds"] > 0
        assert m["last_step_age_seconds"] >= 0
        reg = Registry()
        export_serving_pool(reg, m)
        text = reg.expose()
        assert "tpu_serve_requests_resumed_total 1.0" in text
        assert "tpu_serve_restore_duration_seconds" in text

    def test_live_spec_engine_snapshot_exports(self):
        """End to end against a real speculative paged engine: after a
        drained wave, pool_metrics() carries the spec gauges and the
        exporter publishes them."""

        import jax
        import numpy as np

        from k8s_gpu_scheduler_tpu.metrics import export_serving_pool
        from k8s_gpu_scheduler_tpu.models import LlamaConfig, init_params
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        cfg = LlamaConfig.tiny()
        params = init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        eng = ContinuousBatcher(params, cfg, n_slots=2, max_len=32,
                                chunk=2, prefill_bucket=8,
                                kv_layout="paged", page_size=8,
                                speculative=True, gamma=2)
        eng.submit(list(rng.integers(0, cfg.vocab, 5)), max_new=4)
        eng.run()
        reg = Registry()
        export_serving_pool(reg, eng.pool_metrics())
        text = reg.expose()
        assert "tpu_serve_spec_accept_rate" in text
        assert "tpu_serve_spec_tokens_per_dispatch" in text
        # 3 verify steps after the prefill token, gamma=2 each: the
        # rewound total is (gamma - accepted) summed — present and
        # consistent with the accept counters either way.
        assert "tpu_serve_spec_rewound_tokens_total" in text
        # A non-adaptive engine publishes the flat configured gamma on
        # all three slot_agg series, and the drained per-dispatch accept
        # batch lands in the proposer-labeled histogram.
        assert 'tpu_serve_spec_gamma{slot_agg="mean"} 2.0' in text
        assert ('tpu_serve_spec_accept_count'
                '{proposer="bigram"}') in text

    def test_live_engine_snapshot_exports(self):
        """End to end against a real paged engine with the prefix cache:
        pool_metrics() -> gauges, including the reuse counters."""

        import jax
        import numpy as np

        from k8s_gpu_scheduler_tpu.metrics import export_serving_pool
        from k8s_gpu_scheduler_tpu.models import LlamaConfig, init_params
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

        cfg = LlamaConfig.tiny()
        params = init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        eng = ContinuousBatcher(params, cfg, n_slots=2, max_len=32,
                                chunk=2, prefill_bucket=8,
                                kv_layout="paged", page_size=8,
                                prefix_cache=True)
        sysp = list(rng.integers(0, cfg.vocab, 8))
        for _ in range(2):
            eng.submit(sysp + list(rng.integers(0, cfg.vocab, 3)),
                       max_new=2)
            eng.run()
        reg = Registry()
        export_serving_pool(reg, eng.pool_metrics())
        text = reg.expose()
        assert "tpu_serve_prefill_tokens_skipped 8.0" in text
        assert "tpu_serve_prefix_cached_pages 1.0" in text
        assert "tpu_serve_pages_total 8.0" in text


class TestPhaseHistograms:
    def test_labeled_histogram_exposition(self):
        """Histogram label support (phase=...): per-label-set buckets,
        sums and counts expose side by side; the unlabeled API and text
        format are byte-identical to before."""
        reg = Registry()
        h = reg.histogram("tpu_serve_phase_duration_seconds", "phases",
                          buckets=(0.01, 0.1))
        h.observe(0.005, phase="queue")
        h.observe(0.05, phase="queue")
        h.observe(0.005, phase="reap")
        text = reg.expose()
        assert ('tpu_serve_phase_duration_seconds_bucket'
                '{le="0.01",phase="queue"} 1') in text
        assert ('tpu_serve_phase_duration_seconds_bucket'
                '{le="+Inf",phase="queue"} 2') in text
        assert ('tpu_serve_phase_duration_seconds_count'
                '{phase="reap"} 1') in text
        assert h.count == 3
        assert h.count_for(phase="queue") == 2
        assert h.quantile(0.5, phase="queue") == pytest.approx(0.05)

    def test_export_folds_phase_durations(self):
        """pool_metrics()'s drained phase batch becomes the
        tpu_serve_phase_duration_seconds{phase=} histogram; plain gauge
        keys are untouched by the special key."""
        from k8s_gpu_scheduler_tpu.metrics import export_serving_pool

        reg = Registry()
        export_serving_pool(reg, {
            "pages_free": 3.0,
            "phase_durations": (("queue", 0.001), ("decode_chunk", 0.02),
                                ("decode_chunk", 0.03)),
        })
        text = reg.expose()
        assert "tpu_serve_pages_free 3.0" in text
        assert ('tpu_serve_phase_duration_seconds_count'
                '{phase="decode_chunk"} 2') in text
        assert ('tpu_serve_phase_duration_seconds_count'
                '{phase="queue"} 1') in text
        # And the special key never leaks as a gauge.
        assert "tpu_serve_phase_durations" not in text

    @pytest.mark.slow  # double-covered (PR 15 budget): graftcheck pass
    # 10's torn-snapshot rule guards this class STATICALLY in tier-1
    # (make lint + test_graftcheck_clean); the concurrent hammer rides
    # the unfiltered CI run.
    def test_pool_metrics_atomic_snapshot_regression(self):
        """The torn-read bugfix: tpu_serve_last_step_age_seconds, the
        spec gauges and the phase batch all come from ONE lock snapshot
        in pool_metrics(), and the phase batch drains exactly-once —
        hammered by concurrent scrapers against a stepping engine, no
        observation is lost or double-counted and ages stay finite."""
        import dataclasses

        import jax
        import jax.numpy as jnp

        from k8s_gpu_scheduler_tpu.models import LlamaConfig, init_params
        from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher
        from k8s_gpu_scheduler_tpu.obs import Tracer

        cfg = dataclasses.replace(LlamaConfig.tiny(), dtype=jnp.float32)
        params = init_params(cfg, jax.random.PRNGKey(0))
        eng = ContinuousBatcher(params, cfg, n_slots=2, max_len=64,
                                chunk=4, prefill_bucket=8,
                                kv_layout="paged", page_size=8,
                                tracer=Tracer(capacity=1 << 16))
        drained = []
        stop = threading.Event()

        def scraper():
            while not stop.is_set():
                m = eng.pool_metrics()
                assert m["last_step_age_seconds"] >= 0.0
                drained.append(m.get("phase_durations", ()))

        threads = [threading.Thread(target=scraper) for _ in range(4)]
        for t in threads:
            t.start()
        try:
            for i in range(6):
                eng.submit(list(range(1, 8)), max_new=6)
                eng.run()
        finally:
            stop.set()
            for t in threads:
                t.join()
        drained.append(eng.pool_metrics().get("phase_durations", ()))
        total = sum(len(batch) for batch in drained)
        # Exactly-once drain: every recorded span appears in exactly one
        # scrape's batch. The engine recorded (queue + admit + prefill +
        # per-dispatch decode_chunk + reap) per request; reconstruct the
        # ground truth from the tracer's engine-lane spans.
        tracer_folds = [s for s in eng._tracer.spans()
                        if s.lane == "engine"
                        and s.name != "page_shortage"]
        assert total == len(tracer_folds), (total, len(tracer_folds))


class TestSchedulerMetrics:
    def test_scheduler_records_latency_and_attempts(self):
        from k8s_gpu_scheduler_tpu.cluster import APIServer, Descriptor
        from k8s_gpu_scheduler_tpu.config import SchedulerConfig
        from tests.test_sched import FitFilter, make_scheduler, mk_node, mk_pod, wait_until

        server = APIServer()
        d = Descriptor(server)
        server.create(mk_node("n1", chips=8))
        sched = make_scheduler(server)
        sched.start()
        try:
            d.create_pod(mk_pod("p", chips=2))
            d.create_pod(mk_pod("huge", chips=64))
            assert wait_until(lambda: d.get_pod("p").spec.node_name == "n1")
            assert wait_until(
                lambda: sched.metrics.counter("tpu_sched_attempts_total").value(result="scheduled") == 1
            )
            # The huge pod's cycle runs independently of p's bind — wait,
            # don't assert instantly (its first cycle may still be queued).
            assert wait_until(
                lambda: sched.metrics.counter("tpu_sched_attempts_total").value(result="unschedulable") >= 1
            )
            e2e = sched.metrics.histogram("tpu_sched_e2e_duration_seconds")
            assert e2e.count == 1 and e2e.quantile(0.5) < 1.0
            assert sched.metrics.histogram("tpu_sched_scheduling_cycle_seconds").count >= 2
        finally:
            sched.stop()
