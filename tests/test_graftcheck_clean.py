"""Tier-1 gate: the tree must stay graftcheck-clean.

Runs the FAST passes (AST lint incl. retry/trace/suppression lints, the
lock-order & donated-buffer audit, the determinism lint over the
replay/placement planes [unseeded-rng / builtin-hash /
unordered-iteration / wall-clock-decision], VMEM budgeter — no tracing,
~4 s) over the package exactly as ``make lint`` does, and fails with
the rendered ``file:line: [rule] message`` list if anything regressed.
The traced passes (jaxpr audit, recompile guard, alias, gspmd, symbolic
traffic) and the wire-format schema audit have their own tests in
tests/test_analysis.py + tests/test_wire_compat.py; the full
twelve-pass run is ``python -m k8s_gpu_scheduler_tpu.analysis``.

Suppression policy: ``# graftcheck: ignore[rule]`` with a rationale in
the surrounding comment (see README "graftcheck").
"""
import os

import k8s_gpu_scheduler_tpu
from k8s_gpu_scheduler_tpu.analysis import run_fast_passes

PKG = os.path.dirname(os.path.abspath(k8s_gpu_scheduler_tpu.__file__))


def test_tree_is_graftcheck_clean():
    report = run_fast_passes([PKG])
    assert not report.findings, "\n" + report.render(
        header="graftcheck regressions (fix them or suppress WITH a "
               "rationale — see README):")
