"""Multi-process rendezvous smoke — the consuming half of gang PostBind.

A 2-process CPU ``jax.distributed`` cluster bootstraps purely from the env
the scheduler injects (TPU_WORKER_HOSTNAMES / TPU_WORKER_ID /
TPU_WORKER_COUNT → parallel/distributed.py). This is the end-to-end proof
VERDICT.md r3 #1 asked for: a gang whose injected addresses resolve can
actually run jax.distributed.initialize; with the old node-name injection
this smoke hangs at connect.

Kept deliberately tiny (2 procs, loopback, one psum) so it stays hermetic
and fast; the scheduler-side address derivation is covered in
tests/test_plugins.py::TestGang.
"""
import os
import socket
import subprocess
import sys

import pytest

WORKER = r"""
import os, sys
import jax

# The axon TPU plugin registers even with JAX_PLATFORMS=cpu in the env;
# the config flag wins (same workaround as tests/conftest.py).
jax.config.update("jax_platforms", "cpu")
from k8s_gpu_scheduler_tpu.parallel import distributed_init_from_env

port = int(sys.argv[1])
assert distributed_init_from_env(coordinator_port=port)
import jax.numpy as jnp

assert jax.process_count() == 2, jax.process_count()
# One collective across both processes proves the rendezvous is real —
# where the backend can run one. CPU jaxlib accepts the rendezvous (the
# coordinator handshake above is real: process_count() saw both workers)
# but refuses cross-process computations; the handshake is still the
# contract the scheduler's env injection is on the hook for.
from jax.experimental import multihost_utils

try:
    total = multihost_utils.process_allgather(jnp.ones(())).sum()
    assert int(total) == 2, total
except Exception as e:
    if "Multiprocess computations aren't implemented" not in str(e):
        raise
    print("ALLGATHER_UNSUPPORTED_ON_BACKEND")
print("RENDEZVOUS_OK", jax.process_index())
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_cpu_rendezvous_from_injected_env(tmp_path):
    port = _free_port()
    # Exactly what gang PostBind writes into the members' ConfigMaps,
    # with loopback standing in for the two pods' DNS names.
    hostnames = "127.0.0.1,127.0.0.1"
    procs = []
    for wid in range(2):
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            "TPU_WORKER_HOSTNAMES": hostnames,
            "TPU_WORKER_ID": str(wid),
            "TPU_WORKER_COUNT": "2",
        })
        procs.append(subprocess.Popen(
            [sys.executable, "-c", WORKER, str(port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        ))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=110)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for wid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {wid} failed:\n{out}"
        assert "RENDEZVOUS_OK" in out


def test_single_worker_env_stays_local():
    """Un-injected pods (no gang) must not attempt a rendezvous."""
    from k8s_gpu_scheduler_tpu.parallel import distributed_init_from_env

    assert not distributed_init_from_env(env={})
    assert not distributed_init_from_env(
        env={"TPU_WORKER_HOSTNAMES": "only-me.svc"})


class TestSelfWorkerId:
    """Shared-ConfigMap gangs: every member reads the same last-written
    TPU_WORKER_ID scalar, so the id must be self-derived from $HOSTNAME vs
    the (identical-across-members) address list."""

    ADDRS = [
        "llama-0.llama.default.svc",
        "llama-1.llama.default.svc",
        "llama-2.llama.default.svc",
    ]

    def test_each_member_derives_its_own_index(self):
        from k8s_gpu_scheduler_tpu.parallel.distributed import self_worker_id

        for i in range(3):
            assert self_worker_id(self.ADDRS, {"HOSTNAME": f"llama-{i}"}) == i

    def test_shared_configmap_scalar_is_overridden(self):
        """All members see the loser-written TPU_WORKER_ID=2; hostname
        matching must win so ids still come out distinct."""
        from k8s_gpu_scheduler_tpu.parallel.distributed import (
            self_worker_id,
            worker_addresses,
        )

        ids = set()
        for i in range(3):
            env = {
                "TPU_WORKER_HOSTNAMES": ",".join(self.ADDRS),
                "TPU_WORKER_ID": "2",  # last writer's id, seen by everyone
                "HOSTNAME": f"llama-{i}",
            }
            addrs = worker_addresses(env)
            wid = self_worker_id(addrs, env)
            assert wid is not None
            ids.add(wid)
        assert ids == {0, 1, 2}

    def test_no_match_falls_back_to_injected_scalar(self):
        """Node-address gangs (hostNetwork) can't hostname-match — the
        per-pod injected scalar still applies."""
        from k8s_gpu_scheduler_tpu.parallel.distributed import self_worker_id

        assert self_worker_id(["10.0.0.1", "10.0.0.2"],
                              {"HOSTNAME": "llama-1"}) is None
        assert self_worker_id(self.ADDRS, {}) is None


class TestMultisliceMesh:
    """parallel/mesh.py multislice_mesh: slice-major device order, outer dp
    axis = slice index (the DCN-spanning gang layout gang.py injects
    TPU_SLICE_* env for)."""

    def test_shape_and_slice_major_order(self):
        import jax

        from k8s_gpu_scheduler_tpu.parallel import multislice_mesh

        mesh = multislice_mesh(2, fsdp=2, tp=2)
        assert dict(mesh.shape) == {"dp": 2, "fsdp": 2, "sp": 1, "ep": 1,
                                    "tp": 2}
        devs = jax.devices()
        # dp index 0 holds the FIRST per-slice block of devices, dp index 1
        # the second — the slice boundary, not an interleave.
        first_slice = mesh.devices[0].flatten().tolist()
        second_slice = mesh.devices[1].flatten().tolist()
        assert first_slice == devs[:4]
        assert second_slice == devs[4:8]

    def test_too_few_devices_rejected(self):
        import pytest

        from k8s_gpu_scheduler_tpu.parallel import multislice_mesh

        with pytest.raises(ValueError, match="needs 16"):
            multislice_mesh(4, fsdp=2, tp=2)

    def test_train_step_runs_on_multislice_mesh(self):
        """One full train step with dp spanning the slice boundary — the
        gradient all-reduce is the only cross-slice collective (the
        multislice contract)."""
        import jax
        import jax.numpy as jnp
        import optax

        from k8s_gpu_scheduler_tpu.models import (
            LlamaConfig, init_params, make_train_step,
        )
        from k8s_gpu_scheduler_tpu.parallel import multislice_mesh

        mesh = multislice_mesh(2, tp=2)        # 2 slices x 2 chips
        cfg = LlamaConfig.tiny()
        params = init_params(cfg, jax.random.PRNGKey(0))
        opt = optax.adamw(1e-3)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0,
                                    cfg.vocab)
        batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, axis=1)}
        step = make_train_step(cfg, mesh, opt)
        _, _, loss = step(params, opt.init(params), batch)
        assert jnp.isfinite(loss)
