"""Slice-topology math tests (table-driven, mirroring the reference's style —
SURVEY.md §4 'table-driven cases')."""
import pytest

from k8s_gpu_scheduler_tpu.api.topology import (
    SliceTopology,
    TPUGen,
    chip_count,
    config_for_partitions,
    host_coordinates,
    hosts_needed,
    ici_hop_distance,
    parse_topology,
    partitions_for,
    slice_diameter,
)


@pytest.mark.parametrize(
    "s,want",
    [("2x4", (2, 4)), ("2x2x2", (2, 2, 2)), ("16x16", (16, 16)), ("1x1", (1, 1))],
)
def test_parse_topology(s, want):
    assert parse_topology(s) == want
    assert chip_count(want) == int.__mul__(*want[:2]) * (want[2] if len(want) == 3 else 1)


@pytest.mark.parametrize("s", ["", "0x2", "2x-1", "axb"])
def test_parse_topology_rejects(s):
    with pytest.raises(ValueError):
        parse_topology(s)


@pytest.mark.parametrize(
    "topo,gen,hosts",
    [
        ("2x4", TPUGen.V5E, 1),     # one v5e host = 8 chips
        ("4x4", TPUGen.V5E, 4),     # v5e-16
        ("16x16", TPUGen.V5E, 64),  # v5e-256 full pod
        ("2x2x1", TPUGen.V5P, 1),   # one v5p host = 4 chips
        ("2x2x4", TPUGen.V5P, 4),   # v5p-16: the BASELINE config-4 gang
    ],
)
def test_hosts_needed(topo, gen, hosts):
    assert hosts_needed(parse_topology(topo), gen) == hosts


def test_host_coordinates_v5p16():
    # 2x2x4 on v5p (2x2x1 boards) → host grid (1,1,4): 4 hosts along z.
    coords = host_coordinates(parse_topology("2x2x4"), TPUGen.V5P)
    assert coords == [(0, 0, 0), (0, 0, 1), (0, 0, 2), (0, 0, 3)]


@pytest.mark.parametrize(
    "a,b,dims,wrap,want",
    [
        ((0, 0), (1, 3), (2, 4), False, 4),
        ((0, 0), (0, 3), (4, 4), True, 1),   # wraparound shortens the ring
        ((0, 0, 0), (3, 0, 0), (4, 4, 4), True, 1),
        ((0, 0, 0), (1, 1, 1), (2, 2, 2), False, 3),
    ],
)
def test_ici_hop_distance(a, b, dims, wrap, want):
    assert ici_hop_distance(a, b, dims, wrap=wrap) == want


def test_ici_hop_distance_rank_mismatch():
    with pytest.raises(ValueError):
        ici_hop_distance((0, 0), (0, 0, 0), (2, 2, 2))


def test_slice_diameter():
    assert slice_diameter((2, 4), wrap=False) == 4
    assert slice_diameter((4, 4, 4), wrap=True) == 6


def test_slice_topology_v5p16():
    st = SliceTopology.parse("tpu-v5p-slice", "2x2x4")
    assert st.chips == 16
    assert st.hosts == 4
    assert st.is_multi_host


def test_partition_table_parity():
    # Analogue of the reference's partitions=[4,2,1] MIG table
    # (gpu_plugins.go:52-53): every advertised partition count resolves to a
    # concrete sub-slice topology that tiles the host board.
    for gen in TPUGen:
        for parts in partitions_for(gen):
            sub = parse_topology(config_for_partitions(gen, parts))
            assert chip_count(sub) * parts == gen.chips_per_host


def test_config_for_partitions_rejects_unknown():
    with pytest.raises(ValueError):
        config_for_partitions(TPUGen.V5E, 3)


def test_host_grid_rejects_untileable_axes():
    # ADVICE: '1x16' cannot be tiled by v5e 2x2 multi-host boards — reject,
    # don't round up to 8 hosts (32 chips for a 16-chip slice).
    with pytest.raises(ValueError):
        hosts_needed(parse_topology("1x16"), TPUGen.V5E)


@pytest.mark.parametrize(
    "gen,topo,want",
    [
        (TPUGen.V5E, "16x16", True),   # full v5e pod has wrapped rings
        (TPUGen.V5E, "4x4", False),    # partial v5e slice is a mesh
        (TPUGen.V5P, "4x4x4", True),   # cube-aligned v5p sub-slice wraps
        (TPUGen.V5P, "2x2x2", False),
        (TPUGen.V5P, "2x2x4", False),  # not every axis a multiple of 4
    ],
)
def test_has_wraparound(gen, topo, want):
    assert SliceTopology.parse(gen, topo).has_wraparound is want


@pytest.mark.parametrize(
    "gen,topo,hosts",
    [
        (TPUGen.V5P, "1x1x1", 1),  # sub-host partitions (SLICE_CONFIGS)
        (TPUGen.V5P, "2x1x1", 1),
        (TPUGen.V5E, "1x2", 1),
        (TPUGen.V5E, "1x1", 1),
    ],
)
def test_sub_host_partitions_are_single_host(gen, topo, hosts):
    assert SliceTopology.parse(gen, topo).hosts == hosts


def test_oversized_axis_not_single_host():
    # 4x1x1 has a 4-long axis no 2x2x1 board holds, and its 1-axes can't
    # tile whole boards either — not a GKE topology, rejected.
    with pytest.raises(ValueError):
        hosts_needed(parse_topology("4x1x1"), TPUGen.V5P)
    # 1x8 on v5e exceeds the 2x4 board and can't tile 2x2 boards either.
    with pytest.raises(ValueError):
        hosts_needed(parse_topology("1x8"), TPUGen.V5E)
