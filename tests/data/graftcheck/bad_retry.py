"""Seeded-bad fixture: retry-lint true positives.

Reintroducing this file into the scanned tree must fail
``python -m k8s_gpu_scheduler_tpu.analysis`` (and ``--fast``): it
carries one violation per retry-lint rule — the unbounded
``while True: try/except/continue`` reconnect loop that turns a dead
control-plane dependency into a hung scheduler thread, and a backoff
sleep taken while holding the client lock, stalling every other
thread's call for the whole backoff ladder. tests/test_analysis.py
asserts each specific rule fires; the production shape both rules
demand lives in utils/retry.py + registry/client.py.
"""
import socket
import threading
import time


class StubbornClient:
    """Retries forever and naps under its lock — both anti-patterns."""

    def __init__(self, host: str, port: int) -> None:
        self._mu = threading.Lock()
        self._host = host
        self._port = port
        self._sock = None

    def call_forever(self, payload: bytes) -> bytes:
        while True:                       # no attempt bound, no deadline
            try:
                if self._sock is None:
                    self._sock = socket.create_connection(
                        (self._host, self._port))
                self._sock.sendall(payload)
                return self._sock.recv(4096)
            except OSError:               # swallowed: the failure path
                self._sock = None         # never exits this loop
                time.sleep(0.1)

    def call_napping_under_lock(self, payload: bytes) -> bytes:
        with self._mu:
            for _ in range(3):
                try:
                    self._sock.sendall(payload)
                    return self._sock.recv(4096)
                except OSError:
                    time.sleep(0.5)       # backoff with the lock HELD
            raise ConnectionError("gave up")
