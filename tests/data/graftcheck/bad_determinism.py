"""Seeded-bad fixture for the determinism lint (analysis/determinism.py).

Never imported by the package — it exists so tests/CI can prove the
pass would catch each nondeterminism class if it landed on the
replay/placement planes. The file opts into the scope via the
``GRAFTCHECK_DETERMINISM_LINT`` marker (it does not live under fleet/).
Planted true positives:

- ``unseeded-rng`` ×3: a ``random.Random()`` with no seed (OS entropy —
  replay diverges), a module-global ``random.choice`` (one hidden RNG
  shared across callers/threads), and an unseeded
  ``np.random.default_rng()``.
- ``builtin-hash``: routing keyed on ``hash()`` — PYTHONHASHSEED-salted,
  so two replicas disagree about the same request.
- ``unordered-iteration`` ×2: victim selection appending out of a set,
  and first-match selection returning out of set algebra.
- ``wall-clock-decision``: an expiry decision on a raw ``time.time()``
  read instead of the injectable Clock seam.
"""
import random
import time

import numpy as np

GRAFTCHECK_DETERMINISM_LINT = True   # opt into the scoped pass


class BadFailoverPlanner:
    """Every decision below is one a survivor must replay identically."""

    def __init__(self):
        self._replicas = {"r0", "r1", "r2"}
        self._rng = random.Random()                 # unseeded-rng

    def pick_victims(self, n):
        victims = []
        for r in self._replicas:                    # unordered-iteration
            victims.append(r)
            if len(victims) == n:
                break
        return victims

    def first_live(self, dead):
        for r in self._replicas - dead:             # unordered-iteration
            return r
        return None

    def route_key(self, prompt):
        return hash(tuple(prompt)) % 8              # builtin-hash

    def jitter_s(self):
        g = np.random.default_rng()                 # unseeded-rng
        return float(g.uniform(0.0, 0.05))

    def tie_break(self, candidates):
        return random.choice(candidates)            # unseeded-rng (global)

    def expired(self, deadline_wall):
        return time.time() > deadline_wall          # wall-clock-decision
