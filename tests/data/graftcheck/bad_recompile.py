"""Seeded-bad fixture: a steady-state retrace.

The jitted step takes the tick as a STATIC argument, so every dispatch
after warmup is a fresh trace+compile — the classic quiet serving-
throughput killer the recompile guard exists for.
"""
from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnums=(1,))
def _step(x, tick: int):
    return x * tick


def _build():
    x = jnp.ones((8,))

    def warmup():
        _step(x, 0)

    def make(t):
        return lambda: _step(x, t)       # new static arg -> retrace

    return warmup, [make(1), make(2), make(3)], {"step": _step}


GRAFTCHECK_RECOMPILE_AUDIT = [
    ("retracing_step", _build),
]
