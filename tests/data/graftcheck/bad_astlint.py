"""Seeded-bad fixture: AST-lint true positives.

Reintroducing this file into the scanned tree must fail
``python -m k8s_gpu_scheduler_tpu.analysis`` (and ``--fast``): it carries
one violation per AST rule family — an unguarded access of lock-guarded
state, a tracer cast + host time call inside a traced function, and a
bare except. tests/test_analysis.py asserts each specific rule fires.
"""
import threading
import time

import jax


class LeakyCounter:
    """Writes `self._count` under `self._mu` in one method, reads it
    lock-free in another — the lock-guard true positive."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._count = 0

    def bump(self) -> None:
        with self._mu:
            self._count += 1

    def peek(self) -> int:
        return self._count          # unguarded read of guarded state


def hot_step(x):
    def body(carry, _):
        t = time.time()             # host time inside the traced body
        scale = float(carry.sum())  # tracer cast
        return carry * scale + t, None

    out, _ = jax.lax.scan(body, x, None, length=4)
    return out


def swallow_everything(fn):
    try:
        return fn()
    except:                         # noqa: E722 — the bare-except fixture
        return None
