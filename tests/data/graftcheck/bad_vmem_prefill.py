"""Seeded-bad fixture: a prefix-attention prefill footprint over the
VMEM budget.

Same ``GRAFTCHECK_VMEM_AUDIT`` hook protocol as bad_vmem.py /
bad_vmem_paged.py / bad_vmem_verify.py, tail-prefill edition: the page
blocks here are MODEST (64-row int8 pages — nothing the decode budgeter
would flag), but a 1024-token tail bucket over an 8-head GQA group at
hd=256 stacks tb·g = 8192 q rows, so the q block + three partial
outputs + (acc, m, l) scratch alone blow past the 16 MiB core — the
"skip chunked prefill and dispatch the whole long prompt as one rung"
tuning mistake the prefill footprint's q-window multiplier exists to
catch before Mosaic does, in production, at the first long-prompt
admission. (The runtime guard is ops.prefill_plan's PREFILL_MAX_Q_ROWS
cap; this fixture models the cliff an edit raising that cap without
re-running the budgeter would reopen.)
"""
from k8s_gpu_scheduler_tpu.analysis.vmem import (
    paged_prefill_attention_footprint,
)

GRAFTCHECK_VMEM_AUDIT = [
    ("oversized_prefill_window",
     paged_prefill_attention_footprint(page_size=64, g=8, hd=256,
                                       hb=16, tb=1024, batch=8,
                                       quant=True)),
]
