"""Seeded-bad fixture: a tier promotion upload that WRITES a shared
(demoted-then-promoted and mounted) page.

Same ``GRAFTCHECK_ALIAS_AUDIT`` hook protocol as the repo's own alias
scenarios (analysis/alias.py): ``build()`` returns
``(fn, args, pool_argnums, pool_outnums, shared_pages)``. The jitted
"promotion upload" here scatters the DRAM payload at page ids [1, 2]
while page 1 is declared shared — the exact bookkeeping slip the tier
admission path could introduce (handing the upload the RESIDENT half of
a part-demoted match path instead of only the freshly-reserved promo
pages). Every slot mounting page 1 would silently read the re-uploaded
bytes as its prefix — stale-by-one-demotion KV, no crash, corrupted
streams — which is why the audit byte-compares the declared pages
instead of trusting the admission bookkeeping.
"""
import jax
import jax.numpy as jnp


def _build():
    # [L, n_pages, page_size, Hkv, hd] — the serving pool layout.
    pool = jnp.zeros((2, 4, 8, 2, 4), jnp.float32)
    payload = jnp.ones((2, 2, 8, 2, 4), jnp.float32)

    @jax.jit
    def promote_upload(pool, payload):
        # BUG: page 1 is a resident page another slot mounts; only
        # page 2 (and beyond) was freshly reserved for the promotion.
        return (pool.at[:, jnp.asarray([1, 2])].set(payload),)

    return promote_upload, (pool, payload), (0,), (0,), [1]


GRAFTCHECK_ALIAS_AUDIT = [
    ("promote_upload_writes_shared_page", _build),
]
