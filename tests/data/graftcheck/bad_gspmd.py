"""Seeded-bad fixture: GSPMD sharding-annotation true positives.

Two toy entry points, each wrong in exactly the way the gspmd pass
exists to catch — neither produces wrong tokens, both silently cost
memory/ICI at scale, and none of it is visible to the AST pass:

- ``bad_cache_constraint`` annotates a rank-5 KV cache with ``tp`` on
  the SEQUENCE dim instead of the kv-heads dim (``cache-spec-mismatch``
  — XLA will happily reshuffle the cache every step to satisfy it) and
  pins a multi-MiB buffer explicitly replicated
  (``oversized-replicated``);
- ``bad_scan_carry`` loops a cache-sized carry through ``lax.scan``
  with no sharding constraint anywhere in the program
  (``unconstrained-scan-carry`` — GSPMD free-propagates through the
  loop, typically replicating the biggest buffer in the program onto
  every chip);
- ``bad_replicated_weight_island`` registers a weight-sharded island
  (``weight_specs=True``) whose [L, K, N] weight operand rides UNMAPPED
  — the replicated-weight layout Megatron slicing retires
  (``island-weight-spec``: per-chip weight bytes do not scale 1/tp).

The mesh is built at whatever device count the process has (axis sizes
clamp to 1), because the ANNOTATIONS — all this audit reads — are
identical at any size.
"""
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from k8s_gpu_scheduler_tpu.parallel.sharding import shard_map


def _mesh():
    devs = np.array(jax.devices()[:1])
    return Mesh(devs.reshape((1,) * 5), ("dp", "fsdp", "sp", "ep", "tp"))


def _bad_cache_constraint(cache, big):
    mesh = _mesh()
    # tp on the SEQUENCE dim of [L, B, S, Hkv, hd] — not CACHE_SPEC.
    cache = jax.lax.with_sharding_constraint(
        cache, NamedSharding(mesh, P(None, None, "tp", None, None)))
    # A ~4 MiB buffer explicitly annotated fully-replicated.
    big = jax.lax.with_sharding_constraint(
        big, NamedSharding(mesh, P(None, None)))
    return cache.sum() + big.sum()


def _bad_scan_carry(x):
    def body(carry, _):
        return carry * 1.0001, None

    out, _ = jax.lax.scan(body, x, None, length=2)
    return out


def _bad_replicated_weight_island(pool, w):
    # Pool correctly mapped on the kv-heads dim — the island is fine on
    # that axis — but the weight rides replicated (unmapped): every
    # chip holds and multiplies the full matrix.
    fn = shard_map(
        lambda p, w: (p * 2.0, (p.sum(axis=(0, 1, 2, 4)) @ w).sum()),
        mesh=_mesh(),
        in_specs=(P(None, None, None, "tp", None), P()),
        out_specs=(P(None, None, None, "tp", None), P()),
        check_vma=False)
    new_pool, s = fn(pool, w)
    return new_pool.sum() + s


GRAFTCHECK_GSPMD_AUDIT = [
    ("bad_cache_constraint", _bad_cache_constraint,
     (jnp.zeros((2, 2, 32, 8, 8), jnp.bfloat16),
      jnp.zeros((1024, 1024), jnp.float32)),
     {"cache_spec": True}),
    ("bad_scan_carry", _bad_scan_carry,
     (jnp.zeros((2, 64, 1024), jnp.float32),), {}),
    ("bad_replicated_weight_island", _bad_replicated_weight_island,
     (jnp.zeros((2, 4, 8, 8, 8), jnp.bfloat16),
      jnp.zeros((2, 8, 16), jnp.bfloat16)),
     {"pool_spec": True, "weight_specs": True}),
]
