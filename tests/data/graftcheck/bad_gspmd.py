"""Seeded-bad fixture: GSPMD sharding-annotation true positives.

Two toy entry points, each wrong in exactly the way the gspmd pass
exists to catch — neither produces wrong tokens, both silently cost
memory/ICI at scale, and none of it is visible to the AST pass:

- ``bad_cache_constraint`` annotates a rank-5 KV cache with ``tp`` on
  the SEQUENCE dim instead of the kv-heads dim (``cache-spec-mismatch``
  — XLA will happily reshuffle the cache every step to satisfy it) and
  pins a multi-MiB buffer explicitly replicated
  (``oversized-replicated``);
- ``bad_scan_carry`` loops a cache-sized carry through ``lax.scan``
  with no sharding constraint anywhere in the program
  (``unconstrained-scan-carry`` — GSPMD free-propagates through the
  loop, typically replicating the biggest buffer in the program onto
  every chip).

The mesh is built at whatever device count the process has (axis sizes
clamp to 1), because the ANNOTATIONS — all this audit reads — are
identical at any size.
"""
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _mesh():
    devs = np.array(jax.devices()[:1])
    return Mesh(devs.reshape((1,) * 5), ("dp", "fsdp", "sp", "ep", "tp"))


def _bad_cache_constraint(cache, big):
    mesh = _mesh()
    # tp on the SEQUENCE dim of [L, B, S, Hkv, hd] — not CACHE_SPEC.
    cache = jax.lax.with_sharding_constraint(
        cache, NamedSharding(mesh, P(None, None, "tp", None, None)))
    # A ~4 MiB buffer explicitly annotated fully-replicated.
    big = jax.lax.with_sharding_constraint(
        big, NamedSharding(mesh, P(None, None)))
    return cache.sum() + big.sum()


def _bad_scan_carry(x):
    def body(carry, _):
        return carry * 1.0001, None

    out, _ = jax.lax.scan(body, x, None, length=2)
    return out


GRAFTCHECK_GSPMD_AUDIT = [
    ("bad_cache_constraint", _bad_cache_constraint,
     (jnp.zeros((2, 2, 32, 8, 8), jnp.bfloat16),
      jnp.zeros((1024, 1024), jnp.float32)),
     {"cache_spec": True}),
    ("bad_scan_carry", _bad_scan_carry,
     (jnp.zeros((2, 64, 1024), jnp.float32),), {}),
]
