"""Seeded-bad fixture for the wire-format schema audit
(analysis/wirecompat.py).

Never imported by the package — it registers a
``GRAFTCHECK_WIRECOMPAT_AUDIT`` hook (``(name, live_schema,
golden_schema)`` triples; the live entry may be a callable) describing
a toy telemetry record whose live schema drifted from its committed
golden in every way the pass classifies:

- ``wire-break`` ×2: ``gpu_uuid`` was REMOVED from the live format
  (artifacts already on the wire stop loading), and ``util`` changed
  JSON type int → float (old artifacts decode to the wrong type).
- ``wire-no-default``: ``slice_id`` is NEW and its decoder has no
  default — the new decoder rejects every artifact written before it.
- ``wire-golden-stale``: ``hint`` is a benign add-with-default, but the
  golden was not regenerated — the drift itself is a finding until
  ``--update-schemas`` moves the golden in the same change.
"""

_GOLDEN = {
    "artifact": "bad_telemetry_record",
    "schema_version": 1,
    "groups": {
        "json": {
            "node": {"type": "str", "required": True},
            "gpu_uuid": {"type": "str", "required": True},
            "util": {"type": "int", "required": False},
        },
    },
}

_LIVE = {
    "artifact": "bad_telemetry_record",
    "schema_version": 1,
    "groups": {
        "json": {
            "node": {"type": "str", "required": True},
            "util": {"type": "float", "required": False},
            "slice_id": {"type": "str", "required": True},
            "hint": {"type": "str", "required": False},
        },
    },
}

GRAFTCHECK_WIRECOMPAT_AUDIT = [
    ("bad_telemetry_record", _LIVE, _GOLDEN),
]
