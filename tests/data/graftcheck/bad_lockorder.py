"""Seeded-bad fixture: lock-order / donated-buffer concurrency true
positives (analysis/lockorder.py — plain AST, no hook protocol: the
pass lints any scanned source).

- ``BadLockOrder.ab``/``ba`` acquire the same two locks in OPPOSITE
  orders (``lock-cycle`` — two threads entering from different edges
  deadlock), and ``reenter`` re-acquires a non-reentrant Lock it
  already holds (the degenerate self-cycle);
- ``BadLockOrder.scrape`` drains two guarded gauges under two SEPARATE
  acquisitions of the same lock (``torn-snapshot`` — the values come
  from different instants);
- ``BadDonatedScrape.metrics`` reads an attr that aliases a
  per-dispatch-donated device array from outside the step path
  (``use-after-donate`` — the pool_metrics scrape-race class);
- the bare marker below carries no rationale (``bare-suppression``).
"""
import threading

import jax


class BadLockOrder:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._stats = {}
        self._hist = []

    def ab(self):
        with self._a:
            with self._b:
                self._stats["x"] = 1

    def ba(self):
        with self._b:
            with self._a:
                self._stats["y"] = 2

    def _bump(self):
        with self._a:
            self._hist.append(1)

    def reenter(self):
        with self._a:
            self._bump()               # re-acquires self._a: self-deadlock

    def scrape(self):
        out = {}
        with self._a:
            out["stats"] = dict(self._stats)
        # Torn: a writer between the two acquisitions pairs this
        # instant's stats with the next instant's hist.
        with self._a:
            out["hist"] = list(self._hist)
        return out


def _step(pool, x):
    return (pool + x,)


class BadDonatedScrape:
    def __init__(self, pool):
        self._pool = pool
        self._step_fn = jax.jit(_step, donate_argnums=(0,))

    def step(self, x):
        # The step path: dispatch consumes the pool, rebinds the result.
        self._pool, = self._step_fn(self._pool, x)

    def metrics(self):
        # A scrape thread racing step() reads a DELETED buffer and dies;
        # the blank line below keeps the bare marker genuinely bare.

        probe = float(self._pool[0, 0])  # graftcheck: ignore[host-sync]
        return {"probe": probe}
