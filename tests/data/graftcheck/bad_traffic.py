"""Seeded-bad fixture: symbolic traffic-contract true positives.

Three toy entry points for the traffic audit (analysis/traffic.py),
each wrong in exactly the way the pass exists to catch — none of them
produces wrong numbers, all of them silently burn HBM bandwidth or
residency at scale, and none is visible to the AST or recompile passes:

- ``dense_gather`` materializes the slots×prefix-window cross product
  ``[L, M, hb·ps, Hkv, hd]`` out of the page pool — the PR 13 prefill
  gather class (``dense-materialization``) — under a contract that
  declares no ``hit`` scaling (``traffic-contract``);
- ``broken_donation`` reads the OLD pool after the updated pool exists,
  so even with the argument declared donated the old buffer must
  survive the update — a 2× pool high-water (``peak-residency``), the
  silently-broken-donation shape;
- ``no_contract`` registers with ``None`` — a serving-shaped entry
  whose complexity class was never declared (``traffic-contract``);
- ``replicated_weight_island`` declares ``weight_sharded`` but ships
  the FULL [L, d, d] weight into its shard_map island — the
  replicated-weight layout whose per-chip bytes do not scale 1/tp
  (``traffic-contract``, the Megatron-slicing regression seed).

Geometry values are mutually distinct for every scale symbol, per the
registry convention (TRAFFIC_GEOMETRY).
"""
import jax
import jax.numpy as jnp
import numpy as np

from k8s_gpu_scheduler_tpu.parallel.sharding import shard_map

L, N_PAGES, PS, HKV, HD = 2, 11, 4, 3, 7
M, HB = 5, 2
HIT = HB * PS                              # 8
D, DFF = 6, 13                             # full-weight dims (d, d_ff)

GEOMETRY = {"n_pages": N_PAGES, "hit": HIT, "M": M,
            "L": L, "Hkv": HKV, "hd": HD, "ps": PS,
            "d": D, "d_ff": DFF}

_POOL = jnp.zeros((L, N_PAGES, PS, HKV, HD), jnp.float32)
_TBL = np.tile(np.asarray([[1, 2]], np.int32), (M, 1))    # [M, HB]
_ROW = jnp.ones((PS, HKV, HD), jnp.float32)


def _dense_gather(pool, tbl):
    got = pool[:, tbl]                     # [L, M, HB, PS, HKV, HD]
    got = got.reshape(L, M, HIT, HKV, HD)  # the dense per-slot prefix
    return got.sum()


def _broken_donation(pool, row):
    new = pool.at[:, 1].set(row)
    # The old pool is read AFTER its replacement exists: donation cannot
    # reuse the buffer, so both copies are live at once.
    return new, pool.sum()


def _no_contract(pool):
    return pool.sum()


def _replicated_weight_island(pool, w):
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:1]), ("tp",))
    fn = shard_map(
        lambda p, w: (p * 2.0, (w * 1.0).sum()),
        mesh=mesh,
        in_specs=(P(None, None, None, "tp", None), P()),
        out_specs=(P(None, None, None, "tp", None), P()),
        check_vma=False)
    new_pool, s = fn(pool, w)
    return new_pool.sum() + s


GRAFTCHECK_TRAFFIC_AUDIT = [
    ("bad_dense_gather", _dense_gather, (_POOL, _TBL), GEOMETRY,
     {"kv_scale": {"tb": 1}, "donated": (0,)}),
    ("bad_broken_donation", _broken_donation, (_POOL, _ROW), GEOMETRY,
     {"kv_scale": {}, "donated": (0,)}),
    ("bad_no_contract", _no_contract, (_POOL,), GEOMETRY, None),
    ("bad_replicated_weight_island", _replicated_weight_island,
     (_POOL, jnp.zeros((L, D, D), jnp.float32)), GEOMETRY,
     {"kv_scale": {}, "weight_sharded": True}),
]
