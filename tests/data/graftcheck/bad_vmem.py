"""Seeded-bad fixture: a declared kernel footprint over the VMEM budget.

The ``GRAFTCHECK_VMEM_AUDIT`` hook is how out-of-tree kernels opt into
the budgeter; this one declares the flash-decode working set for a
block_k that streams 16k int8 rows of hd=512 per block with a GQA group
of 32 — ~35 MiB of double-buffered blocks against the 16 MiB core.
"""
from k8s_gpu_scheduler_tpu.analysis.vmem import decode_attention_footprint

GRAFTCHECK_VMEM_AUDIT = [
    ("oversized_flash_decode",
     decode_attention_footprint(s=32768, g=32, hd=512, block_k=16384,
                                quant=True, bitmap=True)),
]
