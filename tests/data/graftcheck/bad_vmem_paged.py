"""Seeded-bad fixture: a paged decode footprint over the VMEM budget.

Same ``GRAFTCHECK_VMEM_AUDIT`` hook protocol as bad_vmem.py, paged
edition: a page size of 8192 rows of hd=512 int8 K/V (double-buffered,
plus f32 scale planes and a 64-wide block table for a batch of 32) is
~18 MiB of page blocks against the 16 MiB core — the kind of "just make
the pages bigger" tuning mistake the budgeter exists to catch before
Mosaic does, in production, at the first long-context config.
"""
from k8s_gpu_scheduler_tpu.analysis.vmem import (
    paged_decode_attention_footprint,
)

GRAFTCHECK_VMEM_AUDIT = [
    ("oversized_paged_decode",
     paged_decode_attention_footprint(page_size=8192, g=32, hd=512,
                                      n_blocks=64, batch=32, quant=True)),
]
