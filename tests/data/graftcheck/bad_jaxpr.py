"""Seeded-bad fixture: jaxpr-audit true positives in one toy function.

The function is deliberately wasteful in exactly the ways the audit
exists to catch — none of which are visible to the AST pass:

- it closes over a 4 MiB weight matrix instead of taking it as an
  argument (``captured-const``);
- it upcasts a large bf16 activation to f32 mid-path (``f32-upcast``);
- it runs a host callback inside the scan hot loop (``host-transfer``);
- it computes a mean nothing consumes (``dead-output``).
"""
import jax
import jax.numpy as jnp

_W = jnp.ones((1024, 1024), jnp.float32)          # 4 MiB, captured by value


def _bad_toy_step(x):
    def body(carry, _):
        jax.debug.callback(lambda v: None, carry[0, 0])   # host round trip
        h = (carry @ _W.astype(jnp.bfloat16)).astype(jnp.float32)  # upcast
        unused = h * 2.0                                   # dead output
        return h.astype(jnp.bfloat16), None

    out, _ = jax.lax.scan(body, x, None, length=2)
    return out


GRAFTCHECK_JAXPR_AUDIT = [
    ("bad_toy_step", _bad_toy_step,
     (jnp.zeros((512, 1024), jnp.bfloat16),)),
]
