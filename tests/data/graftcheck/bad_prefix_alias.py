"""Seeded-bad fixture: a prefill that WRITES a shared prefix page.

Same ``GRAFTCHECK_ALIAS_AUDIT`` hook protocol as the repo's own alias
scenarios (analysis/alias.py): ``build()`` returns
``(fn, args, pool_argnums, pool_outnums, shared_pages)``. The jitted
"prefill" here scatters its page blocks at ids [1, 2] while page 1 is
declared shared — the exact off-by-one a refactor of the admission
bookkeeping could introduce (mounting the hit pages but handing the
scatter the WHOLE block-table row instead of only the owned tail). Every
slot sharing page 1 would silently read this request's KV as its system
prompt — no crash, just corrupted streams — which is why the audit
byte-compares the declared pages instead of trusting the bookkeeping.
"""
import jax
import jax.numpy as jnp


def _build():
    # [L, n_pages, page_size, Hkv, hd] — the serving pool layout.
    pool = jnp.zeros((2, 4, 8, 2, 4), jnp.float32)
    new = jnp.ones((2, 2, 8, 2, 4), jnp.float32)

    @jax.jit
    def prefill(pool, new):
        # BUG: page 1 is a mounted prefix page; only page 2 (and beyond)
        # is this request's own.
        return (pool.at[:, jnp.asarray([1, 2])].set(new),)

    return prefill, (pool, new), (0,), (0,), [1]


GRAFTCHECK_ALIAS_AUDIT = [
    ("prefill_writes_shared_page", _build),
]
