"""Seeded-bad fixture: a multi-query verify footprint over the VMEM
budget.

Same ``GRAFTCHECK_VMEM_AUDIT`` hook protocol as bad_vmem.py /
bad_vmem_paged.py, speculative-verify edition: the page blocks here are
MODEST (256-row int8 pages — nothing the decode budgeter would flag),
but a 64-row verify window over a 32-head GQA group at hd=512 stacks
t·g = 2048 q rows, so the q block + three partial outputs + (acc, m, l)
scratch alone blow past the 16 MiB core — the "just raise gamma" tuning
mistake the verify footprint's q-window multiplier exists to catch
before Mosaic does, in production, at the first speculative config.
"""
from k8s_gpu_scheduler_tpu.analysis.vmem import (
    paged_verify_attention_footprint,
)

GRAFTCHECK_VMEM_AUDIT = [
    ("oversized_verify_window",
     paged_verify_attention_footprint(page_size=256, g=32, hd=512,
                                      n_blocks=32, t=64, batch=32,
                                      quant=True)),
]
