"""Seeded-bad fixture: trace-in-jit true positives.

Reintroducing this file into the scanned tree must fail
``python -m k8s_gpu_scheduler_tpu.analysis`` (and ``--fast``): it puts
obs/ span-API calls inside jit-traced bodies — the host-sync hazard the
``trace-in-jit`` rule exists to catch. A span opened inside a traced
function runs ONCE at trace time: the compiled program replays the
trace-time "duration" forever (a lie), and any tracer attr built from a
traced value concretizes mid-program. The production shape this rule
demands lives in models/serving.py: every span times the HOST side of a
dispatch, never the traced body.
"""
import jax
import jax.numpy as jnp

from k8s_gpu_scheduler_tpu.obs import Tracer

tracer = Tracer()


@jax.jit
def traced_decode_step(x):
    # WRONG: span context manager inside a jit body — evaluated at trace
    # time only; the "timing" is a constant baked into the program.
    with tracer.span("decode_chunk", lane="engine"):
        y = jnp.tanh(x) * 2.0
    return y


def traced_via_wrapper(x, flight_recorder):
    def body(v):
        # WRONG: flight-recorder append inside a scanned body — a host
        # list mutation during tracing records one phantom step.
        flight_recorder.record("decode", tokens=1)
        return v * 0.5, None

    out, _ = jax.lax.scan(lambda c, _: body(c), x, None, length=4)
    return out


@jax.jit
def traced_verify_step(x):
    # WRONG: explicit record()/event() inside a jit body — same class.
    tracer.event("rewind", lane="engine", rewound=2)
    return x + 1
