"""Regenerate the committed golden wire ARTIFACTS (not schemas) —
``python tests/data/wire/regen.py``.

These are serialized bytes from prior-PR wire formats; today's decoders
must keep loading them (tests/test_wire_compat.py). Unlike the schema
goldens (tests/data/graftcheck/schemas/, moved by ``--update-schemas``),
these files should essentially NEVER change: they stand in for
artifacts already on the wire/disk at upgrade time — a shed snapshot
mid-flight, a registry heartbeat from an un-upgraded replica, a journal
checkpoint on a PV. Regenerate only if a format VERSION bump
deliberately orphans them, and say why in the commit.

- ``snapshot_pre_tiering.npz`` — a real tiny-engine mid-run drain
  (queue non-empty, slots mid-decode, prefix tree populated), with the
  PR 16 ``tier_keys`` doc key REMOVED: byte-wise what a pre-tiering
  engine shipped. ``snapshot_pre_tiering.expect.json`` records the
  engine config + drained expectations the test asserts field-by-field.
- ``summary_pr8.json`` — a registry heartbeat with exactly the PR 8
  field set (no prefill_backlog_tokens/tp/weight_device_bytes/
  dram_cached_pages; 2-tuple digest entries).
- ``journal_pr10.json`` — a version-1 journal doc as PR 10 wrote it
  (stored as the JSON doc; the test wraps it into the uint8 carrier).
"""
import dataclasses
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
# Runnable from anywhere: the repo root is three levels up.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(HERE))))
PAGE = 8
SEED = 1234


def regen_snapshot():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import jax.numpy as jnp
    import numpy as np

    from k8s_gpu_scheduler_tpu.models import LlamaConfig, init_params
    from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

    cfg = dataclasses.replace(LlamaConfig.tiny(), dtype=jnp.float32,
                              decode_attn="dense")
    params = init_params(cfg, jax.random.PRNGKey(SEED))
    eng_kw = dict(n_slots=2, max_len=64, chunk=4, prefill_bucket=8,
                  kv_layout="paged", page_size=PAGE, prefix_cache=True)
    eng = ContinuousBatcher(params, cfg, **eng_kw)
    rng = np.random.default_rng(SEED)
    sys_prompt = [int(t) for t in rng.integers(0, cfg.vocab, 2 * PAGE)]
    prompts = [sys_prompt + [int(t) for t in rng.integers(0, cfg.vocab, 3 + i)]
               for i in range(4)]
    prompts += [[int(t) for t in rng.integers(0, cfg.vocab, 11 + i)]
                for i in range(2)]
    ids = [eng.submit(p, max_new=9) for p in prompts]
    for _ in range(3):      # mid-run: slots decoding, queue still populated,
        eng.step()          # finished shared-prefix slots donated tree pages
    snap = eng.drain()
    assert snap.n_requests_in_flight > 0 and snap.queue and snap.slot_req \
        and snap.tree_paths, "drain point no longer mid-run — re-probe"
    tree = dict(snap.to_pytree())
    doc = json.loads(bytes(np.asarray(tree["meta_json"])).decode())
    # PR 16 added tier_keys to the doc (default-[] on load). Strip it:
    # these bytes must be what a PRE-TIERING engine actually wrote.
    doc.pop("tier_keys")
    tree["meta_json"] = np.frombuffer(
        json.dumps(doc).encode(), dtype=np.uint8).copy()
    np.savez(os.path.join(HERE, "snapshot_pre_tiering.npz"), **tree)

    expect = {
        "engine_kw": {k: v for k, v in eng_kw.items()},
        "cfg": {"dtype": "float32", "decode_attn": "dense"},
        "seed": SEED,
        "prompts": prompts,
        "max_new": 9,
        "request_ids": ids,
        "fingerprint": snap.fingerprint,
        "page_ids": [int(p) for p in snap.page_ids],
        "lens": [int(x) for x in snap.lens],
        "n_requests_in_flight": snap.n_requests_in_flight,
        "queue": [[int(r), [int(t) for t in p]] for r, p in snap.queue],
        "out": {str(r): [int(t) for t in ts] for r, ts in snap.out.items()},
        "budgets": {str(r): int(b) for r, b in snap.budgets.items()},
        "n_tree_paths": len(snap.tree_paths),
        "payload_sha256": __import__("hashlib").sha256(
            np.ascontiguousarray(snap.k_pages).tobytes()
            + np.ascontiguousarray(snap.v_pages).tobytes()).hexdigest(),
    }
    with open(os.path.join(HERE, "snapshot_pre_tiering.expect.json"),
              "w") as fh:
        json.dump(expect, fh, indent=2, sort_keys=True)
        fh.write("\n")
    print("snapshot_pre_tiering.npz:", snap.n_requests_in_flight,
          "in flight,", len(snap.page_ids), "pages")


def regen_summary():
    # Exactly the PR 8 field set, handwritten — no constructor, so
    # today's dataclass can never leak new fields into the golden.
    doc = {
        "replica": "replica-3",
        "fleet": "serving",
        "seq": 17,
        "published_wall": 1723456789.5,
        "page_size": 8,
        "pages_total": 64,
        "pages_free": 12,
        "n_slots": 4,
        "active_slots": 3,
        "queued": 2,
        "decode_p50_s": 0.012,
        "prefill_p50_s": 0.085,
        "digest": [[[101, 102, 103, 104, 105, 106, 107, 108], 16],
                   [[201, 202, 203, 204], 8]],
    }
    with open(os.path.join(HERE, "summary_pr8.json"), "w") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")
    print("summary_pr8.json: seq", doc["seq"])


def regen_journal():
    # The version-1 doc exactly as PR 10's router persisted it.
    doc = {
        "version": 1,
        "next_frid": 5,
        "delivered_tokens_total": 23,
        "closed": {"done": 2, "error": 0, "expired": 1},
        "entries": [
            {"frid": 2, "prompt": [11, 12, 13], "max_new": 8,
             "trace_id": "trace-2", "replica": "replica-0",
             "deadline_wall": 1723456800.0, "submitted_wall": 1723456700.0,
             "delivered": [41, 42, 43], "failovers": 1},
            {"frid": 4, "prompt": [21, 22], "max_new": 4,
             "trace_id": None, "replica": None,
             "deadline_wall": None, "submitted_wall": 1723456710.0,
             "delivered": [], "failovers": 0},
        ],
    }
    with open(os.path.join(HERE, "journal_pr10.json"), "w") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")
    print("journal_pr10.json:", len(doc["entries"]), "open entries")


if __name__ == "__main__":
    regen_summary()
    regen_journal()
    regen_snapshot()
