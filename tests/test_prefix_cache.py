"""Shared-prefix KV reuse (models/prefix_cache.py + the ref-counted
PageAllocator + the prefix-aware paged ContinuousBatcher).

The correctness story has two legs the suite pins separately:

1. **Token identity** — with ``prefix_cache=True`` a batch of
   shared-prefix requests must produce byte-identical token streams to
   the cache-off paged path (itself pinned against the contiguous
   engine by tests/test_paged_attention.py), across dense/fused × cache
   dtypes, THROUGH evictions, and after a reaped request's donated pages
   are re-shared. The cached pages hold exactly the bytes the cache-off
   prefill would have written (prefill KV of a prefix is a deterministic
   function of the prefix), so reuse must be output-invisible. For the
   int8-KV cases the guarantee is quantization-noise-bounded rather
   than structural — the tail prefill attends the dequantized prefix
   (the values decode also attends) where cache-off attends its bf16
   mini cache, so a near-exact first-token logit tie could flip; these
   tests pin fixed seeds/configs where it must not (see the parity note
   on serving._prefill_multi_paged_fn).
2. **Reference discipline** — a shared page never returns to the free
   list while any slot or the tree holds it, double frees raise before
   mutating, and free ∪ held ∪ cached always partitions the pool
   (``assert_consistent``). The write-side of the contract (shared pages
   are read-only) is enforced by the graftcheck alias audit
   (tests/test_analysis.py::TestAliasAudit).
"""
import dataclasses
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_scheduler_tpu.models.paging import NULL_PAGE, PageAllocator
from k8s_gpu_scheduler_tpu.models.prefix_cache import PrefixCache


# -- radix tree ---------------------------------------------------------------

class TestPrefixTree:
    def _cache(self, n_pages=17, ps=4):
        alloc = PageAllocator(n_pages)
        return PrefixCache(alloc, ps), alloc

    def test_match_is_page_aligned_longest_prefix(self):
        cache, alloc = self._cache()
        pages = alloc.alloc(3)
        toks = list(range(12))
        assert cache.insert(toks, pages) == pages    # all three adopted
        assert cache.match(toks + [99]) == pages     # full 12-token hit
        assert cache.match(toks[:11]) == pages[:2]   # partial page -> 2
        assert cache.match(toks[:8] + [7, 7, 7, 7]) == pages[:2]
        assert cache.match([5] + toks) == []         # shifted: no hit
        alloc.assert_consistent()

    def test_match_always_leaves_a_token_to_prefill(self):
        """A FULLY cached page-aligned prompt matches one page short —
        admission needs the last-position logits for its first token."""
        cache, alloc = self._cache()
        pages = alloc.alloc(3)
        toks = list(range(12))
        cache.insert(toks, pages)
        assert cache.match(toks) == pages[:2]        # not all 3

    def test_insert_adopts_only_novel_chunks(self):
        cache, alloc = self._cache()
        a = alloc.alloc(2)
        cache.insert(list(range(8)), a)
        b = alloc.alloc(2)
        # Same first chunk, new second chunk: only b[1] adopted; b[0] is
        # the caller's duplicate to release.
        adopted = cache.insert(list(range(4)) + [9, 9, 9, 9], b)
        assert adopted == [b[1]]
        assert cache.match(list(range(4)) + [9, 9, 9, 9, 1]) == [a[0], b[1]]
        alloc.free([b[0]])
        alloc.assert_consistent()

    def test_eviction_is_lru_and_leaf_only(self):
        cache, alloc = self._cache()
        a = alloc.alloc(2)                           # path of depth 2
        cache.insert(list(range(8)), a)
        b = alloc.alloc(1)                           # sibling branch
        cache.insert(list(range(4)) + [7, 7, 7, 7], [a[0]] + b)
        cache.match(list(range(8)) + [0])            # path a is now newest
        # One eviction: the LRU *leaf* is b's node — NOT a[0], which is
        # an interior node (evicting it would strand a[1]'s context).
        assert cache.evict(1) == 1
        assert cache.match(list(range(4)) + [7, 7, 7, 7, 1]) == [a[0]]
        assert cache.match(list(range(8)) + [0]) == a
        # Draining the rest peels leaves upward.
        assert cache.evict(10) == 2
        assert len(cache) == 0
        assert alloc.free_count == alloc.n_pages - 1
        alloc.assert_consistent()

    def test_eviction_skips_pages_slots_still_share(self):
        cache, alloc = self._cache()
        a = alloc.alloc(2)
        cache.insert(list(range(8)), a)
        alloc.retain([a[1]])                         # a slot mounts the leaf
        assert cache.evict(5) == 0                   # leaf pinned, parent interior
        alloc.free([a[1]])                           # slot reaps
        assert cache.evict(5) == 2
        alloc.assert_consistent()

    def test_insert_shorter_than_chunks_raises(self):
        cache, alloc = self._cache()
        with pytest.raises(ValueError, match="chunks"):
            cache.insert(list(range(8)), alloc.alloc(1))


# -- ref-counted allocator ----------------------------------------------------

class TestRefCounting:
    def test_shared_page_outlives_individual_frees(self):
        a = PageAllocator(5)
        pages = a.alloc(2)
        a.retain([pages[0]])                         # second holder
        a.free(pages)                                # first holder drops both
        assert a.ref(pages[0]) == 1 and a.ref(pages[1]) == 0
        assert pages[1] in a._free and pages[0] not in a._free
        a.free([pages[0]])                           # last reference
        assert a.free_count == 4
        a.assert_consistent()

    def test_retain_free_foreign_pages_raise(self):
        a = PageAllocator(5)
        with pytest.raises(RuntimeError, match="retain"):
            a.retain([3])
        with pytest.raises(ValueError, match="null page"):
            a.retain([NULL_PAGE])
        held = a.alloc(1)
        with pytest.raises(RuntimeError, match="double free"):
            a.free(held + held)                      # 2 drops, 1 reference

    def test_cached_page_cannot_leave_via_free(self):
        """The tree's reference drops via drop_cached (eviction) only —
        free() reaching it means slot bookkeeping leaked."""
        a = PageAllocator(5)
        p = a.alloc(1)
        a.adopt(p)
        with pytest.raises(RuntimeError, match="cached"):
            a.free(p)
        a.retain(p)                                  # slot share: free ok
        a.free(p)
        a.drop_cached(p[0])
        assert a.free_count == 4
        with pytest.raises(RuntimeError, match="not cached"):
            a.drop_cached(p[0])
        a.assert_consistent()

    def test_assert_consistent_catches_corruption(self):
        a = PageAllocator(5)
        held = a.alloc(2)
        a.assert_consistent()
        a._free.append(held[0])                      # free AND allocated
        with pytest.raises(RuntimeError, match="both free and allocated"):
            a.assert_consistent()
        a._free.pop()
        del a._ref[held[0]]                          # vanished page
        with pytest.raises(RuntimeError, match="not covered"):
            a.assert_consistent()


# -- engine parity ------------------------------------------------------------

def _engine(params, cfg, **kw):
    from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

    base = dict(n_slots=2, max_len=64, chunk=4, prefill_bucket=8,
                kv_layout="paged", page_size=8)
    base.update(kw)
    return ContinuousBatcher(params, cfg, **base)


class TestPrefixEngineParity:
    def _setup(self, dtype=jnp.float32, **cfg_kw):
        from k8s_gpu_scheduler_tpu.models import LlamaConfig, init_params

        cfg = dataclasses.replace(LlamaConfig.tiny(), dtype=dtype, **cfg_kw)
        params = init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        sysA = list(rng.integers(0, cfg.vocab, 16))  # 2 pages
        sysB = list(rng.integers(0, cfg.vocab, 16))
        prompts = [sysA + list(rng.integers(0, cfg.vocab, 5))
                   for _ in range(3)]
        prompts += [sysB + list(rng.integers(0, cfg.vocab, 3))
                    for _ in range(3)]
        return cfg, params, prompts

    def _drive(self, params, cfg, prompts, prefix_cache, **kw):
        eng = _engine(params, cfg, prefix_cache=prefix_cache, **kw)
        ids = [eng.submit(p, max_new=5) for p in prompts]
        done = eng.run()
        return [done[i] for i in ids], eng

    @pytest.mark.parametrize("impl,kvd", [
        ("dense", None),
        pytest.param("dense", "int8", marks=pytest.mark.slow),
        pytest.param("fused", None, marks=pytest.mark.slow),
        ("fused", "int8"),
    ])
    def test_cache_on_matches_cache_off(self, impl, kvd):
        """The acceptance grid: shared-prefix batches are token-identical
        with the cache on and off, dense and fused, both cache dtypes —
        and the reuse actually happened (tokens skipped, pages shared)."""
        cfg, params, prompts = self._setup(decode_attn=impl)
        on, eng = self._drive(params, cfg, prompts, True, kv_dtype=kvd)
        off, _ = self._drive(params, cfg, prompts, False, kv_dtype=kvd)
        assert on == off
        m = eng.pool_metrics()
        assert m["prefill_tokens_skipped"] > 0
        assert m["prefix_request_hit_rate"] > 0
        # At drain only the tree holds pages: in_use == cached, and the
        # pool still partitions cleanly.
        assert m["pages_in_use"] == m["pages_cached"] > 0
        eng._alloc.assert_consistent()

    # PR 13 rebalance: the fused-int8 production cell above stays
    # tier-1; the bf16 near-tie noise class is documented and this cell
    # rides the unfiltered CI run.
    @pytest.mark.slow
    def test_bf16_cache_on_matches_cache_off(self):
        cfg, params, prompts = self._setup(dtype=jnp.bfloat16,
                                           decode_attn="fused")
        on, _ = self._drive(params, cfg, prompts, True, kv_dtype="int8")
        off, _ = self._drive(params, cfg, prompts, False, kv_dtype="int8")
        assert on == off

    def test_parity_through_evictions_and_resharing(self):
        """A pool too small to cache everything: admissions force LRU
        evictions, reaped requests re-donate, later requests re-share the
        re-donated pages — and the streams still match cache-off exactly."""
        from k8s_gpu_scheduler_tpu.models import LlamaConfig, init_params

        cfg = dataclasses.replace(LlamaConfig.tiny(), dtype=jnp.float32)
        params = init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.default_rng(1)
        sys_prompts = [list(rng.integers(0, cfg.vocab, 16))
                       for _ in range(4)]
        prompts = [sys_prompts[i % 4]
                   + list(rng.integers(0, cfg.vocab, 5))
                   for i in range(12)]
        # 9 usable pages, 4 per admission: constant eviction pressure.
        on, eng = self._drive(params, cfg, prompts, True, n_pages=10)
        off, _ = self._drive(params, cfg, prompts, False, n_pages=10)
        assert on == off
        m = eng.pool_metrics()
        assert m["prefix_evictions"] > 0, "scenario must actually evict"
        assert m["prefix_request_hit_rate"] > 0, "and still hit"
        eng._alloc.assert_consistent()

    def test_reshared_after_reap_matches(self):
        """Sequential waves: wave 1 populates the tree (donation at
        reap), wave 2 re-shares the SAME donated pages — token identity
        must survive the page handoff."""
        cfg, params, prompts = self._setup()
        eng = _engine(params, cfg, prefix_cache=True)
        out_on = {}
        for p in prompts:                            # one at a time: every
            rid = eng.submit(p, max_new=5)           # later wave re-shares
            out_on[rid] = eng.run()[rid]
        off, _ = self._drive(params, cfg, prompts, False)
        assert list(out_on.values()) == off
        assert eng.pool_metrics()["prefix_request_hit_rate"] \
            == pytest.approx(4 / 6)                  # all but the 2 firsts


class TestPrefixEngineBehavior:
    def _tiny(self):
        from k8s_gpu_scheduler_tpu.models import LlamaConfig, init_params

        cfg = dataclasses.replace(LlamaConfig.tiny(), dtype=jnp.float32)
        return cfg, init_params(cfg, jax.random.PRNGKey(0))

    def test_prefix_cache_requires_paged_layout(self):
        cfg, params = self._tiny()
        with pytest.raises(ValueError, match="paged"):
            _engine(params, cfg, kv_layout="contiguous", prefix_cache=True)

    def test_fully_cached_prompt_still_prefills_its_last_page(self):
        """A page-aligned prompt that is entirely cached must still admit
        and produce correct output (the match cap leaves the final page
        to prefill for the first-token logits)."""
        cfg, params = self._tiny()
        rng = np.random.default_rng(2)
        prompt = list(rng.integers(0, cfg.vocab, 16))  # exactly 2 pages
        eng = _engine(params, cfg, prefix_cache=True)
        a = eng.submit(prompt, max_new=4)
        first = eng.run()[a]
        b = eng.submit(prompt, max_new=4)              # full-prompt hit
        second = eng.run()[b]
        assert first == second
        # Only ONE page was reusable (cap), and it was reused.
        assert eng.pool_metrics()["prefill_tokens_skipped"] == 8

    def test_pool_never_leaks_across_a_burst(self):
        cfg, params = self._tiny()
        rng = np.random.default_rng(3)
        eng = _engine(params, cfg, prefix_cache=True, n_slots=2)
        sysp = list(rng.integers(0, cfg.vocab, 8))
        for wave in range(3):
            for _ in range(3):
                eng.submit(sysp + list(rng.integers(0, cfg.vocab, 4)),
                           max_new=3)
            eng.run()
            eng._alloc.assert_consistent()
        m = eng.pool_metrics()
        assert m["pages_in_use"] == m["pages_cached"]
        # Evict everything: the pool drains back to pristine.
        eng._prefix.evict(int(m["pages_cached"]))
        assert eng.pool_metrics()["pages_in_use"] == 0
        eng._alloc.assert_consistent()

    def test_max_new_one_request_still_donates(self):
        """The prefill-only (max_new==1) path retires through the same
        donation bookkeeping: its prompt becomes reusable."""
        cfg, params = self._tiny()
        rng = np.random.default_rng(4)
        prompt = list(rng.integers(0, cfg.vocab, 11))
        eng = _engine(params, cfg, prefix_cache=True)
        eng.submit(prompt, max_new=1)
        eng.run()
        assert eng.pool_metrics()["prefix_cached_pages"] == 1
        assert eng._prefix.match(prompt) != []


class TestBenchLeg:
    @pytest.mark.slow   # the dedicated CI step runs the same leg
    def test_prefix_cache_bench_smoke(self):
        """`bench.py --leg prefix_cache --smoke` must emit ONE JSON line
        whose reuse contract holds: prefill tokens skipped > 0 and a
        steady-state request hit rate >= 0.9 on the K-shared-prompts
        workload — the acceptance numbers the CI bench step gates on."""
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        out = subprocess.run(
            [sys.executable, "bench.py", "--leg", "prefix_cache",
             "--smoke"],
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            capture_output=True, text=True, timeout=600, env=env)
        assert out.returncode == 0, out.stderr[-2000:]
        lines = [ln for ln in out.stdout.splitlines() if ln.strip()]
        assert len(lines) == 1, out.stdout
        rec = json.loads(lines[0])
        assert rec["metric"] == "prefix_cache_bench"
        extra = rec["extra"]
        assert extra["prefix_cache_tokens_skipped"] > 0
        assert extra["prefix_cache_request_hit_rate"] >= 0.9
        assert 0 < extra["prefix_cache_hit_rate"] <= 1.0
        for key in ("prefix_cache_ttft_p50_ms", "prefix_cache_off_ttft_p50_ms",
                    "prefix_cache_page_utilization"):
            assert extra.get(key, 0) > 0, (key, extra)
