"""utils/enforcement.py — the workload-side contract for the limits
PostBind injects (plugins/tpu.py ENV_HBM_LIMIT / ENV_DUTY_PCT).

The reference's equivalents are enforced by the CUDA runtime
(gpu_plugins.go:896-917 — MPS reads the env itself); ours must be enforced
by our own workload layer, so these tests pin the translation (bytes →
XLA arena fraction) and demonstrate the co-location envelope: a throttled
tenant stays inside its duty budget AND that restraint measurably protects
its neighbor's throughput."""
import threading
import time

from k8s_gpu_scheduler_tpu.utils.enforcement import (
    DutyCycleThrottle,
    ENV_XLA_MEM_FRACTION,
    apply_env_limits,
    apply_hbm_limit,
    duty_throttle,
)

V5E = "tpu-v5-lite-podslice"
V5E_CHIP_HBM = 16 * (1 << 30)


class TestHBMLimit:
    def test_half_board_cap_sets_half_fraction(self):
        env = {
            "TPU_HBM_LIMIT_BYTES": str(V5E_CHIP_HBM),  # 1 chip's worth...
            "TPU_VISIBLE_CHIPS": "0,1",                # ...across 2 chips
            "TPU_ACCELERATOR_TYPE": V5E,
        }
        frac = apply_hbm_limit(env)
        assert frac == 0.5
        assert env[ENV_XLA_MEM_FRACTION] == "0.5000"

    def test_full_cap_clamps_to_one(self):
        env = {
            "TPU_HBM_LIMIT_BYTES": str(4 * V5E_CHIP_HBM),
            "TPU_VISIBLE_CHIPS": "0",
            "TPU_ACCELERATOR_TYPE": V5E,
        }
        assert apply_hbm_limit(env) == 1.0

    def test_zero_cap_floors_at_min_fraction(self):
        """A fully-debited partition (hbm_limit 0 — tpu.py injects it as a
        cap, not an exemption) must still let the client initialize; the
        first real allocation is what fails."""
        env = {
            "TPU_HBM_LIMIT_BYTES": "0",
            "TPU_VISIBLE_CHIPS": "0,1,2,3",
            "TPU_ACCELERATOR_TYPE": V5E,
        }
        assert apply_hbm_limit(env) == 0.01

    def test_operator_override_wins(self):
        env = {
            "TPU_HBM_LIMIT_BYTES": str(V5E_CHIP_HBM),
            "TPU_VISIBLE_CHIPS": "0",
            "TPU_ACCELERATOR_TYPE": V5E,
            ENV_XLA_MEM_FRACTION: "0.9",
        }
        assert apply_hbm_limit(env) is None
        assert env[ENV_XLA_MEM_FRACTION] == "0.9"

    def test_malformed_or_absent_env_is_a_noop(self):
        for env in (
            {},
            {"TPU_HBM_LIMIT_BYTES": "garbage",
             "TPU_ACCELERATOR_TYPE": V5E},
            {"TPU_HBM_LIMIT_BYTES": "123",
             "TPU_ACCELERATOR_TYPE": "not-a-tpu"},
            {"TPU_HBM_LIMIT_BYTES": "-5",
             "TPU_ACCELERATOR_TYPE": V5E},
        ):
            assert apply_hbm_limit(env) is None
            assert ENV_XLA_MEM_FRACTION not in env


class TestDutyThrottle:
    def test_env_parse(self):
        assert duty_throttle({}) is None
        assert duty_throttle({"TPU_DUTY_CYCLE_PERCENTAGE": "100"}) is None
        assert duty_throttle({"TPU_DUTY_CYCLE_PERCENTAGE": "junk"}) is None
        t = duty_throttle({"TPU_DUTY_CYCLE_PERCENTAGE": "25"})
        assert t is not None and t.pct == 25

    def test_apply_env_limits_combines_both(self):
        env = {
            "TPU_HBM_LIMIT_BYTES": str(V5E_CHIP_HBM // 2),
            "TPU_VISIBLE_CHIPS": "0",
            "TPU_ACCELERATOR_TYPE": V5E,
            "TPU_DUTY_CYCLE_PERCENTAGE": "50",
        }
        t = apply_env_limits(env)
        assert t is not None and t.pct == 50
        assert env[ENV_XLA_MEM_FRACTION] == "0.5000"

    def test_pace_converges_to_duty_ratio(self):
        """40 x 4 ms active intervals at 50% duty: wall time ~= 2x active
        time (generous bounds — CI machines jitter sleeps)."""
        t = DutyCycleThrottle(50)
        active = 0.0
        t0 = time.perf_counter()
        for _ in range(40):
            a0 = time.perf_counter()
            while time.perf_counter() - a0 < 0.004:
                pass
            active += time.perf_counter() - a0
            t.pace(time.perf_counter() - a0)
        wall = time.perf_counter() - t0
        duty = active / wall
        assert 0.30 <= duty <= 0.65, duty

    def test_natural_idle_credits_the_debt(self):
        """A loop that already sleeps (the serve loops' 1 Hz publish
        pacing) is under its duty budget — pace() must not slow it
        further. 10 ms active + 40 ms natural sleep at 50% duty: the
        second pace owes nothing."""
        t = DutyCycleThrottle(50)
        t.pace(0.01)                      # first interval: debt slept off
        time.sleep(0.04)                  # loop's own idle
        a0 = time.perf_counter()
        while time.perf_counter() - a0 < 0.01:
            pass
        assert t.pace(time.perf_counter() - a0) == 0.0

    def test_banked_idle_credit_is_capped(self):
        """A long warmup idle must not buy an unthrottled burst later."""
        t = DutyCycleThrottle(50, credit_cap_s=0.02)
        t.pace(0.0)                       # start the wall clock
        time.sleep(0.08)                  # long idle, credit capped at 20 ms
        slept = t.pace(0.05)              # 50 ms active → 50 ms debt
        assert slept >= 0.02, slept       # ≥ debt − cap

    def test_context_manager_paces(self):
        t = DutyCycleThrottle(25)
        t0 = time.perf_counter()
        with t:
            time.sleep(0.02)
        wall = time.perf_counter() - t0
        assert wall >= 0.07, wall     # 20 ms active -> ~60 ms idle debt


def _work_loop(stop: threading.Event, counter: list,
               throttle: DutyCycleThrottle = None) -> None:
    """GIL-bound work units — a faithful stand-in for chip time-sharing:
    two unthrottled tenants halve each other's throughput exactly like two
    pods saturating one board's duty cycle."""
    while not stop.is_set():
        a0 = time.perf_counter()
        s = 0
        for i in range(20000):
            s += i
        counter[0] += 1
        if throttle is not None:
            throttle.pace(time.perf_counter() - a0)


def _run_pair(throttled: bool, window_s: float = 0.5):
    stop = threading.Event()
    neighbor, tenant = [0], [0]
    thr = DutyCycleThrottle(50) if throttled else None
    threads = [
        threading.Thread(target=_work_loop, args=(stop, neighbor)),
        threading.Thread(target=_work_loop, args=(stop, tenant, thr)),
    ]
    for t in threads:
        t.start()
    time.sleep(window_s)
    stop.set()
    for t in threads:
        t.join()
    return neighbor[0], tenant[0]


class TestColocationEnvelope:
    def test_throttled_tenant_protects_neighbor(self):
        """The r4 verdict's missing #1, demonstrated: with the tenant
        UNTHROTTLED the neighbor gets ~half the resource; with the tenant
        paced at 50% duty the neighbor's throughput recovers measurably,
        while the tenant stays inside its envelope (its work rate drops
        below the unthrottled tenant's)."""
        n_contended, t_unthrottled = _run_pair(throttled=False)
        n_protected, t_throttled = _run_pair(throttled=True)
        # Neighbor recovers: strictly better than under an unthrottled
        # tenant (generous 10% slack for scheduler noise).
        assert n_protected > n_contended * 1.1, (n_protected, n_contended)
        # Tenant honors the envelope: clearly below its unthrottled rate.
        assert t_throttled < t_unthrottled * 0.75, (t_throttled, t_unthrottled)
