"""Slice-repartition state machine tests — the async reconfigure analogue
(gpu_plugins.go:357-452 rebuilt per SURVEY.md hard part e): idle node
repartitions to fit an incoming pod's SLO while scheduling proceeds; failed
confirmation rolls back."""
import time

import pytest

from k8s_gpu_scheduler_tpu.api.objects import (
    ANN_RESHAPE_STATE,
    ANN_SLICE_CONFIG,
    ConfigMap,
    ObjectMeta,
)
from k8s_gpu_scheduler_tpu.cluster import APIServer
from k8s_gpu_scheduler_tpu.config import SchedulerConfig
from k8s_gpu_scheduler_tpu.plugins import TPUPlugin
from k8s_gpu_scheduler_tpu.registry.inventory import HEARTBEAT_SUFFIX, node_key
from k8s_gpu_scheduler_tpu.sched import Profile, Scheduler, SliceReshaper
from tests.test_plugins import (
    FakeRecommender,
    FakeRegistry,
    mk_node,
    mk_pod,
    wait_until,
)


class TestStateMachine:
    def test_request_annotates_and_confirms_without_registry(self):
        server = APIServer()
        server.create(mk_node("n1"))
        sched = Scheduler(server, profile=Profile(), config=SchedulerConfig())
        reshaper = SliceReshaper(sched.descriptor, registry=None,
                                 poll_interval_s=0.02)
        try:
            assert reshaper.request("n1", "2x2")
            assert wait_until(lambda: not reshaper.in_flight("n1"))
            node = server.get("Node", "n1", "default")
            assert node.metadata.annotations[ANN_SLICE_CONFIG] == "2x2"
            assert ANN_RESHAPE_STATE not in node.metadata.annotations
        finally:
            reshaper.stop()

    def test_duplicate_and_noop_requests_refused(self):
        server = APIServer()
        server.create(mk_node("n1"))
        sched = Scheduler(server, profile=Profile(), config=SchedulerConfig())
        reg = FakeRegistry()  # no heartbeat → stays in flight
        reshaper = SliceReshaper(sched.descriptor, registry=reg,
                                 poll_interval_s=0.02, timeout_s=30)
        try:
            assert reshaper.request("n1", "2x2")
            assert not reshaper.request("n1", "1x2")  # busy
        finally:
            reshaper.stop()
        server2 = APIServer()
        n = mk_node("n2", annotations={ANN_SLICE_CONFIG: "2x2"})
        server2.create(n)
        sched2 = Scheduler(server2, profile=Profile(), config=SchedulerConfig())
        r2 = SliceReshaper(sched2.descriptor)
        assert not r2.request("n2", "2x2")  # already there

    def test_confirmation_via_agent_heartbeat(self):
        server = APIServer()
        server.create(mk_node("n1"))
        sched = Scheduler(server, profile=Profile(), config=SchedulerConfig())
        reg = FakeRegistry()
        reshaper = SliceReshaper(sched.descriptor, registry=reg,
                                 poll_interval_s=0.02, timeout_s=30)
        try:
            assert reshaper.request("n1", "1x2")
            time.sleep(0.1)
            assert reshaper.in_flight("n1")  # no heartbeat yet
            # Agent republishes after the request → confirmed.
            reg.set(node_key("n1") + HEARTBEAT_SUFFIX, str(time.time() + 1))
            assert wait_until(lambda: not reshaper.in_flight("n1"))
            node = server.get("Node", "n1", "default")
            assert node.metadata.annotations[ANN_SLICE_CONFIG] == "1x2"
        finally:
            reshaper.stop()

    def test_timeout_rolls_back(self):
        server = APIServer()
        server.create(mk_node("n1", annotations={ANN_SLICE_CONFIG: "2x4"}))
        sched = Scheduler(server, profile=Profile(), config=SchedulerConfig())
        reshaper = SliceReshaper(sched.descriptor, registry=FakeRegistry(),
                                 poll_interval_s=0.02, timeout_s=0.1)
        try:
            assert reshaper.request("n1", "1x1")
            assert wait_until(lambda: not reshaper.in_flight("n1"))
            node = server.get("Node", "n1", "default")
            assert node.metadata.annotations[ANN_SLICE_CONFIG] == "2x4"
            assert ANN_RESHAPE_STATE not in node.metadata.annotations
        finally:
            reshaper.stop()


class TestRecovery:
    def test_orphaned_applying_annotation_adopted_and_cleared(self):
        """A reshaper restart mid-reshape must not leave the node filtered
        out forever — the new instance adopts the orphan and clears it."""
        server = APIServer()
        server.create(mk_node("n1", annotations={
            ANN_SLICE_CONFIG: "2x2", ANN_RESHAPE_STATE: "applying",
        }))
        sched = Scheduler(server, profile=Profile(), config=SchedulerConfig())
        reshaper = SliceReshaper(sched.descriptor, registry=None,
                                 poll_interval_s=0.02)
        try:
            assert wait_until(lambda: not reshaper.in_flight("n1"))
            node = server.get("Node", "n1", "default")
            assert ANN_RESHAPE_STATE not in node.metadata.annotations
            assert node.metadata.annotations[ANN_SLICE_CONFIG] == "2x2"
        finally:
            reshaper.stop()

    def test_request_after_stop_refused(self):
        server = APIServer()
        server.create(mk_node("n1"))
        sched = Scheduler(server, profile=Profile(), config=SchedulerConfig())
        reshaper = SliceReshaper(sched.descriptor)
        reshaper.stop()
        assert not reshaper.request("n1", "2x2")
        node = server.get("Node", "n1", "default")
        assert ANN_RESHAPE_STATE not in node.metadata.annotations

    def test_rightsize_never_below_pod_request(self):
        """A 4-chip pod must not trigger repartition into 1-chip slices it
        cannot fit (plugins.tpu._rightsize chip floor)."""
        from k8s_gpu_scheduler_tpu.api.topology import SliceTopology
        from k8s_gpu_scheduler_tpu.sched import Handle

        conf = {
            "2x4": {"1P_V5E": 100.0},
            "2x2": {"2P_V5E": 60.0},
            "1x2": {"4P_V5E": 30.0},
            "1x1": {"8P_V5E": 12.0},
        }
        sched = Scheduler(APIServer(), profile=Profile(),
                          config=SchedulerConfig())
        plugin = TPUPlugin(sched.handle, recommender=FakeRecommender(conf=conf))
        topo = SliceTopology.parse("tpu-v5-lite-podslice", "2x4")
        # SLO 10: unconstrained cheapest would be 1x1 (pred 12) — but a
        # 4-chip pod needs at least 2x2.
        assert plugin._rightsize(topo, 10.0, chips_wanted=4) == "2x2"
        assert plugin._rightsize(topo, 10.0, chips_wanted=1) == "1x1"


class TestSchedulerIntegration:
    def test_idle_node_repartitions_while_scheduling_proceeds(self):
        """BASELINE config 5 shape: an SLO pod triggers right-sizing of the
        idle node to a finer partitioning; a concurrent no-SLO pod keeps
        binding elsewhere; the SLO pod lands after the reshape completes."""
        server = APIServer()
        reg = FakeRegistry()
        reg.publish("idle", utilization=0.0)
        reg.publish("other", utilization=0.2)
        conf = {
            "2x4": {"1P_V5E": 100.0},
            "2x2": {"2P_V5E": 60.0},
            "1x2": {"4P_V5E": 30.0},
            "1x1": {"8P_V5E": 12.0},
            "slojob": {"1P_V5E": 100.0, "2P_V5E": 60.0, "4P_V5E": 30.0},
        }
        rec = FakeRecommender(conf=conf, intf={})
        cfg = SchedulerConfig(backoff_initial_s=0.05, backoff_max_s=0.2)
        sched = Scheduler(server, profile=Profile(), config=cfg)
        reshaper = SliceReshaper(sched.descriptor, registry=reg,
                                 poll_interval_s=0.02, timeout_s=10)
        tpu = TPUPlugin(sched.handle, registry=reg, recommender=rec,
                        reshaper=reshaper)
        sched.profile = Profile(pre_filter=[tpu], filter=[tpu], score=[tpu],
                                reserve=[tpu], post_bind=[tpu])
        server.create(mk_node("idle"))
        server.create(mk_node("other"))
        server.create(ConfigMap(metadata=ObjectMeta(name="cm-s"), data={}))
        server.create(ConfigMap(metadata=ObjectMeta(name="cm-p"), data={}))
        # SLO 25 → cheapest satisfying config is 1x2 (pred 30) ≠ whole board.
        slo_pod = mk_pod("slojob-0", chips=2, slo=25.0, cm="cm-s")
        # Steer the SLO pod to the idle node (utilization scoring would pick
        # it anyway; the selector makes the test deterministic).
        slo_pod.spec.node_selector = {"pool": "idle"}
        idle = server.get("Node", "idle", "default")
        plain_pod = mk_pod("plain-0", chips=1, cm="cm-p")
        plain_pod.spec.node_selector = {"pool": "other"}

        def patch(n, pool):
            def fn(node):
                node.metadata.labels["pool"] = pool
            server.mutate("Node", n, "default", fn)
        patch("idle", "idle")
        patch("other", "other")
        server.create(slo_pod)
        server.create(plain_pod)
        sched.start()
        try:
            # The plain pod binds promptly even while the reshape is pending.
            assert wait_until(
                lambda: server.get("Pod", "plain-0", "default").spec.node_name
            )
            # Reshape begins; agent heartbeat confirms it.
            assert wait_until(lambda: reshaper.in_flight("idle"), timeout=5)
            reg.set(node_key("idle") + HEARTBEAT_SUFFIX, str(time.time() + 1))
            assert wait_until(
                lambda: server.get("Pod", "slojob-0", "default").spec.node_name
                == "idle",
                timeout=10,
            )
            node = server.get("Node", "idle", "default")
            assert node.metadata.annotations[ANN_SLICE_CONFIG] == "1x2"
            # The bound pod's assignment reflects the new partitioning.
            cm = server.get("ConfigMap", "cm-s", "default").data
            assert cm["TPU_TOPOLOGY"] == "1x2"
            assert cm["TPU_VISIBLE_CHIPS"] in ("0,1", "2,3", "4,5", "6,7")
        finally:
            sched.stop()
            reshaper.stop()


class TestNoRegistryRefusal:
    def test_in_cluster_mode_refuses_without_confirmation_source(self):
        """r3 weak #7: with no registry AND simulation not opted into, a
        reshape request is refused — applying→idle must never flip on a
        timer nothing observed."""
        server = APIServer()
        server.create(mk_node("n1"))
        sched = Scheduler(server, profile=Profile(), config=SchedulerConfig())
        reshaper = SliceReshaper(sched.descriptor, registry=None,
                                 simulate_without_registry=False)
        try:
            assert not reshaper.request("n1", "2x2")
            node = server.get("Node", "n1", "default")
            assert ANN_RESHAPE_STATE not in node.metadata.annotations
        finally:
            reshaper.stop()
