"""Chunked prefill: the token-budget step scheduler inside the paged
``ContinuousBatcher`` (``prefill_chunk_tokens``).

Proof obligations of the chunked-prefill PR:

- **Token identity** — chunked streams are byte-equal to unchunked
  streams for the same workload, across dense/fused × bf16(f32)/int8-KV
  × prefix-cache × speculative. A continuation chunk is the prefix-cache
  tail-prefill program with the slot's OWN earlier chunks as the
  resident "hit", so the identity argument (and the int8 quantization-
  noise bound) is cache-on == cache-off verbatim.
- **Chunk-boundary edge cases** — drain/snapshot MID-PREFILL restores
  (and shed/absorbs) token-identically, into chunked AND unchunked
  targets; EOS arriving in the very first emitted chunk retires the
  whole reservation; a prefix-cache hit landing exactly on a chunk
  boundary resumes at the right rope offset; a step with zero fully-
  prefilled slots is a pure-prefill step (no decode dispatch, no decode
  flight record).
- **Pressure observability** — ``prefill_backlog_tokens`` rises while a
  long prompt chunks and drains to zero; ``prefill_chunks_total``
  counts dispatches; both ride ``replica_stats()`` / ``pool_metrics()``.
- **Bounded shapes** — zero-retrace steady state is test-pinned in
  tests/test_analysis.py (``batcher_steady_mixed_chunked``) and in the
  ``bench.py --leg chunked_prefill`` CI step.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_scheduler_tpu.models import LlamaConfig, init_params
from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher

PAGE = 8


@pytest.fixture(scope="module")
def setup():
    cfg = dataclasses.replace(LlamaConfig.tiny(), dtype=jnp.float32)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def mk(params, cfg, chunked=None, **kw):
    base = dict(n_slots=3, max_len=128, chunk=4, prefill_bucket=8,
                kv_layout="paged", page_size=PAGE,
                prefill_chunk_tokens=chunked)
    base.update(kw)
    return ContinuousBatcher(params, cfg, **base)


def drive(eng, prompts, max_new=6):
    ids = [eng.submit(p, max_new=max_new) for p in prompts]
    done = {}
    while eng.pending:
        done.update(eng.step())
    return [done[i] for i in ids]


def workload(cfg, seed=0):
    """Long + short + repetitive + shared-prefix prompts: every chunk
    rung, budget contention, and (for spec/prefix cells) accepts and
    cache hits."""
    rng = np.random.default_rng(seed)
    phrase = list(rng.integers(0, cfg.vocab, 3))
    sysp = list(rng.integers(0, cfg.vocab, 2 * PAGE))
    return [
        list(rng.integers(0, cfg.vocab, 40)),        # 5 chunks at budget 8
        list(rng.integers(0, cfg.vocab, 5)),         # single-chunk short
        phrase * 9,                                  # spec accepts
        sysp + list(rng.integers(0, cfg.vocab, 5)),  # prefix-cache class
        sysp + phrase * 4,                           # hit + repetition
        list(rng.integers(0, cfg.vocab, 22)),
    ]


class TestValidation:
    def test_knob_requires_paged_and_page_multiple(self, setup):
        cfg, params = setup
        with pytest.raises(ValueError, match="paged"):
            ContinuousBatcher(params, cfg, n_slots=2, max_len=64,
                              prefill_chunk_tokens=8)
        with pytest.raises(ValueError, match="multiple"):
            mk(params, cfg, chunked=PAGE - 1)
        with pytest.raises(ValueError, match="multiple"):
            mk(params, cfg, chunked=PAGE + 1)
        with pytest.raises(ValueError, match="multiple"):
            mk(params, cfg, chunked=0)


class TestTokenIdentity:
    """Chunked == unchunked streams, the tentpole contract. The
    fused-int8 prefix cell (the production shape) stays tier-1;
    redundant combinations ride slow."""

    CELLS = [
        # PR 15 budget: the dense-plain reference rides slow too —
        # chunking is host-side scheduling (attn-backend-orthogonal),
        # the kept fused-int8-prefix cell pins the identity contract
        # tier-1 and the chunked_prefill bench CI step re-asserts byte
        # identity on every push.
        pytest.param("dense", None, False, False,
                     marks=pytest.mark.slow),
        ("fused", "int8", True, False),
        # PR 13 rebalance: the fused-int8 SPEC cell rides slow too — the
        # kept fused-int8-prefix cell drives the same kernel
        # continuation rungs tier-1, spec×chunked identity rides the
        # unfiltered CI run.
        pytest.param("fused", "int8", False, True,
                     marks=pytest.mark.slow),
        pytest.param("dense", None, True, True, marks=pytest.mark.slow),
        pytest.param("fused", None, False, False, marks=pytest.mark.slow),
        pytest.param("dense", "int8", True, False, marks=pytest.mark.slow),
    ]

    @pytest.mark.parametrize("impl,kvd,prefix,spec", CELLS)
    def test_chunked_matches_unchunked(self, setup, impl, kvd, prefix, spec):
        cfg, params = setup
        cfg = dataclasses.replace(cfg, decode_attn=impl)
        prompts = workload(cfg)
        kw = dict(kv_dtype=kvd, prefix_cache=prefix, speculative=spec,
                  gamma=2)
        ref = drive(mk(params, cfg, chunked=None, **kw), prompts)
        got = drive(mk(params, cfg, chunked=PAGE, **kw), prompts)
        assert got == ref

    @pytest.mark.slow  # double-covered (PR 15 budget): the degenerate
    # whole-prompt budget is a strict subset of the identity cells above
    # (one chunk == the unchunked admission path), and the bench CI step
    # asserts chunked identity on every push.
    def test_budget_larger_than_any_prompt_still_identical(self, setup):
        """A budget that covers whole prompts degenerates to one chunk
        per admission — still byte-identical, still one dispatch."""
        cfg, params = setup
        prompts = workload(cfg)
        ref = drive(mk(params, cfg, chunked=None), prompts)
        got = drive(mk(params, cfg, chunked=64), prompts)
        assert got == ref


class TestChunkBoundaries:
    def test_eos_in_first_chunk(self, setup):
        """The request's FIRST token (emitted by its final prefill
        chunk) is eos: the whole worst-case reservation retires
        immediately — pages back, slot reusable, stream truncated at
        the eos."""
        cfg, params = setup
        prompts = workload(cfg)
        # Learn the first emitted token of the long prompt, then make
        # it the eos id.
        first = drive(mk(params, cfg, chunked=PAGE), [prompts[0]])[0][0]
        eng = mk(params, cfg, chunked=PAGE, eos_id=first)
        rid = eng.submit(prompts[0], max_new=32)
        done = {}
        while eng.pending:
            done.update(eng.step())
        assert done[rid] == [first]
        assert eng._alloc.in_use == 0
        eng._alloc.assert_consistent()
        # The slot admits the next request normally afterwards.
        rid2 = eng.submit(prompts[1], max_new=3)
        while eng.pending:
            done.update(eng.step())
        assert len(done[rid2]) >= 1

    def test_prefix_hit_on_chunk_boundary(self, setup):
        """A cached-prefix hit whose length is an exact multiple of the
        chunk budget: the first chunk resumes at rope offset hit_len
        (= k chunks' worth of rows it never prefilled), byte-identical
        to the unchunked tail prefill."""
        cfg, params = setup
        rng = np.random.default_rng(3)
        sysp = list(rng.integers(0, cfg.vocab, 2 * PAGE))  # hit == 2 chunks
        warm = sysp + list(rng.integers(0, cfg.vocab, 4))
        probe = sysp + list(rng.integers(0, cfg.vocab, 9))

        def run(chunked):
            eng = mk(params, cfg, chunked=chunked, prefix_cache=True)
            drive(eng, [warm], max_new=2)     # reap donates the prefix
            out = drive(eng, [probe], max_new=6)
            return out, eng

        ref, _ = run(None)
        got, eng = run(PAGE)
        assert got == ref
        # The hit really was mounted: the probe skipped 2 pages of
        # prefill, and its first chunk started AT the boundary.
        assert eng.pool_metrics()["prefill_tokens_skipped"] >= 2 * PAGE

    def test_pure_prefill_step(self, setup):
        """An idle engine receiving one long prompt: the first steps
        have ZERO fully-prefilled slots — no decode dispatch runs (the
        flight ring shows admit_only/prefill_chunk records, no decode
        record), backlog drains chunk by chunk, and decode begins only
        after the final chunk."""
        cfg, params = setup
        eng = mk(params, cfg, chunked=PAGE)
        rid = eng.submit(list(np.random.default_rng(4).integers(
            0, cfg.vocab, 40)), max_new=5)
        backlogs = []
        for _ in range(4):                   # 40 tokens / 8 = 5 chunks
            assert eng.step() == {}
            backlogs.append(eng.pool_metrics()["prefill_backlog_tokens"])
        kinds = {r["kind"] for r in eng._flight.records()}
        assert "decode" not in kinds
        assert backlogs == sorted(backlogs, reverse=True)
        assert backlogs[-1] > 0
        done = {}
        while eng.pending:
            done.update(eng.step())
        assert len(done[rid]) == 5
        assert eng.pool_metrics()["prefill_backlog_tokens"] == 0
        assert "decode" in {r["kind"] for r in eng._flight.records()}

    def test_budget_eq_page_is_oldest_first(self, setup):
        """At budget == page_size the quantum allocator degenerates to
        ONE quantum per step, drawn by the oldest pending slot — the
        no-starvation floor (larger budgets round-robin further quanta
        to younger slots, and may fund a small final tail the leftover
        covers even when an older slot's full quantum doesn't fit)."""
        cfg, params = setup
        eng = mk(params, cfg, chunked=PAGE)
        rng = np.random.default_rng(5)
        r_long = eng.submit(rng.integers(0, cfg.vocab, 40), max_new=3)
        r_short = eng.submit(rng.integers(0, cfg.vocab, 5), max_new=3)
        eng.step()
        pend = dict(eng._prefill_pending)
        # Budget 8 went entirely to the long head; the short waits at 0.
        assert max(pend.values()) == PAGE and min(pend.values()) == 0
        done = {}
        while eng.pending:
            done.update(eng.step())
        assert len(done[r_long]) == 3 and len(done[r_short]) == 3


class TestLifecycle:
    def test_drain_restore_mid_prefill(self, setup):
        """A partially-prefilled slot survives drain -> pytree codec ->
        restore and resumes token-identically — into a chunked target
        AND an unchunked one (the tail then prefills in one dispatch)."""
        from k8s_gpu_scheduler_tpu.models.snapshot import ServingSnapshot

        cfg, params = setup
        prompts = workload(cfg)[:3]
        ref = drive(mk(params, cfg, chunked=None), prompts)
        for target_chunked in (PAGE, None):
            src = mk(params, cfg, chunked=PAGE)
            ids = [src.submit(p, max_new=6) for p in prompts]
            done = dict(src.step())          # long prompt now mid-prefill
            assert any(d > 0 or len(src._slot_prompt[s]) > d
                       for s, d in src._prefill_pending.items())
            snap = ServingSnapshot.from_pytree(src.drain().to_pytree())
            tgt = mk(params, cfg, chunked=target_chunked)
            assert tgt.restore(snap) >= len(prompts) - len(done)
            while tgt.pending:
                done.update(tgt.step())
            assert [done[i] for i in ids] == ref
            tgt._alloc.assert_consistent()

    def test_shed_absorb_mid_prefill(self, setup):
        """Load shedding a MID-PREFILL slot: partial drain ships
        lens = prefill_done, absorb re-queues the unprefilled tail on
        the target, and the migrated stream stays byte-identical."""
        cfg, params = setup
        prompts = workload(cfg)[:3]
        ref = drive(mk(params, cfg, chunked=None), prompts)
        src, dst = mk(params, cfg, chunked=PAGE), mk(params, cfg,
                                                     chunked=PAGE)
        ids = [src.submit(p, max_new=6) for p in prompts]
        done = dict(src.step())
        shed = [s for s, d in src._prefill_pending.items()
                if len(src._slot_prompt[s]) - d > PAGE]
        assert shed, "a slot must still be mid-prefill"
        mapping = dst.absorb(src.drain(slots=shed))
        assert dst._prefill_pending, "absorb must re-queue the tail"
        while src.pending:
            done.update(src.step())
        moved = {}
        while dst.pending:
            moved.update(dst.step())
        out = [done[i] if i in done else moved[mapping[i]] for i in ids]
        assert out == ref
        src._alloc.assert_consistent()
        dst._alloc.assert_consistent()


class TestPressureMetrics:
    def test_backlog_and_chunk_gauges(self, setup):
        cfg, params = setup
        eng = mk(params, cfg, chunked=PAGE)
        assert eng.replica_stats()["prefill_backlog_tokens"] == 0
        rng = np.random.default_rng(6)
        eng.submit(rng.integers(0, cfg.vocab, 40), max_new=3)
        eng.step()
        st = eng.replica_stats()
        assert st["prefill_backlog_tokens"] == 40 - PAGE
        pm = eng.pool_metrics()
        assert pm["prefill_backlog_tokens"] == 40 - PAGE
        assert pm["prefill_chunks_total"] == 1.0
        while eng.pending:
            eng.step()
        pm = eng.pool_metrics()
        assert pm["prefill_backlog_tokens"] == 0
        assert pm["prefill_chunks_total"] == 5.0   # ceil(40/8) chunks

    def test_unchunked_engine_reports_zero(self, setup):
        """Chunking off: the gauges exist (the fleet schema is uniform)
        and stay 0/0 — admission dispatches whole prompts as before."""
        cfg, params = setup
        eng = mk(params, cfg, chunked=None)
        rng = np.random.default_rng(7)
        eng.submit(rng.integers(0, cfg.vocab, 20), max_new=2)
        eng.step()
        pm = eng.pool_metrics()
        assert pm["prefill_backlog_tokens"] == 0.0
        assert pm["prefill_chunks_total"] == 0.0

    def test_prefill_chunk_phase_spans(self, setup):
        """With a tracer attached, chunk dispatches record the
        ``prefill_chunk`` phase — engine lane folded into the phase
        batch (the Prometheus histogram feed), per-slot lanes for
        Perfetto — and the per-request timeline shows the chunk walk."""
        from k8s_gpu_scheduler_tpu.obs import Tracer

        cfg, params = setup
        tr = Tracer()
        eng = mk(params, cfg, chunked=PAGE, tracer=tr)
        rng = np.random.default_rng(8)
        rid = eng.submit(rng.integers(0, cfg.vocab, 40), max_new=3,
                         trace_id="chunky")
        while eng.pending:
            eng.step()
        names = {s.name for s in tr.spans()}
        assert "prefill_chunk" in names and "prefill" not in names
        tl = eng.request_timeline(rid)
        assert tl["phases"]["prefill_chunk"]["count"] == 5
        phases = dict(eng.pool_metrics().get("phase_durations") or ())
        assert "prefill_chunk" in phases
