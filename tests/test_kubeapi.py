"""KubeAPIServer adapter tests against a faithful fake kube-apiserver.

The fake speaks enough of the real REST surface (all-namespace LIST,
streaming WATCH with resourceVersion, POST create, merge-PATCH, the Binding
subresource, DELETE) that the ENTIRE scheduler stack — informers, cache,
TPU plugin, binding — runs unchanged over HTTP, which is the `--in-cluster`
deployment mode of cmd/scheduler.py.
"""
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from k8s_gpu_scheduler_tpu.cluster.kubeapi import KubeAPIServer
from k8s_gpu_scheduler_tpu.cluster.apiserver import NotFound


class FakeKube:
    """In-memory k8s REST server. Store: kind -> {ns/name: json-dict}."""

    def __init__(self):
        self.store = {"pods": {}, "nodes": {}, "configmaps": {}, "podgroups": {}}
        self.rv = 100
        self.mu = threading.Lock()
        self.watchers = []  # (plural, queue-like list, condition)
        self.binding_posts = []
        fake = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            # -- helpers --------------------------------------------------
            def _send(self, code, doc):
                body = json.dumps(doc).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _route(self):
                # /api/v1/<plural>, /api/v1/namespaces/<ns>/<plural>[/<name>[/binding]]
                parts = [p for p in self.path.split("?")[0].split("/") if p]
                if parts[:2] == ["apis", "scheduling.tpu.dev"]:
                    parts = parts[3:]  # strip apis/<group>/<version>
                else:
                    parts = parts[2:]  # strip api/v1
                ns = name = sub = None
                if parts and parts[0] == "namespaces":
                    ns, parts = parts[1], parts[2:]
                plural = parts[0]
                if len(parts) > 1:
                    name = parts[1]
                if len(parts) > 2:
                    sub = parts[2]
                return plural, ns, name, sub

            def _body(self):
                n = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(n)) if n else {}

            # -- verbs ----------------------------------------------------
            def do_GET(self):
                plural, ns, name, _ = self._route()
                if name:
                    with fake.mu:
                        obj = fake._get(plural, ns, name)
                    if obj is None:
                        return self._send(404, {"reason": "NotFound"})
                    return self._send(200, obj)
                if "watch=1" in self.path:
                    return self._watch(plural)
                with fake.mu:
                    items = [o for k, o in sorted(fake.store[plural].items())]
                    rv = str(fake.rv)
                return self._send(200, {
                    "kind": "List", "metadata": {"resourceVersion": rv},
                    "items": items,
                })

            def _watch(self, plural):
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                cond = threading.Condition()
                events = []
                with fake.mu:
                    fake.watchers.append((plural, events, cond))
                try:
                    while True:
                        with cond:
                            while not events:
                                if not cond.wait(timeout=10):
                                    return
                            ev = events.pop(0)
                        line = json.dumps(ev).encode() + b"\n"
                        self.wfile.write(f"{len(line):x}\r\n".encode()
                                         + line + b"\r\n")
                        self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError):
                    return

            def do_POST(self):
                plural, ns, name, sub = self._route()
                body = self._body()
                if sub == "binding":
                    node = body["target"]["name"]
                    with fake.mu:
                        obj = fake._get(plural, ns, name)
                        if obj is None:
                            return self._send(404, {})
                        obj["spec"]["nodeName"] = node
                        fake._bump(obj)
                        fake.binding_posts.append((ns, name, node))
                        fake._emit(plural, "MODIFIED", obj)
                    return self._send(201, {"kind": "Status", "status": "Success"})
                with fake.mu:
                    meta = body.setdefault("metadata", {})
                    meta.setdefault("namespace", ns or "default")
                    key = f"{meta['namespace']}/{meta['name']}"
                    if key in fake.store[plural]:
                        return self._send(409, {"reason": "AlreadyExists"})
                    meta.setdefault("uid", f"uid-{meta['name']}")
                    body.setdefault("spec", {})
                    body.setdefault("status", {"phase": "Pending"}
                                    if plural == "pods" else {})
                    fake._bump(body)
                    fake.store[plural][key] = body
                    fake._emit(plural, "ADDED", body)
                return self._send(201, body)

            def do_PATCH(self):
                plural, ns, name, _ = self._route()
                patch = self._body()
                with fake.mu:
                    obj = fake._get(plural, ns, name)
                    if obj is None:
                        return self._send(404, {})
                    fake._merge(obj, patch)
                    fake._bump(obj)
                    fake._emit(plural, "MODIFIED", obj)
                return self._send(200, obj)

            def do_DELETE(self):
                plural, ns, name, _ = self._route()
                with fake.mu:
                    obj = fake._get(plural, ns, name)
                    if obj is None:
                        return self._send(404, {})
                    key = f"{obj['metadata'].get('namespace', 'default')}/{name}"
                    if plural == "nodes":
                        key = f"default/{name}"
                    fake.store[plural].pop(key, None)
                    fake._emit(plural, "DELETED", obj)
                return self._send(200, {"kind": "Status", "status": "Success"})

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.thread = threading.Thread(target=self.server.serve_forever,
                                       daemon=True)
        self.thread.start()

    @property
    def url(self):
        return f"http://127.0.0.1:{self.server.server_port}"

    def _get(self, plural, ns, name):
        key = f"{ns or 'default'}/{name}"
        return self.store[plural].get(key)

    def _bump(self, obj):
        self.rv += 1
        obj.setdefault("metadata", {})["resourceVersion"] = str(self.rv)

    def _merge(self, base, patch):
        """RFC 7386 merge patch: dicts merge recursively, None deletes."""
        for k, v in patch.items():
            if v is None:
                base.pop(k, None)
            elif isinstance(v, dict) and isinstance(base.get(k), dict):
                self._merge(base[k], v)
            else:
                base[k] = v

    def _emit(self, plural, ev_type, obj):
        for wplural, events, cond in self.watchers:
            if wplural == plural:
                with cond:
                    events.append({"type": ev_type,
                                   "object": json.loads(json.dumps(obj))})
                    cond.notify_all()

    def add_node(self, name, chips=8, labels=None):
        lab = {"cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
               "cloud.google.com/gke-tpu-topology": "2x4"}
        lab.update(labels or {})
        with self.mu:
            obj = {
                "kind": "Node",
                "metadata": {"name": name, "labels": lab, "annotations": {},
                             "uid": f"uid-{name}"},
                "status": {
                    "capacity": {"google.com/tpu": str(chips)},
                    "allocatable": {"google.com/tpu": str(chips)},
                    "conditions": [{"type": "Ready", "status": "True"}],
                    "addresses": [{"type": "InternalIP",
                                   "address": "10.0.0.1"}],
                },
            }
            self._bump(obj)
            self.store["nodes"][f"default/{name}"] = obj
            self._emit("nodes", "ADDED", obj)

    def close(self):
        self.server.shutdown()
        self.server.server_close()


@pytest.fixture()
def fake():
    f = FakeKube()
    yield f
    f.close()


class TestAdapter:
    def test_create_get_list_roundtrip(self, fake):
        from tests.test_plugins import mk_pod

        api = KubeAPIServer(base_url=fake.url)
        api.create(mk_pod("p1", chips=2, slo=10.0, cm="cm-a"))
        pod = api.get("Pod", "p1", "default")
        assert pod.spec.tpu_chips() == 2
        assert pod.get_env("SLO") == "10.0"
        assert pod.spec.containers[0].env_from[0].name == "cm-a"
        assert [p.metadata.name for p in api.list("Pod")] == ["p1"]

    def test_node_mapping(self, fake):
        fake.add_node("n1", chips=4)
        api = KubeAPIServer(base_url=fake.url)
        node = api.get("Node", "n1")
        assert node.tpu_capacity() == 4
        assert node.tpu_topology() == "2x4"
        assert "Ready" in node.status.conditions

    def test_mutate_patches_configmap(self, fake):
        from k8s_gpu_scheduler_tpu.api.objects import ConfigMap, ObjectMeta

        api = KubeAPIServer(base_url=fake.url)
        api.create(ConfigMap(metadata=ObjectMeta(name="cm"), data={"a": "1"}))

        def fn(cm):
            cm.data["b"] = "2"

        api.mutate("ConfigMap", "cm", "default", fn)
        assert api.get("ConfigMap", "cm").data == {"a": "1", "b": "2"}

    def test_bind_uses_binding_subresource(self, fake):
        from tests.test_plugins import mk_pod

        fake.add_node("n1")
        api = KubeAPIServer(base_url=fake.url)
        api.create(mk_pod("p1"))

        def fn(p):
            p.spec.node_name = "n1"

        api.mutate("Pod", "p1", "default", fn)
        assert fake.binding_posts == [("default", "p1", "n1")]

    def test_missing_object_raises_notfound(self, fake):
        api = KubeAPIServer(base_url=fake.url)
        with pytest.raises(NotFound):
            api.get("Pod", "nope", "default")

    def test_watch_streams_events(self, fake):
        from tests.test_plugins import mk_pod

        api = KubeAPIServer(base_url=fake.url)
        w = api.watch("Pod", send_initial=True)
        api.create(mk_pod("p1"))
        ev = w.next(timeout=5)
        assert ev is not None and ev.type == "ADDED"
        assert ev.obj.metadata.name == "p1"
        w.stop()
        assert w.next(timeout=1) is None


class TestSchedulerOverREST:
    def test_full_cycle_binds_and_injects(self, fake):
        """The unchanged Scheduler + TPU plugin stack schedules through the
        REST adapter: watch-fed informers, Score, Binding subresource,
        PostBind ConfigMap injection."""
        from k8s_gpu_scheduler_tpu.api.objects import ConfigMap, ObjectMeta
        from k8s_gpu_scheduler_tpu.config import SchedulerConfig
        from k8s_gpu_scheduler_tpu.plugins import TPUPlugin
        from k8s_gpu_scheduler_tpu.sched import Profile, Scheduler
        from tests.test_plugins import FakeRegistry, mk_pod, wait_until

        fake.add_node("n1")
        fake.add_node("n2")
        api = KubeAPIServer(base_url=fake.url)
        reg = FakeRegistry()
        reg.publish("n1", utilization=0.8)
        reg.publish("n2", utilization=0.1)
        cfg = SchedulerConfig(backoff_initial_s=0.05, backoff_max_s=0.2)
        sched = Scheduler(api, profile=Profile(), config=cfg)
        tpu = TPUPlugin(sched.handle, registry=reg)
        sched.profile = Profile(pre_filter=[tpu], filter=[tpu], score=[tpu],
                                reserve=[tpu], post_bind=[tpu])
        api.create(ConfigMap(metadata=ObjectMeta(name="cm-p"), data={}))
        api.create(mk_pod("p1", chips=8, cm="cm-p"))
        sched.start()
        try:
            assert wait_until(
                lambda: api.get("Pod", "p1", "default").spec.node_name,
                timeout=10,
            )
            assert api.get("Pod", "p1", "default").spec.node_name == "n2"
            assert fake.binding_posts == [("default", "p1", "n2")]
            assert wait_until(
                lambda: "TPU_VISIBLE_CHIPS"
                in api.get("ConfigMap", "cm-p").data,
                timeout=5,
            )
        finally:
            sched.stop()
