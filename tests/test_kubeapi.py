"""KubeAPIServer adapter tests against a faithful fake kube-apiserver.

The fake speaks enough of the real REST surface (all-namespace LIST,
streaming WATCH with resourceVersion, POST create, merge-PATCH, the Binding
subresource, DELETE) that the ENTIRE scheduler stack — informers, cache,
TPU plugin, binding — runs unchanged over HTTP, which is the `--in-cluster`
deployment mode of cmd/scheduler.py.
"""
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from k8s_gpu_scheduler_tpu.cluster.kubeapi import KubeAPIServer
from k8s_gpu_scheduler_tpu.cluster.apiserver import NotFound


class FakeKube:
    """In-memory k8s REST server. Store: kind -> {ns/name: json-dict}."""

    def __init__(self):
        self.store = {"pods": {}, "nodes": {}, "configmaps": {},
                      "podgroups": {}, "leases": {}}
        self.rv = 100
        self.mu = threading.Lock()
        self.watchers = []  # (plural, queue-like list, condition)
        self.binding_posts = []
        self.gone_on_watch = False  # next watch connect gets a 410 ERROR
        self.watch_idle_s = 10.0    # idle timeout before closing a watch
        fake = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            # -- helpers --------------------------------------------------
            def _send(self, code, doc):
                body = json.dumps(doc).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _route(self):
                # /api/v1/<plural>, /api/v1/namespaces/<ns>/<plural>[/<name>[/binding]]
                parts = [p for p in self.path.split("?")[0].split("/") if p]
                if parts[0] == "apis":
                    parts = parts[3:]  # strip apis/<group>/<version>
                else:
                    parts = parts[2:]  # strip api/v1
                ns = name = sub = None
                if parts and parts[0] == "namespaces":
                    ns, parts = parts[1], parts[2:]
                plural = parts[0]
                if len(parts) > 1:
                    name = parts[1]
                if len(parts) > 2:
                    sub = parts[2]
                return plural, ns, name, sub

            def _body(self):
                n = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(n)) if n else {}

            # -- verbs ----------------------------------------------------
            def do_GET(self):
                plural, ns, name, _ = self._route()
                if name:
                    with fake.mu:
                        obj = fake._get(plural, ns, name)
                    if obj is None:
                        return self._send(404, {"reason": "NotFound"})
                    return self._send(200, obj)
                if "watch=1" in self.path:
                    return self._watch(plural)
                with fake.mu:
                    items = [o for k, o in sorted(fake.store[plural].items())]
                    rv = str(fake.rv)
                return self._send(200, {
                    "kind": "List", "metadata": {"resourceVersion": rv},
                    "items": items,
                })

            def _watch(self, plural):
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                # Real apiserver semantics: replay everything newer than the
                # requested resourceVersion on connect, registered under the
                # SAME lock — a create landing between the client's LIST and
                # this connect is replayed, not lost (the round-2 fake
                # ignored the param, making test_watch_streams_events racy).
                req_rv = 0
                for part in self.path.split("?", 1)[-1].split("&"):
                    if part.startswith("resourceVersion="):
                        v = part.split("=", 1)[1]
                        req_rv = int(v) if v.isdigit() else 0
                cond = threading.Condition()
                events = []
                with fake.mu:
                    if fake.gone_on_watch:
                        # Simulate etcd compaction: the rv is too old.
                        fake.gone_on_watch = False
                        body = json.dumps({
                            "type": "ERROR",
                            "object": {"kind": "Status", "code": 410,
                                       "reason": "Expired",
                                       "message": "too old resource version"},
                        }).encode() + b"\n"
                        self.wfile.write(f"{len(body):x}\r\n".encode()
                                         + body + b"\r\n")
                        self.wfile.write(b"0\r\n\r\n")
                        self.wfile.flush()
                        return
                    for obj in sorted(fake.store[plural].values(),
                                      key=lambda o: int(o["metadata"]
                                                        ["resourceVersion"])):
                        if int(obj["metadata"]["resourceVersion"]) > req_rv:
                            events.append({
                                "type": "ADDED",
                                "object": json.loads(json.dumps(obj)),
                            })
                    fake.watchers.append((plural, events, cond))
                try:
                    while True:
                        with cond:
                            while not events:
                                if not cond.wait(timeout=fake.watch_idle_s):
                                    return
                            ev = events.pop(0)
                        line = json.dumps(ev).encode() + b"\n"
                        self.wfile.write(f"{len(line):x}\r\n".encode()
                                         + line + b"\r\n")
                        self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError):
                    return

            def do_POST(self):
                plural, ns, name, sub = self._route()
                body = self._body()
                if sub == "binding":
                    node = body["target"]["name"]
                    with fake.mu:
                        obj = fake._get(plural, ns, name)
                        if obj is None:
                            return self._send(404, {})
                        obj["spec"]["nodeName"] = node
                        fake._bump(obj)
                        fake.binding_posts.append((ns, name, node))
                        fake._emit(plural, "MODIFIED", obj)
                    return self._send(201, {"kind": "Status", "status": "Success"})
                with fake.mu:
                    meta = body.setdefault("metadata", {})
                    meta.setdefault("namespace", ns or "default")
                    key = f"{meta['namespace']}/{meta['name']}"
                    if key in fake.store[plural]:
                        return self._send(409, {"reason": "AlreadyExists"})
                    meta.setdefault("uid", f"uid-{meta['name']}")
                    body.setdefault("spec", {})
                    body.setdefault("status", {"phase": "Pending"}
                                    if plural == "pods" else {})
                    fake._bump(body)
                    fake.store[plural][key] = body
                    fake._emit(plural, "ADDED", body)
                return self._send(201, body)

            def do_PATCH(self):
                plural, ns, name, _ = self._route()
                patch = self._body()
                with fake.mu:
                    obj = fake._get(plural, ns, name)
                    if obj is None:
                        return self._send(404, {})
                    fake._merge(obj, patch)
                    fake._bump(obj)
                    fake._emit(plural, "MODIFIED", obj)
                return self._send(200, obj)

            def do_PUT(self):
                plural, ns, name, _ = self._route()
                body = self._body()
                with fake.mu:
                    obj = fake._get(plural, ns, name)
                    if obj is None:
                        return self._send(404, {})
                    want = (body.get("metadata") or {}).get("resourceVersion")
                    have = obj["metadata"]["resourceVersion"]
                    if want is not None and str(want) != str(have):
                        return self._send(409, {
                            "reason": "Conflict",
                            "message": f"rv mismatch {want} != {have}"})
                    key = f"{obj['metadata'].get('namespace', 'default')}/{name}"
                    if plural == "nodes":
                        key = f"default/{name}"
                    body["metadata"]["namespace"] = obj["metadata"].get(
                        "namespace", "default")
                    fake._bump(body)
                    fake.store[plural][key] = body
                    fake._emit(plural, "MODIFIED", body)
                return self._send(200, body)

            def do_DELETE(self):
                plural, ns, name, _ = self._route()
                with fake.mu:
                    obj = fake._get(plural, ns, name)
                    if obj is None:
                        return self._send(404, {})
                    key = f"{obj['metadata'].get('namespace', 'default')}/{name}"
                    if plural == "nodes":
                        key = f"default/{name}"
                    fake.store[plural].pop(key, None)
                    fake._emit(plural, "DELETED", obj)
                return self._send(200, {"kind": "Status", "status": "Success"})

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.thread = threading.Thread(target=self.server.serve_forever,
                                       daemon=True)
        self.thread.start()

    @property
    def url(self):
        return f"http://127.0.0.1:{self.server.server_port}"

    def _get(self, plural, ns, name):
        key = f"{ns or 'default'}/{name}"
        return self.store[plural].get(key)

    def _bump(self, obj):
        self.rv += 1
        obj.setdefault("metadata", {})["resourceVersion"] = str(self.rv)

    def _merge(self, base, patch):
        """RFC 7386 merge patch: dicts merge recursively, None deletes."""
        for k, v in patch.items():
            if v is None:
                base.pop(k, None)
            elif isinstance(v, dict) and isinstance(base.get(k), dict):
                self._merge(base[k], v)
            else:
                base[k] = v

    def _emit(self, plural, ev_type, obj):
        for wplural, events, cond in self.watchers:
            if wplural == plural:
                with cond:
                    events.append({"type": ev_type,
                                   "object": json.loads(json.dumps(obj))})
                    cond.notify_all()

    def add_node(self, name, chips=8, labels=None):
        lab = {"cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
               "cloud.google.com/gke-tpu-topology": "2x4"}
        lab.update(labels or {})
        with self.mu:
            obj = {
                "kind": "Node",
                "metadata": {"name": name, "labels": lab, "annotations": {},
                             "uid": f"uid-{name}"},
                "status": {
                    "capacity": {"google.com/tpu": str(chips)},
                    "allocatable": {"google.com/tpu": str(chips)},
                    "conditions": [{"type": "Ready", "status": "True"}],
                    "addresses": [{"type": "InternalIP",
                                   "address": "10.0.0.1"}],
                },
            }
            self._bump(obj)
            self.store["nodes"][f"default/{name}"] = obj
            self._emit("nodes", "ADDED", obj)

    def close(self):
        self.server.shutdown()
        self.server.server_close()


@pytest.fixture()
def fake():
    f = FakeKube()
    yield f
    f.close()


class TestAdapter:
    def test_create_get_list_roundtrip(self, fake):
        from tests.test_plugins import mk_pod

        api = KubeAPIServer(base_url=fake.url)
        api.create(mk_pod("p1", chips=2, slo=10.0, cm="cm-a"))
        pod = api.get("Pod", "p1", "default")
        assert pod.spec.tpu_chips() == 2
        assert pod.get_env("SLO") == "10.0"
        assert pod.spec.containers[0].env_from[0].name == "cm-a"
        assert [p.metadata.name for p in api.list("Pod")] == ["p1"]

    def test_node_mapping(self, fake):
        fake.add_node("n1", chips=4)
        api = KubeAPIServer(base_url=fake.url)
        node = api.get("Node", "n1")
        assert node.tpu_capacity() == 4
        assert node.tpu_topology() == "2x4"
        assert "Ready" in node.status.conditions

    def test_mutate_patches_configmap(self, fake):
        from k8s_gpu_scheduler_tpu.api.objects import ConfigMap, ObjectMeta

        api = KubeAPIServer(base_url=fake.url)
        api.create(ConfigMap(metadata=ObjectMeta(name="cm"), data={"a": "1"}))

        def fn(cm):
            cm.data["b"] = "2"

        api.mutate("ConfigMap", "cm", "default", fn)
        assert api.get("ConfigMap", "cm").data == {"a": "1", "b": "2"}

    def test_bind_uses_binding_subresource(self, fake):
        from tests.test_plugins import mk_pod

        fake.add_node("n1")
        api = KubeAPIServer(base_url=fake.url)
        api.create(mk_pod("p1"))

        def fn(p):
            p.spec.node_name = "n1"

        api.mutate("Pod", "p1", "default", fn)
        assert fake.binding_posts == [("default", "p1", "n1")]

    def test_missing_object_raises_notfound(self, fake):
        api = KubeAPIServer(base_url=fake.url)
        with pytest.raises(NotFound):
            api.get("Pod", "nope", "default")

    def test_watch_streams_events(self, fake):
        from tests.test_plugins import mk_pod

        api = KubeAPIServer(base_url=fake.url)
        w = api.watch("Pod", send_initial=True)
        api.create(mk_pod("p1"))
        ev = w.next(timeout=5)
        assert ev is not None and ev.type == "ADDED"
        assert ev.obj.metadata.name == "p1"
        w.stop()
        assert w.next(timeout=1) is None

    def test_mutate_deleted_annotation_reaches_server(self, fake):
        """Merge-patch must null out keys the mutation fn removed —
        otherwise a real apiserver keeps them forever (the reshaper clears
        its state annotation exactly this way)."""
        from k8s_gpu_scheduler_tpu.api.objects import ConfigMap, ObjectMeta

        api = KubeAPIServer(base_url=fake.url)
        fake.add_node("n1")

        def set_ann(n):
            n.metadata.annotations["tpu.sched/slice.reshape-state"] = "applying"
            n.metadata.labels["x"] = "1"

        api.mutate("Node", "n1", "default", set_ann)
        assert api.get("Node", "n1").metadata.annotations[
            "tpu.sched/slice.reshape-state"] == "applying"

        def clear_ann(n):
            n.metadata.annotations.pop("tpu.sched/slice.reshape-state")
            n.metadata.labels.pop("x")

        api.mutate("Node", "n1", "default", clear_ann)
        node = api.get("Node", "n1")
        assert "tpu.sched/slice.reshape-state" not in node.metadata.annotations
        assert "x" not in node.metadata.labels

        api.create(ConfigMap(metadata=ObjectMeta(name="cm"),
                             data={"a": "1", "b": "2"}))
        api.mutate("ConfigMap", "cm", "default",
                   lambda cm: cm.data.pop("a"))
        assert api.get("ConfigMap", "cm").data == {"b": "2"}

    def test_notready_node_maps_to_no_conditions(self, fake):
        """A node with Ready=False must NOT default to Ready (round-2 bug:
        the filter never fired against real NotReady nodes)."""
        fake.add_node("n1")
        with fake.mu:
            obj = fake.store["nodes"]["default/n1"]
            obj["status"]["conditions"] = [
                {"type": "Ready", "status": "False"},
                {"type": "MemoryPressure", "status": "Unknown"},
            ]
            fake._bump(obj)
        api = KubeAPIServer(base_url=fake.url)
        node = api.get("Node", "n1")
        assert "Ready" not in node.status.conditions
        # No conditions at all (minimal fakes) still defaults to Ready.
        with fake.mu:
            obj["status"]["conditions"] = []
            fake._bump(obj)
        assert "Ready" in api.get("Node", "n1").status.conditions

    def test_watch_410_relists_and_emits_diff(self, fake):
        """Reflector semantics: on 410 Gone the watch re-lists and emits a
        synthetic diff — including DELETED for objects that vanished while
        the watch was blind."""
        from tests.test_plugins import mk_pod

        fake.watch_idle_s = 0.3
        api = KubeAPIServer(base_url=fake.url)
        api.create(mk_pod("p1"))
        api.create(mk_pod("p2"))
        w = api.watch("Pod", send_initial=True)
        seen = {}
        for _ in range(2):
            ev = w.next(timeout=5)
            seen[ev.obj.metadata.name] = ev.type
        assert seen == {"p1": "ADDED", "p2": "ADDED"}
        # p2 vanishes silently (no watch event), and the next reconnect is
        # answered with 410: only the re-list diff can reveal the delete.
        with fake.mu:
            fake.store["pods"].pop("default/p2")
            fake.gone_on_watch = True
        events = []
        deadline = time.time() + 10
        while time.time() < deadline:
            ev = w.next(timeout=1)
            if ev is None:
                continue
            events.append((ev.type, ev.obj.metadata.name))
            if ("DELETED", "p2") in events and ("ADDED", "p1") in events:
                break
        w.stop()
        assert ("DELETED", "p2") in events
        assert ("ADDED", "p1") in events  # re-list re-asserts live objects


class TestSchedulerOverREST:
    def test_full_cycle_binds_and_injects(self, fake):
        """The unchanged Scheduler + TPU plugin stack schedules through the
        REST adapter: watch-fed informers, Score, Binding subresource,
        PostBind ConfigMap injection."""
        from k8s_gpu_scheduler_tpu.api.objects import ConfigMap, ObjectMeta
        from k8s_gpu_scheduler_tpu.config import SchedulerConfig
        from k8s_gpu_scheduler_tpu.plugins import TPUPlugin
        from k8s_gpu_scheduler_tpu.sched import Profile, Scheduler
        from tests.test_plugins import FakeRegistry, mk_pod, wait_until

        fake.add_node("n1")
        fake.add_node("n2")
        api = KubeAPIServer(base_url=fake.url)
        reg = FakeRegistry()
        reg.publish("n1", utilization=0.8)
        reg.publish("n2", utilization=0.1)
        cfg = SchedulerConfig(backoff_initial_s=0.05, backoff_max_s=0.2)
        sched = Scheduler(api, profile=Profile(), config=cfg)
        tpu = TPUPlugin(sched.handle, registry=reg)
        sched.profile = Profile(pre_filter=[tpu], filter=[tpu], score=[tpu],
                                reserve=[tpu], post_bind=[tpu])
        api.create(ConfigMap(metadata=ObjectMeta(name="cm-p"), data={}))
        api.create(mk_pod("p1", chips=8, cm="cm-p"))
        sched.start()
        try:
            assert wait_until(
                lambda: api.get("Pod", "p1", "default").spec.node_name,
                timeout=10,
            )
            assert api.get("Pod", "p1", "default").spec.node_name == "n2"
            assert fake.binding_posts == [("default", "p1", "n2")]
            assert wait_until(
                lambda: "TPU_VISIBLE_CHIPS"
                in api.get("ConfigMap", "cm-p").data,
                timeout=5,
            )
        finally:
            sched.stop()
