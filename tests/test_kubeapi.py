"""KubeAPIServer adapter tests against a faithful fake kube-apiserver.

The fake speaks enough of the real REST surface (all-namespace LIST,
streaming WATCH with resourceVersion, POST create, merge-PATCH, the Binding
subresource, DELETE) that the ENTIRE scheduler stack — informers, cache,
TPU plugin, binding — runs unchanged over HTTP, which is the `--in-cluster`
deployment mode of cmd/scheduler.py.
"""
import time

import pytest

from tests.fakekube import FakeKube

from k8s_gpu_scheduler_tpu.cluster.kubeapi import KubeAPIServer
from k8s_gpu_scheduler_tpu.cluster.apiserver import NotFound


@pytest.fixture()
def fake():
    f = FakeKube()
    yield f
    f.close()


class TestAdapter:
    def test_create_get_list_roundtrip(self, fake):
        from tests.test_plugins import mk_pod

        api = KubeAPIServer(base_url=fake.url)
        api.create(mk_pod("p1", chips=2, slo=10.0, cm="cm-a"))
        pod = api.get("Pod", "p1", "default")
        assert pod.spec.tpu_chips() == 2
        assert pod.get_env("SLO") == "10.0"
        assert pod.spec.containers[0].env_from[0].name == "cm-a"
        assert [p.metadata.name for p in api.list("Pod")] == ["p1"]

    def test_owner_references_roundtrip(self, fake):
        """ownerReferences must survive create→get: preemption victim
        eligibility and the gang bare-pod guard both key on a pod having a
        controller — a drop here silently disables preemption for every
        pod created through this adapter (found by bench_mixed)."""
        from tests.test_plugins import mk_pod

        api = KubeAPIServer(base_url=fake.url)
        api.create(mk_pod("owned", chips=1, owner="StatefulSet/web"))
        pod = api.get("Pod", "owned", "default")
        assert pod.metadata.owner_references == ["StatefulSet/web"]

    def test_node_mapping(self, fake):
        fake.add_node("n1", chips=4)
        api = KubeAPIServer(base_url=fake.url)
        node = api.get("Node", "n1")
        assert node.tpu_capacity() == 4
        assert node.tpu_topology() == "2x4"
        assert "Ready" in node.status.conditions

    def test_mutate_patches_configmap(self, fake):
        from k8s_gpu_scheduler_tpu.api.objects import ConfigMap, ObjectMeta

        api = KubeAPIServer(base_url=fake.url)
        api.create(ConfigMap(metadata=ObjectMeta(name="cm"), data={"a": "1"}))

        def fn(cm):
            cm.data["b"] = "2"

        api.mutate("ConfigMap", "cm", "default", fn)
        assert api.get("ConfigMap", "cm").data == {"a": "1", "b": "2"}

    def test_bind_uses_binding_subresource(self, fake):
        from tests.test_plugins import mk_pod

        fake.add_node("n1")
        api = KubeAPIServer(base_url=fake.url)
        api.create(mk_pod("p1"))

        def fn(p):
            p.spec.node_name = "n1"

        api.mutate("Pod", "p1", "default", fn)
        assert fake.binding_posts == [("default", "p1", "n1")]

    def test_missing_object_raises_notfound(self, fake):
        api = KubeAPIServer(base_url=fake.url)
        with pytest.raises(NotFound):
            api.get("Pod", "nope", "default")

    def test_watch_streams_events(self, fake):
        from tests.test_plugins import mk_pod

        api = KubeAPIServer(base_url=fake.url)
        w = api.watch("Pod", send_initial=True)
        api.create(mk_pod("p1"))
        ev = w.next(timeout=5)
        assert ev is not None and ev.type == "ADDED"
        assert ev.obj.metadata.name == "p1"
        w.stop()
        assert w.next(timeout=1) is None

    def test_mutate_deleted_annotation_reaches_server(self, fake):
        """Merge-patch must null out keys the mutation fn removed —
        otherwise a real apiserver keeps them forever (the reshaper clears
        its state annotation exactly this way)."""
        from k8s_gpu_scheduler_tpu.api.objects import ConfigMap, ObjectMeta

        api = KubeAPIServer(base_url=fake.url)
        fake.add_node("n1")

        def set_ann(n):
            n.metadata.annotations["tpu.sched/slice.reshape-state"] = "applying"
            n.metadata.labels["x"] = "1"

        api.mutate("Node", "n1", "default", set_ann)
        assert api.get("Node", "n1").metadata.annotations[
            "tpu.sched/slice.reshape-state"] == "applying"

        def clear_ann(n):
            n.metadata.annotations.pop("tpu.sched/slice.reshape-state")
            n.metadata.labels.pop("x")

        api.mutate("Node", "n1", "default", clear_ann)
        node = api.get("Node", "n1")
        assert "tpu.sched/slice.reshape-state" not in node.metadata.annotations
        assert "x" not in node.metadata.labels

        api.create(ConfigMap(metadata=ObjectMeta(name="cm"),
                             data={"a": "1", "b": "2"}))
        api.mutate("ConfigMap", "cm", "default",
                   lambda cm: cm.data.pop("a"))
        assert api.get("ConfigMap", "cm").data == {"b": "2"}

    def test_notready_node_maps_to_no_conditions(self, fake):
        """A node with Ready=False must NOT default to Ready (round-2 bug:
        the filter never fired against real NotReady nodes)."""
        fake.add_node("n1")
        with fake.mu:
            obj = fake.store["nodes"]["default/n1"]
            obj["status"]["conditions"] = [
                {"type": "Ready", "status": "False"},
                {"type": "MemoryPressure", "status": "Unknown"},
            ]
            fake._bump(obj)
        api = KubeAPIServer(base_url=fake.url)
        node = api.get("Node", "n1")
        assert "Ready" not in node.status.conditions
        # No conditions at all (minimal fakes) still defaults to Ready.
        with fake.mu:
            obj["status"]["conditions"] = []
            fake._bump(obj)
        assert "Ready" in api.get("Node", "n1").status.conditions

    def test_watch_410_relists_and_emits_diff(self, fake):
        """Reflector semantics: on 410 Gone the watch re-lists and emits a
        synthetic diff — including DELETED for objects that vanished while
        the watch was blind."""
        from tests.test_plugins import mk_pod

        fake.watch_idle_s = 0.3
        api = KubeAPIServer(base_url=fake.url)
        api.create(mk_pod("p1"))
        api.create(mk_pod("p2"))
        w = api.watch("Pod", send_initial=True)
        seen = {}
        for _ in range(2):
            ev = w.next(timeout=5)
            seen[ev.obj.metadata.name] = ev.type
        assert seen == {"p1": "ADDED", "p2": "ADDED"}
        # p2 vanishes silently (no watch event), and the next reconnect is
        # answered with 410: only the re-list diff can reveal the delete.
        with fake.mu:
            fake.store["pods"].pop("default/p2")
            fake.gone_on_watch = True
        events = []
        deadline = time.time() + 10
        while time.time() < deadline:
            ev = w.next(timeout=1)
            if ev is None:
                continue
            events.append((ev.type, ev.obj.metadata.name))
            if ("DELETED", "p2") in events and ("ADDED", "p1") in events:
                break
        w.stop()
        assert ("DELETED", "p2") in events
        assert ("ADDED", "p1") in events  # re-list re-asserts live objects


class TestSchedulerOverREST:
    def test_full_cycle_binds_and_injects(self, fake):
        """The unchanged Scheduler + TPU plugin stack schedules through the
        REST adapter: watch-fed informers, Score, Binding subresource,
        PostBind ConfigMap injection."""
        from k8s_gpu_scheduler_tpu.api.objects import ConfigMap, ObjectMeta
        from k8s_gpu_scheduler_tpu.config import SchedulerConfig
        from k8s_gpu_scheduler_tpu.plugins import TPUPlugin
        from k8s_gpu_scheduler_tpu.sched import Profile, Scheduler
        from tests.test_plugins import FakeRegistry, mk_pod, wait_until

        fake.add_node("n1")
        fake.add_node("n2")
        api = KubeAPIServer(base_url=fake.url)
        reg = FakeRegistry()
        reg.publish("n1", utilization=0.8)
        reg.publish("n2", utilization=0.1)
        cfg = SchedulerConfig(backoff_initial_s=0.05, backoff_max_s=0.2)
        sched = Scheduler(api, profile=Profile(), config=cfg)
        tpu = TPUPlugin(sched.handle, registry=reg)
        sched.profile = Profile(pre_filter=[tpu], filter=[tpu], score=[tpu],
                                reserve=[tpu], post_bind=[tpu])
        api.create(ConfigMap(metadata=ObjectMeta(name="cm-p"), data={}))
        api.create(mk_pod("p1", chips=8, cm="cm-p"))
        sched.start()
        try:
            assert wait_until(
                lambda: api.get("Pod", "p1", "default").spec.node_name,
                timeout=10,
            )
            assert api.get("Pod", "p1", "default").spec.node_name == "n2"
            assert fake.binding_posts == [("default", "p1", "n2")]
            assert wait_until(
                lambda: "TPU_VISIBLE_CHIPS"
                in api.get("ConfigMap", "cm-p").data,
                timeout=5,
            )
        finally:
            sched.stop()
