"""Prefix-attention prefill kernel + decoded-suffix caching (multi-turn).

Two halves of one feature, tested together because the safety proof is
shared (refcount/alias invariants over the paged pool):

- ``ops.paged_prefill_attention`` — the Pallas kernel that replaces the
  hb>0 tail-prefill's dense prefix gather (``pool[:, prefix_tables]`` →
  [L, M, hb·ps, Hkv, hd]) with blockwise streaming through the block-
  table indirection: parity vs the gather reference across GQA × dtypes
  × int8-KV × ragged hit_lens × split-K × hb rungs (incl. the hb=0
  degenerate), engine token identity kernel-vs-gather, the jaxpr proof
  that the materialization is GONE, and the counted fallback.
- decoded-suffix donation — ``_retire_pages`` donates prompt AND
  decoded full pages, so turn N+1 of a conversation mounts turn N's
  whole transcript: multi-turn reuse, donation-on-vs-off identity,
  eviction of a decoded leaf mid-conversation, drain/restore/absorb
  with decoded pages in the tree, and the mid-prefill donation cap.
"""
import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from k8s_gpu_scheduler_tpu.models import serving
from k8s_gpu_scheduler_tpu.models.llama import LlamaConfig, init_params
from k8s_gpu_scheduler_tpu.models.serving import ContinuousBatcher, _kv_quant
from k8s_gpu_scheduler_tpu.ops.decode_attention import (
    PREFILL_MAX_Q_ROWS, dense_prefill_reference, paged_prefill_attention,
    prefill_plan,
)


@pytest.fixture(scope="module")
def tiny():
    cfg = dataclasses.replace(LlamaConfig.tiny(), decode_attn="fused")
    return cfg, init_params(cfg, jax.random.PRNGKey(0))


def build(cfg, params, **kw):
    kw.setdefault("n_slots", 2)
    kw.setdefault("max_len", 64)
    kw.setdefault("chunk", 2)
    kw.setdefault("prefill_bucket", 8)
    kw.setdefault("page_size", 8)
    kw.setdefault("kv_layout", "paged")
    kw.setdefault("prefix_cache", True)
    return ContinuousBatcher(params, cfg, **kw)


def step_all(eng):
    done = {}
    while eng.pending:
        done.update(eng.step())
    return done


def two_turns(eng, rng, p1_len=16, max_new=12, suffix=4, turn2_new=4):
    """Drive one 2-turn conversation; returns (turn1, turn2) streams."""
    p1 = list(rng.integers(0, eng.cfg.vocab, p1_len))
    eng.submit(p1, max_new=max_new)
    (_, t1), = step_all(eng).items()
    eng.submit(p1 + t1 + list(rng.integers(0, eng.cfg.vocab, suffix)),
               max_new=turn2_new)
    (_, t2), = step_all(eng).items()
    return t1, t2


# -- kernel parity vs the gather reference ------------------------------------

def _mk_case(rng, m, tb, n_heads, n_kv, hd, ps, n_pages, hb, dtype):
    q = jnp.asarray(rng.normal(size=(m, tb, n_heads, hd)), dtype)
    kp = jnp.asarray(rng.normal(size=(n_pages, ps, n_kv, hd)), dtype)
    vp = jnp.asarray(rng.normal(size=(n_pages, ps, n_kv, hd)), dtype)
    tk = jnp.asarray(rng.normal(size=(m, tb, n_kv, hd)), dtype)
    tv = jnp.asarray(rng.normal(size=(m, tb, n_kv, hd)), dtype)
    table = jnp.asarray(
        rng.integers(1, n_pages, size=(m, hb)), jnp.int32)
    # Ragged page-aligned hit lengths: full cover, partial, and zero.
    choices = [hb * ps, (hb // 2) * ps, 0]
    hits = jnp.asarray([choices[i % 3] for i in range(m)], jnp.int32)
    return q, kp, vp, table, hits, tk, tv


class TestKernelParity:
    @pytest.mark.parametrize("n_heads,n_kv", [(8, 8), (16, 4)],
                             ids=["mha", "gqa4"])
    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16],
                             ids=["f32", "bf16"])
    @pytest.mark.parametrize("quant", [False, True],
                             ids=["exact", "int8"])
    def test_matches_gather_reference(self, n_heads, n_kv, dtype, quant):
        """Kernel == gather reference over GQA × dtype × int8-KV with
        ragged (page-aligned) hit lengths, hb=4 prefix window."""
        rng = np.random.default_rng(0)
        q, kp, vp, table, hits, tk, tv = _mk_case(
            rng, m=3, tb=16, n_heads=n_heads, n_kv=n_kv, hd=16, ps=8,
            n_pages=12, hb=4, dtype=dtype)
        sc = {}
        if quant:
            kq, ks = _kv_quant(kp)
            vq, vs = _kv_quant(vp)
            kp, vp = kq, vq
            sc = dict(k_scale=ks, v_scale=vs)
        ref = dense_prefill_reference(q, kp, vp, table, hits, tk, tv, **sc)
        out = paged_prefill_attention(q, kp, vp, table, hits, tk, tv, **sc)
        tol = 2e-5 if dtype == jnp.float32 else 2e-2
        np.testing.assert_allclose(
            np.asarray(ref, np.float32), np.asarray(out, np.float32),
            atol=tol, rtol=tol)

    def test_hb0_degenerate_pure_causal(self):
        """hb=0 (nothing cached): the kernel degenerates to the causal
        self-attention window — one masked null prefix block, same
        program shape."""
        rng = np.random.default_rng(1)
        q, kp, vp, _, _, tk, tv = _mk_case(
            rng, 2, 16, 8, 8, 16, 8, 10, 2, jnp.float32)
        empty = jnp.zeros((2, 0), jnp.int32)
        ref = dense_prefill_reference(q, kp, vp, empty, 0, tk, tv)
        out = paged_prefill_attention(q, kp, vp, empty, 0, tk, tv)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   atol=2e-5, rtol=2e-5)

    def test_split_k_engages_and_matches(self):
        """hb + ntb >= 8 logical blocks → the split axis engages; the
        LSE combine must still match the reference exactly."""
        rng = np.random.default_rng(2)
        q, kp, vp, table, hits, tk, tv = _mk_case(
            rng, 2, 16, 8, 8, 16, 8, 16, 6, jnp.float32)
        assert prefill_plan(6 + 2, 8, 16 * 1) in (2, 4, 8)
        ref = dense_prefill_reference(q, kp, vp, table, hits, tk, tv)
        out = paged_prefill_attention(q, kp, vp, table, hits, tk, tv)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   atol=2e-5, rtol=2e-5)

    def test_plan_gates(self):
        """The q-row cap and page-divisibility gates: over-cap rows and
        non-page tb have no plan / raise — the engine's counted-fallback
        conditions."""
        assert prefill_plan(4, 8, PREFILL_MAX_Q_ROWS) is not None
        assert prefill_plan(4, 8, PREFILL_MAX_Q_ROWS + 1) is None
        assert prefill_plan(4, 8, 0) is None
        assert prefill_plan(4, 12, 64) is None     # non-power-of-two page
        rng = np.random.default_rng(3)
        q, kp, vp, table, hits, tk, tv = _mk_case(
            rng, 2, 16, 8, 8, 16, 8, 10, 2, jnp.float32)
        with pytest.raises(ValueError, match="multiple of the page"):
            paged_prefill_attention(q[:, :12], kp, vp, table, hits,
                                    tk[:, :12], tv[:, :12])

    def test_null_padded_prefix_table_rows_ignored(self):
        """Table entries past ceil(hit/ps) may be null/garbage — the
        clamped index maps and the hit mask must make them unreachable
        (the engine null-pads every hb bucket)."""
        rng = np.random.default_rng(4)
        q, kp, vp, table, _, tk, tv = _mk_case(
            rng, 2, 8, 8, 8, 16, 8, 10, 4, jnp.float32)
        hits = jnp.asarray([16, 8], jnp.int32)     # 2 / 1 real pages
        junk = np.array(table)
        junk[0, 2:] = 0                            # null past the hit
        junk[1, 1:] = 9                            # garbage past the hit
        out_clean = paged_prefill_attention(q, kp, vp, table, hits, tk, tv)
        out_junk = paged_prefill_attention(
            q, kp, vp, jnp.asarray(junk), hits, tk, tv)
        np.testing.assert_array_equal(np.asarray(out_clean),
                                      np.asarray(out_junk))


# -- engine: kernel vs gather -------------------------------------------------

# Tier-1 wall-clock rebalance (the PR 5/8 pattern; PR 15's budget pass
# moved the last cell over too): cells double-covered elsewhere ride
# pytest.mark.slow — the unfiltered CI pytest run still executes every
# cell, and the multiturn bench CI step re-asserts kernel==gather
# engine identity (plus zero fallbacks) on every push, which keeps the
# e2e contract CI-enforced while the TestKernelParity unit grid stays
# tier-1. Slow: int8 (the bench's production engines), f32 (the
# donation suite's engines are f32-adjacent tiny already), speculative
# (test_spec_mode_multiturn_donation pins spec×kernel identity),
# chunked (test_chunked_prefill's fused engines dispatch the kernel's
# continuation rungs tier-1).
ENGINE_GRID = [
    pytest.param(dict(kv_dtype="int8"), id="int8",
                 marks=pytest.mark.slow),
    pytest.param(dict(), id="f32", marks=pytest.mark.slow),
    pytest.param(dict(kv_dtype="int8", speculative=True, gamma=2),
                 id="int8-spec", marks=pytest.mark.slow),
    pytest.param(dict(kv_dtype="int8", prefill_chunk_tokens=8),
                 id="int8-chunked", marks=pytest.mark.slow),
]


class TestEngineKernelVsGather:
    @pytest.mark.parametrize("kw", ENGINE_GRID)
    def test_token_identity(self, tiny, kw):
        """prefill_attn='kernel' == 'gather' token streams over 2-turn
        conversations (the hb>0 rungs mount real transcripts) across
        int8-KV × speculative × chunked prefill."""
        cfg, params = tiny
        streams = []
        for impl in ("kernel", "gather"):
            eng = build(cfg, params, prefill_attn=impl, **kw)
            rng = np.random.default_rng(7)
            streams.append(two_turns(eng, rng))
            eng._alloc.assert_consistent()
        assert streams[0] == streams[1]

    # slow: the jaxpr test pins auto's kernel/gather routing tier-1 and
    # the parity grid pins the numerics; this cross-config stream check
    # rides the unfiltered CI run.
    @pytest.mark.slow
    def test_dense_config_auto_keeps_gather(self, tiny):
        """decode_attn='dense' + auto → the gather path; streams match
        the fused kernel engine (the dense-vs-fused noise class is
        absorbed by greedy argmax on this workload)."""
        cfg, params = tiny
        dense_cfg = dataclasses.replace(cfg, decode_attn="dense")
        rng = np.random.default_rng(9)
        a = two_turns(build(dense_cfg, params), rng)
        rng = np.random.default_rng(9)
        b = two_turns(build(cfg, params, prefill_attn="kernel"), rng)
        assert a == b

    # slow: the plan gate itself is tier-1 (test_plan_gates); the full
    # engine downgrade drive rides the unfiltered CI run and the
    # multiturn bench CI step pins fallbacks == 0 on the real rungs.
    @pytest.mark.slow
    def test_over_cap_rung_falls_back_counted(self, tiny, monkeypatch):
        """A rung past PREFILL_MAX_Q_ROWS downgrades to the gather —
        streams unchanged, tpu_serve_decode_fallback_total{reason=
        "no_prefill_plan"} incremented (never silent)."""
        from k8s_gpu_scheduler_tpu.ops import decode_attention as da

        cfg, params = tiny
        serving.reset_decode_fallback_counts()
        monkeypatch.setattr(da, "PREFILL_MAX_Q_ROWS", 4)
        with pytest.warns(RuntimeWarning, match="no_prefill_plan"):
            eng = build(cfg, params, prefill_attn="kernel")
            rng = np.random.default_rng(11)
            got = two_turns(eng, rng)
        assert serving.decode_fallback_counts().get(
            "no_prefill_plan", 0) >= 1
        serving.reset_decode_fallback_counts()
        monkeypatch.undo()
        rng = np.random.default_rng(11)
        ref = two_turns(build(cfg, params, prefill_attn="gather"), rng)
        assert got == ref

    def test_prefill_attn_validation(self, tiny):
        cfg, params = tiny
        with pytest.raises(ValueError, match="prefill_attn"):
            build(cfg, params, prefill_attn="fused")
        with pytest.raises(ValueError, match="kv_layout='paged'"):
            ContinuousBatcher(params, cfg, n_slots=2, max_len=32,
                              prefill_attn="kernel")

    def test_jaxpr_has_no_prefix_materialization(self, tiny):
        """The acceptance criterion, asserted on the jaxpr: the kernel
        rung contains NO [L, M, hb·ps, Hkv, hd] prefix buffer (nor the
        rank-6 gather it reshapes from), while the gather rung provably
        does — the check has teeth."""
        cfg, params = tiny

        def avals(fn, args):
            out = []

            def walk(jaxpr):
                for eqn in jaxpr.eqns:
                    for v in eqn.outvars:
                        out.append(tuple(getattr(v.aval, "shape", ())))
                    for val in eqn.params.values():
                        for sub in jax.tree_util.tree_leaves(
                                val, is_leaf=lambda x: hasattr(x, "eqns")):
                            if hasattr(sub, "eqns"):
                                walk(sub)
                            elif hasattr(sub, "jaxpr"):
                                walk(sub.jaxpr)
            walk(jax.make_jaxpr(fn)(*args).jaxpr)
            return out

        def prefill_args(eng, hb):
            # hb=2 prefix pages (hp=16) over a tb=8 tail: the banned
            # gather shapes then collide with nothing the kernel path
            # legitimately builds (the tb-row mini K/V is [L, M, 8, ...],
            # the gather [L, M, 16, ...]).
            return (params, eng._k, eng._v, eng._ks, eng._vs, eng._lens,
                    eng._last, np.zeros((2,), np.int32),
                    np.ones((2, 1), np.int32),
                    np.full((2, hb), 2, np.int32),
                    np.full((2,), hb * 8, np.int32),
                    np.zeros((2, 8), np.int32),
                    np.full((2,), 4, np.int32), np.int32(1))

        L, Hkv, hd = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
        banned = {(L, 2, 16, Hkv, hd), (L, 2, 16, Hkv, 1),
                  (L, 2, 2, 8, Hkv, hd), (L, 2, 2, 8, Hkv, 1)}
        for impl, expect in (("kernel", False), ("gather", True)):
            eng = build(cfg, params, kv_dtype="int8", prefill_attn=impl)
            shapes = set(avals(eng._prefill, prefill_args(eng, 2)))
            assert bool(shapes & banned) == expect, (impl, shapes & banned)


# -- decoded-suffix donation --------------------------------------------------

class TestDecodedDonation:
    def test_multiturn_mounts_whole_transcript(self, tiny):
        """Turn 2 mounts turn 1's prompt + decoded full pages: prefill
        tokens skipped >= the whole turn-1 transcript's full pages,
        strictly more than prompt-only donation could give."""
        cfg, params = tiny
        eng = build(cfg, params, kv_dtype="int8")
        rng = np.random.default_rng(0)
        p1 = list(rng.integers(0, cfg.vocab, 16))
        eng.submit(p1, max_new=12)
        (_, t1), = step_all(eng).items()
        m1 = eng.pool_metrics()
        assert m1["decoded_pages_donated_total"] >= 1
        eng.submit(p1 + t1 + list(rng.integers(0, cfg.vocab, 4)),
                   max_new=4)
        step_all(eng)
        m2 = eng.pool_metrics()
        skipped = m2["prefill_tokens_skipped"] - m1["prefill_tokens_skipped"]
        conv = len(p1) + len(t1) - 1           # the final token has no KV
        assert skipped >= (conv // 8) * 8 > (len(p1) // 8) * 8
        eng._alloc.assert_consistent()

    # slow: the multiturn bench CI step asserts the same donation A/B
    # (identity + skipped-tokens win on one trace) on every push; the
    # unfiltered CI pytest run keeps this cell too.
    @pytest.mark.slow
    def test_donation_off_is_prompt_only_and_identical(self, tiny):
        """donate_decoded=False: same streams on the same trace, zero
        decoded pages donated, strictly fewer prefill tokens skipped —
        the PR 4 baseline, kept addressable for the bench A/B."""
        cfg, params = tiny
        res = {}
        for donate in (True, False):
            eng = build(cfg, params, kv_dtype="int8",
                        donate_decoded=donate)
            rng = np.random.default_rng(1)
            res[donate] = (two_turns(eng, rng), eng.pool_metrics())
            eng._alloc.assert_consistent()
        assert res[True][0] == res[False][0]
        assert res[False][1]["decoded_pages_donated_total"] == 0
        assert res[True][1]["decoded_pages_donated_total"] >= 1
        assert res[True][1]["prefill_tokens_skipped"] \
            > res[False][1]["prefill_tokens_skipped"]

    # slow: budget-reap donation is tier-1 via
    # test_multiturn_mounts_whole_transcript; the eos-cap edge rides the
    # unfiltered CI run.
    @pytest.mark.slow
    def test_eos_reap_donates_transcript_through_eos(self, tiny):
        """An eos-terminated turn (the realistic conversation end)
        donates the transcript through the eos token: the reap runs
        post-flush, so nothing is lost to the deferred-readback window."""
        cfg, params = tiny
        rng = np.random.default_rng(2)
        p1 = list(rng.integers(0, cfg.vocab, 16))
        probe = build(cfg, params, kv_dtype="int8")
        probe.submit(p1, max_new=12)
        (_, ref), = step_all(probe).items()
        eos = ref[6]                           # eos mid-stream, mid-chunk
        eng = build(cfg, params, kv_dtype="int8", eos_id=int(eos))
        eng.submit(p1, max_new=12)
        (_, t1), = step_all(eng).items()
        assert t1 == ref[:7]                   # truncated AT the eos
        m1 = eng.pool_metrics()
        # Follow-up turn continues from the eos-terminated transcript.
        eng.submit(p1 + t1 + list(rng.integers(0, cfg.vocab, 6)),
                   max_new=3)
        step_all(eng)
        m2 = eng.pool_metrics()
        skipped = m2["prefill_tokens_skipped"] - m1["prefill_tokens_skipped"]
        assert skipped >= ((len(p1) + len(t1)) // 8) * 8
        eng._alloc.assert_consistent()

    # slow: spec-engine donation shares the reap path this class pins
    # tier-1; the spec×kernel dispatch itself is tier-1 via the
    # speculative suite's fused-prefix cells.
    @pytest.mark.slow
    def test_spec_mode_multiturn_donation(self, tiny):
        """Speculative engines donate the committed stream (spec commits
        land in _out synchronously pre-reap): multi-turn identity with
        the plain engine, decoded pages donated."""
        cfg, params = tiny
        rng = np.random.default_rng(3)
        phrase = list(rng.integers(0, cfg.vocab, 4))
        p1 = phrase * 4                        # repetitive → accepts
        spec = build(cfg, params, kv_dtype="int8", speculative=True,
                     gamma=2)
        spec.submit(p1, max_new=10)
        (_, t1), = step_all(spec).items()
        assert spec.pool_metrics()["decoded_pages_donated_total"] >= 1
        spec.submit(p1 + t1 + phrase, max_new=4)
        (_, t2), = step_all(spec).items()
        spec._alloc.assert_consistent()
        plain = build(cfg, params, kv_dtype="int8")
        plain.submit(p1, max_new=10)
        (_, r1), = step_all(plain).items()
        plain.submit(p1 + r1 + phrase, max_new=4)
        (_, r2), = step_all(plain).items()
        assert (t1, t2) == (r1, r2)

    # slow: refcount-pinned eviction is tier-1 via the prefix-cache
    # suite; the decoded-leaf edition rides the unfiltered CI run.
    @pytest.mark.slow
    def test_evict_decoded_leaf_mid_conversation(self, tiny):
        """A decoded-suffix leaf evicts like any leaf — but never while
        a turn-2 slot mounts it (refcount pins it): mid-conversation
        eviction pressure leaves the mounted path intact, the stream
        identical, and the pool consistent."""
        cfg, params = tiny
        eng = build(cfg, params, kv_dtype="int8")
        rng = np.random.default_rng(4)
        p1 = list(rng.integers(0, cfg.vocab, 16))
        eng.submit(p1, max_new=12)
        (_, t1), = step_all(eng).items()
        cached = len(eng._prefix)
        assert cached >= 3                     # prompt + decoded pages
        # Turn 2 mounts the transcript, then mid-decode the LRU sweep
        # is forced as hard as possible: mounted pages must survive.
        eng.submit(p1 + t1 + list(rng.integers(0, cfg.vocab, 4)),
                   max_new=6)
        eng.step()
        mounted = {p for pages in eng._slot_shared.values() for p in pages}
        assert len(mounted) >= 3
        eng._prefix.evict(1000)
        for p in mounted:
            assert eng._alloc.ref(p) >= 1, "mounted page evicted"
        (_, t2), = step_all(eng).items()
        eng._alloc.assert_consistent()
        # Same trace, no eviction pressure → identical stream.
        ref = build(cfg, params, kv_dtype="int8")
        rng = np.random.default_rng(4)
        r1, r2 = two_turns(ref, rng, max_new=12, turn2_new=6)
        assert (t1, t2) == (r1, r2)

    def test_mid_prefill_retire_caps_donation(self, tiny):
        """A slot cancelled mid-prefill donates ONLY its resident rows
        (the _free_slot_pages cap): donating beyond prefill_done would
        cache pages whose KV was never written."""
        cfg, params = tiny
        eng = build(cfg, params, kv_dtype="int8", prefill_chunk_tokens=8)
        rng = np.random.default_rng(5)
        prompt = list(rng.integers(0, cfg.vocab, 32))
        rid = eng.submit(prompt, max_new=4)
        eng.step()                             # one 8-token chunk lands
        ((_, done),) = eng._prefill_pending.items()
        assert done < len(prompt)
        eng.cancel(rid, "test")
        eng._alloc.assert_consistent()
        assert len(eng._prefix) <= done // 8
        # The cached part is REAL: re-submitting the same prompt mounts
        # exactly the resident pages and nothing beyond (the match walk
        # is the byte-level proof's cheap proxy; the kernel/gather
        # parity suites pin that mounted pages decode correctly).
        assert len(eng._prefix.match(prompt, count=False)) \
            == len(eng._prefix)


# -- multi-turn lifecycle (drain / restore / absorb) --------------------------

class TestMultiTurnLifecycle:
    # slow: drain/restore/absorb with tree pages is tier-1 via
    # test_snapshot_restore/test_fleet; the decoded-pages editions ride
    # the unfiltered CI run (and the fleet followup-turn test keeps the
    # donated-transcript reuse tier-1 across engines).
    @pytest.mark.slow
    def test_drain_restore_with_decoded_pages(self, tiny):
        """Drain mid-turn-2 (decoded pages in the tree AND mounted by a
        live slot) → restore on a fresh engine with a different pool
        size: the stream resumes token-identically and the restored
        tree still serves the transcript to turn 3."""
        cfg, params = tiny
        eng = build(cfg, params, kv_dtype="int8", n_pages=40)
        rng = np.random.default_rng(6)
        p1 = list(rng.integers(0, cfg.vocab, 16))
        eng.submit(p1, max_new=12)
        (_, t1), = step_all(eng).items()
        p2 = p1 + t1 + list(rng.integers(0, cfg.vocab, 4))
        eng.submit(p2, max_new=6)
        eng.step()
        eng.step()                             # mid-decode on shared pages
        snap = eng.drain()
        eng2 = build(cfg, params, kv_dtype="int8", n_pages=48)
        eng2.restore(snap)
        done = step_all(eng2)
        (_, t2), = done.items()
        ref = build(cfg, params, kv_dtype="int8")
        rng = np.random.default_rng(6)
        r1, r2 = two_turns(ref, rng, max_new=12, turn2_new=6)
        assert (t1, t2) == (r1, r2)
        # Turn 3 on the RESTORED engine hits the restored transcript.
        m_before = eng2.pool_metrics()
        eng2.submit(p2[:len(p1) + len(t1)] + list(
            rng.integers(0, cfg.vocab, 5)), max_new=2)
        step_all(eng2)
        m_after = eng2.pool_metrics()
        assert m_after["prefill_tokens_skipped"] \
            - m_before["prefill_tokens_skipped"] \
            >= ((len(p1) + len(t1) - 1) // 8) * 8
        eng2._alloc.assert_consistent()

    @pytest.mark.slow   # see class note
    def test_shed_absorb_midturn_and_source_keeps_transcript(self, tiny):
        """Partial-drain a turn-2 slot mid-decode into a peer: the
        stream finishes identically on the target, BOTH pools stay
        consistent, and the SOURCE keeps the conversation cached (its
        next same-conversation turn still hits locally)."""
        cfg, params = tiny
        src = build(cfg, params, kv_dtype="int8")
        dst = build(cfg, params, kv_dtype="int8")
        rng = np.random.default_rng(8)
        p1 = list(rng.integers(0, cfg.vocab, 16))
        src.submit(p1, max_new=12)
        (_, t1), = step_all(src).items()
        p2 = p1 + t1 + list(rng.integers(0, cfg.vocab, 4))
        rid = src.submit(p2, max_new=6)
        src.step()
        (slot,) = [s for s, r in src._slot_req.items() if r == rid]
        early = src.emitted(rid)
        snap = src.drain(slots=[slot])
        mapping = dst.absorb(snap)
        src._alloc.assert_consistent()
        dst._alloc.assert_consistent()
        done = step_all(dst)
        assert done[mapping[rid]][:len(early)] == early
        got = done[mapping[rid]]
        ref = build(cfg, params, kv_dtype="int8")
        rng = np.random.default_rng(8)
        r1, r2 = two_turns(ref, rng, max_new=12, turn2_new=6)
        assert (t1, got) == (r1, r2)
        # Source still serves the transcript from its tree.
        m0 = src.pool_metrics()
        src.submit(p1 + t1 + list(rng.integers(0, cfg.vocab, 3)),
                   max_new=2)
        step_all(src)
        m1 = src.pool_metrics()
        assert m1["prefill_tokens_skipped"] > m0["prefill_tokens_skipped"]


# -- multi-chip islands -------------------------------------------------------

# slow: test_sharded_serving's prefix grid cells dispatch the kernel
# inside islands tier-1 (fused configs route it by default); this
# explicit kernel-vs-gather-vs-unsharded triangle rides the unfiltered
# CI run.
@pytest.mark.slow
def test_tp2_kernel_vs_gather_identity(tiny):
    """The kernel inside shard_map islands (local head family + exact
    all_gather combine): tp=2 kernel == tp=2 gather == single-chip
    streams on a 2-turn conversation."""
    from jax.sharding import Mesh

    cfg, params = tiny
    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs 2 devices")
    streams = []
    for mesh, impl in ((None, "kernel"),
                       (Mesh(np.array(devs[:2]), ("tp",)), "kernel"),
                       (Mesh(np.array(devs[:2]), ("tp",)), "gather")):
        eng = build(cfg, params, kv_dtype="int8", mesh=mesh,
                    prefill_attn=impl, max_len=32)
        rng = np.random.default_rng(10)
        streams.append(two_turns(eng, rng, p1_len=8, max_new=8, suffix=3,
                                 turn2_new=3))
        eng._alloc.assert_consistent()
    assert streams[0] == streams[1] == streams[2]


# -- metrics ------------------------------------------------------------------

def test_hit_token_batch_drained_once(tiny):
    """pool_metrics() drains the per-admission hit-length batch exactly
    once (the phase-batch contract): misses observe 0, transcript
    mounts observe their full hit length."""
    cfg, params = tiny
    eng = build(cfg, params, kv_dtype="int8")
    rng = np.random.default_rng(12)
    t1, _ = two_turns(eng, rng)
    m = eng.pool_metrics()
    batch = list(m["prefix_hit_token_batch"])
    assert batch[0] == 0                       # turn-1 miss
    assert max(batch) >= ((16 + len(t1) - 1) // 8) * 8
    assert "prefix_hit_token_batch" not in eng.pool_metrics()
