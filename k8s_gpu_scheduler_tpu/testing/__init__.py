"""Deterministic fault injection for chaos tests and the chaos bench leg.

Importable from production code (the hook points in the serving engine,
the control-plane clients and the scheduler cycle are ``if injector is
not None`` guards), but nothing here runs unless a test or bench wires
an injector in.
"""
from .faults import (
    FaultInjector, FaultProxy, FaultRule, InjectedFault, Preempted,
)

__all__ = [
    "FaultInjector",
    "FaultProxy",
    "FaultRule",
    "InjectedFault",
    "Preempted",
]
