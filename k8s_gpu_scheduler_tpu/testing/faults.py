"""Seeded, deterministic fault injection — the chaos harness.

TPU slices on GKE are preempted routinely (spot capacity, maintenance),
registries restart, recommenders roll — and every robustness claim this
repo makes ("drain/restore resumes token-identically", "clients survive
flaps with bounded retries", "the scheduler cycle degrades instead of
dying") is only testable if the failures can be REPRODUCED. This module
makes failure a first-class, replayable input:

- **Hook points** are named ``site`` strings fired from production code
  (``serve.step`` / ``serve.propose`` in the batcher step loop,
  ``registry.connect`` / ``registry.roundtrip`` in the RESP client,
  ``recommender.call`` in the gRPC client, ``sched.cycle`` in the
  scheduler loop, plus whatever a ``FaultProxy`` wraps). A site fires
  on every pass through the hook whether or not any rule matches — the
  per-site call counter IS the injection clock.
- **Rules** (``FaultRule``) select call indices at a site — explicit
  ``at`` indices, periodic ``every``, an ``after``/``until`` window,
  or seeded probability ``p`` — and name the fault kind:
  ``drop`` (raise: dropped connection / failed RPC), ``delay``
  (sleep: rpc-delay / slow-dispatch), ``preempt`` (raise
  :class:`Preempted`: the mid-stream preemption signal the drain/
  restore loop catches), ``crash`` (raise :class:`ReplicaCrashed`: the
  HARD kill — the fleet router discards the engine with no drain),
  ``page_pressure`` (returned to the caller — the batcher holds that
  many pool pages hostage).
- **Determinism**: matching depends only on (rule, per-site call
  index) and, for probabilistic rules, a ``random.Random`` seeded from
  (injector seed, site, rule index) — so the same seed and the same
  call sequence always inject at the same points. ``injector.log``
  records every injection as ``(site, index, kind)``; chaos tests
  assert two runs of the same scenario produce equal logs AND equal
  results (the CI determinism gate).

The harness never monkey-patches: every fault flows through an explicit
hook or a :class:`FaultProxy` wrapper, so what can fail in a test is
exactly what is declared to fail — and a production binary with no
injector attached pays one ``is None`` check per hook.
"""
from __future__ import annotations

import random
import time
import zlib
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple, Type

_KINDS = ("drop", "delay", "preempt", "page_pressure", "crash")


class InjectedFault(Exception):
    """An injected failure (default exception for ``drop`` rules when
    the hook point doesn't name a site-appropriate one)."""


class Preempted(InjectedFault):
    """The preemption signal: raised out of the batcher step loop so the
    driver can drain/snapshot/restore — the in-process stand-in for the
    SIGTERM a GKE spot preemption delivers."""


class ReplicaCrashed(InjectedFault):
    """The HARD-kill signal (kind="crash"): unlike :class:`Preempted`,
    nothing cooperative follows — the fleet router discards the engine
    object outright (no drain, no snapshot; OOM / wedged device / killed
    pod semantics) and recovery is the router-side journal replay, never
    the dead replica's own state. Fired from the fleet hook points
    (``fleet.step`` per router step, ``replica.crash`` once per live
    replica per step — the per-site call index picks WHICH replica dies,
    deterministically, since the router visits replicas in id order)."""


@dataclass(frozen=True)
class FaultRule:
    """One deterministic injection rule at one site (or site prefix:
    ``site="apiserver"`` matches ``apiserver.get``, ``apiserver.update``,
    ... — how one rule flaps a whole proxied client)."""

    site: str
    kind: str
    at: Optional[Sequence[int]] = None   # explicit 1-based call indices
    every: int = 0                       # fire when index % every == 0
    after: int = 0                       # only indices strictly above
    until: int = 0                       # only indices <= until (0 = inf)
    p: float = 0.0                       # seeded per-rule probability
    delay_s: float = 0.0                 # for kind="delay"
    pages: int = 0                       # for kind="page_pressure"
    exc: Optional[Type[BaseException]] = None   # override for kind="drop"

    def __post_init__(self) -> None:
        if self.kind not in _KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r} (one of {_KINDS})")
        if self.at is None and not self.every and not self.p:
            raise ValueError(
                f"rule at {self.site!r} can never fire: set at=, every= "
                f"or p=")

    def _matches_site(self, site: str) -> bool:
        return site == self.site or site.startswith(self.site + ".")

    def _in_window(self, index: int) -> bool:
        if index <= self.after:
            return False
        if self.until and index > self.until:
            return False
        if self.at is not None and index not in self.at:
            return False
        if self.every and index % self.every:
            return False
        return True


class FaultInjector:
    """Fires the rule schedule at named hook points. Thread-compatible
    for the tests' purposes (counters are plain ints guarded by the
    GIL; chaos scenarios drive one site from one thread)."""

    def __init__(self, seed: int = 0,
                 rules: Sequence[FaultRule] = ()) -> None:
        self.seed = seed
        self.rules: List[FaultRule] = list(rules)
        self._counts: Dict[str, int] = {}
        self._rngs: Dict[Tuple[int, str], random.Random] = {}
        # Every injection, in firing order: (site, call index, kind) —
        # the replay transcript the determinism tests byte-compare.
        self.log: List[Tuple[str, int, str]] = []
        self._sleep = time.sleep

    def count(self, site: str) -> int:
        """Calls seen at ``site`` so far (the injection clock)."""
        return self._counts.get(site, 0)

    def _rng_for(self, rule_idx: int, site: str) -> random.Random:
        # crc32, not hash(): str hashing is salted per process
        # (PYTHONHASHSEED), which would silently break the cross-run
        # reproducibility contract the CI determinism gate asserts.
        # Cached PER (rule, site): a prefix-site rule matching several
        # proxied methods must draw from an independent stream at each,
        # or one site's traffic would shift another's injection points.
        key = zlib.crc32(
            f"{self.seed}:{rule_idx}:{site}".encode()) & 0x7FFFFFFF
        if (rule_idx, site) not in self._rngs:
            self._rngs[(rule_idx, site)] = random.Random(key)
        return self._rngs[(rule_idx, site)]

    def fire(self, site: str,
             drop_exc: Type[BaseException] = InjectedFault,
             ) -> List[FaultRule]:
        """One pass through hook point ``site``: advance its clock,
        evaluate every matching rule in declaration order, apply
        ``delay`` sleeps inline, RAISE on the first ``drop``/``preempt``
        (``drop`` raises ``rule.exc`` or the hook's ``drop_exc`` — the
        exception type the call site's real failure would be), and
        return the non-raising matches (``page_pressure``) for the
        caller to interpret."""
        index = self._counts.get(site, 0) + 1
        self._counts[site] = index
        passive: List[FaultRule] = []
        for i, rule in enumerate(self.rules):
            if not rule._matches_site(site) or not rule._in_window(index):
                continue
            if rule.p:
                # Draw exactly once per in-window call so the stream of
                # consumed variates — hence every later decision — is a
                # pure function of the call sequence.
                if self._rng_for(i, site).random() >= rule.p:
                    continue
            self.log.append((site, index, rule.kind))
            if rule.kind == "delay":
                self._sleep(rule.delay_s)
            elif rule.kind == "preempt":
                raise Preempted(f"injected preemption at {site}#{index}")
            elif rule.kind == "crash":
                raise ReplicaCrashed(f"injected crash at {site}#{index}")
            elif rule.kind == "drop":
                exc = rule.exc or drop_exc
                raise exc(f"injected {site}#{index} drop")
            else:
                passive.append(rule)
        return passive


class FaultProxy:
    """Wrap any object so every public method call first fires
    ``<site>.<method>`` on the injector — how a test flaps a whole
    client (the lease APIServer under the leader elector, a registry
    under the collector) without the wrapped class knowing. Attribute
    reads pass through untouched; only calls inject."""

    def __init__(self, target, injector: FaultInjector, site: str,
                 drop_exc: Type[BaseException] = InjectedFault) -> None:
        self._target = target
        self._injector = injector
        self._site = site
        self._drop_exc = drop_exc

    def __getattr__(self, name: str):
        attr = getattr(self._target, name)
        if not callable(attr) or name.startswith("_"):
            return attr
        injector, site, exc = self._injector, self._site, self._drop_exc

        def fired(*args, **kwargs):
            injector.fire(f"{site}.{name}", drop_exc=exc)
            return attr(*args, **kwargs)

        return fired
