"""Pallas VMEM budgeter — static footprint estimates for the ops/ kernels.

A Pallas TPU kernel that oversubscribes VMEM fails at Mosaic compile time
ON THE TPU — i.e. in production, at whatever new (config, block) pair
first exceeds the budget — while CPU interpret-mode tier-1 sails through
because interpret mode has no VMEM. This pass moves that failure to lint
time: it recomputes each kernel's VMEM working set from the SAME block
shapes the wrapper would choose (``decode_plan`` for the flash-decode
kernel, the ``block_q``/``block_k`` defaults for training flash
attention) and checks it against the ~16 MiB/core budget, for every
``LlamaConfig`` preset the repo actually serves or benches.

Footprint model (the standard Mosaic accounting):

- every grid-streamed input/output block is DOUBLE-buffered (the pipeline
  overlaps the next block's DMA with this block's compute), so block
  bytes count twice;
- scratch (``pltpu.VMEM`` shapes) is single-buffered;
- a conservative fraction of the 16 MiB is reserved for Mosaic's own
  spills/temporaries (default 10%).

The block-divisibility side of the same contract is checked here too: a
preset whose cache length has no legal ``decode_plan`` blocking would
silently fall back to the dense path (a perf cliff, not a crash), and a
``max_seq`` the training flash kernel's default blocks don't divide
raises at trace time on the training path.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from .findings import Finding

VMEM_BYTES_PER_CORE = 16 * 2 ** 20
# Fraction of VMEM the estimator may budget for kernel blocks+scratch;
# the rest absorbs Mosaic temporaries and sublane padding slack.
VMEM_USABLE_FRACTION = 0.9

_LANES = 128
_DTYPE_BYTES = {"bfloat16": 2, "float32": 4, "int8": 1, "float16": 2,
                "int32": 4, "bool": 1}


def _nbytes(shape: Tuple[int, ...], dtype: str) -> int:
    n = 1
    for d in shape:
        n *= d
    return n * _DTYPE_BYTES[dtype]


@dataclass(frozen=True)
class KernelFootprint:
    name: str
    in_blocks: int        # double-buffered
    out_blocks: int       # double-buffered
    scratch: int          # single-buffered
    notes: str = ""

    @property
    def total(self) -> int:
        return 2 * (self.in_blocks + self.out_blocks) + self.scratch

    def check(self, budget: int = VMEM_BYTES_PER_CORE,
              usable_fraction: float = VMEM_USABLE_FRACTION,
              anchor: str = "") -> List[Finding]:
        usable = int(budget * usable_fraction)
        if self.total <= usable:
            return []
        return [Finding(
            "vmem-budget", anchor or f"<vmem:{self.name}>", 0,
            f"{self.name}: estimated VMEM working set "
            f"{self.total / 2**20:.2f} MiB exceeds the usable "
            f"{usable / 2**20:.1f} MiB of the {budget / 2**20:.0f} MiB/core "
            f"budget ({self.notes})")]


def decode_attention_footprint(
    s: int, g: int, hd: int, block_k: int,
    kv_dtype: str = "bfloat16", quant: bool = False, bitmap: bool = False,
    q_dtype: str = "bfloat16",
) -> KernelFootprint:
    """Working set of ops/decode_attention._decode_kernel for one grid
    program: q block [1, g, hd], k/v blocks [1, block_k, 1, hd] (int8 in
    quant mode plus f32 scale planes), optional bitmap block, three
    partial outputs, and the (acc, m, l) f32 scratch."""
    kv_d = "int8" if quant else kv_dtype
    in_blocks = _nbytes((1, g, hd), q_dtype) \
        + 2 * _nbytes((1, block_k, 1, hd), kv_d)
    if quant:
        in_blocks += 2 * _nbytes((1, block_k, 1, 1), "float32")
    if bitmap:
        in_blocks += _nbytes((1, block_k), "int8")
    out_blocks = _nbytes((1, 1, g, hd), "float32") \
        + 2 * _nbytes((1, 1, g, _LANES), "float32")
    scratch = _nbytes((g, hd), "float32") + 2 * _nbytes((g, _LANES), "float32")
    return KernelFootprint(
        name=f"flash_decode(S={s}, block_k={block_k}, g={g}, hd={hd}, "
             f"kv={'int8' if quant else kv_dtype})",
        in_blocks=in_blocks, out_blocks=out_blocks, scratch=scratch,
        notes=f"block_k={block_k}, double-buffered blocks",
    )


def _paged_kv_working_set(rows: int, page_size: int, hd: int,
                          n_blocks: int, batch: int, kv_dtype: str,
                          quant: bool, q_dtype: str) -> Tuple[int, int, int]:
    """The shared VMEM accounting of BOTH paged kernels
    (ops/decode_attention._paged_kernel and ._verify_kernel): a q block
    of ``rows`` rows (g for decode, t·g for the verify window),
    double-buffered k/v page blocks (int8 + f32 scale planes in quant
    mode), three partial outputs, (acc, m, l) scratch, and the
    scalar-prefetch working set — ``lengths`` [B] and the block table
    [B, n_blocks] int32, resident for the whole kernel (SMEM-side, but
    counted against the same budget conservatively). ONE definition so
    the decode and verify estimates cannot drift apart. Returns
    (in_blocks, out_blocks, scratch)."""
    kv_d = "int8" if quant else kv_dtype
    in_blocks = _nbytes((1, rows, hd), q_dtype) \
        + 2 * _nbytes((1, page_size, 1, hd), kv_d)
    if quant:
        in_blocks += 2 * _nbytes((1, page_size, 1, 1), "float32")
    out_blocks = _nbytes((1, 1, rows, hd), "float32") \
        + 2 * _nbytes((1, 1, rows, _LANES), "float32")
    scratch = _nbytes((rows, hd), "float32") \
        + 2 * _nbytes((rows, _LANES), "float32")
    scratch += _nbytes((batch,), "int32") \
        + _nbytes((batch, n_blocks), "int32")        # scalar prefetch
    return in_blocks, out_blocks, scratch


def paged_decode_attention_footprint(
    page_size: int, g: int, hd: int, n_blocks: int, batch: int = 8,
    kv_dtype: str = "bfloat16", quant: bool = False,
    q_dtype: str = "bfloat16",
) -> KernelFootprint:
    """Working set of ops/decode_attention._paged_kernel for one grid
    program: the page IS the kv block, so the VMEM picture matches the
    contiguous kernel at block_k == page_size — no bitmap operand (the
    per-slot length bound subsumes it in the paged design) — plus the
    block-table scalar working set (see _paged_kv_working_set)."""
    in_blocks, out_blocks, scratch = _paged_kv_working_set(
        g, page_size, hd, n_blocks, batch, kv_dtype, quant, q_dtype)
    return KernelFootprint(
        name=f"paged_decode(ps={page_size}, n_blocks={n_blocks}, g={g}, "
             f"hd={hd}, kv={'int8' if quant else kv_dtype})",
        in_blocks=in_blocks, out_blocks=out_blocks, scratch=scratch,
        notes=f"page_size={page_size}, double-buffered page blocks + "
              f"[B,{n_blocks}] block table",
    )


def paged_verify_attention_footprint(
    page_size: int, g: int, hd: int, n_blocks: int, t: int, batch: int = 8,
    kv_dtype: str = "bfloat16", quant: bool = False,
    q_dtype: str = "bfloat16",
) -> KernelFootprint:
    """Working set of ops/decode_attention._verify_kernel for one grid
    program — the multi-query speculative verify window. The kv side is
    the paged decode picture unchanged (the page is the kv block,
    double-buffered, int8 scale planes in quant mode, the [B, n_blocks]
    block-table scalar working set); the Q-WINDOW ROWS MULTIPLY the
    query/output/scratch side: q block [1, t·g, hd], three partial
    outputs and the (acc, m, l) scratch all carry t·g rows instead of g.
    That factor is how a \"just raise gamma\" tuning mistake walks the
    kernel over the budget while the kv traffic looks unchanged — the
    exact cliff this estimator exists to catch before Mosaic does."""
    rows = t * g
    in_blocks, out_blocks, scratch = _paged_kv_working_set(
        rows, page_size, hd, n_blocks, batch, kv_dtype, quant, q_dtype)
    return KernelFootprint(
        name=f"paged_verify(ps={page_size}, n_blocks={n_blocks}, t={t}, "
             f"g={g}, hd={hd}, kv={'int8' if quant else kv_dtype})",
        in_blocks=in_blocks, out_blocks=out_blocks, scratch=scratch,
        notes=f"page_size={page_size}, t*g={rows} q-window rows multiply "
              f"the q/out/scratch set",
    )


def paged_prefill_attention_footprint(
    page_size: int, g: int, hd: int, hb: int, tb: int, batch: int = 8,
    kv_dtype: str = "bfloat16", quant: bool = False,
    q_dtype: str = "bfloat16",
) -> KernelFootprint:
    """Working set of ops/decode_attention._prefill_kernel for one grid
    program — the prefix-attention tail-prefill window (the hb>0 rung of
    the serving engine's prefix-cache prefill). The kv side is the paged
    picture (the page is the kv block, double-buffered, int8 scale
    planes in quant mode, a [B, hb] prefix-table scalar working set)
    PLUS the tail's own K/V riding as a second double-buffered
    exact-dtype page block pair; the Q-WINDOW ROWS MULTIPLY the
    query/output/scratch side by tb·g — the verify kernel's t·g blowup
    at t = the whole tail bucket. That factor is how a long prefill rung
    walks the kernel over the budget while the kv traffic looks
    unchanged — the runtime gate is ops.prefill_plan's
    PREFILL_MAX_Q_ROWS cap (rungs past it fall back to the dense
    gather, counted); this estimator is the precise per-preset check
    that the cap actually holds under the 16 MiB budget."""
    rows = tb * g
    in_blocks, out_blocks, scratch = _paged_kv_working_set(
        rows, page_size, hd, hb, batch, kv_dtype, quant, q_dtype)
    # The tail K/V pair: [1, 1, ps, 1, hd] blocks in the compute dtype
    # (these rows are computed by the dispatch — never quantized on the
    # way in), double-buffered like every grid-streamed input.
    in_blocks += 2 * _nbytes((1, 1, page_size, 1, hd), q_dtype)
    return KernelFootprint(
        name=f"paged_prefill(ps={page_size}, hb={hb}, tb={tb}, g={g}, "
             f"hd={hd}, kv={'int8' if quant else kv_dtype})",
        in_blocks=in_blocks, out_blocks=out_blocks, scratch=scratch,
        notes=f"page_size={page_size}, tb*g={rows} q-window rows multiply "
              f"the q/out/scratch set + dense tail K/V blocks",
    )


def flash_attention_footprint(
    block_q: int, block_k: int, d: int, dtype: str = "bfloat16",
    with_lse: bool = True, backward: bool = False,
) -> KernelFootprint:
    """Working set of the training flash kernels (ops/flash_attention.py).
    Forward: q/k/v blocks in, out (+lse) blocks out, (m, l, acc) scratch.
    Backward (the dkv kernel — strictly the larger of the two): six input
    blocks, two output blocks, two f32 accumulators."""
    if not backward:
        in_blocks = _nbytes((1, block_q, d), dtype) \
            + 2 * _nbytes((1, block_k, d), dtype)
        out_blocks = _nbytes((1, block_q, d), dtype)
        if with_lse:
            out_blocks += _nbytes((1, block_q, _LANES), "float32")
        scratch = 2 * _nbytes((block_q, _LANES), "float32") \
            + _nbytes((block_q, d), "float32")
        name = f"flash_fwd(bq={block_q}, bk={block_k}, d={d})"
    else:
        in_blocks = 4 * _nbytes((1, block_q, d), dtype) \
            + 2 * _nbytes((1, block_k, d), dtype) \
            + _nbytes((1, block_q, _LANES), "float32")
        out_blocks = 2 * _nbytes((1, block_k, d), dtype)
        scratch = 2 * _nbytes((block_k, d), "float32")
        name = f"flash_bwd_dkv(bq={block_q}, bk={block_k}, d={d})"
    return KernelFootprint(name=name, in_blocks=in_blocks,
                           out_blocks=out_blocks, scratch=scratch,
                           notes="double-buffered blocks")


# -- preset audit -------------------------------------------------------------

def _presets() -> List[Tuple[str, "object", Dict]]:
    """Every LlamaConfig the repo actually runs, with the serving cache
    lengths it runs them at. Kept HERE (not scattered) so adding a preset
    to serving/bench without extending the audit is a conscious choice."""
    from ..models.llama import LlamaConfig

    serve_cfg = LlamaConfig(                 # models/llama.py main --serve
        vocab=32000, d_model=1024, n_layers=8, n_heads=16, n_kv_heads=16,
        d_ff=4096, max_seq=2048, remat=False)
    longctx_cfg = LlamaConfig(               # bench.py _bench_serving_longctx
        vocab=32000, d_model=1024, n_layers=4, n_heads=16, n_kv_heads=16,
        d_ff=4096, max_seq=8192, remat=False)
    full8b_cfg = LlamaConfig(                # bench.py _bench_serving_8b_full
        vocab=32000, d_model=4096, n_layers=32, n_heads=32, n_kv_heads=8,
        d_ff=14336, max_seq=1024, remat=False)
    return [
        ("llama3_8b", LlamaConfig.llama3_8b(), {"cache_lens": (8192,)}),
        ("tiny", LlamaConfig.tiny(), {"cache_lens": (128,)}),
        ("serve_1b", serve_cfg, {"cache_lens": (2048,)}),
        ("longctx", longctx_cfg, {"cache_lens": (8192,)}),
        ("serve_8b_full", full8b_cfg, {"cache_lens": (512, 1024)}),
    ]


def audit_vmem(budget: int = VMEM_BYTES_PER_CORE) -> List[Finding]:
    """Block-divisibility + VMEM-budget audit of every kernel the presets
    can reach: flash-decode at each preset's serving cache lengths (bf16
    and int8-KV, with the batcher's bitmap), the PAGED decode plan at the
    default page size (page-size divisibility + page-block working set +
    block-table scalar footprint), training flash fwd+bwd at each
    preset's max_seq."""
    from ..ops.decode_attention import (
        DEFAULT_PAGE_SIZE, decode_plan, paged_plan, prefill_plan,
    )
    from ..ops.flash_attention import _shrink_to_divisor

    findings: List[Finding] = []
    anchor = "k8s_gpu_scheduler_tpu/ops/decode_attention.py"
    # Speculation windows the serving engine actually dispatches
    # (ContinuousBatcher speculative=True / generate_speculative): the
    # verify kernel's q side scales with t = 1+gamma, so every preset is
    # checked at the realistic gamma range too — including the padded
    # gamma_max window an adaptive-gamma engine always dispatches
    # (effective windows shrink acceptance, never the kernel shapes).
    gammas = (2, 4, 8)
    for name, cfg, meta in _presets():
        g = cfg.n_heads // cfg.n_kv_heads
        for s in meta["cache_lens"]:
            plan = decode_plan(s)
            if plan is None:
                findings.append(Finding(
                    "block-divisibility", anchor, 0,
                    f"preset {name}: no legal (block_k, n_splits) for "
                    f"cache length S={s} — fused decode silently falls "
                    f"back to the dense path"))
                continue
            block_k, n_splits = plan
            for quant in (False, True):
                fp = decode_attention_footprint(
                    s, g, cfg.head_dim, block_k, quant=quant, bitmap=True)
                findings.extend(fp.check(budget, anchor=anchor))
            # Paged plan at the serving default page size: every preset a
            # paged ContinuousBatcher could serve must both divide into
            # pages AND have a legal kernel plan, or admission at that
            # config silently loses the fused path (a perf cliff the
            # contiguous fallback comment documents).
            ps = DEFAULT_PAGE_SIZE
            if s % ps or paged_plan(s // ps, ps) is None:
                findings.append(Finding(
                    "block-divisibility", anchor, 0,
                    f"preset {name}: cache length S={s} has no legal "
                    f"paged plan at page_size={ps} — paged fused decode "
                    f"would fall back to the dense gather path"))
            else:
                for quant in (False, True):
                    fp = paged_decode_attention_footprint(
                        ps, g, cfg.head_dim, s // ps, quant=quant)
                    findings.extend(fp.check(budget, anchor=anchor))
                    for gamma in gammas:
                        fp = paged_verify_attention_footprint(
                            ps, g, cfg.head_dim, s // ps, 1 + gamma,
                            quant=quant)
                        findings.extend(fp.check(budget, anchor=anchor))
                    # Prefix-attention tail prefill: every (tb) rung of
                    # the engine's page-quantized bucket ladder the
                    # runtime plan ACCEPTS must fit — rungs past the
                    # PREFILL_MAX_Q_ROWS cap fall back to the dense
                    # gather by design and are exempt (a cap the plan
                    # accepts but the budget rejects is exactly the
                    # cliff this audit exists to catch). hb is taken at
                    # the worst case: the rest of the cache as cached
                    # prefix.
                    tb = ps
                    while tb <= s:
                        hb = max((s - tb) // ps, 1)
                        if prefill_plan(hb + tb // ps, ps,
                                        tb * g) is not None:
                            fp = paged_prefill_attention_footprint(
                                ps, g, cfg.head_dim, hb, tb, quant=quant)
                            findings.extend(
                                fp.check(budget, anchor=anchor))
                        tb *= 2
        # Training flash attention at max_seq (forward defaults 256/512;
        # backward shrinks to <=256 divisors — mirror _resolve/_bwd).
        t = cfg.max_seq
        bq, bk = min(256, t), min(512, t)
        fa_anchor = "k8s_gpu_scheduler_tpu/ops/flash_attention.py"
        if t % bq or t % bk:
            findings.append(Finding(
                "block-divisibility", fa_anchor, 0,
                f"preset {name}: max_seq {t} not divisible by the default "
                f"flash blocks ({bq}/{bk}) — attn_impl='flash' would raise "
                f"at trace time"))
        else:
            findings.extend(flash_attention_footprint(
                bq, bk, cfg.head_dim).check(budget, anchor=fa_anchor))
            bq_b, bk_b = _shrink_to_divisor(bq, t), _shrink_to_divisor(bk, t)
            findings.extend(flash_attention_footprint(
                bq_b, bk_b, cfg.head_dim, backward=True).check(
                    budget, anchor=fa_anchor))
    return findings
