"""Lock-order & donated-buffer concurrency audit — graftcheck's tenth pass.

The AST lock-lint (astlint.py ``lock-guard``) answers "is this attribute
touched without its lock"; this pass answers the three questions that
rule cannot see, all of which have bitten this repo in review or in
production-shaped tests:

- ``lock-cycle``: extend the lock→attr map into a repo-wide
  lock-ACQUISITION-ORDER graph — one node per lock (``Class.attr`` or a
  module-level lock), one edge A→B whenever code acquires B while
  holding A (a directly nested ``with``, or a call to a same-scope
  function/method that acquires B). A cycle is a potential deadlock:
  two threads entering the cycle from different edges wait on each
  other forever. A SELF-edge on a non-reentrant ``threading.Lock`` /
  ``Condition`` is the degenerate cycle (re-acquisition deadlocks the
  one thread) and is reported the same way; ``RLock`` self-edges are
  exempt by construction.

- ``use-after-donate``: host-thread reads of engine attributes that
  alias per-dispatch-DONATED device arrays, outside the step path. The
  donated-attr set is derived from the source itself: an assignment
  ``self._f = jax.jit(fn, donate_argnums=(…))`` (or the serving
  engine's ``_jit_island(fn, …, donate=(…))``) marks ``self._f`` a
  donating dispatcher, and every ``self.X`` passed at a donated
  position of a ``self._f(…)`` call site joins the donated set. A read
  of a donated attr is safe only where the buffer's lifetime is under
  the reader's control: ``__init__``, and methods that themselves
  dispatch (they rebind the attr from the dispatch results) or rebind
  the attr (restore/reshard boundaries). Anywhere else — metrics
  scrapes, summaries, exporters — the read races a step: the dispatch
  consumes the buffer and a concurrent ``.addressable_shards`` /
  subscript read dies with "Array has been deleted" (the PR 13
  ``pool_metrics`` crash class). Identity checks (``is None``) and
  metadata reads (``.shape``/``.dtype``/``.ndim``/``.aval``) never
  touch device memory and are exempt.

- ``torn-snapshot``: a method that acquires the SAME lock more than
  once and touches that lock's guarded attributes under two or more of
  the acquisitions — each ``with`` block is individually "held" (so
  ``lock-guard`` stays quiet) but the values come from different
  instants: a scrape between the acquisitions pairs gauge A from this
  step with gauge B from the last one (the PR 7 exporter torn-read bug
  class). Multi-gauge drains must be ONE lock snapshot.

Pure AST (no jax import) — runs inside the fast passes, so ``make
lint`` and the tier-1 gate enforce all three rules on every collection.
Suppression: the standard ``# graftcheck: ignore[rule]`` with a
rationale (e.g. ``drain()``'s pool reads, which happen at a step
boundary with admission stopped and the readbacks flushed).
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .findings import Finding, apply_suppressions, parse_suppressions
from .astlint import (
    _LOCK_TYPES, _self_attr, _terminal_name, _walk_shallow, _MUTATORS,
    iter_python_files,
)

# Reads of these attributes touch only the aval/metadata of a jax Array,
# never device memory — safe on a deleted (donated-and-consumed) buffer.
_METADATA_ATTRS = {"shape", "dtype", "ndim", "aval", "size", "nbytes",
                   "sharding", "weak_type"}
# jit-wrapper callees whose assignment marks a donating dispatcher, and
# the keyword that carries the donated argument positions.
_DONATING_WRAPPERS = {"jit": "donate_argnums", "_jit_island": "donate",
                      "pjit": "donate_argnums"}


def _parents(tree: ast.AST) -> Dict[int, ast.AST]:
    out: Dict[int, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            out[id(child)] = node
    return out


# -- lock-acquisition-order graph ---------------------------------------------

class _LockGraph:
    """Acquisition-order edges between the locks of one scope (a class,
    or a module's top level). Node = lock attr name; edge (a, b, lineno)
    = b acquired while a held."""

    def __init__(self, owner: str, path: str) -> None:
        self.owner = owner
        self.path = path
        self.edges: Dict[str, Dict[str, int]] = {}   # a -> {b: lineno}
        self.rlocks: Set[str] = set()

    def add(self, a: str, b: str, lineno: int) -> None:
        self.edges.setdefault(a, {}).setdefault(b, lineno)

    def cycles(self) -> List[Tuple[List[str], int]]:
        """Every elementary cycle reachable in the (small) graph, as
        (node path, anchor lineno). Self-edges on non-reentrant locks
        are length-1 cycles; RLock self-edges are dropped."""
        out: List[Tuple[List[str], int]] = []
        seen: Set[frozenset] = set()
        for a, nbrs in sorted(self.edges.items()):
            if a in nbrs and a not in self.rlocks:
                out.append(([a, a], nbrs[a]))
        # DFS for multi-node cycles (graphs here have a handful of nodes).
        def dfs(start: str, node: str, trail: List[str]) -> None:
            for b, ln in sorted(self.edges.get(node, {}).items()):
                if b == start and len(trail) > 1:
                    key = frozenset(trail)
                    if key not in seen:
                        seen.add(key)
                        out.append((trail + [start], ln))
                elif b not in trail and b != start:
                    dfs(start, b, trail + [b])

        for a in sorted(self.edges):
            dfs(a, a, [a])
        return out


def _lock_attrs_of_class(cls: ast.ClassDef) -> Tuple[Set[str], Set[str]]:
    """(lock attrs, RLock attrs) assigned as ``self.X = threading.Lock()``
    anywhere in the class body."""
    locks: Set[str] = set()
    rlocks: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            tname = _terminal_name(node.value.func)
            if tname not in _LOCK_TYPES:
                continue
            for tgt in node.targets:
                attr = _self_attr(tgt)
                if attr is not None:
                    locks.add(attr)
                    if tname == "RLock":
                        rlocks.add(attr)
    return locks, rlocks


def _module_locks(tree: ast.Module) -> Set[str]:
    out: Set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if _terminal_name(node.value.func) in _LOCK_TYPES:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        out.add(tgt.id)
    return out


def _acquired_lock(item: ast.withitem, locks: Set[str],
                   self_based: bool) -> Optional[str]:
    expr = item.context_expr
    # `with self._mu:` / `with self._cv:` (also `.acquire()`-less
    # Condition use; `with lock:` at module level when self_based=False).
    if self_based:
        attr = _self_attr(expr)
        return attr if attr in locks else None
    if isinstance(expr, ast.Name) and expr.id in locks:
        return expr.id
    return None


def _direct_acquisitions(fn: ast.AST, locks: Set[str],
                         self_based: bool) -> Set[str]:
    out: Set[str] = set()
    for node in _walk_shallow(fn):
        if isinstance(node, ast.With):
            for item in node.items:
                lk = _acquired_lock(item, locks, self_based)
                if lk:
                    out.add(lk)
    return out


def _scan_order_edges(fn: ast.AST, locks: Set[str], self_based: bool,
                      acquires: Dict[str, Set[str]],
                      graph: _LockGraph) -> None:
    """Walk one function body tracking the held-lock set; record an edge
    held→B for every nested acquisition of B (directly, or through a
    call to a same-scope function whose transitive acquisition set is
    known). Nested defs/lambdas run later (often on another thread):
    held set resets to empty inside them."""

    def callee_name(call: ast.Call) -> Optional[str]:
        if self_based:
            # self.method(...) — same-class resolution only.
            if isinstance(call.func, ast.Attribute) \
                    and isinstance(call.func.value, ast.Name) \
                    and call.func.value.id == "self":
                return call.func.attr
            return None
        if isinstance(call.func, ast.Name):
            return call.func.id
        return None

    def walk(nodes: Iterable[ast.AST], held: Tuple[str, ...]) -> None:
        for node in nodes:
            if isinstance(node, ast.With):
                now = list(held)
                for item in node.items:
                    lk = _acquired_lock(item, locks, self_based)
                    if lk:
                        # Edges from EVERYTHING currently held — including
                        # locks acquired earlier in this same multi-item
                        # statement (`with self._a, self._b:` orders a
                        # before b exactly like nesting does).
                        for h in now:
                            graph.add(h, lk, node.lineno)
                        now.append(lk)
                walk(ast.iter_child_nodes(node), tuple(now))
                continue
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                walk(ast.iter_child_nodes(node), ())
                continue
            if isinstance(node, ast.Call) and held:
                name = callee_name(node)
                if name is not None:
                    for b in acquires.get(name, ()):
                        for h in held:
                            graph.add(h, b, node.lineno)
            walk(ast.iter_child_nodes(node), held)

    body = fn.body if isinstance(fn, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)) else [fn]
    walk(iter(body), ())


def _transitive_acquires(fns: Dict[str, ast.AST], locks: Set[str],
                         self_based: bool) -> Dict[str, Set[str]]:
    """fn name -> locks it may acquire, directly or via same-scope calls
    (fixpoint over the one-scope call graph)."""
    acq = {name: _direct_acquisitions(fn, locks, self_based)
           for name, fn in fns.items()}
    calls: Dict[str, Set[str]] = {}
    for name, fn in fns.items():
        out: Set[str] = set()
        for node in _walk_shallow(fn):
            if not isinstance(node, ast.Call):
                continue
            if self_based:
                if isinstance(node.func, ast.Attribute) \
                        and isinstance(node.func.value, ast.Name) \
                        and node.func.value.id == "self" \
                        and node.func.attr in fns:
                    out.add(node.func.attr)
            elif isinstance(node.func, ast.Name) and node.func.id in fns:
                out.add(node.func.id)
        calls[name] = out
    changed = True
    while changed:
        changed = False
        for name in fns:
            before = len(acq[name])
            for callee in calls[name]:
                acq[name] |= acq[callee]
            if len(acq[name]) != before:
                changed = True
    return acq


def _check_lock_order(path: str, tree: ast.Module) -> List[Finding]:
    findings: List[Finding] = []
    graphs: List[_LockGraph] = []

    # Module-level locks + top-level functions.
    mlocks = _module_locks(tree)
    if mlocks:
        fns = {n.name: n for n in tree.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        graph = _LockGraph("<module>", path)
        acq = _transitive_acquires(fns, mlocks, self_based=False)
        for fn in fns.values():
            _scan_order_edges(fn, mlocks, False, acq, graph)
        graphs.append(graph)

    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        locks, rlocks = _lock_attrs_of_class(node)
        if not locks:
            continue
        methods = {m.name: m for m in node.body
                   if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))}
        graph = _LockGraph(node.name, path)
        graph.rlocks = rlocks
        acq = _transitive_acquires(methods, locks, self_based=True)
        for m in methods.values():
            _scan_order_edges(m, locks, True, acq, graph)
        graphs.append(graph)

    for graph in graphs:
        for trail, lineno in graph.cycles():
            pretty = " -> ".join(f"{graph.owner}.{n}" for n in trail)
            if len(trail) == 2 and trail[0] == trail[1]:
                msg = (f"{graph.owner}.{trail[0]} re-acquired while "
                       f"already held (non-reentrant Lock/Condition): "
                       f"the thread deadlocks on itself; use one "
                       f"acquisition or an RLock with a rationale")
            else:
                msg = (f"lock-order cycle {pretty}: two threads entering "
                       f"from different edges deadlock; pick ONE global "
                       f"acquisition order and restructure the inner "
                       f"acquisition")
            findings.append(Finding("lock-cycle", path, lineno, msg))
    return findings


# -- use-after-donate ---------------------------------------------------------

def _donated_dispatchers(cls: ast.ClassDef) -> Dict[str, Tuple[int, ...]]:
    """Dispatcher attrs assigned from a donating jit wrapper:
    {attr: donated arg positions}. Matches ``self._f = jax.jit(fn,
    donate_argnums=(1, 2))`` and ``self._f = self._jit_island(fn, ...,
    donate=(1, 2))`` (literal int tuples only — what the repo writes)."""
    out: Dict[str, Tuple[int, ...]] = {}
    for node in ast.walk(cls):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            continue
        call = node.value
        wrapper = _terminal_name(call.func)
        kw_name = _DONATING_WRAPPERS.get(wrapper or "")
        if kw_name is None:
            continue
        positions: Tuple[int, ...] = ()
        for kw in call.keywords:
            if kw.arg == kw_name and isinstance(kw.value, (ast.Tuple,
                                                           ast.List)):
                try:
                    positions = tuple(int(ast.literal_eval(e))
                                      for e in kw.value.elts)
                except (ValueError, TypeError):
                    positions = ()
        for tgt in node.targets:
            attr = _self_attr(tgt)
            if attr is None:
                continue
            if attr in out:
                # The same dispatcher attr assigned on several
                # construction branches (e.g. the paged vs contiguous
                # prefill): only positions donated on EVERY branch are
                # certainly donated — a union would indict whatever
                # rides that position on the other branch. A branch
                # that donates NOTHING (no/empty donate kwarg on the
                # same jit wrapper) empties the intersection.
                out[attr] = tuple(p for p in out[attr] if p in positions)
            else:
                out[attr] = positions
    return {attr: pos for attr, pos in out.items() if pos}


def _donated_attrs(cls: ast.ClassDef,
                   dispatchers: Dict[str, Tuple[int, ...]]) -> Set[str]:
    """self attrs passed at donated positions of any dispatcher call."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Call):
            continue
        callee = _self_attr(node.func)
        if callee not in dispatchers:
            continue
        for pos in dispatchers[callee]:
            if pos < len(node.args):
                attr = _self_attr(node.args[pos])
                if attr is not None:
                    out.add(attr)
    return out


def _check_use_after_donate(path: str, tree: ast.Module) -> List[Finding]:
    findings: List[Finding] = []
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        dispatchers = _donated_dispatchers(cls)
        if not dispatchers:
            continue
        donated = _donated_attrs(cls, dispatchers)
        if not donated:
            continue
        parents = _parents(cls)
        methods = [m for m in cls.body
                   if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for m in methods:
            if m.name == "__init__":
                continue
            # Step-path / lifecycle exemption: a method that dispatches
            # (and so rebinds the donated attrs from the results) or
            # rebinds the attr itself owns the buffer's lifetime.
            dispatches = any(
                isinstance(n, ast.Call) and _self_attr(n.func) in dispatchers
                for n in ast.walk(m))
            rebinds: Set[str] = set()
            for n in ast.walk(m):
                attr = _self_attr(n)
                if attr in donated and isinstance(n.ctx, ast.Store):
                    rebinds.add(attr)
            if dispatches:
                continue
            for n in ast.walk(m):
                attr = _self_attr(n)
                if attr not in donated or not isinstance(n.ctx, ast.Load):
                    continue
                if attr in rebinds:
                    continue
                parent = parents.get(id(n))
                if isinstance(parent, ast.Attribute) \
                        and parent.attr in _METADATA_ATTRS:
                    continue      # .shape/.dtype — aval metadata, no device read
                if isinstance(parent, ast.Compare) and all(
                        isinstance(op, (ast.Is, ast.IsNot))
                        for op in parent.ops):
                    continue      # `self._ks is not None` — identity only
                if isinstance(parent, ast.Call) \
                        and parent.func is n:
                    continue      # calling it — not an array read
                findings.append(Finding(
                    "use-after-donate", path, n.lineno,
                    f"{cls.name}.{m.name} reads self.{attr}, which aliases "
                    f"a buffer DONATED on every dispatch "
                    f"({'/'.join(sorted(dispatchers))}): a read racing a "
                    f"step hits a deleted array and dies (the "
                    f"pool_metrics scrape-race class); read a host "
                    f"mirror / build-time constant instead, or suppress "
                    f"with the step-boundary rationale"))
    return findings


# -- torn-snapshot ------------------------------------------------------------

def _guarded_attrs(cls: ast.ClassDef, locks: Set[str]) -> Dict[str, Set[str]]:
    """lock attr -> self attrs WRITTEN under it (the astlint pass-1
    signal, recomputed here so the two passes cannot drift apart on
    import order)."""
    guarded: Dict[str, Set[str]] = {lk: set() for lk in locks}

    def written_attr(node: ast.AST) -> Optional[str]:
        attr = _self_attr(node)
        if attr is not None and isinstance(node.ctx, (ast.Store, ast.Del)):
            return attr
        if isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, (ast.Store, ast.Del)):
            return _self_attr(node.value)
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATORS:
            return _self_attr(node.func.value)
        return None

    for node in ast.walk(cls):
        if not isinstance(node, ast.With):
            continue
        held = {_self_attr(item.context_expr) for item in node.items}
        held &= locks
        if not held:
            continue
        for inner in _walk_shallow(node):
            attr = written_attr(inner)
            if attr and attr not in locks:
                for lk in held:
                    guarded[lk].add(attr)
    return guarded


def _check_torn_snapshot(path: str, tree: ast.Module) -> List[Finding]:
    findings: List[Finding] = []
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        locks, _ = _lock_attrs_of_class(cls)
        if not locks:
            continue
        guarded = _guarded_attrs(cls, locks)
        for m in cls.body:
            if not isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if m.name == "__init__" or m.name.endswith("_locked"):
                continue
            # with-blocks per lock, NOT descending into nested defs.
            per_lock: Dict[str, List[ast.With]] = {}
            for node in _walk_shallow(m):
                if not isinstance(node, ast.With):
                    continue
                for item in node.items:
                    lk = _self_attr(item.context_expr)
                    if lk in locks:
                        per_lock.setdefault(lk, []).append(node)
            for lk, blocks in per_lock.items():
                if len(blocks) < 2:
                    continue
                touching = []
                for blk in sorted(blocks, key=lambda b: b.lineno):
                    # Reads only — and a Load that is merely the receiver
                    # of a mutating call (`self._x.discard(k)`) is the
                    # write-back half of check-then-act, not a snapshot
                    # read.
                    mut_receivers = {
                        id(n.func.value) for n in _walk_shallow(blk)
                        if isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)
                        and n.func.attr in _MUTATORS}
                    attrs = {a for n in _walk_shallow(blk)
                             for a in [_self_attr(n)]
                             if a and isinstance(n.ctx, ast.Load)
                             and id(n) not in mut_receivers
                             } & guarded.get(lk, set())
                    if attrs:
                        touching.append((blk, attrs))
                distinct = set().union(*(a for _, a in touching)) \
                    if touching else set()
                # The torn-SNAPSHOT class is a multi-gauge read split
                # across acquisitions. One attr across two blocks is the
                # idiomatic check-then-act / fill-cache shape (compute
                # outside the lock, write back) — a different, sound
                # pattern.
                if len(touching) >= 2 and len(distinct) >= 2:
                    blk, attrs = touching[1]
                    first = touching[0][0].lineno
                    findings.append(Finding(
                        "torn-snapshot", path, blk.lineno,
                        f"{cls.name}.{m.name} drains/reads "
                        f"{sorted(attrs)} under a SECOND acquisition of "
                        f"self.{lk} (first at line {first}): the two "
                        f"blocks observe different instants — a scrape "
                        f"between them pairs this step's gauges with "
                        f"last step's; take ONE lock snapshot (the PR 7 "
                        f"exporter torn-read class)"))
    return findings


# -- driver -------------------------------------------------------------------

def lint_lockorder_source(path: str, source: str,
                          tree: Optional[ast.Module] = None,
                          ) -> List[Finding]:
    """``tree`` lets run_fast_passes share ONE ast.parse per file across
    the AST and lock-order passes."""
    if tree is None:
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError:
            return []      # astlint already reports the syntax error
    findings = (_check_lock_order(path, tree)
                + _check_use_after_donate(path, tree)
                + _check_torn_snapshot(path, tree))
    return apply_suppressions(findings, parse_suppressions(source))


def run_lockorder(paths: Iterable[str]) -> List[Finding]:
    findings: List[Finding] = []
    for path in iter_python_files(paths):
        with open(path, "r", encoding="utf-8") as fh:
            findings.extend(lint_lockorder_source(path, fh.read()))
    return findings
