"""Recompile guard — jit cache-miss accounting + donation verification.

Steady-state retracing is the quietest way to lose 10-100x serving
throughput: the program still produces correct tokens, every dispatch
just pays trace+compile again because a shape, a weak type, or a Python
scalar changed identity. The guard makes that a test failure:

- ``RecompileGuard`` wraps/adopts a jitted callable and exposes the jit
  cache size (``jax.jit``'s ``_cache_size``) as a miss counter:
  ``snapshot()`` then ``misses_since()`` bounds a steady-state region.
- ``assert_no_retrace`` is the context-manager form: any tracked entry
  point that retraces inside the block raises with the per-entry delta.
- Donation verification: XLA tells us two ways when a ``donate_argnums``
  contract silently broke — the "Some donated buffers were not usable"
  warning at dispatch, and the donated input buffer NOT being deleted
  afterwards. ``check_donation`` captures both.

The tier-1 hook is the ``recompile_guard`` pytest fixture
(tests/conftest.py) built on these; the CLI's dynamic pass
(analysis/__main__.py --recompile) runs the same steady-state-decode
check over the serving entry points.
"""
from __future__ import annotations

import contextlib
import warnings
from typing import Callable, Dict, List, Optional, Sequence

from .findings import Finding


def _cache_size(jitted) -> Optional[int]:
    """jit cache entry count, or None when the callable does not expose
    it (not a jax.jit product)."""
    probe = getattr(jitted, "_cache_size", None)
    if probe is None:
        return None
    try:
        return int(probe())
    except Exception:  # noqa: BLE001 — treat as untrackable
        return None


class RecompileGuard:
    """Track jit cache misses for a set of named jitted callables."""

    def __init__(self) -> None:
        self._tracked: Dict[str, object] = {}
        self._marks: Dict[str, int] = {}

    def track(self, name: str, jitted) -> None:
        if _cache_size(jitted) is None:
            raise TypeError(
                f"{name}: not a trackable jitted callable (no _cache_size); "
                f"pass the jax.jit product itself, not a plain function")
        self._tracked[name] = jitted

    @property
    def snapshotted(self) -> bool:
        """True once snapshot() has run — teardown hooks key off this
        instead of reaching into internals."""
        return bool(self._marks)

    def snapshot(self) -> Dict[str, int]:
        self._marks = {n: _cache_size(f) or 0
                       for n, f in self._tracked.items()}
        return dict(self._marks)

    def misses_since(self) -> Dict[str, int]:
        # max(0, ...): a cache cleared/evicted between snapshot and check
        # (jax.clear_caches) yields a negative delta, which is not a
        # retrace.
        return {n: max(0, (_cache_size(f) or 0) - self._marks.get(n, 0))
                for n, f in self._tracked.items()}

    def assert_steady_state(self) -> None:
        misses = {n: d for n, d in self.misses_since().items() if d > 0}
        if misses:
            raise AssertionError(
                f"steady-state retrace detected (jit cache misses since "
                f"snapshot): {misses} — a shape/dtype/static-arg changed "
                f"identity between dispatches")


@contextlib.contextmanager
def assert_no_retrace(named: Dict[str, object]):
    """``with assert_no_retrace({'decode': eng._decode}): ...`` — raises
    AssertionError on exit if any tracked entry point retraced inside."""
    guard = RecompileGuard()
    for name, fn in named.items():
        guard.track(name, fn)
    guard.snapshot()
    yield guard
    guard.assert_steady_state()


_DONATION_WARNING = "donated buffers were not usable"


def check_donation_leaves(jitted, args: tuple, leaves: Sequence,
                          name: str = "fn") -> List[Finding]:
    """Dispatch ``jitted(*args)`` and verify the donation contract for the
    given donated buffers (already-flattened leaves): no 'not usable'
    warning during the call, and every leaf actually deleted afterwards —
    an aliasing/sharding mismatch leaves it alive, the silent un-donation
    this audits for. The call's result is discarded; callers pass
    throwaway inputs."""
    findings: List[Finding] = []
    anchor = f"<donation:{name}>"
    probeable = [buf for buf in leaves
                 if getattr(buf, "is_deleted", None) is not None]
    if leaves and not probeable:
        # Nothing to verify is itself a finding: host/numpy arrays have no
        # deletion state, so a "clean" result would mean the audit checked
        # nothing at all.
        return [Finding(
            "donation-unverifiable", anchor, 0,
            f"{name}: none of the {len(leaves)} donated leaves expose "
            f"is_deleted — pass device buffers, not host arrays")]
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        jitted(*args)
    for w in caught:
        if _DONATION_WARNING in str(w.message):
            findings.append(Finding(
                "donation-broken", anchor, 0,
                f"{name}: {w.message}"))
    alive = [buf for buf in probeable if not buf.is_deleted()]
    for buf in alive:
        findings.append(Finding(
            "donation-broken", anchor, 0,
            f"{name}: donated buffer ({getattr(buf, 'shape', '?')}, "
            f"{getattr(buf, 'dtype', '?')}) was NOT consumed — still alive "
            f"after dispatch, so every call holds two full copies"))
    return findings


def check_donation(jitted, *args, donated: Sequence[int],
                   name: str = "fn") -> List[Finding]:
    """``check_donation_leaves`` keyed by positional argument index."""
    return check_donation_leaves(
        jitted, args, [args[pos] for pos in donated], name=name)


def audit_steady_state(build: Callable[[], tuple],
                       name: str) -> List[Finding]:
    """Run one (warmup_fn, steady_fns, tracked) scenario from ``build``:
    ``warmup_fn()`` compiles everything, then each fn in ``steady_fns``
    runs with retraces counted across the named ``tracked`` jitted
    callables. Used by the CLI's --recompile pass; exceptions become
    findings so a broken scenario cannot mask the others."""
    anchor = f"<recompile:{name}>"
    try:
        warmup_fn, steady_fns, tracked = build()
        warmup_fn()
        guard = RecompileGuard()
        for n, f in tracked.items():
            guard.track(n, f)
        guard.snapshot()
        for fn in steady_fns:
            fn()
        misses = {n: d for n, d in guard.misses_since().items() if d}
    except Exception as e:  # noqa: BLE001 — report, keep auditing
        return [Finding("recompile-guard", anchor, 0,
                        f"scenario {name} failed to run: "
                        f"{type(e).__name__}: {str(e)[:300]}")]
    if misses:
        return [Finding(
            "steady-state-retrace", anchor, 0,
            f"{name}: jit cache misses after warmup: {misses} — "
            f"steady-state decode must not retrace")]
    return []
