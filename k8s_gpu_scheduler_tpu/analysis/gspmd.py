"""GSPMD sharding-annotation audit — graftcheck's eighth pass.

Sharding bugs are the quietest perf/memory class in a GSPMD program: a
cache constrained on the wrong dim still produces correct tokens (XLA
inserts collectives to fix up the mismatch), a scan carry with no
constraint silently replicates the KV cache onto every chip, and a
shard_map island whose pool operand is mapped on the wrong axis ships
the whole pool through ICI every dispatch. None of it fails a test; all
of it shows up as "the 70B config OOMs" months later. This pass walks
the traced jaxpr of every sharded entry point (tracing only — no
compilation, so it is cheap enough for ``make lint``) and checks the
annotations against the ONE rules table the models declare their specs
from (parallel/sharding.py):

- ``cache-spec-mismatch`` / ``cache-spec-missing``: every
  ``sharding_constraint`` on a rank-5 operand (the KV cache/pool rank —
  the repo convention this pass enforces) must carry exactly
  ``serving.CACHE_SPEC``; decode entry points registered with
  ``cache_spec=True`` must have at least one.
- ``island-pool-spec`` / ``island-missing``: entry points registered
  with ``pool_spec=True`` are shard_map islands over the paged pool —
  every rank-5 island operand must be mapped on the KV-HEADS dim (axis
  3) to the ``tp`` mesh axis and nothing else (``POOL_SPEC``); an entry
  with no island at all is flagged too (the gate that the sharded path
  didn't silently degrade to a replicated dispatch).
- ``unconstrained-scan-carry``: a big (> ``CARRY_ELEMS_LIMIT``) scan
  carry OUTSIDE any island whose shape is never sharding-constrained
  anywhere in the program — GSPMD propagates whatever it likes through
  the loop, usually full replication of the largest buffer in the
  program. Island-internal scans are exempt: the island's specs already
  pin their layout per shard.
- ``oversized-replicated``: an explicitly replicated annotation (an
  all-``None`` constraint, or an unmapped island operand) on a buffer
  bigger than ``REPLICATED_BYTES_LIMIT`` — replication is the default,
  ANNOTATING it on something huge is almost always a wrong spec.
- ``unknown-mesh-axis``: a constraint naming a mesh axis outside the
  rules table's vocabulary (dp/fsdp/sp/ep/tp) — a typo'd axis silently
  replicates.

Entry points come from ``entrypoints.gspmd_entrypoints()``; out-of-tree
code (and the seeded bad fixture) opts in via a module-level
``GRAFTCHECK_GSPMD_AUDIT = [(name, fn, args, expect), ...]`` hook, the
same discovery protocol as the other traced hooks.

Thresholds follow the repo's audit convention (see jaxpr_audit): entry
points trace at TOY shapes, so anything that scales with the model —
including the serving islands' deliberately replicated weight operands —
stays far below the limits, and only a genuinely suspicious tensor
crosses them. A hook registering REAL-model shapes must either pass
``replicated_bytes_limit``/``carry_elems_limit`` overrides or expect the
replicated-weights layout to be flagged (at real scale, a >1 MiB
replicated island operand usually IS the bug this rule hunts).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from .findings import Finding

CARRY_ELEMS_LIMIT = 1 << 15        # 32k-element scan carry
REPLICATED_BYTES_LIMIT = 1 << 20   # 1 MiB explicitly-replicated buffer
CACHE_RANK = 5                     # [L, B|n_pages, S|ps, Hkv, hd]


def _known_mesh_axes() -> Set[str]:
    """The mesh-axis vocabulary every annotation must draw from — the
    VALUES of parallel/sharding.py's rules table, read at audit time so
    a new axis added to the table is automatically legal here."""
    from ..parallel.sharding import DEFAULT_RULES

    axes: Set[str] = set()
    for v in DEFAULT_RULES.values():
        if v is None:
            continue
        if isinstance(v, (tuple, list)):
            axes.update(str(a) for a in v)
        else:
            axes.add(str(v))
    return axes


def _expected_pool_mapping() -> Dict[int, Tuple[str, ...]]:
    """The shard_map in_names mapping a pool operand must carry —
    derived from the SAME rules-table entry the serving islands derive
    POOL_SPEC from (`spec_for(KV_POOL_AXES, DEFAULT_RULES)`), so the
    runtime and this guard rail cannot drift: {3: ('tp',)} under the
    default rules."""
    from ..parallel.sharding import DEFAULT_RULES, KV_POOL_AXES, spec_for

    out: Dict[int, Tuple[str, ...]] = {}
    for i, e in enumerate(spec_for(KV_POOL_AXES, DEFAULT_RULES)):
        if e is None:
            continue
        out[i] = (tuple(str(a) for a in e)
                  if isinstance(e, (tuple, list)) else (str(e),))
    return out


def _norm_spec(spec, rank: int) -> Tuple[Tuple[str, ...], ...]:
    """PartitionSpec → length-``rank`` tuple of mesh-axis tuples (() =
    replicated dim), so trailing-None-trimmed and untrimmed specs
    compare equal."""
    out = []
    n = len(spec) if spec is not None else 0
    for i in range(rank):
        e = spec[i] if i < n else None
        if e is None:
            out.append(())
        elif isinstance(e, (tuple, list)):
            out.append(tuple(str(a) for a in e))
        else:
            out.append((str(e),))
    return tuple(out)


def _expected_cache_spec() -> Tuple[Tuple[str, ...], ...]:
    from ..models.serving import CACHE_SPEC

    return _norm_spec(CACHE_SPEC, CACHE_RANK)


def _expected_weight_mapping() -> Tuple[str, Dict[str, int]]:
    """(tp axis name, {kind: sliced dim}) for Megatron-sliced serving
    weights — derived from the SAME parallel/sharding.py WEIGHT_SPECS
    table serving builds its per-leaf specs from (column slices the
    output axis of the stacked [L, K, N] layout, row the input axis),
    so the runtime and this guard rail cannot drift."""
    from ..parallel.sharding import (
        DEFAULT_RULES, WEIGHT_COLUMN_DIM, WEIGHT_ROW_DIM, WEIGHT_SPECS,
    )

    dims = {"column": WEIGHT_COLUMN_DIM, "row": WEIGHT_ROW_DIM}
    return str(DEFAULT_RULES["kv_heads"]), {
        kind: dims[kind] for kind in set(WEIGHT_SPECS.values())}


def _spec_axes(norm) -> Set[str]:
    return {a for dim in norm for a in dim}


def _iter_subjaxprs(params: dict):
    """(param_key, jaxpr) for every sub-jaxpr in an eqn's params —
    shared shape with jaxpr_audit's walker."""
    import jax.core as jc

    for key, val in params.items():
        vals = val if isinstance(val, (tuple, list)) else [val]
        for v in vals:
            if isinstance(v, jc.ClosedJaxpr):
                yield key, v.jaxpr
            elif isinstance(v, jc.Jaxpr):
                yield key, v


def audit_sharded_jaxpr(closed, name: str, cache_spec: bool = False,
                        pool_spec: bool = False,
                        weight_specs: bool = False,
                        carry_elems_limit: int = CARRY_ELEMS_LIMIT,
                        replicated_bytes_limit: int = REPLICATED_BYTES_LIMIT,
                        ) -> List[Finding]:
    """Audit one ClosedJaxpr (``jax.make_jaxpr(fn)(*args)``) against the
    GSPMD rules. ``cache_spec``/``pool_spec`` assert the entry-point
    expectations described in the module docstring."""
    anchor = f"<gspmd:{name}>"
    findings: List[Finding] = []
    expected_cache = _expected_cache_spec()
    known_axes = _known_mesh_axes()
    expected_pool = _expected_pool_mapping()

    constrained_shapes: Set[tuple] = set()
    cache_constraints: List[Tuple[tuple, tuple]] = []   # (shape, norm spec)
    islands: List[Any] = []
    scans: List[Tuple[Any, bool]] = []                  # (eqn, in_island)

    def collect(jaxpr, in_island: bool) -> None:
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            if prim == "sharding_constraint":
                aval = eqn.invars[0].aval
                shd = eqn.params.get("sharding")
                spec = getattr(shd, "spec", None)
                if spec is None:
                    # A non-Named sharding (GSPMD/HLO-level): nothing to
                    # compare against the rules table — surface it so it
                    # cannot hide a wrong layout behind an opaque type.
                    findings.append(Finding(
                        "opaque-sharding", anchor, 0,
                        f"{name}: sharding_constraint on "
                        f"{tuple(aval.shape)} carries a "
                        f"{type(shd).__name__}, not a NamedSharding — "
                        f"the rules-table audit cannot see it",
                        severity="warning"))
                    continue
                norm = _norm_spec(spec, len(aval.shape))
                constrained_shapes.add(tuple(aval.shape))
                bad_axes = _spec_axes(norm) - known_axes
                if bad_axes:
                    findings.append(Finding(
                        "unknown-mesh-axis", anchor, 0,
                        f"{name}: constraint on {tuple(aval.shape)} names "
                        f"mesh axes {sorted(bad_axes)} outside the rules "
                        f"table (dp/fsdp/sp/ep/tp) — a typo'd axis "
                        f"silently replicates"))
                if len(aval.shape) == CACHE_RANK:
                    cache_constraints.append((tuple(aval.shape), norm))
                    if norm != expected_cache:
                        findings.append(Finding(
                            "cache-spec-mismatch", anchor, 0,
                            f"{name}: rank-5 cache constraint on "
                            f"{tuple(aval.shape)} is {norm}, expected "
                            f"CACHE_SPEC {expected_cache} — a mis-specced "
                            f"cache still decodes correctly while XLA "
                            f"reshuffles it every step"))
                if not _spec_axes(norm) \
                        and aval.size * aval.dtype.itemsize \
                        > replicated_bytes_limit:
                    findings.append(Finding(
                        "oversized-replicated", anchor, 0,
                        f"{name}: {tuple(aval.shape)} "
                        f"({aval.size * aval.dtype.itemsize / 2**20:.1f} "
                        f"MiB) explicitly constrained fully-replicated — "
                        f"annotating replication on a buffer this big is "
                        f"almost always a wrong spec"))
            elif prim == "shard_map":
                islands.append(eqn)
                in_names = eqn.params.get("in_names") or ()
                for var, names in zip(eqn.invars, in_names):
                    aval = var.aval
                    mapped = {int(d): tuple(str(a) for a in ax)
                              for d, ax in dict(names).items()}
                    if mapped:
                        constrained_shapes.add(tuple(aval.shape))
                    elif aval.size * aval.dtype.itemsize \
                            > replicated_bytes_limit:
                        findings.append(Finding(
                            "oversized-replicated", anchor, 0,
                            f"{name}: shard_map operand "
                            f"{tuple(aval.shape)} "
                            f"({aval.size * aval.dtype.itemsize / 2**20:.1f}"
                            f" MiB) is unmapped — replicated onto every "
                            f"chip of the island"))
            elif prim == "scan":
                scans.append((eqn, in_island))

            for key, sub in _iter_subjaxprs(eqn.params):
                collect(sub, in_island or prim == "shard_map")

    collect(closed.jaxpr, in_island=False)

    if cache_spec and not any(norm == expected_cache
                              for _, norm in cache_constraints):
        findings.append(Finding(
            "cache-spec-missing", anchor, 0,
            f"{name}: decode entry point registered with cache_spec=True "
            f"has no rank-5 sharding_constraint matching CACHE_SPEC "
            f"{expected_cache} — the cache's sharding is left to GSPMD "
            f"propagation"))

    if pool_spec:
        pool_ok = 0
        for eqn in islands:
            in_names = eqn.params.get("in_names") or ()
            for var, names in zip(eqn.invars, in_names):
                if len(var.aval.shape) != CACHE_RANK:
                    continue
                mapped = {int(d): tuple(str(a) for a in ax)
                          for d, ax in dict(names).items()}
                if mapped == expected_pool:
                    pool_ok += 1
                else:
                    findings.append(Finding(
                        "island-pool-spec", anchor, 0,
                        f"{name}: island pool operand "
                        f"{tuple(var.aval.shape)} mapped {mapped}, "
                        f"expected the kv-heads dim only "
                        f"{expected_pool} (POOL_SPEC, from the rules "
                        f"table) — any other mapping splits pages or "
                        f"layers across chips and the host block tables "
                        f"stop addressing them"))
        if not islands:
            findings.append(Finding(
                "island-missing", anchor, 0,
                f"{name}: entry point registered with pool_spec=True "
                f"contains no shard_map island — the sharded dispatch "
                f"degraded to a replicated program"))
        elif not pool_ok and not any(f.rule == "island-pool-spec"
                                     for f in findings):
            findings.append(Finding(
                "island-pool-spec", anchor, 0,
                f"{name}: island carries no rank-5 pool operand mapped "
                f"{expected_pool} — the pool is not sharded through "
                f"the island"))

    if weight_specs:
        # Megatron-sliced serving weights (WEIGHT_SPECS): every rank-3
        # [L, K, N] weight operand of an island must be mapped on
        # exactly ONE of its two matmul dims to the tp axis — column
        # slices the output axis, row the input axis — and across the
        # entry BOTH kinds must appear (a q/k/v-only slicing still
        # replicates o/down). Scale planes ([L, 1, N]) are exempt via
        # the min > 1 guard; shapes are never consulted beyond rank, so
        # toy-scale dim collisions (d == H·hd) cannot blind the check.
        tp_axis, kind_dims = _expected_weight_mapping()
        legal_dims = set(kind_dims.values())
        seen_dims: Set[int] = set()
        for eqn in islands:
            in_names = eqn.params.get("in_names") or ()
            for var, names in zip(eqn.invars, in_names):
                shape = var.aval.shape
                if len(shape) != 3 or min(int(shape[1]),
                                          int(shape[2])) <= 1:
                    continue
                mapped = {int(d): tuple(str(a) for a in ax)
                          for d, ax in dict(names).items()}
                if not mapped:
                    findings.append(Finding(
                        "island-weight-spec", anchor, 0,
                        f"{name}: island weight operand {tuple(shape)} "
                        f"is unmapped — a REPLICATED weight inside a "
                        f"weight-sharded island: per-chip weight bytes "
                        f"do not scale 1/tp"))
                    continue
                dims = set(mapped)
                if (len(dims) != 1 or not dims <= legal_dims
                        or any(ax != (tp_axis,)
                               for ax in mapped.values())):
                    findings.append(Finding(
                        "island-weight-spec", anchor, 0,
                        f"{name}: island weight operand {tuple(shape)} "
                        f"mapped {mapped}, expected exactly one of dims "
                        f"{sorted(legal_dims)} on ('{tp_axis}',) "
                        f"(WEIGHT_SPECS: column → output axis "
                        f"{kind_dims.get('column')}, row → input axis "
                        f"{kind_dims.get('row')})"))
                    continue
                seen_dims |= dims
        missing = legal_dims - seen_dims
        if islands and missing and not any(
                f.rule == "island-weight-spec" for f in findings):
            findings.append(Finding(
                "island-weight-spec", anchor, 0,
                f"{name}: entry registered with weight_specs=True but "
                f"no island weight operand is sliced on dim(s) "
                f"{sorted(missing)} — "
                + ("no weights ride the island sliced at all"
                   if not seen_dims else
                   "one Megatron half is missing (column AND row "
                   "slices must both appear)")))

    for eqn, in_island in scans:
        if in_island:
            continue
        num_consts = eqn.params.get("num_consts", 0)
        num_carry = eqn.params.get("num_carry", 0)
        for var in eqn.invars[num_consts:num_consts + num_carry]:
            aval = var.aval
            if len(aval.shape) >= 3 and aval.size > carry_elems_limit \
                    and tuple(aval.shape) not in constrained_shapes:
                findings.append(Finding(
                    "unconstrained-scan-carry", anchor, 0,
                    f"{name}: scan carries {tuple(aval.shape)} "
                    f"({aval.size} elements) with no sharding constraint "
                    f"anywhere in the program — GSPMD free-propagates "
                    f"through the loop, typically replicating the "
                    f"largest buffer in the program onto every chip"))
    return findings


def audit_sharded_callable(fn, args: Sequence, name: str,
                           **expect) -> List[Finding]:
    """Trace ``fn(*args)`` and audit the result; tracing failures become
    findings so one broken entry point cannot hide the others."""
    import jax

    try:
        closed = jax.make_jaxpr(fn)(*args)
    except Exception as e:  # noqa: BLE001 — report, keep auditing
        return [Finding("gspmd-trace-error", f"<gspmd:{name}>", 0,
                        f"could not trace {name}: {type(e).__name__}: "
                        f"{str(e)[:300]}")]
    return audit_sharded_jaxpr(closed, name, **expect)
