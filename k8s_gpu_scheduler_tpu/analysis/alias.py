"""Shared-page write audit — the prefix cache's copy-on-write rule with
teeth.

The radix prefix cache (models/prefix_cache.py) lets one physical KV
page back the block tables of many slots at once; correctness rests on a
single invariant the type system cannot see: **a shared page is never
written**. The engine upholds it by construction (decode scatters at
``lens`` which always points past the mounted prefix; the tail-prefill
scatter receives only the slot's OWN page ids), but "by construction"
is one refactor away from silent KV cross-contamination — the bug class
where request B's system prompt suddenly contains request A's decode
rows and every affected stream corrupts with no crash.

This pass makes the invariant observable: a scenario declares which pool
pages are shared, the audit snapshots those pages, dispatches the real
jitted function once, and byte-compares the pages in the returned pool.
Any difference is a ``shared-page-write`` finding (error severity).

Scenario contract (``build()`` return value, also the
``GRAFTCHECK_ALIAS_AUDIT`` hook protocol — a list of ``(name, build)``
pairs):

    (fn, args, pool_argnums, pool_outnums, shared_pages)

``fn(*args)`` must return a tuple; ``pool_argnums[i]`` is the position
of a pool operand in ``args`` and ``pool_outnums[i]`` the position of
its updated value in the result; pools index pages on AXIS 1 (the
``[L, n_pages, page_size, ...]`` serving layout). Inputs are snapshotted
before the call, so donated pools are fine; callers pass throwaway
engines/args like the donation audit does.
"""
from __future__ import annotations

from typing import Callable, List, Sequence

from .findings import Finding


def check_shared_pages(fn, args: tuple, pool_argnums: Sequence[int],
                       pool_outnums: Sequence[int],
                       shared: Sequence[int],
                       name: str = "fn") -> List[Finding]:
    """Dispatch ``fn(*args)`` once and verify every declared shared page
    of every declared pool operand is byte-identical in the returned
    pool. Shared page ids must be non-empty — a vacuous audit would read
    as a clean COW bill of health while checking nothing."""
    import numpy as np

    anchor = f"<alias:{name}>"
    shared = sorted(int(p) for p in shared)
    if not shared:
        return [Finding(
            "alias-guard", anchor, 0,
            f"{name}: no shared pages declared — the audit verified "
            f"nothing")]
    if len(pool_argnums) != len(pool_outnums):
        return [Finding(
            "alias-guard", anchor, 0,
            f"{name}: {len(pool_argnums)} pool args vs "
            f"{len(pool_outnums)} pool outputs")]
    before = [np.array(np.asarray(args[i])[:, shared]) for i in pool_argnums]
    out = fn(*args)
    findings: List[Finding] = []
    for argnum, outnum, snap in zip(pool_argnums, pool_outnums, before):
        after = np.asarray(out[outnum])[:, shared]
        if snap.shape != after.shape:
            findings.append(Finding(
                "alias-guard", anchor, 0,
                f"{name}: pool arg {argnum} -> out {outnum} changed shape "
                f"{snap.shape} -> {after.shape}"))
            continue
        changed = [p for j, p in enumerate(shared)
                   if not np.array_equal(snap[:, j], after[:, j])]
        if changed:
            findings.append(Finding(
                "shared-page-write", anchor, 0,
                f"{name}: pool arg {argnum} WROTE shared page(s) "
                f"{changed} — aliased prefix pages are read-only by the "
                f"copy-on-write contract; a write corrupts every slot "
                f"sharing them"))
    return findings


def audit_shared_pages(build: Callable[[], tuple],
                       name: str) -> List[Finding]:
    """Run one alias scenario from ``build`` (see the module docstring
    for the contract). Exceptions become findings so a broken scenario
    cannot mask the others — mirroring recompile.audit_steady_state."""
    anchor = f"<alias:{name}>"
    try:
        fn, args, pool_argnums, pool_outnums, shared = build()
        return check_shared_pages(fn, args, pool_argnums, pool_outnums,
                                  shared, name=name)
    except Exception as e:  # noqa: BLE001 — report, keep auditing
        return [Finding("alias-guard", anchor, 0,
                        f"scenario {name} failed to run: "
                        f"{type(e).__name__}: {str(e)[:300]}")]
