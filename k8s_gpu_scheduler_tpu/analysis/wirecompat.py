"""Wire-format schema-compatibility audit — pass 11 (``wirecompat``).

The ROADMAP's cross-process fleet item promotes three in-process pytrees
to the literal wire format: ``ServingSnapshot`` (shed/failover pages +
the JSON-in-uint8 host meta doc), ``ReplicaSummary`` (the registry
heartbeat JSON the placement contract hashes), and the
``RequestJournal`` doc (the replay source of truth). Today their
back-compat guarantees exist as individual hand-written pins — the PR 8
``payload_shape`` default, the PR 16 tier sidecar default, the
default-0 summary fields. This pass turns the property itself into a
contract:

1. **Build** every wire artifact from ``WIRE_ARTIFACTS`` — a registry of
   audit constructors (the ``entrypoints.py`` pattern) producing fully
   populated representative instances (every optional field non-empty,
   so no leaf or doc key can hide).
2. **Extract** the live schema: pytree leaf names + ``dtype[rank]``,
   host-doc/JSON keys + JSON types, and — the part a type signature
   cannot see — whether the *decoder* tolerates each field's absence,
   probed by actually deleting the field and running the real decode
   (``from_pytree``/``from_json``). ``"required": true`` literally means
   "the decoder has no default".
3. **Diff** against the committed goldens in
   ``tests/data/graftcheck/schemas/*.json``. Rules:

   ``wire-break``
       a golden field is gone from the live schema, or its type/rank
       changed — artifacts already in flight (a shed snapshot on the
       wire, a journal checkpoint on disk) stop loading. Renames read
       as remove+add, so a semantics-bearing rename trips this too.
   ``wire-no-default``
       a new live field whose decoder has no default — the NEW decoder
       now rejects OLD artifacts, which is how a rolling fleet upgrade
       bricks itself. The policy (README "wire-format evolution") is
       add-with-default only.
   ``wire-golden-stale``
       any other live≠golden drift (a benign add-with-default, a
       requiredness flip, a missing golden file). Deliberate evolution
       is fine — but the golden must move in the same commit:
       regenerate with ``--update-schemas`` after review. CI asserts
       ``--update-schemas`` is a git no-op, so drift cannot slip
       through even as a warning.

Fixture hook: ``GRAFTCHECK_WIRECOMPAT_AUDIT`` — a module-level list of
``(name, live_schema, golden_schema)`` triples (``live_schema`` may be
a zero-arg callable); how the seeded ``bad_wirecompat.py`` fixture gets
caught if it ever lands in the tree.

Host-only (numpy + json, no tracing, no jax), but it runs with the full
CLI next to gspmd/traffic — schema drift is a review-time event, not a
collection-time one.
"""
from __future__ import annotations

import json
import os
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from .findings import Finding, Report

SCHEMA_VERSION = 1


def default_schema_dir() -> str:
    """tests/data/graftcheck/schemas next to the installed package — the
    committed goldens this pass diffs against."""
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(repo, "tests", "data", "graftcheck", "schemas")


def _json_type(v) -> str:
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "bool"
    if isinstance(v, int):
        return "int"
    if isinstance(v, float):
        return "float"
    if isinstance(v, str):
        return "str"
    if isinstance(v, (list, tuple)):
        return "list"
    if isinstance(v, dict):
        return "object"
    return type(v).__name__


def _decodes(fn: Callable, *args) -> bool:
    try:
        fn(*args)
        return True
    except Exception:  # noqa: BLE001 — ANY decode failure means "required"
        return False


def _doc_to_uint8(doc: dict) -> np.ndarray:
    return np.frombuffer(json.dumps(doc).encode("utf-8"),
                         dtype=np.uint8).copy()


# -- audit constructors --------------------------------------------------
#
# Each builds a fully populated representative artifact: every optional
# field non-empty/non-default so every leaf and doc key appears in the
# live schema (an empty tier sidecar would make tier_k invisible), then
# probes per-field decoder defaults by deletion. Imports are lazy so
# this module stays import-light for the fast CLI path.


def _snapshot_schema() -> dict:
    from ..models.snapshot import ServingSnapshot

    L, R, ps, Hkv, hd = 2, 3, 4, 2, 4
    k = np.arange(L * R * ps * Hkv * hd, dtype=np.int32)
    k = (k % 127 - 63).astype(np.int8).reshape(L, R, ps, Hkv, hd)
    scales = np.linspace(0.5, 2.0, L * R * ps * Hkv).astype(
        np.float32).reshape(L, R, ps, Hkv, 1)
    snap = ServingSnapshot(
        fingerprint={"layout": "paged", "page_size": ps, "n_pages": R,
                     "n_layers": L, "n_kv_heads": Hkv, "head_dim": hd},
        page_ids=np.array([0, 1, 2], dtype=np.int32),
        k_pages=k, v_pages=(-k).copy(),
        k_scales=scales, v_scales=(scales * 0.5).copy(),
        table=np.array([[0, 1], [2, -1]], dtype=np.int32),
        lens=np.array([6, 4], dtype=np.int32),
        last=np.array([11, 22], dtype=np.int32),
        slot_req={0: 7, 1: 8},
        slot_pages={0: [0, 1], 1: [2]},
        slot_shared={0: [0], 1: []},
        slot_prompt={0: [1, 2, 3], 1: [4, 5]},
        budgets={7: 5, 8: 3, 9: 4},
        out={7: [11, 12], 8: [22]},
        queue=[(9, [6, 7, 8])],
        next_id=10,
        eos_scanned={0: 1, 1: 0},
        tree_paths=[([1, 2, 3, 4], [0]), ([5, 6, 7, 8], [-1])],
        arrival={7: 1.0, 8: 1.5},
        first_tok={7: 2.0},
        drained_mono=3.0,
        drained_wall=100.0,
        skipped_tokens=3,
        flight=[{"step": 0, "t": 3.5, "what": "decode"}],
        partial=False,
        tier_keys=[0],
        tier_k=k[:, :1].copy(), tier_v=(-k[:, :1]).copy(),
        tier_ks=scales[:, :1].copy(), tier_vs=(scales[:, :1] * 0.5).copy(),
    )
    snap.validate()
    tree = snap.to_pytree()
    doc = snap._meta_doc()

    def decode(t):
        ServingSnapshot.from_pytree(t)

    pytree: Dict[str, dict] = {}
    for name in sorted(tree):
        arr = np.asarray(tree[name])
        t2 = {kk: vv for kk, vv in tree.items() if kk != name}
        pytree[name] = {"type": f"{arr.dtype}[{arr.ndim}]",
                        "required": not _decodes(decode, t2)}
    doc_group: Dict[str, dict] = {}
    for key in sorted(doc):
        d2 = {kk: vv for kk, vv in doc.items() if kk != key}
        t2 = dict(tree)
        t2["meta_json"] = _doc_to_uint8(d2)
        doc_group[key] = {"type": _json_type(doc[key]),
                          "required": not _decodes(decode, t2)}
    return {"artifact": "serving_snapshot",
            "schema_version": SCHEMA_VERSION,
            "groups": {"pytree": pytree, "doc": doc_group}}


def _summary_schema() -> dict:
    from ..fleet.summary import ReplicaSummary

    summ = ReplicaSummary(
        replica="r0", fleet="blue", seq=4, published_wall=9.5,
        page_size=8, pages_total=64, pages_free=16, n_slots=4,
        active_slots=3, queued=2, decode_p50_s=0.01, prefill_p50_s=0.05,
        prefill_backlog_tokens=96, tp=2, weight_device_bytes=1 << 20,
        dram_cached_pages=5,
        digest=[([11, 22, 33], 3, 2), ([44, 55], 2, 2)],
    )
    d = json.loads(summ.to_json())

    fields: Dict[str, dict] = {}
    for key in sorted(d):
        d2 = {kk: vv for kk, vv in d.items() if kk != key}
        fields[key] = {"type": _json_type(d[key]),
                       "required": not _decodes(
                           ReplicaSummary.from_json, json.dumps(d2))}
    return {"artifact": "replica_summary",
            "schema_version": SCHEMA_VERSION,
            "groups": {"json": fields}}


def _journal_schema() -> dict:
    from ..fleet.journal import RequestJournal

    j = RequestJournal()
    a = j.open(prompt=[1, 2, 3], max_new=8, trace_id="t-a",
               replica="r0", deadline_wall=99.0, submitted_wall=1.0)
    j.deliver(a, [7, 8])
    b = j.open(prompt=[4, 5], max_new=4, trace_id="t-b",
               submitted_wall=2.0)
    j.reassign(b, "r1", failover=True)
    c = j.open(prompt=[6], max_new=2)
    j.deliver(c, [9, 10])
    j.close(c, "done")
    tree = j.to_pytree()
    doc = json.loads(bytes(tree["journal_doc"]).decode("utf-8"))

    def decode(d):
        RequestJournal.from_pytree({"journal_doc": _doc_to_uint8(d)})

    doc_group: Dict[str, dict] = {}
    for key in sorted(doc):
        d2 = {kk: vv for kk, vv in doc.items() if kk != key}
        doc_group[key] = {"type": _json_type(doc[key]),
                          "required": not _decodes(decode, d2)}
    entry_group: Dict[str, dict] = {}
    for field in sorted(doc["entries"][0]):
        d2 = dict(doc)
        d2["entries"] = [{kk: vv for kk, vv in e.items() if kk != field}
                         for e in doc["entries"]]
        entry_group[field] = {
            "type": _json_type(doc["entries"][0][field]),
            "required": not _decodes(decode, d2)}
    return {"artifact": "request_journal",
            "schema_version": SCHEMA_VERSION,
            "groups": {"pytree": {"journal_doc": {"type": "uint8[1]",
                                                  "required": True}},
                       "doc": doc_group, "entry": entry_group}}


# (name, constructor) — the registry the pass walks. A new wire artifact
# gets a row here + a committed golden, not a hand-audit (the PR 14
# rule).
WIRE_ARTIFACTS: List[Tuple[str, Callable[[], dict]]] = [
    ("serving_snapshot", _snapshot_schema),
    ("replica_summary", _summary_schema),
    ("request_journal", _journal_schema),
]


def extract_schemas(report: Optional[Report] = None) -> Dict[str, dict]:
    """Live schema per registered artifact; a constructor that raises
    becomes a ``wire-audit-error`` finding (a wire codec so broken its
    own audit constructor cannot round-trip must fail the run)."""
    out: Dict[str, dict] = {}
    for name, build in WIRE_ARTIFACTS:
        try:
            out[name] = build()
        except Exception as e:  # noqa: BLE001 — a broken codec is a finding
            if report is not None:
                report.extend([Finding(
                    "wire-audit-error", f"<wire:{name}>", 0,
                    f"audit constructor for {name} failed: "
                    f"{type(e).__name__}: {str(e)[:300]}")])
    return out


def golden_path(name: str, schema_dir: Optional[str] = None) -> str:
    return os.path.join(schema_dir or default_schema_dir(),
                        f"{name}.json")


def load_golden(name: str,
                schema_dir: Optional[str] = None) -> Optional[dict]:
    path = golden_path(name, schema_dir)
    if not os.path.exists(path):
        return None
    with open(path, "r", encoding="utf-8") as fh:
        return json.load(fh)


def write_goldens(live: Dict[str, dict],
                  schema_dir: Optional[str] = None) -> List[str]:
    """Rewrite the committed goldens from the live schemas (the CLI's
    ``--update-schemas``). Deterministic output (sorted keys, trailing
    newline) so an unchanged schema is a byte-identical no-op — the CI
    drift check depends on that."""
    schema_dir = schema_dir or default_schema_dir()
    os.makedirs(schema_dir, exist_ok=True)
    written = []
    for name, schema in sorted(live.items()):
        path = golden_path(name, schema_dir)
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(schema, fh, indent=2, sort_keys=True)
            fh.write("\n")
        written.append(path)
    return written


def diff_schemas(name: str, live: dict, golden: Optional[dict],
                 anchor: str = "") -> List[Finding]:
    """Diff one artifact's live schema against its golden. Field-level
    breaks (``wire-break``/``wire-no-default``) are reported per field;
    ANY residual drift also raises one ``wire-golden-stale`` for the
    artifact, so a benign add-with-default still forces the golden (and
    its review) to move in the same commit."""
    anchor = anchor or f"<wire:{name}>"
    if golden is None:
        return [Finding(
            "wire-golden-stale", anchor, 0,
            f"{name}: no committed golden schema — run `python -m "
            f"k8s_gpu_scheduler_tpu.analysis --update-schemas` and "
            f"commit tests/data/graftcheck/schemas/{name}.json")]
    out: List[Finding] = []
    live_groups = live.get("groups", {})
    gold_groups = golden.get("groups", {})
    for group in sorted(set(live_groups) | set(gold_groups)):
        lf: Dict[str, dict] = dict(live_groups.get(group, {}))
        gf: Dict[str, dict] = dict(gold_groups.get(group, {}))
        for field in sorted(set(lf) | set(gf)):
            in_live, in_gold = field in lf, field in gf
            if in_gold and not in_live:
                out.append(Finding(
                    "wire-break", anchor, 0,
                    f"{name}.{group}.{field}: field REMOVED from the "
                    f"live wire format (golden type "
                    f"{gf[field].get('type')}) — artifacts already on "
                    f"the wire/disk stop loading; a rename reads as "
                    f"remove+add. Removal requires a golden bump with "
                    f"rationale (README wire-format evolution policy)"))
                continue
            if in_live and not in_gold:
                if lf[field].get("required"):
                    out.append(Finding(
                        "wire-no-default", anchor, 0,
                        f"{name}.{group}.{field}: NEW field whose "
                        f"decoder has no default — the new decoder "
                        f"rejects every artifact written before this "
                        f"commit (a rolling upgrade bricks itself). "
                        f"Give the decoder an explicit default (the "
                        f"payload_shape / tier-sidecar idiom)"))
                continue
            if lf[field].get("type") != gf[field].get("type"):
                out.append(Finding(
                    "wire-break", anchor, 0,
                    f"{name}.{group}.{field}: wire type changed "
                    f"{gf[field].get('type')} -> {lf[field].get('type')}"
                    f" — old artifacts decode to the wrong "
                    f"dtype/shape/JSON type. Add a NEW field with a "
                    f"default instead, or bump the format version"))
    if live != golden:
        out.append(Finding(
            "wire-golden-stale", anchor, 0,
            f"{name}: live wire schema drifted from the committed "
            f"golden — if the change is deliberate, regenerate with "
            f"`--update-schemas` and commit the golden in the SAME "
            f"change (CI pins `--update-schemas` to a git no-op)"))
    return out
