"""Symbolic HBM-traffic / residency audit — graftcheck's ninth pass.

Every serving perf claim in this repo is a memory-traffic claim: the
decode chunk is O(pos), the speculative verify window O(pos+γ), the
prefix-tail prefill O(hit_len+tail) — and the PR 13 kernel exists
precisely because the dense prefix gather silently materialized an
O(L·M·hb·ps) buffer per dispatch, found by eye. This pass makes the
complexity class a CONTRACT: it traces each registered serving entry
point (tracing only, no compile), costs every equation's result bytes
SYMBOLICALLY in the pool geometry dims, and checks the measured scaling
class against the contract the registry declares for that entry.

Symbolization: the entry's registered ``geometry`` maps symbol names to
the concrete dim values the audit engines were built with — chosen
mutually DISTINCT for every scale-bearing dim (pool pages ``n_pages``,
cache window ``S``, prefix-hit window ``hit`` = hb·ps, tail bucket
``tb``, verify window ``W`` = 1+γ, slots ``M``) — so a shape like
``[L, M, hb·ps, Hkv, hd]`` resolves to the monomial ``L·M·hit·Hkv·hd``
unambiguously. Dims that match no symbol are constants; symbols outside
the TRACKED set (heads, head_dim, vocab, d_model…) are structural, not
scale, and are never policed.

Rules:

- ``traffic-contract``: an intermediate's monomial carries a tracked
  scale symbol beyond the contract's declared class — e.g. anything
  ``S``-scaled in a prefill rung, an ``S²`` quadratic in a decode chunk,
  or (island entries) a rank-5 pool value inside a ``shard_map`` whose
  kv-heads dim is NOT the 1/tp shard — the measured class exceeds the
  declared one. Also fired, at registry level, when an entry declares NO
  contract at all: an unstated complexity class cannot regress because
  it was never stated.
- ``dense-materialization``: an intermediate that scales with the FULL
  pool (``n_pages`` with a size blow-up over every pool operand — the
  update chain pool→pool is exempt, a whole-pool dequant or transpose is
  not) or with the slots×prefix-window cross product (``M·hit`` — the
  PR 13 gather class: per-slot dense prefix K/V). The retained gather
  fallback is the one sanctioned carrier (``dense_ok`` on its contract,
  with a rationale — the registry-level analogue of a source
  suppression).
- ``peak-residency``: donation-aware liveness over the traced program —
  donated pool operands die at their last use, non-donated ones live to
  the end — must keep the pool-scale high-water under the contract's
  declared multiple of the pool working set. Silently-broken donation
  (the old pool read after the new one exists, or an undonated pool
  argument) shows up as a 2× pool copy long before an OOM does.

Entry points come from ``entrypoints.traffic_entrypoints()`` with their
contracts in ``entrypoints.TRAFFIC_CONTRACTS``; out-of-tree code (and
the seeded ``bad_traffic.py`` fixture) opts in via a module-level
``GRAFTCHECK_TRAFFIC_AUDIT = [(name, fn, args, geometry, contract), …]``
hook — ``contract`` a dict of TrafficContract fields, or None to assert
"this entry must be flagged as contract-less".
"""
from __future__ import annotations

from collections import Counter
from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

from .findings import Finding

# Scale symbols the contracts police. Everything else in a geometry is
# structural vocabulary for readable monomials.
TRACKED_KV = ("n_pages", "S", "hit", "tb", "W")
# The pool-pages symbol: monomials containing it are pool-scale.
POOL_SYM = "n_pages"
# The slots symbol (with `hit` it forms the dense-prefix cross product).
SLOTS_SYM = "M"
HIT_SYM = "hit"


@dataclass(frozen=True)
class TrafficContract:
    """Declared per-dispatch traffic class for one entry point.

    ``kv_scale`` maps tracked symbols to the maximum POWER an
    intermediate may carry them at (absent = 0): decode declares
    ``{"S": 1}`` (O(pos), pos ≤ S), verify ``{"S": 1, "W": 2}``, a
    prefix-tail prefill rung ``{"tb": 2}`` (the tail attends itself
    causally) with ``"hit": 1`` only on the gather fallback.
    ``dense_ok`` sanctions ``dense-materialization`` findings (the
    gather fallback) and requires a ``rationale``. ``donated`` are the
    entry's donated argument positions (the recompile pass verifies them
    dynamically; here they drive the liveness analysis).
    ``residency_multiple`` bounds peak pool-scale live bytes as a
    multiple of the pool working set (None skips the residency check).
    ``tp`` > 1 marks an island entry: rank-5 pool values inside its
    shard_map must carry the kv-heads dim at 1/tp. ``weight_sharded``
    marks a Megatron-sliced-weight island (serving
    ``weight_sharding=True``): every [L, K, N] weight INVAR of the
    shard_map must carry a sliced dim — a full (d, d)/(d, ffn)/(ffn, d)
    operand (matched against the geometry's ``d``/``d_ff``) is the
    replicated-weight layout, i.e. per-chip weight bytes that do NOT
    scale 1/tp, flagged as a ``traffic-contract`` finding. Only island
    INVARS are checked: the all_gather combine legitimately
    rematerializes a full weight as a transient inside the body."""
    kv_scale: Mapping[str, int] = field(default_factory=dict)
    dense_ok: bool = False
    rationale: str = ""
    donated: Tuple[int, ...] = ()
    residency_multiple: Optional[float] = 1.25
    tp: int = 1
    weight_sharded: bool = False

    def __post_init__(self):
        if self.dense_ok and not self.rationale.strip():
            raise ValueError(
                "dense_ok=True requires a rationale — a sanctioned dense "
                "materialization is a reviewable exemption, not a default")
        unknown = set(self.kv_scale) - set(TRACKED_KV)
        if unknown:
            raise ValueError(
                f"kv_scale names untracked symbols {sorted(unknown)} "
                f"(tracked: {TRACKED_KV})")


# -- symbolic shapes ----------------------------------------------------------

def symbolize_shape(shape: Sequence[int], geometry: Mapping[str, int],
                    ) -> Tuple[Counter, int]:
    """(symbol multiset, constant factor) for a concrete shape. First
    geometry entry with a matching value wins — the registry orders
    scale symbols first and builds its audit engines with DISTINCT
    values for them, so the mapping is unambiguous where it matters."""
    syms: Counter = Counter()
    const = 1
    for d in shape:
        d = int(d)
        for name, val in geometry.items():
            if val == d and d != 1:
                syms[name] += 1
                break
        else:
            const *= d
    return syms, const


def render_monomial(syms: Counter, const: int) -> str:
    parts = [f"{s}^{p}" if p > 1 else s
             for s, p in sorted(syms.items())]
    if const != 1 or not parts:
        parts.append(str(const))
    return "·".join(parts)


def _aval_bytes(aval) -> int:
    size = getattr(aval, "size", 0)
    dtype = getattr(aval, "dtype", None)
    return int(size) * (dtype.itemsize if dtype is not None else 0)


def _iter_subjaxprs(params: dict):
    import jax.core as jc

    for key, val in params.items():
        vals = val if isinstance(val, (tuple, list)) else [val]
        for v in vals:
            if isinstance(v, jc.ClosedJaxpr):
                yield key, v.jaxpr
            elif isinstance(v, jc.Jaxpr):
                yield key, v


# Primitives-with-one-body wrappers make_jaxpr leaves around a jitted fn.
_WRAPPER_PRIMS = {"pjit", "closed_call", "core_call", "xla_call",
                  "custom_jvp_call", "custom_vjp_call", "remat", "checkpoint"}


def _unwrap(jaxpr, donated_vars: set):
    """Descend through single-eqn wrapper jaxprs (the pjit shell around
    a jitted entry), mapping donated invars through by identity, until a
    jaxpr with real equations is reached."""
    while len(jaxpr.eqns) == 1 \
            and jaxpr.eqns[0].primitive.name in _WRAPPER_PRIMS:
        eqn = jaxpr.eqns[0]
        subs = [j for _k, j in _iter_subjaxprs(eqn.params)]
        if len(subs) != 1:
            break
        inner = subs[0]
        if len(inner.invars) != len(eqn.invars):
            break
        donated_vars = {iv for iv, ov in zip(inner.invars, eqn.invars)
                        if ov in donated_vars}
        jaxpr = inner
    return jaxpr, donated_vars


# -- the audit ----------------------------------------------------------------

def audit_traffic_jaxpr(closed, name: str, geometry: Mapping[str, int],
                        contract: TrafficContract,
                        donated_invars: Optional[set] = None,
                        ) -> List[Finding]:
    """Audit one ClosedJaxpr against its traffic contract.
    ``donated_invars``: the set of top-level invar VARS whose buffers the
    caller donates (computed by audit_traffic_callable from
    ``contract.donated`` and the argument tree structure)."""
    import jax.core as jc

    anchor = f"<traffic:{name}>"
    findings: List[Finding] = []
    seen: set = set()          # (rule, monomial) — dedupe per-layer repeats

    def emit(rule: str, key: str, msg: str, severity: str = "error"):
        if (rule, key) in seen:
            return
        seen.add((rule, key))
        findings.append(Finding(rule, anchor, 0, msg, severity=severity))

    def classify_out(eqn, var, in_island: bool) -> None:
        aval = getattr(var, "aval", None)
        shape = getattr(aval, "shape", None)
        if shape is None or len(shape) < 2:
            return
        syms, const = symbolize_shape(shape, geometry)
        mono = render_monomial(syms, const)
        if POOL_SYM in syms:
            # Pool-scale value: exempt iff it is the pool UPDATE chain —
            # some operand is pool-scale and at least as big in bytes
            # (scatter/select/stack of the pool into the pool). A
            # whole-pool dequant (int8→f32: 4× bytes, same monomial) or
            # a pool-scale buffer born from nothing is a dense
            # materialization of the full pool.
            out_bytes = _aval_bytes(aval)
            chain = any(
                POOL_SYM in symbolize_shape(
                    getattr(getattr(iv, "aval", None), "shape", ()) or (),
                    geometry)[0]
                and _aval_bytes(iv.aval) >= out_bytes
                for iv in eqn.invars
                if not isinstance(iv, jc.Literal)
                and hasattr(getattr(iv, "aval", None), "shape"))
            if not chain and not contract.dense_ok:
                emit("dense-materialization", mono,
                     f"{name}: {eqn.primitive.name} materializes a "
                     f"pool-scale intermediate {tuple(shape)} "
                     f"[{mono}] that is not the pool update chain — "
                     f"full-pool traffic on every dispatch (the class "
                     f"the paged kernels exist to avoid)")
            return                       # pool chain: not policed further
        if SLOTS_SYM in syms and HIT_SYM in syms and not contract.dense_ok:
            emit("dense-materialization", mono,
                 f"{name}: {eqn.primitive.name} materializes "
                 f"{tuple(shape)} [{mono}] — the slots×prefix-window "
                 f"cross product (dense per-slot prefix K/V, the PR 13 "
                 f"gather class); stream the prefix through the kernel "
                 f"table indirection instead, or sanction the fallback "
                 f"in its contract")
        for sym in TRACKED_KV:
            power = syms.get(sym, 0)
            allowed = contract.kv_scale.get(sym, 0)
            if power > allowed:
                emit("traffic-contract", f"{sym}:{mono}",
                     f"{name}: intermediate {tuple(shape)} [{mono}] "
                     f"scales with {sym}^{power}, contract allows "
                     f"{sym}^{allowed} — measured traffic class exceeds "
                     f"the declared one "
                     f"(allowed: {dict(contract.kv_scale) or 'none'})")

    def check_island_pool(jaxpr) -> None:
        hkv = geometry.get("Hkv")
        if contract.tp <= 1 or not hkv:
            return
        vals = list(jaxpr.invars)
        for eqn in jaxpr.eqns:
            vals.extend(v for v in eqn.outvars
                        if not isinstance(v, jc.DropVar))
        for v in vals:
            shape = getattr(getattr(v, "aval", None), "shape", None)
            if shape is None or len(shape) != 5:
                continue
            syms, _ = symbolize_shape(shape, geometry)
            if POOL_SYM in syms and int(shape[3]) * contract.tp != hkv:
                emit("traffic-contract", f"island:{tuple(shape)}",
                     f"{name}: rank-5 pool value {tuple(shape)} inside "
                     f"the tp={contract.tp} island carries kv-heads dim "
                     f"{int(shape[3])}, expected Hkv/tp = "
                     f"{hkv // contract.tp} — the island moves full "
                     f"pool-dim traffic instead of 1/tp per chip")

    def check_island_weights(jaxpr) -> None:
        """Megatron-sliced-weight islands (contract.weight_sharded):
        every [L, K, N] weight INVAR must carry a sliced dim. Matching
        is by the geometry's full ``d``/``d_ff`` values — the registry
        builds its audit engines so the tp-sliced widths (d/tp, ffn/tp)
        collide with neither — and scale planes ([L, 1, N]) are exempt
        via the min(K, N) > 1 guard. Island invars only: the all_gather
        combine legitimately regathers a full weight inside the body."""
        if not contract.weight_sharded:
            return
        L = geometry.get("L")
        full_dims = {geometry.get("d"), geometry.get("d_ff")} - {None}
        if not L or not full_dims:
            emit("traffic-contract", "weights:vacuous-geometry",
                 f"{name}: contract declares weight_sharded but the "
                 f"geometry lacks L/d/d_ff — the replicated-weight "
                 f"check is vacuous; the geometry mapping has drifted",
                 severity="warning")
            return
        shaped = 0
        for v in jaxpr.invars:
            shape = getattr(getattr(v, "aval", None), "shape", None)
            if shape is None or len(shape) != 3 or int(shape[0]) != L \
                    or min(int(shape[1]), int(shape[2])) <= 1:
                continue
            shaped += 1
            if int(shape[1]) in full_dims and int(shape[2]) in full_dims:
                emit("traffic-contract", f"weights:{tuple(shape)}",
                     f"{name}: island weight invar {tuple(shape)} is the "
                     f"FULL [L, K, N] matrix — a replicated weight "
                     f"operand inside a weight_sharded island: per-chip "
                     f"weight bytes do not scale 1/tp (the HBM wall "
                     f"Megatron slicing exists to remove)")
        if not shaped:
            emit("traffic-contract", "weights:none",
                 f"{name}: contract declares weight_sharded but the "
                 f"island has no [L, K, N] weight invars at all — the "
                 f"weights are not riding the island sliced")

    def visit(jaxpr, in_island: bool) -> None:
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            for var in eqn.outvars:
                if not isinstance(var, jc.DropVar):
                    classify_out(eqn, var, in_island)
            for _key, sub in _iter_subjaxprs(eqn.params):
                if prim == "shard_map":
                    check_island_pool(sub)
                    check_island_weights(sub)
                visit(sub, in_island or prim == "shard_map")

    top, donated = _unwrap(closed.jaxpr, set(donated_invars or ()))
    visit(top, in_island=False)
    if contract.tp > 1 and not any(
            eqn.primitive.name == "shard_map"
            for j in _all_jaxprs(top) for eqn in j.eqns):
        emit("traffic-contract", "island-missing",
             f"{name}: contract declares tp={contract.tp} but the traced "
             f"program contains no shard_map island — pool traffic is "
             f"not sharded at all")

    findings.extend(_check_residency(top, name, geometry, contract,
                                     donated))
    return findings


def _all_jaxprs(jaxpr):
    yield jaxpr
    for eqn in jaxpr.eqns:
        for _k, sub in _iter_subjaxprs(eqn.params):
            yield from _all_jaxprs(sub)


def _check_residency(jaxpr, name: str, geometry: Mapping[str, int],
                     contract: TrafficContract,
                     donated_vars: set) -> List[Finding]:
    """Donation-aware liveness over the (unwrapped) top-level equation
    schedule: pool-scale values live from definition to last use —
    donated invars die at their last use, non-donated invars live for
    the whole program (the caller retains them), program outputs live to
    the end. The high-water of live pool-scale bytes must stay under
    ``residency_multiple`` × the pool working set."""
    import jax.core as jc

    anchor = f"<traffic:{name}>"
    if contract.residency_multiple is None:
        return []

    def pool_bytes(v) -> int:
        aval = getattr(v, "aval", None)
        shape = getattr(aval, "shape", None)
        if shape is None:
            return 0
        syms, _ = symbolize_shape(shape, geometry)
        return _aval_bytes(aval) if POOL_SYM in syms else 0

    pool_set = sum(pool_bytes(v) for v in jaxpr.invars)
    if pool_set == 0:
        return [Finding(
            "traffic-contract", anchor, 0,
            f"{name}: no pool-scale ({POOL_SYM}-dim) operands found — "
            f"the residency audit is vacuous; the geometry mapping has "
            f"drifted from the entry's real shapes", severity="warning")]

    n = len(jaxpr.eqns)
    defined_at: Dict[int, int] = {}     # id(var) -> eqn index (invar: -1)
    last_use: Dict[int, int] = {}
    tracked: Dict[int, int] = {}        # id(var) -> pool bytes
    for v in jaxpr.invars:
        b = pool_bytes(v)
        if b:
            tracked[id(v)] = b
            defined_at[id(v)] = -1
            # Non-donated operands stay live for the whole program.
            last_use[id(v)] = last_use.get(id(v), -1) if v in donated_vars \
                else n
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if isinstance(v, jc.Literal):
                continue
            if id(v) in tracked:
                if v in donated_vars:
                    last_use[id(v)] = max(last_use.get(id(v), -1), i)
                # else: pinned to n already
        for v in eqn.outvars:
            if isinstance(v, jc.DropVar):
                continue
            b = pool_bytes(v)
            if b:
                tracked[id(v)] = b
                defined_at[id(v)] = i
                last_use.setdefault(id(v), i)
        # Intermediate uses extend liveness of non-invar pool values.
        for v in eqn.invars:
            if isinstance(v, jc.Literal):
                continue
            if id(v) in tracked and defined_at.get(id(v), -1) >= 0:
                last_use[id(v)] = max(last_use.get(id(v), -1), i)
    for v in jaxpr.outvars:
        if id(v) in tracked:
            last_use[id(v)] = n

    peak, peak_at = 0, -1
    for t in range(-1, n):
        live = sum(b for vid, b in tracked.items()
                   if defined_at.get(vid, -1) <= t < last_use.get(vid, -1))
        if live > peak:
            peak, peak_at = live, t
    limit = contract.residency_multiple * pool_set
    if peak > limit:
        return [Finding(
            "peak-residency", anchor, 0,
            f"{name}: pool-scale live bytes peak at {peak} "
            f"({peak / pool_set:.2f}× the {pool_set}-byte pool working "
            f"set, after eqn {peak_at}) > declared "
            f"{contract.residency_multiple}× — donation is broken or "
            f"the program copies the pool; at real scale this is a "
            f"2×-pool HBM spike per dispatch")]
    return []


def audit_traffic_callable(fn, args: Sequence, name: str,
                           geometry: Mapping[str, int],
                           contract: TrafficContract) -> List[Finding]:
    """Trace ``fn(*args)`` and audit it against ``contract``. Tracing
    failures become findings so one broken entry cannot hide the rest."""
    import jax

    try:
        closed = jax.make_jaxpr(fn)(*args)
    except Exception as e:  # noqa: BLE001 — report, keep auditing
        return [Finding("traffic-trace-error", f"<traffic:{name}>", 0,
                        f"could not trace {name}: {type(e).__name__}: "
                        f"{str(e)[:300]}")]
    donated = set()
    offset = 0
    leaves_per_arg = [len(jax.tree_util.tree_leaves(a)) for a in args]
    invars = list(closed.jaxpr.invars)
    for pos, nleaves in enumerate(leaves_per_arg):
        if pos in contract.donated:
            donated.update(invars[offset:offset + nleaves])
        offset += nleaves
    return audit_traffic_jaxpr(closed, name, geometry, contract,
                               donated_invars=donated)
