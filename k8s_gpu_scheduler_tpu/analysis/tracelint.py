"""Trace-lint — the ``trace-in-jit`` rule (graftcheck's seventh pass).

The obs/ span API is host-side by contract: a ``tracer.span(...)`` /
``tracer.record(...)`` / ``flight.record(...)`` call evaluated inside a
jit-traced body is the same hazard class the host-sync lint already
polices — at best it runs ONCE at trace time (a span that "measures" the
compiled program forever replays the trace-time duration, i.e. lies),
and any data-dependent attr forces a tracer concretization / host sync
in the middle of the hot program. The right shape is always the one the
serving engine uses: time the *dispatch* on the host, outside jit.

Detection is syntactic, like the sibling rules, and runs inside the fast
AST pass (``make lint``, tier-1's test_graftcheck_clean.py): inside a
traced body (astlint's traced-function closure), flag

- attribute calls whose receiver name mentions a tracing object
  (``tracer``/``_tracer``/``trace``/``flight``/``obs``) and whose method
  is part of the span-API surface (``span``/``record``/``event``), and
- direct calls to functions named like span constructors
  (``span``, ``trace_span``, ``start_span``).

Receiver-name matching keeps the rule import-light (no type inference);
the names are the obs/ API's own, so a false positive requires calling
an unrelated ``.record()`` on something *named* a tracer inside jit —
at which point the name is the bug. The seeded failing fixture is
tests/data/graftcheck/bad_trace.py.
"""
from __future__ import annotations

import ast
from typing import List, Optional

from .findings import Finding

# Method names of the obs tracing surface (Tracer.span/record/event,
# FlightRecorder.record).
_SPAN_METHODS = {"span", "record", "event"}
# Receiver-name fragments that identify a tracing object.
_TRACE_RECEIVERS = ("tracer", "trace", "flight", "obs")
# Bare function names that construct spans.
_SPAN_FUNCS = {"span", "trace_span", "start_span"}


def _terminal_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _receiver_label(node: ast.AST) -> str:
    """Dotted-ish label of a call receiver: ``self._tracer`` ->
    ``self._tracer``, ``tr`` -> ``tr``."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _is_trace_receiver(node: ast.AST) -> bool:
    label = _receiver_label(node).lower()
    leaf = label.rsplit(".", 1)[-1]
    return any(frag in leaf for frag in _TRACE_RECEIVERS)


def lint_trace_calls(path: str, fn: ast.AST, fn_label: str,
                     walk_shallow) -> List[Finding]:
    """Scan one TRACED function body (shallow — nested defs are their own
    traced units, exactly like the sibling traced-body rules) for span
    API calls. ``walk_shallow`` is astlint's traversal, passed in to keep
    one definition of 'the body'."""
    out: List[Finding] = []
    for node in walk_shallow(fn):
        if not isinstance(node, ast.Call):
            continue
        if isinstance(node.func, ast.Attribute):
            method = node.func.attr
            if method in _SPAN_METHODS and _is_trace_receiver(node.func.value):
                out.append(Finding(
                    "trace-in-jit", path, node.lineno,
                    f"{_receiver_label(node.func)}() inside traced "
                    f"function {fn_label}: span/tracing calls are host "
                    f"syncs — at best they run once at trace time and "
                    f"replay a constant; time the dispatch on the host, "
                    f"outside jit"))
        elif isinstance(node.func, ast.Name) \
                and node.func.id in _SPAN_FUNCS:
            out.append(Finding(
                "trace-in-jit", path, node.lineno,
                f"{node.func.id}() inside traced function {fn_label}: "
                f"span/tracing calls are host syncs — trace the host-side "
                f"dispatch instead"))
    return out
