"""Retry-lint — unbounded retry loops and blocking I/O under locks.

The robustness PR's static half: the dynamic half (utils/retry.py's
``RetryPolicy``, the chaos harness in testing/faults.py) makes failure
handling *testable*; this pass makes the two failure-handling
anti-patterns that motivated it *unwritable*:

- ``unbounded-retry``: a ``while True:`` loop containing an exception
  handler that SWALLOWS (no ``raise``/``return``/``break`` anywhere in
  the handler) — the shape that turns a dead control-plane dependency
  (registry restarting, recommender rolling) into a silently hung
  thread. A bounded loop always has a failure-path exit: a handler that
  re-raises once ``RetryPolicy.give_up`` says so, or returns a
  degraded answer. Only loop exits on the FAILURE path count — a
  ``return`` on the success path bounds nothing when the dependency
  stays dead.
- ``blocking-io-under-lock``: ``time.sleep`` or a blocking socket call
  (``connect``/``recv``/``accept``/``sendall``/``create_connection``)
  lexically inside a ``with self.<lock>:`` block. One thread's backoff
  nap (or un-timed-out dial) stalls every other thread's call for its
  whole duration — the registry client releases its lock across backoff
  sleeps for exactly this reason. ``Condition.wait`` is exempt (it
  releases the lock); ``*_locked`` helper bodies are the caller's
  responsibility, like the lock-guard rule.

Both rules are purely syntactic, import-light, and run in the fast
passes (``make lint``, tier-1's test_graftcheck_clean.py). The seeded
failing fixture is tests/data/graftcheck/bad_retry.py.
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from .findings import Finding

_LOCK_TYPES = {"Lock", "RLock", "Condition"}
_BLOCKING_SOCKET_ATTRS = {
    "connect", "connect_ex", "recv", "recv_into", "recvfrom", "accept",
    "sendall", "create_connection",
}


def _terminal_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _is_while_true(node: ast.While) -> bool:
    test = node.test
    return isinstance(test, ast.Constant) and bool(test.value)


def _handler_swallows(handler: ast.ExceptHandler) -> bool:
    """True when no failure-path exit exists anywhere in the handler:
    no raise, no return, no break. (A conditional ``raise`` under a
    give_up/deadline check still counts as an exit — precision beats
    recall here; the rule exists to catch loops with NO bound at all.)"""
    for node in ast.walk(handler):
        if isinstance(node, (ast.Raise, ast.Return, ast.Break)):
            return False
    return True


def _lint_unbounded_retry(path: str, tree: ast.Module) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.While) and _is_while_true(node)):
            continue
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Try):
                continue
            for handler in sub.handlers:
                if _handler_swallows(handler):
                    out.append(Finding(
                        "unbounded-retry", path, handler.lineno,
                        "'while True' retry loop swallows this exception "
                        "with no attempt bound or deadline on the failure "
                        "path — a dead dependency hangs the thread "
                        "forever; bound it with utils.retry.RetryPolicy "
                        "(attempts + backoff + deadline) and re-raise "
                        "when give_up() says so"))
    return out


def _walk_class(cls: ast.ClassDef) -> Iterable[ast.AST]:
    """Walk a class's own subtree (methods included) but stop at nested
    ClassDef boundaries: a nested class has its own ``self``, its own
    locks, and its own scan — pooling the two would cross-contaminate
    lock attrs and report its findings twice (once per enclosing
    class)."""
    stack = list(ast.iter_child_nodes(cls))
    while stack:
        n = stack.pop()
        if isinstance(n, ast.ClassDef):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _collect_lock_attrs(cls: ast.ClassDef) -> Set[str]:
    locks: Set[str] = set()
    for node in _walk_class(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if _terminal_name(node.value.func) in _LOCK_TYPES:
                for tgt in node.targets:
                    attr = _self_attr(tgt)
                    if attr is not None:
                        locks.add(attr)
    return locks


def _blocking_call(node: ast.Call) -> Optional[str]:
    """A human-readable label when ``node`` is a blocking call, else
    None. ``<cond>.wait`` is NOT here: Condition.wait releases the lock
    while it blocks — it is the correct way to wait under one."""
    func = node.func
    if isinstance(func, ast.Attribute):
        base = func.value
        if isinstance(base, ast.Name) and base.id == "time" \
                and func.attr == "sleep":
            return "time.sleep"
        if func.attr in _BLOCKING_SOCKET_ATTRS:
            return f".{func.attr}()"
    return None


def _walk_shallow(node: ast.AST) -> Iterable[ast.AST]:
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _lint_blocking_under_lock(path: str, cls: ast.ClassDef) -> List[Finding]:
    locks = _collect_lock_attrs(cls)
    if not locks:
        return []
    out: List[Finding] = []
    for node in _walk_class(cls):
        if not isinstance(node, ast.With):
            continue
        held = [a for item in node.items
                for a in [_self_attr(item.context_expr)] if a in locks]
        if not held:
            continue
        # Shallow: a sleep inside a nested def under the with-block runs
        # later, usually on another thread, without the lock.
        for inner in _walk_shallow(node):
            if isinstance(inner, ast.Call):
                label = _blocking_call(inner)
                if label:
                    out.append(Finding(
                        "blocking-io-under-lock", path, inner.lineno,
                        f"{label} while holding "
                        f"{'/'.join(sorted(held))}: every other thread's "
                        f"call stalls for the whole blocking window — "
                        f"release the lock across sleeps/dials (see "
                        f"registry/client.py's backoff), or use "
                        f"Condition.wait"))
    return out


def lint_retry(path: str, tree: ast.Module) -> List[Finding]:
    """Both retry-lint rules over one parsed module (suppressions are
    applied by the caller, astlint.lint_source)."""
    findings = _lint_unbounded_retry(path, tree)
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            findings.extend(_lint_blocking_under_lock(path, node))
    return findings
