"""AST lint — jit-hostility and lock-discipline rules, no jax import.

Two families of purely syntactic rules over the package source:

**Traced-function rules.** A "traced function" is one whose body runs
under `jax.jit`/`shard_map`/`lax.scan`-style tracing, where host-side
operations are either trace-time constants (silent staleness) or forced
device syncs (silent serialization). Detection is per-module and
syntactic: functions passed to / decorated with the jax wrappers, plus
their nested defs, plus (to a same-module fixpoint) functions they call.
Inside those bodies:

- ``tracer-cast``: ``int()``/``float()``/``bool()`` on a non-literal —
  forces the tracer concrete (ConcretizationTypeError at best, a silent
  host sync under eager fallback at worst).
- ``host-time-in-trace``: ``time.time()`` and friends — evaluated ONCE at
  trace time; the compiled program replays a constant timestamp forever.
- ``numpy-in-trace``: ``np.*()`` calls — host math on tracer values
  either errors or constant-folds at trace time.
- ``host-sync-in-trace``: ``.item()``, ``block_until_ready``,
  ``device_get``/``device_put`` inside a traced body.

**Repo-wide rules.**

- ``host-sync``: ``block_until_ready``/``device_get``/``device_put``
  anywhere in package host code. Every sanctioned sync point (the serving
  entrypoint loops in models/, the batcher's one batched readback) carries
  a ``# graftcheck: ignore[host-sync]`` with its rationale — the rule
  exists so a NEW sync cannot slip into a hot loop unreviewed.
- ``bare-except``: ``except:`` with no exception class.
- ``lock-guard``: per class, map each ``threading.Lock/RLock/Condition``
  attribute to the ``self.*`` attributes accessed inside its ``with
  self._mu:`` blocks (the guarded set), then flag any access of a guarded
  attribute outside the lock. Conventions honored: ``__init__`` is exempt
  (construction happens-before publication), methods named ``*_locked``
  are exempt (documented call-with-lock-held helpers), attributes that
  are themselves thread-safe primitives (Event/Thread/executors/queues)
  are never considered guarded, and nested functions are treated as
  lock-NOT-held (closures usually run on other threads).
- ``unbounded-retry`` / ``blocking-io-under-lock``: the retry-lint pair
  (retrylint.py) — ``while True`` retry loops whose failure path has no
  attempt bound or deadline, and blocking sleeps/socket calls made while
  holding a lock.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .findings import Finding, apply_suppressions, parse_suppressions

# jax tracing wrappers: a function argument of any of these is traced.
_TRACE_WRAPPERS = {
    "jit", "pmap", "shard_map", "checkpoint", "remat", "custom_vjp",
    "custom_jvp", "grad", "value_and_grad", "vjp", "jvp", "linearize",
    "vmap", "scan", "while_loop", "cond", "fori_loop", "switch",
    "pallas_call", "make_jaxpr",
}
_LOCK_TYPES = {"Lock", "RLock", "Condition"}
# NOTE: threading.Thread is deliberately NOT here — the Thread object is
# thread-safe but rebinding a self._thread REFERENCE under a worker
# spawn/exit protocol is exactly the state a lock guards.
_THREADSAFE_TYPES = {
    "Event", "ThreadPoolExecutor", "Semaphore",
    "BoundedSemaphore", "Barrier", "Queue", "SimpleQueue", "local",
}
_HOST_SYNC_ATTRS = {"block_until_ready", "device_get", "device_put"}
# Receiver methods that mutate the receiver — a call under the lock marks
# the receiver attribute as lock-owned state.
_MUTATORS = {
    "append", "extend", "insert", "pop", "popitem", "clear", "update",
    "add", "discard", "remove", "setdefault", "appendleft", "popleft",
    "heappush", "heappop",
}
_HOST_TIME_ATTRS = {"time", "perf_counter", "monotonic", "process_time",
                    "time_ns", "perf_counter_ns", "monotonic_ns"}


def _terminal_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _walk_shallow(node: ast.AST) -> Iterable[ast.AST]:
    """Walk a function body without descending into nested function/lambda
    bodies (those are linted as their own traced units)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))


class _Scopes:
    """Name -> FunctionDef resolution through lexically enclosing scopes.
    Class bodies are not scope boundaries here: methods register in the
    enclosing module/function table (harmless for this lint's purposes)."""

    def __init__(self, tree: ast.Module) -> None:
        # scope node id -> {name: def node}; parent chain for lookup.
        self.tables: Dict[int, Dict[str, ast.AST]] = {}
        self.parents: Dict[int, Optional[ast.AST]] = {}
        self._build(tree, None)

    def _build(self, scope: ast.AST, parent: Optional[ast.AST]) -> None:
        table: Dict[str, ast.AST] = {}
        self.tables[id(scope)] = table
        self.parents[id(scope)] = parent
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                table[n.name] = n
                self._build(n, scope)
            elif isinstance(n, ast.Lambda):
                self._build(n, scope)
            else:
                stack.extend(ast.iter_child_nodes(n))

    def resolve(self, scope: ast.AST, name: str) -> Optional[ast.AST]:
        cur: Optional[ast.AST] = scope
        while cur is not None:
            table = self.tables.get(id(cur), {})
            if name in table:
                return table[name]
            cur = self.parents.get(id(cur))
        return None


def _numpy_aliases(tree: ast.Module) -> Set[str]:
    aliases: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "numpy":
                    aliases.add(a.asname or "numpy")
    return aliases


def _fn_candidates_of_call(call: ast.Call) -> List[ast.AST]:
    """AST nodes that are plausibly the function being traced in a wrapper
    call: every positional arg that is a lambda, a name, or a
    partial(...) whose first arg is one of those."""
    out: List[ast.AST] = []
    for arg in call.args:
        if isinstance(arg, (ast.Lambda, ast.Name)):
            out.append(arg)
        elif (isinstance(arg, ast.Call)
              and _terminal_name(arg.func) == "partial" and arg.args):
            inner = arg.args[0]
            if isinstance(inner, (ast.Lambda, ast.Name)):
                out.append(inner)
        elif isinstance(arg, (ast.List, ast.Tuple)):   # lax.switch branches
            out.extend(e for e in arg.elts
                       if isinstance(e, (ast.Lambda, ast.Name)))
    return out


def _collect_traced(tree: ast.Module, scopes: _Scopes) -> Set[int]:
    """ids of FunctionDef/Lambda nodes whose bodies are traced."""
    # Map node-id -> enclosing scope node, for name resolution.
    enclosing: Dict[int, ast.AST] = {}

    def assign_scopes(node: ast.AST, scope: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            enclosing[id(child)] = scope
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                assign_scopes(child, child)
            else:
                assign_scopes(child, scope)

    assign_scopes(tree, tree)

    traced: Set[int] = set()
    traced_nodes: List[ast.AST] = []

    def mark(node: ast.AST) -> None:
        if id(node) not in traced:
            traced.add(id(node))
            traced_nodes.append(node)

    # Seed: wrapper calls + decorators.
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) \
                and _terminal_name(node.func) in _TRACE_WRAPPERS:
            for cand in _fn_candidates_of_call(node):
                if isinstance(cand, ast.Lambda):
                    mark(cand)
                else:
                    target = scopes.resolve(
                        enclosing.get(id(cand), tree), cand.id)
                    if target is not None:
                        mark(target)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                name = _terminal_name(dec)
                if name in _TRACE_WRAPPERS:
                    mark(node)
                elif isinstance(dec, ast.Call):
                    fname = _terminal_name(dec.func)
                    if fname in _TRACE_WRAPPERS:
                        mark(node)
                    elif fname == "partial" and dec.args and _terminal_name(
                            dec.args[0]) in _TRACE_WRAPPERS:
                        mark(node)

    # Fixpoint: nested defs of traced fns are traced; same-module functions
    # CALLED from traced bodies are traced (one-module call graph closure).
    i = 0
    while i < len(traced_nodes):
        fn = traced_nodes[i]
        i += 1
        for sub in _walk_shallow(fn):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                mark(sub)
            elif isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name):
                target = scopes.resolve(
                    enclosing.get(id(sub), tree), sub.func.id)
                if target is not None:
                    mark(target)
    return traced


def _lint_traced_body(path: str, fn: ast.AST, np_aliases: Set[str],
                      fn_label: str) -> List[Finding]:
    out: List[Finding] = []
    for node in _walk_shallow(fn):
        if not isinstance(node, ast.Call):
            continue
        name = _terminal_name(node.func)
        if isinstance(node.func, ast.Name) and name in ("int", "float",
                                                        "bool"):
            if node.args and not isinstance(node.args[0], ast.Constant):
                out.append(Finding(
                    "tracer-cast", path, node.lineno,
                    f"{name}() on a non-literal inside traced function "
                    f"{fn_label}: concretizes the tracer (host sync or "
                    f"trace error)"))
        elif isinstance(node.func, ast.Attribute):
            base = node.func.value
            if (isinstance(base, ast.Name) and base.id == "time"
                    and name in _HOST_TIME_ATTRS):
                out.append(Finding(
                    "host-time-in-trace", path, node.lineno,
                    f"time.{name}() inside traced function {fn_label}: "
                    f"evaluated once at trace time, constant thereafter"))
            elif isinstance(base, ast.Name) and base.id in np_aliases:
                out.append(Finding(
                    "numpy-in-trace", path, node.lineno,
                    f"{base.id}.{name}() inside traced function {fn_label}: "
                    f"host numpy does not trace; use jnp"))
            elif name == "item" and not node.args:
                out.append(Finding(
                    "host-sync-in-trace", path, node.lineno,
                    f".item() inside traced function {fn_label}"))
            elif name in _HOST_SYNC_ATTRS:
                out.append(Finding(
                    "host-sync-in-trace", path, node.lineno,
                    f"{name}() inside traced function {fn_label}"))
    return out


def _lint_module_wide(path: str, tree: ast.Module,
                      traced: Set[int]) -> List[Finding]:
    out: List[Finding] = []
    # Host-sync sites OUTSIDE traced bodies (traced ones already got the
    # stronger host-sync-in-trace finding).
    traced_ranges: List[Tuple[int, int]] = []
    for node in ast.walk(tree):
        if id(node) in traced and hasattr(node, "lineno"):
            traced_ranges.append(
                (node.lineno, getattr(node, "end_lineno", node.lineno)))

    def in_traced(lineno: int) -> bool:
        return any(a <= lineno <= b for a, b in traced_ranges)

    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            out.append(Finding(
                "bare-except", path, node.lineno,
                "bare 'except:' swallows KeyboardInterrupt/SystemExit; "
                "catch Exception (or narrower)"))
        elif isinstance(node, ast.Call):
            name = _terminal_name(node.func)
            if name in _HOST_SYNC_ATTRS and not in_traced(node.lineno):
                out.append(Finding(
                    "host-sync", path, node.lineno,
                    f"{name}() is a host<->device sync point; sanctioned "
                    f"syncs carry '# graftcheck: ignore[host-sync]' with a "
                    f"rationale"))
    return out


# -- lock lint ----------------------------------------------------------------

def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _lint_class_locks(path: str, cls: ast.ClassDef) -> List[Finding]:
    methods = {n.name: n for n in cls.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    locks: Set[str] = set()
    threadsafe: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            tname = _terminal_name(node.value.func)
            for tgt in node.targets:
                attr = _self_attr(tgt)
                if attr is None:
                    continue
                if tname in _LOCK_TYPES:
                    locks.add(attr)
                elif tname in _THREADSAFE_TYPES:
                    threadsafe.add(attr)
    if not locks:
        return []

    def lock_of_with(item: ast.withitem) -> Optional[str]:
        attr = _self_attr(item.context_expr)
        return attr if attr in locks else None

    # Pass 1: guarded set — self attrs WRITTEN inside `with self.<lock>`
    # (assignment, subscript store/del, or a known mutating method call).
    # Written-under-lock is the signal that the lock owns the attribute;
    # attrs only ever READ under a lock are usually immutable dependencies
    # (config, clients) and flagging them would bury the real races.
    guarded: Dict[str, Set[str]] = {}          # attr -> {locks guarding it}

    def written_attr(node: ast.AST) -> Optional[str]:
        attr = _self_attr(node)
        if attr is not None and isinstance(node.ctx, (ast.Store, ast.Del)):
            return attr
        if isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, (ast.Store, ast.Del)):
            return _self_attr(node.value)
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATORS:
            return _self_attr(node.func.value)
        return None

    def scan_with_blocks(node: ast.AST) -> None:
        for sub in ast.walk(node):
            if not isinstance(sub, ast.With):
                continue
            held = {lk for item in sub.items
                    for lk in [lock_of_with(item)] if lk}
            if not held:
                continue
            # Shallow walk, mirroring check_body: a write inside a nested
            # def/lambda under the with-block runs LATER (usually on a
            # worker thread) and must not count as written-under-lock.
            for inner in _walk_shallow(sub):
                attr = written_attr(inner)
                if attr and attr not in locks and attr not in threadsafe \
                        and attr not in methods:
                    guarded.setdefault(attr, set()).update(held)

    for m in methods.values():
        scan_with_blocks(m)
    if not guarded:
        return []

    # Pass 2: accesses of guarded attrs outside their lock.
    out: List[Finding] = []

    def check_body(nodes: Iterable[ast.AST], held: Set[str],
                   method_name: str) -> None:
        for node in nodes:
            if isinstance(node, ast.With):
                now = set(held)
                for item in node.items:
                    lk = lock_of_with(item)
                    if lk:
                        now.add(lk)
                check_body(ast.iter_child_nodes(node), now, method_name)
                continue
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                # Closures run later, often on another thread: lock NOT held.
                check_body(ast.iter_child_nodes(node), set(), method_name)
                continue
            attr = _self_attr(node)
            if attr in guarded and not (guarded[attr] & held):
                out.append(Finding(
                    "lock-guard", path, node.lineno,
                    f"{cls.name}.{method_name} touches self.{attr} without "
                    f"holding {'/'.join(sorted(guarded[attr]))} (guards it "
                    f"elsewhere); hold the lock, rename the helper "
                    f"*_locked, or suppress with a rationale"))
            check_body(ast.iter_child_nodes(node), held, method_name)

    for name, m in methods.items():
        if name == "__init__" or name.endswith("_locked"):
            continue
        check_body(iter(m.body), set(), name)
    return out


# -- driver -------------------------------------------------------------------

def lint_source(path: str, source: str,
                tree: Optional[ast.Module] = None) -> List[Finding]:
    """``tree`` lets run_fast_passes share ONE ast.parse per file across
    the AST and lock-order passes (parsing dominates both)."""
    if tree is None:
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            return [Finding("syntax-error", path, e.lineno or 0,
                            str(e.msg))]
    from .retrylint import lint_retry

    from .tracelint import lint_trace_calls

    scopes = _Scopes(tree)
    traced = _collect_traced(tree, scopes)
    np_aliases = _numpy_aliases(tree)

    findings: List[Finding] = []
    for node in ast.walk(tree):
        if id(node) in traced and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            label = getattr(node, "name", "<lambda>")
            findings.extend(
                _lint_traced_body(path, node, np_aliases, label))
            findings.extend(
                lint_trace_calls(path, node, label, _walk_shallow))
        elif isinstance(node, ast.ClassDef):
            findings.extend(_lint_class_locks(path, node))
    findings.extend(_lint_module_wide(path, tree, traced))
    findings.extend(lint_retry(path, tree))
    out = apply_suppressions(findings, parse_suppressions(source))
    # After the suppression filter: a bare marker must not vouch for
    # itself (suppression-policy lint, findings.py).
    from .findings import lint_suppressions

    out.extend(lint_suppressions(path, source))
    return out


def iter_python_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = [d for d in dirs
                       if d not in ("__pycache__", ".git", ".venv")]
            out.extend(os.path.join(root, f) for f in sorted(files)
                       if f.endswith(".py"))
    return out


def run_astlint(paths: Iterable[str]) -> List[Finding]:
    findings: List[Finding] = []
    for path in iter_python_files(paths):
        with open(path, "r", encoding="utf-8") as fh:
            findings.extend(lint_source(path, fh.read()))
    return findings
